"""Replicates bench.py's timed region with proper tunnel-safe timing:
fetch ONE scalar that depends on the batch verdict, never whole arrays.
Also reports phase-2 fixpoint iteration counts per batch."""

from __future__ import annotations

import time

import numpy as np

import bench as B


def main() -> None:
    import jax
    import jax.numpy as jnp

    from foundationdb_tpu.conflict.device import DeviceConflictSet

    rng = np.random.default_rng(B.SEED)
    pool = B.gen_pool(rng)
    pool_words = B.pool_to_words(pool)
    versions = iter(range(1, 10_000))
    prefill = [B.gen_batch(rng, pool, next(versions)) for _ in range(B.PREFILL_BATCHES)]
    timed = [B.gen_batch(rng, pool, next(versions)) for _ in range(4)]

    dev = DeviceConflictSet(max_key_bytes=B.MAX_KEY_BYTES, capacity=B.CAP)
    print("prefilling...", flush=True)
    t0 = time.perf_counter()
    for b in prefill:
        dev.resolve_arrays(b["version"], *B.device_pack(pool_words, b, B._bucket))
    print(f"prefill done in {time.perf_counter() - t0:.1f}s, count={dev.boundary_count}", flush=True)

    packed = [
        (b["version"], jax.device_put(B.device_pack(pool_words, b, B._bucket)))
        for b in timed
    ]
    # force staging: fetch one element of each
    for _, args in packed:
        for a in args:
            np.asarray(a).ravel()[:1]

    # per-batch timing, pipelined like bench, but fetch 1-element slices
    for v, args in packed:
        t0 = time.perf_counter()
        verdict = dev.resolve_arrays(v, *args, sync=False)
        t1 = time.perf_counter()
        s = int(jnp.sum(verdict.astype(jnp.int32)))  # scalar fetch => barrier
        t2 = time.perf_counter()
        print(
            f"batch v={v}: dispatch {1e3 * (t1 - t0):.1f} ms, "
            f"execute+scalar-fetch {1e3 * (t2 - t1):.1f} ms (verdict sum {s})",
            flush=True,
        )
    dev.check_pipelined()
    print("count after:", dev.boundary_count)


if __name__ == "__main__":
    main()
