"""Scaling-law probes: how do gather/scatter/sort/table-build costs scale
with index count and output size on this TPU? Decides the kernel redesign."""

from __future__ import annotations

import time

import numpy as np

from profile_kernel import _RTT_MS, _force, bench_one


def main() -> None:
    import jax
    import jax.numpy as jnp

    print(f"backend: {jax.default_backend()}")
    one = jnp.ones((8,), jnp.int32)
    trivial = jax.jit(lambda x: x + 1)
    _force(trivial(one))
    ts = []
    for _ in range(7):
        t0 = time.perf_counter()
        _force(trivial(one))
        ts.append(time.perf_counter() - t0)
    _RTT_MS[0] = sorted(ts)[len(ts) // 2] * 1e3
    print(f"RTT floor {_RTT_MS[0]:.2f} ms")

    rng = np.random.default_rng(3)
    CAP = 1 << 19
    W = 5
    table = jnp.asarray(rng.integers(0, 2**32, size=(CAP, W), dtype=np.uint64).astype(np.uint32))
    vals = jnp.asarray(rng.integers(0, 1 << 20, size=(CAP,), dtype=np.int64).astype(np.int32))

    # --- gather scaling: k indices from CAP rows ---
    for k in (1 << 14, 1 << 16, 1 << 17, 1 << 18, 1 << 19):
        idx = jnp.asarray(rng.integers(0, CAP, size=(k,), dtype=np.int64).astype(np.int32))
        bench_one(f"gather rows k={k:>7}", lambda t, i: jnp.take(t, i, axis=0), table, idx)
    # scalar gather
    for k in (1 << 16, 1 << 19):
        idx = jnp.asarray(rng.integers(0, CAP, size=(k,), dtype=np.int64).astype(np.int32))
        bench_one(f"gather scalars k={k:>7}", lambda t, i: jnp.take(t, i), vals, idx)

    # --- scatter scaling: k updates into m-sized output ---
    for m in (1 << 16, 1 << 18, 1 << 19):
        for k in (1 << 14, 1 << 16, 1 << 18):
            if k > m:
                continue
            idx = jnp.asarray(rng.choice(m, size=k, replace=False).astype(np.int32))
            v = jnp.asarray(rng.integers(0, 100, size=(k,), dtype=np.int64).astype(np.int32))
            bench_one(
                f"scat-set scalars k={k:>7} m={m:>7}",
                lambda i, v, m=m: jnp.zeros(m, jnp.int32).at[i].set(v),
                idx, v,
            )
    # drop-mode and row variants at one size
    m, k = 1 << 19, 1 << 16
    idx = jnp.asarray(rng.choice(m, size=k, replace=False).astype(np.int32))
    v = jnp.asarray(rng.integers(0, 100, size=(k,), dtype=np.int64).astype(np.int32))
    rows = jnp.asarray(rng.integers(0, 2**32, size=(k, W), dtype=np.uint64).astype(np.uint32))
    bench_one("scat-set scalars drop-mode", lambda i, v: jnp.zeros(m, jnp.int32).at[i].set(v, mode="drop"), idx, v)
    bench_one("scat-set scalars sorted idx", lambda i, v: jnp.zeros(m, jnp.int32).at[i].set(v), jnp.sort(idx), v)
    bench_one(
        "scat-set scalars sorted+hints",
        lambda i, v: jnp.zeros(m, jnp.int32).at[i].set(v, indices_are_sorted=True, unique_indices=True),
        jnp.sort(idx), v,
    )
    bench_one("scat-set rows k=65K m=524K", lambda i, r: jnp.zeros((m, W), jnp.uint32).at[i].set(r), idx, rows)
    bench_one("scat-add scalars k=65K m=524K", lambda i, v: jnp.zeros(m, jnp.int32).at[i].add(v), idx, v)

    # --- one-hot matmul alternative for scatter-add (MXU!) ---
    # segment-sum via sort+cumsum alternative
    def sort_cumsum_hist(i):
        si = jnp.sort(i)
        edges = jnp.arange(m + 1, dtype=jnp.int32)
        pos = jnp.searchsorted(si, edges)
        return jnp.diff(pos)

    bench_one("hist via sort+searchsorted k=65K m=524K", sort_cumsum_hist, idx)

    # --- sort scaling ---
    for k in (1 << 16, 1 << 18, (1 << 19) + (1 << 14)):
        x = jnp.asarray(rng.integers(0, 2**31, size=(k,), dtype=np.int64).astype(np.int32))
        p = jnp.asarray(np.arange(k, dtype=np.int32))
        bench_one(f"sort i32+payload k={k:>7}", lambda a, b: jax.lax.sort((a, b), num_keys=1), x, p)
    # multi-word sort: 2 key words + 2 payloads
    k = 1 << 19
    x0 = jnp.asarray(rng.integers(0, 2**31, size=(k,), dtype=np.int64).astype(np.int32))
    x1 = jnp.asarray(rng.integers(0, 2**31, size=(k,), dtype=np.int64).astype(np.int32))
    p = jnp.asarray(np.arange(k, dtype=np.int32))
    bench_one("sort 2-key+1payload k=524K", lambda a, b, c: jax.lax.sort((a, b, c), num_keys=2), x0, x1, p)

    # --- sparse table build variants ---
    from foundationdb_tpu.ops.rmq import build_sparse_table
    bench_one("table build (current, stack of L)", lambda v: build_sparse_table(v, jnp.maximum, 0), vals)

    def build_flat(v):
        # same recurrence but keep only a rolling pair, output concatenated
        n = v.shape[0]
        out = [v]
        prev = v
        for l in range(1, 20):
            s = 1 << (l - 1)
            shifted = jnp.concatenate([prev[s:], jnp.zeros((s,), prev.dtype)])
            prev = jnp.maximum(prev, shifted)
            out.append(prev)
        return jnp.concatenate(out)

    bench_one("table build (concat out)", build_flat, vals)

    def build_2d(v):
        n = v.shape[0]

        def body(l, t):
            s = jnp.int32(1) << (l - 1)
            prev = t[l - 1]
            shifted = jnp.where(
                jnp.arange(n) + s < n,
                jnp.roll(prev, -s).astype(prev.dtype),
                jnp.zeros((), prev.dtype),
            )
            return t.at[l].set(jnp.maximum(prev, shifted))

        t0 = jnp.zeros((20, n), v.dtype).at[0].set(v)
        return jax.lax.fori_loop(1, 20, body, t0)

    bench_one("table build (fori dyn-update)", build_2d, vals)

    # padded-pow2 disjoint-block pyramid (each level half size, total 2N)
    def build_pyramid(v):
        n = v.shape[0]
        out = [v]
        prev = v
        while prev.shape[0] > 1:
            h = prev.shape[0] // 2
            prev = jnp.maximum(prev[0 : 2 * h : 2], prev[1 : 2 * h : 2])
            out.append(prev)
        return out

    bench_one("disjoint pyramid build (total 2N)", build_pyramid, vals)

    # --- concat / slice / elementwise sanity ---
    bench_one("elementwise max CAP x20", lambda v: sum(jnp.maximum(v, v + i) for i in range(20)), vals)
    bench_one(
        "concat shift + max, one level",
        lambda v: jnp.maximum(v, jnp.concatenate([v[256:], jnp.zeros((256,), v.dtype)])),
        vals,
    )
    bench_one("cumsum CAP", lambda v: jnp.cumsum(v), vals)
    bench_one("searchsorted 49K into CAP", lambda v, q: jnp.searchsorted(v, q),
              jnp.sort(vals), jnp.asarray(rng.integers(0, 1 << 20, size=(49152,), dtype=np.int64).astype(np.int32)))


if __name__ == "__main__":
    main()
