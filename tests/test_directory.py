"""Directory layer: transactional path -> prefix mapping over the cluster
(bindings/python/fdb/directory_impl.py surface)."""

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.client.directory_layer import DirectoryLayer
from foundationdb_tpu.client.tuple_layer import pack, unpack, range_of


def test_tuple_roundtrip_and_order():
    cases = [
        (),
        (None,),
        (b"ab", "cd", 0, 7, -7, (1, b"x"), None),
        (2**40, -(2**40)),
        (True, False),
    ]
    for t in cases:
        enc = pack(t)
        got = unpack(enc)
        want = tuple(int(v) if isinstance(v, bool) else v for v in t)
        assert got == want, (t, got)
    # order preservation across mixed ints
    vals = [-300, -2, 0, 1, 255, 256, 70000]
    packed = [pack((v,)) for v in vals]
    assert packed == sorted(packed)


def run(c, coro_fn):
    db = c.database()

    async def main():
        return await db.run(coro_fn)

    return c.run_until(c.loop.spawn(main()), 120)


def test_directory_create_open_list_remove():
    c = RecoverableCluster(seed=121)
    dl = DirectoryLayer()

    async def setup(tr):
        users = await dl.create_or_open(tr, ("app", "users"))
        events = await dl.create_or_open(tr, ("app", "events"))
        tr.set(users.pack((1, "name")), b"alice")
        tr.set(users.pack((2, "name")), b"bob")
        tr.set(events.pack((1,)), b"login")
        return users.key, events.key

    ukey, ekey = run(c, setup)
    assert ukey != ekey and ukey.startswith(b"\xfd")

    async def reopen(tr):
        users = await dl.open(tr, ("app", "users"))
        assert users.key == ukey  # stable prefix across transactions
        rows = await tr.get_range(*users.range())
        names = [users.unpack(k) for k, _ in rows]
        kids = await dl.list(tr, ("app",))
        top = await dl.list(tr, ())
        return names, kids, top

    names, kids, top = run(c, reopen)
    assert names == [(1, "name"), (2, "name")]
    assert sorted(kids) == ["events", "users"]
    assert top == ["app"]

    async def remove(tr):
        await dl.remove(tr, ("app", "users"))
        return (
            await dl.exists(tr, ("app", "users")),
            await dl.exists(tr, ("app", "events")),
            await tr.get_range(ukey, ukey + b"\xff"),
        )

    gone, events_left, leftover = run(c, remove)
    assert not gone and events_left and leftover == []
    c.stop()


def test_directory_move_keeps_content():
    c = RecoverableCluster(seed=122)
    dl = DirectoryLayer()

    async def setup(tr):
        d = await dl.create_or_open(tr, ("a", "b"))
        sub = await dl.create_or_open(tr, ("a", "b", "c"))
        tr.set(d.pack(("k",)), b"v")
        tr.set(sub.pack(("k2",)), b"v2")
        return d.key, sub.key

    dkey, subkey = run(c, setup)

    async def move(tr):
        moved = await dl.move(tr, ("a", "b"), ("x",))
        return moved.key

    newkey = run(c, move)
    assert newkey == dkey  # content prefix untouched by the rename

    async def check(tr):
        assert not await dl.exists(tr, ("a", "b"))
        x = await dl.open(tr, ("x",))
        xc = await dl.open(tr, ("x", "c"))
        return await tr.get(x.pack(("k",))), await tr.get(xc.pack(("k2",)))

    v, v2 = run(c, check)
    assert (v, v2) == (b"v", b"v2")
    c.stop()


def test_directory_create_conflicts_are_safe():
    """Two racing creates of the same path: OCC on the allocator/metadata
    keys means exactly one allocation wins; the loser retries and opens."""
    c = RecoverableCluster(seed=123)
    db = c.database()
    dl = DirectoryLayer()
    keys = []

    async def one():
        async def fn(tr):
            d = await dl.create_or_open(tr, ("contended",))
            return d.key

        keys.append(await db.run(fn))

    async def main():
        from foundationdb_tpu.runtime.combinators import wait_all

        await wait_all([c.loop.spawn(one()) for _ in range(4)])

    c.run_until(c.loop.spawn(main()), 120)
    assert len(set(keys)) == 1, f"allocation raced: {keys}"
    c.stop()


def test_create_raises_on_existing():
    c = RecoverableCluster(seed=124)
    dl = DirectoryLayer()

    async def fn(tr):
        await dl.create(tr, ("dup",))
        with pytest.raises(KeyError):
            await dl.create(tr, ("dup",))
        return True

    assert run(c, fn)
    c.stop()


def test_range_of():
    b, e = range_of(("p",))
    assert b < pack(("p", 1)) < e
