"""Machine/DC topology + correlated failures + swizzle + new workloads
(fdbrpc/sim2.actor.cpp machine model; MachineAttrition; swizzle clogging;
Increment/AtomicOps; WriteDuringRead)."""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.consistency import ConsistencyCheckWorkload
from foundationdb_tpu.workloads.cycle import CycleWorkload
from foundationdb_tpu.workloads.increment import IncrementWorkload
from foundationdb_tpu.workloads.swizzle import SwizzleWorkload
from foundationdb_tpu.workloads.write_during_read import WriteDuringReadWorkload


def test_replicas_placed_across_machines_and_dcs():
    c = RecoverableCluster(seed=701, n_storage_shards=2, storage_replication=2,
                           n_machines=4, n_dcs=2)
    for team in c.storage_teams():
        machines = {ss.process.machine for ss in team}
        dcs = {ss.process.dc for ss in team}
        assert len(machines) == len(team), "replicas share a machine"
        assert len(dcs) == len(team), "replicas share a DC"
    c.stop()


def test_machine_kill_recovers_and_heals():
    """Killing a whole machine (storage replica + pipeline roles at once)
    is a correlated failure the cluster must absorb: recovery restores the
    pipeline, healing restores the team, and data survives."""
    c = RecoverableCluster(seed=702, n_storage_shards=2, storage_replication=2,
                           n_machines=4, n_dcs=2)
    db = c.database()

    async def main():
        for i in range(40):
            tr = db.create_transaction()
            tr.set(b"mk%03d" % i, b"v%d" % i)
            await tr.commit()
        victim = c.storage[0].process.machine
        killed = c.net.kill_machine(victim)
        assert len(killed) >= 2  # storage + at least one pipeline role
        # wait for heal (and any recovery the machine kill triggered)
        for _ in range(600):
            if c.dd.heals >= 1:
                break
            await c.loop.delay(0.1)
        assert c.dd.heals >= 1
        async def fn(tr):
            return await tr.get_range(b"mk", b"ml", limit=10000)
        rows = await db.run(fn)
        return len(rows)

    assert c.run_until(c.loop.spawn(main()), 900) == 40
    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cons], deadline=300.0)
    assert metrics["ConsistencyCheck"]["shards_checked"] == 2
    c.stop()


def test_dc_loss_keeps_all_data():
    """An entire DC dying leaves one replica of every shard alive (the
    placement guarantee) — reads keep working and nothing is lost."""
    c = RecoverableCluster(seed=703, n_storage_shards=2, storage_replication=2,
                           n_machines=4, n_dcs=2)
    db = c.database()

    async def main():
        for i in range(30):
            tr = db.create_transaction()
            tr.set(b"dc%03d" % i, b"v%d" % i)
            await tr.commit()
        c.net.kill_dc("dc1")
        # the write pipeline may need a recovery (roles lived in dc1)
        for _ in range(600):
            try:
                async def fn(tr):
                    return await tr.get_range(b"dc", b"dd", limit=10000)
                rows = await db.run(fn)
                if len(rows) == 30:
                    return 30
            except Exception:  # noqa: BLE001 — recovery window
                pass
            await c.loop.delay(0.2)
        return -1

    assert c.run_until(c.loop.spawn(main()), 900) == 30
    c.stop()


def test_cycle_survives_swizzle():
    c = RecoverableCluster(seed=704, n_storage_shards=2, storage_replication=2)
    cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=6)
    swz = SwizzleWorkload(rounds=2, victims=3, clog_seconds=0.6)
    metrics = run_workloads(c, [cyc, swz], deadline=600.0)
    assert metrics["Cycle"]["committed"] == 12
    assert metrics["Swizzle"]["swizzles"] >= 1
    c.stop()


def test_increment_exactly_once():
    c = RecoverableCluster(seed=705, n_storage_shards=2, storage_replication=2)
    inc = IncrementWorkload(counters=4, clients=3, adds_per_client=8)
    metrics = run_workloads(c, [inc], deadline=600.0)
    assert metrics["Increment"]["committed"] == 24
    c.stop()


def test_increment_exactly_once_under_attrition():
    """The atomic-add grand total is the sharpest exactly-once detector:
    any double-applied unknown-result retry breaks the sum."""
    from foundationdb_tpu.workloads.attrition import AttritionWorkload

    c = RecoverableCluster(seed=706, n_storage_shards=2, storage_replication=2)
    inc = IncrementWorkload(counters=3, clients=2, adds_per_client=8)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.7)
    metrics = run_workloads(c, [inc, att], deadline=900.0)
    assert metrics["Increment"]["committed"] == 16
    c.stop()


def test_write_during_read_ryw_fuzz():
    c = RecoverableCluster(seed=707, n_storage_shards=2, storage_replication=2)
    wdr = WriteDuringReadWorkload(txns=15, ops_per_txn=10)
    metrics = run_workloads(c, [wdr], deadline=600.0)
    assert metrics["WriteDuringRead"]["committed"] >= 10
    c.stop()


def test_all_tlogs_killed_recovers_from_their_disks():
    """Both TLog processes die at once (machine-correlated worst case) with
    their FILES intact: recovery reads the synced logs from disk — a
    process kill is not data loss on a durable cluster."""
    c = RecoverableCluster(seed=708, n_storage_shards=1, storage_replication=2)
    db = c.database()

    async def main():
        for i in range(20):
            tr = db.create_transaction()
            tr.set(b"tk%03d" % i, b"v%d" % i)
            await tr.commit()
        epoch = c.controller.epoch
        for t in c.controller.generation.tlogs:
            t.process.kill()
        for _ in range(600):
            if c.controller.epoch > epoch and c.controller.generation:
                break
            await c.loop.delay(0.1)
        assert c.controller.epoch > epoch

        async def fn(tr):
            return await tr.get_range(b"tk", b"tl", limit=10000)

        rows = await db.run(fn)
        return len(rows)

    assert c.run_until(c.loop.spawn(main()), 900) == 20
    c.stop()


def test_odd_machine_ring_still_separates_dcs():
    """Replica placement must straddle DCs for ANY ring size (an odd count
    must not silently co-locate a team in one DC)."""
    c = RecoverableCluster(seed=709, n_storage_shards=3, storage_replication=2,
                           n_machines=5, n_dcs=2)
    for team in c.storage_teams():
        assert len({ss.process.dc for ss in team}) == len(team)
        assert len({ss.process.machine for ss in team}) == len(team)
    c.stop()


def test_majority_dc_loss_with_spread_coordinators():
    """Coordinators are spread across DCs, so losing dc0 (the bigger half)
    must still leave a usable cluster when quorum permits: with 3 coords on
    a 4-machine/2-DC ring the spread is m0(dc0), m1(dc0), m3(dc1) — dc0
    loss takes 2 of 3, which NO placement survives with 2 DCs; what must
    hold is that killing the MINORITY dc (dc1) never touches quorum and
    data stays live."""
    c = RecoverableCluster(seed=710, n_storage_shards=2, storage_replication=2,
                           n_machines=4, n_dcs=2)
    db = c.database()

    async def main():
        for i in range(10):
            tr = db.create_transaction()
            tr.set(b"md%02d" % i, b"v%d" % i)
            await tr.commit()
        alive_coord_dcs = [co.read_stream._process.dc for co in c.coordinators]
        assert alive_coord_dcs.count("dc1") == 1  # spread put exactly 1 there
        c.net.kill_dc("dc1")
        for _ in range(600):
            try:
                async def fn(tr):
                    return await tr.get_range(b"md", b"me", limit=1000)
                rows = await db.run(fn)
                if len(rows) == 10:
                    return 10
            except Exception:  # noqa: BLE001
                pass
            await c.loop.delay(0.2)
        return -1

    assert c.run_until(c.loop.spawn(main()), 900) == 10
    c.stop()


def test_replica_placement_distinct_machines_small_ring():
    """replication > machines-per-DC must still give distinct machines
    (DC separation is impossible with 2 machines/3 replicas by pigeonhole,
    machine separation is not)."""
    c = RecoverableCluster(seed=711, n_storage_shards=2, storage_replication=3,
                           n_machines=3, n_dcs=2)
    for team in c.storage_teams():
        assert len({ss.process.machine for ss in team}) == 3
    c.stop()
