"""Multi-region replication: a log router carries the full stream across
the region boundary once and remote read replicas rejoin it like storage
rejoins TLogs (fdbserver/LogRouter.actor.cpp + remote tLogs)."""

from foundationdb_tpu.control.recoverable import RecoverableCluster


def _put(c, db, n, prefix=b"mr"):
    async def main():
        for base in range(0, n, 40):
            async def fn(tr, base=base):
                for i in range(base, min(base + 40, n)):
                    tr.set(prefix + b"%04d" % i, b"v%d" % i)

            await db.run(fn)  # retrying: recoveries are in play

    c.run_until(c.loop.spawn(main()), 900)


def test_remote_replicas_converge():
    c = RecoverableCluster(seed=1801, n_storage_shards=2, storage_replication=2,
                           remote_region=True)
    db = c.database()
    _put(c, db, 120)

    async def wait_converged():
        target = [0]

        async def fn(tr):
            target[0] = await tr.get_read_version()

        await db.run(fn)
        for _ in range(600):
            if all(ss.version.get() >= target[0] for ss in c.remote_storage):
                return True
            await c.loop.delay(0.05)
        return False

    assert c.run_until(c.loop.spawn(wait_converged()), 900)
    rdb = c.remote_database()

    async def read_remote():
        async def fn(tr):
            return await tr.get_range(b"mr", b"ms", limit=10000)

        return await rdb.run(fn)

    rows = c.run_until(c.loop.spawn(read_remote()), 900)
    assert len(rows) == 120
    assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))
    c.stop()


def test_remote_survives_primary_storage_loss():
    """Every PRIMARY storage replica dies; the remote region still serves
    every committed row (the read-availability half of region failover)."""
    c = RecoverableCluster(seed=1802, n_storage_shards=2, storage_replication=2,
                           remote_region=True)
    db = c.database()
    _put(c, db, 60)

    async def main():
        v = [0]

        async def fn(tr):
            v[0] = await tr.get_read_version()

        await db.run(fn)
        for _ in range(600):
            if all(ss.version.get() >= v[0] for ss in c.remote_storage):
                break
            await c.loop.delay(0.05)
        # region disaster: all primary storage at once (pipeline survives)
        for ss in c.storage:
            ss.process.kill()
        rdb = c.remote_database()

        async def read(tr):
            return await tr.get_range(b"mr", b"ms", limit=10000)

        return await rdb.run(read)

    rows = c.run_until(c.loop.spawn(main()), 900)
    assert len(rows) == 60
    c.stop()


def test_router_survives_pipeline_recovery():
    """A TLog kill mid-stream: the router rejoins the new generation by its
    tag and remote replicas receive everything, gap-free."""
    c = RecoverableCluster(seed=1803, n_storage_shards=1, storage_replication=2,
                           remote_region=True)
    db = c.database()
    _put(c, db, 30, prefix=b"ra")

    async def main():
        epoch = c.controller.epoch
        c.controller.generation.tlogs[0].process.kill()
        for _ in range(600):
            if c.controller.epoch > epoch and c.controller.generation:
                break
            await c.loop.delay(0.1)
        assert c.controller.epoch > epoch
        return True

    assert c.run_until(c.loop.spawn(main()), 900)
    _put(c, db, 30, prefix=b"rb")

    async def wait_and_read():
        v = [0]

        async def fn(tr):
            v[0] = await tr.get_read_version()

        await db.run(fn)
        for _ in range(600):
            if all(ss.version.get() >= v[0] for ss in c.remote_storage):
                break
            await c.loop.delay(0.05)
        rdb = c.remote_database()

        async def read(tr):
            a = await tr.get_range(b"ra", b"rb", limit=1000)
            b = await tr.get_range(b"rb", b"rc", limit=1000)
            return len(a), len(b)

        return await rdb.run(read)

    na, nb = c.run_until(c.loop.spawn(wait_and_read()), 900)
    assert (na, nb) == (30, 30)
    c.stop()


def test_region_failover_promotion():
    """The write half of region failover: after TOTAL primary storage loss,
    the remote replicas are PROMOTED into the keyServers map, rejoin the
    primary TLogs by tag, and the cluster serves reads AND writes again."""
    c = RecoverableCluster(seed=1804, n_storage_shards=2, storage_replication=2,
                           remote_region=True)
    db = c.database()
    _put(c, db, 50)

    async def main():
        v = [0]

        async def fn(tr):
            v[0] = await tr.get_read_version()

        await db.run(fn)
        for _ in range(600):
            if all(ss.version.get() >= v[0] for ss in c.remote_storage):
                break
            await c.loop.delay(0.05)
        # region disaster
        for ss in c.storage:
            if ss.tag.startswith("ss-"):
                ss.process.kill()
        ok = await c.promote_remote_region()
        assert ok, "promotion failed"
        # WRITES flow again, onto the promoted replicas
        async def put(tr):
            for i in range(50, 70):
                tr.set(b"mr%04d" % i, b"v%d" % i)

        await db.run(put)

        async def read(tr):
            return await tr.get_range(b"mr", b"ms", limit=10000)

        return await db.run(read)

    rows = c.run_until(c.loop.spawn(main()), 900)
    assert len(rows) == 70
    assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))
    # promoted servers are in the serving map; the router RETIRES once
    # the promoted replicas are durable past the promotion boundary (the
    # MVCC window holds their disks back — until then the retained router
    # backlog is the only reboot-surviving copy of their newest data)
    assert all(
        t[0].startswith("remote-") for t in c.controller.storage_teams_tags
    )

    async def drive_retirement():
        for i in range(120):
            if c.log_router is None:
                return True
            async def nudge(tr, i=i):
                tr.set(b"mr-nudge", b"%d" % i)

            await db.run(nudge)
            await c.loop.delay(0.5)
        return c.log_router is None

    assert c.run_until(c.loop.spawn(drive_retirement()), 900)
    c.stop()


def test_router_lag_forces_spill_then_remote_converges():
    """TLog-spill-aware log routing: the router's process dies long enough
    for its tag's backlog to exceed the TLog spill budget; on reboot the
    router drains the backlog — partly from spilled records — and the
    remote replicas converge exactly."""
    from foundationdb_tpu.runtime.knobs import CoreKnobs

    k = CoreKnobs()
    k.TLOG_SPILL_BYTES = 2000
    c = RecoverableCluster(seed=450, n_storage_shards=1, remote_region=True,
                           knobs=k)
    db = c.database()

    async def main():
        # the router is wired; let the remote catch an initial write
        tr = db.create_transaction()
        tr.set(b"pre", b"1")
        await tr.commit()
        for _ in range(200):
            if all(s.version.get() >= 0 and s.store is not None
                   for s in c.remote_storage):
                break
            await c.loop.delay(0.05)

        # router goes dark: its tag stops popping; write far past the
        # spill budget
        c.log_router.process.kill()
        for base in range(0, 300, 50):
            tr = db.create_transaction()
            for i in range(base, base + 50):
                tr.set(b"rl%04d" % i, b"x" * 40)
            await tr.commit()
        tlogs = c.controller.generation.tlogs
        assert any(t.spill_events > 0 for t in tlogs), "no TLog spilled"

        # a fresh router (the worker-restart path) drains the backlog —
        # partly from spilled records
        c.restart_log_router()
        tr = db.create_transaction()
        v = await tr.get_read_version()
        for _ in range(600):
            if all(s.version.get() >= v for s in c.remote_storage):
                break
            await c.loop.delay(0.1)
        assert all(s.version.get() >= v for s in c.remote_storage)

        # exactness: remote replica serves every key
        rdb = c.remote_database()
        tr = rdb.create_transaction()
        rows = await tr.get_range(b"rl", b"rm", limit=1000)
        assert len(rows) == 300
        assert await tr.get(b"pre") == b"1"
        return True

    assert c.run_until(c.loop.spawn(main()), 900)
    c.stop()
