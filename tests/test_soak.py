"""Soak-campaign harness (tools/soak.py + cli soak): per-seed subprocess
runs with trace-file artifacts, verdict classification, the merged
buggify/testcov coverage census against a required-coverage manifest,
automatic failure triage (first errors, slowest sampled transaction,
SlowTask counts, repro command), the SlowTask reactor event, the spec
per-seed hooks, and the conftest census-isolation fixture."""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

from foundationdb_tpu.control.status import validate_coverage_event
from foundationdb_tpu.runtime import buggify, coverage
from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
from foundationdb_tpu.runtime.trace import (
    SEV_ERROR,
    SEV_WARN,
    TraceCollector,
    TraceFileSink,
)
from foundationdb_tpu.tools import soak, trace_tool

REPO = pathlib.Path(__file__).resolve().parent.parent

MINI_SPEC = """\
testTitle=MiniSoak
seed=7
chaos=true

testName=Cycle
nodes=6
clients=2
txnsPerClient=4

testName=Attrition
kills=1
interval=2.0
startDelay=0.8
"""


def _write_spec(tmp_path, coverage_lines="recovery.triggered\n"):
    spec = tmp_path / "Mini.txt"
    spec.write_text(MINI_SPEC)
    (tmp_path / "Mini.coverage").write_text(coverage_lines)
    return spec


# -- census primitives -------------------------------------------------------


def test_census_merge_and_required_check():
    per_seed = {
        1: {"buggify": {"a": {"armed": True, "fires": 2},
                        "b": {"armed": True, "fires": 0}},
            "testcov": {"x": 3, "buggify.a": 2}},
        2: {"buggify": {"a": {"armed": False, "fires": 0},
                        "b": {"armed": True, "fires": 0}},
            "testcov": {"y": 1}},
    }
    m = soak.merge_census(per_seed)
    assert m["buggify"]["a"] == {"armed_seeds": 1, "hit_seeds": 1, "fires": 2}
    # the silently-stopped-injecting shape: armed in both seeds, never hit
    assert m["buggify"]["b"] == {"armed_seeds": 2, "hit_seeds": 0, "fires": 0}
    assert m["testcov"]["x"] == {"hit_seeds": 1, "hits": 3}
    assert soak.check_required(m, ["x", "y", "buggify.a"]) == []
    assert soak.check_required(m, ["buggify.b", "z", "x"]) == ["buggify.b", "z"]


def test_census_round_trips_through_trace_plane(tmp_path):
    """The cross-process path: buggify/coverage emit CodeCoverage events
    into a trace file; census_from_events rebuilds the same census."""
    rng = DeterministicRandom(5)
    buggify.enable(rng)
    buggify.force("soaktest.site", 2)
    assert buggify.buggify("soaktest.site")
    assert buggify.buggify("soaktest.site")
    # forced but never reaching its guard: must still census as ARMED with
    # zero fires — the silently-stopped-injecting row, not a missing row
    buggify.force("soaktest.unreached", 3)
    coverage.testcov("soaktest.path")
    sink = TraceFileSink(str(tmp_path / "t"))
    tc = TraceCollector(sink=sink)
    buggify.emit_coverage(tc)
    coverage.emit_coverage(tc)
    sink.close()
    events = trace_tool.load_events([str(tmp_path)])
    for ev in events:
        validate_coverage_event(ev)
    census = soak.census_from_events(events)
    assert census["buggify"]["soaktest.site"] == {"armed": True, "fires": 2}
    assert census["buggify"]["soaktest.unreached"] == {
        "armed": True, "fires": 0,
    }
    assert census["testcov"]["soaktest.path"] == 1
    assert census["testcov"]["buggify.soaktest.site"] == 2
    # in-process flavor agrees
    direct = soak.seed_census()
    assert direct["buggify"]["soaktest.site"]["fires"] == 2


def test_coverage_census_baseline_delta():
    coverage.testcov("soaktest.before")
    base = coverage.snapshot()
    coverage.testcov("soaktest.after")
    coverage.testcov("soaktest.before")
    c = coverage.census(base)
    assert c == {"soaktest.after": 1, "soaktest.before": 1}


# -- the SlowTask reactor event ----------------------------------------------


def test_slow_task_traced_at_sev_warn():
    """A run-loop callback stalling past the threshold (host wall) traces
    SlowTask at SEV_WARN with its priority and duration; fast callbacks
    stay silent."""
    loop = EventLoop()
    tc = TraceCollector()
    loop.slow_task_trace = tc
    loop.slow_task_trace_threshold = 0.01

    async def slow():
        time.sleep(0.02)

    async def fast():
        pass

    loop.run_until(loop.spawn(slow()))
    evs = tc.find("SlowTask")
    assert evs, "stalled callback traced no SlowTask"
    assert evs[0]["Severity"] == SEV_WARN
    assert evs[0]["DurationS"] >= 0.01
    assert "Priority" in evs[0]
    n = len(evs)
    loop.run_until(loop.spawn(fast()))
    assert len(tc.find("SlowTask")) == n  # fast path added nothing


def test_slow_task_watch_off_by_default():
    loop = EventLoop()
    assert loop.slow_task_trace is None  # bare loops pay no timing


# -- spec per-seed artifact hooks --------------------------------------------


def test_run_spec_seed_sink_and_sampling_hooks(tmp_path):
    """run_spec's soak hooks: seed override beats the file's, trace events
    stream into the sink, the teardown census rides the trace plane as
    schema-valid CodeCoverage events, and sample_rate lands joinable
    TransactionDebug stations in the files."""
    from foundationdb_tpu.workloads.spec import run_spec

    sink = TraceFileSink(str(tmp_path / "trace"))
    m = run_spec(MINI_SPEC, deadline=600.0, seed=4242, trace_sink=sink,
                 sample_rate=1.0)
    sink.close()
    assert m["seed"] == 4242
    assert m["Cycle"]["committed"] == 8
    events = trace_tool.load_events([str(tmp_path)])
    cov = [e for e in events if e["Type"] == "CodeCoverage"]
    assert cov, "teardown emitted no CodeCoverage events"
    for ev in cov:
        validate_coverage_event(ev)
    census = soak.census_from_events(events)
    assert census["buggify"], "chaos run queried no buggify sites"
    assert census["testcov"].get("recovery.triggered", 0) >= 1
    assert any(e["Type"] == "TransactionDebug" for e in events)


def test_run_spec_rejects_unknown_backend():
    import pytest

    from foundationdb_tpu.workloads.spec import run_spec

    with pytest.raises(ValueError, match="unknown backend"):
        run_spec("backend=bogus\ntestName=Cycle\n")


# -- the campaign driver -----------------------------------------------------


def test_soak_campaign_verdicts_census_and_triage(tmp_path, monkeypatch):
    """Acceptance: a campaign writes JSON+markdown reports with per-seed
    verdicts, a merged census with zero missing required sites, and — one
    seed forced to fail — a triage block carrying the first-error events,
    the slowest sampled transaction, and the repro command."""
    spec = _write_spec(tmp_path)
    monkeypatch.setenv("FDBTPU_SOAK_FORCE_FAIL", "3001")
    report = soak.run_campaign(
        str(spec), [3000, 3001, 3002], str(tmp_path / "out"),
        jobs=3, seed_deadline=240.0,
    )
    assert report["verdicts"] == {"pass": 2, "fail": 1,
                                  "timeout": 0, "crash": 0}
    assert not report["ok"]
    # the manifest (recovery.triggered: every seed's attrition kill) is
    # fully covered even though one seed failed
    assert report["coverage"]["missing_required"] == []
    assert report["coverage"]["merged"]["testcov"][
        "recovery.triggered"]["hit_seeds"] == 3
    assert set(report["coverage"]["per_seed"]) == {"3000", "3001", "3002"}

    failing = [r for r in report["per_seed"] if r["verdict"] == "fail"]
    assert [r["seed"] for r in failing] == [3001]
    t = failing[0]["triage"]
    assert any(
        ev["Type"] == "SoakSeedFailed" and ev["Severity"] >= SEV_ERROR
        for ev in t["first_events"]
    ), t["first_events"]
    assert t["error_count"] >= 1
    assert "slow_task_count" in t
    st = t["slowest_transaction"]
    assert st is not None and st["station_count"] >= 3, (
        "triage carried no joined transaction timeline"
    )
    assert "--first-seed 3001" in t["repro"]
    assert str(spec) in t["repro"]

    # artifacts: reports on disk, failing seed keeps its traces for the
    # repro loop, passing seeds are scraped-and-pruned
    out = tmp_path / "out"
    assert json.loads((out / "campaign.json").read_text())["ok"] is False
    md = (out / "campaign.md").read_text()
    assert "seed 3001 — fail" in md and "repro" in md
    assert "buggify site" in md and "testcov name" in md
    assert (out / "seed-3001").is_dir()
    # a passing seed keeps ONLY result.json (now carrying its census — the
    # --resume checkpoint); its bulky trace files are pruned
    assert not list((out / "seed-3000").glob("trace*"))
    r3000 = json.loads((out / "seed-3000" / "result.json").read_text())
    assert r3000["verdict"] == "pass" and r3000["census"]["testcov"]


def test_soak_repro_command_reruns_the_failing_seed(tmp_path):
    """The triage 'unseed' is a working command line: running it (through
    the cli soak subcommand, which is what it names) reruns exactly that
    seed and reproduces the failure."""
    import subprocess

    spec = _write_spec(tmp_path)
    cmd = soak.repro_command(str(spec), 3001).split()
    assert cmd[0] == "python"
    cmd[0] = sys.executable
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", FDBTPU_SOAK_FORCE_FAIL="3001",
        PYTHONPATH=str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    p = subprocess.run(cmd, cwd=str(tmp_path), env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 1, p.stdout + p.stderr  # the failure reproduced
    rep = json.loads((tmp_path / "repro-3001" / "campaign.json").read_text())
    assert rep["per_seed"][0]["seed"] == 3001
    assert rep["per_seed"][0]["verdict"] == "fail"
    assert rep["per_seed"][0]["triage"]["first_events"]


def test_soak_timeout_verdict_and_triage(tmp_path):
    """A seed overrunning its wall deadline is killed and recorded as
    timeout — with a triage block built from whatever its line-buffered
    trace files captured before the kill."""
    spec = tmp_path / "Long.txt"
    spec.write_text(
        "testTitle=LongRun\n\n"
        "testName=Cycle\nnodes=8\nclients=2\ntxnsPerClient=100000\n"
    )
    report = soak.run_campaign(
        str(spec), [3000], str(tmp_path / "out"), jobs=1, seed_deadline=3.0,
    )
    assert report["verdicts"]["timeout"] == 1
    r = report["per_seed"][0]
    assert r["verdict"] == "timeout"
    assert "deadline" in r["error"]
    assert "repro" in r["triage"]


# -- conftest census isolation (satellite regression pair) -------------------
# Part 1 deliberately pollutes the process-global census; part 2 (running
# after it — tier-1 disables random ordering) must see none of it.  This
# is the cross-test-leak regression the autouse fixture exists to pin.


def test_census_isolation_part1_pollutes():
    coverage.testcov("soaktest.isolation_probe")
    buggify.enable(DeterministicRandom(1))
    buggify.force("soaktest.isolation_site")
    assert buggify.buggify("soaktest.isolation_site")
    assert coverage.hits("soaktest.isolation_probe") == 1
    assert buggify.is_enabled()
    assert buggify.census()["soaktest.isolation_site"]["fires"] == 1


def test_census_isolation_part2_sees_clean_state():
    assert coverage.hits("soaktest.isolation_probe") == 0
    assert coverage.all_hits() == {}
    assert not buggify.is_enabled()
    assert buggify.census() == {}


def test_census_snapshot_restore_round_trip():
    coverage.testcov("soaktest.snap")
    cov = coverage.snapshot()
    bug = buggify.snapshot()
    buggify.enable(DeterministicRandom(2))
    buggify.force("soaktest.snap_site")
    buggify.buggify("soaktest.snap_site")
    coverage.testcov("soaktest.snap")
    coverage.restore(cov)
    buggify.restore(bug)
    assert coverage.hits("soaktest.snap") == 1
    assert not buggify.is_enabled()
    assert buggify.census() == {}
