"""Transaction priorities + options: batch-class GRVs starve first under
ratekeeper pressure, immediate-class bypasses admission entirely, and the
option surface behaves (fdbclient TransactionPriority; Ratekeeper's
separate batch limit; fdb_transaction_set_option)."""

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime.core import TimedOut


def test_priority_classes_under_throttle():
    """Squeeze the ratekeeper to 10% budget: batch GRVs stall (their budget
    hits zero below 25% of max), default still trickles, immediate flows."""
    c = RecoverableCluster(seed=1201, n_storage_shards=1, storage_replication=2)
    db = c.database()

    async def main():
        # wedge the budget: pretend storage is drowning (the ratekeeper's
        # own unit tests cover the model; here we force its OUTPUT)
        c.ratekeeper.max_tps = 100.0
        c.ratekeeper.tps_budget = 10.0
        c.ratekeeper.batch_tps_budget = 0.0
        c.ratekeeper.stop()  # freeze the forced budgets

        async def grv_with(priority_option):
            tr = db.create_transaction()
            if priority_option:
                tr.set_option(priority_option)
            await tr.get_read_version()
            return True

        # immediate: many requests, all served fast despite the squeeze
        for _ in range(20):
            assert await grv_with(b"priority_system_immediate")
        # default: trickles at ~10/s of virtual time — but succeeds
        assert await grv_with(None)
        # batch: budget is ZERO — must not get a read version
        tr = db.create_transaction()
        tr.set_option(b"priority_batch")
        try:
            from foundationdb_tpu.runtime.combinators import timeout_error

            await timeout_error(c.loop, c.loop.spawn(tr.get_read_version()), 3.0)
            return "batch_served"
        except (TimedOut, Exception) as e:  # noqa: BLE001
            return type(e).__name__

    out = c.run_until(c.loop.spawn(main()), 600)
    assert out in ("TimedOut",), out
    c.stop()


def test_batch_priority_recovers_with_health():
    c = RecoverableCluster(seed=1202, n_storage_shards=1, storage_replication=2)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set_option(b"priority_batch")
        v = await tr.get_read_version()  # healthy cluster: batch flows
        return v > 0

    assert c.run_until(c.loop.spawn(main()), 300)
    c.stop()


def test_causal_write_risky_skips_self_conflict():
    c = RecoverableCluster(seed=1203, n_storage_shards=1, storage_replication=2)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set_option(b"causal_write_risky")
        tr.set(b"cwr", b"1")
        await tr.commit()
        # blind write with the option: no synthetic self-conflict ranges
        assert not any(k.startswith(b"\xff/SC/") for k, _e in tr._read_ranges)
        tr2 = db.create_transaction()
        tr2.set(b"cwr2", b"1")
        await tr2.commit()
        # without the option a blind write IS made self-conflicting
        assert any(k.startswith(b"\xff/SC/") for k, _e in tr2._read_ranges)
        return True

    assert c.run_until(c.loop.spawn(main()), 300)
    c.stop()


def test_debug_identifier_option_joins_timeline():
    from foundationdb_tpu.runtime.trace import g_trace_batch

    c = RecoverableCluster(seed=1204, n_storage_shards=1, storage_replication=2)
    g_trace_batch.clear()
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set_option(b"debug_transaction_identifier", b"my-op-7")
        tr.set(b"dbg", b"1")
        await tr.commit()

    c.run_until(c.loop.spawn(main()), 300)
    locs = [e["Location"] for e in g_trace_batch.timeline("my-op-7")]
    assert "CommitProxyServer.commitBatch.AfterLogPush" in locs
    c.stop()


def test_unknown_option_rejected():
    c = RecoverableCluster(seed=1205, n_storage_shards=1, storage_replication=2)
    db = c.database()
    tr = db.create_transaction()
    with pytest.raises(ValueError):
        tr.set_option(b"no_such_option")
    c.stop()
