"""Cross-binding stack-machine conformance (reference bindings/
bindingtester/bindingtester.py): the same seed-driven op spec executed by
all three shipped bindings — C ABI (ctypes -> libfdbtpu_c.so -> gateway),
the pure-Python gateway client, and the in-process client — must produce
byte-identical digests."""

from __future__ import annotations

import pathlib
import select
import subprocess
import sys
import tempfile
import textwrap

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
CDIR = REPO / "bindings" / "c"
sys.path.insert(0, str(REPO / "bindings"))

from bindingtester import digest  # noqa: E402

SEEDS = [11, 12, 13]


def _b64(x: bytes) -> str:
    """THE wire encoding for byte fields in the Perl tester exchange."""
    import base64

    return base64.b64encode(x).decode()

GATEWAY_SERVER = textwrap.dedent(
    """
    import sys
    sys.path.insert(0, {repo!r})
    from foundationdb_tpu.control.recoverable import RecoverableCluster
    from foundationdb_tpu.tools.gateway import ClientGateway, GatewayDriver

    c = RecoverableCluster(seed={seed}, n_storage_shards=2,
                           storage_replication=2)
    gw = ClientGateway(c.loop, c.database(), port=0)
    print(gw.port, flush=True)
    GatewayDriver(c.loop, gw).serve_forever(wall_timeout=120.0)
    """
)


def _spawn_gateway(seed: int):
    errf = tempfile.TemporaryFile(mode="w+")
    proc = subprocess.Popen(
        [sys.executable, "-c", GATEWAY_SERVER.format(repo=str(REPO), seed=seed)],
        stdout=subprocess.PIPE, stderr=errf, text=True,
        env={"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
             "PATH": "/usr/bin:/bin"},
    )
    ready, _, _ = select.select([proc.stdout], [], [], 30.0)
    line = proc.stdout.readline() if ready else ""
    if not line.strip():
        proc.kill()
        errf.seek(0)
        raise RuntimeError(f"gateway never came up: {errf.read()[-2000:]}")
    return proc, int(line)


@pytest.fixture(scope="module")
def clib():
    r = subprocess.run(["make", "-C", str(CDIR)], capture_output=True, text=True)
    assert r.returncode == 0, f"C build failed:\n{r.stdout}\n{r.stderr}"
    return CDIR / "libfdbtpu_c.so"


class _CtypesDriver:
    def __init__(self, db) -> None:
        self.db = db

    def new_txn(self):
        outer = self

        class T:
            def __init__(self) -> None:
                self.tr = outer.db.create_transaction()

            def set(self, k, v):
                self.tr.set(k, v)

            def get(self, k):
                return self.tr.get(k)

            def clear_range(self, b, e):
                self.tr.clear_range(b, e)

            def get_range(self, b, e, limit):
                return self.tr.get_range(b, e, limit)

            def atomic_add(self, k, d):
                self.tr.atomic_add(k, d)

            def get_key(self, k, or_equal, offset):
                return self.tr.get_key(k, or_equal, offset)

            def get_range_selector(self, bk, boe, boff, ek, eoe, eoff, limit):
                return self.tr.get_range_selector(
                    bk, boe, boff, ek, eoe, eoff, limit
                )

            def commit(self):
                self.tr.commit()

            def reset(self):
                self.tr.reset()

            def set_option(self, option):
                self.tr.set_option(option)

        return T()


class _GatewayClientDriver:
    def __init__(self, client) -> None:
        self.client = client

    def new_txn(self):
        return self.client.transaction()  # surface already matches


class _InProcessDriver:
    def __init__(self, cluster) -> None:
        self.cluster = cluster
        self.db = cluster.database()

    def new_txn(self):
        c = self.cluster
        tr = self.db.create_ryw_transaction()

        class T:
            def set(self, k, v):
                tr.set(k, v)

            def get(self, k):
                return c.run_until(c.loop.spawn(tr.get(k)), 300)

            def clear_range(self, b, e):
                tr.clear_range(b, e)

            def get_range(self, b, e, limit):
                return c.run_until(
                    c.loop.spawn(tr.get_range(b, e, limit=limit)), 300
                )

            def atomic_add(self, k, d):
                from foundationdb_tpu.roles.types import MutationType

                tr.atomic_op(
                    MutationType.ADD, k, d.to_bytes(8, "little", signed=True)
                )

            def get_key(self, k, or_equal, offset):
                from foundationdb_tpu.roles.types import KeySelector

                return c.run_until(
                    c.loop.spawn(tr.get_key(KeySelector(k, or_equal, offset))),
                    300,
                )

            def get_range_selector(self, bk, boe, boff, ek, eoe, eoff, limit):
                from foundationdb_tpu.roles.types import KeySelector

                return c.run_until(
                    c.loop.spawn(tr.get_range(
                        KeySelector(bk, boe, boff),
                        KeySelector(ek, eoe, eoff),
                        limit=limit,
                    )),
                    300,
                )

            def commit(self):
                c.run_until(c.loop.spawn(tr.commit()), 300)

            def reset(self):
                tr.reset()

            def set_option(self, option):
                tr.set_option(option)

        return T()


@pytest.mark.parametrize("seed", SEEDS)
def test_three_bindings_conform(seed, clib):
    from foundationdb_tpu.client.gateway_client import GatewayClient
    from foundationdb_tpu.control.recoverable import RecoverableCluster

    sys.path.insert(0, str(REPO / "bindings" / "python"))
    from fdbtpu_ctypes import FdbTpu

    digests = {}

    # binding 1: C ABI over its own fresh gateway cluster
    proc1, port1 = _spawn_gateway(900 + seed)
    try:
        db_c = FdbTpu(str(clib), "127.0.0.1", port1)
        digests["ctypes"] = digest(_CtypesDriver(db_c), seed)
        db_c.close()
    finally:
        proc1.kill()

    # binding 2: pure-Python gateway client over its own gateway cluster
    proc2, port2 = _spawn_gateway(950 + seed)
    try:
        gc = GatewayClient("127.0.0.1", port2)
        digests["gateway_py"] = digest(_GatewayClientDriver(gc), seed)
        gc.close()
    finally:
        proc2.kill()

    # binding 3: in-process client on a fresh deterministic cluster
    c = RecoverableCluster(seed=990 + seed, n_storage_shards=2,
                           storage_replication=2)
    digests["in_process"] = digest(_InProcessDriver(c), seed)
    c.stop()

    assert digests["ctypes"] == digests["gateway_py"], (
        "C ABI vs gateway-python divergence"
    )
    assert digests["gateway_py"] == digests["in_process"], (
        "gateway-python vs in-process divergence"
    )


def _perlize(digest):
    """Convert the Python digest to the Perl tester's wire form (byte
    fields base64) for comparison."""
    out = []
    for e in digest:
        if e[0] == "range":
            out.append(["range", _b64(e[1]), _b64(e[2]), e[3], _b64(e[4])])
        elif e[0] in ("getkey", "rangesel"):
            out.append([e[0], _b64(e[1])])
        elif e[0] == "top":
            out.append(["top", _b64(e[1])])
        elif e[0] == "stack":
            out.append(["stack", [_b64(x) for x in e[1]]])
        else:
            raise AssertionError(e)
    return out


@pytest.mark.parametrize("seed", [21, 22])
def test_perl_binding_conforms(seed):
    """The Perl binding (bindings/perl/FdbTpu.pm, pure sockets) executes
    the same stack-machine spec via its own tester.pl and must produce the
    same digest as the Python gateway client — the reference's
    cross-LANGUAGE bindingtester comparison."""
    import json

    from bindingtester import gen_ops
    from foundationdb_tpu.client.gateway_client import GatewayClient

    b64 = _b64
    ops = gen_ops(seed)
    wire_ops = []
    for op in ops:
        kind = op[0]
        if kind in ("PUSH", "GET", "SET_OPTION"):
            wire_ops.append([kind, b64(op[1])])
        elif kind in ("SET", "CLEAR_RANGE"):
            wire_ops.append([kind, b64(op[1]), b64(op[2])])
        elif kind == "GET_RANGE":
            wire_ops.append([kind, b64(op[1]), b64(op[2]), op[3]])
        elif kind == "GET_KEY":
            # booleans as 0/1 ints: JSON::PP booleans don't survive a
            # round-trip into perl pack() cleanly
            wire_ops.append([kind, b64(op[1]), int(op[2]), op[3]])
        elif kind == "GET_RANGE_SELECTOR":
            wire_ops.append([kind, b64(op[1]), int(op[3]), op[4],
                             b64(op[2]), int(op[5]), op[6], op[7]])
        elif kind == "ATOMIC_ADD":
            wire_ops.append([kind, b64(op[1]), op[2]])
        else:
            wire_ops.append([kind])

    # perl against its own fresh gateway cluster
    proc1, port1 = _spawn_gateway(870 + seed)
    try:
        spec = json.dumps({"host": "127.0.0.1", "port": port1, "ops": wire_ops})
        r = subprocess.run(
            ["perl", str(REPO / "bindings" / "perl" / "tester.pl")],
            input=spec, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, f"perl tester failed:\n{r.stderr[-2000:]}"
        perl_digest = json.loads(r.stdout)
    finally:
        proc1.kill()

    # python gateway client against another fresh cluster
    proc2, port2 = _spawn_gateway(880 + seed)
    try:
        gc = GatewayClient("127.0.0.1", port2)
        py_digest = _perlize(digest(_GatewayClientDriver(gc), seed))
        gc.close()
    finally:
        proc2.kill()

    assert perl_digest == py_digest, "perl vs python binding divergence"


def test_watch_over_the_wire(clib):
    """Op 14 WATCH through every binding: a dedicated watcher connection
    blocks until another connection changes the key, and the returned
    version is the firing commit's."""
    import threading

    from foundationdb_tpu.client.gateway_client import GatewayClient

    sys.path.insert(0, str(REPO / "bindings" / "python"))
    from fdbtpu_ctypes import FdbTpu

    proc, port = _spawn_gateway(860)
    try:
        writer = GatewayClient("127.0.0.1", port)
        writer.run(lambda tr: tr.set(b"w/k", b"v0"))

        results = {}

        def py_watch():
            w = GatewayClient("127.0.0.1", port, timeout=60)
            tr = w.transaction()
            results["py"] = tr.watch(b"w/k")
            w.close()

        def c_watch():
            db = FdbTpu(str(clib), "127.0.0.1", port)
            tr = db.create_transaction()
            results["c"] = tr.watch(b"w/k")
            db.close()

        def perl_watch():
            r = subprocess.run(
                ["perl", "-I", str(REPO / "bindings" / "perl"), "-MFdbTpu",
                 "-e",
                 f'my $db = FdbTpu->new("127.0.0.1", {port});'
                 'my $t = $db->new_txn;'
                 'print $db->watch($t, "w/k"), "\\n";'],
                capture_output=True, text=True, timeout=60,
            )
            assert r.returncode == 0, r.stderr
            results["perl"] = int(r.stdout.strip())

        threads = [threading.Thread(target=f)
                   for f in (py_watch, c_watch, perl_watch)]
        for t in threads:
            t.start()
        import time as _t

        # fire REPEATEDLY with fresh values until every watcher returns: a
        # late registrant (slow interpreter start) needs a change AFTER its
        # registration, so a single timed write would be a race
        commit_versions = []
        for i in range(60):
            tr = writer.transaction()
            tr.set(b"w/k", b"v%d" % (i + 1))
            commit_versions.append(tr.commit())
            tr.destroy()
            if all(not t.is_alive() for t in threads):
                break
            _t.sleep(0.5)
        for t in threads:
            t.join(30)
            assert not t.is_alive(), "a watch never fired"
        assert set(results) == {"py", "c", "perl"}
        # the returned version is a real firing commit's: within the span
        # of versions this test committed
        for name, v in results.items():
            assert commit_versions[0] <= v <= commit_versions[-1], (name, v)
        writer.close()
    finally:
        proc.kill()


def test_status_json_through_gateway():
    """The special-key status document (\xff\xff/status/json) is readable
    through the gateway GET op — every binding gets the status client for
    free (fdbclient/StatusClient.actor.cpp's special-key fetch path)."""
    import json

    from foundationdb_tpu.client.gateway_client import GatewayClient

    proc, port = _spawn_gateway(855)
    try:
        db = GatewayClient("127.0.0.1", port)
        raw = db.read(lambda tr: tr.get(b"\xff\xff/status/json"))
        doc = json.loads(raw)
        assert doc["cluster"]["generation"]["state"] == "fully_recovered"
        assert doc["cluster"]["configuration"]["team_sizes"] == [2, 2]
        db.close()
    finally:
        proc.kill()
