"""Observability: per-transaction pipeline timelines (g_traceBatch analog),
the flow-profiler analog, and the schema-checked status document
(flow/Trace.h:253; fdbclient/Schemas.cpp; the reference profiler)."""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.control.status import cluster_status, validate_status
from foundationdb_tpu.runtime.trace import g_trace_batch


def test_transaction_timeline_covers_pipeline_stations():
    """A sampled transaction's debug ID is traceable through client GRV,
    commit-proxy batch phases, and storage reads, in causal order."""
    c = RecoverableCluster(seed=601, n_storage_shards=1, storage_replication=2)
    g_trace_batch.clear()
    db = c.database()
    db.debug_sample_rate = 1.0

    async def main():
        tr = db.create_transaction()
        tr.set(b"obs", b"1")
        await tr.commit()
        tr2 = db.create_transaction()
        val = await tr2.get(b"obs")
        return tr.debug_id, tr2.debug_id, val

    cid, rid, val = c.run_until(c.loop.spawn(main()), 300)
    assert val == b"1"
    assert cid is not None and rid is not None and cid != rid

    commit_locs = [e["Location"] for e in g_trace_batch.timeline(cid)]
    for want in [
        "NativeAPI.createTransaction",
        "NativeAPI.getConsistentReadVersion.Before",
        "GrvProxyServer.transactionStarter.AskLiveCommittedVersion",
        "NativeAPI.getConsistentReadVersion.After",
        "NativeAPI.commit.Before",
        "CommitProxyServer.commitBatch.Before",
        "CommitProxyServer.commitBatch.GotCommitVersion",
        "CommitProxyServer.commitBatch.AfterResolution",
        "CommitProxyServer.commitBatch.AfterLogPush",
        "NativeAPI.commit.After",
    ]:
        assert want in commit_locs, f"missing {want}: {commit_locs}"
    # causal order within the commit path
    order = [commit_locs.index(x) for x in (
        "NativeAPI.commit.Before",
        "CommitProxyServer.commitBatch.GotCommitVersion",
        "CommitProxyServer.commitBatch.AfterResolution",
        "CommitProxyServer.commitBatch.AfterLogPush",
        "NativeAPI.commit.After",
    )]
    assert order == sorted(order)

    read_locs = [e["Location"] for e in g_trace_batch.timeline(rid)]
    for want in [
        "NativeAPI.getValue.Before",
        "StorageServer.getValue.Received",
        "StorageServer.getValue.Replied",
        "NativeAPI.getValue.After",
    ]:
        assert want in read_locs, f"missing {want}: {read_locs}"
    c.stop()


def test_unsampled_transactions_emit_nothing():
    c = RecoverableCluster(seed=602, n_storage_shards=1, storage_replication=2)
    g_trace_batch.clear()
    db = c.database()  # debug_sample_rate defaults to 0

    async def main():
        tr = db.create_transaction()
        tr.set(b"q", b"1")
        await tr.commit()
        return tr.debug_id

    assert c.run_until(c.loop.spawn(main()), 300) is None
    assert g_trace_batch.events == []
    c.stop()


def test_status_document_matches_schema():
    c = RecoverableCluster(seed=603, n_storage_shards=2, storage_replication=2)
    c.loop.profile = True
    db = c.database()

    async def main():
        for i in range(10):
            tr = db.create_transaction()
            tr.set(b"s%02d" % i, b"v")
            await tr.commit()

    c.run_until(c.loop.spawn(main()), 300)
    doc = cluster_status(c)
    validate_status(doc)  # raises on any schema violation
    assert doc["proxy"]["txns_committed"] >= 1
    assert doc["cluster"]["data_distribution"]["shards"] == 2
    assert doc["cluster"]["backup_running"] is False
    assert doc["profiler"]["busy_s_by_priority"]  # profiler accumulated
    c.stop()


def test_profiler_accumulates_busy_time():
    c = RecoverableCluster(seed=604, n_storage_shards=1, storage_replication=2)
    c.loop.profile = True
    db = c.database()

    async def main():
        for i in range(20):
            tr = db.create_transaction()
            tr.set(b"p%02d" % i, b"v")
            await tr.commit()

    c.run_until(c.loop.spawn(main()), 300)
    assert sum(c.loop.busy_s_by_priority.values()) > 0
    assert len(c.loop.busy_s_by_priority) > 1  # multiple priorities ran
    c.stop()
