"""Observability: per-transaction pipeline timelines (g_traceBatch analog),
the flow-profiler analog, latency bands + kernel profiling counters, and
the schema-checked status document
(flow/Trace.h:253; fdbclient/Schemas.cpp; the reference profiler)."""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.control.status import cluster_status, validate_status
from foundationdb_tpu.runtime.trace import g_trace_batch


def test_transaction_timeline_covers_pipeline_stations():
    """A sampled transaction's debug ID is traceable through client GRV,
    commit-proxy batch phases, and storage reads, in causal order."""
    c = RecoverableCluster(seed=601, n_storage_shards=1, storage_replication=2)
    g_trace_batch.clear()
    db = c.database()
    db.debug_sample_rate = 1.0

    async def main():
        tr = db.create_transaction()
        tr.set(b"obs", b"1")
        await tr.commit()
        tr2 = db.create_transaction()
        val = await tr2.get(b"obs")
        return tr.debug_id, tr2.debug_id, val

    cid, rid, val = c.run_until(c.loop.spawn(main()), 300)
    assert val == b"1"
    assert cid is not None and rid is not None and cid != rid

    commit_locs = [e["Location"] for e in g_trace_batch.timeline(cid)]
    for want in [
        "NativeAPI.createTransaction",
        "NativeAPI.getConsistentReadVersion.Before",
        "GrvProxyServer.transactionStarter.AskLiveCommittedVersion",
        "NativeAPI.getConsistentReadVersion.After",
        "NativeAPI.commit.Before",
        "CommitProxyServer.commitBatch.Before",
        "CommitProxyServer.commitBatch.GotCommitVersion",
        "CommitProxyServer.commitBatch.AfterResolution",
        "CommitProxyServer.commitBatch.AfterLogPush",
        "NativeAPI.commit.After",
    ]:
        assert want in commit_locs, f"missing {want}: {commit_locs}"
    # causal order within the commit path
    order = [commit_locs.index(x) for x in (
        "NativeAPI.commit.Before",
        "CommitProxyServer.commitBatch.GotCommitVersion",
        "CommitProxyServer.commitBatch.AfterResolution",
        "CommitProxyServer.commitBatch.AfterLogPush",
        "NativeAPI.commit.After",
    )]
    assert order == sorted(order)

    read_locs = [e["Location"] for e in g_trace_batch.timeline(rid)]
    for want in [
        "NativeAPI.getValue.Before",
        "StorageServer.getValue.Received",
        "StorageServer.getValue.Replied",
        "NativeAPI.getValue.After",
    ]:
        assert want in read_locs, f"missing {want}: {read_locs}"
    c.stop()


def test_unsampled_transactions_emit_nothing():
    c = RecoverableCluster(seed=602, n_storage_shards=1, storage_replication=2)
    g_trace_batch.clear()
    db = c.database()  # debug_sample_rate defaults to 0

    async def main():
        tr = db.create_transaction()
        tr.set(b"q", b"1")
        await tr.commit()
        return tr.debug_id

    assert c.run_until(c.loop.spawn(main()), 300) is None
    assert g_trace_batch.events == []
    c.stop()


def test_status_document_matches_schema():
    c = RecoverableCluster(seed=603, n_storage_shards=2, storage_replication=2)
    c.loop.profile = True
    db = c.database()

    async def main():
        for i in range(10):
            tr = db.create_transaction()
            tr.set(b"s%02d" % i, b"v")
            await tr.commit()

    c.run_until(c.loop.spawn(main()), 300)
    doc = cluster_status(c)
    validate_status(doc)  # raises on any schema violation
    assert doc["proxy"]["txns_committed"] >= 1
    assert doc["cluster"]["data_distribution"]["shards"] == 2
    assert doc["cluster"]["backup_running"] is False
    assert doc["profiler"]["busy_s_by_priority"]  # profiler accumulated
    c.stop()


def test_phase_profile_schema_check():
    """PHASE_PROFILE_SCHEMA guards the bench-embedded phase_timings
    artifact: a conforming doc passes, and missing/unknown/mistyped keys
    are each reported (the artifact cannot silently drift)."""
    from foundationdb_tpu.control.status import (
        PHASE_PROFILE_SCHEMA,
        check_phase_profile,
    )

    doc = {
        "backend": "cpu", "small": True, "cap": 1 << 15, "rec_cap": 1 << 12,
        "merge_impl_default": "scatter",
        "shapes": {"n_txn": 8, "n_read": 16, "n_write": 16, "cap": 1 << 15},
        "rtt_ms": 0.1, "intra_iters": 2,
        "cumulative_ms": {"search": 1.0, "FULL kernel": 4.0},
        "phases_ms": {"search": 1.0, "history": 1.0, "intra": 1.0,
                      "merge_buckets": 1.0, "full": 4.0},
        "lsm": {"full_ms": 2.0, "compact_ms": 1.0, "batches_per_compact": 4,
                "effective_ms": 2.25},
        "merge_shootout_ms": {"main2^15": {"sort": 3.0, "gather": 2.0,
                                           "scatter": 1.0}},
    }
    assert set(doc) == set(PHASE_PROFILE_SCHEMA)
    assert check_phase_profile(doc) == []
    bad = dict(doc)
    del bad["phases_ms"]
    bad["surprise"] = 1
    bad["cap"] = "not-an-int"
    problems = check_phase_profile(bad)
    assert any("missing key: phases_ms" in p for p in problems)
    assert any("unknown key: surprise" in p for p in problems)
    assert any("phase_profile.cap" in p for p in problems)


def test_profiler_accumulates_busy_time():
    c = RecoverableCluster(seed=604, n_storage_shards=1, storage_replication=2)
    c.loop.profile = True
    db = c.database()

    async def main():
        for i in range(20):
            tr = db.create_transaction()
            tr.set(b"p%02d" % i, b"v")
            await tr.commit()

    c.run_until(c.loop.spawn(main()), 300)
    assert sum(c.loop.busy_s_by_priority.values()) > 0
    assert len(c.loop.busy_s_by_priority) > 1  # multiple priorities ran
    c.stop()


# -- latency bands + kernel counters + timeline tool (observability PR) ------


def test_latency_bands_unit():
    """Metrics smoke: disjoint buckets sum to the count, percentiles order,
    merged snapshots pool correctly — the fast tier-1 regression for the
    LatencyBands/LatencyTracker primitives."""
    from foundationdb_tpu.runtime.metrics import LatencyBands, LatencyTracker

    lb = LatencyBands()
    for v in (0.0001, 0.002, 0.03, 0.3, 7.0):
        lb.add(v)
    snap = lb.snapshot()
    assert snap["count"] == 5
    assert sum(snap["bands"].values()) == 5
    assert snap["bands"]["<0.001"] == 1 and snap["bands"][">=5"] == 1

    t = LatencyTracker()
    for i in range(100):
        t.observe(i * 0.001)
    s = t.snapshot()
    assert s["count"] == 100 and sum(s["bands"].values()) == 100
    assert s["p50"] <= s["p95"] <= s["p99"] <= s["max"] == 0.099
    assert abs(s["mean"] - 0.0495) < 1e-9

    t2 = LatencyTracker()
    t2.observe(1.0)
    m = LatencyTracker.merged([t, t2])
    assert m["count"] == 101 and sum(m["bands"].values()) == 101
    assert m["max"] == 1.0 and m["p50"] < 1.0


def test_kernel_stats_uniform_across_backends():
    """Every conflict backend answers kernel_stats() with the same shape,
    so parity checks can also compare cost (tentpole seam 2)."""
    from foundationdb_tpu.conflict.api import TxInfo
    from foundationdb_tpu.conflict.device import DeviceConflictSet
    from foundationdb_tpu.conflict.oracle import OracleConflictSet

    txns = [
        TxInfo(5, [(b"a", b"b")], [(b"a", b"b")]),
        TxInfo(5, [(b"a", b"b")], []),
    ]
    oracle, device = OracleConflictSet(), DeviceConflictSet(capacity=1 << 9)
    vo = oracle.resolve_batch(10, txns)
    vd = device.resolve_batch(10, txns)
    assert vo == vd  # parity on the tiny batch
    so, sd = oracle.kernel_stats(), device.kernel_stats()
    assert set(so) == set(sd)  # ONE shape across backends
    for s in (so, sd):
        assert s["batches"] == 1 and s["txns"] == 2 and s["aborted"] == 1
        assert s["abort_rate"] == 0.5
        assert s["node_count"] > 0
        assert s["resolve_ms_p50"] >= 0
    assert so["occupancy"] == 1.0        # the oracle never pads
    assert 0 < sd["occupancy"] < 1.0     # bucketing always pads a 3-row batch
    assert sd["recompiles"] == 1
    # GC is visible uniformly too
    oracle.remove_before(8)
    device.remove_before(8)
    assert oracle.kernel_stats()["gc_calls"] == 1
    assert device.kernel_stats()["gc_calls"] == 1


def test_timeline_tool_reconstructs_stations():
    """A sampled transaction's debug ID joins >= 4 pipeline stations in
    monotonically non-decreasing time order, and the scrape surfaces
    (module API + special key) agree."""
    import json

    from foundationdb_tpu.tools.timeline import (
        format_report,
        sampled_ids,
        timeline_report,
    )

    c = RecoverableCluster(seed=611, n_storage_shards=1, storage_replication=2)
    g_trace_batch.clear()
    db = c.database()
    db.debug_sample_rate = 1.0

    async def main():
        tr = db.create_transaction()
        tr.set(b"tl", b"1")
        await tr.commit()
        tr2 = db.create_transaction()
        blob = await tr2.get(b"\xff\xff/timeline/json")
        return tr.debug_id, blob

    cid, blob = c.run_until(c.loop.spawn(main()), 300)
    rep = timeline_report(cid)
    assert rep["station_count"] >= 4
    times = [s["time"] for s in rep["stations"]]
    assert times == sorted(times)  # monotonically non-decreasing
    assert all(s["delta"] >= 0 for s in rep["stations"])
    assert rep["total_s"] > 0
    # the commit pipeline's stations are all on the journey
    locs = [s["location"] for s in rep["stations"]]
    for want in (
        "CommitProxyServer.commitBatch.Before",
        "CommitProxyServer.commitBatch.GotCommitVersion",
        "CommitProxyServer.commitBatch.AfterResolution",
        "CommitProxyServer.commitBatch.AfterLogPush",
    ):
        assert want in locs
    assert cid in sampled_ids()
    assert cid in format_report(rep)
    # the scrape endpoint serves the same reconstruction
    doc = json.loads(blob)
    assert any(t["id"] == cid for t in doc["transactions"])
    c.stop()


def test_status_latency_bands_and_kernel():
    """Acceptance: after a workload, cluster_status carries latency_bands
    (commit + GRV, bucket counts summing to total operations) and a
    populated kernel section."""
    c = RecoverableCluster(seed=612, n_storage_shards=1, storage_replication=2)
    db = c.database()

    async def main():
        for i in range(12):
            tr = db.create_transaction()
            tr.set(b"lb%02d" % i, b"v")
            await tr.commit()
        tr = db.create_transaction()
        return await tr.get(b"lb00")

    assert c.run_until(c.loop.spawn(main()), 300) == b"v"
    doc = cluster_status(c)
    validate_status(doc)
    lb = doc["latency_bands"]
    assert lb["commit"]["count"] >= 12
    assert sum(lb["commit"]["bands"].values()) == lb["commit"]["count"]
    assert lb["grv"]["count"] >= 13
    assert sum(lb["grv"]["bands"].values()) == lb["grv"]["count"]
    assert lb["commit"]["p99"] >= lb["commit"]["p50"] > 0
    for stage in ("batch_wait", "version_assign", "resolution", "tlog_push"):
        st = lb["stages"][stage]
        assert st["count"] >= 12
        assert sum(st["bands"].values()) == st["count"]
    assert lb["resolver"]["count"] >= 1
    assert lb["storage_read"]["count"] >= 1
    k = doc["kernel"]
    assert k["txns"] >= 12 and k["batches"] >= 1
    assert 0 < k["occupancy"] <= 1.0
    assert 0.0 <= k["abort_rate"] <= 1.0
    assert k["node_count"] > 0
    assert k["resolve_ms_p99"] >= k["resolve_ms_p50"] >= 0
    assert len(k["per_resolver"]) == 1
    # the roll-up carries the SAME shape as a per-backend snapshot
    assert set(k) - {"per_resolver"} == set(k["per_resolver"][0])
    assert isinstance(doc["cluster"]["messages"], list)
    c.stop()


def test_status_messages_surface_warnings_and_ratekeeper():
    """SEV_WARN+ track_latest events and a limited ratekeeper become
    operator messages."""
    from foundationdb_tpu.runtime.trace import SEV_WARN

    c = RecoverableCluster(seed=613, n_storage_shards=1, storage_replication=2)
    c.trace.trace(
        "TestDegradation", severity=SEV_WARN, track_latest="test-degraded",
        Detail="synthetic",
    )
    c.ratekeeper.limit_reason = "storage_lag"
    c.ratekeeper.limiting_server = "ss-0-r0"
    doc = cluster_status(c)
    validate_status(doc)
    names = [m["name"] for m in doc["cluster"]["messages"]]
    assert "TestDegradation" in names
    assert "performance_limited" in names
    perf = next(m for m in doc["cluster"]["messages"]
                if m["name"] == "performance_limited")
    assert "storage_lag" in perf["description"]
    assert "ss-0-r0" in perf["description"]
    c.stop()
