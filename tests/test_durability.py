"""Durability plane: DiskQueue framing/recovery, durable KV engine, durable
TLogs, and whole-cluster power-loss restart (the reference's
tests/restarting/ + AsyncFileNonDurable data-loss model —
fdbserver/DiskQueue.actor.cpp, KeyValueStoreMemory.actor.cpp,
fdbrpc/AsyncFileNonDurable.actor.h:173).
"""

import pytest

from foundationdb_tpu.roles.types import Mutation, MutationType
from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.storage.files import SimFilesystem
from foundationdb_tpu.storage.kvstore import DurableMemoryKeyValueStore


def mk_env(seed=1):
    loop = EventLoop()
    rng = DeterministicRandom(seed)
    fs = SimFilesystem(loop, rng)
    return loop, fs


def drain(loop, coro):
    return loop.run_until(loop.spawn(coro), deadline=60.0)


class TestSimFile:
    def test_unsynced_lost_on_kill(self):
        from foundationdb_tpu.rpc.network import SimNetwork

        loop = EventLoop()
        rng = DeterministicRandom(3)
        net = SimNetwork(loop, rng)
        fs = SimFilesystem(loop, rng)
        proc = net.create_process("p")
        f = fs.open("x", proc)
        f.append(b"synced")

        async def go():
            await f.sync()
            f.append(b"lost")

        drain(loop, go())
        proc.kill()
        f2 = fs.open("x", None)
        assert f2.read_all() == b"synced"

    def test_synced_survives_kill(self):
        from foundationdb_tpu.rpc.network import SimNetwork

        loop = EventLoop()
        rng = DeterministicRandom(3)
        net = SimNetwork(loop, rng)
        fs = SimFilesystem(loop, rng)
        proc = net.create_process("p")
        f = fs.open("x", proc)
        f.append(b"a")
        f.append(b"b")
        drain(loop, f.sync())
        proc.kill()
        assert fs.open("x", None).read_all() == b"ab"


class TestDiskQueue:
    def test_push_sync_recover(self):
        loop, fs = mk_env()
        dq = DiskQueue(fs.open("q", None))
        dq.push(b"one")
        dq.push(b"two")
        drain(loop, dq.sync())
        dq.push(b"unsynced")
        dq2 = DiskQueue(fs.open("q", None))
        assert dq2.recover() == [b"one", b"two"]
        assert dq2.recover(include_unsynced=True) == [b"one", b"two", b"unsynced"]

    def test_torn_tail_discarded(self):
        loop, fs = mk_env()
        dq = DiskQueue(fs.open("q", None))
        dq.push(b"good")
        drain(loop, dq.sync())
        # simulate a torn write: garbage appended and synced (e.g. a crash
        # mid-page where the frame header landed but the payload is junk)
        f = fs.open("q", None)
        f.append(b"\x01\xb7\xfdQ\x99\x00\x00\x00")  # valid magic, absurd len
        drain(loop, f.sync())
        assert DiskQueue(fs.open("q", None)).recover() == [b"good"]

    def test_corrupt_crc_discarded(self):
        import struct

        loop, fs = mk_env()
        dq = DiskQueue(fs.open("q", None))
        dq.push(b"good")
        drain(loop, dq.sync())
        f = fs.open("q", None)
        bad = struct.pack("<III", 0x51FDB701, 3, 0xDEAD) + b"xyz"
        f.append(bad)
        drain(loop, f.sync())
        assert DiskQueue(fs.open("q", None)).recover() == [b"good"]


class TestDurableKV:
    def test_commit_then_recover(self):
        loop, fs = mk_env()
        kv = DurableMemoryKeyValueStore(fs, "kv", None)
        kv.set(b"a", b"1")
        kv.set(b"b", b"2")
        drain(loop, kv.commit({"durable_version": 7}))
        kv.set(b"c", b"3")  # never committed
        kv2 = DurableMemoryKeyValueStore.recover(fs, "kv", None)
        assert kv2.get(b"a") == b"1" and kv2.get(b"b") == b"2"
        assert kv2.get(b"c") is None  # uncommitted tail dropped
        assert kv2.meta["durable_version"] == 7

    def test_clear_range_and_snapshot_cycle(self):
        loop, fs = mk_env()
        kv = DurableMemoryKeyValueStore(fs, "kv", None)
        for i in range(50):
            kv.set(b"k%03d" % i, b"v%d" % i)
        kv.clear_range(b"k010", b"k020")
        drain(loop, kv.commit())
        kv._write_snapshot()
        drain(loop, kv.commit())
        kv2 = DurableMemoryKeyValueStore.recover(fs, "kv", None)
        assert kv2.get(b"k005") == b"v5"
        assert kv2.get(b"k015") is None
        assert kv2.key_count() == 40


class TestClusterRestart:
    def test_power_loss_preserves_committed_data(self):
        """Kill the ENTIRE cluster; relaunch from files; committed data is
        all there and the cluster accepts new commits."""
        from foundationdb_tpu.control.recoverable import RecoverableCluster

        c = RecoverableCluster(seed=41, n_storage_shards=2, durable=True)
        db = c.database()

        async def write_phase():
            for i in range(8):
                tr = db.create_transaction()
                tr.set(b"key/%02d" % i, b"val%d" % i)
                await tr.commit()
            # let storage flush past the MVCC window? No: power loss happens
            # NOW, mid-window — recovery must replay from TLog files alone.

        c.run_until(c.loop.spawn(write_phase()), 60)
        fs = c.power_off()

        c2 = RecoverableCluster(seed=42, n_storage_shards=2, fs=fs, restart=True)
        db2 = c2.database()

        async def read_phase():
            tr = db2.create_transaction()
            vals = [await tr.get(b"key/%02d" % i) for i in range(8)]
            tr2 = db2.create_transaction()
            tr2.set(b"post-restart", b"yes")
            await tr2.commit()
            tr3 = db2.create_transaction()
            return vals, await tr3.get(b"post-restart")

        vals, post = c2.run_until(c2.loop.spawn(read_phase()), 120)
        assert vals == [b"val%d" % i for i in range(8)]
        assert post == b"yes"
        c2.stop()

    def test_power_loss_mid_cycle_invariant(self):
        """Cycle workload, power loss mid-run, restart: the cycle invariant
        (sum preserved) holds over the committed prefix."""
        from foundationdb_tpu.control.recoverable import RecoverableCluster
        from foundationdb_tpu.workloads.cycle import CycleWorkload
        from foundationdb_tpu.workloads.base import run_workloads

        c = RecoverableCluster(seed=43, n_storage_shards=2, durable=True)
        cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=8)
        run_workloads(c, [cyc], deadline=300.0)
        fs = c.power_off()

        c2 = RecoverableCluster(seed=44, n_storage_shards=2, fs=fs, restart=True)
        db2 = c2.database()

        async def check():
            tr = db2.create_transaction()
            rows = await tr.get_range(b"cycle/", b"cycle0", limit=1000)
            return rows

        rows = c2.run_until(c2.loop.spawn(check()), 120)
        # cycle invariant: the nodes form one permutation cycle
        kv = dict(rows)
        assert len(kv) == 8, f"expected 8 cycle nodes, got {len(kv)}"
        nxt = {int(k.split(b"/")[1]): int(v) for k, v in kv.items()}
        seen, cur = set(), 0
        for _ in range(8):
            assert cur not in seen
            seen.add(cur)
            cur = nxt[cur]
        assert cur == 0, "not a single cycle"
        c2.stop()

    def test_restart_determinism(self):
        """Same seeds, same power-loss point => identical restarted state."""
        from foundationdb_tpu.control.recoverable import RecoverableCluster

        def once():
            c = RecoverableCluster(seed=45, durable=True)
            db = c.database()

            async def w():
                for i in range(5):
                    tr = db.create_transaction()
                    tr.set(b"k%d" % i, b"v%d" % i)
                    await tr.commit()

            c.run_until(c.loop.spawn(w()), 60)
            fs = c.power_off()
            c2 = RecoverableCluster(seed=46, fs=fs, restart=True)
            db2 = c2.database()

            async def r():
                tr = db2.create_transaction()
                return [await tr.get(b"k%d" % i) for i in range(5)]

            out = c2.run_until(c2.loop.spawn(r()), 60)
            epoch = c2.controller.epoch
            c2.stop()
            return out, epoch

        assert once() == once()


class TestChaosPowerLoss:
    def _ring_ok(self, rows, nodes):
        kv = dict(rows)
        if len(kv) != nodes:
            return False
        nxt = {int(k.split(b"/")[1]): int(v) for k, v in kv.items()}
        seen, cur = set(), 0
        for _ in range(nodes):
            if cur in seen:
                return False
            seen.add(cur)
            cur = nxt[cur]
        return cur == 0

    def test_power_loss_mid_recovery_mid_cycle(self):
        """The chaos combination: Cycle running, a proxy kill triggers a
        generation recovery, and the WHOLE cluster loses power while that
        recovery is still in flight.  Restart from files: the ring invariant
        holds over the committed prefix (no half-applied rotation, no lost
        acked commit)."""
        from foundationdb_tpu.control.recoverable import RecoverableCluster
        from foundationdb_tpu.workloads.cycle import CycleWorkload

        nodes = 8
        c = RecoverableCluster(seed=47, n_storage_shards=2, n_resolvers=2)
        cyc = CycleWorkload(nodes=nodes, clients=3, txns_per_client=1000)
        rng = c.rng.split()

        async def chaos():
            await cyc.setup(c, rng.split())
            c.loop.spawn(cyc.start(c, rng.split()))
            await c.loop.delay(1.0)  # let rotations commit
            c.controller.generation.proxy.commit_stream._process.kill()
            for _ in range(10_000):  # wait for recovery to BEGIN
                if c.controller._recovering:
                    return
                await c.loop.delay(0.01)
            raise AssertionError("recovery never started")

        c.run_until(c.loop.spawn(chaos()), 120)
        assert cyc.committed > 0, "no rotations committed before the chaos"
        fs = c.power_off()

        c2 = RecoverableCluster(seed=48, n_storage_shards=2, n_resolvers=2,
                                fs=fs, restart=True)
        db2 = c2.database()

        async def check():
            tr = db2.create_transaction()
            rows = await tr.get_range(b"cycle/", b"cycle0", limit=1000)
            # and the cluster still accepts commits after the chaos
            tr2 = db2.create_transaction()
            tr2.set(b"alive", b"1")
            await tr2.commit()
            return rows

        rows = c2.run_until(c2.loop.spawn(check()), 120)
        assert self._ring_ok(rows, nodes), f"ring broken: {sorted(rows)}"
        c2.stop()

    def test_power_loss_sweep_over_kill_offsets(self):
        """Sweep the power-loss instant across the recovery window (several
        offsets after the proxy kill): every restart must keep the ring."""
        from foundationdb_tpu.control.recoverable import RecoverableCluster
        from foundationdb_tpu.workloads.cycle import CycleWorkload

        nodes = 6
        for offset in (0.0, 0.05, 0.2, 1.0, 3.0):
            c = RecoverableCluster(seed=49, n_storage_shards=2)
            cyc = CycleWorkload(nodes=nodes, clients=2, txns_per_client=1000)
            rng = c.rng.split()

            async def chaos():
                await cyc.setup(c, rng.split())
                c.loop.spawn(cyc.start(c, rng.split()))
                await c.loop.delay(0.8)
                c.controller.generation.proxy.commit_stream._process.kill()
                await c.loop.delay(offset)

            c.run_until(c.loop.spawn(chaos()), 120)
            assert cyc.committed > 0, f"offset={offset}: nothing committed"
            fs = c.power_off()
            c2 = RecoverableCluster(seed=50, n_storage_shards=2,
                                    fs=fs, restart=True)
            db2 = c2.database()

            async def check():
                tr = db2.create_transaction()
                return await tr.get_range(b"cycle/", b"cycle0", limit=1000)

            rows = c2.run_until(c2.loop.spawn(check()), 120)
            assert self._ring_ok(rows, nodes), f"offset={offset}: ring broken"
            c2.stop()


class TestSsdEngineChaos:
    """The power-loss discipline applied to the ssd (B+tree) engine: its
    COW commit protocol must give the same no-torn-state guarantee as the
    WAL memory engine under kills at arbitrary instants."""

    @staticmethod
    def _ring_ok(rows, nodes):
        data = dict(rows)
        if len(data) != nodes:
            return False
        seen, cur = set(), 0
        for _ in range(nodes):
            if cur in seen:
                return False
            seen.add(cur)
            cur = int(data[b"cycle/%04d" % cur])
        return cur == 0 and len(seen) == nodes

    def test_ssd_power_loss_sweep(self):
        from foundationdb_tpu.control.recoverable import RecoverableCluster
        from foundationdb_tpu.workloads.cycle import CycleWorkload

        nodes = 6
        for offset in (0.0, 0.2, 1.0):
            c = RecoverableCluster(seed=51, n_storage_shards=2,
                                   storage_engine="ssd")
            cyc = CycleWorkload(nodes=nodes, clients=2, txns_per_client=1000)
            rng = c.rng.split()

            async def chaos():
                await cyc.setup(c, rng.split())
                c.loop.spawn(cyc.start(c, rng.split()))
                await c.loop.delay(0.8)
                c.controller.generation.proxy.commit_stream._process.kill()
                await c.loop.delay(offset)

            c.run_until(c.loop.spawn(chaos()), 120)
            assert cyc.committed > 0, f"offset={offset}: nothing committed"
            fs = c.power_off()
            c2 = RecoverableCluster(seed=52, n_storage_shards=2,
                                    storage_engine="ssd", fs=fs, restart=True)
            db2 = c2.database()

            async def check():
                tr = db2.create_transaction()
                return await tr.get_range(b"cycle/", b"cycle0", limit=1000)

            rows = c2.run_until(c2.loop.spawn(check()), 120)
            assert self._ring_ok(rows, nodes), f"offset={offset}: ring broken"
            c2.stop()


class TestTLogResetCompat:
    def test_legacy_reset_record_still_recovers(self):
        """A disk queue written by a PRE-wire-overhaul build framed its
        RESET record per-mutation (BinaryWriter, record type _R_RESET);
        the overhaul writes struct-of-arrays _R_RESET2 records.  Old logs
        must keep recovering byte-for-byte (the compatible-addition
        contract behind the PROTOCOL_VERSION low-byte bump)."""
        from foundationdb_tpu.roles.tlog import _R_RESET, TLog
        from foundationdb_tpu.runtime.serialize import BinaryWriter, write_mutation

        tags = {
            "ss-0": [
                (5, [Mutation(MutationType.SET_VALUE, b"k", b"v"),
                     Mutation(MutationType.CLEAR_RANGE, b"a", b"z")]),
                (7, []),
            ],
            "ss-1": [],
        }
        # the OLD builds' _encode_reset, verbatim
        w = BinaryWriter().u8(_R_RESET).i64(5).i64(3)
        w.u32(len(tags))
        for tag, entries in tags.items():
            w.str_(tag).u32(len(entries))
            for v, muts in entries:
                w.i64(v).u32(len(muts))
                for m in muts:
                    write_mutation(w, m)
        loop, fs = mk_env()
        dq = DiskQueue(fs.open("old-tlog", None))
        dq.push(w.data())
        drain(loop, dq.sync())
        end, kc, got = TLog.recover_state(DiskQueue(fs.open("old-tlog", None)))
        assert (end, kc) == (5, 3)
        # legacy write_mutation collapses a None value to b"" — compare
        # against that normalization, not the wire codec's None-preserving one
        assert got == tags

    def test_new_reset_record_roundtrip(self):
        """And the NEW record (None-preserving mutation values included)
        round-trips through recover_state."""
        from foundationdb_tpu.roles.tlog import _encode_reset, TLog

        tags = {
            "t": [(9, [Mutation(MutationType.SET_VALUE, b"k", None),
                       Mutation(MutationType.ADD, b"c", b"\x01")])],
        }
        loop, fs = mk_env()
        dq = DiskQueue(fs.open("new-tlog", None))
        dq.push(_encode_reset(9, 4, tags))
        drain(loop, dq.sync())
        end, kc, got = TLog.recover_state(DiskQueue(fs.open("new-tlog", None)))
        assert (end, kc, got) == (9, 4, tags)
