"""Storage replication: 2x teams, load-balanced reads with failover, and
replica-equality consistency checking.

Reference behaviours: per-server tags with team-tagged mutations
(CommitTransaction tag fan-out), load-balanced replica reads
(fdbrpc/LoadBalance.actor.h:159), ConsistencyCheck replica equality
(fdbserver/workloads/ConsistencyCheck.actor.cpp).
"""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.workloads.attrition import AttritionWorkload
from foundationdb_tpu.workloads.bank import BankWorkload
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.consistency import ConsistencyCheckWorkload
from foundationdb_tpu.workloads.cycle import CycleWorkload


def test_replicas_converge_after_workload():
    """Every shard's replicas hold identical data after a contended run."""
    c = RecoverableCluster(seed=91, n_storage_shards=2, storage_replication=2)
    cyc = CycleWorkload(nodes=10, clients=3, txns_per_client=8)
    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cyc, cons], deadline=600.0)
    assert metrics["Cycle"]["committed"] == 24
    assert metrics["ConsistencyCheck"]["shards_checked"] == 2
    assert metrics["ConsistencyCheck"]["replicas_compared"] == 4
    assert metrics["ConsistencyCheck"]["rows_checked"] >= 10  # real data compared
    c.stop()


def test_replica_kill_loses_no_data_and_reads_continue():
    """Killing one replica of a team mid-run: reads fail over to the
    survivor, commits keep landing, and nothing is lost."""
    c = RecoverableCluster(seed=92, n_storage_shards=2, storage_replication=2)
    db = c.database()

    async def main():
        for i in range(10):
            tr = db.create_transaction()
            tr.set(b"r%02d" % i, b"v%d" % i)
            await tr.commit()

        # kill shard 0's replica 0 (storage lives outside generations, so
        # this does not trigger a pipeline recovery — reads must fail over)
        victim = next(s for s in c.storage if s.tag == "ss-0-r0")
        victim.process.kill()
        victim.stop()

        # reads still see everything (random replica picks re-route off the
        # dead endpoint), and new commits land
        for i in range(10, 20):
            tr = db.create_transaction()
            tr.set(b"r%02d" % i, b"v%d" % i)
            await tr.commit()
        tr = db.create_transaction()
        rows = await tr.get_range(b"r0", b"r2")
        return len(rows)

    n = c.run_until(c.loop.spawn(main()), 300)
    assert n == 20

    # the replicas are still internally consistent; by now data
    # distribution has healed the killed replica, so every team is whole
    # again (3 survivors + 1 replacement)
    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cons], deadline=120.0)
    assert metrics["ConsistencyCheck"]["shards_checked"] == 2
    assert metrics["ConsistencyCheck"]["replicas_compared"] >= 3
    c.stop()


def test_replication_survives_pipeline_attrition():
    """Bank invariant + replica equality through TLog/proxy kills."""
    c = RecoverableCluster(seed=93, n_storage_shards=2, storage_replication=2)
    bank = BankWorkload(accounts=6, clients=2, transfers_per_client=8)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [bank, att, cons], deadline=600.0)
    assert metrics["Bank"]["committed"] == 16
    assert metrics["ConsistencyCheck"]["shards_checked"] == 2
    c.stop()


def test_watch_fails_over_to_live_replica():
    """A watch registered while one replica is dead must land on a live one
    and still fire on the value change."""
    c = RecoverableCluster(seed=94, n_storage_shards=1, storage_replication=2)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"w", b"0")
        await tr.commit()

        victim = next(s for s in c.storage if s.tag == "ss-0-r0")
        victim.process.kill()
        victim.stop()

        fired = []
        for _ in range(4):  # several registrations: some would pick the corpse
            fut = await db.watch(b"w")
            fired.append(fut)
        tr = db.create_transaction()
        tr.set(b"w", b"1")
        await tr.commit()
        from foundationdb_tpu.runtime.combinators import wait_all

        await wait_all(fired)
        return True

    assert c.run_until(c.loop.spawn(main()), 120)
    c.stop()
