"""ManagementAPI depth: exclusion draining, database lock, coordinator
changes, maintenance mode (fdbclient/ManagementAPI.actor.cpp excludeServers /
lockDatabase / changeQuorum; fdbcli/fdbcli.actor.cpp exclude command)."""

import pytest

from foundationdb_tpu.client import management as mgmt
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.roles.types import DatabaseLocked


def test_exclude_drains_storage_under_load():
    """VERDICT r4 #3 acceptance: exclude a storage server's machine under
    load; data drains to surviving machines; the excluded processes are
    removable with zero data loss."""
    c = RecoverableCluster(
        seed=510, n_machines=6, n_dcs=2, n_storage_shards=2,
        storage_replication=2,
    )
    db = c.database()

    async def main():
        tr = db.create_transaction()
        for i in range(40):
            tr.set(b"pre%02d" % i, b"v%d" % i)
        await tr.commit()

        # pick the machine of the first storage server
        target = c.storage[0].process.machine
        assert target is not None
        victims = [
            ss for ss in c.controller.storage if ss.process.machine == target
        ]
        assert victims
        await mgmt.exclude(db, [target])

        # concurrent load while the drain runs
        async def load():
            for i in range(30):
                async def fn(tr, i=i):
                    tr.set(b"load%02d" % i, b"w%d" % i)
                await db.run(fn)
                await c.loop.delay(0.02)

        load_task = c.loop.spawn(load())

        for _ in range(600):
            await c.loop.delay(0.1)
            if mgmt.exclusion_safe(c, [target]):
                break
        assert mgmt.exclusion_safe(c, [target]), "drain never completed"
        await load_task

        # the excluded machine's processes are now removable: kill them all
        c.net.kill_machine(target)
        await c.loop.delay(2.0)

        # zero data loss: every pre-exclusion and under-drain key survives
        tr = db.create_transaction()
        pre = await tr.get_range(b"pre", b"prf")
        ld = await tr.get_range(b"load", b"loae")
        return len(pre), len(ld), [s.tag for s in victims]

    npre, nload, _tags = c.run_until(c.loop.spawn(main()), 600)
    assert npre == 40
    assert nload == 30
    assert c.dd.exclusion_drains >= 1
    c.stop()


def test_lock_unlock_and_recovery():
    c = RecoverableCluster(seed=511)
    db = c.database()

    async def main():
        async def w(tr):
            tr.set(b"before", b"1")
        await db.run(w)

        uid = await mgmt.lock_database(db)
        # wait for the conf poll to arm the proxies
        for _ in range(100):
            await c.loop.delay(0.1)
            gen = c.controller.generation
            if gen is not None and all(p.locked == uid for p in gen.proxies):
                break
        assert all(p.locked == uid for p in c.controller.generation.proxies)

        tr = db.create_transaction()
        tr.set(b"user", b"x")
        with pytest.raises(DatabaseLocked):
            await tr.commit()

        # lock-aware transactions pass (the reference's LOCK_AWARE option)
        tr = db.create_transaction()
        tr.set_option(b"lock_aware")
        tr.set(b"aware", b"y")
        await tr.commit()

        # the lock survives a recovery (it is durable \xff state)
        c.controller.generation.proxies[0].commit_stream._process.kill()
        for _ in range(300):
            await c.loop.delay(0.1)
            gen = c.controller.generation
            if (
                gen is not None and not c.controller._recovering
                and all(p.commit_stream._process.alive for p in gen.proxies)
                and all(p.locked == uid for p in gen.proxies)
            ):
                break
        tr = db.create_transaction()
        tr.set(b"user2", b"x")
        with pytest.raises(DatabaseLocked):
            await tr.commit()

        # wrong-uid unlock refused; right uid unlocks
        with pytest.raises(DatabaseLocked):
            await mgmt.unlock_database(db, b"wrong-uid")
        await mgmt.unlock_database(db, uid)
        for _ in range(100):
            await c.loop.delay(0.1)
            gen = c.controller.generation
            if gen is not None and all(p.locked is None for p in gen.proxies):
                break
        async def w2(tr):
            tr.set(b"after", b"2")
        await db.run(w2)
        return True

    assert c.run_until(c.loop.spawn(main()), 600)
    c.stop()


def test_change_coordinators_and_restart():
    """changeQuorum: swap to a 5-coordinator quorum, then power-loss restart
    — the cluster file must point recovery at the NEW registers."""
    c = RecoverableCluster(seed=512, n_coordinators=3)
    db = c.database()

    async def main():
        async def w(tr):
            tr.set(b"k", b"v")
        await db.run(w)
        await mgmt.set_coordinators(db, 5)
        for _ in range(300):
            await c.loop.delay(0.1)
            if len(c.coordinators) == 5:
                break
        assert len(c.coordinators) == 5
        # the new quorum serves recoveries: force one and write again
        async def w2(tr):
            tr.set(b"k2", b"v2")
        await db.run(w2)
        return True

    assert c.run_until(c.loop.spawn(main()), 600)
    fs = c.power_off()

    c2 = RecoverableCluster(seed=513, fs=fs, restart=True)
    db2 = c2.database()

    async def check():
        tr = db2.create_transaction()
        v1 = await tr.get(b"k")
        v2 = await tr.get(b"k2")
        return v1, v2, len(c2.coordinators)

    v1, v2, ncoord = c2.run_until(c2.loop.spawn(check()), 300)
    assert (v1, v2) == (b"v", b"v2")
    assert ncoord == 5  # restart read the moved quorum from the cluster file
    c2.stop()


def test_maintenance_suppresses_healing():
    c = RecoverableCluster(
        seed=514, n_machines=4, n_dcs=2, n_storage_shards=1,
        storage_replication=2,
    )
    db = c.database()

    async def main():
        target = c.storage[0].process.machine
        await mgmt.set_maintenance(db, target, 30.0)
        # let the conf poll pick it up
        for _ in range(100):
            await c.loop.delay(0.1)
            if c.controller.maintenance_zones:
                break
        assert target in c.controller.maintenance_zones
        c.storage[0].process.kill()
        await c.loop.delay(6.0)
        assert c.dd.heals == 0  # healing suppressed during maintenance
        await mgmt.clear_maintenance(db, target)
        for _ in range(600):
            await c.loop.delay(0.1)
            if c.dd.heals >= 1:
                break
        return c.dd.heals

    heals = c.run_until(c.loop.spawn(main()), 600)
    assert heals >= 1  # maintenance over: the dead replica heals normally
    c.stop()


def test_manual_throttle_caps_admission():
    """fdbcli `throttle`: an operator TPS ceiling composed with the
    automatic ratekeeper model; clearing restores the model's budget."""
    c = RecoverableCluster(seed=515)
    db = c.database()

    async def main():
        await mgmt.set_throttle(db, 50.0)
        for _ in range(100):
            await c.loop.delay(0.1)
            if c.ratekeeper.manual_tps_cap == 50.0:
                break
        assert c.ratekeeper.manual_tps_cap == 50.0
        # the budget converges under the ceiling
        for _ in range(100):
            await c.loop.delay(0.1)
            if c.ratekeeper.tps_budget <= 50.0:
                break
        assert c.ratekeeper.tps_budget <= 50.0
        assert c.ratekeeper.limit_reason == "manual_throttle"
        # commits still flow (throttled, not blocked)
        async def w(tr):
            tr.set(b"thr", b"1")
        await db.run(w)
        await mgmt.set_throttle(db, None)
        for _ in range(200):
            await c.loop.delay(0.1)
            if c.ratekeeper.manual_tps_cap is None and \
                    c.ratekeeper.tps_budget > 50.0:
                break
        assert c.ratekeeper.manual_tps_cap is None
        assert c.ratekeeper.tps_budget > 50.0
        return True

    assert c.run_until(c.loop.spawn(main()), 300)
    c.stop()


def test_exclude_worker_mode_pipeline_moves():
    """Worker-recruited pipeline: excluding a machine whose workers host
    pipeline roles triggers a recovery that recruits on other machines;
    include re-admits it for future recruitment."""
    c = RecoverableCluster(
        seed=516, n_machines=6, n_dcs=2, n_workers=8, n_storage_shards=1,
        storage_replication=2,
    )
    db = c.database()

    async def main():
        gen = c.controller.generation
        target = next(
            p.machine for p in gen.processes if p.machine is not None
        )
        await mgmt.exclude(db, [target])
        for _ in range(600):
            await c.loop.delay(0.1)
            gen = c.controller.generation
            if (
                gen is not None and not c.controller._recovering
                and not any(
                    c.controller.is_excluded(p) for p in gen.processes
                )
                and mgmt.exclusion_safe(c, [target])
            ):
                break
        assert not any(c.controller.is_excluded(p) for p in gen.processes)
        # commits flow on the re-recruited pipeline
        async def w(tr):
            tr.set(b"wk", b"1")
        await db.run(w)

        # include: the machine is recruitable again (no forced move back,
        # just eligibility — verify the exclusion state cleared)
        await mgmt.include(db, [target])
        for _ in range(100):
            await c.loop.delay(0.1)
            if not c.controller.excluded_targets:
                break
        assert not c.controller.excluded_targets
        assert (await mgmt.get_excluded(db)) == []
        return True

    assert c.run_until(c.loop.spawn(main()), 900)
    c.stop()


def test_round5_coverage_accounting():
    """coveragetool discipline for the round-5 rare paths: a management
    battery must actually fire the drain/lock/merge/redundancy sites."""
    from foundationdb_tpu.runtime import coverage

    coverage.reset()
    # exclusion drain under load
    test_exclude_drains_storage_under_load()
    # lock gate (refusal path) + coordinators + throttle
    test_lock_unlock_and_recovery()
    test_manual_throttle_caps_admission()

    assert coverage.hits("dd.excluded_drained") >= 1
    assert coverage.hits("proxy.database_locked") >= 1
