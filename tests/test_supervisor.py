"""DeviceSupervisor tests — watchdog, backoff, circuit breaker, graceful
CPU degradation, and parity-checked re-promotion (conflict/supervisor.py).

The invariant every test here defends: across the degrade → serve-degraded
→ re-promote cycle, the verdict stream is bit-identical to a plain CPU
oracle fed the same batches — a sick device may cost performance, never a
transaction aborted in error."""

import random

import pytest

from foundationdb_tpu.conflict.api import TxInfo, Verdict, validate_verdicts
from foundationdb_tpu.conflict.device import DeviceConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.supervisor import (
    DeviceHang,
    DeviceLost,
    DeviceSupervisor,
    Watchdog,
    classify_failure,
)
from foundationdb_tpu.runtime import buggify, coverage
from foundationdb_tpu.runtime.core import DeterministicRandom


@pytest.fixture(autouse=True)
def _buggify_off():
    yield
    buggify.disable()


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _batch_stream(seed: int, n: int, alphabet: bytes = b"abcd"):
    """Version-chained random batches (the conflict-shape generator the
    pipeline tests use, trimmed)."""
    rng = random.Random(seed)

    def rkey():
        return bytes(rng.choice(alphabet) for _ in range(rng.randrange(1, 5)))

    def rrange():
        a, b = sorted((rkey(), rkey()))
        return a, b + b"\x00"

    v = 0
    out = []
    for _ in range(n):
        v += rng.randrange(1, 4)
        out.append((
            v,
            [
                TxInfo(
                    rng.randrange(max(v - 5, 0), v),
                    [rrange() for _ in range(rng.randrange(3))],
                    [rrange() for _ in range(rng.randrange(3))],
                )
                for _ in range(rng.randrange(1, 5))
            ],
        ))
    return out


def _mk(clock, **kw):
    return DeviceSupervisor(
        lambda oldest=0: DeviceConflictSet(oldest, capacity=1 << 10),
        clock=clock,
        **kw,
    )


def _force_sites():
    """Arm buggify so only force()d sites fire (deterministic injection)."""
    buggify.enable(DeterministicRandom(1), enable_prob=0.0)


# ---------------------------------------------------------------------------
# unit pieces

def test_classify_failure_vocabulary():
    assert classify_failure(DeviceHang("x")) == "hang"
    assert classify_failure(DeviceLost("x")) == "lost"
    assert classify_failure(TimeoutError()) == "hang"
    assert classify_failure(RuntimeError("UNAVAILABLE: connection reset by peer")) == "lost"
    assert classify_failure(RuntimeError("Unable to initialize backend 'tpu'")) == "no_device"
    assert classify_failure(RuntimeError("Mosaic compilation failed")) == "compile_fail"
    assert classify_failure(RuntimeError("wat")) == "error"


def test_validate_verdicts_rejects_garbage():
    validate_verdicts([Verdict.COMMITTED, 0, 1], 3)
    with pytest.raises(ValueError, match="verdict"):
        validate_verdicts([7], 1)
    with pytest.raises(ValueError, match="verdict"):
        validate_verdicts([0, 1], 3)


def test_watchdog_wall_mode_bounds_a_hang():
    import time as _time

    wd = Watchdog(0.1, wall=True)
    assert wd.run(lambda: 42) == 42
    with pytest.raises(DeviceHang):
        wd.run(lambda: _time.sleep(5))
    # the executor was replaced: the next call is not queued behind the hang
    assert wd.run(lambda: 43) == 43
    wd.close()


# ---------------------------------------------------------------------------
# degrade -> serve-degraded -> re-promote, sync path

def test_degrade_and_repromote_parity_sync():
    """Trip the breaker mid-stream; every verdict (device, degraded-CPU,
    parity batch, post-promotion device) must match the oracle referee."""
    clock = FakeClock()
    sup = _mk(clock)
    ref = OracleConflictSet(0)
    _force_sites()
    states = []
    for i, (v, txns) in enumerate(_batch_stream(3, 50)):
        if i == 10:
            buggify.force("device.lost", int(sup.retry_limit))  # trip exactly
        clock.advance(0.7)
        assert sup.resolve_batch(v, txns) == ref.resolve_batch(v, txns), (i, v)
        if v > 8:
            sup.remove_before(v - 8)
            ref.remove_before(v - 8)
        states.append(sup.health()["state"])
    h = sup.health()
    assert "degraded" in states, "breaker never tripped"
    assert h["state"] == "healthy", h
    assert h["trips"] == 1 and h["promotions"] >= 1
    assert h["time_degraded_s"] > 0
    assert coverage.hits("device.degraded") == 1
    assert coverage.hits("device.promoted") >= 1
    assert coverage.hits("device.cpu_rebuild") >= 1
    sup.close()


def test_single_failure_retries_with_backoff_before_tripping():
    """One failure quarantines the device (served from CPU) but does not
    trip the breaker; the retry rebuild waits out the exponential backoff."""
    clock = FakeClock()
    sup = _mk(clock)
    ref = OracleConflictSet(0)
    _force_sites()
    stream = _batch_stream(5, 12)
    v0, t0 = stream[0]
    assert sup.resolve_batch(v0, t0) == ref.resolve_batch(v0, t0)
    buggify.force("device.lost", 1)
    v1, t1 = stream[1]
    assert sup.resolve_batch(v1, t1) == ref.resolve_batch(v1, t1)
    h = sup.health()
    assert h["state"] == "healthy" and h["serving"] == "cpu"
    assert h["consecutive_failures"] == 1 and h["trips"] == 0
    # inside the backoff window: still CPU, no probe attempted
    v2, t2 = stream[2]
    assert sup.resolve_batch(v2, t2) == ref.resolve_batch(v2, t2)
    assert sup.health()["serving"] == "cpu"
    # past the backoff: probe + parity batch re-promotes (the startup
    # promotion was #1 — device construction is lazy, first batch promotes)
    clock.advance(sup.max_backoff + 0.1)
    v3, t3 = stream[3]
    assert sup.resolve_batch(v3, t3) == ref.resolve_batch(v3, t3)
    assert sup.health()["serving"] == "device"
    assert sup.health()["promotions"] == 2
    sup.close()


def test_readback_corrupt_is_detected_and_served_from_cpu():
    """Garbage verdict codes from the device must be caught by validation
    (classified readback_corrupt) and the batch answered by the CPU."""
    clock = FakeClock()
    sup = _mk(clock)
    ref = OracleConflictSet(0)
    _force_sites()
    for i, (v, txns) in enumerate(_batch_stream(9, 8)):
        if i == 3:
            buggify.force("device.readback_corrupt", 1)
        assert sup.resolve_batch(v, txns) == ref.resolve_batch(v, txns), i
    assert coverage.hits("device.fail.readback_corrupt") == 1
    assert "readback_corrupt" in sup.health()["last_failure"]
    sup.close()


def test_repromotion_replays_state_bit_identically():
    """The record replay (_replay_record) must reconstruct the committed
    step function EXACTLY: replaying into a fresh oracle reproduces the
    live referee's boundary keys and versions bit-for-bit, and the first
    post-promotion batch passes the kernel parity check."""
    clock = FakeClock()
    sup = _mk(clock)
    ref = OracleConflictSet(0)
    _force_sites()
    stream = _batch_stream(11, 30)
    for i, (v, txns) in enumerate(stream[:20]):
        if i == 8:
            buggify.force("device.lost", int(sup.retry_limit))
        clock.advance(0.9)
        assert sup.resolve_batch(v, txns) == ref.resolve_batch(v, txns)
        if v > 10:
            sup.remove_before(v - 10)
            ref.remove_before(v - 10)
    # direct bit-identity of the record replay vs the live referee
    rebuilt = OracleConflictSet(0)
    sup._replay_record(rebuilt)
    if sup.oldest_version > rebuilt.oldest_version:
        rebuilt.remove_before(sup.oldest_version)
    assert rebuilt._history._keys == ref._history._keys
    assert rebuilt._history._vals == ref._history._vals
    # the promotion itself: first promoted batch is parity-checked
    clock.advance(sup.reprobe_interval + 1)
    for v, txns in stream[20:]:
        assert sup.resolve_batch(v, txns) == ref.resolve_batch(v, txns)
    assert sup.health()["state"] == "healthy"
    assert coverage.hits("device.promoted") >= 1
    sup.close()


# ---------------------------------------------------------------------------
# device loss mid-pipeline (deferred window)

@pytest.mark.parametrize("site", [
    "device.lost", "device.dispatch_hang", "device.compile_fail",
    "device.readback_corrupt",
])
def test_deferred_window_survives_device_loss(site):
    """Kill the device while a deferred window is open: the supervisor must
    replay the window through the CPU fallback and keep every verdict equal
    to the oracle's — including batches whose handles were already waited."""
    clock = FakeClock()
    sup = _mk(clock)
    ref = OracleConflictSet(0)
    _force_sites()
    handles = []
    for i, (v, txns) in enumerate(_batch_stream(21 + len(site), 36)):
        if i == 14:
            buggify.force(site, 1)
        clock.advance(0.8)
        handles.append((sup.resolve_deferred(v, txns), ref.resolve_batch(v, txns), v))
        if len(handles) >= 3:  # keep a 2-deep window open
            h, want, hv = handles.pop(0)
            assert h.wait() == want, (i, hv)
        if v > 9:
            sup.remove_before(v - 9)
            ref.remove_before(v - 9)
    for h, want, hv in handles:
        assert h.wait() == want, hv
    assert coverage.hits(f"buggify.{site}") >= 1, "site never fired"
    assert sup.health()["state"] in ("healthy", "degraded")
    sup.close()


def test_mid_window_gc_replay_order():
    """remove_before while a window is open must replay at each batch's
    dispatch-time floor: a batch dispatched BEFORE a GC must not see the
    raised floor when the window is recovered on the CPU."""
    clock = FakeClock()
    sup = _mk(clock)
    ref = OracleConflictSet(0)
    _force_sites()
    # batch 1 writes k; batch 2 reads k at a snapshot below the coming GC
    h1 = sup.resolve_deferred(10, [TxInfo(5, [], [(b"k", b"k\x00")])])
    ref.resolve_batch(10, [TxInfo(5, [], [(b"k", b"k\x00")])])
    probe = [TxInfo(8, [(b"k", b"k\x00")], [])]
    h2 = sup.resolve_deferred(12, list(probe))
    want2 = ref.resolve_batch(12, list(probe))
    # GC past the probe's snapshot AFTER batch 2 dispatched, then lose the
    # device before anything was waited
    sup.remove_before(11)
    ref.remove_before(11)
    buggify.force("device.lost", 1)
    h3 = sup.resolve_deferred(14, [TxInfo(13, [], [(b"z", b"z\x00")])])
    want3 = ref.resolve_batch(14, [TxInfo(13, [], [(b"z", b"z\x00")])])
    assert h2.wait() == want2 == [Verdict.CONFLICT]  # floor at dispatch was 0
    assert h1.wait() == [Verdict.COMMITTED]
    assert h3.wait() == want3
    assert coverage.hits("device.window_recover") >= 1
    sup.close()


# ---------------------------------------------------------------------------
# operator surface

def test_force_degrade_and_force_promote():
    clock = FakeClock()
    sup = _mk(clock)
    ref = OracleConflictSet(0)
    stream = _batch_stream(31, 10)
    v0, t0 = stream[0]
    assert sup.resolve_batch(v0, t0) == ref.resolve_batch(v0, t0)
    sup.force_degrade()
    assert sup.health()["state"] == "degraded"
    v1, t1 = stream[1]
    assert sup.resolve_batch(v1, t1) == ref.resolve_batch(v1, t1)
    # forced: a passing clock does NOT auto-promote
    clock.advance(sup.reprobe_interval * 3)
    v2, t2 = stream[2]
    assert sup.resolve_batch(v2, t2) == ref.resolve_batch(v2, t2)
    assert sup.health()["serving"] == "cpu"
    sup.force_promote()
    v3, t3 = stream[3]
    assert sup.resolve_batch(v3, t3) == ref.resolve_batch(v3, t3)
    assert sup.health()["state"] == "healthy"
    assert sup.health()["serving"] == "device"
    sup.close()


def test_lazy_construction_and_empty_batch_parity():
    """Device construction is lazy (nothing touches the device until the
    owner could arm the wall watchdog), and the promotion parity check is
    NOT satisfied by an empty batch — it stays armed until the first batch
    that actually has transactions."""
    clock = FakeClock()
    sup = _mk(clock)
    assert sup._dev is None, "constructor must not touch the device"
    ref = OracleConflictSet(0)
    # empty batches only: probed, but never promoted (nothing verified)
    assert sup.resolve_batch(2, []) == ref.resolve_batch(2, []) == []
    assert sup.resolve_batch(3, []) == ref.resolve_batch(3, []) == []
    h = sup.health()
    assert h["probes"] >= 1 and h["promotions"] == 0, h
    assert h["serving"] == "cpu"
    # the first real batch completes the parity check and promotes
    txns = [TxInfo(3, [(b"a", b"b")], [(b"a", b"b")])]
    assert sup.resolve_batch(5, list(txns)) == ref.resolve_batch(5, list(txns))
    assert sup.health()["promotions"] == 1
    assert sup.health()["serving"] == "device"
    sup.close()


def test_force_degrade_env_knob(monkeypatch):
    monkeypatch.setenv("FDBTPU_FORCE_DEGRADE", "1")
    clock = FakeClock()
    sup = _mk(clock)
    assert sup.health()["state"] == "degraded"
    assert sup.health()["serving"] == "cpu"
    ref = OracleConflictSet(0)
    for v, txns in _batch_stream(41, 6):
        clock.advance(sup.reprobe_interval + 1)  # must still not promote
        assert sup.resolve_batch(v, txns) == ref.resolve_batch(v, txns)
    assert sup.health()["serving"] == "cpu"
    sup.close()


def test_failmon_feed_and_transitions():
    from foundationdb_tpu.rpc.failmon import FailureMonitor

    clock = FakeClock()
    fm = FailureMonitor(clock)
    sup = _mk(clock)
    sup.bind_failmon(fm, "resolver0.device")
    assert fm.device_report()["resolver0.device"]["state"] == "healthy"
    assert fm.degraded_devices() == []
    t0 = fm.device_transitions
    sup.force_degrade()
    rep = fm.device_report()["resolver0.device"]
    assert rep["state"] == "degraded" and rep["trips"] == 1
    assert fm.degraded_devices() == ["resolver0.device"]
    assert fm.device_transitions > t0
    # a FAILED re-probe must not leave the monitor frozen at "probing"
    _force_sites()
    buggify.force("device.lost", 1)
    sup.force_promote()  # probe fires and dies on the forced loss
    assert fm.device_report()["resolver0.device"]["state"] == "degraded"
    assert fm.degraded_devices() == ["resolver0.device"]
    sup.close()


def test_kernel_stats_and_node_count_survive_degrade():
    clock = FakeClock()
    sup = _mk(clock)
    ref = OracleConflictSet(0)
    for v, txns in _batch_stream(51, 4):
        assert sup.resolve_batch(v, txns) == ref.resolve_batch(v, txns)
    snap = sup.kernel_stats()
    assert snap["supervisor"]["state"] == "healthy"
    sup.force_degrade()
    snap = sup.kernel_stats()
    assert snap["supervisor"]["state"] == "degraded"
    assert snap["backend"] == "oracle"  # the active (fallback) backend's stats
    assert sup.node_count >= 0
    sup.close()


def test_resolver_enables_wall_watchdog_on_real_network():
    """On the REAL network the Resolver must arm the wall-clock watchdog
    (under sim it stays off — threads there are forbidden and hangs are
    injected virtually); the sim resolver must NOT arm it."""
    from foundationdb_tpu.cluster import SimCluster
    from foundationdb_tpu.roles.resolver import Resolver
    from foundationdb_tpu.rpc.transport import RealNetwork
    from foundationdb_tpu.runtime.core import EventLoop
    from foundationdb_tpu.runtime.knobs import CoreKnobs

    loop = EventLoop()
    net = RealNetwork(loop, name="real-resolver")
    sup = _mk(FakeClock())
    r = Resolver(net.process, loop, CoreKnobs(), sup)
    assert sup._watchdog.wall, "real-network resolver left the watchdog inert"
    r.stop()
    net.close()

    c = SimCluster(seed=17)
    sup2 = _mk(FakeClock())
    p = c.net.create_process("resolver-simwd")
    r2 = Resolver(p, c.loop, c.knobs, sup2)
    assert not sup2._watchdog.wall
    r2.stop()
    c.stop()


def test_cluster_status_reports_device_health():
    """cluster_status: kernel.device roll-up + a degraded-mode message, and
    the schema still validates (the acceptance criterion's status half)."""
    from foundationdb_tpu.cluster import SimCluster
    from foundationdb_tpu.control.status import cluster_status, validate_status

    c = SimCluster(
        seed=91,
        conflict_backend=lambda: DeviceSupervisor(
            lambda oldest=0: DeviceConflictSet(oldest, capacity=1 << 10),
        ),
    )
    db = c.database()

    async def commit_one():
        tr = db.create_transaction()
        tr.set(b"k", b"v")
        await tr.commit()

    c.run_until(c.loop.spawn(commit_one()), 60.0)
    doc = cluster_status(c)
    validate_status(doc)
    dev = doc["kernel"]["device"]
    assert dev["states"]["healthy"] == len(c.resolvers)
    assert dev["trips"] == 0
    assert not any(
        m["name"] == "device_backend_degraded" for m in doc["cluster"]["messages"]
    )
    c.resolvers[0].cs.force_degrade()
    doc = cluster_status(c)
    validate_status(doc)
    assert doc["kernel"]["device"]["states"]["degraded"] == 1
    assert doc["kernel"]["device"]["serving_cpu"] == 1
    assert any(
        m["name"] == "device_backend_degraded" for m in doc["cluster"]["messages"]
    )
    c.stop()
