"""Spec-file-driven simulation runs (tester.actor.cpp readTests): every
tests/specs/*.txt is parsed, composed, and run — the tests/fast/ corpus
shape."""

import pathlib

import pytest

from foundationdb_tpu.workloads.spec import parse_spec, run_spec_file

SPEC_DIR = pathlib.Path(__file__).parent / "specs"
SPECS = sorted(SPEC_DIR.glob("*.txt"))


def test_corpus_not_empty():
    assert len(SPECS) >= 4


@pytest.mark.parametrize("spec", SPECS, ids=lambda p: p.stem)
def test_spec_file_runs_green(spec):
    metrics = run_spec_file(str(spec), deadline=900.0)
    assert metrics["testTitle"]


def test_parse_rejects_unknowns():
    with pytest.raises(ValueError, match="unknown workload"):
        parse_spec("testName=NoSuchWorkload\n")
    with pytest.raises(ValueError, match="unknown cluster key"):
        parse_spec("bogus=1\ntestName=Cycle\n")
    with pytest.raises(ValueError, match="no testName"):
        parse_spec("seed=1\n")


def test_camel_case_mapping():
    _t, ck, st = parse_spec(
        "seed=5\nchaos=true\ntestName=Cycle\ntxnsPerClient=7\n"
    )
    assert ck == {"seed": 5, "chaos": True}
    assert st == [("Cycle", {"txns_per_client": 7})]


def test_knob_override_lines():
    """`knob.NAME=value` cluster lines land in knob_overrides (and an
    unknown knob name fails loudly at cluster construction)."""
    _t, ck, _st = parse_spec(
        "seed=5\nknob.PAGE_CACHE_BYTES=8192\ntestName=Cycle\n"
    )
    assert ck["knob_overrides"] == {"PAGE_CACHE_BYTES": "8192"}
    from foundationdb_tpu.control.recoverable import RecoverableCluster

    with pytest.raises(KeyError):
        RecoverableCluster(seed=1, durable=False,
                           knob_overrides={"NO_SUCH_KNOB": "1"})
