"""KeyRangeMap — coalescing range->value map (fdbclient/KeyRangeMap.h)."""

import random

from foundationdb_tpu.utils.rangemap import KeyRangeMap


def test_assign_get_and_coalesce():
    m = KeyRangeMap(default=0)
    assert m[b""] == 0 and m[b"zzz"] == 0
    m.assign(b"b", b"f", 1)
    assert m[b"a"] == 0
    assert m[b"b"] == 1 and m[b"e"] == 1
    assert m[b"f"] == 0
    # adjacent equal values coalesce into one range
    m.assign(b"f", b"k", 1)
    assert list(m.ranges()) == [(b"", b"b", 0), (b"b", b"k", 1), (b"k", None, 0)]
    # overwrite the middle: splits both sides
    m.assign(b"d", b"g", 2)
    assert [v for _b, _e, v in m.ranges()] == [0, 1, 2, 1, 0]
    # assigning the default over everything coalesces back to one range
    m.assign(b"", None, 0)
    assert m.boundary_count == 1


def test_ranges_clipping_and_unbounded_tail():
    m = KeyRangeMap(default=b"x")
    m.assign(b"m", None, b"y")  # to +infinity
    assert m[b"zzzz"] == b"y"
    assert list(m.ranges(b"k", b"p")) == [(b"k", b"m", b"x"), (b"m", b"p", b"y")]
    assert list(m.ranges(b"q")) == [(b"q", None, b"y")]


def test_merge_combines_per_subrange():
    m = KeyRangeMap(default=0)
    m.assign(b"c", b"h", 5)
    m.merge(b"a", b"e", 3, max)  # floors merged by max
    assert [(b, v) for b, _e, v in m.ranges()] == [
        (b"", 0), (b"a", 3), (b"c", 5), (b"h", 0)
    ]
    m.merge(b"c", b"h", 9, max)
    assert m[b"d"] == 9


def test_map_values_clamp():
    m = KeyRangeMap(default=0)
    m.assign(b"a", b"b", 3)
    m.assign(b"c", b"d", 7)
    m.map_values(lambda v: 0 if v < 5 else v)
    assert [v for _b, _e, v in m.ranges()] == [0, 7, 0]


def test_randomized_against_model():
    """Model check: the map must agree with a brute-force dict over a
    discretized keyspace for any interleaving of assigns and merges."""
    rng = random.Random(7)
    keys = [bytes([k]) for k in range(16)]
    m = KeyRangeMap(default=0)
    model = {k: 0 for k in keys}
    for _ in range(300):
        a, b = sorted((rng.randrange(16), rng.randrange(17)))
        begin = bytes([a])
        end = None if b == 16 else bytes([b])
        v = rng.randrange(5)
        if rng.random() < 0.5:
            m.assign(begin, end, v)
            for k in keys:
                if k >= begin and (end is None or k < end):
                    model[k] = v
        else:
            m.merge(begin, end, v, max)
            for k in keys:
                if k >= begin and (end is None or k < end):
                    model[k] = max(model[k], v)
        for k in keys:
            assert m[k] == model[k], (k, m._keys, m._vals)
        # coalescing invariant: no equal adjacent values
        vs = m._vals
        assert all(vs[i] != vs[i + 1] for i in range(len(vs) - 1))
