"""KeyRangeMap — coalescing range->value map (fdbclient/KeyRangeMap.h) —
and KeyPartitionMap's bisect range routing (roles/proxy.py), refereed
against the old per-partition clip loop."""

import random

from foundationdb_tpu.conflict.api import TxInfo
from foundationdb_tpu.roles.proxy import KeyPartitionMap
from foundationdb_tpu.utils.rangemap import KeyRangeMap


def test_assign_get_and_coalesce():
    m = KeyRangeMap(default=0)
    assert m[b""] == 0 and m[b"zzz"] == 0
    m.assign(b"b", b"f", 1)
    assert m[b"a"] == 0
    assert m[b"b"] == 1 and m[b"e"] == 1
    assert m[b"f"] == 0
    # adjacent equal values coalesce into one range
    m.assign(b"f", b"k", 1)
    assert list(m.ranges()) == [(b"", b"b", 0), (b"b", b"k", 1), (b"k", None, 0)]
    # overwrite the middle: splits both sides
    m.assign(b"d", b"g", 2)
    assert [v for _b, _e, v in m.ranges()] == [0, 1, 2, 1, 0]
    # assigning the default over everything coalesces back to one range
    m.assign(b"", None, 0)
    assert m.boundary_count == 1


def test_ranges_clipping_and_unbounded_tail():
    m = KeyRangeMap(default=b"x")
    m.assign(b"m", None, b"y")  # to +infinity
    assert m[b"zzzz"] == b"y"
    assert list(m.ranges(b"k", b"p")) == [(b"k", b"m", b"x"), (b"m", b"p", b"y")]
    assert list(m.ranges(b"q")) == [(b"q", None, b"y")]


def test_merge_combines_per_subrange():
    m = KeyRangeMap(default=0)
    m.assign(b"c", b"h", 5)
    m.merge(b"a", b"e", 3, max)  # floors merged by max
    assert [(b, v) for b, _e, v in m.ranges()] == [
        (b"", 0), (b"a", 3), (b"c", 5), (b"h", 0)
    ]
    m.merge(b"c", b"h", 9, max)
    assert m[b"d"] == 9


def test_map_values_clamp():
    m = KeyRangeMap(default=0)
    m.assign(b"a", b"b", 3)
    m.assign(b"c", b"d", 7)
    m.map_values(lambda v: 0 if v < 5 else v)
    assert [v for _b, _e, v in m.ranges()] == [0, 7, 0]


def test_randomized_against_model():
    """Model check: the map must agree with a brute-force dict over a
    discretized keyspace for any interleaving of assigns and merges."""
    rng = random.Random(7)
    keys = [bytes([k]) for k in range(16)]
    m = KeyRangeMap(default=0)
    model = {k: 0 for k in keys}
    for _ in range(300):
        a, b = sorted((rng.randrange(16), rng.randrange(17)))
        begin = bytes([a])
        end = None if b == 16 else bytes([b])
        v = rng.randrange(5)
        if rng.random() < 0.5:
            m.assign(begin, end, v)
            for k in keys:
                if k >= begin and (end is None or k < end):
                    model[k] = v
        else:
            m.merge(begin, end, v, max)
            for k in keys:
                if k >= begin and (end is None or k < end):
                    model[k] = max(model[k], v)
        for k in keys:
            assert m[k] == model[k], (k, m._keys, m._vals)
        # coalescing invariant: no equal adjacent values
        vs = m._vals
        assert all(vs[i] != vs[i + 1] for i in range(len(vs) - 1))


# ---------------------------------------------------------------------------
# KeyPartitionMap bisect routing (the proxy's phase-2/phase-4 workhorse)


def _clip_loop_route(pmap: KeyPartitionMap, ranges) -> dict:
    """The OLD phase-2 routing: every partition clip-probes every range.
    Kept here as the referee oracle for split_ranges."""
    out = {}
    for r in range(len(pmap.members)):
        clipped = [c for b, e in ranges if (c := pmap.clip_to_member(r, b, e))]
        if clipped:
            out[r] = clipped
    return out


def test_partition_span_edges():
    pmap = KeyPartitionMap([b"c", b"f"], [0, 1, 2])
    # range spanning ALL partitions
    assert pmap.span_for_range(b"", b"\xff") == (0, 2)
    assert pmap.split_ranges([(b"", b"\xff")]) == {
        0: [(b"", b"c")], 1: [(b"c", b"f")], 2: [(b"f", b"\xff")]
    }
    # begin == split key: routes RIGHT of the split (member_for_key parity)
    assert pmap.span_for_range(b"c", b"d") == (1, 1)
    assert pmap.split_ranges([(b"c", b"d")]) == {1: [(b"c", b"d")]}
    assert pmap.member_for_key(b"c") == 1
    # end == split key: the left partition's piece keeps `end` uncut and
    # the right partition is NOT touched (half-open ranges)
    assert pmap.span_for_range(b"a", b"c") == (0, 0)
    assert pmap.split_ranges([(b"a", b"c")]) == {0: [(b"a", b"c")]}
    # empty range: clips to nothing anywhere
    assert pmap.span_for_range(b"d", b"d") == (0, -1)
    assert pmap.split_ranges([(b"d", b"d"), (b"e", b"d")]) == {}
    assert pmap.members_for_range(b"d", b"d") == []
    # piece order within a partition follows input range order
    got = pmap.split_ranges([(b"x", b"z"), (b"g", b"h")])
    assert got == {2: [(b"x", b"z"), (b"g", b"h")]}


def test_partition_no_splits_single_member():
    pmap = KeyPartitionMap([], ["only"])
    assert pmap.split_ranges([(b"a", b"b"), (b"", b"\xff" * 9)]) == {
        0: [(b"a", b"b"), (b"", b"\xff" * 9)]
    }
    assert pmap.position_for_key(b"anything") == 0


def test_partition_split_ranges_referee_randomized():
    """Randomized referee: bisect routing must produce BYTE-IDENTICAL
    per-partition clipped pieces vs the old all-partition clip loop, over
    random split maps (including duplicate-prefix splits) and adversarial
    ranges (empty, inverted, on-split boundaries, full-keyspace)."""
    rng = random.Random(2026)
    for trial in range(300):
        n_splits = rng.randrange(0, 9)
        splits = sorted({bytes([rng.randrange(1, 255)]) + (b"\x00" * rng.randrange(2))
                         for _ in range(n_splits)})
        pmap = KeyPartitionMap(splits, list(range(len(splits) + 1)))
        ranges = []
        for _ in range(rng.randrange(1, 12)):
            pick = rng.random()
            if pick < 0.25 and splits:
                b = rng.choice(splits)  # begin exactly on a split key
            else:
                b = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 3)))
            if pick > 0.8 and splits:
                e = rng.choice(splits)  # end exactly on a split key
            else:
                e = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 3)))
            if rng.random() < 0.15:
                e = b  # empty
            ranges.append((b, e))
        assert pmap.split_ranges(ranges) == _clip_loop_route(pmap, ranges), (
            trial, splits, ranges
        )


def test_partition_phase2_txinfo_referee():
    """End-to-end phase-2 referee: per-resolver TxInfo lists assembled via
    split_ranges are equal (dataclass-equal, which is field/byte equality)
    to the old clip-loop assembly, including the empty-TxInfo padding for
    untouched resolvers."""
    rng = random.Random(7)
    splits = [b"d", b"m", b"t"]
    pmap = KeyPartitionMap(splits, [0, 1, 2, 3])
    n_res = 4

    def rkey():
        return bytes(rng.randrange(97, 123) for _ in range(rng.randrange(0, 3)))

    for _ in range(60):
        txns = []
        for _ in range(rng.randrange(1, 6)):
            rr = [tuple(sorted((rkey(), rkey()))) for _ in range(rng.randrange(3))]
            wr = [tuple(sorted((rkey(), rkey()))) for _ in range(rng.randrange(3))]
            txns.append((rng.randrange(20), rr, wr))
        # old assembly
        old = [[] for _ in range(n_res)]
        for snap, rr, wr in txns:
            for r in range(n_res):
                crr = [c for b, e in rr if (c := pmap.clip_to_member(r, b, e))]
                cwr = [c for b, e in wr if (c := pmap.clip_to_member(r, b, e))]
                old[r].append(TxInfo(snap, crr, cwr))
        # new assembly (mirrors roles/proxy.py phase 2)
        new = [[] for _ in range(n_res)]
        for snap, rr, wr in txns:
            rr_by = pmap.split_ranges(rr)
            wr_by = pmap.split_ranges(wr)
            for r in range(n_res):
                crr = rr_by.get(r)
                cwr = wr_by.get(r)
                new[r].append(TxInfo(snap, crr or [], cwr or []))
        assert new == old
