"""Worker bootstrap: registration, fitness-ordered RPC recruitment, worker
survival across generations, and the fdbmonitor restart loop
(fdbserver/worker.actor.cpp:577; ClusterController registerWorker;
fdbmonitor/fdbmonitor.cpp)."""

from foundationdb_tpu.control.recoverable import RecoverableCluster


def _commit_n(c, db, n, prefix=b"w"):
    async def main():
        for i in range(n):
            tr = db.create_transaction()
            tr.set(prefix + b"%03d" % i, b"v%d" % i)
            await tr.commit()

        async def fn(tr):
            return await tr.get_range(prefix, prefix + b"\xff", limit=10000)

        return await db.run(fn)

    return c.run_until(c.loop.spawn(main()), 900)


def test_roles_recruited_onto_workers_with_fitness():
    c = RecoverableCluster(seed=901, n_storage_shards=1, storage_replication=2,
                           n_tlogs=2, n_proxies=2, n_workers=8)
    gen = c.controller.generation
    assert gen.workers, "no worker hosted any role"
    worker_addrs = {w.process.address for w in c.workers}
    assert all(p.address in worker_addrs for p in gen.processes)
    # fitness: every TLog sits on a transaction-class worker (enough exist)
    by_addr = {w.process.address: w for w in c.workers}
    for t in gen.tlogs:
        host = by_addr[t.commit_stream.endpoint.address]
        assert host.pclass == "transaction"
    rows = _commit_n(c, c.database(), 20)
    assert len(rows) == 20
    c.stop()


def test_workers_survive_generation_changes():
    """A pipeline kill triggers recovery; the NEW generation is recruited
    onto the same worker pool, and the old generation's roles are destroyed
    without killing any worker."""
    c = RecoverableCluster(seed=902, n_storage_shards=1, storage_replication=2,
                           n_workers=8)
    db = c.database()
    _commit_n(c, db, 5, prefix=b"a")
    gen1 = c.controller.generation
    victim = gen1.tlogs[0]

    async def main():
        epoch = c.controller.epoch
        victim.process.kill()  # kills the WORKER hosting that tlog
        for _ in range(600):
            if c.controller.epoch > epoch and c.controller.generation:
                break
            await c.loop.delay(0.1)
        assert c.controller.epoch > epoch
        return True

    assert c.run_until(c.loop.spawn(main()), 900)
    rows = _commit_n(c, db, 5, prefix=b"b")
    assert len(rows) == 5
    gen2 = c.controller.generation
    assert gen2.workers
    # surviving workers from gen1 are still alive and have dropped gen1's
    # roles (DestroyGenerationRequest)
    survivors = [w for w in c.workers if w.process.alive]
    assert len(survivors) >= 7
    assert all(gen1.epoch not in w.hosted for w in survivors)
    c.stop()


def test_fdbmonitor_restarts_dead_worker():
    c = RecoverableCluster(seed=903, n_storage_shards=1, storage_replication=2,
                           n_workers=6)
    db = c.database()
    _commit_n(c, db, 3)
    victim = c.workers[0]
    victim.process.kill()

    async def wait_restart():
        for _ in range(100):
            if c.workers[0] is not victim and c.workers[0].process.alive:
                return True
            await c.loop.delay(0.2)
        return False

    assert c.run_until(c.loop.spawn(wait_restart()), 600)
    # the replacement registers and becomes recruitable: force a recovery
    # and verify the cluster still works end-to-end
    async def main():
        epoch = c.controller.epoch
        c.controller.generation.sequencer.stream._process.kill()
        for _ in range(600):
            if c.controller.epoch > epoch and c.controller.generation:
                break
            await c.loop.delay(0.1)
        return c.controller.epoch > epoch

    assert c.run_until(c.loop.spawn(main()), 900)
    rows = _commit_n(c, db, 4, prefix=b"c")
    assert len(rows) == 4
    c.stop()


def test_worker_cluster_durability_roundtrip():
    """Worker-recruited TLogs still land durable files: power-off + restart
    recovers everything."""
    c = RecoverableCluster(seed=904, n_storage_shards=1, storage_replication=2,
                           n_workers=6)
    db = c.database()
    _commit_n(c, db, 15)

    async def settle():
        await c.loop.delay(6.0)

    c.run_until(c.loop.spawn(settle()), 600)
    fs = c.power_off()
    c2 = RecoverableCluster(seed=905, n_storage_shards=1,
                            storage_replication=2, fs=fs, restart=True,
                            n_workers=6)
    rows = _commit_n(c2, c2.database(), 0)
    assert len(rows) == 15
    c2.stop()


def test_tlog_refuses_pre_epoch_versions():
    """A TLog must never duplicate-ack a version at or below its epoch
    start: such a push comes from a DEPOSED generation's zombie batch that
    reached a successor role (regression for the phantom-ack hole found by
    the chaos soak — the client would get COMMITTED for data nobody
    stored)."""
    from foundationdb_tpu.roles.tlog import TLog
    from foundationdb_tpu.roles.types import TLogCommitRequest
    from foundationdb_tpu.rpc.network import SimNetwork
    from foundationdb_tpu.rpc.stream import RequestStreamRef
    from foundationdb_tpu.runtime.core import (
        DeterministicRandom,
        EventLoop,
        TimedOut,
    )
    from foundationdb_tpu.runtime.trace import TraceCollector

    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(1), TraceCollector())
    p = net.create_process("tlog")
    t = TLog(p, loop, start_version=2_000_000, sync_delay=0.0)
    cc = net.create_process("caller")
    ref = RequestStreamRef(net, cc, t.commit_stream.endpoint)

    async def main():
        # a stale push from a deposed generation (version below the epoch
        # start): must NOT be acked — the caller times out instead
        try:
            await ref.get_reply(
                TLogCommitRequest(1_110_000, 1_111_171, {}, known_committed=0),
                timeout=0.5,
            )
            return "acked"
        except TimedOut:
            return "refused"

    assert loop.run_until(loop.spawn(main()), 60) == "refused"
    t.stop()
