"""Regression tests for the round-2 advisor findings (ADVICE.md):

1. (high) DiskQueue.rewrite() destroyed the synced prefix before the
   replacement snapshot was durable — a power loss between compaction and
   the next fsync recovered an EMPTY log (lost acked commits).  truncate()
   is now journaled: the old synced contents survive until the next
   successful sync().
2. (medium) Whole-cluster restart enumerated TLog slots with the NEW
   config's n_tlogs, silently skipping higher-slot files when restarting
   with fewer slots — losing tags whose replica pair lived in the dropped
   slots.  Recovery now uses the slot count recorded in the cstate write.
3. (low) A fresh-but-lower request_num was silently dropped as a "stale
   retry", wedging an out-of-order in-flight batch until the proxy's
   give-up deadline forced an unnecessary recovery.  The sequencer now only
   goes silent for request_nums actually evicted after assignment.
"""

from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.storage.files import SimFilesystem


def mk_env(seed=1):
    loop = EventLoop()
    rng = DeterministicRandom(seed)
    fs = SimFilesystem(loop, rng)
    return loop, fs


def drain(loop, coro):
    return loop.run_until(loop.spawn(coro), deadline=60.0)


class TestRewriteCrashWindow:
    def test_unsynced_rewrite_recovers_old_contents(self):
        """Crash between rewrite() and the next sync(): recovery must see
        the PRE-compaction log, never an empty file."""
        loop, fs = mk_env()
        dq = DiskQueue(fs.open("q", None))
        dq.push(b"one")
        dq.push(b"two")
        drain(loop, dq.sync())
        dq.rewrite([b"snapshot"])
        # no sync: the power loss happens here
        assert DiskQueue(fs.open("q", None)).recover() == [b"one", b"two"]
        # same-process readers see the compacted view
        assert dq.recover(include_unsynced=True) == [b"snapshot"]
        # once synced, the replacement is the durable contents
        drain(loop, dq.sync())
        assert DiskQueue(fs.open("q", None)).recover() == [b"snapshot"]

    def test_rewrite_then_pushes_then_sync(self):
        """Records pushed after an unsynced rewrite become durable together
        with the truncate at the next sync (no torn half-state)."""
        loop, fs = mk_env()
        dq = DiskQueue(fs.open("q", None))
        dq.push(b"old")
        drain(loop, dq.sync())
        dq.rewrite([b"snap"])
        dq.push(b"later")
        assert DiskQueue(fs.open("q", None)).recover() == [b"old"]
        drain(loop, dq.sync())
        assert DiskQueue(fs.open("q", None)).recover() == [b"snap", b"later"]

    def test_kvstore_snapshot_crash_window(self):
        """A crash during the fsync latency of the commit that carries a
        snapshot rewrite must recover the old committed state, not empty."""
        from foundationdb_tpu.storage.kvstore import DurableMemoryKeyValueStore

        loop, fs = mk_env()
        kv = DurableMemoryKeyValueStore(fs, "kv", None)
        kv.set(b"a", b"1")
        kv.set(b"b", b"2")
        drain(loop, kv.commit({"durable_version": 5}))
        kv._write_snapshot()  # compaction staged, NOT yet durable — crash now
        kv2 = DurableMemoryKeyValueStore.recover(fs, "kv", None)
        assert kv2.get(b"a") == b"1" and kv2.get(b"b") == b"2"
        assert kv2.meta["durable_version"] == 5

    def test_recover_resnapshot_crash_window(self):
        """recover() itself re-logs a fresh snapshot without syncing; a
        second crash before any commit must STILL recover the data."""
        from foundationdb_tpu.storage.kvstore import DurableMemoryKeyValueStore

        loop, fs = mk_env()
        kv = DurableMemoryKeyValueStore(fs, "kv", None)
        kv.set(b"a", b"1")
        drain(loop, kv.commit())
        kv2 = DurableMemoryKeyValueStore.recover(fs, "kv", None)
        # crash immediately after recovery (its snapshot is unsynced)
        kv3 = DurableMemoryKeyValueStore.recover(fs, "kv", None)
        assert kv3.get(b"a") == b"1"


class TestRestartFewerTLogSlots:
    def test_restart_with_fewer_slots_keeps_all_tags(self):
        """Previous epoch ran 4 TLog slots (tag ss-2's replica pair lives
        entirely in slots 2,3); restarting with 2 slots must still replay
        those files or shard 2's data silently vanishes."""
        from foundationdb_tpu.control.recoverable import RecoverableCluster

        c = RecoverableCluster(
            seed=61, n_storage_shards=3, n_tlogs=4, durable=True
        )
        db = c.database()
        keys = [b"\x10low", b"\x70mid", b"\xcchigh"]  # one key per shard

        async def write_phase():
            for i, k in enumerate(keys):
                tr = db.create_transaction()
                tr.set(k, b"v%d" % i)
                await tr.commit()

        c.run_until(c.loop.spawn(write_phase()), 60)
        fs = c.power_off()

        c2 = RecoverableCluster(
            seed=62, n_storage_shards=3, n_tlogs=2, fs=fs, restart=True
        )
        db2 = c2.database()

        async def read_phase():
            tr = db2.create_transaction()
            return [await tr.get(k) for k in keys]

        vals = c2.run_until(c2.loop.spawn(read_phase()), 120)
        assert vals == [b"v0", b"v1", b"v2"]
        c2.stop()


class TestSequencerOutOfOrder:
    def _mk(self):
        from foundationdb_tpu.roles.sequencer import Sequencer
        from foundationdb_tpu.rpc.network import SimNetwork
        from foundationdb_tpu.rpc.stream import RequestStreamRef
        from foundationdb_tpu.runtime.knobs import CoreKnobs

        loop = EventLoop()
        net = SimNetwork(loop, DeterministicRandom(9))
        seq = Sequencer(net.create_process("seq"), loop, CoreKnobs())
        ref = RequestStreamRef(
            net, net.create_process("proxy"), seq.stream.endpoint
        )
        return loop, seq, ref

    def test_fresh_lower_request_num_gets_version(self):
        """request 2 arrives before request 1 (independent pipelined batch
        retries reordered by the network): BOTH must be assigned versions."""
        from foundationdb_tpu.roles.types import GetCommitVersionRequest

        loop, seq, ref = self._mk()

        async def main():
            b = await ref.get_reply(GetCommitVersionRequest("p1", 2))
            a = await ref.get_reply(GetCommitVersionRequest("p1", 1), timeout=2.0)
            return a, b

        a, b = loop.run_until(loop.spawn(main()), deadline=10.0)
        assert b.version > 0
        assert a.version > b.version  # fresh assignment, chained after b
        assert a.prev_version == b.version

    def test_evicted_request_num_stays_silent(self):
        """A retry of a request_num that was evicted after assignment may
        already hold a version: the sequencer must NOT assign a fresh one."""
        from foundationdb_tpu.roles.types import GetCommitVersionRequest
        from foundationdb_tpu.runtime.core import TimedOut

        loop, seq, ref = self._mk()
        seq._cache_cap = 2

        async def main():
            for n in (1, 2, 3, 4):  # evicts 1 and 2 from the cache
                await ref.get_reply(GetCommitVersionRequest("p1", n))
            try:
                await ref.get_reply(GetCommitVersionRequest("p1", 1), timeout=0.5)
                return "replied"
            except TimedOut:
                return "silent"

        assert loop.run_until(loop.spawn(main()), deadline=10.0) == "silent"


class TestLostReplicaPair:
    def test_both_slots_of_a_pair_missing_is_an_error(self):
        """If BOTH files of some tag's old replica pair are gone, restart
        must fail loudly (data loss), not quietly proceed without the tag."""
        import pytest

        from foundationdb_tpu.control.recoverable import RecoverableCluster

        c = RecoverableCluster(
            seed=63, n_storage_shards=3, n_tlogs=4, durable=True
        )
        db = c.database()

        async def write_phase():
            tr = db.create_transaction()
            tr.set(b"\xcchigh", b"v")  # shard 2 -> tag ss-2 -> slots {2,3}
            await tr.commit()

        c.run_until(c.loop.spawn(write_phase()), 60)
        fs = c.power_off()
        for path in fs.list("tlog2"):
            fs.delete(path)
        for path in fs.list("tlog3"):
            fs.delete(path)
        with pytest.raises(Exception, match="ss-2.*data loss|lost cstate|data loss"):
            RecoverableCluster(
                seed=64, n_storage_shards=3, n_tlogs=4, fs=fs, restart=True
            )
