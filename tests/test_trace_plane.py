"""Distributed commit-path tracing plane (docs/OBSERVABILITY.md
"Distributed tracing"): flight-recorder ring retention, rate-converted
counters, severity filtering + rolling trace files, wire-propagated spans,
the periodic per-role `*Metrics` emission, the trace_tool join, and the
sampling-off overhead contract.  The WARN+ event-type and metrics-schema
AST guards that lived here migrated into flowlint (PR 9: `warn-events` /
`metrics-schema` rules in foundationdb_tpu/lint/rules_registry.py); the
thin wrappers below prove those rules still fire on their bad fixtures."""

from __future__ import annotations

import json
import os
import pathlib
import time

from foundationdb_tpu.cluster import SimCluster
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.control.status import validate_metrics_event
from foundationdb_tpu.runtime.knobs import CoreKnobs
from foundationdb_tpu.runtime.trace import (
    SEV_DEBUG,
    SEV_WARN,
    CounterCollection,
    TraceCollector,
    TraceFileSink,
    g_trace_batch,
)


# -- satellite: flight-recorder retention ------------------------------------


def test_trace_collector_ring_keeps_newest():
    """A flight recorder keeps the NEWEST events: the ring overwrites the
    oldest, and count() still reports every event ever traced."""
    tc = TraceCollector(keep=5)
    for i in range(12):
        tc.trace("RingEv", I=i)
    assert tc.count("RingEv") == 12
    assert len(tc.events) == 5
    assert [e["I"] for e in tc.find("RingEv")] == [7, 8, 9, 10, 11]
    # a different type interleaved still counts correctly after overwrite
    tc.trace("OtherEv")
    assert tc.count("OtherEv") == 1
    assert tc.count("RingEv") == 12
    assert len(tc.events) == 5  # ring bound holds


def test_trace_severity_filter():
    """Events below TRACE_SEVERITY are dropped entirely (ring, latest,
    count) — the reference's --trace severity floor."""
    tc = TraceCollector(min_severity=SEV_WARN)
    tc.trace("Quiet", severity=SEV_DEBUG, track_latest="q")
    tc.trace("Loud", severity=SEV_WARN, track_latest="l")
    assert tc.count("Quiet") == 0 and not tc.find("Quiet")
    assert "q" not in tc.latest
    assert tc.count("Loud") == 1 and "l" in tc.latest


# -- satellite: rate-converted counters --------------------------------------


def test_counter_collection_rates():
    """rates() reports per-second deltas against the remembered previous
    snapshot (Counter::getRate) — not lifetime totals."""
    cc = CounterCollection("T")
    a = cc.counter("a")
    b = cc.counter("b")
    a.add(100)
    assert cc.rates(10.0) == {"a": 0.0, "b": 0.0}  # first call arms
    a.add(30)
    b.add(4)
    r = cc.rates(12.0)
    assert r == {"a": 15.0, "b": 2.0}
    r2 = cc.rates(13.0)  # nothing moved since the last call
    assert r2 == {"a": 0.0, "b": 0.0}
    # snapshot() still reports absolute values
    assert cc.snapshot() == {"a": 130, "b": 4}


# -- rolling trace files -----------------------------------------------------


def test_trace_file_rolling(tmp_path):
    """TRACE_ROLL_SIZE/TRACE_MAX_LOGS analogs: files roll by size, old
    generations are deleted, and every line is complete JSON (line-buffered
    crash-safe flush)."""
    base = str(tmp_path / "trace")
    sink = TraceFileSink(base, roll_size=400, max_logs=2)
    tc = TraceCollector(sink=sink, machine="m0")
    for i in range(40):
        tc.trace("RollEv", I=i, Pad="x" * 50)
    files = sink.files()
    assert len(files) >= 2, "expected the sink to roll"
    assert len(files) <= 2, "max_logs must bound retained generations"
    # the oldest generation was deleted
    assert not os.path.exists(base + ".0.jsonl")
    seen = []
    for f in files:
        for line in open(f):
            ev = json.loads(line)  # complete JSON on every line
            assert ev["Machine"] == "m0"
            assert "WallTime" in ev  # the cross-process join clock
            seen.append(ev["I"])
    assert seen == sorted(seen)
    assert seen[-1] == 39  # the newest event survived the rolls
    sink.close()


def test_trace_file_sink_resumes_after_pruned_run(tmp_path):
    """A restarted process must resume ABOVE the previous run's newest
    generation even when pruning deleted the low sequence numbers — not
    re-open seq 0 and later append into the old run's surviving files."""
    base = str(tmp_path / "trace")
    s1 = TraceFileSink(base, roll_size=80, max_logs=2)
    for i in range(30):
        s1.write(json.dumps({"I": i}) + "\n")
    s1.close()
    survivors = sorted(s1.files())
    assert len(survivors) == 2 and not os.path.exists(base + ".0.jsonl")
    prev_max = max(int(f.rsplit(".", 2)[1]) for f in survivors)

    s2 = TraceFileSink(base, roll_size=80, max_logs=2)
    s2.write(json.dumps({"I": "restart"}) + "\n")
    s2.close()
    assert s2.current_file == f"{base}.{prev_max + 1}.jsonl"
    # the old run's files were not touched
    for f in survivors:
        assert all(json.loads(l)["I"] != "restart" for l in open(f))


# -- wire-propagated spans ---------------------------------------------------


def test_rpc_envelope_spans_codec():
    """The RpcMessage codec: spanless envelopes keep tag 60 (zero extra
    bytes on the un-sampled path); span-carrying ones ride tag 61 and
    round-trip exactly."""
    import struct

    from foundationdb_tpu.rpc.stream import RpcMessage
    from foundationdb_tpu.runtime import serialize as wire

    plain = RpcMessage(b"payload")
    blob = wire.encode_payload(plain)
    assert struct.unpack_from("<H", blob, 0)[0] == 60
    assert wire.decode_payload(blob) == plain

    spanned = RpcMessage(b"payload", None, ("dbg-1", "dbg-2"))
    blob2 = wire.encode_payload(spanned)
    assert struct.unpack_from("<H", blob2, 0)[0] == 61
    back = wire.decode_payload(blob2)
    assert back == spanned and back.spans == ("dbg-1", "dbg-2")
    # the span prefix costs exactly its own bytes: the envelope after it
    # is byte-identical to the spanless layout
    assert blob2.endswith(blob[2:])


def test_sampled_commit_spans_cross_roles():
    """A sampled transaction's debug ID propagates through the RpcMessage
    envelope into the resolver, TLog, and sequencer stations — the
    stations the in-process proxy loop cannot emit for them."""
    c = SimCluster(seed=31, n_resolvers=2, n_tlogs=2)
    g_trace_batch.attach_clock(c.loop.now, c.trace)
    db = c.database()
    db.debug_sample_rate = 1.0

    async def main():
        tr = db.create_transaction()
        tr.set(b"span", b"1")
        await tr.commit()
        return tr.debug_id

    did = c.run_until(c.loop.spawn(main()), 60.0)
    assert did is not None
    locs = [e["Location"] for e in g_trace_batch.timeline(did)]
    for want in (
        "MasterServer.getCommitVersion",
        "Resolver.resolveBatch.Before",
        "Resolver.resolveBatch.AfterOrderer",
        "Resolver.resolveBatch.After",
        "TLog.tLogCommit.BeforeWaitForVersion",
        "TLog.tLogCommit.AfterTLogCommit",
    ):
        assert want in locs, f"missing {want}: {locs}"
    # causal order across the hops the envelope carried the ID over
    order = [locs.index(x) for x in (
        "CommitProxyServer.commitBatch.Before",
        "MasterServer.getCommitVersion",
        "Resolver.resolveBatch.Before",
        "Resolver.resolveBatch.After",
        "CommitProxyServer.commitBatch.AfterResolution",
        "TLog.tLogCommit.BeforeWaitForVersion",
        "TLog.tLogCommit.AfterTLogCommit",
        "CommitProxyServer.commitBatch.AfterLogPush",
    )]
    assert order == sorted(order), locs
    # every station also landed in the cluster collector as
    # TransactionDebug (the trace-FILE surface trace_tool joins)
    td = [e for e in c.trace.find("TransactionDebug") if e["ID"] == did]
    assert {e["Location"] for e in td} == set(locs)
    c.stop()


def test_unsampled_commit_rides_spanless_envelopes():
    """Sampling off: no envelope carries spans (the zero-cost contract)
    and no station events are emitted."""
    c = SimCluster(seed=32)
    g_trace_batch.attach_clock(c.loop.now, c.trace)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"q", b"1")
        await tr.commit()

    c.run_until(c.loop.spawn(main()), 60.0)
    assert g_trace_batch.events == []
    assert not c.trace.find("TransactionDebug")
    c.stop()


# -- periodic per-role metrics ----------------------------------------------


def test_every_role_emits_metrics_within_one_interval():
    """Acceptance: every role type emits its `*Metrics` event within one
    METRICS_INTERVAL, with the schema'd fields (ROLE_METRICS_SCHEMA)."""
    knobs = CoreKnobs()
    knobs.METRICS_INTERVAL = 0.5
    c = RecoverableCluster(
        seed=77, n_storage_shards=1, storage_replication=1,
        knobs=knobs, remote_region=True,
    )
    db = c.database()

    async def main():
        for i in range(8):
            tr = db.create_transaction()
            tr.set(b"mm%02d" % i, b"v")
            await tr.commit()
        tr = db.create_transaction()
        await tr.get(b"mm00")
        # one full interval beyond the workload so every emitter fires
        await c.loop.delay(0.6)

    c.run_until(c.loop.spawn(main()), 300)
    for etype in ("ProxyMetrics", "ResolverMetrics", "TLogMetrics",
                  "StorageMetrics", "SequencerMetrics", "LogRouterMetrics",
                  "WireMetrics"):
        evs = c.trace.find(etype)
        assert evs, f"no {etype} emitted"
        for ev in evs:
            validate_metrics_event(ev)
    # rates are real rates: after the workload some proxy interval saw
    # committed transactions per second, and the sim fabric moved frames
    assert any(
        e["TxnsCommittedPerSec"] > 0 for e in c.trace.find("ProxyMetrics")
    )
    assert any(
        e["FramesEncodedPerSec"] > 0 for e in c.trace.find("WireMetrics")
    )
    assert any(e["TxnsPerSec"] > 0 for e in c.trace.find("ResolverMetrics"))
    # track_latest: status's latest_events holds the newest sample per role
    assert any(k.startswith("ProxyMetrics:") for k in c.trace.latest)
    c.stop()


def test_metrics_schema_guard_migrated_to_flowlint():
    """Every-emitted-*Metrics-type-is-schema-listed (both ways) is now
    flowlint's `metrics-schema` rule, enforced tree-wide by the tier-1
    gate (tests/test_flowlint.py).  This wrapper proves the rule still
    fires: the bad fixture emits a type missing from its schema AND
    carries a stale schema entry nothing emits."""
    from foundationdb_tpu.lint import run_lint
    from foundationdb_tpu.tools.flowlint import REPO_ROOT

    fx = pathlib.Path(__file__).resolve().parent / "lint_fixtures" / "metrics-schema"
    msgs = [f.message
            for f in run_lint([str(fx / "bad")], root=REPO_ROOT, spec_dir=None)
            if f.rule == "metrics-schema"]
    assert any("not in" in m for m in msgs), msgs
    assert any("emitted nowhere" in m for m in msgs), msgs
    assert not [f for f in run_lint([str(fx / "ok")], root=REPO_ROOT,
                                    spec_dir=None)
                if f.rule == "metrics-schema"]


# -- trace_tool: the cross-process join --------------------------------------


def test_trace_tool_joins_files_and_extracts_series(tmp_path):
    """trace_tool reads rolled trace files from several 'processes', joins
    one debug ID's timeline across them with role/host attribution, and
    extracts a named metric time-series."""
    from foundationdb_tpu.tools import trace_tool

    # two "processes", each with its own rolling trace file + wall clock
    a = TraceCollector(
        clock=lambda: 1.0,
        sink=TraceFileSink(str(tmp_path / "proc-a"), roll_size=300),
        machine="host-a",
    )
    b = TraceCollector(
        clock=lambda: 2.0,
        sink=TraceFileSink(str(tmp_path / "proc-b"), roll_size=300),
        machine="host-b",
    )
    a.trace("TransactionDebug", Location="NativeAPI.commit.Before", ID="t1")
    time.sleep(0.01)
    b.trace("TransactionDebug",
            Location="CommitProxyServer.commitBatch.Before", ID="t1")
    b.trace("TransactionDebug",
            Location="Resolver.resolveBatch.After", ID="t1")
    time.sleep(0.01)
    a.trace("TransactionDebug", Location="NativeAPI.commit.After", ID="t1")
    for i in range(6):
        b.trace("ProxyMetrics", TxnsCommittedPerSec=float(i), Elapsed=0.5)

    events = trace_tool.load_events([str(tmp_path)])
    joined = trace_tool.join_timelines(events)
    rep = trace_tool.report_from_stations("t1", joined["t1"])
    assert rep["station_count"] == 4
    assert rep["roles"] == ["client", "proxy", "resolver"]
    # the join spanned BOTH processes' (rolled) files
    assert {s.split(".")[0] for s in rep["sources"]} == {"proc-a", "proc-b"}
    times = [s["time"] for s in rep["stations"]]
    assert times == sorted(times)
    # WallTime (not the per-process Time origins 1.0/2.0) ordered the join:
    # the client's closing station sorts LAST despite its early clock
    assert rep["stations"][0]["location"] == "NativeAPI.commit.Before"
    assert rep["stations"][-1]["location"] == "NativeAPI.commit.After"
    assert rep["stations"][0]["machine"] == "host-a"
    assert rep["stations"][1]["machine"] == "host-b"

    series = trace_tool.metric_series(events, "ProxyMetrics",
                                      "TxnsCommittedPerSec")
    assert [p["value"] for p in series] == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]

    hist = trace_tool.event_histogram(events)
    assert hist["by_type"]["TransactionDebug"]["count"] == 4
    assert hist["by_type"]["ProxyMetrics"]["count"] == 6

    # the CLI surface renders the same join
    out = trace_tool.run_report([str(tmp_path), "--id", "t1"])
    assert "NativeAPI.commit.Before" in out and "proxy" in out

    # slowest-transactions ranking includes t1
    slow = trace_tool.top_slow(events, 3)
    assert any(r["id"] == "t1" for r in slow)


def test_timeline_is_a_thin_consumer_of_the_join():
    """tools/timeline.py reports come from the same report builder as
    trace_tool (role attribution present in the in-memory view too)."""
    from foundationdb_tpu.tools.timeline import timeline_report

    g_trace_batch.attach_clock(lambda: 5.0)
    g_trace_batch.add("CommitProxyServer.commitBatch.Before", "x1")
    g_trace_batch.add("TLog.tLogCommit.AfterTLogCommit", "x1")
    rep = timeline_report("x1")
    assert rep["station_count"] == 2
    assert rep["roles"] == ["proxy", "tlog"]
    assert rep["stations"][0]["role"] == "proxy"
    g_trace_batch.attach_clock(lambda: 0.0)


# -- guard: WARN+ event types unique and schema-listed (migrated) ------------


def test_warn_event_guard_migrated_to_flowlint():
    """The WARN+ event-type discipline (registered in WARN_EVENT_TYPES,
    ONE call site per type, no stale registry names) is now flowlint's
    `warn-events` rule, enforced tree-wide by the tier-1 gate
    (tests/test_flowlint.py).  This wrapper proves the rule still fires:
    the bad fixture has an unregistered WARN+ event, a duplicated call
    site, and a stale registry entry."""
    from foundationdb_tpu.lint import run_lint
    from foundationdb_tpu.tools.flowlint import REPO_ROOT

    fx = pathlib.Path(__file__).resolve().parent / "lint_fixtures" / "warn-events"
    msgs = [f.message
            for f in run_lint([str(fx / "bad")], root=REPO_ROOT, spec_dir=None)
            if f.rule == "warn-events"]
    assert any("not in WARN_EVENT_TYPES" in m for m in msgs), msgs
    assert any("multiple call sites" in m for m in msgs), msgs
    assert any("no call site" in m for m in msgs), msgs
    assert not [f for f in run_lint([str(fx / "ok")], root=REPO_ROOT,
                                    spec_dir=None)
                if f.rule == "warn-events"]


# -- sampling-off overhead smoke ---------------------------------------------


def _fixed_workload_wall(knobs: CoreKnobs) -> float:
    """The fixed 600-commit sim workload (the PR-5 measurement shape):
    returns host wall seconds."""
    c = SimCluster(seed=17, n_resolvers=2, n_tlogs=2, knobs=knobs)
    db = c.database()

    async def drive():
        for i in range(600):
            tr = db.create_transaction()
            tr.set(b"w%03d" % (i % 251), b"v")
            await tr.commit()

    t0 = time.perf_counter()
    c.run_until(c.loop.spawn(drive()), 300.0)
    wall = time.perf_counter() - t0
    c.stop()
    return wall


def test_tracing_plane_overhead_sampling_off():
    """With sampling OFF, the tracing plane (span plumbing + metrics
    emitters + collector) must cost <2% wall on the fixed 600-commit sim
    workload vs a maximally quiesced plane.  min-of-2 per config with up
    to three measurement rounds (host-timing smoke de-flaking)."""
    def quiesced() -> CoreKnobs:
        k = CoreKnobs()
        k.METRICS_INTERVAL = 1e9   # emitters never fire
        k.TRACE_SEVERITY = 1 << 20  # collector drops everything
        return k

    _fixed_workload_wall(CoreKnobs())  # warmup (JIT/imports/allocator)
    last = None
    for _round in range(3):
        base = min(_fixed_workload_wall(quiesced()) for _ in range(2))
        plane = min(_fixed_workload_wall(CoreKnobs()) for _ in range(2))
        last = (plane, base)
        if plane <= base * 1.02:
            return
    plane, base = last
    raise AssertionError(
        f"tracing plane regressed the sampling-off workload "
        f">2%: {plane:.3f}s vs {base:.3f}s"
    )
