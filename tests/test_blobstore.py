"""BlobStore object store + retrying client + blob:// backup containers
(storage/blobstore.py, client/backup.py): checksummed multipart uploads,
torn-upload refusal, fault-injection recovery, the real HTTP server, and
point-in-time restore through the blob container with the uploader killed
mid-stream (fdbclient/BlobStore.actor.cpp + BackupContainer.actor.cpp
semantics)."""

import asyncio

import pytest

from foundationdb_tpu.client.backup import (
    BackupAgent,
    BackupContainer,
    BlobBackupContainer,
    apply_backup,
    backup_container,
    restore,
)
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime import buggify
from foundationdb_tpu.runtime.core import ActorCancelled, DeterministicRandom, EventLoop
from foundationdb_tpu.storage.blobstore import (
    BlobChecksumError,
    BlobError,
    BlobNotFound,
    BlobObjectStore,
    BlobStoreClient,
    BlobStoreServer,
    BlobQueue,
    HostBacking,
    HttpBlobTransport,
    SimBlobTransport,
    SimFSBacking,
    blob_crc,
)
from foundationdb_tpu.storage.files import SimFilesystem


def _sim_client(c, nonce="cT"):
    store = BlobObjectStore(SimFSBacking(c.fs))
    client = BlobStoreClient(
        SimBlobTransport(store, c.loop, c.rng),
        knobs=c.knobs, trace=c.trace,
        sleep=lambda s: c.loop.delay(s), nonce=nonce,
    )
    return store, client


def _run(c, coro, deadline=300.0):
    return c.run_until(c.loop.spawn(coro), deadline)


# ---------------------------------------------------------------------------
# object store + client basics (sim transport)


def test_object_store_roundtrip_and_listing():
    c = RecoverableCluster(seed=7301)
    _store, client = _sim_client(c)

    async def main():
        await client.write_object("a/x", b"hello")
        await client.write_object("a/y", b"world" * 10000)  # multipart
        await client.write_object("b/z", b"!")
        assert await client.read_object("a/x") == b"hello"
        assert await client.read_object("a/y") == b"world" * 10000
        assert await client.list_objects("a/") == ["a/x", "a/y"]
        meta = await client.head_object("a/y")
        assert meta["size"] == 50000
        assert meta["crc32"] == blob_crc(b"world" * 10000)
        await client.delete_object("a/x")
        assert await client.list_objects("a/") == ["a/y"]
        with pytest.raises(BlobNotFound):
            await client.read_object("a/x")
        return True

    assert _run(c, main())
    c.stop()


def test_torn_part_refused_at_complete_then_reuploaded():
    """The torn-upload gate: a part whose bytes fail their claimed crc32
    refuses the WHOLE upload at complete (staging discarded), and the
    client's retry re-uploads under a fresh upload id."""
    c = RecoverableCluster(seed=7302, chaos=True)
    _store, client = _sim_client(c)
    buggify.force("blob.upload_torn")
    data = bytes(range(256)) * 400  # several parts

    async def main():
        await client.write_object("t/obj", data)
        assert await client.read_object("t/obj") == data
        return True

    assert _run(c, main())
    assert client.retries >= 1
    from foundationdb_tpu.runtime import coverage

    cen = coverage.census()
    assert cen.get("blob.torn_refused", 0) >= 1
    assert cen.get("blob.retry_recovered", 0) >= 1
    c.stop()


def test_torn_upload_exhausted_leaves_previous_objects_restorable():
    """A blob.upload_torn storm that exhausts the retry budget fails the
    NEW object loudly — and the container still reads exactly the
    previous complete object set (the torn staging is invisible, refused
    by checksum, never restorable)."""
    c = RecoverableCluster(seed=7303, chaos=True)
    _store, client = _sim_client(c)

    async def main():
        await client.write_object("p/good", b"G" * 99999)
        # enough forced fires that EVERY retry attempt tears at least its
        # first part (each attempt uploads ceil(size/part) parts)
        nparts = -(-99999 // c.knobs.BLOB_PART_BYTES)
        buggify.force("blob.upload_torn",
                      times=(c.knobs.BLOB_RETRY_LIMIT + 1) * nparts)
        with pytest.raises(BlobError):
            await client.write_object("p/bad", b"B" * 99999)
        assert await client.list_objects("p/") == ["p/good"]
        assert await client.read_object("p/good") == b"G" * 99999
        return True

    assert _run(c, main())
    c.stop()


def test_read_corrupt_detected_and_refetched():
    c = RecoverableCluster(seed=7304, chaos=True)
    _store, client = _sim_client(c)

    async def main():
        await client.write_object("r/obj", b"payload" * 50)
        buggify.force("blob.read_corrupt")
        assert await client.read_object("r/obj") == b"payload" * 50
        return True

    assert _run(c, main())
    from foundationdb_tpu.runtime import coverage

    assert coverage.census().get("blob.read_corrupt_detected", 0) >= 1
    c.stop()


def test_connect_fail_backoff_and_exhaustion():
    c = RecoverableCluster(seed=7305, chaos=True)
    _store, client = _sim_client(c)

    async def main():
        buggify.force("blob.connect_fail", times=2)
        await client.write_object("c/obj", b"x")   # recovers via backoff
        buggify.force("blob.connect_fail",
                      times=(c.knobs.BLOB_RETRY_LIMIT + 1) * 2)
        with pytest.raises(BlobError, match="retries exhausted"):
            await client.read_object("c/obj")
        return True

    assert _run(c, main())
    # every retry traced SEV_WARN for soak triage
    assert len(c.trace.find("BlobRequestRetried")) >= 2
    c.stop()


def test_permanently_corrupt_object_refused_not_restored():
    """A completed object whose PAYLOAD was later corrupted on disk never
    passes the client's checksum: read_object raises after retries, it
    never returns the corrupt bytes."""
    c = RecoverableCluster(seed=7306)
    store, client = _sim_client(c)

    async def main():
        await client.write_object("x/obj", b"precious data")
        # corrupt the stored payload behind the store's back
        await store.backing.write("o/x/obj", b"precious dat!")
        with pytest.raises(BlobError):
            await client.read_object("x/obj")
        return True

    assert _run(c, main())
    c.stop()


def test_torn_meta_reads_as_absent_not_corrupt():
    """A power kill mid-finalize leaves a truncated meta record: the
    object was never committed (its uploader never got an ack, so it
    never released its source data) — it must read as ABSENT and vanish
    from listings, never fail the reader as corrupt."""
    c = RecoverableCluster(seed=7313)
    store, client = _sim_client(c)

    async def main():
        await client.write_object("tm/good", b"ok")
        await client.write_object("tm/torn", b"doomed")
        # tear the meta the way a mid-sync power kill does
        await store.backing.write("m/tm/torn", b'{"size": 6, "crc')
        assert await client.list_objects("tm/") == ["tm/good"]
        with pytest.raises(BlobNotFound):
            await client.read_object("tm/torn")
        assert await client.read_object("tm/good") == b"ok"
        return True

    assert _run(c, main())
    from foundationdb_tpu.runtime import coverage

    assert coverage.census().get("blob.torn_meta_ignored", 0) >= 1
    c.stop()


def test_killed_uploader_leaves_no_visible_object():
    """Cancelling a write_object mid-multipart (the uploader power-kill)
    leaves only invisible staging — LIST/GET never see a half object —
    and a replacement re-uploads cleanly under its own nonce."""
    c = RecoverableCluster(seed=7307)
    _store, client = _sim_client(c)
    data = b"D" * 200000

    async def killed():
        await client.write_object("k/obj", data)

    async def main():
        t = c.loop.spawn(killed())
        await c.loop.delay(0.001)  # a few parts staged, no complete
        t.cancel()
        try:
            await t
        except ActorCancelled:
            pass
        assert await client.list_objects("k/") == []
        with pytest.raises(BlobNotFound):
            await client.read_object("k/obj")
        _s2, client2 = _sim_client(c, nonce="cR")
        await client2.write_object("k/obj", data)
        assert await client2.read_object("k/obj") == data
        return True

    assert _run(c, main())
    c.stop()


def test_blob_queue_roundtrip_and_version_dedup():
    c = RecoverableCluster(seed=7308)
    _store, client = _sim_client(c)

    async def main():
        q = BlobQueue(client, "q/log", "w1")
        q.push(b"rec1")
        q.push(b"rec2")
        await q.sync()
        q.push(b"rec3")
        await q.sync()
        # a restarted writer re-uploads an overlapping record set under
        # its own nonce (the dead-worker duplicate shape)
        q2 = BlobQueue(client, "q/log", "w2")
        q2.push(b"rec3")
        await q2.sync()
        recs = await BlobQueue(client, "q/log", "r").recover()
        assert sorted(recs) == [b"rec1", b"rec2", b"rec3", b"rec3"]
        return True

    assert _run(c, main())
    c.stop()


# ---------------------------------------------------------------------------
# container URL factory


def test_backup_container_url_schemes(monkeypatch):
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(1))
    cont = backup_container("file://bk1", fs=fs)
    assert isinstance(cont, BackupContainer)
    cont = backup_container("bk2", fs=fs)  # bare prefix = file scheme
    assert isinstance(cont, BackupContainer)
    client = BlobStoreClient(
        SimBlobTransport(BlobObjectStore(HostBacking()), loop,
                         DeterministicRandom(2)),
        sleep=lambda s: loop.delay(s),
    )
    cont = backup_container("blob://bk3", blob_client=client)
    assert isinstance(cont, BlobBackupContainer)
    cont = backup_container("http://127.0.0.1:1234/bk4")
    assert isinstance(cont, BlobBackupContainer)
    with pytest.raises(ValueError, match="blob_client"):
        backup_container("blob://bk5")
    with pytest.raises(ValueError, match="fs="):
        backup_container("file://bk6")
    with pytest.raises(ValueError, match="FDBTPU_BLOB_URL"):
        monkeypatch.delenv("FDBTPU_BLOB_URL", raising=False)
        backup_container(None)
    monkeypatch.setenv("FDBTPU_BLOB_URL", "file://bk7")
    cont = backup_container(None, fs=fs)
    assert isinstance(cont, BackupContainer)
    assert cont.prefix == "bk7"


# ---------------------------------------------------------------------------
# the real-network half: HTTP server + transport under asyncio


def test_http_server_roundtrip():
    async def main():
        server = BlobStoreServer()
        port = await server.start()
        client = BlobStoreClient(HttpBlobTransport("127.0.0.1", port))
        data = b"H" * 150000  # several parts
        await client.write_object("h/obj", data)
        assert await client.read_object("h/obj") == data
        assert await client.list_objects("h/") == ["h/obj"]
        meta = await client.head_object("h/obj")
        assert meta == {"size": len(data), "crc32": blob_crc(data)}
        with pytest.raises(BlobNotFound):
            await client.read_object("h/nope")
        # a torn part over the wire: server refuses the finalize with 409
        t = HttpBlobTransport("127.0.0.1", port)
        await t.request("put_part", upload="u9", part=0, data=b"torn",
                        crc32=blob_crc(b"whole"))
        with pytest.raises(BlobChecksumError):
            await t.request("complete", name="h/torn", upload="u9",
                            crc32=blob_crc(b"whole"), parts=1)
        assert await client.list_objects("h/") == ["h/obj"]
        await client.delete_object("h/obj")
        assert await client.list_objects("h/") == []
        await server.stop()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# blob backup end-to-end: PIT restore with the uploader killed mid-stream


def test_blob_backup_pit_restore_with_uploader_kill():
    """THE acceptance path: back a cluster up into a blob container while
    killing the uploader mid-stream at a jittered offset, then restore —
    both point-in-time and full — byte-exact against the committed model
    into a second cluster."""
    src = RecoverableCluster(seed=7310, n_storage_shards=2,
                            storage_replication=2)
    db = src.database()
    _store, client = _sim_client(src)
    cont = backup_container("blob://bk", blob_client=client,
                            uid=lambda: src.rng.random_unique_id()[:8])
    agent = BackupAgent(src)
    model = {}

    async def commit(i, v):
        async def fn(tr):
            tr.set(b"d%04d" % i, v)

        await db.run(fn)
        model[b"d%04d" % i] = v

    async def main():
        await agent.start(cont)
        for i in range(12):
            await commit(i, b"a%d" % i)
        snap_v = await agent.snapshot(cont, chunk_rows=5)
        pit_model = dict(model)
        # wait for log coverage of the PIT point
        tr = db.create_transaction()
        pit_v = await tr.get_read_version()
        assert pit_v >= snap_v
        # jittered mid-stream kill, then more traffic the replacement
        # must re-upload
        await src.loop.delay(0.01 + src.rng.random() * 0.2)
        agent.kill_worker()
        await agent.restart_worker(cont)
        for i in range(12, 24):
            await commit(i, b"b%d" % i)
        tr = db.create_transaction()
        vfin = await tr.get_read_version()
        await agent.wait_backed_up_to(vfin)
        await agent.stop()
        return snap_v, pit_v, pit_model, vfin

    snap_v, pit_v, pit_model, vfin = _run(src, main())

    # referee: full fold matches the full model; PIT fold matches the
    # mid-point model
    chunks, log = _run(src, cont.read())
    assert {k: v for k, v in apply_backup(chunks, log).items()
            if k.startswith(b"d")} == model
    assert {k: v for k, v in apply_backup(chunks, log, pit_v).items()
            if k.startswith(b"d")} == pit_model

    # and through restore() into a second cluster on the same loop
    dst = RecoverableCluster(seed=7311, loop=src.loop)
    dst_db = dst.database()
    _run(src, restore(dst_db, cont, target_version=pit_v))

    async def read_all():
        async def fn(tr):
            return await tr.get_range(b"d", b"e", limit=10000)

        return dict(await dst_db.run(fn))

    assert _run(src, read_all()) == pit_model
    dst.stop()
    src.stop()


def test_blob_container_survives_restart_image():
    """The blob store lives on the simulated filesystem: a whole-sim
    power kill + restart image carries exactly the completed objects
    (synced prefixes), and the rebooted container still restores."""
    c = RecoverableCluster(seed=7312)
    db = c.database()
    _store, client = _sim_client(c)
    cont = backup_container("blob://bk", blob_client=client,
                            uid=lambda: c.rng.random_unique_id()[:8])
    agent = BackupAgent(c)

    async def main():
        await agent.start(cont)
        for i in range(8):
            async def fn(tr, i=i):
                tr.set(b"s%02d" % i, b"v%d" % i)

            await db.run(fn)
        snap_v = await agent.snapshot(cont, chunk_rows=4)
        tr = db.create_transaction()
        vfin = await tr.get_read_version()
        await agent.wait_backed_up_to(max(snap_v, vfin))
        await agent.stop()
        return True

    assert _run(c, main())
    fs = c.power_off()

    c2 = RecoverableCluster(seed=7312, fs=fs, restart=True)
    _store2, client2 = _sim_client(c2)
    cont2 = backup_container("blob://bk", blob_client=client2,
                             uid=lambda: c2.rng.random_unique_id()[:8])
    chunks, log = c2.run_until(c2.loop.spawn(cont2.read()), 300)
    state = apply_backup(chunks, log)
    assert {k: v for k, v in state.items() if k.startswith(b"s")} == {
        b"s%02d" % i: b"v%d" % i for i in range(8)
    }
    c2.stop()
