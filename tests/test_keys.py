import random

import numpy as np
import pytest

from foundationdb_tpu import keys


def _order_of(enc_row):
    return tuple(int(x) for x in enc_row)


def test_roundtrip():
    ks = [b"", b"a", b"abc", b"\x00", b"\xff" * 32, bytes(range(20))]
    enc = keys.encode_keys(ks)
    for i, k in enumerate(ks):
        assert keys.decode_key(enc[i]) == k


def test_order_matches_bytes_random():
    rng = random.Random(0)
    ks = []
    for _ in range(2000):
        n = rng.randrange(0, 33)
        ks.append(bytes(rng.randrange(256) for _ in range(n)))
    # adversarial: shared prefixes, trailing NULs, trailing 0xFF
    for base in (b"", b"ab", b"ab\x00", b"\xff\xff", b"prefix"):
        ks += [base, base + b"\x00", base + b"\x00\x00", base + b"\xff", base + b"\x01"]
    enc = keys.encode_keys(ks)
    by_bytes = sorted(range(len(ks)), key=lambda i: ks[i])
    by_enc = sorted(range(len(ks)), key=lambda i: _order_of(enc[i]))
    assert [ks[i] for i in by_bytes] == [ks[i] for i in by_enc]


def test_sentinel_sorts_last():
    s = _order_of(keys.sentinel())
    enc = keys.encode_keys([b"\xff" * 32, b""])
    assert _order_of(enc[0]) < s and _order_of(enc[1]) < s


def test_too_long_raises():
    with pytest.raises(keys.KeyTooLongError):
        keys.encode_keys([b"x" * 33])


def test_key_after_and_strinc():
    assert keys.key_after(b"a") == b"a\x00"
    assert keys.strinc(b"a") == b"b"
    assert keys.strinc(b"a\xff\xff") == b"b"
    e = keys.encode_keys([b"a", keys.key_after(b"a"), b"a\x01"])
    assert _order_of(e[0]) < _order_of(e[1]) < _order_of(e[2])


def test_empty_key_is_minimum():
    enc = keys.encode_keys([b"", b"\x00"])
    assert _order_of(enc[0]) < _order_of(enc[1])
    assert np.all(enc[0] == 0)
