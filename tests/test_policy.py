"""Declarative replication policy (fdbrpc/ReplicationPolicy.h:101 PolicyOne,
:121 PolicyAcross) + the online redundancy flip it drives."""

import pytest

from foundationdb_tpu.client import management as mgmt
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.rpc.policy import (
    Locality,
    PolicyAcross,
    PolicyOne,
    policy_for_redundancy,
)


def L(p, m=None, d=None):
    return Locality(p, m, d)


def test_policy_one():
    p = PolicyOne()
    assert p.replicas() == 1
    assert p.validate([L("a", "m1")])
    assert not p.validate([])
    assert p.select([L("a"), L("b")]) == [0]
    assert p.select([]) is None


def test_policy_across_machines():
    p = PolicyAcross(2, "machine")
    assert p.replicas() == 2
    assert p.validate([L("a", "m1"), L("b", "m2")])
    # same machine twice: REFUSED — the team builder contract
    assert not p.validate([L("a", "m1"), L("b", "m1")])
    # selection picks one per machine, stable order
    sel = p.select([L("a", "m1"), L("b", "m1"), L("c", "m2")])
    assert sel == [0, 2]
    assert p.select([L("a", "m1"), L("b", "m1")]) is None


def test_policy_nested_across():
    # two DCs, two machines each: the reference's composition
    p = PolicyAcross(2, "dc", PolicyAcross(2, "machine"))
    assert p.replicas() == 4
    good = [
        L("a", "m1", "dc0"), L("b", "m2", "dc0"),
        L("c", "m3", "dc1"), L("d", "m4", "dc1"),
    ]
    assert p.validate(good)
    bad = [
        L("a", "m1", "dc0"), L("b", "m1", "dc0"),  # same machine in dc0
        L("c", "m3", "dc1"), L("d", "m4", "dc1"),
    ]
    assert not p.validate(bad)
    # unset locality values are distinct groups (reference semantics)
    assert PolicyAcross(2, "machine").validate([L("a"), L("b")])


def test_redundancy_modes():
    assert policy_for_redundancy("double").replicas() == 2
    assert policy_for_redundancy("triple").replicas() == 3
    assert policy_for_redundancy("three_datacenter").attr == "dc"
    with pytest.raises(ValueError):
        policy_for_redundancy("quadruple-rainbow")


def test_team_builder_refuses_policy_violation():
    # 2 replicas cannot be placed across machines when only 1 machine exists
    with pytest.raises(ValueError):
        RecoverableCluster(
            seed=520, n_machines=1, n_dcs=1, storage_replication=2,
        )


def test_redundancy_flip_online():
    """configure(redundancy=...) flips double -> triple -> double with data
    intact and teams policy-valid throughout (VERDICT r4 #4 acceptance)."""
    c = RecoverableCluster(
        seed=521, n_machines=6, n_dcs=2, n_storage_shards=2,
        redundancy="double",
    )
    assert all(len(t) == 2 for t in c.controller.storage_teams_tags)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        for i in range(30):
            tr.set(b"k%02d" % i, b"v%d" % i)
        await tr.commit()

        await mgmt.configure(db, redundancy="triple")
        for _ in range(600):
            await c.loop.delay(0.1)
            if all(len(t) == 3 for t in c.controller.storage_teams_tags):
                break
        assert all(len(t) == 3 for t in c.controller.storage_teams_tags)

        # policy-valid teams: three distinct machines per team
        from foundationdb_tpu.rpc.policy import Locality

        pol = policy_for_redundancy("triple")
        for team in c.controller._storage_teams():
            locs = [Locality.of(ss.process) for ss in team]
            assert pol.validate(locs), locs

        # data fully readable (replicas consistent is checked by reads
        # hitting any replica through the view refresh)
        tr = db.create_transaction()
        rows = await tr.get_range(b"k", b"l")
        assert len(rows) == 30

        # flip back down
        await mgmt.configure(db, redundancy="double")
        for _ in range(600):
            await c.loop.delay(0.1)
            if all(len(t) == 2 for t in c.controller.storage_teams_tags):
                break
        assert all(len(t) == 2 for t in c.controller.storage_teams_tags)
        tr = db.create_transaction()
        rows = await tr.get_range(b"k", b"l")
        assert len(rows) == 30

        # writes still flow after both flips
        async def w(tr):
            tr.set(b"after", b"1")
        await db.run(w)
        return True

    assert c.run_until(c.loop.spawn(main()), 600)
    assert c.dd.exclusion_drains == 0
    c.stop()
