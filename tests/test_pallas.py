"""Pallas sort-scan conflict kernel: interpret-mode parity vs the oracle,
and the incremental (run-append + deferred k-way merge) machinery.

The Pallas kernel (conflict/pallas_kernel.py) is the device lowering of the
committed-run probe; tier-1 pins its semantics on CPU via
`pl.pallas_call(..., interpret=True)` — the same kernel body the TPU
compiles, run by the Pallas interpreter — against the pure-Python oracle.
The XLA fallback must agree bit-for-bit with both (the capability-probe
chain of docs/KERNEL.md).  A `slow`-marked variant covers the compiled
lowering on real TPU hardware.
"""

import random

import pytest

pytest.importorskip(
    "jax.experimental.pallas", reason="installed jax lacks Pallas support"
)

from foundationdb_tpu.conflict import pallas_kernel
from foundationdb_tpu.conflict.api import TxInfo, Verdict
from foundationdb_tpu.conflict.device import DeviceConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet


def _rand_key(rng, alphabet=b"abcd", max_len=5):
    return bytes(rng.choice(alphabet) for _ in range(rng.randrange(max_len + 1)))


def _rand_range(rng):
    if rng.random() < 0.5:  # point range [k, k+\0)
        k = _rand_key(rng)
        return k, k + b"\x00"
    a, b = sorted((_rand_key(rng), _rand_key(rng)))
    return a, b + b"\x00"


def _rand_batch(rng, version, oldest, n):
    txns = []
    for _ in range(n):
        lo = max(oldest - 3, 0)
        snap = rng.randrange(lo, version)
        txns.append(
            TxInfo(
                read_snapshot=snap,
                read_ranges=[_rand_range(rng) for _ in range(rng.randrange(4))],
                write_ranges=[_rand_range(rng) for _ in range(rng.randrange(3))],
            )
        )
    return txns


@pytest.mark.parametrize("lsm", [False, True], ids=["flat", "lsm"])
@pytest.mark.parametrize("seed", range(4))
def test_pallas_interpret_parity_sweep(seed, lsm):
    """Randomized batches through the interpret-mode Pallas probe, flat and
    LSM layouts, with mid-stream GC (version-window edges) and small run
    slots so deferred compactions fire repeatedly."""
    rng = random.Random(seed)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(
        capacity=1 << 10, lsm=lsm, incremental=True,
        run_slots=3, run_capacity=64, pallas="interpret",
    )
    assert dev._probe_impl == "interpret"
    version = 0
    for _ in range(20):
        version += rng.randrange(1, 8)
        txns = _rand_batch(rng, version, oracle.oldest_version, rng.randrange(1, 12))
        want = oracle.resolve_batch(version, txns)
        got = dev.resolve_batch(version, txns)
        assert got == want, f"seed={seed} lsm={lsm} version={version}"
        if rng.random() < 0.3:
            floor = rng.randrange(version + 1)
            oracle.remove_before(floor)
            dev.remove_before(floor)
    assert dev.stats.runs_appended == 20
    assert dev.stats.full_merges == 0
    assert dev.compactions >= 1, "run slots never filled — weak test setup"


def test_version_window_edges_interpret():
    """Exact window-edge semantics through the run probe: a conflict is
    `run version > snapshot` (strict), runs GC'd below the floor go dead,
    and snapshots below the floor are TOO_OLD."""
    dev = DeviceConflictSet(
        capacity=1 << 9, incremental=True, run_slots=4, run_capacity=32,
        pallas="interpret",
    )
    r = lambda k: (k, k + b"\x00")
    # write k at version 10: the run carries exactly version 10
    assert dev.resolve_batch(
        10, [TxInfo(0, [], [r(b"k")])]
    ) == [Verdict.COMMITTED]
    # snapshot 9 < 10 conflicts; snapshot 10 does not (strict >)
    assert dev.resolve_batch(
        11, [TxInfo(9, [r(b"k")], []), TxInfo(10, [r(b"k")], [])]
    ) == [Verdict.CONFLICT, Verdict.COMMITTED]
    # floor past the run's version: the run is dead, the write invisible —
    # and snapshots below the floor are TOO_OLD before any range check
    dev.remove_before(11)
    assert dev.resolve_batch(
        20, [TxInfo(5, [r(b"k")], []), TxInfo(11, [r(b"k")], [])]
    ) == [Verdict.TOO_OLD, Verdict.COMMITTED]


def test_probe_chain_agrees_xla_vs_interpret():
    """The capability-probe chain must be semantics-free: the same stream
    through the XLA fallback and the interpret-mode Pallas kernel produces
    identical verdicts (bit-for-bit, docs/KERNEL.md contract)."""
    streams = []
    for impl_override in ("off", "interpret"):
        rng = random.Random(99)
        dev = DeviceConflictSet(
            capacity=1 << 10, incremental=True, run_slots=3,
            run_capacity=64, pallas=impl_override,
        )
        out = []
        version = 0
        for _ in range(15):
            version += rng.randrange(1, 5)
            txns = _rand_batch(rng, version, dev.oldest_version, rng.randrange(1, 10))
            out.append(dev.resolve_batch(version, txns))
        streams.append(out)
    assert streams[0] == streams[1]


def test_pallas_mode_probe():
    assert pallas_kernel.pallas_mode("off") is None
    assert pallas_kernel.pallas_mode("interpret") == "interpret"
    with pytest.raises(ValueError, match="unknown"):
        pallas_kernel.pallas_mode("bogus")
    # auto on CPU: never interpret implicitly (orders of magnitude slower)
    assert pallas_kernel.pallas_mode("auto") in (None, "tpu")


def test_incremental_compaction_regrows_capacity():
    """Twin of test_device.test_capacity_regrowth for the incremental path:
    the deferred fold (not the per-batch merge) is what outgrows main, and
    it must regrow transparently with oracle-exact verdicts throughout."""
    rng = random.Random(7)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(
        capacity=16, incremental=True, run_slots=2, run_capacity=64,
    )
    version = 0
    for _ in range(6):
        version += 5
        txns = [
            TxInfo(
                read_snapshot=version - 5,
                read_ranges=[_rand_range(rng)],
                write_ranges=[(k := _rand_key(rng, b"abcdefgh", 6), k + b"\x00")],
            )
            for _ in range(24)
        ]
        assert dev.resolve_batch(version, txns) == oracle.resolve_batch(version, txns)
    assert dev.compactions >= 2
    assert dev.capacity > 16


def test_pipelined_incremental_stream_parity():
    """sync=False incremental stream: run bookkeeping is host-deterministic
    (appends cannot overflow), so drain only checks search convergence; the
    verdicts must still match a sync oracle run batch-for-batch."""
    import numpy as np

    from foundationdb_tpu.conflict.device import pack_batch

    rng = random.Random(21)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(
        capacity=1 << 10, incremental=True, run_slots=3, run_capacity=64,
    )
    version, pending = 0, []
    for _ in range(12):
        version += rng.randrange(1, 4)
        txns = _rand_batch(rng, version, oracle.oldest_version, rng.randrange(1, 8))
        want = oracle.resolve_batch(version, txns)
        packed = pack_batch(txns, dev.oldest_version, dev._offset, dev._max_key_bytes)
        got = dev.resolve_arrays(version, *packed[:-1], sync=False)
        pending.append((len(txns), got, want))
    dev.check_pipelined()
    for n, got, want in pending:
        assert [Verdict(int(c)) for c in np.asarray(got)[:n]] == want


def test_phase_counters_populated():
    """Phase timing mode splits the fused kernel into per-phase dispatches;
    all four sort/scan/merge/compact counters must land in kernel_stats."""
    rng = random.Random(5)
    dev = DeviceConflictSet(
        capacity=1 << 9, incremental=True, run_slots=2, run_capacity=64,
    )
    dev._phase_timing = True
    version = 0
    for _ in range(5):
        version += 2
        dev.resolve_batch(version, _rand_batch(rng, version, 0, 6))
    phase = dev.kernel_stats()["phase"]
    assert phase["sort_ms"] > 0
    assert phase["scan_ms"] > 0
    assert phase["merge_ms"] > 0
    assert phase["compact_ms"] > 0  # run_slots=2 forces a deferred fold


def test_sharded_incremental_parity():
    """The sharded backend reuses the incremental kernel per shard (clip →
    probe → append → pmin); parity vs the per-partition multi-oracle."""
    from foundationdb_tpu.parallel.sharded import (
        ShardedDeviceConflictSet,
        make_resolver_mesh,
    )
    from tests.test_sharded import MultiOracle

    mesh = make_resolver_mesh(2)
    splits = [b"c"]
    rng = random.Random(13)
    ref = MultiOracle(splits)
    cs = ShardedDeviceConflictSet(
        mesh, splits, capacity=1 << 9, incremental=True,
        run_slots=2, run_capacity=64,
    )
    version = 0
    for _ in range(12):
        version += rng.randrange(1, 5)
        txns = _rand_batch(rng, version, cs.oldest_version, rng.randrange(1, 8))
        assert cs.resolve_batch(version, txns) == ref.resolve_batch(version, txns)
        if rng.random() < 0.25:
            floor = rng.randrange(version + 1)
            ref.remove_before(floor)
            cs.remove_before(floor)
    assert cs.compactions >= 1


def test_sharded_interpret_probe():
    """The Pallas kernel traces under shard_map too (interpret mode on CPU):
    one small stream, parity vs the multi-oracle."""
    from foundationdb_tpu.parallel.sharded import (
        ShardedDeviceConflictSet,
        make_resolver_mesh,
    )
    from tests.test_sharded import MultiOracle

    mesh = make_resolver_mesh(2)
    splits = [b"c"]
    rng = random.Random(3)
    ref = MultiOracle(splits)
    cs = ShardedDeviceConflictSet(
        mesh, splits, capacity=1 << 8, incremental=True,
        run_slots=2, run_capacity=32, pallas="interpret",
    )
    version = 0
    for _ in range(4):
        version += 2
        txns = _rand_batch(rng, version, 0, 4)
        assert cs.resolve_batch(version, txns) == ref.resolve_batch(version, txns)


@pytest.mark.parametrize("lsm", [False, True], ids=["flat", "lsm"])
def test_merge_impl_parity_sweep(lsm):
    """sort / gather / scatter merge impls are parity REFEREES for each
    other: the same adversarial stream (tiny alphabet → heavy duplicate
    boundary keys, occasional write-free batches → empty runs) must produce
    identical verdicts AND a bit-identical boundary state through repeated
    deferred folds."""
    import numpy as np

    streams, states = {}, {}
    for impl in ("sort", "scatter", "gather"):
        rng = random.Random(42)
        dev = DeviceConflictSet(
            capacity=1 << 9, lsm=lsm, incremental=True,
            run_slots=2, run_capacity=64, merge_impl=impl,
        )
        out = []
        version = 0
        for i in range(16):
            version += rng.randrange(1, 6)
            txns = [
                TxInfo(
                    read_snapshot=max(version - rng.randrange(1, 4), 0),
                    read_ranges=[_rand_range(rng) for _ in range(rng.randrange(3))],
                    # every 4th batch writes nothing: the run append must
                    # fold empty interval sets identically under all impls
                    write_ranges=(
                        [] if i % 4 == 3
                        else [(k := _rand_key(rng, b"ab", 2), k + b"\x00")
                              for _ in range(rng.randrange(1, 4))]
                    ),
                )
                for _ in range(rng.randrange(1, 8))
            ]
            out.append(dev.resolve_batch(version, txns))
        assert dev.compactions >= 1, "deferred fold never fired — weak setup"
        streams[impl] = out
        states[impl] = (
            np.asarray(dev._ks).copy(), np.asarray(dev._vs).copy(),
            dev.boundary_count,
        )
        assert dev.kernel_stats()["merge_impl"] == impl
        assert impl in dev.kernel_stats()["fold_ms"]
    for impl in ("scatter", "gather"):
        assert streams[impl] == streams["sort"], impl
        assert np.array_equal(states[impl][0], states["sort"][0]), impl
        assert np.array_equal(states[impl][1], states["sort"][1]), impl
        assert states[impl][2] == states["sort"][2], impl


def test_compact_fold_parity_adversarial():
    """Direct compact_lsm fold parity across all three impls and the Pallas
    interpret lowering of the rank search, on adversarial inputs: recent
    rows duplicating main boundary keys exactly, and an empty recent level."""
    import numpy as np

    jnp = pytest.importorskip("jax.numpy")
    from foundationdb_tpu import keys as keymod
    from foundationdb_tpu.conflict import device as D

    rng = np.random.default_rng(7)
    W = keymod.num_words(16)
    SENT = np.uint32(0xFFFFFFFF)
    cap, rec_cap = 256, 64

    def sorted_rows(raws):
        rows = keymod.encode_keys(raws, 16)
        order = np.lexsort(tuple(rows[:, w] for w in range(W - 1, -1, -1)))
        return rows[order]

    for trial in range(4):
        n_live = int(rng.integers(2, 120))
        pool = sorted({int(x).to_bytes(4, "big")
                       for x in rng.integers(0, 1 << 30, n_live * 2)})
        rows = sorted_rows([b""] + pool[: n_live - 1])
        ks = np.full((cap, W), SENT, dtype=np.uint32)
        ks[: rows.shape[0]] = rows
        vs = np.zeros(cap, np.int32)
        vs[: rows.shape[0]] = np.sort(
            rng.integers(0, 1000, rows.shape[0]).astype(np.int32))
        if trial == 0:
            n_rec = 0          # empty recent: the fold must be an identity
        elif trial == 1:
            # adversarial: recent duplicates main boundary keys exactly
            rec_rows = np.asarray(ks)[1: 1 + min(8, rows.shape[0] - 1)]
            n_rec = rec_rows.shape[0]
        else:
            n_rec = int(rng.integers(1, rec_cap // 2))
            rpool = sorted({int(x).to_bytes(4, "big")
                            for x in rng.integers(0, 1 << 30, n_rec * 2)})
            rec_rows = sorted_rows(rpool[:n_rec])
            n_rec = rec_rows.shape[0]
        rec_ks = np.full((rec_cap, W), SENT, dtype=np.uint32)
        rec_vs = np.zeros(rec_cap, np.int32)
        if n_rec:
            rec_ks[:n_rec] = rec_rows
            rec_vs[:n_rec] = rng.integers(0, 1000, n_rec).astype(np.int32)
        args = (jnp.asarray(ks), jnp.asarray(vs),
                jnp.asarray(rec_ks), jnp.asarray(rec_vs))
        ref = D.compact_lsm(*args, cap=cap, merge_impl="sort")
        for impl in ("scatter", "gather"):
            for lowering in ("xla", "interpret"):
                got = D.compact_lsm(*args, cap=cap, merge_impl=impl,
                                    lowering=lowering)
                for i, name in enumerate(("ks", "vs", "count", "bidx", "tab")):
                    assert np.array_equal(
                        np.asarray(ref[i]), np.asarray(got[i])
                    ), (trial, impl, lowering, name)


def test_intra_rank_space_parity():
    """The rank-space intra-batch fixpoint (sparse-table over local ranks)
    must match the dense [R,Wn] referee bit-for-bit — verdict bits AND
    iteration counts — and so must its Pallas interpret lowering."""
    import numpy as np

    jnp = pytest.importorskip("jax.numpy")
    from foundationdb_tpu import keys as keymod
    from foundationdb_tpu.conflict import device as D

    rng = np.random.default_rng(3)
    B, R, Wn = 32, 64, 64

    def intervals(n):
        b = rng.integers(0, 1 << 20, n)
        e = b + rng.integers(1, 1 << 10, n)
        rows_b = keymod.encode_keys([int(x).to_bytes(4, "big") for x in b], 16)
        rows_e = keymod.encode_keys([int(x).to_bytes(4, "big") for x in e], 16)
        return jnp.asarray(rows_b), jnp.asarray(rows_e)

    for trial in range(4):
        rb, re_ = intervals(R)
        wb, we = intervals(Wn)
        r_tx = rng.integers(-1, B, R).astype(np.int32)
        w_tx = rng.integers(-1, B, Wn).astype(np.int32)
        args = (
            rb, re_, wb, we,
            jnp.asarray(r_tx >= 0), jnp.asarray(w_tx >= 0),
            jnp.asarray(np.clip(r_tx, 0, B - 1)),
            jnp.asarray(np.clip(w_tx, 0, B - 1)),
            jnp.asarray(w_tx),
            jnp.asarray(rng.random(B) < 0.9),   # active
            jnp.asarray(rng.random(B) < 0.2),   # prior history conflicts
            B,
        )
        a_dense, n_dense = D.phase_intra_dense(*args)
        a_rank, n_rank = D.phase_intra(*args)
        a_pl, n_pl = D.phase_intra(*args, impl="interpret")
        assert np.array_equal(np.asarray(a_dense), np.asarray(a_rank)), trial
        assert int(n_dense) == int(n_rank), trial
        assert np.array_equal(np.asarray(a_dense), np.asarray(a_pl)), trial
        assert int(n_dense) == int(n_pl), trial


def test_fused_probe_and_run_to_step_parity():
    """The fused history+probe kernel equals hist OR unfused probe (the OR
    of scatters == scatter of ORs identity), XLA vs interpret; and the
    interleave (run_to_step) Pallas lowering is bit-identical to XLA."""
    import numpy as np

    jnp = pytest.importorskip("jax.numpy")
    from foundationdb_tpu import keys as keymod
    from foundationdb_tpu.conflict import device as D

    rng = np.random.default_rng(11)
    Wn, K, run_cap, R = 32, 4, 128, 64

    def intervals(n):
        b = rng.integers(0, 1 << 16, n)
        e = b + rng.integers(1, 1 << 8, n)
        rows_b = keymod.encode_keys([int(x).to_bytes(4, "big") for x in b], 16)
        rows_e = keymod.encode_keys([int(x).to_bytes(4, "big") for x in e], 16)
        return jnp.asarray(rows_b), jnp.asarray(rows_e)

    wb, we = intervals(Wn)
    w_ins = jnp.asarray(rng.random(Wn) < 0.7)
    u_sort = D._union_intervals(wb, we, w_ins, run_cap=run_cap,
                                merge_impl="sort")
    u_scat = D._union_intervals(wb, we, w_ins, run_cap=run_cap,
                                merge_impl="scatter")
    assert np.array_equal(np.asarray(u_sort[0]), np.asarray(u_scat[0]))
    assert np.array_equal(np.asarray(u_sort[1]), np.asarray(u_scat[1]))

    u_b, u_e = u_sort
    s_xla = D.run_to_step(u_b, u_e, jnp.int32(42))
    s_pl = D.run_to_step(u_b, u_e, jnp.int32(42), impl="interpret")
    assert np.array_equal(np.asarray(s_xla[0]), np.asarray(s_pl[0]))
    assert np.array_equal(np.asarray(s_xla[1]), np.asarray(s_pl[1]))

    runs_b = jnp.stack([u_b] * K)
    runs_e = jnp.stack([u_e] * K)
    runs_ver = jnp.asarray(rng.integers(0, 100, K).astype(np.int32))
    rb, re_ = intervals(R)
    snap_r = jnp.asarray(rng.integers(0, 100, R).astype(np.int32))
    r_ok = jnp.asarray(rng.random(R) < 0.9)
    hist_r = jnp.asarray(rng.random(R) < 0.3) & r_ok
    fused_args = (rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver, hist_r)
    f_xla = pallas_kernel.run_conflicts_fused(*fused_args, impl="xla")
    f_int = pallas_kernel.run_conflicts_fused(*fused_args, impl="interpret")
    unfused = hist_r | pallas_kernel.run_conflicts(
        rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver, impl="xla")
    assert np.array_equal(np.asarray(f_xla), np.asarray(unfused))
    assert np.array_equal(np.asarray(f_xla), np.asarray(f_int))


def test_merge_impl_sharded_parity():
    """The sharded backend folds per-partition with the same impl family:
    all three must agree with the multi-oracle on one duplicate-heavy
    stream that forces at least one deferred fold."""
    from foundationdb_tpu.parallel.sharded import (
        ShardedDeviceConflictSet,
        make_resolver_mesh,
    )
    from tests.test_sharded import MultiOracle

    mesh = make_resolver_mesh(2)
    splits = [b"b"]
    for impl in ("sort", "scatter", "gather"):
        rng = random.Random(17)
        ref = MultiOracle(splits)
        cs = ShardedDeviceConflictSet(
            mesh, splits, capacity=1 << 8, incremental=True,
            run_slots=2, run_capacity=32, merge_impl=impl,
        )
        version = 0
        for _ in range(8):
            version += rng.randrange(1, 4)
            txns = [
                TxInfo(
                    read_snapshot=max(version - 2, 0),
                    read_ranges=[_rand_range(rng)],
                    write_ranges=[(k := _rand_key(rng, b"abc", 3), k + b"\x00")],
                )
                for _ in range(rng.randrange(1, 6))
            ]
            assert cs.resolve_batch(version, txns) == ref.resolve_batch(
                version, txns), impl
        assert cs.compactions >= 1, impl
        assert cs.kernel_stats()["merge_impl"] == impl


@pytest.mark.slow
def test_pallas_compiled_tpu_parity():
    """Compiled-Pallas lowering on real TPU hardware (the production path
    of the capability probe).  Skips unless the default backend is a TPU —
    the CPU twin of this sweep is test_pallas_interpret_parity_sweep."""
    import jax

    if jax.default_backend() != "tpu":
        pytest.skip("no TPU backend available")
    rng = random.Random(1)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(
        capacity=1 << 12, incremental=True, run_slots=4,
        run_capacity=256, pallas="tpu",
    )
    assert dev._probe_impl == "tpu"
    version = 0
    for _ in range(30):
        version += rng.randrange(1, 8)
        txns = _rand_batch(rng, version, oracle.oldest_version, rng.randrange(1, 16))
        assert dev.resolve_batch(version, txns) == oracle.resolve_batch(version, txns)
        if rng.random() < 0.3:
            floor = rng.randrange(version + 1)
            oracle.remove_before(floor)
            dev.remove_before(floor)


def test_sharded_incremental_fold_regrow():
    """The sharded deferred fold must regrow a partition's main level when
    the folded union outgrows it (the incremental twin of
    test_sharded.test_sharded_capacity_regrow), with multi-oracle parity."""
    from foundationdb_tpu.parallel.sharded import (
        ShardedDeviceConflictSet,
        make_resolver_mesh,
    )
    from tests.test_sharded import MultiOracle

    mesh = make_resolver_mesh(2)
    splits = [b"\x80"]
    ref = MultiOracle(splits)
    cs = ShardedDeviceConflictSet(
        mesh, splits, capacity=16, incremental=True,
        run_slots=2, run_capacity=64,
    )
    version = 0
    for b in range(6):
        version += 2
        txns = [
            TxInfo(max(version - 2, 0), [], [(bytes([0, b, i]), bytes([0, b, i, 0]))])
            for i in range(20)
        ]
        assert cs.resolve_batch(version, txns) == ref.resolve_batch(version, txns)
    assert cs.compactions >= 1
    assert cs.regrows >= 1 and cs.capacity > 16
    probe = [TxInfo(1, [(bytes([0, 0, 5]), bytes([0, 0, 6]))], [])]
    version += 1
    assert cs.resolve_batch(version, probe) == ref.resolve_batch(version, probe)
