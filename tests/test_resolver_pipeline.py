"""Split-phase (pipelined) resolver tests — the FDBTPU_PIPELINE input
pipeline of docs/KERNEL.md: verdicts identical to the synchronous resolver,
strictly version-ordered verdict delivery, retry-cache correctness when a
proxy retries a batch whose verdicts are still deferred in the stream, and
chaos/serializability coverage with the knob on."""

import random

import pytest

from foundationdb_tpu.cluster import SimCluster
from foundationdb_tpu.conflict.api import TxInfo, Verdict
from foundationdb_tpu.conflict.device import DeviceConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.roles import resolver as resolver_mod
from foundationdb_tpu.roles.resolver import Resolver
from foundationdb_tpu.roles.types import ResolveTransactionBatchRequest
from foundationdb_tpu.rpc.stream import RequestStreamRef
from foundationdb_tpu.runtime import buggify
from foundationdb_tpu.runtime.combinators import wait_all


@pytest.fixture(autouse=True)
def _buggify_off():
    yield
    buggify.disable()


def _mk_resolver(c, cs, pipeline):
    p = c.net.create_process(f"resolver-test-{id(cs) & 0xFFFF}")
    r = Resolver(p, c.loop, c.knobs, cs, pipeline=pipeline)
    client = c.net.create_process(f"client-{id(cs) & 0xFFFF}")
    ref = RequestStreamRef(c.net, client, r.stream.endpoint)
    return r, ref


def _rand_batches(seed: int, n_batches: int, oldest_fn=None):
    rng = random.Random(seed)

    def rkey():
        return bytes(rng.choice(b"abcde") for _ in range(rng.randrange(6)))

    def rrange():
        a, b = sorted((rkey(), rkey()))
        return a, b + b"\x00"

    batches = []
    version = 0
    for _ in range(n_batches):
        prev = version
        version += rng.randrange(1, 5)
        txns = [
            TxInfo(
                rng.randrange(max(version - 6, 0), version),
                [rrange() for _ in range(rng.randrange(3))],
                [rrange() for _ in range(rng.randrange(3))],
            )
            for _ in range(rng.randrange(1, 6))
        ]
        batches.append((prev, version, txns))
    return batches


def _drive(c, ref, batches, deadline=120.0):
    """Send every batch concurrently (so successors queue behind the version
    chain and the split-phase path genuinely overlaps); returns committed
    lists in batch order."""

    async def one(prev, v, txns):
        return await ref.get_reply(
            ResolveTransactionBatchRequest(prev, v, txns)
        )

    async def main():
        tasks = [c.loop.spawn(one(p, v, t)) for p, v, t in batches]
        replies = await wait_all(tasks)
        return [r.committed for r in replies]

    return c.run_until(c.loop.spawn(main()), deadline)


@pytest.mark.parametrize("backend", ["oracle", "device"])
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_pipelined_resolver_identical_verdicts(backend, seed):
    """The pipelined resolver's reply stream must be bit-identical to the
    synchronous resolver's on the same version-chained batch stream."""
    c = SimCluster(seed=seed)
    mk = (
        (lambda: DeviceConflictSet(capacity=1 << 10))
        if backend == "device"
        else OracleConflictSet
    )
    r_sync, ref_sync = _mk_resolver(c, mk(), pipeline=False)
    r_pipe, ref_pipe = _mk_resolver(c, mk(), pipeline=True)
    batches = _rand_batches(seed, 18)
    got_sync = _drive(c, ref_sync, batches)
    got_pipe = _drive(c, ref_pipe, batches)
    assert got_pipe == got_sync
    r_sync.stop(), r_pipe.stop()
    c.stop()


def test_pipelined_verdict_delivery_version_ordered(monkeypatch):
    """Verdict delivery (reply-cache insertion via _finish) must be strictly
    version-ordered even when batches arrive bunched and out of order."""
    c = SimCluster(seed=77)
    r, ref = _mk_resolver(c, DeviceConflictSet(capacity=1 << 10), pipeline=True)
    finished = []
    orig = Resolver._finish

    def recording_finish(self, pend):
        finished.append(pend.r.version)
        return orig(self, pend)

    monkeypatch.setattr(Resolver, "_finish", recording_finish)
    batches = _rand_batches(21, 20)
    shuffled = list(batches)
    random.Random(3).shuffle(shuffled)  # arrival order != version order
    _drive(c, ref, shuffled)
    assert finished == sorted(finished) and len(finished) == len(batches)
    r.stop()
    c.stop()


def test_retry_of_deferred_batch_gets_real_verdicts(monkeypatch):
    """A proxy retry of a batch whose verdicts are still parked deferred in
    the pipeline must flush the pending batch and receive its REAL cached
    verdicts — not the conservative abort-all fallback."""
    # widen the flush tick so the retry provably lands inside the window
    # where the batch is parked pending
    monkeypatch.setattr(resolver_mod, "_PIPELINE_FLUSH_S", 0.05)
    c = SimCluster(seed=5)
    cs = DeviceConflictSet(capacity=1 << 10)
    twin = DeviceConflictSet(capacity=1 << 10)  # sync referee
    r, ref = _mk_resolver(c, cs, pipeline=True)
    txa = [TxInfo(0, [], [(b"a", b"b")])]
    txb = [TxInfo(5, [(b"a", b"a\x00")], []), TxInfo(5, [], [(b"q", b"r")])]
    want_a = [int(v) for v in twin.resolve_batch(10, txa)]
    want_b = [int(v) for v in twin.resolve_batch(20, txb)]
    assert int(Verdict.COMMITTED) in want_b  # abort-all would differ

    flushed_pending = []
    orig_flush = Resolver._flush_pending

    def recording_flush(self):
        flushed_pending.append(self._pending is not None)
        return orig_flush(self)

    monkeypatch.setattr(Resolver, "_flush_pending", recording_flush)

    async def call(req):
        return await ref.get_reply(req)

    async def main():
        ra = await ref.get_reply(ResolveTransactionBatchRequest(0, 10, txa))
        tb = c.loop.spawn(call(ResolveTransactionBatchRequest(10, 20, txb)))
        # duplicate delivery while B's verdicts are still deferred (B's
        # task parks pending for _PIPELINE_FLUSH_S = 50ms of sim time; the
        # retry arrives within a couple ms)
        tb2 = c.loop.spawn(call(ResolveTransactionBatchRequest(10, 20, txb)))
        rb, rb2 = await wait_all([tb, tb2])
        return ra.committed, rb.committed, rb2.committed

    got_a, got_b, got_b_retry = c.run_until(c.loop.spawn(main()), 60.0)
    assert got_a == want_a
    assert got_b == want_b
    assert got_b_retry == want_b  # the retry saw real verdicts
    # the duplicate path really flushed a parked (deferred) batch
    assert any(flushed_pending)
    r.stop()
    c.stop()


def test_pipelined_resolver_deferred_failure_recovers():
    """Adversarial shared-prefix keys force the device's deferred validity
    check to fail mid-stream; the pipelined resolver must still reply
    oracle-exact verdicts (snapshot/replay recovery in resolve_deferred)."""
    c = SimCluster(seed=9)
    cs = DeviceConflictSet(
        capacity=1 << 14, search_impl="bucket", incremental=False
    )
    ref_cs = OracleConflictSet()
    r, ref = _mk_resolver(c, cs, pipeline=True)
    keys = [b"ZZ%04d" % i for i in range(3000)]
    b1 = [TxInfo(0, [], [(k, k + b"\x00")]) for k in keys]
    b2 = [
        TxInfo(5, [(b"ZZ1500", b"ZZ1501")], [(b"q", b"q\x00")]),
        TxInfo(5, [(b"ZZ0001", b"ZZ2999")], []),
    ]
    want = [
        [int(v) for v in ref_cs.resolve_batch(10, b1)],
        [int(v) for v in ref_cs.resolve_batch(20, b2)],
    ]
    got = _drive(c, ref, [(0, 10, b1), (10, 20, b2)])
    assert got == want
    r.stop()
    c.stop()


def test_deferred_recovery_replays_drained_window_from_txns():
    """A deferred failure with already-drained handles still in the replay
    window: recovery must replay from each handle's TxInfo stream (the
    staging-arena buffers have rotated since those batches packed) and keep
    every verdict oracle-exact — including batches drained BEFORE the
    failure surfaced."""
    dev = DeviceConflictSet(
        capacity=1 << 14, search_impl="bucket", incremental=False
    )
    ref = OracleConflictSet()
    keys = [b"ZZ%04d" % i for i in range(3000)]
    b1 = [TxInfo(0, [], [(k, k + b"\x00")]) for k in keys]  # deep bucket
    wants = [ref.resolve_batch(10, b1)]
    handles = [dev.resolve_deferred(10, b1)]
    v = 10
    for i in range(4):  # benign batches; drain trailing ones so the window
        v += 10         # accumulates replayable (drained) handles
        txns = [
            TxInfo(v - 5, [(b"a%02d" % i, b"a%02d\x00" % i)],
                   [(b"b%02d" % i, b"b%02d\x00" % i)])
        ]
        wants.append(ref.resolve_batch(v, txns))
        handles.append(dev.resolve_deferred(v, txns))
        handles[-2].wait()
    v += 10
    probe = [TxInfo(v - 5, [(b"ZZ1500", b"ZZ1501")], [(b"q", b"q\x00")])]
    wants.append(ref.resolve_batch(v, probe))
    handles.append(dev.resolve_deferred(v, probe))  # deferred non-convergence
    for h, want in zip(handles, wants):
        assert h.wait() == want
    from foundationdb_tpu.runtime import coverage

    assert coverage.hits("kernel.pipeline_recover") >= 1


def test_deferred_window_advance_then_failure():
    """A stream long enough to trip the replay-window validation (which
    force-drains the validated window) followed by a deferred failure must
    still recover to oracle-exact verdicts."""
    dev = DeviceConflictSet(
        capacity=1 << 14, search_impl="bucket", incremental=False
    )
    ref = OracleConflictSet()
    keys = [b"ZZ%04d" % i for i in range(3000)]
    b1 = [TxInfo(0, [], [(k, k + b"\x00")]) for k in keys]
    wants = [ref.resolve_batch(10, b1)]
    handles = [dev.resolve_deferred(10, b1)]
    v = 10
    for i in range(12):  # > _REPLAY_WINDOW drained-with-inflight batches
        v += 10
        txns = [
            TxInfo(v - 5, [(b"c%02d" % i, b"c%02d\x00" % i)],
                   [(b"d%02d" % i, b"d%02d\x00" % i)])
        ]
        wants.append(ref.resolve_batch(v, txns))
        handles.append(dev.resolve_deferred(v, txns))
        handles[-2].wait()  # keeps one in flight while the window grows
    v += 10
    probe = [TxInfo(v - 5, [(b"ZZ1500", b"ZZ1501")], [(b"q", b"q\x00")])]
    wants.append(ref.resolve_batch(v, probe))
    handles.append(dev.resolve_deferred(v, probe))
    for h, want in zip(handles, wants):
        assert h.wait() == want


def test_pipelined_cluster_occ_end_to_end(monkeypatch):
    """Whole commit path (proxy → pipelined resolver → TLogs) with a
    device backend: OCC conflicts still detected, non-conflicting txns
    commit."""
    monkeypatch.setenv("FDBTPU_PIPELINE", "1")
    from foundationdb_tpu.roles.types import NotCommitted

    c = SimCluster(
        seed=31, conflict_backend=lambda: DeviceConflictSet(capacity=1 << 10)
    )
    db = c.database()

    async def main():
        tr1, tr2 = db.create_transaction(), db.create_transaction()
        await tr1.get(b"k")
        await tr2.get(b"k")
        tr1.set(b"k", b"one")
        tr2.set(b"k", b"two")
        await tr1.commit()
        try:
            await tr2.commit()
            return "second commit unexpectedly succeeded"
        except NotCommitted:
            pass
        tr3 = db.create_transaction()
        await tr3.get(b"other")
        tr3.set(b"other", b"x")
        await tr3.commit()
        tr4 = db.create_transaction()
        return await tr4.get(b"k")

    assert c.run_until(c.loop.spawn(main()), 60.0) == b"one"
    c.stop()


def test_chaos_sweep_pipelined(monkeypatch):
    """The cycle invariant + exact commit count must survive chaos with the
    pipelined resolver path on — and stay deterministic under a seed."""
    monkeypatch.setenv("FDBTPU_PIPELINE", "1")
    from foundationdb_tpu.control.recoverable import RecoverableCluster
    from foundationdb_tpu.workloads.attrition import AttritionWorkload
    from foundationdb_tpu.workloads.base import run_workloads
    from foundationdb_tpu.workloads.cycle import CycleWorkload

    def once():
        cl = RecoverableCluster(seed=1404, n_storage_shards=2, chaos=True)
        cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=6)
        att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.9)
        m = run_workloads(cl, [cyc, att], deadline=600.0)
        out = (m, cl.controller.recoveries, round(cl.loop.now(), 9))
        cl.stop()
        buggify.disable()
        return out

    a = once()
    assert a[0]["Cycle"]["committed"] == 12
    assert a[1] >= 1
    assert a == once(), "pipelined chaos run not deterministic"


def test_serializability_pipelined(monkeypatch):
    """Serial-replay equivalence holds with the pipelined resolver on (the
    workload's journal replay is the serializability referee)."""
    monkeypatch.setenv("FDBTPU_PIPELINE", "1")
    from foundationdb_tpu.control.recoverable import RecoverableCluster
    from foundationdb_tpu.workloads.base import run_workloads
    from foundationdb_tpu.workloads.serializability import (
        SerializabilityWorkload,
    )

    cl = RecoverableCluster(seed=543, n_storage_shards=2)
    metrics = run_workloads(
        cl, [SerializabilityWorkload(clients=3, txns_per_client=12)],
        deadline=600.0,
    )
    assert metrics["Serializability"]["committed"] >= 30
    cl.stop()
