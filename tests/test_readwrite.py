"""ReadWrite perf workload: rates + latency percentiles exist and behave
(the repo counterpart of BASELINE.md's per-core ops/s rows — numbers to
regress against; ref fdbserver/workloads/ReadWrite.actor.cpp:252-270)."""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.readwrite import ReadWriteWorkload, percentile


def test_percentile_helper():
    xs = sorted([0.001 * i for i in range(100)])
    assert percentile(xs, 0.50) == 0.050
    assert percentile(xs, 0.99) == 0.099
    assert percentile([], 0.5) == 0.0


def test_readwrite_90_10_mix():
    c = RecoverableCluster(seed=95, n_storage_shards=2)
    rw = ReadWriteWorkload(keys=200, clients=4, duration=3.0,
                           reads_per_tx=9, writes_per_tx=1)
    metrics = run_workloads(c, [rw], deadline=600.0)
    m = metrics["ReadWrite"]
    assert m["committed"] > 50
    assert m["tx_per_s"] > 10
    # percentiles are populated and ordered
    for op in ("grv", "read", "commit"):
        assert 0 < m[f"{op}_p50_ms"] <= m[f"{op}_p90_ms"] <= m[f"{op}_p99_ms"]
    c.stop()


def test_readwrite_write_heavy_mix():
    c = RecoverableCluster(seed=96, n_storage_shards=2)
    rw = ReadWriteWorkload(keys=200, clients=4, duration=3.0,
                           reads_per_tx=1, writes_per_tx=5)
    metrics = run_workloads(c, [rw], deadline=600.0)
    m = metrics["ReadWrite"]
    assert m["committed"] > 50
    assert m["commit_p50_ms"] > 0
    c.stop()
