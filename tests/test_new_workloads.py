"""Round-5 invariant workloads: Serializability (versionstamped journal,
serial-replay equivalence), FuzzApiCorrectness (randomized API sequences),
and the restarting pair (save state, new process, resume — the
tests/restarting/ CycleTestRestart-1/-2 shape)."""

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime import buggify
from foundationdb_tpu.workloads.attrition import AttritionWorkload
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.cycle import CycleWorkload
from foundationdb_tpu.workloads.fuzzapi import FuzzApiWorkload
from foundationdb_tpu.workloads.serializability import SerializabilityWorkload


@pytest.fixture(autouse=True)
def _buggify_off():
    yield
    buggify.disable()


def test_versionstamped_key_substitution():
    """SET_VERSIONSTAMPED_KEY: the proxy splices (commit version, batch
    order) into the placeholder, keys sort in commit order."""
    from foundationdb_tpu.roles.types import MutationType

    c = RecoverableCluster(seed=540)
    db = c.database()

    async def main():
        versions = []
        for i in range(3):
            tr = db.create_transaction()
            key = b"vs/" + b"\x00" * 10 + (3).to_bytes(4, "little")
            tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key, b"p%d" % i)
            versions.append(await tr.commit())
        tr = db.create_transaction()
        rows = await tr.get_range(b"vs/", b"vs0", limit=100)
        return versions, rows

    versions, rows = c.run_until(c.loop.spawn(main()), 120)
    assert [v for _k, v in rows] == [b"p0", b"p1", b"p2"]  # commit order
    for (k, _v), ver in zip(rows, versions):
        assert int.from_bytes(k[3:11], "big") == ver  # stamped version
    c.stop()


def test_serializability_plain():
    c = RecoverableCluster(seed=541, n_storage_shards=2)
    metrics = run_workloads(
        c, [SerializabilityWorkload(clients=3, txns_per_client=12)],
        deadline=600.0,
    )
    assert metrics["Serializability"]["committed"] >= 30
    c.stop()


def test_serializability_under_chaos():
    """The serial-replay equivalence must hold through kills + buggify —
    this is the workload's whole point."""
    c = RecoverableCluster(seed=542, n_storage_shards=2, chaos=True)
    ser = SerializabilityWorkload(clients=2, txns_per_client=8)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
    metrics = run_workloads(c, [ser, att], deadline=900.0)
    assert metrics["Serializability"]["committed"] >= 10
    assert c.controller.recoveries >= 1
    c.stop()


def test_fuzz_api_correctness():
    c = RecoverableCluster(seed=543)
    metrics = run_workloads(
        c, [FuzzApiWorkload(clients=3, ops_per_client=150)], deadline=600.0
    )
    assert metrics["FuzzApi"]["ops"] == 450
    c.stop()


def test_fuzz_api_under_chaos():
    c = RecoverableCluster(seed=544, chaos=True)
    fz = FuzzApiWorkload(clients=2, ops_per_client=80)
    att = AttritionWorkload(kills=1, interval=1.5, start_delay=0.6)
    metrics = run_workloads(c, [fz, att], deadline=900.0)
    assert metrics["FuzzApi"]["ops"] == 160
    c.stop()


def test_restarting_pair_cycle():
    """The tests/restarting/ shape: part 1 runs Cycle and powers off
    mid-state; part 2 resumes from the same disks (a NEW cluster object —
    the 'new binary' of an upgrade test) and the ring invariant still
    holds, then more rotations run."""
    c1 = RecoverableCluster(seed=545, n_storage_shards=2)
    cyc1 = CycleWorkload(nodes=8, clients=2, txns_per_client=6)
    metrics1 = run_workloads(c1, [cyc1], deadline=600.0)
    assert metrics1["Cycle"]["committed"] == 12
    fs = c1.power_off()

    c2 = RecoverableCluster(seed=546, fs=fs, restart=True)
    # part 2's check: the ring survived the restart...
    cyc2 = CycleWorkload(nodes=8, clients=2, txns_per_client=6)
    cyc2.skip_setup = True

    async def no_setup(cluster, rng):
        return None

    cyc2.setup = no_setup  # the ring already exists on disk
    metrics2 = run_workloads(c2, [cyc2], deadline=600.0)
    # ...and more rotations committed on the restarted cluster
    assert metrics2["Cycle"]["committed"] == 12
    c2.stop()


def test_configure_database_swizzle_with_cycle():
    """Random role-count + redundancy flips under a Cycle load: every flip
    converges and the ring invariant holds throughout (the reference's
    ConfigureDatabase workload composed with an invariant checker)."""
    from foundationdb_tpu.workloads.configure_db import ConfigureDatabaseWorkload

    c = RecoverableCluster(
        seed=547, n_machines=6, n_dcs=2, n_storage_shards=2,
        redundancy="double",
    )
    cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=10)
    cfg = ConfigureDatabaseWorkload(flips=3, interval=1.0)
    metrics = run_workloads(c, [cyc, cfg], deadline=900.0)
    assert metrics["Cycle"]["committed"] == 20
    assert metrics["ConfigureDatabase"]["applied"] == 3
    assert metrics["ConfigureDatabase"]["converged"] == 3
    c.stop()
