"""Regression tests for the round-1 advisor findings (ADVICE.md):

1. VersionedOverlay.forget_before replayed clears into the base AFTER newer
   per-key sets, silently deleting committed data (clear@v1 + set@v2,
   flush@5 -> base lost the set).
2. TLog published mutations to its tag queues before the sync delay, so
   peek/lock could serve unacked data; with a replica loss this left storage
   applied above the recovery version (phantom UNKNOWN-result mutations).
   Storage now also rolls back past the recovery version on rewire.
3. A single dropped commit-path packet left the sequencer-assigned version
   as a permanent hole in the prev->version chain, wedging the pipeline
   forever.  The sequencer now dedups retried request_nums and the proxy
   retries idempotently.
"""

from foundationdb_tpu.roles.storage import MemoryKeyValueStore, VersionedOverlay
from foundationdb_tpu.roles.types import Mutation, MutationType


def mk_set(k, v):
    return Mutation(MutationType.SET_VALUE, k, v)


def mk_clear(b, e):
    return Mutation(MutationType.CLEAR_RANGE, b, e)


class TestForgetBefore:
    def test_set_after_clear_survives_flush(self):
        """ADVICE high #1 repro: clear [a,z)@1 + set b@2, flush@5 -> get(b)
        must return the set value from the base, not None."""
        base = MemoryKeyValueStore()
        base.set(b"a", b"old-a")
        base.set(b"b", b"old-b")
        ov = VersionedOverlay()
        ov.apply(1, mk_clear(b"a", b"z"), base.get)
        ov.apply(2, mk_set(b"b", b"new-b"), base.get)
        assert ov.get(b"b", 3, base.get) == b"new-b"
        ov.forget_before(5, base.set, base.clear_range)
        # after the window ages out, reads come straight from the base
        assert ov.get(b"b", 100, base.get) == b"new-b"
        assert ov.get(b"a", 100, base.get) is None

    def test_same_version_clear_then_set(self):
        """A set AFTER a clear in mutation order at the same version wins
        (chain position, not version comparison)."""
        base = MemoryKeyValueStore()
        base.set(b"k", b"old")
        ov = VersionedOverlay()
        ov.apply(3, mk_clear(b"a", b"z"), base.get)
        ov.apply(3, mk_set(b"k", b"new"), base.get)
        assert ov.get(b"k", 3, base.get) == b"new"
        ov.forget_before(4, base.set, base.clear_range)
        assert ov.get(b"k", 100, base.get) == b"new"

    def test_set_then_clear_is_cleared(self):
        base = MemoryKeyValueStore()
        ov = VersionedOverlay()
        ov.apply(1, mk_set(b"k", b"v"), base.get)
        ov.apply(2, mk_clear(b"a", b"z"), base.get)
        ov.forget_before(3, base.set, base.clear_range)
        assert ov.get(b"k", 100, base.get) is None

    def test_rollback_to_discards_phantoms(self):
        base = MemoryKeyValueStore()
        ov = VersionedOverlay()
        ov.apply(1, mk_set(b"a", b"committed"), base.get)
        ov.apply(5, mk_set(b"a", b"phantom"), base.get)
        ov.apply(6, mk_clear(b"b", b"c"), base.get)
        ov.rollback_to(3)
        assert ov.get(b"a", 10, base.get) == b"committed"
        assert ov._clears == []


class TestTLogUnackedInvisible:
    def _mk(self, sync_delay):
        from foundationdb_tpu.roles.tlog import TLog
        from foundationdb_tpu.rpc.network import SimNetwork
        from foundationdb_tpu.rpc.stream import RequestStreamRef
        from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop

        loop = EventLoop()
        rng = DeterministicRandom(7)
        net = SimNetwork(loop, rng)
        tproc = net.create_process("tlog")
        cproc = net.create_process("client")
        tlog = TLog(tproc, loop, sync_delay=sync_delay)
        return loop, net, cproc, tlog

    def test_peek_never_serves_unacked(self):
        """During the sync delay the commit is not durable: peek must not
        serve it, and lock must not include it."""
        from foundationdb_tpu.roles.types import (
            TLogCommitRequest,
            TLogLockRequest,
            TLogPeekRequest,
        )
        from foundationdb_tpu.rpc.stream import RequestStreamRef

        loop, net, cproc, tlog = self._mk(sync_delay=0.05)

        results = {}

        async def committer():
            ref = RequestStreamRef(net, cproc, tlog.commit_stream.endpoint)
            m = {"ss-0": [mk_set(b"k", b"v")]}
            results["ack"] = await ref.get_reply(TLogCommitRequest(0, 10, m))

        async def peeker():
            # wait until the commit is mid-sync, then peek
            await loop.delay(0.02)
            ref = RequestStreamRef(net, cproc, tlog.peek_stream.endpoint)
            rep = await ref.get_reply(TLogPeekRequest("ss-0", 1))
            results["mid_sync_entries"] = list(rep.entries)
            results["mid_sync_end"] = rep.end_version
            lref = RequestStreamRef(net, cproc, tlog.lock_stream.endpoint)
            # lock fires after sync completes; check the final state too
            await loop.delay(0.1)
            rep2 = await ref.get_reply(TLogPeekRequest("ss-0", 1))
            results["after_entries"] = list(rep2.entries)
            results["lock"] = await lref.get_reply(TLogLockRequest())

        t1 = loop.spawn(committer())
        t2 = loop.spawn(peeker())
        loop.run_until(t2, deadline=10.0)
        assert results["mid_sync_entries"] == []
        assert results["mid_sync_end"] <= 1  # no version beyond acked
        assert results["ack"] == 10
        assert [v for v, _ in results["after_entries"]] == [10]
        assert results["lock"].end_version == 10

    def test_lock_mid_sync_discards_unacked(self):
        """A lock arriving during the sync delay ends the epoch: the unacked
        commit must never be acked nor appear in the locked tag data."""
        from foundationdb_tpu.roles.types import (
            TLogCommitRequest,
            TLogLockRequest,
        )
        from foundationdb_tpu.rpc.stream import RequestStreamRef
        from foundationdb_tpu.runtime.core import TimedOut

        loop, net, cproc, tlog = self._mk(sync_delay=0.05)
        results = {}

        async def committer():
            ref = RequestStreamRef(net, cproc, tlog.commit_stream.endpoint)
            m = {"ss-0": [mk_set(b"k", b"v")]}
            try:
                results["ack"] = await ref.get_reply(
                    TLogCommitRequest(0, 10, m), timeout=1.0
                )
            except TimedOut:
                results["ack"] = "timed-out"

        async def locker():
            await loop.delay(0.02)  # mid-sync
            lref = RequestStreamRef(net, cproc, tlog.lock_stream.endpoint)
            results["lock"] = await lref.get_reply(TLogLockRequest())

        async def settle():
            await loop.delay(2.0)

        loop.spawn(committer())
        t = loop.spawn(locker())
        loop.run_until(t, deadline=10.0)
        loop.run_until(loop.spawn(settle()), deadline=10.0)
        assert results["ack"] == "timed-out"
        assert results["lock"].end_version == 0
        assert results["lock"].tags.get("ss-0", []) == []


class TestSequencerDedup:
    def test_retried_request_num_reuses_version(self):
        from foundationdb_tpu.roles.sequencer import Sequencer
        from foundationdb_tpu.roles.types import GetCommitVersionRequest
        from foundationdb_tpu.rpc.network import SimNetwork
        from foundationdb_tpu.rpc.stream import RequestStreamRef
        from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
        from foundationdb_tpu.runtime.knobs import CoreKnobs

        loop = EventLoop()
        net = SimNetwork(loop, DeterministicRandom(9))
        sp = net.create_process("seq")
        cp = net.create_process("proxy")
        seq = Sequencer(sp, loop, CoreKnobs())
        ref = RequestStreamRef(net, cp, seq.stream.endpoint)

        async def main():
            a = await ref.get_reply(GetCommitVersionRequest("p1", 1))
            dup = await ref.get_reply(GetCommitVersionRequest("p1", 1))
            b = await ref.get_reply(GetCommitVersionRequest("p1", 2))
            return a, dup, b

        a, dup, b = loop.run_until(loop.spawn(main()), deadline=10.0)
        assert (a.prev_version, a.version) == (dup.prev_version, dup.version)
        assert b.prev_version == a.version  # chain continues, no hole
        assert b.version > a.version


class TestCommitPathRetry:
    def test_dropped_commit_packet_does_not_wedge(self):
        """ADVICE medium repro: clog the proxy<->resolver pair long enough
        for one RPC timeout; the retried batch must land and later commits
        must keep flowing (previously the pipeline wedged forever)."""
        from foundationdb_tpu.cluster import SimCluster

        c = SimCluster(seed=77, n_resolvers=2)
        db = c.database()

        async def main():
            tr = db.create_transaction()
            tr.set(b"before", b"1")
            await tr.commit()
            # clog proxy <-> resolver0 past the RPC timeout (1s) but well
            # under the proxy's give-up budget
            proxy_addr = c.proxy.commit_stream.endpoint.address
            res_addr = c.resolvers[0].stream.endpoint.address
            c.net.clog_pair(proxy_addr, res_addr, 1.5)
            tr = db.create_transaction()
            tr.set(b"during", b"2")
            await tr.commit()
            tr = db.create_transaction()
            tr.set(b"after", b"3")
            await tr.commit()
            tr2 = db.create_transaction()
            return [
                await tr2.get(b"before"),
                await tr2.get(b"during"),
                await tr2.get(b"after"),
            ]

        got = c.run_until(c.loop.spawn(main()), 120)
        assert got == [b"1", b"2", b"3"]
        c.stop()


class TestOverlayIndexedPaths:
    """The sorted-index fast paths (chain-key bisect for range clears,
    begin-sorted prefix-max-end stabbing for base-miss reads) must agree
    with a brute-force model, including the MVCC version filter."""

    def test_randomized_overlay_vs_bruteforce(self):
        import random

        from foundationdb_tpu.roles.types import Mutation, MutationType

        rng = random.Random(13)
        ov = VersionedOverlay()
        base = MemoryKeyValueStore()
        for i in range(30):
            base.set(b"%02d" % (3 * i), b"base%d" % i)

        model_sets: list[tuple[int, bytes, bytes | None]] = []  # (v, key, val)
        model_clears: list[tuple[int, bytes, bytes]] = []

        def model_get(key: bytes, version: int):
            best = None
            for v, k, val in model_sets:
                if k == key and v <= version:
                    best = (v, val) if best is None or v >= best[0] else best
            cl = max(
                (v for v, b, e in model_clears if v <= version and b <= key < e),
                default=None,
            )
            if best is not None and (cl is None or best[0] >= cl):
                return best[1]
            if cl is not None:
                return None
            return base.get(key)

        v = 0
        for _ in range(200):
            v += rng.randrange(1, 3)
            k = b"%02d" % rng.randrange(95)
            if rng.random() < 0.3:
                e = b"%02d" % rng.randrange(95)
                b, e = min(k, e), max(k, e)
                if b == e:
                    e = b + b"\x00"
                ov.apply(v, Mutation(MutationType.CLEAR_RANGE, b, e), base.get)
                model_clears.append((v, b, e))
            else:
                val = b"v%d" % v
                ov.apply(v, Mutation(MutationType.SET_VALUE, k, val), base.get)
                model_sets.append((v, k, val))
            if rng.random() < 0.1:
                probe_v = rng.randrange(max(v - 20, 0), v + 1)
                for pk in (b"%02d" % rng.randrange(95) for _ in range(5)):
                    assert ov.get(pk, probe_v, base.get) == model_get(pk, probe_v), (
                        f"divergence at key {pk} version {probe_v}"
                    )


def test_unknown_result_fence_commits_through_locked_database():
    """ADVICE r5 #1 regression: the unknown-result fence dummy is ALWAYS
    lock-aware — a commit whose outcome is unknown must be fenceable even
    if the database was locked between the commit and the retry (without
    the fix, on_error raised DatabaseLocked and the fence never ran)."""
    from foundationdb_tpu.client import management as mgmt
    from foundationdb_tpu.control.recoverable import RecoverableCluster
    from foundationdb_tpu.roles.types import CommitUnknownResult

    c = RecoverableCluster(seed=570)
    db = c.database()

    async def main():
        uid = await mgmt.lock_database(db)
        for _ in range(100):
            await c.loop.delay(0.1)
            gen = c.controller.generation
            if gen is not None and all(p.locked == uid for p in gen.proxies):
                break
        assert all(p.locked == uid for p in c.controller.generation.proxies)

        # a NON-lock-aware transaction whose commit outcome is 'unknown':
        # on_error must fence (commit a conflicting dummy) — through the lock
        tr = db.create_transaction()
        tr._read_ranges.append((b"fence/k", b"fence/l"))
        tr._write_ranges.append((b"fence/k", b"fence/l"))
        await tr.on_error(CommitUnknownResult())  # raises without the fix
        return True

    assert c.run_until(c.loop.spawn(main()), 300)
    c.stop()


class TestRound5Advice:
    """Round-5 advisor findings (observability PR satellites)."""

    def test_memory_engine_refuses_total_tag_loss(self):
        """ADVICE round 5: required_tags is passed to LogSystem.lock even
        with no filesystem — a memory-engine cluster losing EVERY replica
        slot of a storage tag must refuse to recover (the data is gone and
        there is no disk fallback), not silently recruit a fresh empty
        generation."""
        from foundationdb_tpu.control.recoverable import RecoverableCluster

        c = RecoverableCluster(
            seed=651, n_storage_shards=1, storage_replication=2,
            durable=False,  # memory engine: no TLog files to fall back to
        )
        db = c.database()

        async def main():
            tr = db.create_transaction()
            tr.set(b"k", b"v")
            await tr.commit()
            # kill EVERY TLog: all replica slots of every tag are lost
            for t in c.controller.generation.tlogs:
                t.process.kill()
            await c.loop.delay(5.0)

        c.run_until(c.loop.spawn(main()), 300)
        errs = c.trace.find("MasterRecoveryError")
        assert any("lost" in e["Error"] for e in errs), errs
        # and no fresh generation ever reached ACCEPTING_COMMITS
        assert c.controller.recovery_state != "fully_recovered" or not errs
        c.stop()

    def test_lock_recovered_before_first_conf_poll(self):
        """ADVICE round 5: a restarted cluster re-learns the database lock
        from the recovered system keyspace DURING recovery — even when the
        lock commit never reached durable storage (it survives only in the
        TLog files) — so not a single non-lock-aware commit can slip in
        before the first conf-poll tick."""
        from foundationdb_tpu.client import management as mgmt
        from foundationdb_tpu.control.recoverable import RecoverableCluster

        c = RecoverableCluster(seed=652, n_storage_shards=1,
                               storage_replication=2)
        db = c.database()

        async def do_lock():
            await mgmt.lock_database(db, b"lock-uid-9")
            # deliberately SHORTER than the storage durability lag: the
            # lock row lives only in the TLogs at power-off
            await c.loop.delay(0.5)

        c.run_until(c.loop.spawn(do_lock()), 300)
        fs = c.power_off()
        c2 = RecoverableCluster(seed=653, fs=fs, restart=True,
                                n_storage_shards=1, storage_replication=2)
        # immediately after bootstrap — no conf poll has run yet
        assert c2.controller._locked == b"lock-uid-9"
        for p in c2.controller.generation.proxies:
            assert p.locked == b"lock-uid-9"
        # and the lock is enforced: a plain commit is refused
        from foundationdb_tpu.roles.errors import DatabaseLocked

        db2 = c2.database()

        async def try_commit():
            tr = db2.create_transaction()
            tr.set(b"x", b"y")
            try:
                await tr.commit()
            except DatabaseLocked:
                return "locked"
            return "committed"

        assert c2.run_until(c2.loop.spawn(try_commit()), 300) == "locked"
        c2.stop()


# ---------------------------------------------------------------------------
# Round-5 advisor findings (ADVICE.md r5)


class TestVersionstampPreResolve:
    """ADVICE r5 low (roles/proxy.py:425): a malformed versionstamp offset
    must fail the transaction BEFORE the resolution phase.  The old code
    flipped the verdict to CONFLICT in phase 4 — after the resolvers had
    already merged the txn's write ranges as committed — leaving phantom
    conflict state that spuriously aborted later readers of those keys."""

    def test_offset_validator_matches_resolver(self):
        from foundationdb_tpu.roles.types import (
            Mutation,
            MutationType,
            resolve_versionstamp,
            versionstamp_offset_ok,
        )

        cases = [
            b"\x00" * 10 + (0).to_bytes(4, "little"),          # ok
            b"k/" + b"\x00" * 10 + (2).to_bytes(4, "little"),  # ok
            b"\x00" * 10 + (200).to_bytes(4, "little"),        # out of range
            b"\x00" * 5 + (0).to_bytes(4, "little"),           # too short
            b"\x01",                                           # < 4 bytes
        ]
        for raw in cases:
            for mt, m in [
                (MutationType.SET_VERSIONSTAMPED_KEY,
                 Mutation(MutationType.SET_VERSIONSTAMPED_KEY, raw, b"v")),
                (MutationType.SET_VERSIONSTAMPED_VALUE,
                 Mutation(MutationType.SET_VERSIONSTAMPED_VALUE, b"k", raw)),
            ]:
                ok = versionstamp_offset_ok(m)
                try:
                    resolve_versionstamp(m, 7, 0)
                    resolved = True
                except ValueError:
                    resolved = False
                assert ok == resolved, (mt, raw)

    def test_malformed_offset_leaves_conflict_set_clean(self):
        """A hostile client's malformed offset (injected past the client
        API's validation) fails its own txn pre-resolve; a reader of the
        same key with a PRE-commit snapshot must then commit — phantom
        committed ranges would abort it."""
        import pytest

        from foundationdb_tpu.control.recoverable import RecoverableCluster
        from foundationdb_tpu.keys import key_after
        from foundationdb_tpu.roles.types import NotCommitted

        c = RecoverableCluster(seed=565)
        db = c.database()

        async def main():
            # snapshot pinned BEFORE the malformed commit: phantom write
            # ranges at the malformed txn's version would conflict with it
            tr2 = db.create_transaction()
            await tr2.get_read_version()

            tr_bad = db.create_transaction()
            tr_bad.set(b"dummy", b"x")
            bad_key = b"vs/" + b"\x00" * 10 + (200).to_bytes(4, "little")
            tr_bad._mutations.append(
                Mutation(MutationType.SET_VERSIONSTAMPED_KEY, bad_key, b"p")
            )
            tr_bad._write_ranges.append((bad_key, key_after(bad_key)))
            with pytest.raises(NotCommitted):
                await tr_bad.commit()

            # reads the exact keys the malformed txn would have poisoned
            assert await tr2.get(bad_key) is None
            assert await tr2.get(b"dummy") is None
            tr2.set(b"clean", b"1")
            await tr2.commit()  # phantom state would raise NotCommitted

            tr3 = db.create_transaction()
            return await tr3.get(b"clean"), await tr3.get(b"dummy")

        clean, dummy = c.run_until(c.loop.spawn(main()), 300)
        assert clean == b"1"
        # pre-resolve failure is all-or-nothing: no mutation of the
        # malformed txn was applied either
        assert dummy is None
        c.stop()


class TestFailoverDrain:
    """ADVICE r5 medium (client/dr.py:277): DR failover's drain target must
    be version-consistent with the lock.  A commit already past the lock
    gate when failover arms it used to commit at a version above `final`,
    surviving on the primary only — the drained failover (pause_commits +
    in-flight drain before reading `final`) makes the outcome atomic."""

    def test_failover_covers_inflight_commit(self):
        from foundationdb_tpu.client.dr import DRAgent
        from foundationdb_tpu.control.recoverable import RecoverableCluster
        from foundationdb_tpu.roles.proxy import CommitProxy
        from foundationdb_tpu.roles.types import GetCommitVersionRequest

        primary = RecoverableCluster(seed=566)
        secondary = RecoverableCluster(seed=567, loop=primary.loop)
        pri_db = primary.database()

        async def main():
            tr = pri_db.create_transaction()
            tr.set(b"base", b"1")
            await tr.commit()

            agent = DRAgent(primary, secondary)
            await agent.start()
            for _ in range(100):
                await primary.loop.delay(0.1)
                gen = secondary.controller.generation
                if gen is not None and all(p.locked for p in gen.proxies):
                    break

            # one-shot stall BETWEEN the lock gate and version assignment,
            # keyed to the batch that actually carries the racer's mutation
            # (other traffic — DR bookkeeping, the failover's own lock txn —
            # must flow): the racing commit is in flight (past the gate, no
            # version yet) exactly while failover arms the lock and samples
            # `final`.
            state = {"armed": True}
            orig = CommitProxy._retry_reply
            orig_inner = CommitProxy._commit_batch_inner

            async def tagged_inner(self, batch):
                if any(
                    any(m.key == b"raced" for m in pc.request.mutations)
                    for pc in batch
                ):
                    self._racer_inflight = True
                try:
                    return await orig_inner(self, batch)
                finally:
                    self._racer_inflight = False

            async def stalled(self, ref, payload, deadline, **kw):
                if (
                    isinstance(payload, GetCommitVersionRequest)
                    and getattr(self, "_racer_inflight", False)
                    and state["armed"]
                ):
                    state["armed"] = False
                    await self.loop.delay(1.0)
                return await orig(self, ref, payload, deadline, **kw)

            CommitProxy._commit_batch_inner = tagged_inner

            CommitProxy._retry_reply = stalled
            try:
                async def racer():
                    tr = pri_db.create_transaction()
                    tr.set(b"raced", b"1")
                    try:
                        await tr.commit()
                        return True
                    except Exception:
                        return False

                task = primary.loop.spawn(racer())
                await primary.loop.delay(0.2)  # let it pass the gate + stall
                final = await agent.failover(timeout=240.0)
                committed = await task
            finally:
                CommitProxy._retry_reply = orig
                CommitProxy._commit_batch_inner = orig_inner

            sec_db = secondary.database()
            tr = sec_db.create_transaction()
            return committed, await tr.get(b"raced"), await tr.get(b"base"), final

        committed, raced, base, final = primary.run_until(
            primary.loop.spawn(main()), 600
        )
        assert base == b"1"
        # atomic outcome: a commit that succeeded on the primary is visible
        # on the promoted secondary (it drained below `final`), and one that
        # failed left no trace on either side
        assert (raced == b"1") == committed
        primary.stop()
        secondary.stop()
