"""Workload harness: invariant workloads against the simulated cluster,
including the device conflict backend in the resolver (the north-star
configuration: same cluster, conflict checks on the XLA kernel)."""

import pytest

from foundationdb_tpu.cluster import SimCluster
from foundationdb_tpu.workloads.bank import BankWorkload
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.conflict_range import ConflictRangeWorkload
from foundationdb_tpu.workloads.cycle import CycleWorkload


def test_cycle_single_resolver():
    c = SimCluster(seed=21)
    w = CycleWorkload(nodes=12, clients=3, txns_per_client=10)
    metrics = run_workloads(c, [w])
    assert metrics["Cycle"]["committed"] == 30
    c.stop()


def test_cycle_and_bank_composed_multi_resolver():
    c = SimCluster(seed=22, n_resolvers=3, n_storage_shards=2, n_tlogs=2)
    cyc = CycleWorkload(nodes=10, clients=2, txns_per_client=8)
    bank = BankWorkload(accounts=8, clients=2, transfers_per_client=8)
    metrics = run_workloads(c, [cyc, bank])
    assert metrics["Cycle"]["committed"] == 16
    assert metrics["Bank"]["committed"] == 16
    c.stop()


def test_conflict_range_parity():
    c = SimCluster(seed=23, n_resolvers=2)
    w = ConflictRangeWorkload(rounds=30)
    metrics = run_workloads(c, [w])
    assert metrics["ConflictRange"]["checked"] == 30
    c.stop()


def test_cycle_with_device_conflict_backend():
    """The north-star wiring: resolver hosts the JAX device kernel; the
    whole cluster sim stays deterministic on the CPU backend."""
    from foundationdb_tpu.conflict.device import DeviceConflictSet

    c = SimCluster(
        seed=24,
        n_resolvers=2,
        conflict_backend=lambda: DeviceConflictSet(capacity=1 << 12),
    )
    w = CycleWorkload(nodes=8, clients=2, txns_per_client=5)
    cr = ConflictRangeWorkload(rounds=10)
    metrics = run_workloads(c, [w, cr])
    assert metrics["Cycle"]["committed"] == 10
    c.stop()


def test_workload_determinism():
    def once():
        c = SimCluster(seed=25, n_resolvers=2)
        w = CycleWorkload(nodes=10, clients=3, txns_per_client=6)
        m = run_workloads(c, [w])
        t = c.loop.now()
        c.stop()
        return m, t

    assert once() == once()
