"""Input-pipeline unit tests: the bulk batch packer (conflict/device.py
pack_batch) vs its loop-path referee, the encode_concat batch encoder vs a
scalar reference, staging-arena discipline, and the recompile-stability
contract (docs/KERNEL.md "Input pipeline")."""

import random
import time

import numpy as np
import pytest

from foundationdb_tpu import keys as keymod
from foundationdb_tpu.conflict.api import KernelStats, TxInfo
from foundationdb_tpu.conflict.device import (
    pack_batch,
    pack_batch_loop,
)
from foundationdb_tpu.conflict.pipeline import PackArena


# ---------------------------------------------------------------------------
# encoder parity: vectorized batch encoder vs a scalar per-key reference
def _encode_scalar(key: bytes, max_key_bytes: int) -> np.ndarray:
    """Per-key reference encoding straight off the keys.py contract:
    big-endian uint32 data words, zero padded, then the length word."""
    kw = max_key_bytes // 4
    out = np.zeros(kw + 1, dtype=np.uint32)
    padded = key + b"\x00" * (4 * kw - len(key))
    for w in range(kw):
        out[w] = int.from_bytes(padded[4 * w : 4 * w + 4], "big")
    out[kw] = len(key)
    return out


ADVERSARIAL_KEYS = [
    b"",                                  # empty key
    b"\x00",                              # single NUL
    b"\x00" * 32,                         # max-length all-NUL
    b"\xff" * 32,                         # max-length all-0xFF
    b"\xff" * 31,                         # non-word-aligned 0xFF run
    b"a",                                 # 1 byte (non-aligned)
    b"ab\x00\x00\x00",                    # interior NUL run, len 5
    b"ab\xff\xff\xff\xff\xffz",           # interior 0xFF run
    b"\x00\xffx" * 7,                     # 21 bytes, mixed runs
    bytes(range(29)),                     # 29 bytes (non-aligned)
    b"prefix\x00suffix",
    b"\xff\x00" * 16,                     # max-length alternating
]


def test_encode_concat_parity_adversarial():
    ks = ADVERSARIAL_KEYS + [
        bytes(random.Random(5).randrange(256) for _ in range(n))
        for n in range(33)  # every length 0..32, incl. non-word-aligned
    ]
    want = np.stack([_encode_scalar(k, 32) for k in ks])
    got_list = keymod.encode_keys(ks, 32)
    lens = np.array([len(k) for k in ks], dtype=np.int64)
    got_concat = keymod.encode_concat(b"".join(ks), lens, 32)
    assert np.array_equal(got_list, want)
    assert np.array_equal(got_concat, want)
    # round trip through decode_key as well
    for i, k in enumerate(ks):
        assert keymod.decode_key(got_concat[i]) == k


def test_encode_concat_too_long_raises():
    with pytest.raises(keymod.KeyTooLongError):
        keymod.encode_concat(b"x" * 40, np.array([40]), 32)


def test_encode_concat_empty():
    assert keymod.encode_concat(b"", np.zeros(0, np.int64), 32).shape == (0, 9)


# ---------------------------------------------------------------------------
# bulk pack vs loop pack: bit-identical tensors
def _rand_txns(rng: random.Random, n: int, with_empty=True):
    def rkey():
        return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 20)))

    def rrange():
        a, b = sorted((rkey(), rkey()))
        if with_empty and rng.random() < 0.25:
            return (a, a)  # empty range: both paths must drop it
        return (a, b + b"\x00")

    return [
        TxInfo(
            rng.randrange(0, 30),
            [rrange() for _ in range(rng.randrange(4))],
            [rrange() for _ in range(rng.randrange(3))],
        )
        for _ in range(n)
    ]


@pytest.mark.parametrize("seed", range(4))
def test_pack_bulk_bit_identical_randomized(seed):
    rng = random.Random(seed)
    arena = PackArena(depth=3)
    off = lambda v: max(v - 3, 0)  # noqa: E731
    off_arr = lambda a: np.maximum(a - 3, 0)  # noqa: E731
    for trial in range(60):
        txns = _rand_txns(rng, rng.randrange(1, 12))
        oldest = rng.randrange(0, 12)  # some txns fall below: TOO_OLD
        a = pack_batch_loop(txns, oldest, off, 32)
        b = pack_batch(txns, oldest, off, 32, arena=arena, offset_array=off_arr)
        c = pack_batch(txns, oldest, off, 32)  # no arena, scalar offset
        assert a[-1] == b[-1] == c[-1]
        for x, y, z in zip(a[:-1], b[:-1], c[:-1]):
            assert np.array_equal(x, y), (seed, trial)
            assert np.array_equal(x, z), (seed, trial)


def test_pack_bulk_over_length_semantics():
    """A live over-length key raises (KeyTooLongError, both paths); an
    over-length key inside a TOO_OLD transaction is silently dropped."""
    long_range = (b"x" * 40, b"x" * 40 + b"y")
    with pytest.raises(keymod.KeyTooLongError):
        pack_batch([TxInfo(5, [long_range], [])], 0, lambda v: v, 32)
    a = pack_batch_loop([TxInfo(0, [long_range], [])], 10, lambda v: v, 32)
    b = pack_batch([TxInfo(0, [long_range], [])], 10, lambda v: v, 32)
    for x, y in zip(a[:-1], b[:-1]):
        assert np.array_equal(x, y)


# ---------------------------------------------------------------------------
# perf smoke: the marshalling phase the bulk path replaced
def test_pack_bulk_marshalling_speedup_smoke():
    """Perf contract of the bulk packer at bench-like shapes (8K txns, 2
    point reads + 1 point write, 15-byte keys in 16-byte lanes).

    Both paths share the (vectorized) lane encoder, which dominates total
    pack time for either — so the headline comparison is the MARSHALLING
    phase the bulk path actually replaced: the per-transaction, per-range
    Python loops + fresh padded-array builds, isolated by the encode_s /
    pad_s split both paths now record.  Nominal measured ratio is ~5x
    (see docs/KERNEL.md); the assertion uses a generous CI margin.  The
    bulk path must also never be slower end to end."""
    rng = np.random.default_rng(0)
    pool = rng.integers(0, 256, size=(1 << 14, 15), dtype=np.uint8)
    keys = [bytes(pool[i]) for i in range(pool.shape[0])]
    B = 4096
    idx = rng.integers(0, len(keys), size=(B, 3))
    txns = [
        TxInfo(5, [(keys[i], keys[i] + b"\x00"), (keys[j], keys[j] + b"\x00")],
               [(keys[k], keys[k] + b"\x00")])
        for i, j, k in idx
    ]
    off = lambda v: max(v - 1, 0)  # noqa: E731
    off_arr = lambda a: np.maximum(a - 1, 0)  # noqa: E731
    arena = PackArena(depth=3)
    # warm both paths (allocations, caches)
    pack_batch_loop(txns, 0, off, 16)
    pack_batch(txns, 0, off, 16, arena=arena, offset_array=off_arr)

    def best(f, n=7):
        out = []
        for _ in range(n):
            s = KernelStats()
            t0 = time.perf_counter()
            f(s)
            out.append((time.perf_counter() - t0, s.pad_s))
        return min(t for t, _ in out), min(p for _, p in out)

    t_loop, pad_loop = best(lambda s: pack_batch_loop(txns, 0, off, 16, stats=s))
    t_bulk, pad_bulk = best(
        lambda s: pack_batch(txns, 0, off, 16, arena=arena, stats=s,
                             offset_array=off_arr)
    )
    assert pad_bulk > 0 and pad_loop > 0  # the split is actually recorded
    marshal_ratio = pad_loop / pad_bulk
    assert marshal_ratio >= 2.5, (
        f"bulk marshalling only {marshal_ratio:.2f}x faster "
        f"(loop pad {pad_loop * 1e3:.2f} ms vs bulk pad {pad_bulk * 1e3:.2f} ms)"
    )
    assert t_bulk <= t_loop * 1.10, (
        f"bulk pack slower end-to-end: {t_bulk * 1e3:.2f} ms vs "
        f"{t_loop * 1e3:.2f} ms"
    )


# ---------------------------------------------------------------------------
# staging arena discipline
def test_arena_role_pools_are_disjoint():
    """Reads and writes of the same bucketed shape must come from separate
    pools (regression: a shared pool rotated twice per batch and handed a
    live in-flight slot to the next pack — JAX zero-copies aligned numpy
    inputs on CPU, so that was real corruption)."""
    a = PackArena(depth=2)
    r = a.rows("r", 16, 5, 0xFFFFFFFF)
    w = a.rows("w", 16, 5, 0xFFFFFFFF)
    assert r.b is not w.b and r.e is not w.e and r.t is not w.t
    # per-role rotation: depth distinct slots before any reuse
    r2 = a.rows("r", 16, 5, 0xFFFFFFFF)
    r3 = a.rows("r", 16, 5, 0xFFFFFFFF)
    assert r2.b is not r.b and r3.b is r.b


def test_arena_pad_region_resentinelled():
    """A slot reused by a smaller batch must show sentinel rows past the
    new live count (bit-identity with fresh np.full allocation)."""
    rng = random.Random(9)
    arena = PackArena(depth=2)
    off = lambda v: v  # noqa: E731
    big = _rand_txns(rng, 10, with_empty=False)
    small = _rand_txns(rng, 2, with_empty=False)
    for _ in range(4):  # cycle slots: big, small through both copies
        pack_batch(big, 0, off, 32, arena=arena)
    got = pack_batch(small, 0, off, 32, arena=arena)
    want = pack_batch_loop(small, 0, off, 32)
    for x, y in zip(want[:-1], got[:-1]):
        assert np.array_equal(x, y)


# ---------------------------------------------------------------------------
# recompile thrash regression (jit cache keyed on bucketed shapes)
def test_recompiles_stable_within_bucket_class():
    """Batch sizes wandering WITHIN one power-of-two bucket class must not
    add compiled shapes; crossing a bucket boundary adds exactly one."""
    from foundationdb_tpu.conflict.device import DeviceConflictSet

    dev = DeviceConflictSet(capacity=1 << 10)
    version = 0

    def batch(n):
        nonlocal version
        version += 1
        txns = [
            TxInfo(
                max(version - 1, 0),
                [(b"r%04d" % ((version * 37 + i) % 997), b"r%04d\x00" % ((version * 37 + i) % 997))],
                [(b"w%04d" % ((version * 31 + i) % 997), b"w%04d\x00" % ((version * 31 + i) % 997))],
            )
            for i in range(n)
        ]
        dev.resolve_batch(version, txns)

    batch(12)  # warmup: compiles the (Bp=16, R=16, Wn=16) shape
    warm = dev.stats.recompiles
    assert warm >= 1
    for n in (9, 11, 13, 15, 10, 14, 12, 16):  # wander within the bucket
        batch(n)
    assert dev.stats.recompiles == warm, "recompile inside one bucket class"
    batch(17)  # crosses into the (32, 32, 32) bucket
    assert dev.stats.recompiles == warm + 1, "bucket crossing must add exactly one shape"
    batch(20)  # stays in the new bucket
    assert dev.stats.recompiles == warm + 1
