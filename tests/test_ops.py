"""Unit tests for device building blocks vs numpy brute force."""

import numpy as np
import pytest

import jax.numpy as jnp

from foundationdb_tpu import keys
from foundationdb_tpu.ops import rmq, search


def _rand_keys(rng, n, max_len=12):
    return [bytes(rng.integers(0, 256, rng.integers(0, max_len + 1)).astype(np.uint8)) for _ in range(n)]


def test_lex_less_matches_bytes():
    rng = np.random.default_rng(0)
    ks = _rand_keys(rng, 300) + [b"", b"a", b"a\x00", b"a" * 12]
    enc = keys.encode_keys(ks, max_key_bytes=16)
    a = jnp.asarray(enc[: len(ks) // 2 * 2 : 2])
    b = jnp.asarray(enc[1 : len(ks) // 2 * 2 : 2])
    got = np.asarray(search.lex_less(a, b))
    want = np.array([ks[2 * i] < ks[2 * i + 1] for i in range(len(got))])
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("n", [1, 7, 64, 100])
def test_bounds_match_numpy(n):
    rng = np.random.default_rng(n)
    pool = sorted(set(_rand_keys(rng, n)))
    enc_sorted = jnp.asarray(keys.encode_keys(pool, max_key_bytes=16))
    qs = _rand_keys(rng, 200) + list(pool)
    qenc = jnp.asarray(keys.encode_keys(qs, max_key_bytes=16))
    lb = np.asarray(search.lower_bound(enc_sorted, qenc))
    ub = np.asarray(search.upper_bound(enc_sorted, qenc))
    for i, q in enumerate(qs):
        want_lb = sum(1 for k in pool if k < q)
        want_ub = sum(1 for k in pool if k <= q)
        assert lb[i] == want_lb, (q, pool)
        assert ub[i] == want_ub


def test_sparse_table_max():
    rng = np.random.default_rng(1)
    v = rng.integers(0, 1000, 97).astype(np.uint32)
    table = rmq.build_sparse_table(jnp.asarray(v), jnp.maximum, 0)
    los = rng.integers(0, 97, 200)
    his = rng.integers(0, 98, 200)
    got = np.asarray(
        rmq.query_sparse_table(table, jnp.asarray(los, jnp.int32), jnp.asarray(his, jnp.int32), jnp.maximum, 0)
    )
    for i in range(200):
        want = v[los[i] : his[i]].max() if his[i] > los[i] else 0
        assert got[i] == want


def test_range_update_point_query_min():
    rng = np.random.default_rng(2)
    n, j = 113, 64
    lo = rng.integers(0, n, j).astype(np.int32)
    hi = np.minimum(lo + rng.integers(1, 40, j), n).astype(np.int32)
    val = rng.integers(0, 500, j).astype(np.int32)
    mask = rng.random(j) < 0.8
    got = np.asarray(
        rmq.range_update_point_query(
            n, jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(val), jnp.asarray(mask), "min", rmq.I32_MAX
        )
    )
    want = np.full(n, int(rmq.I32_MAX), np.int64)
    for t in range(j):
        if mask[t]:
            want[lo[t] : hi[t]] = np.minimum(want[lo[t] : hi[t]], val[t])
    np.testing.assert_array_equal(got, want)


def test_range_update_point_query_max():
    rng = np.random.default_rng(3)
    n, j = 64, 40
    lo = rng.integers(0, n, j).astype(np.int32)
    hi = np.minimum(lo + rng.integers(1, 20, j), n).astype(np.int32)
    val = rng.integers(1, 500, j).astype(np.uint32)
    mask = np.ones(j, bool)
    got = np.asarray(
        rmq.range_update_point_query(n, jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(val), jnp.asarray(mask), "max", 0)
    )
    want = np.zeros(n, np.int64)
    for t in range(j):
        want[lo[t] : hi[t]] = np.maximum(want[lo[t] : hi[t]], val[t])
    np.testing.assert_array_equal(got, want)
