"""Backup/restore (client/backup.py + roles/backup.py): continuous
mutation-log capture via the backup tag, chunked snapshots, clipped log
replay, point-in-time restore, and survival across pipeline recoveries
(fdbclient/FileBackupAgent.actor.cpp semantics)."""

from foundationdb_tpu.client.backup import (
    BackupAgent,
    BackupContainer,
    restore,
)
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.roles.types import MutationType


def _full_read(c, db):
    async def main():
        async def fn(tr):
            return await tr.get_range(b"", b"\xff", limit=1 << 20)

        return await db.run(fn)

    return c.run_until(c.loop.spawn(main()), 900)


def test_backup_restore_roundtrip_under_load():
    src = RecoverableCluster(seed=501, n_storage_shards=2, storage_replication=2)
    db = src.database()
    agent = BackupAgent(src)
    cont = BackupContainer(src.fs, "bk1")

    async def main():
        # phase 1: pre-backup data (only visible via the snapshot)
        for i in range(40):
            tr = db.create_transaction()
            tr.set(b"pre%03d" % i, b"p%d" % i)
            await tr.commit()
        await agent.start(cont)
        snap_v = await agent.snapshot(cont, chunk_rows=16)
        # phase 2: post-snapshot mutations (only visible via the log):
        # overwrites, new keys, a clear, and atomic adds
        for i in range(20):
            tr = db.create_transaction()
            tr.set(b"pre%03d" % i, b"OVER%d" % i)
            tr.set(b"post%03d" % i, b"q%d" % i)
            await tr.commit()
        tr = db.create_transaction()
        tr.clear_range(b"pre030", b"pre035")
        tr.atomic_op(MutationType.ADD, b"ctr", (7).to_bytes(8, "little"))
        await tr.commit()
        tr = db.create_transaction()
        tr.atomic_op(MutationType.ADD, b"ctr", (5).to_bytes(8, "little"))
        await tr.commit()
        v = await db.run(lambda tr: tr.get_read_version())
        await agent.wait_backed_up_to(v)
        await agent.stop()
        return snap_v

    src.run_until(src.loop.spawn(main()), 900)
    want = _full_read(src, db)
    src.stop()

    dst = RecoverableCluster(seed=502, n_storage_shards=2, storage_replication=2)
    db2 = dst.database()

    async def do_restore():
        await restore(db2, cont)

    dst.run_until(dst.loop.spawn(do_restore()), 900)
    got = _full_read(dst, db2)
    assert got == want
    assert (b"ctr", (12).to_bytes(8, "little")) in got  # atomic replay exact
    assert not any(b"pre030" <= k < b"pre035" for k, _v in got)
    dst.stop()


def test_point_in_time_restore():
    src = RecoverableCluster(seed=503, n_storage_shards=1, storage_replication=2)
    db = src.database()
    agent = BackupAgent(src)
    cont = BackupContainer(src.fs, "bk2")

    async def main():
        for i in range(10):
            tr = db.create_transaction()
            tr.set(b"k%02d" % i, b"v1")
            await tr.commit()
        await agent.start(cont)
        await agent.snapshot(cont, chunk_rows=4)
        tr = db.create_transaction()
        tr.set(b"marker", b"mid")
        await tr.commit()
        v_mid = await db.run(lambda tr: tr.get_read_version())
        # phase 3: changes AFTER the point-in-time target
        for i in range(10):
            tr = db.create_transaction()
            tr.set(b"k%02d" % i, b"v2")
            await tr.commit()
        v_end = await db.run(lambda tr: tr.get_read_version())
        await agent.wait_backed_up_to(v_end)
        await agent.stop()
        return v_mid

    v_mid = src.run_until(src.loop.spawn(main()), 900)
    src.stop()

    dst = RecoverableCluster(seed=504, n_storage_shards=1, storage_replication=2)
    db2 = dst.database()

    async def do_restore():
        await restore(db2, cont, target_version=v_mid)

    dst.run_until(dst.loop.spawn(do_restore()), 900)
    got = dict(_full_read(dst, db2))
    assert got[b"marker"] == b"mid"
    assert all(got[b"k%02d" % i] == b"v1" for i in range(10))  # v2 not restored
    dst.stop()


def test_backup_survives_pipeline_recovery():
    """Kill a TLog mid-backup: the worker rejoins the new generation by tag
    and the log stays complete (nothing acked is missing after restore)."""
    src = RecoverableCluster(seed=505, n_storage_shards=1, storage_replication=2)
    db = src.database()
    agent = BackupAgent(src)
    cont = BackupContainer(src.fs, "bk3")

    async def main():
        await agent.start(cont)
        await agent.snapshot(cont, chunk_rows=8)
        for i in range(15):
            tr = db.create_transaction()
            tr.set(b"a%02d" % i, b"x%d" % i)
            await tr.commit()
        epoch = src.controller.epoch
        src.controller.generation.tlogs[0].process.kill()
        for _ in range(400):
            if src.controller.epoch > epoch and src.controller.generation:
                break
            await src.loop.delay(0.1)
        assert src.controller.epoch > epoch
        for i in range(15, 30):
            tr = db.create_transaction()
            tr.set(b"a%02d" % i, b"x%d" % i)
            await tr.commit()
        v = await db.run(lambda tr: tr.get_read_version())
        await agent.wait_backed_up_to(v, timeout=120.0)
        await agent.stop()

    src.run_until(src.loop.spawn(main()), 900)
    want = _full_read(src, db)
    src.stop()

    dst = RecoverableCluster(seed=506, n_storage_shards=1, storage_replication=2)
    db2 = dst.database()

    async def do_restore():
        await restore(db2, cont)

    dst.run_until(dst.loop.spawn(do_restore()), 900)
    got = _full_read(dst, db2)
    assert got == want
    assert len(got) == 30
    dst.stop()


def test_backup_restore_exact_under_chaos():
    """Chaos + attrition while a backup runs: the restored cluster matches
    the source byte-for-byte (the soak's backup dimension, one seed in CI)."""
    from foundationdb_tpu.runtime import buggify
    from foundationdb_tpu.workloads.attrition import AttritionWorkload
    from foundationdb_tpu.workloads.base import run_workloads
    from foundationdb_tpu.workloads.cycle import CycleWorkload
    from foundationdb_tpu.workloads.increment import IncrementWorkload

    try:
        src = RecoverableCluster(seed=3205, n_storage_shards=2,
                                 storage_replication=2, chaos=True)
        agent = BackupAgent(src)
        cont = BackupContainer(src.fs, "bk-chaos")
        src.run_until(src.loop.spawn(agent.start(cont)), 300)
        src.run_until(src.loop.spawn(agent.snapshot(cont, chunk_rows=16)), 600)
        cyc = CycleWorkload(nodes=6, clients=2, txns_per_client=4)
        inc = IncrementWorkload(counters=3, clients=2, adds_per_client=4)
        att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
        run_workloads(src, [cyc, inc, att], deadline=900.0)
        db = src.database()

        async def settle():
            v = [0]

            async def fn(tr):
                v[0] = await tr.get_read_version()

            await db.run(fn)
            await agent.wait_backed_up_to(v[0], timeout=120.0)
            await agent.stop()

            async def fn2(tr):
                return await tr.get_range(b"", b"\xff", limit=100000)

            return await db.run(fn2)

        want = src.run_until(src.loop.spawn(settle()), 900)
        src.stop()
    finally:
        buggify.disable()

    dst = RecoverableCluster(seed=8205, n_storage_shards=2,
                             storage_replication=2)
    db2 = dst.database()

    async def do_restore():
        await restore(db2, cont)

        async def fn(tr):
            return await tr.get_range(b"", b"\xff", limit=100000)

        return await db2.run(fn)

    got = dst.run_until(dst.loop.spawn(do_restore()), 900)
    dst.stop()
    assert got == want
