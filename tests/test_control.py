"""Coordinators + leader election: quorum register semantics, split-brain
prevention, failover."""

import dataclasses

from foundationdb_tpu.control.coordination import (
    CoordinatedState,
    Coordinator,
)
from foundationdb_tpu.control.election import LeaderElector
from foundationdb_tpu.rpc.network import SimNetwork
from foundationdb_tpu.rpc.stream import RequestStreamRef
from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop


def make_coords(n=3, seed=1):
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(seed))
    coords = [Coordinator(net.create_process(f"coord-{i}"), loop) for i in range(n)]
    return loop, net, coords


def cstate_for(net, loop, coords, owner):
    proc = net.create_process(f"client-{owner}")
    return CoordinatedState(
        loop,
        [RequestStreamRef(net, proc, c.read_stream.endpoint) for c in coords],
        [RequestStreamRef(net, proc, c.write_stream.endpoint) for c in coords],
        owner,
    )


def test_read_write_roundtrip():
    loop, net, coords = make_coords()
    cs = cstate_for(net, loop, coords, "a")

    async def main():
        v0, g0 = await cs.read()
        assert v0 is None
        assert await cs.write({"epoch": 1})
        v1, g1 = await cs.read()
        return v1, g1 > g0

    v1, newer = loop.run_until(loop.spawn(main()), 30)
    assert v1 == {"epoch": 1} and newer


def test_survives_minority_coordinator_failure():
    loop, net, coords = make_coords(5)
    cs = cstate_for(net, loop, coords, "a")

    async def main():
        await cs.write("alive")
        coords[0].process.kill()
        coords[3].process.kill()
        assert await cs.write("still-alive")  # 3 of 5 remain
        v, _ = await cs.read()
        return v

    assert loop.run_until(loop.spawn(main()), 30) == "still-alive"


def test_stale_writer_rejected():
    """Two racing writers: after B writes with a newer generation, A's next
    write with its stale generation must fail (split-brain prevention)."""
    loop, net, coords = make_coords()
    a = cstate_for(net, loop, coords, "a")
    b = cstate_for(net, loop, coords, "b")

    async def main():
        await a.read()
        # b races ahead: reads (bumping promises) and writes several times
        for i in range(3):
            await b.read()
            assert await b.write(f"b{i}")
        ok_a = await a.write("a-stale")
        v, _ = await b.read()
        return ok_a, v

    ok_a, v = loop.run_until(loop.spawn(main()), 30)
    assert not ok_a and v == "b2"


def test_leader_election_and_failover():
    loop, net, coords = make_coords(3, seed=7)
    rng = DeterministicRandom(7)
    events = []

    elect_a = LeaderElector(loop, cstate_for(net, loop, coords, "A"), rng, "A", "ep-A", lease=1.0)
    elect_b = LeaderElector(loop, cstate_for(net, loop, coords, "B"), rng, "B", "ep-B", lease=1.0)
    elect_a.start(lambda: events.append(("A", "leader", round(loop.now(), 3))),
                  lambda: events.append(("A", "deposed", round(loop.now(), 3))))
    elect_b.start(lambda: events.append(("B", "leader", round(loop.now(), 3))),
                  lambda: events.append(("B", "deposed", round(loop.now(), 3))))

    async def main():
        await loop.delay(3.0)
        leaders = [e for e in events if e[1] == "leader"]
        assert len(leaders) == 1, f"exactly one leader expected: {events}"
        winner = leaders[0][0]
        # kill the winner's election loop: lease expires, other takes over
        (elect_a if winner == "A" else elect_b).stop()
        await loop.delay(4.0)
        leaders = [e for e in events if e[1] == "leader"]
        assert len(leaders) == 2 and leaders[1][0] != winner, events
        return events

    loop.run_until(loop.spawn(main()), 60)
    elect_a.stop()
    elect_b.stop()
