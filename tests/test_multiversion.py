"""Multi-version client (client/multiversion.py): protocol-probed client
selection, transparent re-selection across an upgrade, and the live
GET_PROTOCOL probe against a real gateway
(fdbclient/MultiVersionTransaction.actor.cpp)."""

import pathlib
import select
import struct
import subprocess
import sys
import textwrap

import pytest

from foundationdb_tpu.client.multiversion import (
    MultiVersionDatabase,
    NoMatchingClient,
    ProtocolMismatch,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


class _FakeClient:
    def __init__(self, version: int, cluster):
        self.version = version
        self.cluster = cluster
        self.closed = False

    def op(self):
        if self.cluster["proto"] != self.version:
            raise ProtocolMismatch()
        return f"served-by-v{self.version}"

    def close(self):
        self.closed = True


def test_selects_matching_client_and_switches_on_upgrade():
    cluster = {"proto": 1}
    made = []

    def factory(v):
        def make():
            c = _FakeClient(v, cluster)
            made.append(c)
            return c

        return make

    mv = MultiVersionDatabase(
        {1: factory(1), 2: factory(2)}, probe=lambda: cluster["proto"]
    )
    assert mv.run(lambda db: db.op()) == "served-by-v1"
    assert mv.active_version == 1

    # UPGRADE: the cluster starts speaking v2; the in-flight client raises
    # ProtocolMismatch and the wrapper re-selects transparently
    cluster["proto"] = 2
    assert mv.run(lambda db: db.op()) == "served-by-v2"
    assert mv.active_version == 2
    assert made[0].closed  # the deposed client was released


def test_unknown_protocol_is_loud():
    mv = MultiVersionDatabase({1: lambda: _FakeClient(1, {"proto": 1})},
                              probe=lambda: 9)
    with pytest.raises(NoMatchingClient):
        mv.run(lambda db: db.op())


GATEWAY_SERVER = textwrap.dedent(
    """
    import sys
    sys.path.insert(0, {repo!r})
    from foundationdb_tpu.control.recoverable import RecoverableCluster
    from foundationdb_tpu.tools.gateway import ClientGateway, GatewayDriver

    c = RecoverableCluster(seed=1401, n_storage_shards=1, storage_replication=2)
    gw = ClientGateway(c.loop, c.database(), port=0)
    print(gw.port, flush=True)
    GatewayDriver(c.loop, gw).serve_forever(wall_timeout=30.0)
    """
)


def test_live_protocol_probe():
    """GET_PROTOCOL round-trips against a real gateway: the probe a
    MultiVersionDatabase would use to pick its client."""
    import socket

    import tempfile

    errf = tempfile.TemporaryFile(mode="w+")
    proc = subprocess.Popen(
        [sys.executable, "-c", GATEWAY_SERVER.format(repo=str(REPO))],
        stdout=subprocess.PIPE, stderr=errf, text=True,
        env={"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
             "PATH": "/usr/bin:/bin"},
    )
    try:
        ready, _, _ = select.select([proc.stdout], [], [], 20.0)
        line = proc.stdout.readline() if ready else ""
        assert line.strip(), "gateway never started"
        port = int(line)

        def probe() -> int:
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            try:
                payload = struct.pack("<QB", 1, 12)  # req 1, GET_PROTOCOL
                s.sendall(struct.pack("<I", len(payload)) + payload)
                hdr = b""
                while len(hdr) < 4:
                    hdr += s.recv(4 - len(hdr))
                (n,) = struct.unpack("<I", hdr)
                body = b""
                while len(body) < n:
                    body += s.recv(n - len(body))
                _req, status = struct.unpack_from("<QB", body)
                assert status == 0
                (version,) = struct.unpack_from("<I", body, 9)
                return version
            finally:
                s.close()

        from foundationdb_tpu.tools.gateway import PROTOCOL_VERSION

        mv = MultiVersionDatabase(
            {PROTOCOL_VERSION: lambda: "real-client"}, probe=probe
        )
        assert mv.run(lambda db: db) == "real-client"
        assert mv.active_version == PROTOCOL_VERSION
    finally:
        proc.kill()
        proc.wait()
        errf.close()
