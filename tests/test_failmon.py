"""Cluster-wide FailureMonitor (fdbrpc/FailureMonitor.h:65): fed by the
controller's heartbeats + data distribution's storage pings, consulted by
client load-balancing; the sim can lie to it for partition tests."""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.rpc.failmon import FailureMonitor


def test_monitor_transitions_and_override():
    clock = [0.0]
    fm = FailureMonitor(lambda: clock[0])
    a = ("1.2.3.4", 1)
    fm.set_status(a, False)
    assert not fm.is_failed(a)
    clock[0] = 5.0
    fm.set_status(a, True)
    assert fm.is_failed(a)
    assert fm.status(a).since == 5.0
    fm.set_status(a, True)  # idempotent: no new transition
    assert fm.transitions == 2
    # the sim lies: a live address reported failed (partition injection)
    b = ("5.6.7.8", 2)
    fm.set_status(b, False)
    fm.set_override(b, True)
    assert fm.is_failed(b)
    fm.set_override(b, None)
    assert not fm.is_failed(b)
    assert fm.failed_addresses() == [a]


def test_loadbalance_consults_monitor():
    """A dead replica's address is marked failed by the DD pings, and
    client reads then SKIP it at pick time (no per-read timeout to
    rediscover) — LoadBalance.actor.h consulting getState."""
    c = RecoverableCluster(seed=550, n_storage_shards=1, storage_replication=2)
    db = c.database()
    fm = c.controller.failure_monitor
    assert db._qm.failmon is fm  # the view carries the monitor

    async def main():
        tr = db.create_transaction()
        for i in range(10):
            tr.set(b"k%d" % i, b"v")
        await tr.commit()

        dead = c.storage[0]
        dead.process.kill()
        # the DD ping cycle marks it failed (and may then heal + forget it
        # within the same window — both observations prove the feed)
        saw_failed = False
        for _ in range(300):
            await c.loop.delay(0.1)
            saw_failed = saw_failed or fm.is_failed(dead.process.address)
            if saw_failed or c.dd.heals >= 1:
                break
        assert saw_failed or c.dd.heals >= 1

        # reads now avoid the dead replica AT PICK TIME: 20 reads complete
        # well inside what even two per-read discovery timeouts would cost
        t0 = c.loop.now()
        for i in range(20):
            tr = db.create_transaction()
            assert await tr.get(b"k%d" % (i % 10)) == b"v"
        elapsed = c.loop.now() - t0
        assert elapsed < 2.0, f"reads took {elapsed}s: monitor not consulted"

        # the healed replacement is eventually marked live again, and the
        # RETIRED address leaves the map (forget on heal)
        for _ in range(600):
            await c.loop.delay(0.1)
            if c.dd.heals >= 1:
                break
        assert c.dd.heals >= 1
        assert fm.status(dead.process.address) is None
        return True

    assert c.run_until(c.loop.spawn(main()), 600)
    c.stop()


def test_override_steers_reads_away_from_live_replica():
    """Partition-test hook: lie that a LIVE replica is failed; reads still
    succeed (the other replica serves) — and recover when the lie clears."""
    c = RecoverableCluster(seed=551, n_storage_shards=1, storage_replication=2)
    db = c.database()
    fm = c.controller.failure_monitor

    async def main():
        tr = db.create_transaction()
        tr.set(b"x", b"1")
        await tr.commit()
        victim = c.storage[0]
        fm.set_override(victim.process.address, True)
        for _ in range(10):
            tr = db.create_transaction()
            assert await tr.get(b"x") == b"1"
        fm.set_override(victim.process.address, None)
        tr = db.create_transaction()
        assert await tr.get(b"x") == b"1"
        return True

    assert c.run_until(c.loop.spawn(main()), 300)
    c.stop()
