"""bench.py device-probe budget contract (ISSUE r6 satellite).

BENCH_r05 burned ~6 minutes on two consecutive ~180 s probe "hangs"
despite PR 4's documented <60 s worst case.  Two holes, both pinned here:

  * `subprocess.run(capture_output=True, timeout=...)` kills only the
    direct probe child on timeout, then BLOCKS reading its pipes until
    every grandchild holding them exits — a wedged PJRT helper stretched
    a 20 s budget to the driver's outer bound.  `_run_probe` now runs the
    probe in its own process group and group-kills it.
  * driver-supplied BENCH_INIT_TIMEOUT could raise the retry budget
    arbitrarily.  `_probe_budgets` clamps every attempt to the
    supervisor's DEVICE_WATCHDOG_S knob.
"""

import sys
import time

import bench
from foundationdb_tpu.runtime.knobs import CoreKnobs

WATCHDOG = CoreKnobs().DEVICE_WATCHDOG_S


def test_probe_budgets_total_is_bounded():
    """No env/cache combination may push total probe wall past
    2x DEVICE_WATCHDOG_S (the documented <60 s worst case)."""
    hostile_envs = [
        {},
        {"BENCH_INIT_TIMEOUT": "180"},            # the r05 driver override
        {"BENCH_INIT_TIMEOUT": "600", "BENCH_PROBE_FAST_S": "500"},
        {"BENCH_INIT_TIMEOUT": "nonsense", "BENCH_PROBE_FAST_S": "-x"},
        {"BENCH_PROBE_FAST_S": "5"},
    ]
    for env in hostile_envs:
        for cache in (None, {"ok": True}, {"ok": False, "detail": "down"}):
            budgets = bench._probe_budgets(cache, env)
            assert budgets, (env, cache)
            assert sum(budgets) <= 2 * WATCHDOG, (env, cache, budgets)
            assert all(b <= WATCHDOG for b in budgets), (env, cache, budgets)


def test_probe_budgets_cached_failure_single_attempt():
    """A cached tunnel-down verdict keeps exactly ONE short attempt."""
    assert len(bench._probe_budgets({"ok": False}, {})) == 1
    assert len(bench._probe_budgets({"ok": True}, {})) == 2
    assert len(bench._probe_budgets(None, {})) == 2


def test_run_probe_group_kill_beats_pipe_holding_grandchild(monkeypatch):
    """THE r05 regression: a probe that spawns a long-lived grandchild
    inheriting its stdout/stderr pipes must still be reaped within the
    budget (+ small grace), not when the grandchild exits."""
    hang_src = (
        "import subprocess, sys, time\n"
        # grandchild inherits our pipes and would hold them ~60s
        "subprocess.Popen([sys.executable, '-c', 'import time; time.sleep(60)'])\n"
        "time.sleep(60)\n"
    )
    monkeypatch.setattr(bench, "_PROBE_SRC", hang_src)
    t0 = time.monotonic()
    ok, timed_out, rc, detail = bench._run_probe(2.0)
    elapsed = time.monotonic() - t0
    assert not ok and timed_out
    assert "hung" in detail
    assert elapsed < 12.0, (
        f"probe reap took {elapsed:.1f}s for a 2s budget — the grandchild "
        f"pipe hold is back"
    )


def test_run_probe_success_path(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC", "print('PROBE_OK fake 0.0s')")
    ok, timed_out, rc, detail = bench._run_probe(20.0)
    assert ok and not timed_out and rc == 0
    assert "PROBE_OK" in detail
