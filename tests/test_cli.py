"""Admin CLI: command surface incl. chaos-driven recovery."""

import io

from foundationdb_tpu.tools.cli import Cli


def test_cli_commands_and_kill_recovery():
    cli = Cli(seed=51, n_storage_shards=2)
    assert "committed" in cli.one_command("set k1 v1")
    assert cli.one_command("get k1") == repr(b"v1")
    assert cli.one_command("get nothing") == "<missing>"
    cli.one_command("set k2 v2")
    rng = cli.one_command("getrange k k3")
    assert "k1" in rng and "k2" in rng
    assert "committed" in cli.one_command("clear k1")
    assert cli.one_command("get k1") == "<missing>"
    status = cli.one_command("status")
    assert "epoch 1" in status and "committed" in status

    # chaos: kill the proxy by name, expect a recovery and working cluster
    procs = cli.one_command("processes")
    proxy_name = next(l.split()[0] for l in procs.splitlines() if l.startswith("proxy"))
    out = cli.one_command(f"kill {proxy_name}")
    assert "epoch now 2" in out
    assert "committed" in cli.one_command("set after-kill yes")
    assert cli.one_command("get after-kill") == repr(b"yes")
    cli.cluster.stop()


def test_cli_scriptable_repl():
    cli = Cli(seed=52)
    out = io.StringIO()
    cli.repl(stdin=io.StringIO("set a 1; get a\nexit\n"), stdout=out)
    text = out.getvalue()
    assert "committed" in text and repr(b"1") in text
    cli.cluster.stop()


def test_vexillographer_doc_in_sync():
    """The generated options/knobs surface must match the committed doc
    (the vexillographer can-never-drift discipline)."""
    import pathlib

    from foundationdb_tpu.tools.vexillographer import generate

    committed = (pathlib.Path(__file__).resolve().parent.parent / "KNOBS.md").read_text()
    assert committed == generate(), (
        "KNOBS.md is stale: run python -m foundationdb_tpu.tools.vexillographer"
    )


def test_cli_move_backup_configure_errorcode():
    from foundationdb_tpu.tools.cli import Cli

    cli = Cli(seed=1701, n_storage_shards=2, storage_replication=2)
    for i in range(30):
        cli.one_command(f"set mk{i:03d} v{i}")
    out = cli.one_command("move mk010 mk020 1")
    assert out == "moved"
    assert cli.one_command("get mk015") == repr(b"v15")

    out = cli.one_command("backup start bk-cli")
    assert out.startswith("backup running")
    assert cli.one_command("backup status").startswith("backed up to v")
    assert cli.one_command("backup stop") == "backup stopped"

    out = cli.one_command("configure n_tlogs=3")
    assert "n_tlogs" in out
    assert cli.one_command("errorcode 1020") == "not_committed"
    cli.cluster.stop()


def test_cli_dr_verbs():
    """fdbdr verbs: start streams to an embedded secondary, status reports
    lag, switch drains and promotes (primary locked after)."""
    from foundationdb_tpu.tools.cli import Cli

    cli = Cli(seed=61)
    assert "committed" in cli.one_command("set drk v1")
    out = cli.one_command("dr start")
    assert "dr streaming" in out
    assert "committed" in cli.one_command("set drk2 v2")
    assert "applied to" in cli.one_command("dr status")
    out = cli.one_command("dr switch")
    assert "switched" in out
    # the secondary serves the exact data
    c2 = cli._dr_secondary
    db2 = c2.database()

    async def check():
        tr = db2.create_transaction()
        return await tr.get(b"drk"), await tr.get(b"drk2")

    v = cli.cluster.run_until(cli.cluster.loop.spawn(check()), 120)
    assert v == (b"v1", b"v2")
    # the deposed primary refuses writes
    from foundationdb_tpu.roles.types import DatabaseLocked
    import pytest

    with pytest.raises(DatabaseLocked):
        cli.one_command("set stale x")
    cli.cluster.stop()
    c2.stop()
