"""The load-metric plane: byte-sampled StorageMetrics accuracy vs exact
accounting, sampled split-point estimation, hot-shard relocation, the
status/metrics schema surface, and the fdbtop renderer
(fdbserver/StorageMetrics.actor.h byteSample/bytesReadSample;
DataDistributionTracker's waitMetrics poll; the community fdbtop)."""

import json
import math
import random

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.control.status import (
    cluster_status,
    validate_metrics_event,
    validate_status,
)
from foundationdb_tpu.roles.storage_metrics import BandwidthSample, ByteSample
from foundationdb_tpu.tools.fdbtop import render


# ---------------------------------------------------------------------------
# sampling accuracy vs exact accounting


def test_byte_sample_unbiased_under_random_sizes():
    """Horvitz–Thompson bound: for unit u and a range holding B exact
    bytes, the estimate's standard deviation is at most sqrt(u * B) —
    randomized key/value sizes must land within a few sigma, and the
    whole-range totals must track across several units."""
    rng = random.Random(20160)
    entries = {}
    for i in range(4000):
        key = b"acc/%06d" % i
        entries[key] = rng.randint(8, 600)  # spans below AND above unit
    for unit in (64, 256, 1024):
        s = ByteSample(unit)
        for k, sz in entries.items():
            s.set(k, sz)
        exact_total = sum(entries.values())
        sd = math.sqrt(unit * exact_total)
        assert abs(s.total - exact_total) < 6 * sd + unit
        # sub-range estimates: error bound scales with the RANGE's bytes
        for lo, hi in ((0, 1000), (1000, 3000), (2500, 4000)):
            b, e = b"acc/%06d" % lo, b"acc/%06d" % hi
            exact = sum(
                sz for k, sz in entries.items() if b <= k < e
            )
            est = s.bytes_range(b, e)
            assert abs(est - exact) < 6 * math.sqrt(unit * exact) + unit


def test_byte_sample_exact_above_unit():
    """Entries at least as large as the unit are sampled with p=1 and
    weight sz: the estimate is EXACT, not merely unbiased."""
    s = ByteSample(128)
    total = 0
    for i in range(300):
        sz = 128 + (i % 400)
        s.set(b"big/%04d" % i, sz)
        total += sz
    assert s.total == total
    assert s.bytes_range(b"big/", b"big0") == total


def test_byte_sample_clear_and_reset_deterministic():
    """The sample decision hashes the KEY: re-set/remove/clear always
    touch the same entry, so mirrored mutations return the sample to
    exactly its prior state (seeded sims replay identically)."""
    s = ByteSample(256)
    rng = random.Random(7)
    sizes = {b"d/%05d" % i: rng.randint(10, 500) for i in range(2000)}
    for k, sz in sizes.items():
        s.set(k, sz)
    before_total, before_len = s.total, len(s)
    # re-set every key to the same size: nothing changes
    for k, sz in sizes.items():
        s.set(k, sz)
    assert (s.total, len(s)) == (before_total, before_len)
    # remove half, re-add: back to the same state
    removed = list(sizes)[::2]
    for k in removed:
        s.remove(k)
    for k in removed:
        s.set(k, sizes[k])
    assert (s.total, len(s)) == (before_total, before_len)
    s.clear_range(b"d/", b"d0")
    assert s.total == 0 and len(s) == 0


def test_bandwidth_sample_tracks_rate_and_decays():
    """Steady traffic at rate R holds the decayed estimate near R; going
    idle for several time constants forgets it."""
    tau = 10.0
    s = BandwidthSample(64, tau)
    rng = random.Random(99)
    t = 0.0
    # 300 B per 0.1s across a few keys = 3000 B/s, for 5*tau seconds
    for _ in range(int(5 * tau / 0.1)):
        t += 0.1
        for _ in range(3):
            s.add(b"bw/%02d" % rng.randint(0, 20), 100, t)
    est = s.rate_range(b"bw/", b"bw0", t)
    assert 0.7 * 3000 < est < 1.3 * 3000
    # the busiest key is one of the sampled hot keys, at a plausible rate
    k, r = s.busiest_key(t)
    assert k is not None and k.startswith(b"bw/") and r > 0
    # idle: five time constants later the estimate is noise
    assert s.rate_range(b"bw/", b"bw0", t + 5 * tau) < 0.01 * 3000


# ---------------------------------------------------------------------------
# split-point estimation


def test_split_point_near_byte_weighted_median():
    s = ByteSample(128)
    for i in range(3000):
        s.set(b"sp/%05d" % i, 100)  # uniform weights
    k = s.split_point(b"sp/", b"sp0")
    assert k is not None
    idx = int(k[3:])
    # sampled median of a uniform keyspace lands near the middle
    assert 1000 < idx < 2000


def test_split_point_follows_byte_weight_not_key_count():
    """One huge prefix dominates the bytes: the byte-weighted median must
    sit inside it even though most KEYS are elsewhere."""
    s = ByteSample(128)
    for i in range(100):
        s.set(b"a/%04d" % i, 5000)  # 500KB in 100 keys
    for i in range(2000):
        s.set(b"z/%04d" % i, 20)  # 40KB in 2000 keys
    k = s.split_point(b"a/", b"z0")
    assert k is not None and k < b"z/"  # median is in the heavy prefix
    assert k > b"a/"  # but never AT the range start


def test_storage_sampled_split_point_matches_exact_median():
    """Against a live storage server: the sampled split point of a real
    shard lands near the exact key median."""
    c = RecoverableCluster(seed=881, n_storage_shards=2,
                           storage_replication=2, durable=False)
    db = c.database()

    async def fill():
        for base in range(0, 600, 50):
            tr = db.create_transaction()
            for i in range(base, base + 50):
                tr.set(b"m/%05d" % i, b"v" * 40)
            await tr.commit()

    c.run_until(c.loop.spawn(fill()), 300)
    ss = c.controller._tag_to_ss[c.controller.storage_teams_tags[0][0]]
    k = ss.sampled_split_point(b"m/", b"m0")
    assert k is not None
    idx = int(k[2:])
    assert 150 < idx < 450  # near the 300 median, sampling tolerance
    c.stop()


# ---------------------------------------------------------------------------
# hot-shard relocation (deterministic: manufactured team imbalance)


def test_hot_shard_relocates_to_least_loaded_team():
    """Two trafficked shards stacked on one team, an idle team elsewhere:
    the hot loop must detect the hottest shard and move it — whole, via
    the two-phase MoveKeys — to the idle team.  (With the hot shard ALONE
    on its team the anti-thrash guard correctly refuses: moving the whole
    load merely shifts the problem.)"""
    c = RecoverableCluster(
        seed=883, n_storage_shards=3, storage_replication=2, durable=False,
        knob_overrides={
            # splits/merges out of the way: relocation is the subject
            "DD_SHARD_SPLIT_BYTES": 1 << 30,
            "DD_SHARD_SPLIT_KEYS": 1 << 30,
            "DD_SHARD_SPLIT_WRITE_BYTES_PER_SEC": 1 << 30,
            "DD_SHARD_MERGE_BYTES": 0,
            "DD_HOT_SHARD_BYTES_PER_KSEC": 100_000,  # 100 B/s combined
            "DD_HOT_RELOCATION_INTERVAL": 0.5,
        },
    )
    db = c.database()
    splits = list(c.controller.storage_splits)  # 3 shards -> 2 boundaries
    team0 = list(c.controller.storage_teams_tags[0])
    # shard-0 keys sort below the first boundary; shard-1 keys inside it
    k_hot = b"A/%04d"
    k_warm = splits[0] + b"/%04d"

    async def fill():
        tr = db.create_transaction()
        for i in range(50):
            tr.set(k_hot % i, b"v" * 64)
            tr.set(k_warm % i, b"v" * 64)
        await tr.commit()

    c.run_until(c.loop.spawn(fill()), 300)

    # manufacture the imbalance: pile shard 1 onto shard 0's team
    moved = c.run_until(
        c.loop.spawn(c.dd.move_range(splits[0], splits[1], team0)), 300
    )
    assert moved

    async def drive_and_wait():
        import random as _r

        from foundationdb_tpu.client.transaction import RETRYABLE_ERRORS

        prng = _r.Random(1)
        deadline = c.loop.now() + 40.0
        while c.loop.now() < deadline:
            tr = db.create_transaction()
            try:
                for _ in range(6):
                    await tr.get(k_hot % prng.randint(0, 49))
                # enough warm traffic that the piled team's total STRICTLY
                # exceeds the hot shard alone — the anti-thrash guard needs
                # a real improvement, not an equality
                for _ in range(3):
                    await tr.get(k_warm % prng.randint(0, 49))
                tr.set(k_hot % prng.randint(0, 49), b"w" * 64)
                tr.set(k_warm % prng.randint(0, 49), b"w" * 64)
                await tr.commit()
            except RETRYABLE_ERRORS as e:
                # e.g. TransactionTooOld: read version below the floor of a
                # range the relocation just moved — retry like a real client
                await tr.on_error(e)
                continue
            if c.dd.hot_relocations >= 1:
                return True
        return False

    assert c.run_until(c.loop.spawn(drive_and_wait()), 600)
    # the hot shard left the overloaded team
    hot_team = set(c.controller.storage_teams_tags[0])
    assert hot_team != set(team0)
    c.stop()


def test_datadistribution_freeze_stops_relocation():
    """fdbcli `datadistribution off` analog: with dd.frozen the hot loop
    must not move anything even under detectable load."""
    c = RecoverableCluster(
        seed=884, n_storage_shards=2, storage_replication=2, durable=False,
        knob_overrides={
            "DD_SHARD_SPLIT_BYTES": 1 << 30,
            "DD_SHARD_SPLIT_KEYS": 1 << 30,
            "DD_SHARD_SPLIT_WRITE_BYTES_PER_SEC": 1 << 30,
            "DD_HOT_SHARD_BYTES_PER_KSEC": 100_000,
            "DD_HOT_RELOCATION_INTERVAL": 0.5,
        },
    )
    c.dd.frozen = True
    db = c.database()

    async def drive():
        for _ in range(60):
            tr = db.create_transaction()
            tr.set(b"fz", b"x" * 200)
            await tr.get(b"fz")
            await tr.commit()
        await c.loop.delay(3.0)

    c.run_until(c.loop.spawn(drive()), 300)
    assert c.dd.hot_relocations == 0 and c.dd.shard_splits == 0
    c.stop()


# ---------------------------------------------------------------------------
# schema surface: status cluster.data, StorageMetrics gauges, special keys


def test_status_data_block_and_metrics_range():
    c = RecoverableCluster(seed=882, n_storage_shards=2,
                           storage_replication=2, durable=False)
    db = c.database()

    async def main():
        for base in range(0, 200, 50):
            tr = db.create_transaction()
            for i in range(base, base + 50):
                tr.set(b"sd/%05d" % i, b"v" * 30)
            await tr.commit()
        # read traffic so the read-bandwidth gauges move
        tr = db.create_transaction()
        for i in range(0, 200, 5):
            await tr.get(b"sd/%05d" % i)
        await tr.commit()
        # one \xff\xff/metrics/ range read through the normal read path
        tr = db.create_transaction()
        rows = await tr.get_range(b"\xff\xff/metrics/", b"\xff\xff/metrics0",
                                  limit=1000)
        return rows

    rows = c.run_until(c.loop.spawn(main()), 300)
    doc = cluster_status(c)
    validate_status(doc)  # schema covers cluster.data + ratekeeper fields
    data = doc["cluster"]["data"]
    assert data["shard_count"] == 2
    assert data["total_kv_bytes_estimate"] > 0
    assert data["hot_shards"] and "bytes_read_per_ksec" in data["hot_shards"][0]
    assert "limiting_shard" in doc["ratekeeper"]

    # special range: one row per shard, JSON values carrying the gauges
    assert len(rows) == 2
    for k, v in rows:
        assert k.startswith(b"\xff\xff/metrics/")
        m = json.loads(v)
        for field in ("bytes", "bytes_read_per_ksec",
                      "bytes_written_per_ksec", "team"):
            assert field in m
    c.stop()


def test_storage_metrics_trace_event_gauges():
    """The per-role StorageMetrics trace event carries the sampled gauges
    and passes the metrics-event schema guard."""
    c = RecoverableCluster(seed=885, n_storage_shards=2,
                           storage_replication=2, durable=False)
    db = c.database()

    async def main():
        for i in range(80):
            tr = db.create_transaction()
            tr.set(b"tm/%04d" % i, b"v" * 50)
            await tr.get(b"tm/%04d" % i)
            await tr.commit()
        await c.loop.delay(c.knobs.METRICS_INTERVAL + 1.0)

    c.run_until(c.loop.spawn(main()), 300)
    evs = [e for e in c.trace.events if e["Type"] == "StorageMetrics"]
    assert evs
    for ev in evs:
        validate_metrics_event(ev)
    # the tm/ keys all land in shard 0: ITS servers' gauges must be live
    # (the other shard's servers legitimately report zero)
    assert max(ev["SampledBytes"] for ev in evs) > 0
    assert max(ev["SampledKeys"] for ev in evs) > 0
    c.stop()


# ---------------------------------------------------------------------------
# fdbtop renderer (pure text unit; `cli top --once` is the live flavor)


def test_fdbtop_render_frame():
    doc = {
        "cluster": {
            "generation": {"epoch": 3, "state": "accepting", "count": 1},
            "clock": 12.5,
            "data": {
                "total_kv_bytes_estimate": 1 << 20,
                "moving_bytes_estimate": 2048,
                "moving_ranges": 1,
                "shard_count": 3,
                "hot_shards": [],
            },
            "data_distribution": {"hot_relocations": 2, "frozen": True},
            "messages": [{
                "severity": 30, "name": "e_brake",
                "description": "queue hard limit",
            }],
        },
        "ratekeeper": {
            "tps_budget": 500.0, "limit_reason": "storage_queue",
            "limiting_server": "ss-0-r1", "limiting_shard": "b'hot/key'",
            "limiting_shard_bps": 4096.0, "e_brake": True,
        },
        "proxy": {"committed_version": 900, "txns_committed": 100,
                  "txns_conflicted": 5},
        "tlogs": [{"version": 900, "bytes_queued": 4096, "locked": False}],
        "storage": [{"tag": "ss-0-r0", "version": 900,
                     "durable_version": 880, "queue_bytes": 1024,
                     "keys": 1000}],
    }
    shards = [
        {"begin": "b''", "bytes": 9000, "bytes_read_per_ksec": 2e6,
         "bytes_written_per_ksec": 1e6, "team": ["ss-0-r0", "ss-0-r1"]},
        {"begin": "b'\\x80'", "bytes": 100, "bytes_read_per_ksec": 0.0,
         "bytes_written_per_ksec": 0.0, "team": ["ss-1-r0"]},
    ]
    prev = {"proxy": {"txns_committed": 80, "txns_conflicted": 5}}
    frame = render(doc, shards, prev, dt=2.0)
    assert "epoch 3" in frame
    assert "500 tps budget" in frame
    assert "storage_queue" in frame and "ss-0-r1" in frame
    assert "hot range b'hot/key'" in frame
    assert "[E-BRAKE]" in frame
    assert "DD FROZEN" in frame and "2 hot relocation(s)" in frame
    assert "10 commit/s" in frame  # (100-80)/2.0
    assert "shards (hottest first, sampled)" in frame
    # hottest shard sorts first
    assert frame.index("b''") < frame.index("b'\\x80'")
    assert "message [30] e_brake" in frame


def test_fdbtop_render_empty_doc():
    """A frame from an empty doc (connection just established) renders
    without crashing — the monitor must survive a mid-recovery scrape."""
    frame = render({}, [], None, 0.0)
    assert "fdbtpu top" in frame
