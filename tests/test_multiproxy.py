"""Multi-proxy commit plane semantics: GRV causality across proxies, the
MVCC-window commit throttle, and deposed-proxy GRV refusal.

Reference behaviours under test:
  * getLiveCommittedVersion (MasterProxyServer.actor.cpp:1002): a GRV is the
    max committed version over ALL proxies, confirmed live with the TLogs —
    so a client's write acknowledged by proxy A is visible to a read version
    served by proxy B, and a deposed proxy (locked TLogs) never answers.
  * the versions-in-flight commit throttle (:850-870): a batch whose
    version runs more than MAX_VERSIONS_IN_FLIGHT ahead of the newest
    fully-committed version parks until the gap closes.
"""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.roles.types import GetReadVersionRequest, TLogLockRequest
from foundationdb_tpu.rpc.stream import RequestStreamRef
from foundationdb_tpu.runtime.combinators import wait_all
from foundationdb_tpu.runtime.core import BrokenPromise, TimedOut
from foundationdb_tpu.runtime.knobs import CoreKnobs


def test_grv_causal_across_proxies():
    """A commit acknowledged by one proxy is covered by the read version any
    OTHER proxy serves afterwards (peer-max + confirm-epoch-live)."""
    c = RecoverableCluster(seed=81, n_proxies=2)
    db = c.database()
    assert len(db.view.grvs) == 2

    async def main():
        vmax = 0
        for i in range(5):
            tr = db.create_transaction()
            tr.set(b"k%d" % i, b"v")
            vmax = max(vmax, await tr.commit())
            # EVERY proxy must now serve a read version >= the ack'd commit
            for ref in db.view.grvs:
                rep = await ref.get_reply(GetReadVersionRequest(), timeout=5.0)
                assert rep.version >= vmax, (
                    f"proxy served stale GRV {rep.version} < committed {vmax}"
                )
        return True

    assert c.run_until(c.loop.spawn(main()), 120)
    c.stop()


def test_both_proxies_carry_commits():
    """Clients spread commits across the proxy list; both proxies commit."""
    c = RecoverableCluster(seed=82, n_proxies=2)
    db = c.database()

    async def main():
        for i in range(40):
            tr = db.create_transaction()
            tr.set(b"lk%02d" % i, b"v")
            await tr.commit()

    c.run_until(c.loop.spawn(main()), 120)
    committed = [p.c_committed.value for p in c.controller.generation.proxies]
    assert all(n > 0 for n in committed), f"one proxy idle: {committed}"
    assert sum(committed) >= 40
    c.stop()


def test_mvcc_window_throttle_engages_and_releases():
    """Clog every proxy<->TLog link so commits cannot become durable while
    the version clock runs past a shrunken versions-in-flight bound: the
    phase-4 throttle must engage (counter observable), and after the clog
    heals every parked commit must land."""
    knobs = CoreKnobs()
    knobs.MAX_VERSIONS_IN_FLIGHT = 50_000    # 50ms of version clock
    c = RecoverableCluster(seed=83, n_proxies=2, knobs=knobs)
    db = c.database()
    gen = c.controller.generation

    async def main():
        tr = db.create_transaction()
        tr.set(b"pre", b"x")
        await tr.commit()

        # sever durability: clog both directions of every proxy<->TLog pair
        for p in gen.proxies:
            pa = p.commit_stream._process.address
            for t in gen.tlogs:
                ta = t.commit_stream._process.address
                c.net.clog_pair(pa, ta, 0.5)

        async def one(i):
            async def fn(tr):
                tr.set(b"thr%02d" % i, b"y")

            await db.run(fn)

        tasks = [c.loop.spawn(one(i)) for i in range(6)]
        await wait_all(tasks)
        # all landed post-heal
        tr = db.create_transaction()
        rows = await tr.get_range(b"thr", b"ths")
        return len(rows)

    n = c.run_until(c.loop.spawn(main()), 300)
    assert n == 6
    throttles = sum(p.c_throttled.value for p in c.controller.generation.proxies)
    assert throttles >= 1, "MVCC throttle never engaged during the stall"
    c.stop()


def test_deposed_proxy_never_serves_grv():
    """Once a generation's TLogs are locked (what recovery does first), its
    proxies must never answer another GRV — the reply could be stale.  The
    client sees a timeout (parked) or a broken promise (proxy killed by the
    recovery the lock precipitates), NEVER a version."""
    c = RecoverableCluster(seed=84, n_proxies=2)
    db = c.database()
    gen = c.controller.generation
    old_refs = list(db.view.grvs)

    async def main():
        tr = db.create_transaction()
        tr.set(b"a", b"1")
        await tr.commit()

        # lock the generation's TLogs, exactly as a competing recovery would
        proc = c.net.create_process("usurper")
        for t in gen.tlogs:
            ref = RequestStreamRef(c.net, proc, t.lock_stream.endpoint)
            await ref.get_reply(TLogLockRequest(), timeout=5.0)

        outcomes = []
        for ref in old_refs:
            try:
                rep = await ref.get_reply(GetReadVersionRequest(), timeout=2.0)
                outcomes.append(("REPLIED", rep.version))
            except (TimedOut, BrokenPromise) as e:
                outcomes.append((type(e).__name__, None))
        return outcomes

    outcomes = c.run_until(c.loop.spawn(main()), 120)
    assert all(kind != "REPLIED" for kind, _ in outcomes), (
        f"deposed proxy answered a GRV: {outcomes}"
    )
    c.stop()
