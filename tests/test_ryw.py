"""Read-your-writes layer semantics."""

from foundationdb_tpu.client.ryw import ReadYourWritesTransaction
from foundationdb_tpu.cluster import SimCluster
from foundationdb_tpu.roles.types import MutationType


def run(c, coro):
    return c.run_until(c.loop.spawn(coro), 60.0)


def test_ryw_sees_own_writes():
    c = SimCluster(seed=11)
    db = c.database()

    async def main():
        tr = ReadYourWritesTransaction(db)
        tr.set(b"a", b"1")
        assert await tr.get(b"a") == b"1"      # before commit
        tr.clear(b"a")
        assert await tr.get(b"a") is None
        tr.set(b"a", b"2")
        await tr.commit()
        tr2 = ReadYourWritesTransaction(db)
        return await tr2.get(b"a")

    assert run(c, main()) == b"2"
    c.stop()


def test_ryw_range_merge():
    c = SimCluster(seed=12)
    db = c.database()

    async def main():
        tr = ReadYourWritesTransaction(db)
        for i in range(5):
            tr.set(b"k%d" % i, b"old")
        await tr.commit()

        tr = ReadYourWritesTransaction(db)
        tr.set(b"k2", b"new")          # overwrite
        tr.clear(b"k3")                # delete
        tr.set(b"k9", b"added")        # insert
        rows = await tr.get_range(b"k", b"l")
        return rows

    rows = run(c, main())
    assert rows == [
        (b"k0", b"old"),
        (b"k1", b"old"),
        (b"k2", b"new"),
        (b"k4", b"old"),
        (b"k9", b"added"),
    ]
    c.stop()


def test_ryw_atomic_fold():
    c = SimCluster(seed=13)
    db = c.database()

    async def main():
        tr = ReadYourWritesTransaction(db)
        tr.set(b"n", (10).to_bytes(4, "little"))
        tr.atomic_op(MutationType.ADD, b"n", (5).to_bytes(4, "little"))
        local = await tr.get(b"n")      # folded locally
        await tr.commit()
        tr2 = ReadYourWritesTransaction(db)
        stored = await tr2.get(b"n")
        return local, stored

    local, stored = run(c, main())
    assert int.from_bytes(local, "little") == 15
    assert int.from_bytes(stored, "little") == 15
    c.stop()


def test_limited_range_read_refills_past_buffered_clears():
    """A limited get_range whose snapshot window is mostly cleared by THIS
    transaction must keep fetching until the limit is genuinely met — not
    return a falsely-short result (RYWIterator lockstep semantics)."""
    from foundationdb_tpu.cluster import SimCluster

    c = SimCluster(seed=55)
    db = c.database()

    async def main():
        tr0 = db.create_transaction()
        for i in range(20):
            tr0.set(b"k%02d" % i, b"v")
        await tr0.commit()

        tr = ReadYourWritesTransaction(db)
        tr.clear_range(b"k00", b"k15")
        rows = await tr.get_range(b"k00", b"k99", limit=10)
        assert [k for k, _ in rows] == [b"k%02d" % i for i in range(15, 20)], rows
        # buffered sets beyond the first snapshot window appear exactly once
        tr.set(b"k25", b"new")
        rows2 = await tr.get_range(b"k00", b"k99", limit=10)
        assert [k for k, _ in rows2] == [
            b"k15", b"k16", b"k17", b"k18", b"k19", b"k25"
        ], rows2
        return True

    assert c.run_until(c.loop.spawn(main()), 60)
    c.stop()
