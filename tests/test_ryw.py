"""Read-your-writes layer semantics."""

from foundationdb_tpu.client.ryw import ReadYourWritesTransaction
from foundationdb_tpu.cluster import SimCluster
from foundationdb_tpu.roles.types import MutationType


def run(c, coro):
    return c.run_until(c.loop.spawn(coro), 60.0)


def test_ryw_sees_own_writes():
    c = SimCluster(seed=11)
    db = c.database()

    async def main():
        tr = ReadYourWritesTransaction(db)
        tr.set(b"a", b"1")
        assert await tr.get(b"a") == b"1"      # before commit
        tr.clear(b"a")
        assert await tr.get(b"a") is None
        tr.set(b"a", b"2")
        await tr.commit()
        tr2 = ReadYourWritesTransaction(db)
        return await tr2.get(b"a")

    assert run(c, main()) == b"2"
    c.stop()


def test_ryw_range_merge():
    c = SimCluster(seed=12)
    db = c.database()

    async def main():
        tr = ReadYourWritesTransaction(db)
        for i in range(5):
            tr.set(b"k%d" % i, b"old")
        await tr.commit()

        tr = ReadYourWritesTransaction(db)
        tr.set(b"k2", b"new")          # overwrite
        tr.clear(b"k3")                # delete
        tr.set(b"k9", b"added")        # insert
        rows = await tr.get_range(b"k", b"l")
        return rows

    rows = run(c, main())
    assert rows == [
        (b"k0", b"old"),
        (b"k1", b"old"),
        (b"k2", b"new"),
        (b"k4", b"old"),
        (b"k9", b"added"),
    ]
    c.stop()


def test_ryw_atomic_fold():
    c = SimCluster(seed=13)
    db = c.database()

    async def main():
        tr = ReadYourWritesTransaction(db)
        tr.set(b"n", (10).to_bytes(4, "little"))
        tr.atomic_op(MutationType.ADD, b"n", (5).to_bytes(4, "little"))
        local = await tr.get(b"n")      # folded locally
        await tr.commit()
        tr2 = ReadYourWritesTransaction(db)
        stored = await tr2.get(b"n")
        return local, stored

    local, stored = run(c, main())
    assert int.from_bytes(local, "little") == 15
    assert int.from_bytes(stored, "little") == 15
    c.stop()
