SEV_WARN = 20

WARN_EVENT_TYPES = frozenset({
    "FixtureRegistered",
})


def emit(trace):
    trace.trace("FixtureRegistered", severity=SEV_WARN)
    trace.trace("FixtureInfoOnly")
