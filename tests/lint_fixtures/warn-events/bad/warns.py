SEV_WARN = 20

WARN_EVENT_TYPES = frozenset({
    "FixtureRegistered",
    "FixtureStale",  # no call site anywhere
})


def emit(trace):
    trace.trace("FixtureRogue", severity=SEV_WARN)
    trace.trace("FixtureRegistered", severity=SEV_WARN)
    trace.trace("FixtureRegistered", severity=SEV_WARN)  # second site
