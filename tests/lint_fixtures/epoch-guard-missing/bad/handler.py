class TLogLike:
    def __init__(self, loop, stream):
        self.loop = loop
        self.stream = stream
        self.locked = False

    def lock(self):
        self.locked = True  # recovery ends this epoch

    async def serve_one(self):
        req = await self.stream.next()
        if self.locked:
            return
        await self.loop.delay(0.001)   # e.g. the durability sync
        req.reply("ok")                # lock not re-validated: a commit
        #                                acked into a dead epoch
