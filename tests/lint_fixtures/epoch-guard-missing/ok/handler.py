class TLogLike:
    def __init__(self, loop, stream):
        self.loop = loop
        self.stream = stream
        self.locked = False

    def lock(self):
        self.locked = True

    async def serve_one(self):
        req = await self.stream.next()
        if self.locked:
            return
        await self.loop.delay(0.001)
        if self.locked:                # re-validated after resumption
            return
        req.reply("ok")

    async def serve_inline(self):
        req = await self.stream.next()
        req.reply(self.locked)         # read in the reply statement: fresh
