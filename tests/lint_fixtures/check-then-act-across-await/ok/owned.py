class Election:
    def __init__(self, loop):
        self.loop = loop
        self.leader = None

    def set_leader(self, who):
        self.leader = who

    async def elect_owned(self, me):
        if self.leader is None:
            self.leader = me           # ownership taken BEFORE suspending
            await self.loop.delay(0.1)
            self.leader = me           # release-style write: owned

    async def elect_recheck(self, me):
        if self.leader is None:
            await self.loop.delay(0.1)
            if self.leader is None:    # re-checked after resumption
                self.leader = me
