class Election:
    def __init__(self, loop):
        self.loop = loop
        self.leader = None

    def set_leader(self, who):
        self.leader = who  # another actor can win while we sleep

    async def elect(self, me):
        if self.leader is None:        # check
            await self.loop.delay(0.1)  # scheduler runs other actors
            self.leader = me           # act: tested state, unrechecked
