class Controller:
    def __init__(self, loop):
        self.loop = loop
        self.generation = None

    def swap(self, gen):
        self.generation = gen  # rebound outside __init__: shared mutable

    async def act(self):
        gen = self.generation          # cached before the suspension
        await self.loop.delay(0.1)
        return gen.proxies             # stale use: no re-read, no guard
