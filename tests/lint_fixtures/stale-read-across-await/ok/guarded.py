class Controller:
    def __init__(self, loop):
        self.loop = loop
        self.generation = None

    def swap(self, gen):
        self.generation = gen

    async def reread(self):
        gen = self.generation
        await self.loop.delay(0.1)
        gen = self.generation          # re-read after the await: fresh
        return gen

    async def token_compare(self):
        gen = self.generation
        await self.loop.delay(0.1)
        if gen is not self.generation:  # identity guard: managed cache
            return None
        return gen

    async def quick(self):
        return 1                       # no awaits: runs synchronously

    async def nonsuspending(self):
        gen = self.generation
        await self.quick()             # not a real scheduling point
        return gen
