from foundationdb_tpu.runtime.trace import spawn_role_metrics

# annotated assignment on purpose: the real registry (control/status.py)
# is an AnnAssign, which the anchor scan once silently missed
ROLE_METRICS_SCHEMA: dict = {
    "FixGoodMetrics": {},
}


def start(loop, proc, trace, fields):
    spawn_role_metrics(loop, proc, trace, "FixGoodMetrics", fields, 1.0)
