async def poll(db, loop):
    while True:
        try:
            await db.run()
        except Exception:
            pass  # eats ActorCancelled: the actor keeps polling
        await loop.delay(1.0)


async def fake_shield(db, loop):
    while True:
        try:
            await db.run()
        except ActorCancelled:
            pass  # swallows the cancel itself: the actor keeps polling
        except Exception:
            pass  # shielded from the rule, but the handler above fires
        await loop.delay(1.0)
