from foundationdb_tpu.runtime.core import ActorCancelled


async def poll(db, loop):
    while True:
        try:
            await db.run()
        except ActorCancelled:
            raise
        except Exception:
            pass  # shielded by the dedicated handler above
        await loop.delay(1.0)


async def recording(db, fut):
    try:
        await db.run()
    except ActorCancelled as e:
        fut.set_error(e)
        return  # ends the coroutine: visible handling, not a zombie


async def reraising(db):
    try:
        await db.run()
    except Exception:
        raise  # transforming but re-raising is visible handling


def sync_helper(items):
    try:
        items.validate()
    except Exception:
        return None  # no await in the try: cancel cannot land here
