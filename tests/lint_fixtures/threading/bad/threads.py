import threading

LOCK = threading.Lock()
