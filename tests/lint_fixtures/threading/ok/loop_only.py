async def work(loop):
    await loop.delay(0.1)
