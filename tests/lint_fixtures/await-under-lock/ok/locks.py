class Pipeline:
    def __init__(self, loop, make_mutex):
        self.loop = loop
        self._lock = make_mutex()
        self.n = 0

    async def flush(self):
        with self._lock:
            self.n += 1                # synchronous critical section only
        await self.loop.delay(0.1)     # suspension OUTSIDE the lock


class Store:
    def __init__(self, mutex):
        self.mutex = mutex
        self.rows = {}

    async def _size_unlocked(self):
        return len(self.rows)

    async def write(self, k, v):
        async with self.mutex:
            self.rows[k] = v
            await self._size_unlocked()  # callee takes no lock

    async def wipe_atomic(self):
        self.rows = {}                 # never suspends: atomic on the
        #                                single-threaded loop — no lock
        #                                needed, exactly per the hint
