class Pipeline:
    def __init__(self, loop, make_mutex):
        self.loop = loop
        self._lock = make_mutex()

    async def flush(self):
        with self._lock:               # a THREAD lock
            await self.loop.delay(0.1)  # run loop parks holding it


class Store:
    def __init__(self, mutex):
        self.mutex = mutex

    async def _compact(self):
        async with self.mutex:
            return 1

    async def write(self, k):
        async with self.mutex:
            await self._compact()      # re-acquires self.mutex: deadlock


class Table:
    def __init__(self, loop, mutex):
        self.loop = loop
        self.mutex = mutex
        self.rows = {}

    async def insert(self, k, v):
        async with self.mutex:
            self.rows[k] = v           # the lock protocol for rows

    async def wipe(self):
        await self.loop.delay(0.01)    # this method CAN interleave ...
        self.rows = {}                 # ... and writes without the lock
