import random


def draw(seed):
    return random.Random(seed).random()


def census(items):
    return [x for x in sorted(set(items))]


def member(items, x):
    return x in set(items)  # membership is order-free
