import os
import random


def draw():
    return random.random() + random.randrange(5)


def salt():
    return os.urandom(8)


def census(items):
    out = []
    for x in set(items):  # hash-ordered iteration
        out.append(x)
    return out + [y for y in {1, 2, 3}]
