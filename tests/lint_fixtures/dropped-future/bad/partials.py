from functools import partial


class Worker:
    async def flush_all(self):
        return 1

    def kick_alias(self):
        f = self.flush_all             # method alias to an async def
        f()                            # coroutine built, dropped

    def kick_partial(self):
        f = partial(self.flush_all)
        f()                            # partial-wrapped coroutine dropped

    def kick_lambda(self):
        f = lambda: self.flush_all()   # noqa: E731 — the fixture shape
        f()                            # lambda-wrapped coroutine dropped

    def kick_inline(self):
        partial(self.flush_all)()      # called and dropped in one statement

    def kick_spawn(self, loop):
        loop.spawn(partial(self.flush_all))  # factory, not a coroutine:
        #                                      spawn builds nothing
