class Worker:
    async def flush_all(self):
        return 1

    def kick(self):
        self.flush_all()  # coroutine constructed, never awaited


async def helper():
    return 2


def run():
    helper()  # dropped local async def
