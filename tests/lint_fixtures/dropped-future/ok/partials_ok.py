from functools import partial


class Worker:
    async def flush_all(self):
        return 1

    def kick_bg(self, loop):
        f = partial(self.flush_all)
        return loop.spawn(f())         # invoked: a coroutine reaches spawn

    async def kick_alias(self):
        f = self.flush_all
        await f()                      # awaited through the alias

    def factory(self):
        f = partial(self.flush_all)
        return f                       # stored/returned, not dropped
