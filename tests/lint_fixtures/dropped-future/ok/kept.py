class Worker:
    async def flush_all(self):
        return 1

    async def kick(self):
        await self.flush_all()

    def kick_bg(self, loop):
        return loop.spawn(self.flush_all())


async def helper():
    return 2


def run(loop):
    loop.spawn(helper())
    t = helper()
    return t
