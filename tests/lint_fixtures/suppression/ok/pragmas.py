import time

Z = time.time()  # flowlint: ok wall-clock (fixture: a reasoned, known-rule pragma)
