X = 1  # flowlint: ok wall-clock
# flowlint: ok no-such-rule (naming a rule that does not exist)
Y = 2
