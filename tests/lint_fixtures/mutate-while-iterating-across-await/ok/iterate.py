class Region:
    def __init__(self, loop):
        self.loop = loop
        self.replicas = []
        self.index = {}

    def rebuild(self, i, ss):
        self.replicas[i] = ss

    def track(self, k, v):
        self.index[k] = v

    async def converge(self, vm):
        # snapshot the tags, then re-resolve from the LIVE set every poll
        for tag in [ss.tag for ss in self.replicas]:
            while True:
                ss = next(
                    (s for s in self.replicas if s.tag == tag), None
                )
                if ss is None or ss.version >= vm:
                    break
                await self.loop.delay(0.05)

    async def broadcast(self):
        for k in list(self.index):         # snapshot iteration
            await self.loop.delay(0.01)

    async def sync_only(self):
        for ss in self.replicas:
            ss.poke()                      # no suspension in the body
