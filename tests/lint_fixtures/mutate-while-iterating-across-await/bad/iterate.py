class Region:
    def __init__(self, loop):
        self.loop = loop
        self.replicas = []
        self.index = {}

    def rebuild(self, i, ss):
        self.replicas[i] = ss  # rebuilt in place while others iterate

    def track(self, k, v):
        self.index[k] = v

    async def converge(self, vm):
        for ss in self.replicas:           # live iteration ...
            while ss.version < vm:
                await self.loop.delay(0.05)  # ... across scheduling points

    async def broadcast(self):
        for k, v in self.index.items():    # live dict view across awaits
            await self.loop.delay(0.01)
