import os


def knobs():
    return (
        os.environ.get("FDBTPU_GOOD"),
        os.environ.get("FDBTPU_ROGUE"),  # unregistered
    )
