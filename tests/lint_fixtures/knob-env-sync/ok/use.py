import os


def knobs():
    return os.environ.get("FDBTPU_GOOD")
