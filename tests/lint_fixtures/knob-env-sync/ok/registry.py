# annotated assignment on purpose: the real registry (runtime/knobs.py)
# is an AnnAssign, which the anchor scan once silently missed
ENV_KNOBS: dict[str, str] = {
    "FDBTPU_GOOD": "a registered and used knob",
}
