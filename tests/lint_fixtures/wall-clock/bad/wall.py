import time
from datetime import datetime


def stamp():
    return time.time()


def pace():
    time.sleep(0.1)
    return time.monotonic()


def day():
    return datetime.now()
