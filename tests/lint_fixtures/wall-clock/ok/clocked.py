import time


def stamp(loop):
    return loop.now()


def phase_wall():
    return time.perf_counter()  # observability timers are host wall by design


def probe_budget():
    time.sleep(0.01)  # flowlint: ok wall-clock (fixture: reasoned suppression silences the rule)
