from foundationdb_tpu.runtime.coverage import testcov
from foundationdb_tpu.runtime.buggify import buggify


def a():
    testcov("fixture.site_a")


def b():
    if buggify("fixture.site_b"):
        testcov("fixture.site_b_armed")
