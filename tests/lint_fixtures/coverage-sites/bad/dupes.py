from foundationdb_tpu.runtime.coverage import testcov
from foundationdb_tpu.runtime.buggify import buggify


def a():
    testcov("fixture.dup_site")


def b():
    testcov("fixture.dup_site")  # duplicate merges two census rows


def c():
    testcov("buggify.shadowed")  # shadows the buggify mirror namespace
