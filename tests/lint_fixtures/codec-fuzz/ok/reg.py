from foundationdb_tpu.runtime import serialize as _wire


class FooMsg:
    pass


reg = _wire.register_codec
reg(200, FooMsg, None, None)
