from .reg import FooMsg

BUILDERS = {
    FooMsg: lambda r: FooMsg(),
}
