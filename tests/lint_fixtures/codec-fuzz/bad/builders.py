class BarMsg:
    pass


BUILDERS = {
    BarMsg: lambda r: BarMsg(),  # stale: BarMsg is registered nowhere
}
