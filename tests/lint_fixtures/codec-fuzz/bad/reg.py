from foundationdb_tpu.runtime import serialize as _wire


class FooMsg:
    pass


_wire.register_codec(200, FooMsg, None, None)
