"""Resource-exhaustion fault plane: sim-disk fault models, role
degradation (TLog hard limit / disk-error refusal, storage durability
retry), ratekeeper's free-space + queue-byte inputs and e-brake, the
io_timeout fail-fast, and the negative durability pairs proving the
handling is load-bearing (a build with the handling stubbed out must
demonstrably fail the same invariant)."""

import re

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime import buggify, coverage
from foundationdb_tpu.runtime.core import (
    DeterministicRandom,
    EventLoop,
    TaskPriority,
    TimedOut,
)
from foundationdb_tpu.runtime.knobs import CoreKnobs
from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.storage.files import DiskFull, SimFilesystem


# ---------------------------------------------------------------------------
# fault-plane units (storage/files.py)


def _fs(loop=None):
    return SimFilesystem(loop or EventLoop(), DeterministicRandom(7))


def test_capacity_enospc_refuses_append_atomically():
    fs = _fs()
    f = fs.open("d0", None)
    f.append(b"x" * 100)
    fs.set_capacity("d0", 150)
    with pytest.raises(DiskFull):
        f.append(b"y" * 100)
    # the refused append buffered NOTHING (no partial state)
    assert f.size() == 100
    assert fs.usage_for("d0") == (100, 150)
    assert fs.disk_usage()["d0"]["enospc_errors"] == 1
    assert coverage.hits("disk.enospc_hit") == 1
    # the operator adds space: the same append now lands
    fs.set_capacity("d0", None)
    f.append(b"y" * 100)
    assert f.size() == 200


def test_injected_error_budget_and_gauges():
    fs = _fs()
    f = fs.open("d0", None)
    fs.inject_errors("d0", 2)
    for _ in range(2):
        with pytest.raises(IOError):
            f.append(b"x")
    f.append(b"x")  # budget drained: back to healthy
    g = fs.disk_usage()["d0"]
    assert g["errors_injected"] == 2 and g["bytes_used"] == 1


def test_degraded_mode_multiplies_sync_latency():
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(7),
                       min_sync_latency=0.01, max_sync_latency=0.01)
    f = fs.open("d0", None)

    async def timed_sync():
        t0 = loop.now()
        f.append(b"x")
        await f.sync()
        return loop.now() - t0

    base = loop.run_until(loop.spawn(timed_sync()), 10)
    fs.degrade("d0", 20.0)
    slow = loop.run_until(loop.spawn(timed_sync()), 10)
    assert slow > 10 * base
    fs.degrade("d0", 1.0)
    again = loop.run_until(loop.spawn(timed_sync()), 10)
    assert again < 2 * base


def test_stall_holds_syncs_until_window_closes():
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(7))
    f = fs.open("d0", None)
    fs.stall("d0", 3.0)

    async def timed_sync():
        t0 = loop.now()
        f.append(b"x")
        await f.sync()
        return loop.now() - t0

    dt = loop.run_until(loop.spawn(timed_sync()), 30)
    assert dt >= 3.0
    assert fs.disk_usage()["d0"]["stalls"] == 1


def test_corrupt_read_is_detected_and_retried_by_diskqueue():
    from foundationdb_tpu.rpc.network import SimNetwork

    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(7))
    buggify.enable(DeterministicRandom(3))
    net = SimNetwork(loop, DeterministicRandom(1), None)
    # buggify disk faults arm only for process-OWNED handles (the blob
    # store's process-less disks keep their own blob.* fault vocabulary)
    dq = DiskQueue(fs.open("d0", net.create_process("reader")))
    off = dq.push(b"payload-one")
    # force the flip on the NEXT pread: read_at's checksum catches it and
    # the re-read returns clean data — detected, healed, counted
    buggify.force("disk.corrupt_read", 1)
    assert dq.read_at(off) == b"payload-one"
    assert coverage.hits("disk.corrupt_read_retried") >= 1
    assert fs.disk_usage()["d0"]["corrupt_reads"] == 1


def test_io_timeout_fail_fasts_the_owning_process():
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(7))
    fs.io_timeout_s = 1.0
    from foundationdb_tpu.rpc.network import SimNetwork

    net = SimNetwork(loop, DeterministicRandom(1), None)
    proc = net.create_process("victim")
    f = fs.open("d0", proc)
    f.append(b"x" * 10)
    fs.stall("d0", 30.0)

    async def sync():
        await f.sync()

    with pytest.raises(IOError):
        loop.run_until(loop.spawn(sync()), 120)
    assert not proc.alive  # killed, not wedged
    assert coverage.hits("disk.io_timeout_kill") == 1
    # the kill dropped the un-synced buffer, like any power kill
    assert f.read_durable() == b""


# ---------------------------------------------------------------------------
# roles under disk pressure


def _run(c, coro, deadline):
    return c.run_until(c.loop.spawn(coro), deadline)


def _write_n(db, prefix: bytes, n: int, size: int = 120):
    async def go():
        for i in range(n):
            async def body(tr, i=i):
                tr.set(prefix + b"%04d" % i, bytes(size))

            await db.run(body)

    return go()


def test_tlog_hard_limit_refuses_loudly_never_silently_acks():
    """Tier-1 pin for the acceptance criterion: past TLOG_HARD_LIMIT_BYTES
    the TLog refuses with a traced SEV_WARN and NO ack — and an operator
    raising the limit un-wedges admission with zero acked-data loss."""
    k = CoreKnobs()
    k.TLOG_HARD_LIMIT_BYTES = 2500
    c = RecoverableCluster(seed=21, n_storage_shards=1,
                           storage_replication=2, knobs=k)
    try:
        db = c.database()
        acked: list[bytes] = []

        async def fill():
            # commit until the refusal bites (bounded); every COMPLETED
            # db.run is an acked commit
            for i in range(40):
                key = b"hl/%04d" % i

                async def body(tr, key=key):
                    tr.set(key, bytes(200))

                try:
                    await db.run(body)
                    acked.append(key)
                except Exception:
                    return

        try:
            _run(c, fill(), 30)
        except TimedOut:
            pass  # wedged-on-refusal is the expected shape
        tlogs = c.controller.generation.tlogs
        refused = sum(t.commits_refused for t in tlogs)
        assert refused > 0, "hard limit never engaged"
        assert coverage.hits("tlog.hard_limit_refused") > 0
        assert any(
            key.startswith("tlog-hard-limit-") for key in c.trace.latest
        ), "refusal must be loud (SEV_WARN TLogCommitRefused, track_latest)"
        # operator action: raise the limit — admission resumes, and every
        # previously ACKED key is still readable (no refusal ever lost
        # acknowledged data)
        for t in c.controller.generation.tlogs:
            t.hard_limit_bytes = 1 << 30
        k.TLOG_HARD_LIMIT_BYTES = 1 << 30

        async def verify():
            async def body(tr):
                tr.set(b"hl/after", b"1")

            await db.run(body)
            for key in acked:
                async def rd(tr, key=key):
                    assert await tr.get(key) is not None, key

                await db.run(rd)

        _run(c, verify(), 120)
    finally:
        c.stop()


def test_storage_durability_retries_through_enospc_and_drains():
    """A full storage disk never crashes the durability loop: flushes are
    refused atomically (WAL-push-first), the queue ledger grows, and
    lifting the capacity lets durability catch up with nothing lost."""
    c = RecoverableCluster(seed=23, n_storage_shards=1,
                           storage_replication=2)
    try:
        db = c.database()
        ss = c.storage[0]
        path = ss.store._dq.file.path
        used0, _ = c.fs.usage_for(path)
        c.fs.set_capacity(path, used0 + 400)  # a flush can't fit
        _run(c, _write_n(db, b"en/", 30), 60)

        async def wait_errors():
            while coverage.hits("storage.durability_io_error") < 2:
                await c.loop.delay(0.25)
            frozen = ss.durable_version
            # once the disk refuses, nothing further may be claimed
            # durable — the durable version FREEZES while the fault holds
            await c.loop.delay(2.0)
            assert ss.durable_version == frozen, (
                "durable version advanced past a refusing disk"
            )
            return frozen

        frozen = _run(c, wait_errors(), 120)
        assert ss.queue_bytes > 0
        c.fs.set_capacity(path, None)

        async def wait_drain():
            while ss.durable_version <= frozen:
                await c.loop.delay(0.25)
            # and the data really is in the recovered-visible store
            async def rd(tr):
                assert await tr.get(b"en/0000") is not None

            await db.run(rd)

        _run(c, wait_drain(), 300)
        assert coverage.hits("storage.durability_io_error") >= 2
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# ratekeeper inputs + e-brake (tier-1 pins for the acceptance criterion)


def test_e_brake_slams_on_tlog_queue_past_hard_limit():
    """Unit pin: a raw TLog queue gauge at the hard limit slams the
    budget to the floor immediately (no smoothing lag), and releases the
    moment the gauge drops."""
    from foundationdb_tpu.control.ratekeeper import Ratekeeper

    class _Ep:
        token = "tok-1"

    class _Stream:
        endpoint = _Ep()

    class _StubTLog:
        commit_stream = _Stream()
        bytes_queued = 0

    k = CoreKnobs()
    k.TLOG_HARD_LIMIT_BYTES = 1000
    loop = EventLoop()
    t = _StubTLog()
    rk = Ratekeeper(loop, k, storage=[], tlogs_fn=lambda: [t])
    rk._update()
    assert rk.limit_reason == "unlimited" and not rk.e_brake
    t.bytes_queued = 1000
    rk._update()
    assert rk.limit_reason == "e_brake" and rk.e_brake
    assert rk.limiting_server == "tlog0"
    assert rk.tps_budget == rk.max_tps * 0.001
    assert rk.batch_tps_budget == 0.0
    t.bytes_queued = 10
    rk._update()
    assert not rk.e_brake and rk.limit_reason != "e_brake"
    rk.stop()


def test_ratekeeper_storage_queue_input_limits():
    k = CoreKnobs()
    k.TARGET_STORAGE_QUEUE_BYTES = 1500
    k.STORAGE_HARD_LIMIT_BYTES = 1 << 30
    c = RecoverableCluster(seed=31, n_storage_shards=1,
                           storage_replication=2, knobs=k)
    try:
        db = c.database()
        _run(c, _write_n(db, b"sq/", 40), 60)

        async def wait_reason():
            while c.ratekeeper.limit_reason != "storage_queue":
                await c.loop.delay(0.25)

        _run(c, wait_reason(), 60)
        st = c.ratekeeper.status()
        assert st["limit_reason"] == "storage_queue"
        assert st["limiting_server"].startswith("ss-")
        assert coverage.hits("ratekeeper.limit_storage_queue") >= 1
        assert max(st["storage_queue_smoothed"].values()) > 1500
    finally:
        c.stop()


def test_ratekeeper_free_space_then_e_brake_then_release():
    c = RecoverableCluster(seed=33, n_storage_shards=1,
                           storage_replication=2)
    try:
        db = c.database()
        ss = c.storage[0]
        path = ss.store._dq.file.path
        _run(c, _write_n(db, b"fs/", 30), 60)

        async def wait_used():
            # the WAL never fully settles (each durability tick appends a
            # commit marker), so wait for the BULK of the burst to land:
            # usage past the burst's data volume, then a short grace
            while True:
                await c.loop.delay(0.25)
                used, _cap = c.fs.usage_for(path)
                if used > 30 * 120:
                    break
            await c.loop.delay(2.0)
            return c.fs.usage_for(path)[0]

        used = _run(c, wait_used(), 300)
        # squeeze band: ~15% free — free_space limits, no brake
        c.fs.set_capacity(path, int(used / 0.85))

        async def wait(reason):
            while c.ratekeeper.limit_reason != reason:
                await c.loop.delay(0.25)

        _run(c, wait("free_space"), 60)
        assert not c.ratekeeper.e_brake
        assert coverage.hits("ratekeeper.limit_free_space") >= 1
        st = c.ratekeeper.status()
        assert 0.0 <= st["free_space"][ss.tag] < 0.25
        # under the minimum: the e-brake slams the budget to the floor
        c.fs.set_capacity(path, int(used / 0.97))
        _run(c, wait("e_brake"), 60)
        assert c.ratekeeper.e_brake
        assert c.ratekeeper.tps_budget <= c.ratekeeper.max_tps * 0.001
        assert c.ratekeeper.batch_tps_budget == 0.0
        assert coverage.hits("ratekeeper.e_brake") >= 1
        # operator adds space: admission releases
        c.fs.set_capacity(path, None)
        _run(c, wait("unlimited"), 120)
        assert not c.ratekeeper.e_brake
    finally:
        c.stop()


def test_ratekeeper_status_keys_are_slot_names_and_schema_pinned():
    """Satellite pin: tlog_queue_smoothed is keyed `tlogN` like
    limiting_server — never raw endpoint tokens — and the ratekeeper
    block validates against the status schema."""
    from foundationdb_tpu.control.status import cluster_status, validate_status

    c = RecoverableCluster(seed=35, n_storage_shards=1,
                           storage_replication=2)
    try:
        db = c.database()
        _run(c, _write_n(db, b"rk/", 5), 30)

        async def tick():
            await c.loop.delay(1.0)

        _run(c, tick(), 10)
        st = c.ratekeeper.status()
        assert st["tlog_queue_smoothed"], "model never saw the tlogs"
        assert all(
            re.fullmatch(r"tlog\d+", key)
            for key in st["tlog_queue_smoothed"]
        ), st["tlog_queue_smoothed"]
        assert set(st["storage_queue_smoothed"]) <= {s.tag for s in c.storage}
        assert set(st["free_space"]) == {s.tag for s in c.storage}
        doc = cluster_status(c)
        validate_status(doc)
        assert "disks" in doc["cluster"]
        row = doc["cluster"]["disks"]["ss0r0.kv"]
        assert set(row) >= {"bytes_used", "capacity", "latency_mult",
                            "stalled", "errors_injected", "enospc_errors"}
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# negative durability pairs (PR-10 style: the handling must be load-bearing)


def _enospc_reboot_invariant(stub_out_handling: bool) -> None:
    """Shared body: commit acked keys, clamp every TLog disk, attempt one
    more commit, power-kill, reboot, and require every ACKED key present.
    With the refusal handling stubbed out (the TLog lies: acks although
    its disk refused the data) the same invariant must demonstrably
    break — proving the loud-refusal path is what preserves it."""
    k = CoreKnobs()
    c = RecoverableCluster(seed=41, n_storage_shards=1,
                           storage_replication=2, knobs=k)
    acked: list[bytes] = []
    db = c.database()
    _run(c, _write_n(db, b"neg/", 6, size=80), 60)
    acked = [b"neg/%04d" % i for i in range(6)]
    tlogs = c.controller.generation.tlogs
    for t in tlogs:
        used, _cap = c.fs.usage_for(t.dq.file.path)
        c.fs.set_capacity(t.dq.file.path, used + 40)  # next push refuses
        if stub_out_handling:
            # the stub: swallow the disk's refusal and ack anyway — the
            # exact silent-ack hole the loud-refusal path closes
            def lying_push(payload, dq=t.dq):
                try:
                    return DiskQueue.push(dq, payload)
                except IOError:
                    return -1

            async def lying_sync(dq=t.dq):
                try:
                    await DiskQueue.sync(dq)
                except IOError:
                    pass

            t.dq.push = lying_push
            t.dq.sync = lying_sync

    async def one_more():
        tr = db.create_transaction()
        tr.set(b"neg/extra", b"1")
        await tr.commit()
        acked.append(b"neg/extra")

    try:
        _run(c, one_more(), 12)
    except Exception:
        pass  # refused/unknown: NOT acked, so not in the invariant set
    if not stub_out_handling:
        assert coverage.hits("tlog.disk_error_refused") > 0, (
            "the clamp never bit — the pair would prove nothing"
        )
    fs = c.power_off()
    for t in tlogs:
        fs.set_capacity(t.dq.file.path, None)
    c2 = RecoverableCluster(seed=41, n_storage_shards=1,
                            storage_replication=2, fs=fs, restart=True)
    try:
        db2 = c2.database()

        async def verify():
            for key in acked:
                async def rd(tr, key=key):
                    v = await tr.get(key)
                    assert v is not None, (
                        f"ACKED key {key!r} lost across the reboot"
                    )

                await db2.run(rd)

        _run(c2, verify(), 60)
    finally:
        c2.stop()


def test_enospc_refusal_preserves_acked_data_across_reboot():
    _enospc_reboot_invariant(stub_out_handling=False)


def test_enospc_with_handling_stubbed_out_loses_acked_data():
    # the SAME invariant check must fail when the TLog silently acks
    # through a refusing disk: the fault is real, the handling load-bearing
    with pytest.raises(AssertionError, match="lost across the reboot"):
        _enospc_reboot_invariant(stub_out_handling=True)


def _stalled_storage_observations(io_timeout_on: bool) -> dict:
    """Shared body for the io_timeout pair: permanently stall a storage
    server's disk mid-run and observe, inside a bounded window, whether
    the process was fail-fasted (killed -> healed) or left wedged."""
    k = CoreKnobs()
    k.IO_TIMEOUT_S = 1.0
    c = RecoverableCluster(seed=43, n_storage_shards=1,
                           storage_replication=2, knobs=k)
    if not io_timeout_on:
        c.fs.io_timeout_s = None  # the stub: the fail-fast disabled
    try:
        db = c.database()
        _run(c, _write_n(db, b"io/", 8), 60)
        ss = c.storage[0]
        proc0 = ss.process
        c.fs.stall(ss.store._dq.file.path, 300.0)

        async def window():
            # keep light traffic flowing so durability keeps trying
            for i in range(30):
                async def body(tr, i=i):
                    tr.set(b"io/w%03d" % i, b"1")

                try:
                    await db.run(body)
                except Exception:
                    pass
                await c.loop.delay(0.5)

        _run(c, window(), 120)
        return {
            "killed": not proc0.alive,
            "io_timeout_kills": coverage.hits("disk.io_timeout_kill"),
            "traced": any(
                ev.get("Type") == "IoTimeoutKilled"
                for ev in c.trace.latest.values()
            ),
        }
    finally:
        c.stop()


def test_io_timeout_kills_the_wedged_process_through_recovery_machinery():
    obs = _stalled_storage_observations(io_timeout_on=True)
    assert obs["killed"], "a wedged disk must fail-fast its process"
    assert obs["io_timeout_kills"] >= 1
    assert obs["traced"], "the kill must be loud (SEV_WARN IoTimeoutKilled)"


def test_io_timeout_stubbed_out_leaves_the_process_wedged():
    # the SAME observations demonstrably fail with the fail-fast disabled:
    # the process stays "alive" (and wedged) and nothing is traced
    obs = _stalled_storage_observations(io_timeout_on=False)
    assert not obs["killed"]
    assert obs["io_timeout_kills"] == 0
    assert not obs["traced"]


def test_dead_process_sync_on_stalled_disk_raises_instead_of_spinning():
    """Review regression: a sync issued by an already-dead process's
    zombie actor on a stalled disk whose io_timeout deadline passes
    mid-stall must wait the stall out and RAISE — the watchdog (which
    has nothing to kill) must never clamp the wait to a passed deadline
    and spin the loop at zero delay forever."""
    from foundationdb_tpu.rpc.network import SimNetwork

    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(7))
    fs.io_timeout_s = 1.0
    net = SimNetwork(loop, DeterministicRandom(1), None)
    proc = net.create_process("zombie")
    f = fs.open("d0", proc)
    f.append(b"x")
    proc.kill()  # the owner is ALREADY dead when the sync is issued
    fs.stall("d0", 10.0)

    async def sync():
        await f.sync()

    with pytest.raises(IOError):
        loop.run_until(loop.spawn(sync()), 60)
    assert loop.now() < 60, "the stall must end, not eat the deadline"
    assert coverage.hits("disk.io_timeout_kill") == 0  # nothing to kill


def test_restart_refuses_engine_mismatched_disks():
    """Review regression: booting a restart image with the WRONG engine
    (the disks were migrated by an online `configure engine=` before the
    save) must refuse loudly — recovering the configured engine against
    the other engine's files would silently boot empty stores and lose
    acked data through the resumed swap."""
    from foundationdb_tpu.client.management import configure
    from foundationdb_tpu.storage.btree import BTreeKeyValueStore

    c = RecoverableCluster(seed=61, n_storage_shards=1,
                           storage_replication=2)
    db = c.database()
    _run(c, _write_n(db, b"em/", 8, size=40), 60)

    async def swap_and_wait():
        await configure(db, engine="ssd")
        while c._engine_applied != "ssd":
            await c.loop.delay(0.25)

    _run(c, swap_and_wait(), 300)
    fs = c.clean_shutdown()
    with pytest.raises(ValueError, match="engine mismatch"):
        RecoverableCluster(seed=61, n_storage_shards=1,
                           storage_replication=2, fs=fs, restart=True,
                           storage_engine="memory")
    # the disks' own engine boots fine with every row intact
    c2 = RecoverableCluster(seed=61, n_storage_shards=1,
                            storage_replication=2, fs=fs, restart=True,
                            storage_engine="ssd")
    try:
        assert all(
            type(ss.store) is BTreeKeyValueStore for ss in c2.storage
        )
        db2 = c2.database()

        async def verify():
            for i in range(8):
                async def rd(tr, i=i):
                    assert await tr.get(b"em/%04d" % i) is not None

                await db2.run(rd)

        _run(c2, verify(), 60)
    finally:
        c2.stop()


def test_infeasible_engine_swap_rejected_once_not_retried_forever():
    """Review regression: `configure engine=` on a cluster that can never
    satisfy it (replication 1 — no live teammate to re-fetch from) is
    REJECTED once (StorageEngineChangeRejected) and not re-entered every
    conf poll as phantom drift."""
    from foundationdb_tpu.client.management import configure

    c = RecoverableCluster(seed=63, n_storage_shards=1,
                           storage_replication=1)
    try:
        db = c.database()

        async def ask_and_wait():
            await configure(db, engine="ssd")
            while getattr(c.controller, "_engine_rejected", None) != "ssd":
                await c.loop.delay(0.25)
            # several more polls: the rejection must HOLD (no respawn spam)
            await c.loop.delay(3 * c.knobs.CONF_POLL_INTERVAL + 0.5)

        _run(c, ask_and_wait(), 120)
        assert c._engine_applied == "memory"
        assert len(c.trace.find("StorageEngineChangeRejected")) == 1, (
            "rejected exactly ONCE — not re-entered every poll"
        )
        assert c.controller._engine_rejected == "ssd"
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# engine swap through the conf plane


def test_engine_swap_migrates_every_replica_and_keeps_data():
    from foundationdb_tpu.client.management import configure
    from foundationdb_tpu.storage.btree import BTreeKeyValueStore

    c = RecoverableCluster(seed=51, n_storage_shards=2,
                           storage_replication=2)
    try:
        db = c.database()
        _run(c, _write_n(db, b"es/", 12, size=40), 60)

        async def swap_and_wait(engine):
            await configure(db, engine=engine)
            while c._engine_applied != engine:
                await c.loop.delay(0.25)

        _run(c, swap_and_wait("ssd"), 300)
        assert all(
            type(cc_ss.store) is BTreeKeyValueStore
            for cc_ss in c.controller.storage
        )
        assert coverage.hits("configure.engine_converged") >= 1
        assert coverage.hits("management.engine_swapped") >= 1

        async def verify():
            for i in range(12):
                async def rd(tr, i=i):
                    assert await tr.get(b"es/%04d" % i) is not None

                await db.run(rd)

        _run(c, verify(), 60)
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# spec smokes + soak resume


def test_low_space_spec_transitions_through_both_reasons():
    from foundationdb_tpu.workloads.spec import run_spec_file

    m = run_spec_file("tests/specs/LowSpace.txt", deadline=600)
    lw = m["LowSpace"]
    assert lw["engaged"] and lw["drained"]
    assert "free_space" in lw["reasons_seen"]
    assert "e_brake" in lw["reasons_seen"]
    assert lw["reasons_seen"][-1] == "unlimited"


@pytest.mark.slow
def test_disk_swizzle_spec_green_with_all_fault_classes():
    from foundationdb_tpu.workloads.spec import run_spec_file

    run_spec_file("tests/specs/DiskSwizzle.txt", deadline=600)
    for site in ("disk.slow", "disk.stall", "disk.error", "disk.enospc",
                 "disk.corrupt_read"):
        assert coverage.hits(f"buggify.{site}") >= 1, site
    assert coverage.hits("disk.enospc_hit") >= 1


@pytest.mark.slow
def test_disk_fault_restart_pair_green():
    from foundationdb_tpu.workloads.spec import run_restarting_pair

    m = run_restarting_pair(
        "tests/specs/restarting/DiskFaultRestart-1.txt", deadline=600
    )
    assert m["part1"]["DiskSwizzle"]["faults_applied"] > 0
    assert "Cycle" in m["part2"]


def test_soak_campaign_kill_and_resume(tmp_path):
    """Satellite pin: a campaign killed mid-run resumes from completed
    per-seed result.json dirs instead of restarting from seed 0 — the
    already-finished seed is adopted byte-for-byte (result.json
    untouched, census preserved through the pruned traces)."""
    import os

    from foundationdb_tpu.tools import soak

    spec = "tests/specs/CycleTest.txt"
    out = str(tmp_path / "camp")
    first = soak.run_campaign(spec, [9001, 9002], out, jobs=2,
                              seed_deadline=240.0)
    assert first["ok"], first["verdicts"]
    res1 = os.path.join(out, "seed-9001", "result.json")
    mtime1 = os.path.getmtime(res1)
    census1 = first["coverage"]["per_seed"]["9001"]
    assert census1["testcov"], "pruned seed must keep its census"
    # simulate the kill: seed 9002 never completed (its dir is gone)
    import shutil

    shutil.rmtree(os.path.join(out, "seed-9002"), ignore_errors=True)
    second = soak.run_campaign(spec, [9001, 9002], out, jobs=2,
                               seed_deadline=240.0, resume=True)
    assert second["ok"], second["verdicts"]
    # seed 9001 was ADOPTED, not re-run
    assert os.path.getmtime(res1) == mtime1
    assert second["coverage"]["per_seed"]["9001"] == census1
