"""Core runtime: futures, deterministic loop, combinators — the dsltest
analog (reference fdbrpc/dsltest.actor.cpp exercises flow primitives)."""

import pytest

from foundationdb_tpu.runtime import buggify
from foundationdb_tpu.runtime.combinators import (
    AsyncTrigger,
    AsyncVar,
    quorum,
    timeout_error,
    wait_all,
    wait_any,
)
from foundationdb_tpu.runtime.core import (
    ActorCancelled,
    BrokenPromise,
    DeterministicRandom,
    EventLoop,
    Future,
    FutureStream,
    Promise,
    TaskPriority,
    TimedOut,
)


def test_promise_future_basics():
    p = Promise()
    assert not p.future.done()
    p.send(42)
    assert p.future.done() and p.future.result() == 42
    with pytest.raises(RuntimeError):
        p.send(43)  # single assignment

    p2 = Promise()
    p2.fail(ValueError("boom"))
    with pytest.raises(ValueError):
        p2.future.result()


def test_broken_promise():
    p = Promise()
    f = p.future
    del p
    assert isinstance(f.exception(), BrokenPromise)


def test_loop_runs_coroutines_in_virtual_time():
    loop = EventLoop()
    order = []

    async def worker(name, d):
        await loop.delay(d)
        order.append((name, loop.now()))
        return name

    t1 = loop.spawn(worker("a", 2.0))
    t2 = loop.spawn(worker("b", 1.0))
    loop.run_until(wait_all([t1, t2]))
    assert order == [("b", 1.0), ("a", 2.0)]
    assert loop.now() == 2.0  # virtual clock jumped, no wall time spent


def test_priority_ordering_at_same_time():
    loop = EventLoop()
    order = []
    loop._at(1.0, TaskPriority.LOW, lambda: order.append("low"))
    loop._at(1.0, TaskPriority.PROXY_COMMIT, lambda: order.append("commit"))
    loop._at(1.0, TaskPriority.STORAGE_SERVER, lambda: order.append("ss"))
    loop.drain()
    assert order == ["commit", "ss", "low"]


def test_determinism_same_seed_same_schedule():
    def run(seed):
        loop = EventLoop()
        rng = DeterministicRandom(seed)
        log = []

        async def chatter(i):
            for _ in range(5):
                await loop.delay(rng.random() * 0.1)
                log.append((i, round(loop.now(), 9)))

        tasks = [loop.spawn(chatter(i)) for i in range(4)]
        loop.run_until(wait_all(tasks))
        return log

    assert run(7) == run(7)
    assert run(7) != run(8)


def test_cancellation_throws_actor_cancelled():
    loop = EventLoop()
    witness = []

    async def stubborn():
        try:
            await loop.delay(100.0)
        except ActorCancelled:
            witness.append("cancelled")
            raise

    t = loop.spawn(stubborn())
    loop.run_one()  # start it
    t.cancel()
    loop.drain()
    assert witness == ["cancelled"]
    assert isinstance(t.exception(), ActorCancelled)


def test_future_stream():
    loop = EventLoop()
    s = FutureStream()
    got = []

    async def consumer():
        for _ in range(3):
            got.append(await s.pop())

    t = loop.spawn(consumer())
    s.send(1)
    s.send(2)
    loop.drain()
    s.send(3)
    loop.run_until(t)
    assert got == [1, 2, 3]


def test_wait_any_and_timeout():
    loop = EventLoop()

    async def main():
        i, v = await wait_any([loop.delay(5.0), loop.delay(1.0)])
        assert i == 1
        with pytest.raises(TimedOut):
            await timeout_error(loop, loop.delay(10.0), 2.0)
        return "done"

    assert loop.run_until(loop.spawn(main())) == "done"


def test_quorum():
    loop = EventLoop()
    ps = [Promise() for _ in range(5)]
    q = quorum([p.future for p in ps], 3)
    ps[0].send(None)
    ps[1].send(None)
    assert not q.done()
    ps[4].send(None)
    assert q.done() and q.exception() is None

    ps2 = [Promise() for _ in range(3)]
    q2 = quorum([p.future for p in ps2], 3)
    ps2[1].fail(ValueError("x"))
    assert q2.done() and isinstance(q2.exception(), ValueError)


def test_async_var_and_trigger():
    loop = EventLoop()
    av = AsyncVar(1)
    f = av.on_change()
    av.set(1)  # no change, no fire
    assert not f.done()
    av.set(2)
    assert f.done() and f.result() == 2

    trig = AsyncTrigger()
    f1, f2 = trig.on_trigger(), trig.on_trigger()
    trig.trigger()
    assert f1.done() and f2.done()
    assert not trig.on_trigger().done()  # new waiter needs a new trigger


def test_buggify_deterministic_and_off_outside_sim():
    assert not buggify.buggify("site1")  # disabled by default
    buggify.enable(DeterministicRandom(3), enable_prob=1.0, fire_prob=1.0)
    assert buggify.buggify("site1")
    buggify.disable()
    assert not buggify.buggify("site1")


def test_knobs():
    from foundationdb_tpu.runtime.knobs import CoreKnobs

    k = CoreKnobs()
    assert k.VERSIONS_PER_SECOND == 1_000_000
    k.set_knob("VERSIONS_PER_SECOND", "500")
    assert k.VERSIONS_PER_SECOND == 500
    with pytest.raises(KeyError):
        k.set_knob("NO_SUCH", "1")
    assert k.mvcc_window_versions == int(500 * k.MAX_WRITE_TRANSACTION_LIFE)


def test_trace_collector():
    from foundationdb_tpu.runtime.trace import SEV_WARN, TraceCollector

    clock = {"t": 0.0}
    tc = TraceCollector(clock=lambda: clock["t"])
    tc.trace("CommitBatch", Txns=5)
    clock["t"] = 1.5
    tc.trace("MasterRecoveryState", severity=SEV_WARN, track_latest="master", State="locking")
    assert tc.count("CommitBatch") == 1
    assert tc.latest["master"]["State"] == "locking"
    assert tc.find("MasterRecoveryState")[0]["Time"] == 1.5
