"""Test config: force a fast 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on XLA's
host platform with 8 virtual devices (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip).

This environment injects a TPU-tunnel PJRT plugin ("axon") via
sitecustomize.py in every interpreter and sets JAX_PLATFORMS=axon globally;
initializing it costs ~2 minutes of tunnel handshake.  Tests must never pay
that, so we re-point JAX at CPU *after* import (the env var was already
latched when sitecustomize imported jax) and drop the plugin's backend
factory before the first op initializes backends.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
