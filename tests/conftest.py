"""Test config: force a fast 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on XLA's
host platform with 8 virtual devices (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip).

This environment injects a TPU-tunnel PJRT plugin ("axon") via
sitecustomize.py in every interpreter and sets JAX_PLATFORMS=axon globally;
initializing it costs ~2 minutes of tunnel handshake.  Tests must never pay
that, so we re-point JAX at CPU *after* import (the env var was already
latched when sitecustomize imported jax) and drop the plugin's backend
factory before the first op initializes backends.
"""

import os

# older jax (< jax_num_cpu_devices) reads the device count from XLA_FLAGS at
# CPU-client creation; set it before any op initializes backends so both
# paths below produce the same 8-device mesh
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: the XLA_FLAGS fallback above applies
    pass

from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)


import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: device-dependent or long-running; excluded from tier-1 "
        "(-m 'not slow')",
    )


@pytest.fixture(autouse=True)
def _census_isolation():
    """Coverage counters (runtime/coverage.py) and buggify arming state
    (runtime/buggify.py) are process-global; without isolation they bleed
    between tests and census numbers depend on which tests ran before.
    Every test starts with an empty census and a disabled buggify, and
    whatever it armed/hit is rolled back afterwards — even when the test
    body raises mid-run.  (tests/test_soak.py pins this with a
    regression pair.)"""
    from foundationdb_tpu.runtime import buggify, coverage

    cov_snap = coverage.snapshot()
    bug_snap = buggify.snapshot()
    coverage.reset()
    buggify.disable()
    yield
    coverage.restore(cov_snap)
    buggify.restore(bug_snap)
