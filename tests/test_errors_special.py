"""Error registry numbering, broadcast combinator, special-key status
client, and trace-file streaming (flow/Error.h error codes;
genericactors broadcast; SpecialKeySpace \\xff\\xff/status/json;
the reference's rolling trace files)."""

import io
import json

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.roles.errors import error_code, error_for_code, error_name
from foundationdb_tpu.roles.types import (
    CommitUnknownResult,
    NotCommitted,
    TransactionTooOld,
)
from foundationdb_tpu.runtime.core import BrokenPromise, TimedOut


def test_error_codes_match_reference_numbering():
    assert error_code(NotCommitted()) == 1020
    assert error_code(CommitUnknownResult()) == 1021
    assert error_code(TransactionTooOld()) == 1007
    assert error_code(TimedOut("x")) == 1004
    assert error_code(BrokenPromise("x")) == 1100
    assert error_code(ValueError("internal")) == 4100
    assert error_name(1020) == "not_committed"
    # wire roundtrip: code -> typed exception -> same code
    for code in (1004, 1007, 1009, 1020, 1021, 1100, 1101):
        assert error_code(error_for_code(code)) == code


def test_broadcast_best_effort():
    from foundationdb_tpu.roles.types import TLogConfirmRequest
    from foundationdb_tpu.runtime.combinators import broadcast

    c = RecoverableCluster(seed=1601, n_storage_shards=1, storage_replication=2)
    gen = c.controller.generation
    cc = c.controller._cc_proc()
    from foundationdb_tpu.rpc.stream import RequestStreamRef

    refs = [
        RequestStreamRef(c.net, cc, t.confirm_stream.endpoint)
        for t in gen.tlogs
    ]
    # kill one TLog: its slot yields None, the other still answers
    gen.tlogs[0].process.kill()

    async def main():
        return await broadcast(c.loop, refs, TLogConfirmRequest(), timeout=0.5)

    replies = c.run_until(c.loop.spawn(main()), 300)
    assert len(replies) == 2
    assert sum(r is not None for r in replies) >= 1
    assert any(r is None for r in replies)
    c.stop()


def test_status_json_special_key():
    from foundationdb_tpu.control.status import validate_status

    c = RecoverableCluster(seed=1602, n_storage_shards=2, storage_replication=2)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        raw = await tr.get(b"\xff\xff/status/json")
        missing = await tr.get(b"\xff\xff/no/such/module")
        return raw, missing

    raw, missing = c.run_until(c.loop.spawn(main()), 300)
    assert missing is None
    doc = json.loads(raw)
    validate_status(doc)  # the client-fetched doc obeys the schema
    assert doc["cluster"]["generation"]["state"] == "fully_recovered"
    c.stop()


def test_trace_sink_streams_jsonl():
    sink = io.StringIO()
    c = RecoverableCluster(seed=1603, n_storage_shards=1,
                           storage_replication=2, trace_sink=sink)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"t", b"1")
        await tr.commit()

    c.run_until(c.loop.spawn(main()), 300)
    c.stop()
    lines = [json.loads(l) for l in sink.getvalue().splitlines() if l.strip()]
    assert any(e["Type"] == "MasterRecoveryState" for e in lines)
    assert all("Time" in e and "Severity" in e for e in lines)


def test_special_key_range_modules():
    """SpecialKeySpace RANGE modules: \xff\xff/keyservers/, /excluded/,
    /server_list/ read controller metadata like keys (the readable
    SystemData vocabulary, fdbclient/SystemData.cpp)."""
    from foundationdb_tpu.client import management as mgmt
    from foundationdb_tpu.control.recoverable import RecoverableCluster

    c = RecoverableCluster(seed=560, n_storage_shards=2, storage_replication=2)
    db = c.database()

    async def main():
        rows = await db.create_transaction().get_range(
            b"\xff\xff/keyservers/", b"\xff\xff/keyservers0"
        )
        assert len(rows) == 2  # one row per shard
        assert rows[0][0] == b"\xff\xff/keyservers/"
        teams0 = rows[0][1].split(b",")
        assert len(teams0) == 2  # replication factor

        srv = await db.create_transaction().get_range(
            b"\xff\xff/server_list/", b"\xff\xff/server_list0"
        )
        assert len(srv) == 4  # 2 shards x 2 replicas
        assert all(b"@" in v for _k, v in srv)

        # exclusion shows up in the excluded module once committed + polled
        await mgmt.exclude(db, ["bogus-machine"])
        for _ in range(100):
            await c.loop.delay(0.1)
            if c.controller.excluded_targets:
                break
        ex = await db.create_transaction().get_range(
            b"\xff\xff/excluded/", b"\xff\xff/excluded0"
        )
        assert ex == [(b"\xff\xff/excluded/bogus-machine", b"1")]
        return True

    assert c.run_until(c.loop.spawn(main()), 300)
    c.stop()
