"""Real TCP transport: the typed RPC layer over OS processes.

The same RequestStream/ReplyPromise code that runs on the simulated fabric
runs here over sockets (the Net2/FlowTransport production twin of the
seam).  Tests spawn genuine child processes.
"""

import subprocess
import sys
import textwrap

import pytest

from foundationdb_tpu.rpc.stream import RequestStream, RequestStreamRef
from foundationdb_tpu.rpc.transport import NetDriver, RealNetwork
from foundationdb_tpu.runtime.core import BrokenPromise, EventLoop

SERVER = textwrap.dedent(
    """
    import sys
    sys.path.insert(0, {repo!r})
    from foundationdb_tpu.rpc.stream import RequestStream
    from foundationdb_tpu.rpc.transport import NetDriver, RealNetwork
    from foundationdb_tpu.runtime.core import EventLoop

    loop = EventLoop()
    net = RealNetwork(loop, name="server")
    rs = RequestStream(net.process, "wlt:echo")
    kv = {{}}
    kvs = RequestStream(net.process, "wlt:kv")

    async def serve_echo():
        while True:
            req = await rs.next()
            req.reply(("echoed", req.payload))

    async def serve_kv():
        while True:
            req = await kvs.next()
            op, k, v = req.payload
            if op == "set":
                kv[k] = v
                req.reply(("ok", None))
            else:
                req.reply(("ok", kv.get(k)))

    loop.spawn(serve_echo())
    loop.spawn(serve_kv())
    print(net.address.port, flush=True)
    NetDriver(loop, net).serve_forever(wall_timeout=30.0)
    """
)


@pytest.fixture()
def server():
    import foundationdb_tpu

    repo = str(__import__("pathlib").Path(foundationdb_tpu.__file__).parent.parent)
    import tempfile

    errf = tempfile.TemporaryFile(mode="w+")
    proc = subprocess.Popen(
        [sys.executable, "-c", SERVER.format(repo=repo)],
        stdout=subprocess.PIPE,
        stderr=errf,  # a file, so a chatty child can never block on a pipe
        text=True,
        env={"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin"},
    )
    try:
        import select as _select

        ready, _, _ = _select.select([proc.stdout], [], [], 15.0)
        line = proc.stdout.readline() if ready else ""
        if not line.strip():
            proc.kill()
            errf.seek(0)
            pytest.fail(f"transport server never started: {errf.read()[-2000:]}")
        yield int(line)
    finally:
        proc.kill()
        proc.wait()
        errf.close()


def test_cross_process_request_reply(server):
    from foundationdb_tpu.rpc.network import Endpoint, NetworkAddress

    loop = EventLoop()
    net = RealNetwork(loop, name="client")
    drv = NetDriver(loop, net)
    ref = RequestStreamRef(
        net, net.process, Endpoint(NetworkAddress("127.0.0.1", server), "wlt:echo")
    )
    out = drv.run_until(ref.get_reply({"n": 42}, timeout=5.0), wall_timeout=10.0)
    assert out == ("echoed", {"n": 42})
    net.close()


def test_cross_process_kv_roundtrip(server):
    from foundationdb_tpu.rpc.network import Endpoint, NetworkAddress

    loop = EventLoop()
    net = RealNetwork(loop, name="client")
    drv = NetDriver(loop, net)
    ref = RequestStreamRef(
        net, net.process, Endpoint(NetworkAddress("127.0.0.1", server), "wlt:kv")
    )

    async def main():
        for i in range(20):
            st, _ = await ref.get_reply(("set", b"k%d" % i, b"v%d" % i), timeout=5.0)
            assert st == "ok"
        vals = []
        for i in range(20):
            _, v = await ref.get_reply(("get", b"k%d" % i, None), timeout=5.0)
            vals.append(v)
        return vals

    vals = drv.run_until(loop.spawn(main()), wall_timeout=20.0)
    assert vals == [b"v%d" % i for i in range(20)]
    net.close()


def test_dead_peer_fails_fast():
    """Connecting to a port nobody listens on must surface BrokenPromise
    (connection refused), not burn the full timeout — the same contract as
    the simulated fabric."""
    from foundationdb_tpu.rpc.network import Endpoint, NetworkAddress

    loop = EventLoop()
    net = RealNetwork(loop, name="client")
    drv = NetDriver(loop, net)
    # grab a port and close it so nothing listens there
    import socket as _s

    probe = _s.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    ref = RequestStreamRef(
        net, net.process, Endpoint(NetworkAddress("127.0.0.1", dead_port), "wlt:echo")
    )
    import time

    t0 = time.monotonic()
    with pytest.raises(BrokenPromise):
        drv.run_until(ref.get_reply("x", timeout=5.0), wall_timeout=10.0)
    assert time.monotonic() - t0 < 3.0, "refusal should beat the timeout"
    net.close()


def _hostile_send(port: int, blob: bytes, *, also_valid_probe=None,
                  wall_timeout: float = 10.0):
    """Open a raw socket to a RealNetwork listener, send `blob` verbatim,
    and pump the victim's reactor until it processes the bytes.  Returns
    once the victim has either severed the connection or gone idle."""
    import socket as _s
    import time as _t

    s = _s.socket()
    s.connect(("127.0.0.1", port))
    s.sendall(blob)
    deadline = _t.monotonic() + wall_timeout
    severed = False
    s.settimeout(0.2)
    while _t.monotonic() < deadline:
        also_valid_probe(0.05)
        try:
            if s.recv(1 << 12) == b"":
                severed = True
                break
        except _s.timeout:
            continue
        except OSError:
            severed = True
            break
    s.close()
    return severed


@pytest.mark.parametrize("header,reason", [
    (0xFFFFFFFF, "oversized frame"),   # 4 GiB declared: hostile buffering
    ((64 << 20) + 1, "oversized frame"),
    (0, "length-corrupt frame"),       # zero-length: corrupt header
    (1, "length-corrupt frame"),
])
def test_corrupt_frame_rejected_at_connection_level(header, reason):
    """An oversized or length-corrupt frame header must sever the
    connection with a traced error BEFORE any bytes reach the pickle
    deserializer — and without buffering the declared body."""
    import struct as _struct

    from foundationdb_tpu.runtime.trace import TraceCollector

    loop = EventLoop()
    trace = TraceCollector(loop.now)
    victim = RealNetwork(loop, name="victim", trace=trace)
    blob = _struct.pack("<I", header) + b"\x00" * 64  # header + partial junk
    severed = _hostile_send(victim.address.port, blob,
                            also_valid_probe=victim.pump)
    assert severed, "victim kept the hostile connection open"
    assert victim.frames_rejected == 1
    assert victim.decode_failures == 0
    evs = trace.find("TransportFrameRejected")
    assert len(evs) == 1 and evs[0]["Reason"] == reason
    assert evs[0]["DeclaredLen"] == header
    victim.close()


def test_undeserializable_frame_severs_with_decode_error():
    """A well-framed but unpicklable payload is the deserializer-level
    failure: severed too, but counted/traced as a decode failure."""
    import struct as _struct

    from foundationdb_tpu.runtime.trace import TraceCollector

    loop = EventLoop()
    trace = TraceCollector(loop.now)
    victim = RealNetwork(loop, name="victim", trace=trace)
    body = b"\x95garbage-not-pickle"
    blob = _struct.pack("<I", len(body)) + body
    severed = _hostile_send(victim.address.port, blob,
                            also_valid_probe=victim.pump)
    assert severed
    assert victim.frames_rejected == 0
    assert victim.decode_failures == 1
    assert len(trace.find("TransportDecodeFailed")) == 1
    victim.close()


def test_valid_traffic_unaffected_by_frame_guards(server):
    """Regression guard: the MIN/MAX frame validation must not reject real
    frames (the smallest legitimate payloads ride well above MIN_FRAME)."""
    from foundationdb_tpu.rpc.network import Endpoint, NetworkAddress

    loop = EventLoop()
    net = RealNetwork(loop, name="client")
    drv = NetDriver(loop, net)
    ref = RequestStreamRef(
        net, net.process, Endpoint(NetworkAddress("127.0.0.1", server), "wlt:echo")
    )
    out = drv.run_until(ref.get_reply(None, timeout=5.0), wall_timeout=10.0)
    assert out == ("echoed", None)
    assert net.frames_rejected == 0 and net.decode_failures == 0
    net.close()


def test_truncated_codec_frame_severs_with_decode_error():
    """A well-framed but TRUNCATED codec body (valid tag, lengths pointing
    past the buffer) is rejected at the connection level exactly like an
    unpicklable frame: severed + counted as a decode failure."""
    import struct as _struct

    from foundationdb_tpu.conflict.api import TxInfo
    from foundationdb_tpu.roles.types import ResolveTransactionBatchRequest
    from foundationdb_tpu.runtime.serialize import encode_frame
    from foundationdb_tpu.runtime.trace import TraceCollector
    from foundationdb_tpu.rpc.network import NetworkAddress

    loop = EventLoop()
    trace = TraceCollector(loop.now)
    victim = RealNetwork(loop, name="victim", trace=trace)
    good = encode_frame(
        "wlt:resolve", NetworkAddress("127.0.0.1", 1),
        ResolveTransactionBatchRequest(
            1, 2, [TxInfo(1, [(b"abcdef", b"abcdef\x00")], [])] * 4
        ),
    )
    body = good[: len(good) - 9]  # cut mid key blob: lengths now lie
    blob = _struct.pack("<I", len(body)) + body
    severed = _hostile_send(victim.address.port, blob,
                            also_valid_probe=victim.pump)
    assert severed, "victim kept the corrupt-codec connection open"
    assert victim.frames_rejected == 0
    assert victim.decode_failures == 1
    assert len(trace.find("TransportDecodeFailed")) == 1
    victim.close()


def test_write_coalescing_frames_per_flush(server):
    """A burst of sends queued in one reactor turn must leave in ONE
    coalesced write (frames_per_flush ≈ burst size), and every frame must
    still arrive."""
    from foundationdb_tpu.rpc.network import Endpoint, NetworkAddress

    loop = EventLoop()
    net = RealNetwork(loop, name="client")
    drv = NetDriver(loop, net)
    ref = RequestStreamRef(
        net, net.process, Endpoint(NetworkAddress("127.0.0.1", server), "wlt:echo")
    )

    async def burst():
        futs = [ref.get_reply({"n": i}, timeout=5.0) for i in range(32)]
        out = []
        for f in futs:
            out.append(await f)
        return out

    out = drv.run_until(loop.spawn(burst()), wall_timeout=20.0)
    assert [o[1]["n"] for o in out] == list(range(32))
    snap = net.wire.snapshot()
    # 32 requests + 1 hello queued before the first pump tick: at least a
    # 4x coalescing factor even if the reactor splits the burst
    assert snap["frames_per_flush"] >= 4.0, snap
    assert net.wire.pickle_fallbacks <= 33  # dict payloads pickle, counted
    net.close()


def test_flush_byte_threshold_bounds_queue(server):
    """Past WIRE_FLUSH_BYTES the queue flushes inside send() (the memory
    bound): with a tiny threshold a burst degrades toward flush-per-send
    — many more flush events than the coalesced default — while traffic
    still round-trips correctly."""
    from foundationdb_tpu.runtime.knobs import CoreKnobs
    from foundationdb_tpu.rpc.network import Endpoint, NetworkAddress

    knobs = CoreKnobs()
    knobs.WIRE_FLUSH_BYTES = 1  # every queued frame passes the threshold
    loop = EventLoop()
    net = RealNetwork(loop, name="client", knobs=knobs)
    drv = NetDriver(loop, net)
    ref = RequestStreamRef(
        net, net.process, Endpoint(NetworkAddress("127.0.0.1", server), "wlt:echo")
    )

    async def burst():
        # warm the connection first: sends queued while still CONNECTING
        # legitimately coalesce regardless of threshold
        await ref.get_reply({"n": -1}, timeout=5.0)
        futs = [ref.get_reply({"n": i}, timeout=5.0) for i in range(16)]
        return [await f for f in futs]

    out = drv.run_until(loop.spawn(burst()), wall_timeout=20.0)
    assert [o[1]["n"] for o in out] == list(range(16))
    snap = net.wire.snapshot()
    # on the warm connection every send crosses the 1-byte threshold and
    # flushes itself: flush count approaches frame count
    assert snap["flushes"] >= 10, snap
    net.close()


def test_protocol_mismatch_hello_severs_with_named_reason():
    """A peer stamping a DIFFERENT protocol version in its hello is severed
    with a traced TransportProtocolMismatch naming both versions — not a
    bare decode-failure loop."""
    import struct as _struct

    from foundationdb_tpu.runtime.serialize import PROTOCOL_VERSION, encode_frame
    from foundationdb_tpu.runtime.trace import TraceCollector
    from foundationdb_tpu.rpc.network import NetworkAddress

    loop = EventLoop()
    trace = TraceCollector(loop.now)
    victim = RealNetwork(loop, name="victim", trace=trace)
    body = encode_frame(
        "__hello__", NetworkAddress("127.0.0.1", 1), PROTOCOL_VERSION + 1
    )
    blob = _struct.pack("<I", len(body)) + body
    severed = _hostile_send(victim.address.port, blob,
                            also_valid_probe=victim.pump)
    assert severed, "victim kept the mixed-version connection open"
    evs = trace.find("TransportProtocolMismatch")
    assert len(evs) == 1
    assert evs[0]["Ours"] == hex(PROTOCOL_VERSION)
    assert evs[0]["Theirs"] == hex(PROTOCOL_VERSION + 1)
    victim.close()
