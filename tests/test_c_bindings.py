"""C client ABI + foreign-language bindings (bindings/c + bindings/python):
build libfdbtpu_c.so with the system toolchain, run a compiled C program
against a live cluster through the client gateway, and run a bindingtester-
style conformance script through BOTH the ctypes→C→gateway stack and the
in-process Python client, asserting identical results
(reference bindings/c/fdb_c.cpp; bindings/bindingtester/bindingtester.py)."""

import pathlib
import select
import subprocess
import sys
import textwrap

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
CDIR = REPO / "bindings" / "c"

GATEWAY_SERVER = textwrap.dedent(
    """
    import sys
    sys.path.insert(0, {repo!r})
    from foundationdb_tpu.control.recoverable import RecoverableCluster
    from foundationdb_tpu.tools.gateway import ClientGateway, GatewayDriver

    c = RecoverableCluster(seed=801, n_storage_shards=2, storage_replication=2)
    gw = ClientGateway(c.loop, c.database(), port=0)
    print(gw.port, flush=True)
    GatewayDriver(c.loop, gw).serve_forever(wall_timeout=60.0)
    """
)


@pytest.fixture(scope="module")
def clib():
    r = subprocess.run(
        ["make", "-C", str(CDIR)], capture_output=True, text=True
    )
    assert r.returncode == 0, f"C build failed:\n{r.stdout}\n{r.stderr}"
    return CDIR / "libfdbtpu_c.so"


@pytest.fixture()
def gateway():
    import tempfile

    errf = tempfile.TemporaryFile(mode="w+")
    proc = subprocess.Popen(
        [sys.executable, "-c", GATEWAY_SERVER.format(repo=str(REPO))],
        stdout=subprocess.PIPE,
        stderr=errf,
        text=True,
        env={"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
             "PATH": "/usr/bin:/bin"},
    )
    try:
        ready, _, _ = select.select([proc.stdout], [], [], 20.0)
        line = proc.stdout.readline() if ready else ""
        if not line.strip():
            proc.kill()
            errf.seek(0)
            pytest.fail(f"gateway never started: {errf.read()[-2000:]}")
        yield int(line)
    finally:
        proc.kill()
        proc.wait()
        errf.close()


def test_c_program_end_to_end(clib, gateway):
    """The compiled C driver exercises set/get/RYW/atomic-add/clear/range/
    commit/on_error against the live cluster."""
    r = subprocess.run(
        [str(CDIR / "ctest"), "127.0.0.1", str(gateway)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, f"ctest failed:\n{r.stdout}\n{r.stderr}"
    assert r.stdout.startswith("C-OK ")
    assert int(r.stdout.split()[1]) > 0


# -- bindingtester-mini: one op script, two stacks, identical results --------

OPS = [
    ("set", b"bt/a", b"1"),
    ("set", b"bt/b", b"2"),
    ("commit",),
    ("get", b"bt/a"),
    ("set", b"bt/a", b"override"),
    ("get", b"bt/a"),          # read-your-writes
    ("atomic_add", b"bt/n", 5),
    ("atomic_add", b"bt/n", 7),
    ("commit",),
    ("clear_range", b"bt/b", b"bt/c"),
    ("get", b"bt/b"),          # RYW sees the clear
    ("commit",),
    ("get_range", b"bt/", b"bt0"),
]


def _run_script(tr_factory, commit, results):
    tr = tr_factory()
    for op in OPS:
        kind = op[0]
        if kind == "set":
            tr.set(op[1], op[2])
        elif kind == "get":
            results.append(("get", op[1], tr.get(op[1])))
        elif kind == "atomic_add":
            tr.atomic_add(op[1], op[2])
        elif kind == "clear_range":
            tr.clear_range(op[1], op[2])
        elif kind == "get_range":
            results.append(("range", tr.get_range(op[1], op[2])))
        elif kind == "commit":
            commit(tr)
            tr = tr_factory()
    commit(tr)
    return results


def test_bindingtester_conformance(clib, gateway):
    """The same op script through ctypes→C→gateway and through the
    in-process Python client must produce byte-identical results."""
    sys.path.insert(0, str(REPO / "bindings" / "python"))
    from fdbtpu_ctypes import FdbTpu

    # stack 1: C ABI against the live gateway cluster
    db_c = FdbTpu(str(clib), "127.0.0.1", gateway)
    c_results: list = []

    class _CWrap:
        def __init__(self, tr):
            self.tr = tr

        def set(self, k, v):
            self.tr.set(k, v)

        def get(self, k):
            return self.tr.get(k)

        def atomic_add(self, k, d):
            self.tr.atomic_add(k, d)

        def clear_range(self, b, e):
            self.tr.clear_range(b, e)

        def get_range(self, b, e):
            return self.tr.get_range(b, e)

    _run_script(
        lambda: _CWrap(db_c.create_transaction()),
        lambda w: w.tr.commit(),
        c_results,
    )
    db_c.close()

    # stack 2: in-process Python client on a fresh deterministic cluster
    from foundationdb_tpu.control.recoverable import RecoverableCluster
    from foundationdb_tpu.roles.types import MutationType

    c = RecoverableCluster(seed=802, n_storage_shards=2, storage_replication=2)
    db_py = c.database()
    py_results: list = []

    class _PyWrap:
        def __init__(self, tr):
            self.tr = tr

        def set(self, k, v):
            self.tr.set(k, v)

        def get(self, k):
            return c.run_until(c.loop.spawn(self.tr.get(k)), 300)

        def atomic_add(self, k, d):
            self.tr.atomic_op(
                MutationType.ADD, k, d.to_bytes(8, "little", signed=True)
            )

        def clear_range(self, b, e):
            self.tr.clear_range(b, e)

        def get_range(self, b, e):
            return c.run_until(c.loop.spawn(self.tr.get_range(b, e)), 300)

    _run_script(
        lambda: _PyWrap(db_py.create_ryw_transaction()),
        lambda w: c.run_until(c.loop.spawn(w.tr.commit()), 300),
        py_results,
    )
    c.stop()

    assert c_results == py_results


def test_server_entrypoint(clib):
    """The fdbserver-main analog: `python -m foundationdb_tpu.tools.server`
    boots a whole cluster + gateway; a compiled C client transacts
    against it."""
    import os
    import tempfile

    errf = tempfile.TemporaryFile(mode="w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.tools.server",
         "--port", "0", "--engine", "ssd", "--run-seconds", "60"],
        stdout=subprocess.PIPE, stderr=errf, text=True,
        env={"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
             "PATH": "/usr/bin:/bin", "PYTHONPATH": str(REPO)},
        cwd=str(REPO),
    )
    try:
        ready, _, _ = select.select([proc.stdout], [], [], 25.0)
        line = proc.stdout.readline() if ready else ""
        if "ready on" not in line:
            proc.kill()
            errf.seek(0)
            raise AssertionError(f"server never started: {errf.read()[-2000:]}")
        port = int(line.rsplit(":", 1)[1])
        r = subprocess.run(
            [str(CDIR / "ctest"), "127.0.0.1", str(port)],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, f"ctest vs server failed:\n{r.stdout}\n{r.stderr}"
        assert r.stdout.startswith("C-OK ")
    finally:
        proc.kill()
        proc.wait()
        errf.close()
