"""ssd-class B+tree storage engine (storage/btree.py): model-checked ops,
crash-window recovery, compaction safety, bounded memory, and the full
cluster running on storage_engine="ssd"
(reference: KeyValueStoreSQLite.actor.cpp / VersionedBTree.actor.cpp)."""

import random

from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
from foundationdb_tpu.storage.btree import BTreeKeyValueStore
from foundationdb_tpu.storage.files import SimFilesystem


def _fixture():
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(3))
    return loop, fs


def _crash(store):
    """Drop every unsynced buffer — the power-loss the files model."""
    for f in store._files:
        f._drop_unsynced()
    store._hdr.file._drop_unsynced()


def test_model_fuzz_with_crashes():
    loop, fs = _fixture()
    store = BTreeKeyValueStore(fs, "t", None, cache_bytes=2048)
    rng = random.Random(11)
    model: dict[bytes, bytes] = {}
    committed: dict[bytes, bytes] = {}

    def key():
        return bytes(rng.choice(b"abcdefgh") for _ in range(rng.randint(1, 5)))

    async def run():
        nonlocal store, model, committed
        for step in range(3000):
            op = rng.random()
            if op < 0.5:
                k = key()
                v = bytes(rng.choice(b"xyz") for _ in range(rng.randint(0, 6)))
                store.set(k, v)
                model[k] = v
            elif op < 0.62:
                a, b = sorted((key(), key()))
                store.clear_range(a, b)
                for k in [k for k in model if a <= k < b]:
                    del model[k]
            elif op < 0.72:
                k = key()
                assert store.get(k) == model.get(k)
            elif op < 0.82:
                a, b = sorted((key(), key()))
                want = sorted((k, v) for k, v in model.items() if a <= k < b)
                assert store.range_read(a, b, 1 << 30) == want
                assert store.count_range(a, b) == len(want)
                mid = store.middle_key(a, b)
                if mid is not None:
                    assert a <= mid < b
            elif op < 0.95:
                await store.commit({"durable_version": step})
                committed = dict(model)
            else:
                _crash(store)
                store = BTreeKeyValueStore.recover(fs, "t", None, cache_bytes=2048)
                model = dict(committed)
                assert store.meta.get("durable_version", 0) <= step
        assert store.range_read(b"", b"\xff" * 8, 1 << 30) == sorted(model.items())
        # parsed-page cache stays BYTE-bounded (a lone over-budget page is
        # the only allowed overhang — evicting it would thrash)
        assert store._cache_bytes <= 2048 or len(store._cache) == 1

    loop.run_until(loop.spawn(run()), 1e12)


def test_crash_between_data_and_header_sync_recovers_old_root():
    """The commit protocol's crash window: data pages synced, header not —
    recovery must see the PREVIOUS committed tree, never a torn one."""
    loop, fs = _fixture()
    store = BTreeKeyValueStore(fs, "t", None)

    async def run():
        nonlocal store
        store.set(b"a", b"1")
        store.set(b"b", b"2")
        await store.commit({"durable_version": 1})
        # second commit: stop after the data sync, before the header sync
        store.set(b"a", b"NEW")
        store.set(b"c", b"3")
        store._fold_memtable()
        root = store._write_branches()
        await store._files[store._file_id].sync()
        store._write_header(root)  # header REWRITTEN but not synced
        _crash(store)
        store = BTreeKeyValueStore.recover(fs, "t", None)
        assert store.get(b"a") == b"1"
        assert store.get(b"b") == b"2"
        assert store.get(b"c") is None
        assert store.meta["durable_version"] == 1

    loop.run_until(loop.spawn(run()), 1e12)


def test_crash_mid_compaction_keeps_old_tree():
    """Compaction writes the OTHER file; a crash before its header swap
    recovers the old epoch's tree untouched."""
    loop, fs = _fixture()
    store = BTreeKeyValueStore(fs, "t", None)

    async def run():
        nonlocal store
        for i in range(300):
            store.set(b"k%04d" % i, b"v%d" % i)
        await store.commit({"durable_version": 1})
        old_file = store._file_id
        # start a compaction but crash before its syncs land
        rows = list(store._tree_range(b"", b"\xff" * 8))
        other = 1 - store._file_id
        store._files[other].truncate()
        store._file_id = other
        store._cache.clear()
        store._dir_keys, store._dir_offs, store._dir_cnts = [], [], []
        store._replace_leaves(0, 0, rows)  # appended, never synced
        _crash(store)
        store = BTreeKeyValueStore.recover(fs, "t", None)
        assert store._file_id == old_file
        got = store.range_read(b"", b"\xff" * 8, 1 << 30)
        assert got == [(b"k%04d" % i, b"v%d" % i) for i in range(300)]

    loop.run_until(loop.spawn(run()), 1e12)


def test_compaction_bounds_file_growth():
    """Repeated overwrites trigger compaction; the data file does not grow
    without bound and contents stay exact."""
    loop, fs = _fixture()
    store = BTreeKeyValueStore(fs, "t", None)

    async def run():
        compacted = 0
        for round_ in range(40):
            for i in range(200):
                store.set(b"k%03d" % i, b"r%d" % round_)
            before = store._file_id
            await store.commit({"durable_version": round_})
            if store._file_id != before:
                compacted += 1
        assert compacted >= 1
        got = store.range_read(b"", b"\xff" * 8, 1 << 30)
        assert got == [(b"k%03d" % i, b"r39") for i in range(200)]
        total = sum(f.size() for f in store._files)
        assert total < 40 * 200 * 16  # far below sum-of-all-commits

    loop.run_until(loop.spawn(run()), 1e12)


def test_cluster_on_ssd_engine_survives_power_loss():
    """End-to-end: a durable cluster on the B+tree engine commits, powers
    off, restarts, and serves everything back."""
    from foundationdb_tpu.control.recoverable import RecoverableCluster

    c = RecoverableCluster(seed=301, n_storage_shards=2, storage_replication=2,
                           storage_engine="ssd")
    db = c.database()

    async def put():
        for base in range(0, 120, 40):
            tr = db.create_transaction()
            for i in range(base, base + 40):
                tr.set(b"s%04d" % i, b"v%d" % i)
            await tr.commit()
        await c.loop.delay(8.0)  # storage durability catches up (MVCC lag)

    c.run_until(c.loop.spawn(put()), 900)
    fs = c.power_off()
    c2 = RecoverableCluster(seed=302, n_storage_shards=2,
                            storage_replication=2, fs=fs, restart=True,
                            storage_engine="ssd")
    db2 = c2.database()

    async def readall():
        async def fn(tr):
            return await tr.get_range(b"s", b"t", limit=100000)

        return await db2.run(fn)

    rows = c2.run_until(c2.loop.spawn(readall()), 900)
    assert len(rows) == 120
    assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))
    c2.stop()
