"""tools/fdbmonitor.py — the process supervisor (fdbmonitor analog).

Unit tests drive Monitor.poll() directly against cheap `python -c`
children (no cluster, no TCP): conf parsing/inheritance, crash-restart
backoff and its reset, restart-disabled sections, hot-reload diffs
(including the nasty mid-backoff and mid-crash-loop cases), torn confs,
and the schema'd trace plane.  One real-fabric test boots a supervised
coordserver + fdbserver cluster, bounces the server under a live client,
and proves acked data survives (the rolling-bounce seam end to end)."""

from __future__ import annotations

import json
import os
import signal
import sys
import time

import pytest

from foundationdb_tpu.control.status import validate_monitor_event
from foundationdb_tpu.tools.fdbmonitor import (
    ConfError,
    Monitor,
    parse_conf,
)
from foundationdb_tpu.tools.soak import process_deaths, render_markdown

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def write_conf(path, body: str) -> None:
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, str(path))


def pump(mon: Monitor, until, timeout: float = 15.0, step: float = 0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        mon.poll()
        if until():
            return True
        time.sleep(step)
    return False


SLEEPER = f"command = {PY} -c \"import time; time.sleep(60)\""
CRASHER = f"command = {PY} -c \"raise SystemExit(3)\""


def base_conf(*sections: str) -> str:
    return "\n".join(
        [
            "[general]",
            "restart-delay = 0.05",
            "max-restart-delay = 0.4",
            "backoff-reset = 5",
            "conf-poll = 0.05",
            "kill-grace = 5",
            "",
        ]
        + list(sections)
    )


# -- conf parsing -------------------------------------------------------------


def test_parse_conf_inheritance_and_substitution(tmp_path):
    conf = tmp_path / "m.conf"
    write_conf(conf, "\n".join([
        "[general]",
        "restart-delay = 1",
        "[worker]",
        "command = prog serve",
        "port = $ID",
        "mode = shared",
        "env.COMMON = base",
        "[worker.4001]",
        "[worker.4002]",
        "mode = special",
        "env.EXTRA = $ID",
        "restart = false",
    ]))
    general, specs = parse_conf(str(conf))
    assert general["restart-delay"] == "1"
    assert sorted(specs) == ["worker.4001", "worker.4002"]
    s1, s2 = specs["worker.4001"], specs["worker.4002"]
    # $ID substitution + base/instance merge, instance keys winning
    assert s1.argv[:2] == ["prog", "serve"]
    assert ["--port", "4001"] == s1.argv[s1.argv.index("--port"):][:2]
    assert ["--mode", "shared"] == s1.argv[s1.argv.index("--mode"):][:2]
    assert ["--mode", "special"] == s2.argv[s2.argv.index("--mode"):][:2]
    # env.* keys become the child's env overlay, not argv
    assert s1.env == {"COMMON": "base"}
    assert s2.env == {"COMMON": "base", "EXTRA": "4002"}
    assert not any(a.startswith("--env") for a in s1.argv)
    # restart is a supervisor directive: parsed, never passed down
    assert s1.restart and not s2.restart
    assert "--restart" not in " ".join(s2.argv)


def test_parse_conf_ready_file_resolved_and_passed(tmp_path):
    conf = tmp_path / "m.conf"
    write_conf(conf, "\n".join([
        "[w]",
        "command = prog",
        "ready-file = run/w.$ID.ready",
        "[w.1]",
    ]))
    _, specs = parse_conf(str(conf))
    spec = specs["w.1"]
    # relative ready-file resolves against the CONF dir (children run
    # there; the supervisor may not) and is passed down as --ready-file
    assert spec.ready_file == str(tmp_path / "run" / "w.1.ready")
    i = spec.argv.index("--ready-file")
    assert spec.argv[i + 1] == spec.ready_file


def test_parse_conf_rejects_garbage(tmp_path):
    conf = tmp_path / "m.conf"
    write_conf(conf, "[w.1]\nport = 5\n")  # no command
    with pytest.raises(ConfError):
        parse_conf(str(conf))
    write_conf(conf, "not an ini at all [[[")
    with pytest.raises(ConfError):
        parse_conf(str(conf))
    write_conf(conf, "[general]\nrestart-delay = 1\n")  # no process sections
    with pytest.raises(ConfError):
        parse_conf(str(conf))


# -- supervision --------------------------------------------------------------


def make_monitor(tmp_path, *sections: str, status: bool = True) -> Monitor:
    conf = tmp_path / "m.conf"
    write_conf(conf, base_conf(*sections))
    mon = Monitor(
        str(conf),
        status_file=str(tmp_path / "status.json") if status else None,
    )
    mon.start()
    return mon


def test_crash_restart_backoff_and_disabled(tmp_path):
    mon = make_monitor(
        tmp_path,
        "[crash]", CRASHER, "[crash.1]", "",
        "[oneshot]", CRASHER, "restart = false", "[oneshot.1]",
    )
    try:
        # the crash-looping child is restarted with escalating delays
        crash = mon.children["crash.1"]
        assert pump(mon, lambda: crash.restarts >= 3)
        died = [e for e in mon.trace.events if e["Type"] == "ProcessDied"
                and e["Section"] == "crash.1"]
        delays = [e["RestartInS"] for e in died]
        assert delays[0] == pytest.approx(0.05, abs=0.01)
        # escalation doubles and caps at max-restart-delay
        assert all(b >= a for a, b in zip(delays, delays[1:]))
        assert max(delays) <= 0.4 + 1e-9
        assert all(e["ExitCode"] == 3 for e in died)
        # restart-disabled: exactly one death, stays dead
        one = mon.children["oneshot.1"]
        assert pump(mon, lambda: one.state() == "dead")
        dead_evs = [e for e in mon.trace.events if e["Type"] == "ProcessDied"
                    and e["Section"] == "oneshot.1"]
        assert len(dead_evs) == 1 and dead_evs[0]["RestartInS"] == -1.0
        mon.poll()
        assert one.state() == "dead"  # no resurrection on later polls
        # status file reflects both
        status = json.load(open(tmp_path / "status.json"))
        assert status["processes"]["oneshot.1"]["state"] == "dead"
        assert status["processes"]["crash.1"]["restarts"] >= 3
    finally:
        mon.shutdown()


def test_backoff_resets_after_stable_run(tmp_path):
    mon = make_monitor(tmp_path, "[w]", SLEEPER, "[w.1]")
    # stable-run threshold low enough for a test to cross it
    mon.knobs.MONITOR_BACKOFF_RESET = 0.3
    try:
        child = mon.children["w.1"]
        # two quick kills escalate the delay past the base
        for _ in range(2):
            pid = child.pid
            os.kill(pid, signal.SIGKILL)
            assert pump(mon, lambda: child.pid not in (None, pid)
                        and child.proc is not None)
        assert child.delay > mon.knobs.MONITOR_RESTART_BACKOFF
        # now let it run past the stability window, then kill again:
        # the NEXT restart must use the base delay, not the escalated one
        time.sleep(0.35)
        pid = child.pid
        os.kill(pid, signal.SIGKILL)
        assert pump(mon, lambda: any(
            e["Type"] == "ProcessDied" and e["Pid"] == pid
            for e in mon.trace.events))
        last = [e for e in mon.trace.events
                if e["Type"] == "ProcessDied" and e["Pid"] == pid][-1]
        assert last["RestartInS"] == pytest.approx(0.05, abs=0.01)
    finally:
        mon.shutdown()


def test_hot_reload_add_remove_change(tmp_path):
    conf = tmp_path / "m.conf"
    mon = make_monitor(tmp_path, "[w]", SLEEPER, "[w.1]")
    try:
        keeper_pid = mon.children["w.1"].pid
        # ADD a section: exactly the new child starts
        write_conf(conf, base_conf("[w]", SLEEPER, "[w.1]", "[w.2]"))
        assert pump(mon, lambda: "w.2" in mon.children
                    and mon.children["w.2"].proc is not None)
        assert mon.children["w.1"].pid == keeper_pid  # untouched by contract
        # CHANGE w.2's argv: bounced now, with a new pid
        pid2 = mon.children["w.2"].pid
        write_conf(conf, base_conf(
            "[w]", SLEEPER, "[w.1]", "[w.2]",
            f"command = {PY} -c \"import time; time.sleep(61)\""))
        assert pump(mon, lambda: mon.children["w.2"].pid not in (None, pid2))
        assert mon.children["w.1"].pid == keeper_pid
        # REMOVE w.2: stopped and forgotten
        write_conf(conf, base_conf("[w]", SLEEPER, "[w.1]"))
        assert pump(mon, lambda: "w.2" not in mon.children)
        assert mon.children["w.1"].pid == keeper_pid
        reloads = [e for e in mon.trace.events if e["Type"] == "ConfReloaded"]
        assert [r["Added"] for r in reloads] == ["w.2", "", ""]
        assert [r["Removed"] for r in reloads] == ["", "", "w.2"]
        assert [r["Changed"] for r in reloads] == ["", "w.2", ""]
    finally:
        mon.shutdown()


def test_hot_reload_remove_during_backoff(tmp_path):
    conf = tmp_path / "m.conf"
    mon = make_monitor(tmp_path, "[w]", SLEEPER, "[w.1]", "",
                       "[crash]", CRASHER, "[crash.1]")
    mon.knobs.MONITOR_RESTART_BACKOFF = 2.0  # park the crasher in backoff
    try:
        crash = mon.children["crash.1"]
        assert pump(mon, lambda: crash.state() == "backoff")
        # removing a section whose child is mid-backoff just forgets the
        # pending restart — nothing to kill, nothing respawns later
        write_conf(conf, base_conf("[w]", SLEEPER, "[w.1]"))
        assert pump(mon, lambda: "crash.1" not in mon.children)
        deaths_before = sum(1 for e in mon.trace.events
                            if e["Type"] == "ProcessDied")
        time.sleep(0.15)
        mon.poll()
        deaths_after = sum(1 for e in mon.trace.events
                           if e["Type"] == "ProcessDied")
        assert deaths_after == deaths_before
    finally:
        mon.shutdown()


def test_hot_reload_param_change_during_crash_loop(tmp_path):
    conf = tmp_path / "m.conf"
    marker = tmp_path / "fixed.marker"
    mon = make_monitor(tmp_path, "[crash]", CRASHER, "[crash.1]")
    mon.knobs.MONITOR_RESTART_BACKOFF = 0.3  # stay in backoff long enough
    try:
        crash = mon.children["crash.1"]
        assert pump(mon, lambda: crash.state() == "backoff")
        # the operator fixes the command while the child is in backoff:
        # the ALREADY-SCHEDULED restart must pick up the new argv
        write_conf(conf, base_conf(
            "[crash]",
            f"command = {PY} -c \"import sys, time; "
            f"open({str(marker)!r}, 'w').close(); time.sleep(60)\"",
            "[crash.1]",
        ))
        assert pump(mon, lambda: marker.exists() and crash.proc is not None)
        assert crash.state() == "running"
    finally:
        mon.shutdown()


def test_torn_conf_keeps_last_good(tmp_path):
    conf = tmp_path / "m.conf"
    mon = make_monitor(tmp_path, "[w]", SLEEPER, "[w.1]")
    try:
        pid = mon.children["w.1"].pid
        # a torn write (half an ini) must not kill the world: the last
        # good conf stays in force and the bad content traces ONCE
        write_conf(conf, "[w]\ncommand = ")
        assert pump(mon, lambda: any(
            e["Type"] == "MonitorConfInvalid" for e in mon.trace.events))
        n = sum(1 for e in mon.trace.events
                if e["Type"] == "MonitorConfInvalid")
        for _ in range(5):
            mon.poll()
            time.sleep(0.02)
        assert sum(1 for e in mon.trace.events
                   if e["Type"] == "MonitorConfInvalid") == n
        assert mon.children["w.1"].pid == pid
        assert mon.children["w.1"].state() == "running"
        # the repaired conf reloads normally
        write_conf(conf, base_conf("[w]", SLEEPER, "[w.1]", "[w.2]"))
        assert pump(mon, lambda: "w.2" in mon.children)
        assert mon.children["w.1"].pid == pid
    finally:
        mon.shutdown()


def test_sighup_triggers_reload_and_events_validate(tmp_path):
    conf = tmp_path / "m.conf"
    mon = make_monitor(tmp_path, "[w]", SLEEPER, "[w.1]")
    try:
        # SIGHUP path: the flag forces a reload even with identical bytes
        mon._hup = True
        mon.poll()
        assert any(e["Type"] == "ConfReloaded" for e in mon.trace.events)
        os.kill(mon.children["w.1"].pid, signal.SIGKILL)
        assert pump(mon, lambda: any(
            e["Type"] == "ProcessDied" for e in mon.trace.events))
        write_conf(conf, "totally [[ torn")
        assert pump(mon, lambda: any(
            e["Type"] == "MonitorConfInvalid" for e in mon.trace.events))
    finally:
        mon.shutdown()
    # every event the supervisor ever emits is schema-valid — and this
    # run covered started/died/restarted/stopped/reloaded/invalid/stopped
    types = {e["Type"] for e in mon.trace.events}
    assert {"MonitorStarted", "ProcessStarted", "ProcessDied",
            "ConfReloaded", "MonitorConfInvalid", "ProcessStopped",
            "MonitorStopped"} <= types
    for e in mon.trace.events:
        validate_monitor_event(e)


def test_spawn_failure_backs_off(tmp_path):
    mon = make_monitor(
        tmp_path, "[w]", "command = /nonexistent/binary-xyzzy", "[w.1]")
    try:
        child = mon.children["w.1"]
        assert child.proc is None
        assert pump(mon, lambda: sum(
            1 for e in mon.trace.events
            if e["Type"] == "ProcessSpawnFailed") >= 2)
        assert child.state() == "backoff"
    finally:
        mon.shutdown()


def test_soak_folds_process_deaths(tmp_path):
    mon = make_monitor(tmp_path, "[crash]", CRASHER, "[crash.1]", "",
                       "[oneshot]", CRASHER, "restart = false",
                       "[oneshot.1]")
    try:
        assert pump(mon, lambda: mon.children["crash.1"].restarts >= 2
                    and mon.children["oneshot.1"].state() == "dead")
    finally:
        mon.shutdown()
    rows = process_deaths(list(mon.trace.events))
    by_sec = {r["section"]: r for r in rows}
    assert by_sec["crash.1"]["deaths"] >= 2
    assert by_sec["crash.1"]["last_exit_code"] == 3
    assert not by_sec["crash.1"]["restart_disabled"]
    assert by_sec["oneshot.1"]["restart_disabled"]
    # most-deaths-first ordering feeds the triage report
    assert rows[0]["section"] == "crash.1"
    md = render_markdown({
        "spec": "monitor-fold", "seeds": [0], "jobs": 1, "wall_s": 0.0,
        "ok": False,
        "verdicts": {"pass": 0, "fail": 1, "timeout": 0, "crash": 0},
        "coverage": {"required": [], "missing_required": [],
                     "merged": {"buggify": {}, "testcov": {}}},
        "per_seed": [{"seed": 0, "verdict": "fail", "wall_s": 0.0,
                      "error": "x",
                      "triage": {"process_deaths": rows}}],
    })
    assert "supervised process deaths (fdbmonitor)" in md
    assert "restart disabled, stayed dead" in md


# -- the real fabric ----------------------------------------------------------


def test_supervised_cluster_server_bounce(tmp_path):
    """End-to-end rolling-bounce seam on real TCP: a supervised
    coordserver + fdbserver cluster; the server is SIGTERMed under a live
    gateway client and acked data must survive the bounce (restart image
    + durable coordinator registers + client reconnect)."""
    import socket

    from foundationdb_tpu.client.gateway_client import GatewayClient
    from foundationdb_tpu.client.cluster_file import write_cluster_file
    from foundationdb_tpu.rpc.network import NetworkAddress

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    coord_port, gw_port = free_port(), free_port()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
    os.environ["PYTHONPATH"] = (
        REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    write_cluster_file(str(tmp_path / "fdb.cluster"),
                       [NetworkAddress("127.0.0.1", coord_port)])
    conf = tmp_path / "m.conf"
    write_conf(conf, "\n".join([
        "[general]",
        "restart-delay = 0.25",
        "conf-poll = 0.2",
        "kill-grace = 20",
        "logdir = logs",
        "",
        "[coordserver]",
        f"command = {PY} -m foundationdb_tpu.tools.coordserver",
        "port = $ID",
        "run-seconds = 300",
        "ready-file = logs/coord.$ID.ready",
        "store-dir = logs/coord.$ID.store",
        f"[coordserver.{coord_port}]",
        "",
        "[fdbserver]",
        f"command = {PY} -m foundationdb_tpu.tools.server",
        "port = $ID",
        "cluster-file = fdb.cluster",
        "shards = 1",
        "replication = 1",
        "workers = 0",
        "engine = memory",
        "image-dir = image",
        "ready-file = logs/server.ready",
        "run-seconds = 300",
        f"[fdbserver.{gw_port}]",
    ]))
    mon = Monitor(str(conf), status_file=str(tmp_path / "status.json"))
    mon.start()
    try:
        assert pump(mon, lambda: all(
            mon._ready(c) for c in mon.children.values()), timeout=120.0)
        db = GatewayClient("127.0.0.1", gw_port, timeout=30.0,
                           reconnect_window=60.0)
        try:
            db.run(lambda tr: tr.set(b"bounce/k", b"v1"))
            server = mon.children[f"fdbserver.{gw_port}"]
            pid = server.pid
            os.kill(pid, signal.SIGTERM)
            assert pump(mon, lambda: server.pid not in (None, pid)
                        and mon._ready(server), timeout=120.0)
            # the SAME client rides its reconnect path across the bounce;
            # the acked write survived via the restart image
            assert db.run(lambda tr: tr.get(b"bounce/k")) == b"v1"
            db.run(lambda tr: tr.set(b"bounce/k2", b"v2"))
            assert db.read(lambda tr: tr.get(b"bounce/k2")) == b"v2"
        finally:
            db.close()
        died = [e for e in mon.trace.events if e["Type"] == "ProcessDied"]
        assert [e["Section"] for e in died] == [f"fdbserver.{gw_port}"]
        for e in mon.trace.events:
            validate_monitor_event(e)
    finally:
        mon.shutdown()
    # shutdown stopped everything: no stray children
    assert all(c.proc is None for c in mon.children.values())
