"""Parity tests: DeviceConflictSet vs OracleConflictSet.

The oracle is the abort-set referee (port of SlowConflictSet semantics,
reference fdbserver/SkipList.cpp:59-88); the device kernel must produce
bit-identical verdicts on randomized batches — the ConflictRange-workload
discipline (reference fdbserver/workloads/ConflictRange.actor.cpp) applied
at the ConflictSet seam.
"""

import random

import pytest

from foundationdb_tpu.conflict.api import TxInfo, Verdict
from foundationdb_tpu.conflict.device import DeviceConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet


def _rand_key(rng: random.Random, alphabet: bytes = b"abc", max_len: int = 5) -> bytes:
    return bytes(rng.choice(alphabet) for _ in range(rng.randrange(max_len + 1)))


def _rand_range(rng: random.Random) -> tuple[bytes, bytes]:
    if rng.random() < 0.5:  # point range [k, k+\0)
        k = _rand_key(rng)
        return k, k + b"\x00"
    a, b = sorted((_rand_key(rng), _rand_key(rng)))
    return a, b + b"\x00"  # ensure non-empty


def _rand_batch(rng: random.Random, version: int, oldest: int, n: int) -> list[TxInfo]:
    txns = []
    for _ in range(n):
        # snapshots spread across the window, some below oldest (TOO_OLD)
        lo = max(oldest - 3, 0)
        snap = rng.randrange(lo, version)
        txns.append(
            TxInfo(
                read_snapshot=snap,
                read_ranges=[_rand_range(rng) for _ in range(rng.randrange(4))],
                write_ranges=[_rand_range(rng) for _ in range(rng.randrange(3))],
            )
        )
    return txns


@pytest.mark.parametrize("seed", range(8))
def test_randomized_parity(seed):
    rng = random.Random(seed)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(capacity=1 << 10)
    version = 0
    for _ in range(25):
        version += rng.randrange(1, 8)
        txns = _rand_batch(rng, version, oracle.oldest_version, rng.randrange(1, 14))
        want = oracle.resolve_batch(version, txns)
        got = dev.resolve_batch(version, txns)
        assert got == want, f"seed={seed} version={version}"
        if rng.random() < 0.3:
            floor = rng.randrange(version + 1)
            oracle.remove_before(floor)
            dev.remove_before(floor)
            assert dev.oldest_version == oracle.oldest_version


def test_intra_batch_chain():
    """t0 commits; t1 conflicts with t0; t2 reads what t1 would have written
    and must COMMIT (conflicted txns' writes are invisible — the
    order-dependence of SkipList.cpp:1139-1152)."""
    dev = DeviceConflictSet()
    r = lambda k: (k, k + b"\x00")
    txns = [
        TxInfo(read_snapshot=0, read_ranges=[], write_ranges=[r(b"a")]),
        TxInfo(read_snapshot=0, read_ranges=[r(b"a")], write_ranges=[r(b"b")]),
        TxInfo(read_snapshot=0, read_ranges=[r(b"b")], write_ranges=[r(b"c")]),
        TxInfo(read_snapshot=0, read_ranges=[r(b"c")], write_ranges=[]),
    ]
    got = dev.resolve_batch(5, txns)
    assert got == [
        Verdict.COMMITTED,  # t0
        Verdict.CONFLICT,  # t1: reads a, written by committed t0
        Verdict.COMMITTED,  # t2: t1 aborted, so b unwritten
        Verdict.CONFLICT,  # t3: reads c, written by committed t2
    ]


def test_history_and_window():
    dev = DeviceConflictSet()
    r = lambda k: (k, k + b"\x00")
    assert dev.resolve_batch(
        10, [TxInfo(read_snapshot=0, read_ranges=[], write_ranges=[r(b"k")])]
    ) == [Verdict.COMMITTED]
    # snapshot before the write => conflict; at/after => commit
    got = dev.resolve_batch(
        20,
        [
            TxInfo(read_snapshot=5, read_ranges=[r(b"k")], write_ranges=[]),
            TxInfo(read_snapshot=10, read_ranges=[r(b"k")], write_ranges=[]),
        ],
    )
    assert got == [Verdict.CONFLICT, Verdict.COMMITTED]
    dev.remove_before(15)
    got = dev.resolve_batch(
        30,
        [
            TxInfo(read_snapshot=5, read_ranges=[], write_ranges=[]),  # too old
            TxInfo(read_snapshot=15, read_ranges=[r(b"k")], write_ranges=[]),
        ],
    )
    assert got == [Verdict.TOO_OLD, Verdict.COMMITTED]


def test_capacity_regrowth():
    """Overflowing the boundary array regrows and replays transparently."""
    rng = random.Random(7)
    oracle = OracleConflictSet()
    # legacy (full-merge) path: regrowth-on-overflow is its mechanism;
    # the incremental path absorbs the same batches as runs (see
    # test_pallas.py for the compaction-regrow twin)
    dev = DeviceConflictSet(capacity=16, incremental=False)
    version = 0
    for _ in range(4):
        version += 5
        # many distinct point writes => boundary count far above 16
        txns = [
            TxInfo(
                read_snapshot=version - 5,
                read_ranges=[_rand_range(rng)],
                write_ranges=[(k := _rand_key(rng, b"abcdefgh", 6), k + b"\x00")],
            )
            for _ in range(24)
        ]
        assert dev.resolve_batch(version, txns) == oracle.resolve_batch(version, txns)
    assert dev.capacity > 16


def test_wide_ranges_parity():
    rng = random.Random(99)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(capacity=1 << 10)
    version = 0
    for _ in range(10):
        version += 3
        txns = [
            TxInfo(
                read_snapshot=max(version - rng.randrange(1, 6), 0),
                read_ranges=[(b"", b"\xff")] if rng.random() < 0.4 else [_rand_range(rng)],
                write_ranges=[_rand_range(rng)],
            )
            for _ in range(6)
        ]
        assert dev.resolve_batch(version, txns) == oracle.resolve_batch(version, txns)


def test_shared_prefix_search_fallback():
    """Adversarial batch: >2**FAST_SEARCH_ITERS boundaries share one
    word0-prefix bucket, so the fast bucketed search cannot converge and the
    sync path must replay at full depth (device.py resolve_arrays fallback).
    Verdicts must still match the oracle exactly."""
    from foundationdb_tpu.conflict.device import DeviceConflictSet

    # the bucketed search is the impl with the depth fallback; the sort
    # search is exact at any depth and never needs one
    dev = DeviceConflictSet(capacity=1 << 14, search_impl="bucket",
                            incremental=False)
    ref = OracleConflictSet()

    # 3000 distinct point writes, all sharing the 2-byte prefix ZZ: their
    # ~6000 endpoint boundaries all land in one 16-bit prefix bucket
    keys = [b"ZZ%04d" % i for i in range(3000)]
    fill = [TxInfo(0, [], [(k, k + b"\x00")]) for k in keys]
    assert dev.resolve_batch(10, fill) == ref.resolve_batch(10, fill)
    assert dev.search_fallbacks == 0  # state was shallow during the insert

    # now any read into that bucket needs a deeper-than-2**11 window
    probes = [
        TxInfo(5, [(b"ZZ1500", b"ZZ1501")], [(b"q", b"q\x00")]),
        TxInfo(5, [(b"ZZ0001", b"ZZ2999")], []),
        TxInfo(5, [(b"yy", b"yz")], [(b"ZZ2000", b"ZZ2000\x00")]),
    ]
    got = dev.resolve_batch(20, probes)
    want = ref.resolve_batch(20, probes)
    assert got == want
    assert dev.search_fallbacks >= 1, "full-depth replay never engaged"


def test_pipelined_deferred_failure_replays_through_sync():
    """A pipelined (sync=False) stream hits the adversarial shared-prefix
    case: the deferred convergence check must fail at drain time, and
    replaying the same host-side TxInfo stream through sync resolves on a
    fresh instance must produce oracle-exact verdicts (the documented
    recovery contract of check_pipelined)."""
    import numpy as np

    import pytest

    from foundationdb_tpu.conflict.device import DeviceConflictSet, pack_batch

    keys = [b"ZZ%04d" % i for i in range(3000)]
    stream = [
        (10, [TxInfo(0, [], [(k, k + b"\x00")]) for k in keys]),
        (20, [TxInfo(5, [(b"ZZ1500", b"ZZ1501")], [(b"q", b"q\x00")]),
              TxInfo(5, [(b"ZZ0001", b"ZZ2999")], [])]),
    ]

    dev = DeviceConflictSet(capacity=1 << 14, search_impl="bucket",
                            incremental=False)
    for v, txns in stream:
        packed = pack_batch(txns, dev.oldest_version, dev._offset, dev._max_key_bytes)
        dev.resolve_arrays(v, *packed[:-1], sync=False)
    with pytest.raises(RuntimeError, match="deferred"):
        dev.check_pipelined()

    # recovery: replay the stream sync on a fresh set; parity vs oracle
    fresh = DeviceConflictSet(capacity=1 << 14, search_impl="bucket",
                              incremental=False)
    ref = OracleConflictSet()
    for v, txns in stream:
        assert fresh.resolve_batch(v, txns) == ref.resolve_batch(v, txns)
    assert fresh.search_fallbacks >= 1


def test_regrow_preserves_pending_pipelined_failure():
    """A capacity regrow (sync path) must NOT reset the pipelined-stream
    validity accumulator: a deferred failure recorded before the regrow
    still surfaces at the next check_pipelined()."""
    import pytest

    from foundationdb_tpu.conflict.device import DeviceConflictSet, pack_batch

    dev = DeviceConflictSet(capacity=1 << 14, search_impl="bucket",
                            incremental=False)

    def packed(txns):
        return pack_batch(txns, dev.oldest_version, dev._offset, dev._max_key_bytes)[:-1]

    # batch 1 (pipelined, converges): fill one prefix bucket deep
    keys = [b"ZZ%04d" % i for i in range(3000)]
    dev.resolve_arrays(10, *packed([TxInfo(0, [], [(k, k + b"\x00")]) for k in keys]), sync=False)
    # batch 2 (pipelined): probes the deep bucket -> deferred non-convergence
    dev.resolve_arrays(
        20, *packed([TxInfo(5, [(b"ZZ1500", b"ZZ1501")], [(b"q", b"q\x00")])]), sync=False
    )
    # batch 3 (sync): a mass insert that overflows capacity and regrows.
    # 6000 more distinct prefixes pushes the boundary count past 2**14.
    more = [b"YY%04d" % i for i in range(6000)]
    dev.resolve_batch(30, [TxInfo(25, [], [(k, k + b"\x00")]) for k in more])
    assert dev.capacity > (1 << 14), "test setup: regrow never happened"
    with pytest.raises(RuntimeError, match="deferred"):
        dev.check_pipelined()


def test_merge_impl_parity_scatter_vs_sort():
    """The scatter and sort merge implementations must produce identical
    verdict streams AND identical post-merge state (count + probing reads)
    on a randomized workload including range writes and GC."""
    import random

    from foundationdb_tpu.conflict.device import DeviceConflictSet

    rng = random.Random(77)

    def rand_key():
        return bytes(rng.randrange(6) for _ in range(rng.randrange(1, 8)))

    def rand_range():
        a, b = rand_key(), rand_key()
        if a == b:
            b = a + b"\x00"
        return (min(a, b), max(a, b))

    a = DeviceConflictSet(capacity=1 << 10, merge_impl="scatter")
    b = DeviceConflictSet(capacity=1 << 10, merge_impl="sort")
    v = 0
    for i in range(15):
        v += rng.randrange(3, 30)
        txns = [
            TxInfo(
                max(v - rng.randrange(1, 50), 0),
                [rand_range() for _ in range(rng.randrange(0, 3))],
                [rand_range() for _ in range(rng.randrange(0, 3))],
            )
            for _ in range(rng.randrange(1, 12))
        ]
        va = a.resolve_batch(v, txns)
        vb = b.resolve_batch(v, txns)
        assert va == vb, f"batch {i}: verdict divergence {va} vs {vb}"
        assert a.boundary_count == b.boundary_count, f"batch {i}: state count drift"
        if i == 8:
            a.remove_before(v - 20)
            b.remove_before(v - 20)
    import numpy as np

    assert np.array_equal(np.asarray(a._ks), np.asarray(b._ks))
    assert np.array_equal(np.asarray(a._vs), np.asarray(b._vs))


@pytest.mark.parametrize("seed", range(6))
def test_merge_impl_parity_gather(seed):
    """The gather merge (scatter-free, full-sort-free) must match the sort
    merge bit-for-bit: verdicts, state count, and the state arrays —
    including range writes, duplicate/adjacent ranges, equal begin/end
    keys, GC, and capacity-regrow overflow."""
    import random

    import numpy as np

    from foundationdb_tpu.conflict.device import DeviceConflictSet

    rng = random.Random(9000 + seed)

    def rand_key():
        return bytes(rng.randrange(4) for _ in range(rng.randrange(1, 6)))

    def rand_range():
        if rng.random() < 0.4:
            k = rand_key()
            return (k, k + b"\x00")
        a, b = rand_key(), rand_key()
        if a == b:
            b = a + b"\x00"
        return (min(a, b), max(a, b))

    a = DeviceConflictSet(capacity=1 << 8, merge_impl="sort")
    b = DeviceConflictSet(capacity=1 << 8, merge_impl="gather")
    v = 0
    for i in range(20):
        v += rng.randrange(3, 20)
        txns = [
            TxInfo(
                max(v - rng.randrange(1, 40), 0),
                [rand_range() for _ in range(rng.randrange(0, 3))],
                [rand_range() for _ in range(rng.randrange(0, 4))],
            )
            for _ in range(rng.randrange(1, 10))
        ]
        va = a.resolve_batch(v, txns)
        vb = b.resolve_batch(v, txns)
        assert va == vb, f"seed {seed} batch {i}: {va} vs {vb}"
        assert a.boundary_count == b.boundary_count, f"seed {seed} batch {i}"
        if rng.random() < 0.25:
            a.remove_before(v - 10)
            b.remove_before(v - 10)
    assert np.array_equal(np.asarray(a._ks), np.asarray(b._ks))
    assert np.array_equal(np.asarray(a._vs), np.asarray(b._vs))


def test_lsm_gather_merge_parity_with_oracle():
    """End-to-end: the LSM state with the gather merge against the oracle
    (compactions folding gather-built recent levels into main)."""
    import random

    rng = random.Random(91)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(
        capacity=1 << 9, lsm=True, recent_capacity=64,
        merge_impl="gather",
    )
    version = 0
    for i in range(30):
        version += rng.randrange(1, 6)
        txns = _rand_batch(rng, version, oracle.oldest_version, rng.randrange(1, 10))
        want = oracle.resolve_batch(version, txns)
        got = dev.resolve_batch(version, txns)
        assert got == want, f"version={version}"
        if i == 15:
            # explicitly fold a gather-built recent level into main and
            # keep checking parity on the compacted state
            dev._compact()
    assert dev.compactions >= 1


# ---------------------------------------------------------------------------
# LSM (two-level) state: the TPU-fast path — per-batch merges go into a
# small recent level, compactions fold it into main (device.py
# resolve_core_lsm / compact_lsm).


@pytest.mark.parametrize("seed", range(4))
def test_lsm_randomized_parity(seed):
    """LSM twin of the randomized parity suite, with a tiny recent level so
    compactions (and main regrowth) happen constantly mid-stream."""
    rng = random.Random(1000 + seed)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(capacity=1 << 8, lsm=True, recent_capacity=64)
    version = 0
    for _ in range(25):
        version += rng.randrange(1, 8)
        txns = _rand_batch(rng, version, oracle.oldest_version, rng.randrange(1, 14))
        want = oracle.resolve_batch(version, txns)
        got = dev.resolve_batch(version, txns)
        assert got == want, f"seed={seed} version={version}"
        if rng.random() < 0.3:
            floor = rng.randrange(version + 1)
            oracle.remove_before(floor)
            dev.remove_before(floor)
    # fold whatever recent holds and check parity still holds afterwards
    dev._compact()
    version += 1
    txns = _rand_batch(rng, version, oracle.oldest_version, 8)
    assert oracle.resolve_batch(version, txns) == dev.resolve_batch(version, txns)


def test_lsm_pipelined_parity_with_compactions():
    """sync=False streaming through compactions: deferred checks stay green
    and verdicts match the oracle batch-for-batch."""
    import numpy as np

    from foundationdb_tpu.conflict.device import pack_batch

    rng = random.Random(77)
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(capacity=1 << 9, lsm=True, recent_capacity=128)
    version = 0
    pending = []
    for i in range(40):
        version += rng.randrange(1, 5)
        txns = _rand_batch(rng, version, oracle.oldest_version, rng.randrange(1, 10))
        want = oracle.resolve_batch(version, txns)
        packed = pack_batch(txns, dev._oldest, dev._offset, dev._max_key_bytes)
        got_dev = dev.resolve_arrays(version, *packed[:8], sync=False)
        pending.append((got_dev, want, len(txns)))
        if i % 13 == 12:
            dev.check_pipelined()
    dev.check_pipelined()
    for got_dev, want, B in pending:
        got = [Verdict(int(c)) for c in np.asarray(got_dev)[:B]]
        assert got == want


def test_lsm_gc_clamps_all_levels():
    """remove_before must clamp main, its cached RMQ table, and recent —
    a read below the new floor is TOO_OLD, and history semantics survive."""
    oracle = OracleConflictSet()
    dev = DeviceConflictSet(capacity=1 << 8, lsm=True, recent_capacity=64)
    for v, key in [(5, b"a"), (10, b"b"), (15, b"c")]:
        txns = [TxInfo(read_snapshot=v - 1, read_ranges=[],
                       write_ranges=[(key, key + b"\x00")])]
        assert oracle.resolve_batch(v, txns) == dev.resolve_batch(v, txns)
    oracle.remove_before(8)
    dev.remove_before(8)
    txns = [
        # snapshot below floor: TOO_OLD
        TxInfo(read_snapshot=7, read_ranges=[(b"a", b"b")], write_ranges=[]),
        # reads b (written at 10 > snap 9): conflict
        TxInfo(read_snapshot=9, read_ranges=[(b"b", b"b\x00")], write_ranges=[]),
        # reads a (clamped history, snap 9 >= floor): commits
        TxInfo(read_snapshot=9, read_ranges=[(b"a", b"a\x00")], write_ranges=[]),
    ]
    assert oracle.resolve_batch(20, txns) == dev.resolve_batch(20, txns)
