"""Multi-OS-process cluster deployment: coordinators as separate OS
processes over real TCP, a server process hosting the cluster, and a
client connecting via the cluster-file bootstrap (MonitorLeader analog —
fdbclient/MonitorLeader.actor.cpp:435; fdbserver coordinationServer).

Five OS processes total: 3 coordinators + 1 server + this test as the
client.  A coordinator is killed mid-run; the quorum of two carries on."""

from __future__ import annotations

import os
import queue
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "PALLAS_AXON_POOL_IPS": "",  # skip the TPU-tunnel plugin: CPU-only procs
    "JAX_PLATFORMS": "cpu",
}


class Proc:
    def __init__(self, *mod_args: str) -> None:
        self.p = subprocess.Popen(
            [sys.executable, "-m", *mod_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=ENV, cwd=REPO,
        )
        self.lines: queue.Queue[str] = queue.Queue()
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self) -> None:
        for line in self.p.stdout:
            self.lines.put(line)

    def wait_line(self, needle: str, timeout: float = 60.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                line = self.lines.get(timeout=0.5)
            except queue.Empty:
                if self.p.poll() is not None:
                    raise RuntimeError(
                        f"process exited rc={self.p.returncode} before {needle!r}"
                    )
                continue
            if needle in line:
                return line
        raise TimeoutError(f"never saw {needle!r}")

    def kill(self) -> None:
        self.p.kill()
        self.p.wait()


def test_cluster_file_bootstrap_and_coordinator_kill(tmp_path):
    from foundationdb_tpu.client.cluster_file import write_cluster_file
    from foundationdb_tpu.client.gateway_client import open_cluster
    from foundationdb_tpu.rpc.network import NetworkAddress

    coords: list[Proc] = []
    server: Proc | None = None
    try:
        addrs = []
        for _ in range(3):
            c = Proc("foundationdb_tpu.tools.coordserver", "--run-seconds", "240")
            line = c.wait_line("coordinator ready on")
            hostport = line.strip().rsplit(" ", 1)[1]
            ip, _, port = hostport.rpartition(":")
            addrs.append(NetworkAddress(ip, int(port)))
            coords.append(c)

        cf = str(tmp_path / "fdb.cluster")
        write_cluster_file(cf, addrs)

        server = Proc(
            "foundationdb_tpu.tools.server",
            "--cluster-file", cf,
            "--shards", "1", "--replication", "1", "--workers", "0",
            "--engine", "memory", "--run-seconds", "240",
        )
        server.wait_line("fdbtpu server ready on", timeout=120.0)

        # client: coordinator discovery via the cluster file ONLY (no port
        # was passed to this test code path)
        db = open_cluster(cf, timeout=30.0)
        assert db.protocol_version() >= 1

        # Cycle: a ring of N pointers; each txn atomically advances one
        # link — the ring-sum invariant must hold at every read
        N = 5
        with db.transaction() as tr:
            for i in range(N):
                tr.set(b"cyc%d" % i, b"%d" % ((i + 1) % N))

        def cycle_step(k1: int, k2: int):
            def fn(tr):
                a = tr.get(b"cyc%d" % k1)
                b = tr.get(b"cyc%d" % k2)
                tr.set(b"cyc%d" % k1, b)
                tr.set(b"cyc%d" % k2, a)
            db.run(fn)

        for i in range(6):
            cycle_step(i % N, (i + 2) % N)

        # kill one coordinator: quorum of 2/3 still stands, commits flow
        coords[0].kill()
        for i in range(6):
            cycle_step((i + 1) % N, (i + 3) % N)

        def check(tr):
            vals = [tr.get(b"cyc%d" % i) for i in range(N)]
            return sorted(int(v) for v in vals)

        # the ring's values are a permutation of 0..N-1 throughout
        assert db.read(check) == list(range(N))

        # raw-field ops over the wire: atomic_add and a limited get_range
        for _ in range(3):
            db.run(lambda tr: tr.atomic_add(b"ctr", 2))
        rows = db.read(lambda tr: tr.get_range(b"cyc", b"cyd", limit=3))
        assert len(rows) == 3 and rows[0][0] == b"cyc0"
        ctr = db.read(lambda tr: tr.get(b"ctr"))
        assert int.from_bytes(ctr, "little", signed=True) == 6

        # a FRESH client can still discover through the surviving quorum
        db2 = open_cluster(cf, timeout=30.0)
        assert db2.read(check) == list(range(N))
        db2.close()
        db.close()
    finally:
        for c in coords:
            c.kill()
        if server is not None:
            server.kill()
