"""ManagementAPI + system keyspace: live reconfiguration of pipeline role
counts through \\xff/conf (fdbclient/ManagementAPI.actor.cpp changeConfig)."""

import pytest

from foundationdb_tpu.client.management import configure, get_configuration
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.cycle import CycleWorkload


def test_configure_changes_live_cluster():
    c = RecoverableCluster(seed=131, n_tlogs=2, n_proxies=2, n_resolvers=1)
    db = c.database()

    async def main():
        # write some data first: the reconfiguration recovery must keep it
        tr = db.create_transaction()
        for i in range(10):
            tr.set(b"pre%d" % i, b"v")
        await tr.commit()
        await configure(db, n_tlogs=3, n_proxies=1, n_resolvers=2)
        # wait for the controller to notice and re-recruit
        for _ in range(200):
            await c.loop.delay(0.1)
            gen = c.controller.generation
            if (
                gen is not None
                and not c.controller._recovering
                and len(gen.tlogs) == 3
                and len(gen.proxies) == 1
                and len(gen.resolvers) == 2
            ):
                break
        gen = c.controller.generation
        conf = await get_configuration(db)
        tr = db.create_transaction()
        rows = await tr.get_range(b"pre", b"prf")
        tr2 = db.create_transaction()
        tr2.set(b"post", b"alive")
        await tr2.commit()
        return (
            len(gen.tlogs), len(gen.proxies), len(gen.resolvers), conf, len(rows)
        )

    nt, np_, nr, conf, nrows = c.run_until(c.loop.spawn(main()), 300)
    assert (nt, np_, nr) == (3, 1, 2)
    assert conf == {"n_tlogs": 3, "n_proxies": 1, "n_resolvers": 2}
    assert nrows == 10  # no data lost across the reconfiguration recovery
    assert c.controller.recoveries >= 1
    c.stop()


def test_configuration_survives_power_loss():
    c = RecoverableCluster(seed=132)
    db = c.database()

    async def main():
        await configure(db, n_tlogs=3)
        for _ in range(200):
            await c.loop.delay(0.1)
            gen = c.controller.generation
            if gen is not None and not c.controller._recovering and len(gen.tlogs) == 3:
                return True
        return False

    assert c.run_until(c.loop.spawn(main()), 300)
    fs = c.power_off()

    # the restarted cluster starts with the constructor default (2) but must
    # converge to the durably-committed configuration (3)
    c2 = RecoverableCluster(seed=133, fs=fs, restart=True)
    db2 = c2.database()

    async def wait_conf():
        assert (await get_configuration(db2))["n_tlogs"] == 3
        for _ in range(200):
            await c2.loop.delay(0.1)
            gen = c2.controller.generation
            if gen is not None and not c2.controller._recovering and len(gen.tlogs) == 3:
                return True
        return False

    assert c2.run_until(c2.loop.spawn(wait_conf()), 300)
    c2.stop()


def test_workload_runs_through_reconfiguration():
    c = RecoverableCluster(seed=134, n_storage_shards=2)
    db = c.database()

    async def reconf():
        await c.loop.delay(0.6)
        await configure(db, n_tlogs=3)

    c.loop.spawn(reconf())
    cyc = CycleWorkload(nodes=10, clients=3, txns_per_client=10)
    metrics = run_workloads(c, [cyc], deadline=600.0)
    assert metrics["Cycle"]["committed"] == 30

    async def wait_reconf():
        for _ in range(200):
            gen = c.controller.generation
            if gen is not None and not c.controller._recovering and len(gen.tlogs) == 3:
                return True
            await c.loop.delay(0.1)
        return False

    assert c.run_until(c.loop.spawn(wait_reconf()), 300)
    c.stop()


def test_configure_validates():
    c = RecoverableCluster(seed=135)
    db = c.database()

    async def main():
        with pytest.raises(ValueError):
            await configure(db, bogus=1)
        with pytest.raises(ValueError):
            await configure(db, n_tlogs=0)
        return True

    assert c.run_until(c.loop.spawn(main()), 60)
    c.stop()
