"""Cluster-to-cluster DR (fdbclient/DatabaseBackupAgent.actor.cpp): the
mutation stream into a second live cluster, exactness under primary chaos,
and failover promotion."""

import pytest

from foundationdb_tpu.client import management as mgmt
from foundationdb_tpu.client.dr import DRAgent
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.roles.types import DatabaseLocked


async def _dump_user(db) -> dict:
    tr = db.create_transaction()
    rows = await tr.get_range(b"", b"\xff", limit=100000)
    return dict(rows)


def test_dr_exactness_under_primary_kill_and_failover():
    """VERDICT r4 #6 acceptance: kill the primary mid-stream; the secondary
    serves the exact keyspace after failover."""
    primary = RecoverableCluster(seed=530, n_storage_shards=2)
    secondary = RecoverableCluster(seed=531, loop=primary.loop)
    pri_db = primary.database()

    async def main():
        # pre-existing data (covered by the initial snapshot)
        tr = pri_db.create_transaction()
        for i in range(40):
            tr.set(b"snap%02d" % i, b"s%d" % i)
        await tr.commit()

        agent = DRAgent(primary, secondary)
        await agent.start()

        # the secondary refuses direct application writes while DR runs
        sec_db = secondary.database()
        for _ in range(100):
            await primary.loop.delay(0.1)
            gen = secondary.controller.generation
            if gen is not None and all(p.locked for p in gen.proxies):
                break
        tr = sec_db.create_transaction()
        tr.set(b"rogue", b"x")
        with pytest.raises(DatabaseLocked):
            await tr.commit()

        # live traffic: sets, clears, atomics — with a primary pipeline
        # kill in the middle (the stream consumer rejoins by tag)
        for i in range(20):
            async def fn(tr, i=i):
                from foundationdb_tpu.roles.types import MutationType

                tr.set(b"live%02d" % i, b"v%d" % i)
                tr.atomic_op(
                    MutationType.ADD, b"counter",
                    (1).to_bytes(8, "little", signed=True),
                )
                if i == 7:
                    tr.clear_range(b"snap00", b"snap05")
            await pri_db.run(fn)
            if i == 9:
                gen = primary.controller.generation
                gen.tlogs[0].commit_stream._process.kill()
        # wait for the primary to recover and the stream to drain
        for _ in range(300):
            await primary.loop.delay(0.1)
            gen = primary.controller.generation
            if gen is not None and not primary.controller._recovering:
                break
        assert primary.controller.recoveries >= 1

        final = await agent.failover(timeout=240.0)

        # exactness: the secondary's user keyspace == the primary's
        pri = await _dump_user(pri_db)
        sec = await _dump_user(secondary.database())
        sec.pop(b"counter-applied", None)
        assert sec == pri, (
            f"divergence: only-primary={set(pri) - set(sec)}, "
            f"only-secondary={set(sec) - set(pri)}"
        )
        assert pri[b"counter"] == (20).to_bytes(8, "little", signed=True)

        # the promoted secondary accepts writes now
        async def w(tr):
            tr.set(b"post-failover", b"1")
        await sec_db.run(w)
        v = None
        tr = sec_db.create_transaction()
        v = await tr.get(b"post-failover")
        assert v == b"1"

        # and the primary is locked (apps must not write the deposed side)
        tr = pri_db.create_transaction()
        tr.set(b"stale", b"x")
        with pytest.raises(DatabaseLocked):
            await tr.commit()
        return final

    final = primary.run_until(primary.loop.spawn(main()), 900)
    assert final > 0
    secondary.stop()
    primary.stop()


def test_dr_lag_and_stop():
    primary = RecoverableCluster(seed=532)
    secondary = RecoverableCluster(seed=533, loop=primary.loop)
    pri_db = primary.database()

    async def main():
        agent = DRAgent(primary, secondary)
        await agent.start()
        for i in range(10):
            async def fn(tr, i=i):
                tr.set(b"k%d" % i, b"v")
            await pri_db.run(fn)
        tr = pri_db.create_transaction()
        v = await tr.get_read_version()
        await agent.wait_applied_to(v, timeout=120.0)
        assert agent.lag_versions <= 1_000_000  # drained to within a batch
        await agent.stop(unlock_secondary=True)
        # after stop the secondary is writable again
        sec_db = secondary.database()
        async def w(tr):
            tr.set(b"own", b"1")
        await sec_db.run(w)
        return True

    assert primary.run_until(primary.loop.spawn(main()), 600)
    secondary.stop()
    primary.stop()
