"""Status doc, ratekeeper, tuple layer, subspaces, watches."""

import pytest

from foundationdb_tpu.client.tuple_layer import Subspace, pack, range_of, unpack
from foundationdb_tpu.cluster import SimCluster
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.control.status import cluster_status


def test_tuple_roundtrip_and_order():
    cases = [
        (),
        (None,),
        (b"bytes", "text", 0),
        (1, 255, 256, 65535, 2**40),
        (-1, -255, -256, -(2**40)),
        (b"a\x00b",),               # embedded null escape
        (("nested", 1, (b"deep",)),),
        (True, False),
    ]
    for t in cases:
        enc = pack(t)
        dec = unpack(enc)
        norm = tuple(int(v) if isinstance(v, bool) else v for v in t)
        assert dec == norm, (t, dec)

    # order preservation: ints and strings sort naturally
    vals = [(-300,), (-2,), (0,), (1,), (255,), (256,), (70000,)]
    packed = [pack(v) for v in vals]
    assert packed == sorted(packed)
    svals = [("a",), ("a", None), ("a", 0), ("ab",), ("b",)]
    spacked = [pack(v) for v in svals]
    assert spacked == sorted(spacked)


def test_subspace():
    users = Subspace(("app", "users"))
    k = users.pack((42, "alice"))
    assert users.unpack(k) == (42, "alice")
    assert users.contains(k)
    sub = users[42]
    assert sub.unpack(sub.pack(("alice",))) == ("alice",)
    lo, hi = users.range()
    assert lo < k < hi


def test_tuple_layer_against_cluster():
    c = SimCluster(seed=41)
    db = c.database()
    users = Subspace(("users",))

    async def main():
        tr = db.create_transaction()
        for uid, name in [(3, "c"), (1, "a"), (2, "b")]:
            tr.set(users.pack((uid,)), name.encode())
        await tr.commit()
        tr = db.create_transaction()
        lo, hi = users.range()
        rows = await tr.get_range(lo, hi)
        return [(users.unpack(k)[0], v) for k, v in rows]

    assert c.run_until(c.loop.spawn(main()), 60) == [(1, b"a"), (2, b"b"), (3, b"c")]
    c.stop()


def test_status_document():
    c = RecoverableCluster(seed=42, n_storage_shards=2)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"x", b"1")
        await tr.commit()
        await c.loop.delay(0.5)
        return cluster_status(c)

    doc = c.run_until(c.loop.spawn(main()), 60)
    assert doc["cluster"]["generation"]["state"] == "fully_recovered"
    assert doc["proxy"]["txns_committed"] >= 1
    # 2 shards x 2 replicas: status lists every storage SERVER
    assert len(doc["storage"]) == 4
    assert {e["tag"] for e in doc["storage"]} == {
        "ss-0-r0", "ss-0-r1", "ss-1-r0", "ss-1-r1"
    }
    assert doc["resolvers"][0]["txns"] >= 1
    c.stop()


def test_ratekeeper_limits_under_storage_lag():
    c = RecoverableCluster(seed=43)
    rk = c.ratekeeper
    assert rk.tps_budget == rk.max_tps
    # simulate a drowning storage server: huge applied-vs-durable lag, with
    # the durability loop stalled (as if the disk stopped keeping up)
    ss = c.storage[0]
    for t in ss._tasks:
        if t.name.startswith("ss-dur"):
            t.cancel()
    ss.version._value += 10 * c.knobs.mvcc_window_versions

    async def main():
        await c.loop.delay(1.0)
        return rk.tps_budget, rk.limit_reason

    budget, reason = c.run_until(c.loop.spawn(main()), 30)
    assert budget < rk.max_tps and reason == "storage_lag"
    c.stop()


def test_watch_fires_on_change():
    c = SimCluster(seed=44)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"w", b"before")
        await tr.commit()
        watch = await db.watch(b"w")
        assert not watch.done()
        # unrelated write does not fire it
        tr = db.create_transaction()
        tr.set(b"other", b"x")
        await tr.commit()
        await c.loop.delay(0.2)
        assert not watch.done()
        tr = db.create_transaction()
        tr.set(b"w", b"after")
        await tr.commit()
        await watch
        tr = db.create_transaction()
        return await tr.get(b"w")

    assert c.run_until(c.loop.spawn(main()), 60) == b"after"
    c.stop()
