"""Metrics core (Smoother/ContinuousSample), the ratekeeper's smoothed
per-server model, and the client's QueueModel load balancing
(flow/Smoother.h; flow/ContinuousSample.h; Ratekeeper.actor.cpp updateRate;
fdbrpc/QueueModel.h + LoadBalance.actor.h)."""

from foundationdb_tpu.runtime.metrics import ContinuousSample, Smoother


def test_smoother_tracks_constant_rate():
    t = [0.0]
    s = Smoother(1.0, clock=lambda: t[0])
    for i in range(1, 101):
        t[0] = i * 0.1
        s.set_total(100.0 * t[0])  # 100 units/sec
    # discrete 0.1s updates overshoot the continuous-time rate by ~dt/2/e
    assert abs(s.smooth_rate() - 100.0) < 10.0
    # smoothed total lags the true total by rate * e_time
    assert s.smooth_total() < 100.0 * t[0]


def test_smoother_step_converges():
    t = [0.0]
    s = Smoother(1.0, clock=lambda: t[0])
    s.set_total(10.0)
    t[0] = 0.5
    mid = s.smooth_total()
    assert 0 < mid < 10.0
    t[0] = 10.0
    assert abs(s.smooth_total() - 10.0) < 0.01


def test_continuous_sample_percentiles():
    cs = ContinuousSample(500)
    for i in range(10000):
        cs.add(float(i % 100))
    assert cs.count == 10000
    assert abs(cs.median() - 50.0) < 10.0
    assert cs.percentile(0.95) >= 85.0
    assert cs.percentile(0.05) <= 15.0


class _FakeVersion:
    def __init__(self, v):
        self.v = v

    def get(self):
        return self.v


class _FakeSS:
    def __init__(self, tag, lag):
        self.tag = tag
        self.version = _FakeVersion(lag)
        self.durable_version = 0


def test_ratekeeper_squeezes_on_storage_lag_and_recovers():
    from foundationdb_tpu.control.ratekeeper import Ratekeeper
    from foundationdb_tpu.runtime.core import EventLoop
    from foundationdb_tpu.runtime.knobs import CoreKnobs

    loop = EventLoop()
    knobs = CoreKnobs()
    window = knobs.mvcc_window_versions
    ss = _FakeSS("ss-0-r0", 0)
    rk = Ratekeeper(loop, knobs, [ss], tlogs_fn=lambda: [], max_tps=1000.0)

    async def run(seconds):
        await loop.delay(seconds)

    # healthy: full budget
    loop.run_until(loop.spawn(run(3.0)), 1e9)
    assert rk.tps_budget > 900.0
    # drown the server: 4x window lag -> squeezed to the floor
    ss.version.v = 4 * window
    loop.run_until(loop.spawn(run(8.0)), 1e9)
    assert rk.tps_budget < 200.0
    assert rk.limit_reason == "storage_lag"
    assert rk.limiting_server == "ss-0-r0"
    # catch up: the SMOOTHED model recovers (not instantly)
    ss.version.v = 0
    loop.run_until(loop.spawn(run(0.3)), 1e9)
    partway = rk.tps_budget
    loop.run_until(loop.spawn(run(10.0)), 1e9)
    assert rk.tps_budget > 900.0 > partway
    rk.stop()


def test_queue_model_prefers_fast_replica_and_penalizes_broken():
    from foundationdb_tpu.client.transaction import QueueModel
    from foundationdb_tpu.rpc.network import Endpoint, NetworkAddress
    from foundationdb_tpu.runtime.core import DeterministicRandom

    t = [0.0]
    qm = QueueModel(clock=lambda: t[0])

    class _Ref:
        def __init__(self, i):
            self.endpoint = Endpoint(NetworkAddress(f"1.0.0.{i}", 1), f"tok{i}")

    fast, slow = _Ref(1), _Ref(2)
    members = [{"getvalue": fast}, {"getvalue": slow}]
    for _ in range(20):
        qm.on_start(fast)
        qm.on_reply(fast, 0.001)
        qm.on_start(slow)
        qm.on_reply(slow, 0.2)
    rng = DeterministicRandom(5)
    picks = [qm.pick(rng, members, "getvalue") for _ in range(50)]
    assert picks.count(fast) > 45  # two-choice pick lands on the fast one

    # a broken endpoint is avoided while its penalty lasts, then forgiven
    qm.on_broken(fast)
    picks = [qm.pick(rng, members, "getvalue") for _ in range(50)]
    assert picks.count(slow) > 45
    t[0] = 2.0  # penalty expired
    picks = [qm.pick(rng, members, "getvalue") for _ in range(50)]
    assert picks.count(fast) > 45
