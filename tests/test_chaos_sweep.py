"""Seed-sweep chaos runner — the simulation campaign shape of the reference
(many seeds x randomized knobs x BUGGIFY fault injection x workloads with
invariant checks; fdbserver/SimulatedCluster.actor.cpp + tests/fast).

Every cluster here runs with chaos=True: knob randomization
(CoreKnobs(randomize=...), Knobs.cpp:33-34) AND buggify sites armed
(flow/flow.h:65) — delayed replies, dropped TLog pushes/pops, truncated
peeks, early batch fires — on top of process attrition.
"""

import os

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime import buggify
from foundationdb_tpu.workloads.attrition import AttritionWorkload
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.cycle import CycleWorkload

# seed matrix: FDBTPU_SOAK_SEEDS=N scales the sweep (CI default 5; a
# nightly-style campaign runs FDBTPU_SOAK_SEEDS=50 — the reference's
# methodology is thousands of random seeds, tester.actor.cpp rerun loop)
_N_SEEDS = int(os.environ.get("FDBTPU_SOAK_SEEDS", "5"))
SWEEP_SEEDS = [1000 + i for i in range(1, _N_SEEDS + 1)]


@pytest.fixture(autouse=True)
def _buggify_off():
    """Chaos state is module-global: never leak it into later tests, even
    when an assertion fails mid-test."""
    yield
    buggify.disable()


@pytest.mark.parametrize("seed", SWEEP_SEEDS)
def test_chaos_sweep_cycle_with_attrition(seed):
    """Cycle invariant + exact commit count must survive any seed's mix of
    injected faults, randomized knobs, and a pipeline kill."""
    c = RecoverableCluster(seed=seed, n_storage_shards=2, chaos=True)
    assert buggify.is_enabled()
    cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=6)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.9)
    metrics = run_workloads(c, [cyc, att], deadline=600.0)
    assert metrics["Cycle"]["committed"] == 12
    assert c.controller.recoveries >= 1
    c.stop()


def test_chaos_power_loss_restart():
    """Chaos + whole-cluster power loss: committed data survives restart."""
    from foundationdb_tpu.client.transaction import Database

    c = RecoverableCluster(seed=1100, chaos=True)
    db = c.database()

    async def write():
        for i in range(8):
            tr = db.create_transaction()
            tr.set(b"pl%d" % i, b"v%d" % i)
            await tr.commit()

    c.run_until(c.loop.spawn(write()), 120)
    fs = c.power_off()

    c2 = RecoverableCluster(seed=1101, fs=fs, restart=True, chaos=True)
    db2 = c2.database()

    async def read():
        tr = db2.create_transaction()
        return await tr.get_range(b"pl", b"pm")

    rows = c2.run_until(c2.loop.spawn(read()), 120)
    assert len(rows) == 8
    c2.stop()


def test_chaos_is_deterministic():
    """Same seed + chaos => identical run (fault injection draws from the
    cluster's seeded RNG, so the whole chaos campaign replays exactly)."""

    def once():
        c = RecoverableCluster(seed=1200, n_storage_shards=2, chaos=True)
        cyc = CycleWorkload(nodes=6, clients=2, txns_per_client=5)
        att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.7)
        m = run_workloads(c, [cyc, att], deadline=600.0)
        out = (m, c.controller.epoch, round(c.loop.now(), 9),
               c.knobs.MAX_WRITE_TRANSACTION_LIFE, c.knobs.FAILURE_TIMEOUT)
        c.stop()
        buggify.disable()
        return out

    a, b = once(), once()
    assert a == b, f"chaos run not deterministic:\n{a}\n{b}"


def test_chaos_sites_actually_fire():
    """The sweep is not decorative: across the seeds, at least one buggify
    site must have been armed (sites arm per-run with probability 0.25 per
    site; 12 sites x 5 seeds makes all-disarmed vanishingly unlikely)."""
    fired = []
    for seed in SWEEP_SEEDS:
        c = RecoverableCluster(seed=seed, n_storage_shards=2, chaos=True)
        cyc = CycleWorkload(nodes=6, clients=2, txns_per_client=4)
        run_workloads(c, [cyc], deadline=600.0)
        fired.extend(k for k, v in buggify._state.items() if v)
        c.stop()
        buggify.disable()
    assert fired, "no buggify site ever armed across the sweep"


def test_sweep_covers_rare_paths():
    """The coveragetool discipline (flow/UnitTest.h TEST() + the reference's
    coveragetool): a chaos campaign must actually EXERCISE the rare paths
    its fault injection exists to reach — if buggify stops firing or the
    recovery path stops running, this fails loudly instead of the campaign
    silently testing nothing."""
    from foundationdb_tpu.runtime import coverage
    from foundationdb_tpu.workloads.bank import BankWorkload

    coverage.reset()
    for seed in (1301, 1302, 1303):
        c = RecoverableCluster(seed=seed, n_storage_shards=2, chaos=True)
        bank = BankWorkload(accounts=6, clients=2, transfers_per_client=6)
        att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
        run_workloads(c, [bank, att], deadline=600.0)
        c.stop()
    hits = coverage.all_hits()
    assert coverage.hits("recovery.triggered") >= 3  # one per seed's kill
    # fault injection genuinely fired somewhere across the sweep
    assert any(k.startswith("buggify.") for k in hits), hits
