"""Seed-sweep chaos runner — the simulation campaign shape of the reference
(many seeds x randomized knobs x BUGGIFY fault injection x workloads with
invariant checks; fdbserver/SimulatedCluster.actor.cpp + tests/fast).

Every cluster here runs with chaos=True: knob randomization
(CoreKnobs(randomize=...), Knobs.cpp:33-34) AND buggify sites armed
(flow/flow.h:65) — delayed replies, dropped TLog pushes/pops, truncated
peeks, early batch fires — on top of process attrition.
"""

import os

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime import buggify
from foundationdb_tpu.workloads.attrition import AttritionWorkload
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.cycle import CycleWorkload

# seed matrix: FDBTPU_SOAK_SEEDS=N scales the sweep (CI default 5; a
# nightly-style campaign runs FDBTPU_SOAK_SEEDS=50 — the reference's
# methodology is thousands of random seeds, tester.actor.cpp rerun loop)
_N_SEEDS = int(os.environ.get("FDBTPU_SOAK_SEEDS", "5"))
SWEEP_SEEDS = [1000 + i for i in range(1, _N_SEEDS + 1)]


@pytest.fixture(autouse=True)
def _buggify_off():
    """Chaos state is module-global: never leak it into later tests, even
    when an assertion fails mid-test."""
    yield
    buggify.disable()


@pytest.mark.parametrize("seed", SWEEP_SEEDS)
def test_chaos_sweep_cycle_with_attrition(seed):
    """Cycle invariant + exact commit count must survive any seed's mix of
    injected faults, randomized knobs, and a pipeline kill."""
    c = RecoverableCluster(seed=seed, n_storage_shards=2, chaos=True)
    assert buggify.is_enabled()
    cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=6)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.9)
    metrics = run_workloads(c, [cyc, att], deadline=600.0)
    assert metrics["Cycle"]["committed"] == 12
    assert c.controller.recoveries >= 1
    c.stop()


@pytest.mark.parametrize("seed", SWEEP_SEEDS)
def test_chaos_sweep_selector_oracle(seed):
    """The client-API referee (ROADMAP item #2): selector resolution and
    cache-merged RYW reads must be byte-identical to a naive in-memory
    oracle on EVERY seed, with attrition and swizzle clogging injecting
    storage failovers, clogged links, and recoveries mid-transaction."""
    from foundationdb_tpu.workloads.selector_oracle import SelectorOracleWorkload
    from foundationdb_tpu.workloads.swizzle import SwizzleWorkload

    c = RecoverableCluster(seed=seed + 40, n_storage_shards=2, chaos=True)
    assert buggify.is_enabled()
    w = SelectorOracleWorkload(rounds=3, checks_per_round=10)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=1.3)
    sw = SwizzleWorkload(rounds=2, victims=2, start_delay=0.6)
    metrics = run_workloads(c, [w, att, sw], deadline=600.0)
    assert metrics["SelectorOracle"]["divergences"] == 0
    assert metrics["SelectorOracle"]["selector_checks"] >= 3
    assert metrics["SelectorOracle"]["checks"] == 30
    c.stop()


def test_chaos_power_loss_restart():
    """Chaos + whole-cluster power loss: committed data survives restart."""
    from foundationdb_tpu.client.transaction import Database

    c = RecoverableCluster(seed=1100, chaos=True)
    db = c.database()

    async def write():
        for i in range(8):
            tr = db.create_transaction()
            tr.set(b"pl%d" % i, b"v%d" % i)
            await tr.commit()

    c.run_until(c.loop.spawn(write()), 120)
    fs = c.power_off()

    c2 = RecoverableCluster(seed=1101, fs=fs, restart=True, chaos=True)
    db2 = c2.database()

    async def read():
        tr = db2.create_transaction()
        return await tr.get_range(b"pl", b"pm")

    rows = c2.run_until(c2.loop.spawn(read()), 120)
    assert len(rows) == 8
    c2.stop()


def test_chaos_is_deterministic():
    """Same seed + chaos => identical run (fault injection draws from the
    cluster's seeded RNG, so the whole chaos campaign replays exactly)."""

    def once():
        c = RecoverableCluster(seed=1200, n_storage_shards=2, chaos=True)
        cyc = CycleWorkload(nodes=6, clients=2, txns_per_client=5)
        att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.7)
        m = run_workloads(c, [cyc, att], deadline=600.0)
        out = (m, c.controller.epoch, round(c.loop.now(), 9),
               c.knobs.MAX_WRITE_TRANSACTION_LIFE, c.knobs.FAILURE_TIMEOUT)
        c.stop()
        buggify.disable()
        return out

    a, b = once(), once()
    assert a == b, f"chaos run not deterministic:\n{a}\n{b}"


def test_chaos_sites_actually_fire():
    """The sweep is not decorative: across the seeds, at least one buggify
    site must have been armed (sites arm per-run with probability 0.25 per
    site; 12 sites x 5 seeds makes all-disarmed vanishingly unlikely)."""
    fired = []
    for seed in SWEEP_SEEDS:
        c = RecoverableCluster(seed=seed, n_storage_shards=2, chaos=True)
        cyc = CycleWorkload(nodes=6, clients=2, txns_per_client=4)
        run_workloads(c, [cyc], deadline=600.0)
        fired.extend(k for k, v in buggify._state.items() if v)
        c.stop()
        buggify.disable()
    assert fired, "no buggify site ever armed across the sweep"


class _RefereedConflictSet:
    """Test-only wrapper running every batch through BOTH the supervised
    device backend and a plain CPU oracle, recording any verdict mismatch —
    the 'no verdict ever differs from the CPU oracle' referee for the
    device-fault chaos sweep.  Mismatches are recorded, not raised, so a
    bug surfaces as a clean assertion after the run instead of wedging the
    resolver task mid-simulation."""

    def __init__(self, inner, referee, mismatches):
        self.inner = inner
        self.referee = referee
        self.mismatches = mismatches

    def resolve_batch(self, version, txns):
        got = self.inner.resolve_batch(version, txns)
        want = self.referee.resolve_batch(version, txns)
        if [int(v) for v in got] != [int(v) for v in want]:
            self.mismatches.append((version, got, want))
        return got

    def resolve_deferred(self, version, txns):
        handle = self.inner.resolve_deferred(version, txns)
        want = self.referee.resolve_batch(version, txns)
        outer = self

        class _H:
            def wait(self):
                got = handle.wait()
                if [int(v) for v in got] != [int(v) for v in want]:
                    outer.mismatches.append((version, got, want))
                return got

        return _H()

    def remove_before(self, version):
        self.inner.remove_before(version)
        self.referee.remove_before(version)

    @property
    def oldest_version(self):
        return self.inner.oldest_version

    @property
    def node_count(self):
        return self.inner.node_count

    def kernel_stats(self):
        return self.inner.kernel_stats()

    def health(self):
        return self.inner.health()

    def bind_clock(self, clock):
        self.inner.bind_clock(clock)

    def bind_failmon(self, failmon, name=None):
        self.inner.bind_failmon(failmon, name)

    def healthcheck(self):
        return self.inner.healthcheck()

    def close(self):
        self.inner.close()
        self.referee.close()


DEVICE_SITES = (
    "device.lost",
    "device.dispatch_hang",
    "device.compile_fail",
    "device.readback_corrupt",
)


def test_chaos_device_faults_mid_pipeline(monkeypatch):
    """The device-fault campaign (ISSUE 4 acceptance): with each new
    device.* buggify site tripped mid-run — in the split-phase pipeline
    (FDBTPU_PIPELINE=1), so faults land inside an open deferred window —

      (a) no verdict ever differs from the CPU oracle (referee wrapper),
      (b) the workload completes exactly and every resolver ends healthy
          or explicitly degraded — never wedged,
      (c) cluster_status reports the device health roll-up,

    and each site is *required* to have fired — asserted through the soak
    driver's merged coverage census (tools/soak.py), the same API a
    cross-process campaign uses: fault injection that silently stops
    injecting fails here."""
    from foundationdb_tpu.conflict.device import DeviceConflictSet
    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    from foundationdb_tpu.conflict.supervisor import DeviceSupervisor
    from foundationdb_tpu.control.status import cluster_status, validate_status
    from foundationdb_tpu.runtime import coverage
    from foundationdb_tpu.tools import soak

    monkeypatch.setenv("FDBTPU_PIPELINE", "1")
    per_seed: dict = {}
    for i, site in enumerate(DEVICE_SITES):
        cov_base = coverage.snapshot()
        mismatches: list = []

        def make_cs(oldest=0, _m=mismatches):
            return _RefereedConflictSet(
                DeviceSupervisor(
                    lambda o=0: DeviceConflictSet(o, capacity=1 << 10),
                    oldest_version=oldest,
                ),
                OracleConflictSet(oldest),
                _m,
            )

        c = RecoverableCluster(
            seed=1500 + i, n_storage_shards=2, chaos=True,
            conflict_backend=make_cs,
        )

        async def tripper(site=site):
            # mid-run, mid-window: commits are flowing when the site fires.
            # device.lost fires enough consecutive times to TRIP the
            # breaker (DEVICE_RETRY_LIMIT), so the campaign provably walks
            # the full degrade -> serve-degraded path, not just a retry.
            await c.loop.delay(0.4)
            buggify.force(site, 3 if site == "device.lost" else 2)

        c.loop.spawn(tripper())
        cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=6)
        metrics = run_workloads(c, [cyc], deadline=600.0)
        assert metrics["Cycle"]["committed"] == 12, site
        if not coverage.hits(f"buggify.{site}"):
            # the workload outran the trip point: drive a few more commits
            # so the armed fault meets live traffic (a forced site only
            # fires when a device interaction actually happens)
            db = c.database()

            async def drive():
                for j in range(4):
                    tr = db.create_transaction()
                    tr.set(b"post%d" % j, b"x")
                    await tr.commit()

            c.run_until(c.loop.spawn(drive()), 120.0)
        assert mismatches == [], f"{site}: verdicts diverged from oracle"
        assert coverage.hits(f"buggify.{site}") >= 1, f"{site} never fired"
        for r in c.controller.generation.resolvers:
            assert r.cs.health()["state"] in ("healthy", "degraded"), site
        doc = cluster_status(c)
        validate_status(doc)
        assert "device" in doc["kernel"], site
        # per-seed census, captured BEFORE disable() clears the buggify
        # half (the same order tools/soak.py's teardown emission uses)
        per_seed[site] = soak.seed_census(cov_base)
        c.stop()
        buggify.disable()
    # the campaign-level coverage contract through the merged census: every
    # device fault class fired in some seed AND at least one full breaker
    # trip actually happened (soak.check_required is the same check a
    # required-coverage manifest drives in a cross-process campaign)
    merged = soak.merge_census(per_seed)
    missing = soak.check_required(
        merged,
        [f"buggify.{s}" for s in DEVICE_SITES]
        + ["device.cpu_rebuild", "device.degraded"],
    )
    assert missing == [], f"campaign census missing required sites: {missing}"
    # and the armed-vs-hit gap is empty for the fault classes under test:
    # every ARMED device.* buggify site was HIT across the sweep
    for site, row in merged["buggify"].items():
        if site.startswith("device.") and row["armed_seeds"]:
            assert row["hit_seeds"] >= 1, f"{site} armed but never fired"


def test_sweep_covers_rare_paths():
    """The coveragetool discipline (flow/UnitTest.h TEST() + the reference's
    coveragetool): a chaos campaign must actually EXERCISE the rare paths
    its fault injection exists to reach — if buggify stops firing or the
    recovery path stops running, this fails loudly instead of the campaign
    silently testing nothing."""
    from foundationdb_tpu.runtime import coverage
    from foundationdb_tpu.workloads.bank import BankWorkload

    coverage.reset()
    for seed in (1301, 1302, 1303):
        c = RecoverableCluster(seed=seed, n_storage_shards=2, chaos=True)
        bank = BankWorkload(accounts=6, clients=2, transfers_per_client=6)
        att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
        run_workloads(c, [bank, att], deadline=600.0)
        c.stop()
    hits = coverage.all_hits()
    assert coverage.hits("recovery.triggered") >= 3  # one per seed's kill
    # fault injection genuinely fired somewhere across the sweep
    assert any(k.startswith("buggify.") for k in hits), hits
