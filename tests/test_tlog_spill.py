"""TLog spill-to-disk: lagging tags evict payloads to the disk queue and
serve peeks by re-reading records (TLogServer.actor.cpp spilled-data path).
Cluster data volume is disk-bounded, not TLog-RAM-bounded."""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime.knobs import CoreKnobs


def _knobs(spill: int) -> CoreKnobs:
    k = CoreKnobs()
    k.TLOG_SPILL_BYTES = spill
    return k


def test_lagging_storage_forces_spill_then_catches_up():
    """Kill one storage replica so its tag stops popping; write until the
    TLog spills; the healed replacement must still receive EVERYTHING —
    served partly from spilled records."""
    c = RecoverableCluster(seed=401, n_storage_shards=1, storage_replication=2,
                           knobs=_knobs(2000))
    db = c.database()

    async def main():
        # stop the lagging tag: kill replica r1 (heal will later take over)
        victim = next(s for s in c.storage if s.tag == "ss-0-r1")
        victim.process.kill()
        # write enough bytes that r1's unpopped tag stream exceeds the
        # spill budget many times over
        for base in range(0, 300, 50):
            tr = db.create_transaction()
            for i in range(base, base + 50):
                tr.set(b"sp%04d" % i, b"x" * 40)
            await tr.commit()
        tlogs = c.controller.generation.tlogs
        assert any(t.spill_events > 0 for t in tlogs), "no TLog ever spilled"
        # wait for the heal: the replacement pulls the spilled backlog
        for _ in range(400):
            if c.dd.heals >= 1:
                break
            await c.loop.delay(0.1)
        assert c.dd.heals >= 1
        # quiesce, then compare replicas
        await c.loop.delay(2.0)
        return True

    assert c.run_until(c.loop.spawn(main()), 900)
    from foundationdb_tpu.workloads.base import run_workloads
    from foundationdb_tpu.workloads.consistency import ConsistencyCheckWorkload

    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cons], deadline=300.0)
    assert metrics["ConsistencyCheck"]["shards_checked"] == 1
    assert metrics["ConsistencyCheck"]["replicas_compared"] == 2
    assert metrics["ConsistencyCheck"]["rows_checked"] >= 300
    c.stop()


def test_spill_survives_recovery_lock():
    """A pipeline recovery locks the TLogs while entries are spilled: the
    lock reply must carry the spilled data, and the new generation's seeds
    must include it (nothing lost across the generation change)."""
    c = RecoverableCluster(seed=402, n_storage_shards=1, storage_replication=2,
                           knobs=_knobs(1500))
    db = c.database()

    async def main():
        victim = next(s for s in c.storage if s.tag == "ss-0-r1")
        victim.process.kill()
        for base in range(0, 200, 50):
            tr = db.create_transaction()
            for i in range(base, base + 50):
                tr.set(b"rl%04d" % i, b"y" * 40)
            await tr.commit()
        assert any(t.spill_events > 0 for t in c.controller.generation.tlogs)
        # force a recovery while spilled: kill the sequencer
        epoch = c.controller.epoch
        c.controller.generation.sequencer.stream._process.kill()
        for _ in range(400):
            if c.controller.epoch > epoch and c.controller.generation:
                break
            await c.loop.delay(0.1)
        assert c.controller.epoch > epoch
        # the new generation must serve every committed row
        for _ in range(400):
            if c.dd.heals >= 1:
                break
            await c.loop.delay(0.1)
        await c.loop.delay(2.0)

        async def fn(tr):
            return await tr.get_range(b"rl", b"rm", limit=100000)

        rows = await db.run(fn)
        return len(rows)

    n = c.run_until(c.loop.spawn(main()), 900)
    assert n == 200
    c.stop()
