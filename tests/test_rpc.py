"""Sim network + typed RPC: delivery, isolation, faults, determinism."""

import dataclasses

import pytest

from foundationdb_tpu.rpc.network import SimNetwork
from foundationdb_tpu.rpc.stream import RequestStream, RequestStreamRef
from foundationdb_tpu.runtime.core import BrokenPromise, DeterministicRandom, EventLoop, TimedOut


@dataclasses.dataclass
class Echo:
    text: str
    tags: list


def make_world(seed=1):
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(seed))
    return loop, net


def test_request_reply_roundtrip():
    loop, net = make_world()
    server = net.create_process("server")
    client = net.create_process("client")
    rs = RequestStream(server, "wlt:echo")
    ref = RequestStreamRef(net, client, rs.endpoint)

    async def serve():
        req = await rs.next()
        req.reply(req.payload.text.upper())

    loop.spawn(serve())
    fut = ref.get_reply(Echo("hello", []))
    assert loop.run_until(fut) == "HELLO"
    assert loop.now() > 0  # latency was simulated


def test_payload_isolation_deepcopy():
    loop, net = make_world()
    server = net.create_process("server")
    client = net.create_process("client")
    rs = RequestStream(server, "wlt:echo")
    ref = RequestStreamRef(net, client, rs.endpoint)
    sent = Echo("x", tags=[1])

    async def serve():
        req = await rs.next()
        req.payload.tags.append(99)  # mutating the server copy...
        req.reply(req.payload.tags)

    loop.spawn(serve())
    got = loop.run_until(ref.get_reply(sent))
    assert got == [1, 99]
    assert sent.tags == [1]  # ...never touches the client's object


def test_error_reply():
    loop, net = make_world()
    server = net.create_process("server")
    client = net.create_process("client")
    rs = RequestStream(server, "wlt:boom")
    ref = RequestStreamRef(net, client, rs.endpoint)

    async def serve():
        req = await rs.next()
        req.reply_error(ValueError("nope"))

    loop.spawn(serve())
    with pytest.raises(ValueError):
        loop.run_until(ref.get_reply(Echo("x", [])))


def test_dead_server_fails_fast_with_broken_promise():
    """A request to a dead process fails the caller quickly (the TCP
    connection-reset analog) instead of burning its full timeout."""
    loop, net = make_world()
    server = net.create_process("server")
    client = net.create_process("client")
    rs = RequestStream(server, "wlt:echo")
    ref = RequestStreamRef(net, client, rs.endpoint)
    server.kill()
    fut = ref.get_reply(Echo("x", []), timeout=1.0)
    with pytest.raises(BrokenPromise):
        loop.run_until(fut)
    assert net.messages_dropped == 1
    assert loop.now() < 1.0  # failed fast, well before the timeout


def test_partitioned_server_times_out():
    """A partition (message silently lost in the network) cannot produce a
    fast failure — only the caller's timeout fires."""
    loop, net = make_world()
    server = net.create_process("server")
    client = net.create_process("client")
    rs = RequestStream(server, "wlt:echo")
    ref = RequestStreamRef(net, client, rs.endpoint)
    net.partition(server.address, client.address)
    fut = ref.get_reply(Echo("x", []), timeout=1.0)
    with pytest.raises(TimedOut):
        loop.run_until(fut)


def test_partition_and_heal():
    loop, net = make_world()
    server = net.create_process("server")
    client = net.create_process("client")
    rs = RequestStream(server, "wlt:echo")
    ref = RequestStreamRef(net, client, rs.endpoint)

    async def serve_forever():
        while True:
            req = await rs.next()
            req.reply("pong")

    loop.spawn(serve_forever())
    net.partition(server.address, client.address)
    with pytest.raises(TimedOut):
        loop.run_until(ref.get_reply("ping", timeout=0.5))
    net.heal_partition(server.address, client.address)
    assert loop.run_until(ref.get_reply("ping", timeout=0.5)) == "pong"


def test_clog_delays_but_delivers():
    loop, net = make_world()
    server = net.create_process("server")
    client = net.create_process("client")
    rs = RequestStream(server, "wlt:echo")
    ref = RequestStreamRef(net, client, rs.endpoint)

    async def serve():
        req = await rs.next()
        req.reply("pong")

    loop.spawn(serve())
    net.clog_pair(server.address, client.address, 3.0)
    fut = ref.get_reply("ping")
    assert loop.run_until(fut) == "pong"
    assert loop.now() > 3.0


def test_fifo_per_pair():
    loop, net = make_world()
    server = net.create_process("server")
    client = net.create_process("client")
    rs = RequestStream(server, "wlt:q")
    ref = RequestStreamRef(net, client, rs.endpoint)
    got = []

    async def serve():
        for _ in range(20):
            req = await rs.next()
            got.append(req.payload)

    t = loop.spawn(serve())
    for i in range(20):
        ref.send(i)
    loop.run_until(t)
    assert got == list(range(20))


def test_network_determinism():
    def run(seed):
        loop, net = make_world(seed)
        server = net.create_process("server")
        client = net.create_process("client")
        rs = RequestStream(server, "wlt:echo")
        ref = RequestStreamRef(net, client, rs.endpoint)
        times = []

        async def serve():
            while True:
                req = await rs.next()
                req.reply(req.payload * 2)

        loop.spawn(serve())

        async def drive():
            for i in range(10):
                v = await ref.get_reply(i)
                times.append((v, round(loop.now(), 9)))

        loop.run_until(loop.spawn(drive()))
        return times

    assert run(5) == run(5)
    assert run(5) != run(6)
