"""flowlint test surface (docs/LINT.md).

Three layers, mirroring how the reference trusts its actor compiler:

  1. every rule is PROVEN to fire — a `tests/lint_fixtures/<rule>/bad`
     tree must trip the rule and the sibling `ok` tree must not, so a
     rule that silently stops matching fails the suite, not the field;
  2. the baseline ratchet only tightens — grandfathered findings pass,
     a NEW finding fails, and a STALE baseline entry (the site was
     fixed) also fails until the entry is deleted;
  3. the committed tree is clean — the tier-1 gate runs the full pass
     over foundationdb_tpu/ + tests/ and requires zero unbaselined
     findings, which is what `python -m foundationdb_tpu.tools.flowlint
     foundationdb_tpu tests` enforces from the command line.

Plus the PR-9 regression pins for sites the lint audit FIXED (rather
than suppressed): discover_gateway's retry pacing and the sim clusters'
deterministic trace-file WallTime.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

from foundationdb_tpu.lint import (
    apply_baseline,
    default_rules,
    load_baseline,
    run_lint,
)
from foundationdb_tpu.tools.flowlint import DEFAULT_BASELINE, REPO_ROOT
from foundationdb_tpu.tools.flowlint import main as flowlint_main

FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"
RULE_DIRS = sorted(d.name for d in FIXTURES.iterdir() if d.is_dir())


def lint_fixture(rule: str, which: str):
    """Lint one fixture tree.  Root is the repo so the fixture paths keep
    their `lint_fixtures` marker (package-scope treatment); spec_dir is
    disabled so manifest checks don't resolve against the REAL spec
    corpus while only fixture call sites are in view."""
    return run_lint([str(FIXTURES / rule / which)], root=REPO_ROOT,
                    spec_dir=None)


def test_fixture_dirs_cover_every_rule():
    """One bad/ok pair per rule — a new rule without fixtures (or a
    fixture dir for a deleted rule) fails here before it can rot."""
    ids = {r.id for r in default_rules()} | {"suppression"}
    assert ids == set(RULE_DIRS)
    # PR-12 floor: the 10 PR-9 rules plus the 5 flowcheck interleaving
    # rules (docs/LINT.md "Interleaving hazards") — a new rule landing
    # without a fixture pair fails the set equality above
    assert len(default_rules()) >= 15
    for rule in ("stale-read-across-await", "check-then-act-across-await",
                 "epoch-guard-missing", "await-under-lock",
                 "mutate-while-iterating-across-await"):
        assert rule in ids


@pytest.mark.parametrize("rule", RULE_DIRS)
def test_rule_fires_on_bad_fixture(rule):
    hits = [f for f in lint_fixture(rule, "bad") if f.rule == rule]
    assert hits, f"rule {rule!r} did not fire on its bad fixture"
    for f in hits:
        # findings carry the full triage surface: file:line + rule + hint
        assert f.path.startswith("tests/lint_fixtures/")
        assert f.line > 0
        assert f.message
        rendered = f.render()
        assert f"[{rule}]" in rendered and f":{f.line}:" in rendered


@pytest.mark.parametrize("rule", RULE_DIRS)
def test_rule_stays_silent_on_ok_fixture(rule):
    hits = [f for f in lint_fixture(rule, "ok") if f.rule == rule]
    assert not hits, [f.render() for f in hits]


def test_findings_carry_fix_hints():
    """The one-line fix hint rides every finding (Flow's compiler errors
    tell you what to do, not just what you did)."""
    findings = lint_fixture("wall-clock", "bad")
    assert findings and all(f.hint for f in findings if f.rule == "wall-clock")
    assert any("bound clock" in f.hint for f in findings)


# -- the PR-9 effect-summary blind spot (partial/lambda/alias) ----------------


def test_dropped_future_sees_through_partial_lambda_and_alias():
    """Each wrapper shape is pinned individually: an async callable bound
    via functools.partial, a trivial lambda, or a method-alias assignment
    must still read as async when its call is dropped — and a partial (or
    the bare callable) handed to spawn() builds NO coroutine at all."""
    hits = [f for f in lint_fixture("dropped-future", "bad")
            if f.rule == "dropped-future"
            and f.path.endswith("partials.py")]
    msgs = "\n".join(f"{f.line}: {f.message}" for f in hits)
    assert any("alias" in f.message and "'f'" in f.message for f in hits), msgs
    assert any("partial-wrapped" in f.message for f in hits), msgs
    assert sum(
        1 for f in hits if "bound via partial/lambda/alias" in f.message
    ) >= 3, msgs  # the alias, partial, and lambda bindings each fire
    assert any("spawn() received" in f.message for f in hits), msgs
    assert len(hits) >= 5, msgs


# -- flowcheck interleave rules: effect-census precision ----------------------


def test_nonsuspending_await_is_not_a_scheduling_point():
    """Awaiting a coroutine that never reaches a real suspension runs
    synchronously under this runtime — the ok fixture's `nonsuspending`
    case only stays silent because the effect census resolves
    `await self.quick()` transitively.  Pin the census directly too."""
    from foundationdb_tpu.lint import LintContext, SourceFile
    from foundationdb_tpu.lint.dataflow import EffectCensus

    src = (
        "class A:\n"
        "    async def quick(self):\n"
        "        return 1\n"
        "    async def chain(self):\n"
        "        return await self.quick()\n"
        "    async def slow(self, loop):\n"
        "        await loop.delay(1)\n"
        "    async def chain_slow(self):\n"
        "        return await self.slow(None)\n"
    )
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        f = pathlib.Path(d) / "foundationdb_tpu" / "m.py"
        f.parent.mkdir()
        f.write_text(src)
        sf = SourceFile(str(f), str(f.relative_to(d)), "package")
        census = EffectCensus(LintContext([sf], d))
    assert not census.summaries["A.quick"].suspends
    assert not census.summaries["A.chain"].suspends  # transitive
    assert census.summaries["A.slow"].suspends       # opaque await
    assert census.summaries["A.chain_slow"].suspends


# -- suppression semantics ----------------------------------------------------


def test_inline_and_standalone_pragmas_cover_their_line():
    """ok/pragmas.py mixes an inline reasoned pragma and the fixture set
    proves a suppressed site yields nothing; bad/pragmas.py's reasonless
    and unknown-rule pragmas are themselves findings (the escape hatch
    stays auditable)."""
    bad = lint_fixture("suppression", "bad")
    msgs = [f.message for f in bad if f.rule == "suppression"]
    assert any("without a reason" in m for m in msgs)
    assert any("unknown rule" in m for m in msgs)


# -- baseline ratchet ---------------------------------------------------------


def _write_mod(tmp_path: pathlib.Path, body: str) -> pathlib.Path:
    pkg = tmp_path / "foundationdb_tpu"
    pkg.mkdir(exist_ok=True)
    mod = pkg / "mod.py"
    mod.write_text(body)
    return mod


def test_new_finding_fails_the_run():
    # the committed default baseline grandfathers nothing for fixtures,
    # so a bad fixture linted through the CLI surface exits non-zero
    rc = flowlint_main([str(FIXTURES / "wall-clock" / "bad"),
                        "--root", REPO_ROOT])
    assert rc == 1


def test_baseline_grandfathers_then_goes_stale(tmp_path):
    """The full ratchet cycle: violation -> grandfathered (exit 0) ->
    site fixed -> the now-stale baseline entry FAILS the run until it is
    deleted (zero-or-fail in both directions)."""
    mod = _write_mod(tmp_path, "import time\n\n\ndef f():\n    return time.time()\n")
    bl = tmp_path / "baseline.json"
    pkg = str(tmp_path / "foundationdb_tpu")
    args = [pkg, "--root", str(tmp_path), "--baseline", str(bl)]

    assert flowlint_main(args + ["--write-baseline"]) == 0
    doc = json.loads(bl.read_text())
    assert doc["findings"], "grandfathering recorded no findings"
    assert flowlint_main(args) == 0  # baselined: green

    mod.write_text("def f(loop):\n    return loop.now()\n")
    assert flowlint_main(args) == 1  # stale entry: red

    assert flowlint_main(args + ["--write-baseline"]) == 0  # prune it
    assert json.loads(bl.read_text())["findings"] == []
    assert flowlint_main(args) == 0


def test_committed_baseline_is_fresh():
    """Tier-1 gate: the full pass over the real tree yields zero
    unbaselined findings AND zero stale baseline entries — exactly what
    `python -m foundationdb_tpu.tools.flowlint foundationdb_tpu tests`
    enforces."""
    findings = run_lint([str(pathlib.Path(REPO_ROOT) / "foundationdb_tpu"),
                         str(pathlib.Path(REPO_ROOT) / "tests")],
                        root=REPO_ROOT)
    new, _old, stale = apply_baseline(findings, load_baseline(DEFAULT_BASELINE))
    assert not new, "unbaselined findings:\n" + "\n".join(f.render() for f in new)
    assert not stale, f"stale baseline entries: {stale}"


# -- CLI surfaces -------------------------------------------------------------


def test_list_rules_names_every_rule(capsys):
    assert flowlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for r in default_rules():
        assert r.id in out


def test_cli_lint_subcommand_is_green_on_the_tree():
    """`cli lint` (no args) lints foundationdb_tpu + tests against the
    committed baseline and exits 0 — the CI invocation."""
    r = subprocess.run(
        [sys.executable, "-m", "foundationdb_tpu.tools.cli", "lint"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new finding(s)" in r.stdout


def test_flag_only_invocation_defaults_to_the_tree(capsys):
    """Review-pass pin: `cli lint --json` forwards flag-only argv; flowlint
    must default the paths to foundationdb_tpu + tests instead of dying
    with a usage error because argv was non-empty."""
    rc = flowlint_main(["--json"])
    out = capsys.readouterr().out
    assert rc == 0, out
    doc = json.loads(out)
    assert doc["new"] == [] and doc["stale_baseline"] == []


def test_diff_mode_reports_only_changed_files(tmp_path):
    """`flowlint --diff REV` still ANALYZES the full tree (cross-file
    censuses need everything in view) but reports and gates only on
    findings in files changed vs REV + untracked files — the pre-commit
    spelling wired through `cli lint --diff`."""
    import os

    # the git toplevel sits ABOVE the lint root (review pin: `git diff
    # --relative` keeps changed paths in the root-relative dialect the
    # findings use — toplevel-relative names would empty the intersection
    # and silently gate nothing)
    ws = tmp_path / "ws"
    pkg = ws / "foundationdb_tpu"
    pkg.mkdir(parents=True)
    bad = pkg / "bad.py"
    bad.write_text("import time\n\n\ndef f():\n    return time.time()\n")
    clean = pkg / "clean.py"
    clean.write_text("def g():\n    return 1\n")
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}

    def git(*args):
        r = subprocess.run(["git", *args], cwd=tmp_path, env=env,
                           capture_output=True, text=True, timeout=30)
        assert r.returncode == 0, r.stderr

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    clean.write_text("def g():\n    return 2\n")  # only the CLEAN file changes

    argv = [str(pkg), "--root", str(ws)]
    # full run: the unchanged bad file fails the tree
    assert flowlint_main(argv) == 1
    # diff run: bad.py is unchanged vs HEAD, so nothing gates
    assert flowlint_main(argv + ["--diff", "HEAD"]) == 0
    # touching the bad file brings its finding back into scope
    bad.write_text("import time\n\n\ndef f():\n    return time.time()\n\n")
    assert flowlint_main(argv + ["--diff", "HEAD"]) == 1
    # an unresolvable rev falls back to the full report, never to silence
    assert flowlint_main(argv + ["--diff", "no-such-rev"]) == 1



def test_metrics_schema_rule_fails_loudly_when_emitter_scan_breaks(tmp_path):
    """Review-pass pin: a populated ROLE_METRICS_SCHEMA with NO
    spawn_role_metrics/spawn_wire_metrics call found across the other
    package files is a broken scan anchor (or a fully stale schema) and
    must be a finding — the silent `return` here is exactly how the
    deleted AST-guard test would have failed loudly.  The anchor module
    linted ALONE is a partial tree and must stay silent."""
    pkg = tmp_path / "foundationdb_tpu"
    pkg.mkdir()
    (pkg / "status.py").write_text(
        "ROLE_METRICS_SCHEMA: dict = {\n    \"GhostMetrics\": {},\n}\n")
    (pkg / "other.py").write_text("def noop():\n    return 1\n")
    full = run_lint([str(pkg)], root=str(tmp_path), spec_dir=None)
    hits = [f for f in full if f.rule == "metrics-schema"]
    assert hits and "no spawn_role_metrics" in hits[0].message
    partial = run_lint([str(pkg / "status.py")], root=str(tmp_path),
                       spec_dir=None)
    assert not [f for f in partial if f.rule == "metrics-schema"]


# -- regression pins for sites the audit FIXED --------------------------------


def test_discover_gateway_stays_off_the_wall_clock():
    """PR-9 fix pin: discover_gateway paced its quorum-retry loop with
    time.monotonic()/time.sleep() (blocking the process so a late quorum
    reply could only land AFTER the backoff).  It now routes deadlines
    and backoff through the bound clock and keeps pumping the network.
    The wall-clock rule must stay silent on this file — and silent
    because the site is FIXED, not because a pragma crept in."""
    path = pathlib.Path(REPO_ROOT) / "foundationdb_tpu" / "client" / "cluster_file.py"
    findings = run_lint([str(path)], root=REPO_ROOT, spec_dir=None)
    assert not [f for f in findings if f.rule == "wall-clock"]
    # silent because fixed, not because suppressed: a pragma would hide a
    # reintroduced wall clock from the rule but not from this assert
    assert "ok wall-clock" not in path.read_text()


def test_sim_trace_walltime_comes_from_the_bound_clock():
    """PR-9 fix pin: trace-file lines used to stamp WallTime from the
    host (time.time), so two runs of one seed produced different bytes.
    TraceCollector now accepts a wall_clock and the sim clusters bind
    their virtual clock — identical runs, identical trace files."""
    from foundationdb_tpu.runtime.trace import TraceCollector

    lines: list[str] = []

    class Sink:
        def write(self, s: str) -> None:
            lines.append(s)

    t = TraceCollector(clock=lambda: 7.25, sink=Sink(), wall_clock=lambda: 7.25)
    t.trace("FixturePinEvent")
    assert json.loads(lines[0])["WallTime"] == 7.25

    # and SimCluster actually binds it (the sim trace plane is virtual
    # end to end — the integration the fixture above pins in isolation)
    from foundationdb_tpu.cluster import SimCluster

    c = SimCluster(seed=11)
    assert c.trace._wall_clock == c.loop.now
    c.stop()


def test_same_seed_reruns_roll_byte_stable_trace_files(tmp_path):
    """PR-9 fix pin, end to end: one seed run twice must roll
    byte-identical trace files.  The single sanctioned exception is
    SlowTask — its DurationS measures how long a reactor callback
    stalled in HOST wall time (runtime/core.py), profiling data the
    virtual clock cannot see and so nondeterministic by definition.
    Everything else, WallTime stamps included, must match to the byte."""
    from foundationdb_tpu.runtime.trace import TraceFileSink
    from foundationdb_tpu.workloads.spec import run_spec

    spec = (
        "testTitle=TraceByteStability\n"
        "seed=99\n"
        "chaos=true\n"
        "\n"
        "testName=Cycle\n"
        "nodes=6\n"
        "clients=2\n"
        "txnsPerClient=4\n"
    )

    def one_run(name: str) -> list[str]:
        outdir = tmp_path / name
        outdir.mkdir()
        sink = TraceFileSink(str(outdir / "trace"))
        try:
            run_spec(spec, deadline=600.0, seed=99, trace_sink=sink,
                     sample_rate=1.0)
        finally:
            sink.close()
        return [
            line
            for f in sorted(outdir.glob("trace.*.jsonl"))
            for line in f.read_text().splitlines()
        ]

    def sans_slow_tasks(lines: list[str]) -> list[str]:
        return [l for l in lines if '"Type": "SlowTask"' not in l]

    a, b = one_run("a"), one_run("b")
    assert sans_slow_tasks(a) == sans_slow_tasks(b)
    # and not vacuously: the runs actually rolled a real event stream
    assert len(sans_slow_tasks(a)) > 50
