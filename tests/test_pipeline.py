"""End-to-end write pipeline: client -> proxy -> sequencer/resolvers ->
TLogs -> storage, all under deterministic simulation (SURVEY §7 step 5)."""

import pytest

from foundationdb_tpu.cluster import SimCluster
from foundationdb_tpu.roles.types import MutationType, NotCommitted
from foundationdb_tpu.runtime.core import TimedOut


def run(cluster, coro, deadline=60.0):
    return cluster.run_until(cluster.loop.spawn(coro), deadline)


def test_set_then_get():
    c = SimCluster(seed=1)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"hello", b"world")
        v = await tr.commit()
        assert v > 0
        tr2 = db.create_transaction()
        got = await tr2.get(b"hello")
        missing = await tr2.get(b"nothing")
        return got, missing

    got, missing = run(c, main())
    assert got == b"world" and missing is None
    c.stop()


def test_occ_conflict_detected():
    c = SimCluster(seed=2)
    db = c.database()

    async def main():
        # tr1 and tr2 both read k then write it; the later committer must abort
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        await tr1.get(b"k")
        await tr2.get(b"k")
        tr1.set(b"k", b"one")
        tr2.set(b"k", b"two")
        await tr1.commit()
        with pytest.raises(NotCommitted):
            await tr2.commit()
        # non-overlapping transaction sails through
        tr3 = db.create_transaction()
        await tr3.get(b"other")
        tr3.set(b"other", b"x")
        await tr3.commit()
        tr4 = db.create_transaction()
        return await tr4.get(b"k")

    assert run(c, main()) == b"one"
    c.stop()


def test_retry_loop_resolves_contention():
    c = SimCluster(seed=3)
    db = c.database()

    async def incr(tr):
        cur = await tr.get(b"counter")
        n = int(cur or b"0")
        tr.set(b"counter", str(n + 1).encode())
        return n + 1

    async def main():
        # 10 concurrent increments; OCC + retry must serialize them all
        tasks = [c.loop.spawn(db.run(incr)) for _ in range(10)]
        from foundationdb_tpu.runtime.combinators import wait_all

        await wait_all(tasks)
        tr = db.create_transaction()
        return await tr.get(b"counter")

    assert run(c, main()) == b"10"
    c.stop()


def test_clear_range_and_range_read():
    c = SimCluster(seed=4, n_storage_shards=3)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        for i in range(20):
            tr.set(b"row/%03d" % i, b"v%d" % i)
        await tr.commit()

        tr = db.create_transaction()
        rows = await tr.get_range(b"row/", b"row0")
        assert len(rows) == 20
        tr.clear_range(b"row/005", b"row/015")
        await tr.commit()

        tr = db.create_transaction()
        rows = await tr.get_range(b"row/", b"row0")
        return [k for k, _ in rows]

    keys = run(c, main())
    assert keys == [b"row/%03d" % i for i in list(range(5)) + list(range(15, 20))]
    c.stop()


def test_atomic_add_concurrent_no_conflict():
    c = SimCluster(seed=5)
    db = c.database()

    async def main():
        # atomic ADD has no read conflict range: all commit without retries
        from foundationdb_tpu.runtime.combinators import wait_all

        async def add_once():
            tr = db.create_transaction()
            tr.atomic_op(MutationType.ADD, b"sum", (3).to_bytes(4, "little"))
            await tr.commit()

        await wait_all([c.loop.spawn(add_once()) for _ in range(8)])
        tr = db.create_transaction()
        raw = await tr.get(b"sum")
        return int.from_bytes(raw, "little")

    assert run(c, main()) == 24
    c.stop()


def test_multi_resolver_multi_shard():
    c = SimCluster(seed=6, n_resolvers=4, n_storage_shards=4, n_tlogs=2)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        # keys spread across all 4 partitions ([0x40/0x80/0xc0] splits)
        for b in (b"\x10aa", b"\x50bb", b"\x90cc", b"\xd0dd"):
            tr.set(b, b"val-" + b)
        await tr.commit()
        tr2 = db.create_transaction()
        vals = [await tr2.get(k) for k in (b"\x10aa", b"\x50bb", b"\x90cc", b"\xd0dd")]
        # cross-partition conflict: reads all, writes one
        tr3 = db.create_transaction()
        await tr3.get_range(b"\x00", b"\xff")
        tr4 = db.create_transaction()
        tr4.set(b"\x90cc", b"changed")
        await tr4.commit()
        tr3.set(b"\x10aa", b"doomed")
        with pytest.raises(NotCommitted):
            await tr3.commit()
        return vals

    vals = run(c, main())
    assert vals == [b"val-\x10aa", b"val-\x50bb", b"val-\x90cc", b"val-\xd0dd"]
    c.stop()


def test_read_your_future_writes_not_visible_before_commit():
    c = SimCluster(seed=7)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"x", b"1")
        # plain Transaction is not RYW: the read goes to storage
        val = await tr.get(b"x")
        await tr.commit()
        return val

    assert run(c, main()) is None
    c.stop()


def test_pipeline_determinism():
    def once(seed):
        c = SimCluster(seed=seed, n_resolvers=2, n_storage_shards=2)
        db = c.database()
        events = []

        async def writer(i):
            for j in range(3):
                try:
                    tr = db.create_transaction()
                    await tr.get(b"shared")
                    tr.set(b"shared", b"%d-%d" % (i, j))
                    v = await tr.commit()
                    events.append((i, j, v, round(c.loop.now(), 9)))
                except NotCommitted:
                    events.append((i, j, "abort", round(c.loop.now(), 9)))

        from foundationdb_tpu.runtime.combinators import wait_all

        c.run_until(
            wait_all([c.loop.spawn(writer(i)) for i in range(3)]), 60.0
        )
        c.stop()
        return events

    assert once(42) == once(42)
    assert once(42) != once(43)
