"""Region configuration + configure-driven failover + KillRegion
(control/region.py, fdbrpc/simulator.h:285 usableRegions analog,
fdbserver/workloads/KillRegion.actor.cpp): the region plane as committed
`\\xff/conf/` state, the satellite-style recovery requirement on the
log-router tag, whole-region kills with zero committed-data loss, and the
promoted/un-promoted reboot paths."""

import pytest

from foundationdb_tpu.client import management as mgmt
from foundationdb_tpu.control.logsystem import region_required_tags
from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.control.region import (
    PRIMARY_KEY,
    SATELLITE_KEY,
    USABLE_REGIONS_KEY,
    RegionConfiguration,
    parse_region_rows,
)
from foundationdb_tpu.roles.logrouter import ROUTER_TAG
from foundationdb_tpu.runtime.core import ActorCancelled
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.kill_region import KillRegionWorkload


def _run(c, coro, deadline=900.0):
    return c.run_until(c.loop.spawn(coro), deadline)


# ---------------------------------------------------------------------------
# the configuration object + codec


def test_region_configuration_validate_and_rows():
    cfg = RegionConfiguration(usable_regions=2, primary="remote")
    cfg.validate()
    assert cfg.router_tag_required
    assert not RegionConfiguration().router_tag_required
    assert not RegionConfiguration(usable_regions=2,
                                   satellite="none").router_tag_required
    with pytest.raises(ValueError, match="usable_regions"):
        RegionConfiguration(usable_regions=3).validate()
    with pytest.raises(ValueError, match="satellite"):
        RegionConfiguration(satellite="maybe").validate()
    with pytest.raises(ValueError, match="primary"):
        RegionConfiguration(primary="mars").validate()
    # rows -> parse roundtrip
    assert parse_region_rows(cfg.rows()) == cfg


def test_parse_region_rows_absent_and_malformed():
    assert parse_region_rows([(b"\xff/conf/n_tlogs", b"2")]) is None
    # malformed values fall back field-by-field, never raise
    cfg = parse_region_rows([
        (USABLE_REGIONS_KEY, b"banana"),
        (SATELLITE_KEY, b"\xff\xfe"),
        (PRIMARY_KEY, b"remote"),
    ])
    assert cfg == RegionConfiguration(primary="remote")
    base = RegionConfiguration(usable_regions=2, satellite="none")
    cfg = parse_region_rows([(PRIMARY_KEY, b"remote")], base=base)
    assert cfg.usable_regions == 2 and cfg.satellite == "none"


def test_region_required_tags():
    consumers = {ROUTER_TAG: object()}
    tags = ["ss-0-r0", "ss-0-r1"]
    assert region_required_tags(tags, RegionConfiguration(), consumers) == tags
    got = region_required_tags(
        tags, RegionConfiguration(usable_regions=2), consumers
    )
    assert got == tags + [ROUTER_TAG]
    # no registered router (already promoted): nothing to require
    assert region_required_tags(
        tags, RegionConfiguration(usable_regions=2), {}
    ) == tags
    # satellite=none opts the router tag out of the requirement
    assert region_required_tags(
        tags, RegionConfiguration(usable_regions=2, satellite="none"),
        consumers,
    ) == tags


def test_configure_regions_verbs():
    c = RecoverableCluster(seed=7401, usable_regions=2)
    db = c.database()

    async def main():
        assert await mgmt.get_region_configuration(db) is None
        await mgmt.configure_regions(db, usable_regions=2,
                                     satellite="required")
        cfg = await mgmt.get_region_configuration(db)
        assert cfg == RegionConfiguration(usable_regions=2)
        with pytest.raises(ValueError):
            await mgmt.configure_regions(db, primary="mars")
        return True

    assert _run(c, main())
    # the conf watch applied the (non-failover) config
    for _ in range(40):
        if c.controller.region_config == RegionConfiguration(usable_regions=2):
            break
        _run(c, c.loop.delay(0.25))
    assert c.controller.region_config.usable_regions == 2
    c.stop()


# ---------------------------------------------------------------------------
# topology bootstrap + configure-driven failover


def test_usable_regions_2_builds_remote_plane():
    c = RecoverableCluster(seed=7402, n_storage_shards=2, usable_regions=2)
    assert c.log_router is not None
    assert len(c.remote_storage) == 2
    assert c.controller.region_config.usable_regions == 2
    assert ROUTER_TAG in c.controller.stream_consumers
    assert c.controller.conf_fallback_servers == c.remote_storage[-1:]
    c.stop()


def test_online_enable_copies_history_then_failover_serves_everything():
    """usable_regions 1→2 on a LIVE single-region cluster with existing
    data: the conf watch builds the relay plane through the
    enable_stream_consumer drain barrier (commits tagged from the
    boundary on) AND snapshot-fetches the pre-boundary history into the
    new replicas — so a later failover serves EVERY committed key, not
    just post-enable traffic."""
    c = RecoverableCluster(seed=7410, n_storage_shards=2,
                          storage_replication=2)  # single-region birth
    assert not c.remote_storage
    db = c.database()

    async def main():
        for i in range(15):  # pre-enable history
            async def fn(tr, i=i):
                tr.set(b"oe%03d" % i, b"v%d" % i)

            await db.run(fn)
        await mgmt.configure_regions(db, usable_regions=2)
        for _ in range(2000):
            if c.remote_storage and c._remote_history_complete:
                break
            await c.loop.delay(0.05)
        assert c.remote_storage and c._remote_history_complete
        assert c.log_router is not None
        for i in range(15, 25):  # post-enable traffic rides the relay
            async def fn(tr, i=i):
                tr.set(b"oe%03d" % i, b"v%d" % i)

            await db.run(fn)
        v = [0]

        async def fv(tr):
            v[0] = await tr.get_read_version()

        await db.run(fv)
        for _ in range(2000):
            if all(s.version.get() >= v[0] for s in c.remote_storage):
                break
            await c.loop.delay(0.05)
        # the configure-driven failover must now serve the FULL history
        for ss in c.storage:
            ss.process.kill()
        await mgmt.configure_regions(db, primary="remote")
        for _ in range(6000):
            if c._region_promoted:
                break
            await c.loop.delay(0.05)
        assert c._region_promoted

        async def rd(tr):
            return await tr.get_range(b"oe", b"of", limit=1000)

        rows = dict(await db.run(rd))
        assert rows == {b"oe%03d" % i: b"v%d" % i for i in range(25)}
        return True

    assert _run(c, main())
    from foundationdb_tpu.runtime import coverage

    assert coverage.census().get("region.enabled_online", 0) >= 1
    c.stop()


def test_online_enable_failed_fetch_resumes(monkeypatch):
    """Review regression: a history fetch that fails mid-enable must be
    RESUMED by a later conf poll (the applied region_config is recorded
    only on full success, so the desired-vs-applied drift persists), and
    the failover gate refuses until the copy lands."""
    from foundationdb_tpu.roles.storage import StorageServer
    from foundationdb_tpu.runtime.core import TimedOut

    c = RecoverableCluster(seed=7411, n_storage_shards=2,
                          storage_replication=2)
    db = c.database()
    orig = StorageServer.start_fetch
    broke = {"n": 0}

    def flaky(self, begin, end, boundary, sources):
        if broke["n"] == 0:
            broke["n"] += 1
            raise TimedOut("injected mid-enable fetch failure")
        return orig(self, begin, end, boundary, sources)

    monkeypatch.setattr(StorageServer, "start_fetch", flaky)

    async def main():
        for i in range(8):
            async def fn(tr, i=i):
                tr.set(b"rf%02d" % i, b"v%d" % i)

            await db.run(fn)
        await mgmt.configure_regions(db, usable_regions=2)
        for _ in range(2000):
            if c.remote_storage and c._remote_history_complete:
                break
            await c.loop.delay(0.05)
        assert broke["n"] == 1, "the injected failure never fired"
        assert c._remote_history_complete, "enable was never resumed"
        v = [0]

        async def fv(tr):
            v[0] = await tr.get_read_version()

        await db.run(fv)
        for _ in range(2000):
            if all(s.version.get() >= v[0] for s in c.remote_storage):
                break
            await c.loop.delay(0.05)
        rdb = c.remote_database()

        async def rd(tr):
            return await tr.get_range(b"rf", b"rg", limit=100)

        rows = dict(await rdb.run(rd))
        assert rows == {b"rf%02d" % i: b"v%d" % i for i in range(8)}
        return True

    assert _run(c, main())
    c.stop()


def test_torn_region_row_holds_applied_config():
    """Review regression: a malformed region row must hold the APPLIED
    configuration (parse base), never decay to the defaults — a decayed
    usable_regions=1 would read as a legitimate request to dismantle the
    remote durability plane."""
    c = RecoverableCluster(seed=7412, n_storage_shards=2, usable_regions=2)
    db = c.database()

    async def main():
        await mgmt.configure_regions(db, usable_regions=2)
        for _ in range(100):
            if c.controller.region_config.usable_regions == 2:
                break
            await c.loop.delay(0.25)

        async def torn(tr):
            tr.set(USABLE_REGIONS_KEY, b"banana")

        await db.run(torn)
        await c.loop.delay(5.0)  # several conf polls over the torn row
        assert c.controller.region_config.usable_regions == 2
        assert c.log_router is not None and c.remote_storage
        return True

    assert _run(c, main())
    c.stop()


def test_configure_driven_failover_with_dead_primary_region():
    """The KillRegion.actor.cpp contract in miniature: every primary
    storage replica dies, the failover is COMMITTED as configuration
    (readable only through the surviving remote replica), the controller
    promotes, and writes+reads flow through the former remote region."""
    c = RecoverableCluster(seed=7403, n_storage_shards=2,
                          storage_replication=2, usable_regions=2)
    db = c.database()

    async def main():
        for i in range(20):
            async def fn(tr, i=i):
                tr.set(b"f%03d" % i, b"v%d" % i)

            await db.run(fn)
        v = [0]

        async def fv(tr):
            v[0] = await tr.get_read_version()

        await db.run(fv)
        for _ in range(600):
            if all(s.version.get() >= v[0] for s in c.remote_storage):
                break
            await c.loop.delay(0.05)
        for ss in c.storage:
            ss.process.kill()
        await mgmt.configure_regions(db, primary="remote")
        for _ in range(6000):
            if c._region_promoted:
                break
            await c.loop.delay(0.05)
        assert c._region_promoted, "configured failover never applied"
        assert c.controller.region_config.primary == "remote"

        async def fn2(tr):
            tr.set(b"f999", b"post-failover")

        await db.run(fn2)

        async def rd(tr):
            return await tr.get_range(b"f", b"g", limit=1000)

        rows = await db.run(rd)
        assert len(rows) == 21
        # the router retires only once the promoted replicas are DURABLE
        # past the boundary (their MVCC-window hold-back) — keep the
        # version clock moving and wait it out
        for i in range(120):
            if c.log_router is None:
                break

            async def nudge(tr, i=i):
                tr.set(b"f-nudge", b"%d" % i)

            await db.run(nudge)
            await c.loop.delay(0.5)
        assert c.log_router is None  # the relay ended with the failover
        return True

    assert _run(c, main())
    from foundationdb_tpu.runtime import coverage

    assert coverage.census().get("region.router_retired", 0) >= 1
    c.stop()


def test_stop_cancels_midflight_promotion():
    """Satellite regression: stop() must cancel a mid-flight
    promote_remote_region() cleanly — the promotion's convergence wait
    dies with ActorCancelled instead of spinning against a stopped
    cluster."""
    c = RecoverableCluster(seed=7404, n_storage_shards=2, usable_regions=2)
    db = c.database()

    async def setup():
        for i in range(5):
            async def fn(tr, i=i):
                tr.set(b"m%02d" % i, b"1")

            await db.run(fn)
        return True

    assert _run(c, setup())
    # kill the ROUTER so the remote replicas stop converging: the
    # promotion's convergence wait can never complete
    c.log_router.process.kill()
    for ss in c.storage:
        ss.process.kill()
    t = c.loop.spawn(c.promote_remote_region())
    c.loop.run_until(c.loop.delay(2.0))
    assert not t.done(), "promotion should be stuck on convergence"
    assert c._region_task is not None
    c.stop()
    c.loop.run_until(c.loop.delay(0.5))
    assert t.done()
    assert isinstance(t.exception(), ActorCancelled)


def test_restart_remote_region_repulls_retained_backlog():
    """Remote-region power kill + reboot from its disks: the replacement
    router re-pulls the retained TLog backlog and the rebuilt replicas
    converge exactly (zero committed-data loss, structurally)."""
    c = RecoverableCluster(seed=7405, n_storage_shards=2, usable_regions=2)
    db = c.database()

    async def main():
        for i in range(15):
            async def fn(tr, i=i):
                tr.set(b"rr%03d" % i, b"v%d" % i)

            await db.run(fn)
        # region power loss: router + every remote replica at once
        c.log_router.process.kill()
        for ss in c.remote_storage:
            ss.process.kill()
        for i in range(15, 30):
            async def fn(tr, i=i):
                tr.set(b"rr%03d" % i, b"v%d" % i)

            await db.run(fn)
        c.restart_remote_region()
        v = [0]

        async def fv(tr):
            v[0] = await tr.get_read_version()

        await db.run(fv)
        for _ in range(2000):
            if all(s.version.get() >= v[0] for s in c.remote_storage):
                break
            await c.loop.delay(0.05)
        rdb = c.remote_database()

        async def rd(tr):
            return await tr.get_range(b"rr", b"rs", limit=1000)

        rows = await rdb.run(rd)
        assert len(rows) == 30
        assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))
        return True

    assert _run(c, main())
    from foundationdb_tpu.runtime import coverage

    assert coverage.census().get("region.router_repull", 0) >= 1
    c.stop()


# ---------------------------------------------------------------------------
# reboot-from-disk paths


def test_promoted_reboot_serves_from_former_remote():
    """After a completed failover, a whole-sim power kill + reboot must
    resolve the promoted keyServers map (remote tags) and serve every
    acked commit through the former remote region."""
    c = RecoverableCluster(seed=7406, n_storage_shards=2,
                          storage_replication=2, usable_regions=2)
    w = KillRegionWorkload(keys=24, burst=6)
    run_workloads(c, [w], deadline=900)
    assert c._region_promoted
    fs = c.power_off()

    c2 = RecoverableCluster(seed=7406, n_storage_shards=2,
                           storage_replication=2, usable_regions=2,
                           fs=fs, restart=True)
    assert c2._region_promoted
    assert all(
        t[0].startswith("remote-") for t in c2.controller.storage_teams_tags
    )
    w2 = KillRegionWorkload(keys=24, action="verify")
    w2.run_setup = False
    w2.part1_acked = w.acked  # what the manifest hook would carry
    res = run_workloads(c2, [w2], deadline=900)
    assert res["KillRegion"]["acked"] == 0
    c2.stop()


def test_promoted_reboot_inside_durability_window_loses_nothing():
    """Regression (KillRegionRestart seed 7711): a whole-sim power kill
    right after promotion — inside the promoted replicas' MVCC-window
    durability lag — must lose NO acked commit.  The router tag is still
    registered (retirement is durability-gated), so the reboot re-tags
    its retained backlog into the remote tags' seeds
    (region.router_seed_remap) and the replicas re-pull the stream they
    owe their disks."""
    c = RecoverableCluster(seed=7409, n_storage_shards=2,
                          storage_replication=2, usable_regions=2)
    db = c.database()

    async def main():
        for i in range(10):
            async def fn(tr, i=i):
                tr.set(b"w%03d" % i, b"v%d" % i)

            await db.run(fn)
        v = [0]

        async def fv(tr):
            v[0] = await tr.get_read_version()

        await db.run(fv)
        for _ in range(600):
            if all(s.version.get() >= v[0] for s in c.remote_storage):
                break
            await c.loop.delay(0.05)
        for ss in c.storage:
            ss.process.kill()
        assert await c.promote_remote_region()
        # every acked commit above is still INSIDE the promoted replicas'
        # durability window (their durable floor was ~0 at promotion):
        # the retained router backlog is the only copy a promoted reboot
        # can re-serve them
        return True

    assert _run(c, main())
    assert c.log_router is not None, (
        "retirement should still be pending inside the window"
    )
    fs = c.power_off()

    c2 = RecoverableCluster(seed=7409, n_storage_shards=2,
                           storage_replication=2, usable_regions=2,
                           fs=fs, restart=True)
    assert c2._region_promoted
    db2 = c2.database()

    async def read_all():
        async def fn(tr):
            return await tr.get_range(b"w", b"x", limit=1000)

        return await db2.run(fn)

    rows = dict(c2.run_until(c2.loop.spawn(read_all()), 900))
    assert rows == {b"w%03d" % i: b"v%d" % i for i in range(10)}
    from foundationdb_tpu.runtime import coverage

    assert coverage.census().get("region.router_seed_remap", 0) >= 1
    c2.stop()


def test_unpromoted_reboot_keeps_router_plane():
    """A two-region cluster rebooted BEFORE any failover rebuilds the
    router plane and the remote replicas converge again (the router tag
    rode the TLog seeds because the consumer is registered pre-boot)."""
    c = RecoverableCluster(seed=7407, n_storage_shards=2, usable_regions=2)
    db = c.database()

    async def put(n):
        for i in range(n):
            async def fn(tr, i=i):
                tr.set(b"u%03d" % i, b"v%d" % i)

            await db.run(fn)
        return True

    assert _run(c, put(10))
    fs = c.clean_shutdown()

    c2 = RecoverableCluster(seed=7407, n_storage_shards=2, usable_regions=2,
                           fs=fs, restart=True)
    assert not c2._region_promoted
    assert c2.log_router is not None
    db2 = c2.database()

    async def read_remote():
        v = [0]

        async def fv(tr):
            v[0] = await tr.get_read_version()

        await db2.run(fv)
        for _ in range(2000):
            if all(s.version.get() >= v[0] for s in c2.remote_storage):
                break
            await c2.loop.delay(0.05)
        rdb = c2.remote_database()

        async def rd(tr):
            return await tr.get_range(b"u", b"v", limit=1000)

        return await rdb.run(rd)

    rows = c2.run_until(c2.loop.spawn(read_remote()), 900)
    assert len(rows) == 10
    c2.stop()


# ---------------------------------------------------------------------------
# the composed workload + restarting pair


def test_kill_region_workload_standalone():
    c = RecoverableCluster(seed=7408, n_storage_shards=2,
                          storage_replication=2, usable_regions=2)
    w = KillRegionWorkload(keys=30, burst=6)
    res = run_workloads(c, [w], deadline=900)
    assert res["KillRegion"]["acked"] == 30
    assert res["KillRegion"]["kills"] == ["remote", "primary"]
    c.stop()


def test_kill_region_restart_pair_runs_green(tmp_path):
    from foundationdb_tpu.workloads.spec import run_restarting_pair

    res = run_restarting_pair(
        "tests/specs/restarting/KillRegionRestart.txt",
        image_dir=str(tmp_path / "image"),
    )
    assert res["part1"]["phase"] == 1
    assert res["part2"]["KillRegion"] is not None
