"""Sharded (multi-resolver mesh) conflict set parity tests.

Parity referee: N independent oracles, one per key partition, each seeing
only the ranges clipped to its partition, verdicts min-combined — exactly
the reference's multi-Resolver semantics (proxy min-combine
MasterProxyServer.actor.cpp:558-569, with each resolver inserting writes of
transactions it *locally* judged committed).
"""

import random

import pytest

from foundationdb_tpu.conflict.api import TxInfo, Verdict
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.parallel.sharded import ShardedDeviceConflictSet, make_resolver_mesh


def clip(r, lo, hi):
    b = max(r[0], lo)
    e = r[1] if hi is None else min(r[1], hi)
    return (b, e) if b < e else None


class MultiOracle:
    """N partition oracles + min-combine — the reference-semantics referee."""

    def __init__(self, split_keys, oldest=0):
        self._bounds = [b""] + list(split_keys) + [None]
        self._parts = [OracleConflictSet(oldest) for _ in split_keys] + [OracleConflictSet(oldest)]

    def resolve_batch(self, commit_version, txns):
        all_verdicts = []
        for i, part in enumerate(self._parts):
            lo, hi = self._bounds[i], self._bounds[i + 1]
            local = [
                TxInfo(
                    t.read_snapshot,
                    [c for r in t.read_ranges if (c := clip(r, lo, hi))],
                    [c for r in t.write_ranges if (c := clip(r, lo, hi))],
                )
                for t in txns
            ]
            all_verdicts.append(part.resolve_batch(commit_version, local))
        return [Verdict(min(int(v[i]) for v in all_verdicts)) for i in range(len(txns))]

    def remove_before(self, version):
        for p in self._parts:
            p.remove_before(version)


def random_key(rng, n=6):
    return bytes(rng.randrange(4) for _ in range(rng.randrange(1, n)))


def random_range(rng):
    a, b = random_key(rng), random_key(rng)
    if a == b:
        b = a + b"\x00"
    return (min(a, b), max(a, b))


def random_tx(rng, snap_lo, snap_hi):
    return TxInfo(
        read_snapshot=rng.randrange(snap_lo, snap_hi + 1),
        read_ranges=[random_range(rng) for _ in range(rng.randrange(0, 3))],
        write_ranges=[random_range(rng) for _ in range(rng.randrange(0, 3))],
    )


@pytest.fixture(scope="module")
def mesh():
    return make_resolver_mesh(4)


SPLITS = [b"\x01", b"\x02", b"\x03"]


def test_sharded_matches_multi_oracle(mesh):
    rng = random.Random(7)
    dev = ShardedDeviceConflictSet(mesh, SPLITS, capacity=1 << 10)
    ref = MultiOracle(SPLITS)
    version = 0
    for _ in range(30):
        version += rng.randrange(1, 5)
        txns = [random_tx(rng, max(version - 8, 0), version - 1) for _ in range(rng.randrange(1, 9))]
        got = dev.resolve_batch(version, txns)
        want = ref.resolve_batch(version, txns)
        assert got == want, f"at version {version}: {got} != {want}"


def test_sharded_gc_and_too_old(mesh):
    rng = random.Random(11)
    dev = ShardedDeviceConflictSet(mesh, SPLITS, capacity=1 << 10)
    ref = MultiOracle(SPLITS)
    version = 0
    for i in range(20):
        version += rng.randrange(1, 4)
        if i % 5 == 4:
            floor = max(version - 6, 0)
            dev.remove_before(floor)
            ref.remove_before(floor)
        txns = [random_tx(rng, max(version - 10, 0), version - 1) for _ in range(4)]
        assert dev.resolve_batch(version, txns) == ref.resolve_batch(version, txns)


def test_sharded_cross_partition_write(mesh):
    """A write range spanning several partitions must conflict reads in each."""
    dev = ShardedDeviceConflictSet(mesh, SPLITS, capacity=1 << 10)
    w = TxInfo(0, [], [(b"\x00\x88", b"\x03\x20")])  # spans all 4 partitions
    assert dev.resolve_batch(1, [w]) == [Verdict.COMMITTED]
    reads = [
        TxInfo(0, [(b"\x00\x90", b"\x00\x91")], []),  # partition 0
        TxInfo(0, [(b"\x01\x10", b"\x01\x11")], []),  # partition 1
        TxInfo(0, [(b"\x02\x10", b"\x02\x11")], []),  # partition 2
        TxInfo(0, [(b"\x03\x10", b"\x03\x11")], []),  # partition 3
        TxInfo(0, [(b"\x03\x30", b"\x04")], []),      # beyond the write
    ]
    got = dev.resolve_batch(2, reads)
    assert got == [Verdict.CONFLICT] * 4 + [Verdict.COMMITTED]


@pytest.fixture(scope="module")
def mesh8():
    return make_resolver_mesh(8)


SPLITS8 = [bytes([i]) for i in range(1, 8)]


def test_sharded_8dev_matches_multi_oracle(mesh8):
    """Full parity sweep on the 8-device mesh (the dryrun_multichip scale)."""
    rng = random.Random(23)
    dev = ShardedDeviceConflictSet(mesh8, SPLITS8, capacity=1 << 10)
    ref = MultiOracle(SPLITS8)
    version = 0
    for i in range(25):
        version += rng.randrange(1, 5)
        if i % 7 == 6:
            floor = max(version - 8, 0)
            dev.remove_before(floor)
            ref.remove_before(floor)
        txns = [random_tx(rng, max(version - 8, 0), version - 1) for _ in range(rng.randrange(1, 9))]
        got = dev.resolve_batch(version, txns)
        want = ref.resolve_batch(version, txns)
        assert got == want, f"at version {version}: {got} != {want}"


def test_sharded_capacity_regrow(mesh):
    """Overflowing one partition's boundary capacity must regrow (replaying
    from the pre-batch state), not raise — parity with the multi-oracle
    referee throughout.  Legacy (per-batch merge) path: the incremental
    path absorbs these batches as runs and regrows at the deferred fold
    instead (tests/test_pallas.py)."""
    dev = ShardedDeviceConflictSet(mesh, SPLITS, capacity=16, incremental=False)
    ref = MultiOracle(SPLITS)
    version = 0
    for b in range(3):
        version += 2
        # 20 distinct point writes per batch, all inside partition 0
        txns = [
            TxInfo(max(version - 2, 0), [], [(bytes([0, b, i]), bytes([0, b, i, 0]))])
            for i in range(20)
        ]
        assert dev.resolve_batch(version, txns) == ref.resolve_batch(version, txns)
    assert dev.regrows >= 1, "capacity overflow never triggered a regrow"
    assert dev.capacity > 16
    # state survived the regrow: a read over the inserted keys conflicts
    probe = [TxInfo(1, [(bytes([0, 0, 5]), bytes([0, 0, 6]))], [])]
    version += 1
    assert dev.resolve_batch(version, probe) == ref.resolve_batch(version, probe)


def test_sharded_pipelined_stream(mesh):
    """sync=False stream on the mesh: verdicts parity after a clean drain."""
    import numpy as np

    from foundationdb_tpu.conflict.device import pack_batch

    rng = random.Random(31)
    dev = ShardedDeviceConflictSet(mesh, SPLITS, capacity=1 << 10)
    ref = MultiOracle(SPLITS)
    version = 0
    outs, wants, lens = [], [], []
    for _ in range(10):
        version += rng.randrange(1, 4)
        txns = [random_tx(rng, max(version - 6, 0), version - 1) for _ in range(5)]
        packed = pack_batch(txns, dev.oldest_version, dev._offset, dev._max_key_bytes)
        outs.append(dev.resolve_arrays(version, *packed[:-1], sync=False))
        wants.append(ref.resolve_batch(version, txns))
        lens.append(len(txns))
    dev.check_pipelined()  # clean drain: no fallback, no overflow
    for got_dev, want, n in zip(outs, wants, lens):
        got = [Verdict(int(c)) for c in np.asarray(got_dev)[:n]]
        assert got == want


# -- LSM (two-level) state on the mesh: per-partition main+recent with
# sharded compaction (parallel/sharded.py _sharded_resolve_lsm) -------------


def test_sharded_lsm_matches_multi_oracle(mesh):
    rng = random.Random(21)
    dev = ShardedDeviceConflictSet(mesh, SPLITS, capacity=1 << 9, lsm=True,
                                   recent_capacity=64)
    ref = MultiOracle(SPLITS)
    version = 0
    for _ in range(30):
        version += rng.randrange(1, 5)
        txns = [random_tx(rng, max(version - 8, 0), version - 1)
                for _ in range(rng.randrange(1, 9))]
        got = dev.resolve_batch(version, txns)
        want = ref.resolve_batch(version, txns)
        assert got == want, f"at version {version}: {got} != {want}"
    # fold recent into main explicitly, then parity must still hold
    dev._compact()
    version += 1
    txns = [random_tx(rng, max(version - 8, 0), version - 1) for _ in range(6)]
    assert dev.resolve_batch(version, txns) == ref.resolve_batch(version, txns)
    assert dev.compactions >= 1


def test_sharded_lsm_gc_and_compaction_interleave(mesh):
    rng = random.Random(22)
    dev = ShardedDeviceConflictSet(mesh, SPLITS, capacity=1 << 9, lsm=True,
                                   recent_capacity=64)
    ref = MultiOracle(SPLITS)
    version = 0
    for i in range(40):
        version += rng.randrange(1, 4)
        txns = [random_tx(rng, max(version - 6, dev.oldest_version), version - 1)
                for _ in range(rng.randrange(1, 7))]
        got = dev.resolve_batch(version, txns)
        want = ref.resolve_batch(version, txns)
        assert got == want, f"v{version}: {got} != {want}"
        if i % 12 == 11:
            floor = version - 3
            dev.remove_before(floor)
            ref.remove_before(floor)


def test_sharded_lsm_pipelined_stream(mesh):
    import numpy as np
    from foundationdb_tpu.conflict.device import pack_batch

    rng = random.Random(23)
    dev = ShardedDeviceConflictSet(mesh, SPLITS, capacity=1 << 9, lsm=True,
                                   recent_capacity=128)
    ref = MultiOracle(SPLITS)
    version = 0
    pending = []
    for i in range(30):
        version += rng.randrange(1, 4)
        txns = [random_tx(rng, max(version - 8, 0), version - 1)
                for _ in range(rng.randrange(1, 7))]
        want = ref.resolve_batch(version, txns)
        packed = pack_batch(txns, dev._oldest, dev._offset, dev._max_key_bytes)
        got_dev = dev.resolve_arrays(version, *packed[:8], sync=False)
        pending.append((got_dev, want, len(txns)))
        if i % 9 == 8:
            dev.check_pipelined()
    dev.check_pipelined()
    for got_dev, want, B in pending:
        got = [Verdict(int(c)) for c in np.asarray(got_dev)[:B]]
        assert got == want


def test_sharded_gather_merge_matches_multi_oracle(mesh):
    """The gather merge under shard_map on the 4-device mesh (searchsorted
    rank trick + row gathers inside a sharded kernel) — bit-parity with the
    multi-partition oracle, single-level and LSM."""
    rng = random.Random(23)
    dev = ShardedDeviceConflictSet(
        mesh, SPLITS, capacity=1 << 10, merge_impl="gather"
    )
    lsm = ShardedDeviceConflictSet(
        mesh, SPLITS, capacity=1 << 10, merge_impl="gather",
        lsm=True, recent_capacity=1 << 6,
    )
    ref = MultiOracle(SPLITS)
    version = 0
    for _ in range(25):
        version += rng.randrange(1, 5)
        txns = [random_tx(rng, max(version - 8, 0), version - 1)
                for _ in range(rng.randrange(1, 9))]
        want = ref.resolve_batch(version, txns)
        assert dev.resolve_batch(version, txns) == want
        assert lsm.resolve_batch(version, txns) == want
