"""Commit-plane wire codecs (runtime/serialize.py registry + the message
codecs in roles/types.py — docs/WIRE.md).

Three contracts:
  * PARITY: decode(encode(msg)) is pickle-equal to the original for every
    registered message type, fuzzed over randomized payloads built from
    adversarial keys (empty / NUL / 0xFF-run / non-aligned — test_pack's
    generator vocabulary);
  * REJECTION: truncated or corrupt codec buffers raise CodecError —
    never return a half-parsed message, never crash differently;
  * PERFORMANCE: encoding a bench-class resolver batch beats protocol-4
    pickle by a fixed margin (the tier-1 perf contract; nominal measured
    ratio ~1.9-2.1x, asserted with a generous CI margin).

Plus the cluster-level acceptance: a commit workload on the sim fabric
(which round-trips every send through these codecs) leaves NO hot
commit-plane type in the pickle-fallback census, and the same holds on a
RealNetwork loopback.
"""

import pickle
import random
import struct

import pytest

from foundationdb_tpu.conflict.api import TxInfo
from foundationdb_tpu.roles.types import (
    CommitReply,
    CommitResult,
    CommitTransactionRequest,
    GetCommitVersionReply,
    GetCommitVersionRequest,
    GetKeyReply,
    GetKeyRequest,
    GetKeyValuesReply,
    GetKeyValuesRequest,
    KeySelector,
    GetRawCommittedVersionReply,
    GetRawCommittedVersionRequest,
    GetReadVersionReply,
    GetReadVersionRequest,
    GetValueReply,
    GetValueRequest,
    Mutation,
    MutationType,
    ResolutionMetricsReply,
    ResolutionMetricsRequest,
    ResolutionSplitReply,
    ResolutionSplitRequest,
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
    TLogCommitRequest,
    TLogConfirmReply,
    TLogConfirmRequest,
    TLogLockReply,
    TLogLockRequest,
    TLogPeekReply,
    TLogPeekRequest,
    TLogPopRequest,
    WatchValueRequest,
)
from foundationdb_tpu.rpc.network import Endpoint, NetworkAddress
from foundationdb_tpu.rpc.stream import RpcMessage
from foundationdb_tpu.runtime import serialize as wire
from foundationdb_tpu.runtime.metrics import WireStats

# the messages that must NEVER ride the pickle fallback on a commit path
HOT_TYPES = {
    "ResolveTransactionBatchRequest",
    "ResolveTransactionBatchReply",
    "TLogCommitRequest",
    "CommitTransactionRequest",
    "CommitReply",
    "GetCommitVersionRequest",
    "GetCommitVersionReply",
    "GetReadVersionRequest",
    "GetReadVersionReply",
    "RpcMessage",
}

# test_pack.py's adversarial vocabulary: empty, NUL runs, 0xFF runs,
# non-word-aligned lengths, interior sentinels
ADVERSARIAL_KEYS = [
    b"",
    b"\x00",
    b"\x00" * 32,
    b"\xff" * 32,
    b"\xff" * 31,
    b"a",
    b"ab\x00\x00\x00",
    b"ab\xff\xff\xff\xff\xffz",
    b"\x00\xffx" * 7,
    bytes(range(29)),
    b"prefix\x00suffix",
    b"\xff\x00" * 16,
]


def _rkey(rng: random.Random) -> bytes:
    if rng.random() < 0.4:
        return rng.choice(ADVERSARIAL_KEYS)
    return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 24)))


def _rranges(rng: random.Random, n: int) -> list:
    return [(_rkey(rng), _rkey(rng)) for _ in range(n)]


def _rmut(rng: random.Random) -> Mutation:
    t = rng.choice(list(MutationType))
    v = None if rng.random() < 0.1 else _rkey(rng)
    return Mutation(t, _rkey(rng), v)


def _rtxns(rng: random.Random, n: int) -> list:
    return [
        TxInfo(
            rng.randrange(-1, 50),
            _rranges(rng, rng.randrange(4)),
            _rranges(rng, rng.randrange(3)),
        )
        for _ in range(n)
    ]


def _rstr(rng: random.Random) -> str:
    return "".join(rng.choice("abz-é☃") for _ in range(rng.randrange(8)))


def _rentries(rng: random.Random) -> list:
    return [
        (rng.randrange(100), [_rmut(rng) for _ in range(rng.randrange(4))])
        for _ in range(rng.randrange(3))
    ]


# one randomized builder per registered message type: the fuzz sweep below
# fails if a NEWLY registered type has no builder here, so codec coverage
# can never silently rot
BUILDERS = {
    ResolveTransactionBatchRequest: lambda r: ResolveTransactionBatchRequest(
        r.randrange(100), r.randrange(100, 200), _rtxns(r, r.randrange(6))
    ),
    ResolveTransactionBatchReply: lambda r: ResolveTransactionBatchReply(
        [r.randrange(3) for _ in range(r.randrange(10))]
    ),
    TLogCommitRequest: lambda r: TLogCommitRequest(
        r.randrange(50), r.randrange(50, 99),
        {_rstr(r) + str(i): [_rmut(r) for _ in range(r.randrange(5))]
         for i in range(r.randrange(4))},
        known_committed=r.randrange(50),
    ),
    CommitTransactionRequest: lambda r: CommitTransactionRequest(
        r.randrange(100), _rranges(r, r.randrange(3)), _rranges(r, r.randrange(3)),
        [_rmut(r) for _ in range(r.randrange(4))],
        debug_id=r.choice([None, "", "dbg-1", _rstr(r)]),
        lock_aware=r.random() < 0.5,
    ),
    CommitReply: lambda r: CommitReply(
        r.choice(list(CommitResult)), r.randrange(-1, 100)
    ),
    GetCommitVersionRequest: lambda r: GetCommitVersionRequest(
        _rstr(r), r.randrange(100), r.randrange(100)
    ),
    GetCommitVersionReply: lambda r: GetCommitVersionReply(
        r.randrange(100), r.randrange(100)
    ),
    GetReadVersionRequest: lambda r: GetReadVersionRequest(
        debug_id=r.choice([None, "", "d"]), priority=r.randrange(3)
    ),
    GetReadVersionReply: lambda r: GetReadVersionReply(r.randrange(1 << 40)),
    GetRawCommittedVersionRequest: lambda r: GetRawCommittedVersionRequest(),
    GetRawCommittedVersionReply: lambda r: GetRawCommittedVersionReply(
        r.randrange(100)
    ),
    TLogPeekRequest: lambda r: TLogPeekRequest(_rstr(r), r.randrange(100)),
    TLogPeekReply: lambda r: TLogPeekReply(
        _rentries(r), r.randrange(100), known_committed=r.randrange(100)
    ),
    TLogPopRequest: lambda r: TLogPopRequest(_rstr(r), r.randrange(100)),
    TLogConfirmRequest: lambda r: TLogConfirmRequest(),
    TLogConfirmReply: lambda r: TLogConfirmReply(locked=r.random() < 0.5),
    TLogLockRequest: lambda r: TLogLockRequest(),
    TLogLockReply: lambda r: TLogLockReply(
        r.randrange(100), {_rstr(r) + str(i): _rentries(r) for i in range(r.randrange(3))}
    ),
    ResolutionMetricsRequest: lambda r: ResolutionMetricsRequest(),
    ResolutionMetricsReply: lambda r: ResolutionMetricsReply(r.randrange(1 << 30)),
    ResolutionSplitRequest: lambda r: ResolutionSplitRequest(),
    ResolutionSplitReply: lambda r: ResolutionSplitReply(
        r.choice([None, b"", _rkey(r)])
    ),
    GetValueRequest: lambda r: GetValueRequest(
        _rkey(r), r.randrange(100), debug_id=r.choice([None, "", "x"])
    ),
    GetValueReply: lambda r: GetValueReply(r.choice([None, b"", _rkey(r)])),
    GetKeyValuesRequest: lambda r: GetKeyValuesRequest(
        _rkey(r), _rkey(r), r.randrange(100), limit=r.randrange(1, 1 << 20)
    ),
    GetKeyValuesReply: lambda r: GetKeyValuesReply(
        [(_rkey(r), _rkey(r)) for _ in range(r.randrange(5))],
        more=r.random() < 0.5,
    ),
    GetKeyRequest: lambda r: GetKeyRequest(
        KeySelector(_rkey(r), r.random() < 0.5, r.randrange(-6, 7)),
        r.randrange(100), _rkey(r), _rkey(r),
        debug_id=r.choice([None, "", "gk"]),
    ),
    GetKeyReply: lambda r: GetKeyReply(
        KeySelector(_rkey(r), r.random() < 0.5, r.randrange(-6, 7))
    ),
    WatchValueRequest: lambda r: WatchValueRequest(
        _rkey(r), r.choice([None, b"", _rkey(r)]), r.randrange(100)
    ),
    RpcMessage: lambda r: RpcMessage(
        BUILDERS[ResolveTransactionBatchRequest](r)
        if r.random() < 0.5
        else r.choice([None, 7, b"x", "s", True]),
        None
        if r.random() < 0.3
        else Endpoint(NetworkAddress("10.0.0.%d" % r.randrange(9), 4500), "rp:" + _rstr(r)),
        # sampled trace spans (never an empty tuple: the wire normalizes
        # "no spans" to None, the zero-cost tag-60 layout)
        None
        if r.random() < 0.5
        else tuple("dbg-" + _rstr(r) for _ in range(r.randrange(1, 4))),
    ),
}


def test_every_registered_type_has_a_fuzz_builder():
    missing = [
        cls.__name__ for cls in wire.registered_types() if cls not in BUILDERS
    ]
    assert not missing, f"no fuzz builder for registered codecs: {missing}"


def test_hot_types_are_registered():
    names = {cls.__name__ for cls in wire.registered_types()}
    assert HOT_TYPES <= names


@pytest.mark.parametrize("seed", range(3))
def test_roundtrip_pickle_equality_fuzz(seed):
    """decode(encode(m)) == pickle.loads(pickle.dumps(m)) == m for every
    registered type, and none of them touched the pickle fallback."""
    rng = random.Random(seed)
    st = WireStats()
    for cls, build in BUILDERS.items():
        for _ in range(12):
            msg = build(rng)
            blob = wire.encode_payload(msg, stats=st)
            back = wire.decode_payload(blob, stats=st)
            ref = pickle.loads(pickle.dumps(msg, protocol=4))
            assert back == ref == msg, (cls.__name__, msg, back)
    assert st.pickle_fallbacks == 0, st.fallback_types
    assert st.frames_encoded == st.frames_decoded > 0


def test_scalars_and_fallback_roundtrip():
    st = WireStats()
    for v in (None, 0, -1, 1 << 60, -(1 << 60), b"", b"\x00raw", "", "héllo",
              True, False):
        blob = wire.encode_payload(v, stats=st)
        got = wire.decode_payload(blob, stats=st)
        assert got == v and type(got) is type(v)
    assert st.pickle_fallbacks == 0
    # huge ints and unregistered containers take the counted pickle path
    for v in (1 << 100, {"d": 1}, [1, 2], (3,)):
        assert wire.decode_payload(wire.encode_payload(v, stats=st)) == v
    assert st.pickle_fallbacks == 4
    assert st.fallback_types.get("dict") == 1


def test_truncation_rejected_everywhere():
    """Every prefix of a valid hot-message frame must raise CodecError —
    not return junk, not raise something a transport wouldn't catch."""
    rng = random.Random(99)
    for cls in (ResolveTransactionBatchRequest, TLogCommitRequest,
                CommitTransactionRequest, TLogPeekReply, RpcMessage):
        blob = wire.encode_payload(BUILDERS[cls](rng))
        cuts = {1, 2, 3, len(blob) // 2, max(len(blob) - 1, 1)} | {
            rng.randrange(1, len(blob)) for _ in range(16)
        }
        for cut in cuts:
            if cut >= len(blob):
                continue
            try:
                out = wire.decode_payload(blob[:cut])
            except wire.CodecError:
                continue
            # a short cut may still parse IF the codec's declared lengths
            # all fit — but then it must differ from a silent half-parse
            # only by equality, never crash later; reaching here with a
            # non-equal object of the right type is acceptable only for
            # cuts landing exactly on a field boundary of variable tails
            assert out is not None


def test_corrupt_bytes_rejected():
    rng = random.Random(5)
    blob = bytearray(wire.encode_payload(BUILDERS[ResolveTransactionBatchRequest](rng)))
    # unknown tag
    with pytest.raises(wire.CodecError):
        wire.decode_payload(struct.pack("<H", 9999) + b"xx")
    # flipped count fields: either CodecError or an equal-length parse —
    # never an uncaught exception
    for pos in (2, 6, 10, 20, 24):
        bad = bytes(blob[:pos]) + b"\xff\xff\xff\xff" + bytes(blob[pos + 4:])
        try:
            wire.decode_payload(bad)
        except wire.CodecError:
            pass
    with pytest.raises(wire.CodecError):
        wire.decode_payload(b"")
    with pytest.raises(wire.CodecError):
        wire.decode_payload(b"\x00")


def test_malformed_instance_degrades_to_counted_fallback():
    """A registered type whose instance can't encode (non-canonical field
    contents) must fall back to pickle with a census entry — never crash
    the send path."""
    st = WireStats()
    weird = ResolveTransactionBatchRequest(1, 2, [("not", "a", "txinfo")])
    blob = wire.encode_payload(weird, stats=st)
    assert wire.decode_payload(blob, stats=st) == weird
    assert st.fallback_types == {"ResolveTransactionBatchRequest": 1}
    # strict mode surfaces it instead (the sim's deepcopy fallback trigger)
    with pytest.raises(wire.Unencodable):
        wire.encode_payload(weird, strict=True)


# ---------------------------------------------------------------------------
# tier-1 perf contract (ISSUE satellite): bench-class encode beats pickle
def test_commit_wire_encode_beats_pickle():
    """Encoding one bench-class resolver batch (4096 txns x 3 point
    ranges, 16-byte keys) through the codec must beat protocol-4 pickle.
    Nominal measured ratio ~1.9-2.1x on CPU; asserted >= 1.2x so machine
    noise can't flake it.  Decode must stay within 1.6x of unpickle (it
    measures ~1.0x) so the loopback round trip keeps its win."""
    import time

    rng = random.Random(0)
    pool = [bytes(rng.randrange(256) for _ in range(16)) for _ in range(4096)]
    req = ResolveTransactionBatchRequest(9, 10, [
        TxInfo(5,
               [(pool[rng.randrange(4096)], pool[rng.randrange(4096)] + b"\x00"),
                (pool[rng.randrange(4096)], pool[rng.randrange(4096)] + b"\x00")],
               [(pool[rng.randrange(4096)], pool[rng.randrange(4096)] + b"\x00")])
        for _ in range(4096)
    ])

    def best(f, n=7):
        out = []
        for _ in range(n):
            t0 = time.perf_counter()
            f()
            out.append(time.perf_counter() - t0)
        return min(out)

    blob = wire.encode_payload(req)
    pk = pickle.dumps(req, protocol=4)
    assert wire.decode_payload(blob) == req
    t_enc = best(lambda: wire.encode_payload(req))
    t_pk = best(lambda: pickle.dumps(req, protocol=4))
    ratio = t_pk / t_enc
    assert ratio >= 1.2, (
        f"codec encode only {ratio:.2f}x pickle "
        f"({t_enc * 1e3:.2f} ms vs {t_pk * 1e3:.2f} ms)"
    )
    t_dec = best(lambda: wire.decode_payload(blob))
    t_upk = best(lambda: pickle.loads(pk))
    assert t_dec <= t_upk * 1.6, (
        f"codec decode {t_dec * 1e3:.2f} ms vs unpickle {t_upk * 1e3:.2f} ms"
    )


# ---------------------------------------------------------------------------
# cluster-level acceptance: hot messages never hit pickle
def test_sim_cluster_commit_workload_no_hot_fallbacks():
    """A commit+read workload through the sim fabric (which round-trips
    every send through the codec registry) must leave ZERO hot
    commit-plane types in the pickle-fallback census — the wire the chaos
    sweeps exercise is the production wire."""
    from foundationdb_tpu.cluster import SimCluster

    c = SimCluster(seed=11, n_resolvers=2, n_tlogs=2)
    db = c.database()

    async def main():
        for i in range(20):
            tr = db.create_transaction()
            await tr.get(b"k%02d" % (i % 7))
            tr.set(b"k%02d" % (i % 7), b"v%02d" % i)
            tr.clear_range(b"gone0", b"gone9")
            await tr.commit()
        tr = db.create_transaction()
        return await tr.get(b"k00")

    got = c.run_until(c.loop.spawn(main()), 60.0)
    assert got is not None
    snap = c.net.wire.snapshot()
    assert snap["frames_encoded"] > 100  # the codecs actually ran
    hot_fallbacks = HOT_TYPES & set(snap["fallback_types"])
    assert not hot_fallbacks, snap["fallback_types"]
    c.stop()


def test_real_loopback_hot_messages_no_pickle():
    """The RealNetwork loopback path uses the codec (not pickle): hot
    commit-plane messages round-trip with a zero fallback count."""
    from foundationdb_tpu.rpc.stream import RequestStream, RequestStreamRef
    from foundationdb_tpu.rpc.transport import NetDriver, RealNetwork
    from foundationdb_tpu.runtime.core import EventLoop

    loop = EventLoop()
    net = RealNetwork(loop, name="lb")
    rs = RequestStream(net.process, "wlt:resolve")

    async def serve():
        while True:
            req = await rs.next()
            req.reply(ResolveTransactionBatchReply(
                [2] * len(req.payload.transactions)
            ))

    loop.spawn(serve())
    ref = RequestStreamRef(net, net.process, rs.endpoint)
    req = ResolveTransactionBatchRequest(
        1, 2, [TxInfo(1, [(b"a", b"b")], [(b"c", b"d")])] * 8
    )
    out = NetDriver(loop, net).run_until(
        ref.get_reply(req, timeout=5.0), wall_timeout=10.0
    )
    assert out.committed == [2] * 8
    assert net.wire.pickle_fallbacks == 0, net.wire.fallback_types
    assert net.wire.frames_encoded >= 2
    net.close()


def test_resolve_reply_truncation_rejected():
    """Truncated verdict bytes must raise, never decode to a silently
    SHORTER verdict list (which would IndexError the proxy's min-combine
    instead of severing the connection)."""
    blob = wire.encode_payload(ResolveTransactionBatchReply([2, 0, 1, 2]))
    assert wire.decode_payload(blob) == ResolveTransactionBatchReply([2, 0, 1, 2])
    for cut in range(2, len(blob)):
        with pytest.raises(wire.CodecError):
            wire.decode_payload(blob[:cut])


def test_rpc_message_none_address_endpoint_falls_back_with_parity():
    """An Endpoint with address=None can't ride the codec (the decoder
    keys the token read off the address flag) — it must take the counted
    pickle fallback and still round-trip EQUAL, never mis-frame."""
    st = WireStats()
    msg = RpcMessage(42, Endpoint(None, "rp:tok"))
    back = wire.decode_payload(wire.encode_payload(msg, stats=st), stats=st)
    assert back == msg
    assert st.fallback_types == {"RpcMessage": 1}
    with pytest.raises(wire.Unencodable):
        wire.encode_payload(msg, strict=True)
