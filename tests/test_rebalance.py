"""Online resolver rebalancing: a hot key prefix pulls a partition boundary
toward the load, mid-run, without breaking any transactional invariant.

Reference: masterserver.actor.cpp:964 resolutionBalancing,
Resolver.actor.cpp:276-284 ResolutionMetrics/Split, and the proxies'
version-indexed keyResolvers map (MasterProxyServer.actor.cpp:287-299).
"""

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.consistency import ConsistencyCheckWorkload
from foundationdb_tpu.workloads.cycle import CycleWorkload
from foundationdb_tpu.workloads.readwrite import ReadWriteWorkload


def test_hot_prefix_triggers_split_migration():
    """All load lands below 0x80 (resolver 0); the balancer must move the
    boundary into the hot prefix mid-run, and every invariant holds."""
    c = RecoverableCluster(seed=86, n_resolvers=2, n_storage_shards=2)
    assert c.controller.resolver_splits == [b"\x80"]
    cyc = CycleWorkload(nodes=12, clients=4, txns_per_client=12)
    rw = ReadWriteWorkload(keys=300, clients=4, duration=4.0)
    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cyc, rw, cons], deadline=600.0)
    assert metrics["Cycle"]["committed"] == 48
    assert metrics["ReadWrite"]["committed"] > 0
    assert c.controller.resolver_moves >= 1, "no split migration happened"
    # the boundary moved INTO the hot ascii range
    assert c.controller.resolver_splits[0] < b"\x80"
    c.stop()


def test_rebalance_is_deterministic():
    def once():
        c = RecoverableCluster(seed=87, n_resolvers=2)
        rw = ReadWriteWorkload(keys=200, clients=4, duration=3.0)
        m = run_workloads(c, [rw], deadline=600.0)
        out = (
            m["ReadWrite"]["committed"],
            c.controller.resolver_moves,
            list(c.controller.resolver_splits),
            round(c.loop.now(), 9),
        )
        c.stop()
        return out

    a, b = once(), once()
    assert a == b, f"rebalancing not deterministic:\n{a}\n{b}"
    assert a[1] >= 1  # the deterministic runs actually rebalanced


def test_rebalance_survives_recovery():
    """A split move followed by a pipeline kill: the new generation starts
    from the moved splits and the workload still completes exactly."""
    from foundationdb_tpu.workloads.attrition import AttritionWorkload

    c = RecoverableCluster(seed=88, n_resolvers=2, n_storage_shards=2)
    cyc = CycleWorkload(nodes=10, clients=3, txns_per_client=10)
    rw = ReadWriteWorkload(keys=200, clients=3, duration=4.0)
    att = AttritionWorkload(kills=1, interval=2.5, start_delay=2.0)
    metrics = run_workloads(c, [cyc, rw, att], deadline=600.0)
    assert metrics["Cycle"]["committed"] == 30
    assert c.controller.recoveries >= 1
    c.stop()
