"""Data distribution: MoveKeys-style range moves, auto shard splitting, and
dead-replica healing (fdbserver/DataDistribution.actor.cpp,
MoveKeys.actor.cpp:875, storageserver.actor.cpp fetchKeys)."""

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.workloads.bank import BankWorkload
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.consistency import ConsistencyCheckWorkload


def _put_many(c, db, n, prefix=b"k"):
    async def main():
        for base in range(0, n, 50):
            tr = db.create_transaction()
            for i in range(base, min(base + 50, n)):
                tr.set(prefix + b"%05d" % i, b"v%d" % i)
            await tr.commit()

    c.run_until(c.loop.spawn(main()), 600)


def _get_all(c, db, begin=b"k", end=b"l"):
    async def main():
        async def fn(tr):
            return await tr.get_range(begin, end, limit=100000)

        return await db.run(fn)

    return c.run_until(c.loop.spawn(main()), 600)


def test_move_range_between_teams():
    """An explicit range move: data lands on the dest team, reads stay
    correct throughout, and the source team drops its copy."""
    c = RecoverableCluster(seed=201, n_storage_shards=2, storage_replication=2,
                           durable=False)
    db = c.database()
    _put_many(c, db, 200)  # keys k00000..k00199 all in shard 0 (prefix 'k')

    assert b"k" < c.controller.storage_splits[0]  # sanity: data in shard 0
    src = list(c.controller.storage_teams_tags[0])
    dest = list(c.controller.storage_teams_tags[1])

    moved = c.run_until(
        c.loop.spawn(c.dd.move_range(b"k00100", b"k00150", dest)), 600
    )
    assert moved
    assert c.dd.moves == 1
    # the map now has extra boundaries and the moved segment belongs to dest
    splits = c.controller.storage_splits
    assert b"k00100" in splits and b"k00150" in splits
    seg = splits.index(b"k00100") + 1
    assert c.controller.storage_teams_tags[seg] == dest

    rows = _get_all(c, db)
    assert len(rows) == 200
    assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))

    # dest servers hold the moved segment
    for tag in dest:
        ss = c.controller._tag_to_ss[tag]
        n = ss.store.count_range(b"k00100", b"k00150") + sum(
            1 for _ in ss.overlay.overlay_keys_in(b"k00100", b"k00150")
        )
        assert n >= 50

    # source drop is delayed; advance sim time past it
    async def wait():
        await c.loop.delay(3.0)

    c.run_until(c.loop.spawn(wait()), 600)
    for tag in src:
        ss = c.controller._tag_to_ss[tag]
        assert ss.store.count_range(b"k00100", b"k00150") == 0

    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cons], deadline=300.0)
    assert metrics["ConsistencyCheck"]["shards_checked"] == len(
        c.controller.storage_teams_tags
    )
    c.stop()


def test_move_range_under_load():
    """Bank invariant holds while a range containing the accounts moves."""
    c = RecoverableCluster(seed=202, n_storage_shards=2, storage_replication=2,
                           durable=False)
    bank = BankWorkload(accounts=8, clients=2, transfers_per_client=10)

    async def mover():
        await c.loop.delay(0.3)
        dest = list(c.controller.storage_teams_tags[0])
        # bank keys live under b"bank/" (shard 0); move a slice to... the
        # other team.  Work out which shard holds them first.
        import bisect

        i = bisect.bisect_right(c.controller.storage_splits, b"bank/")
        src_idx = i
        dest = next(
            list(t)
            for j, t in enumerate(c.controller.storage_teams_tags)
            if set(t) != set(c.controller.storage_teams_tags[src_idx])
        )
        bounds = [b""] + list(c.controller.storage_splits) + [None]
        ok = await c.dd.move_range(b"bank/", bounds[src_idx + 1], dest)
        return ok

    mover_task = c.loop.spawn(mover())
    metrics = run_workloads(c, [bank], deadline=600.0)
    assert metrics["Bank"]["committed"] == 20
    assert c.run_until(mover_task, 600)

    cons = ConsistencyCheckWorkload()
    m2 = run_workloads(c, [cons], deadline=300.0)
    assert m2["ConsistencyCheck"]["shards_checked"] >= 2
    c.stop()


def test_auto_shard_split():
    """A shard past DD_SHARD_SPLIT_KEYS splits at its median and the hot
    half migrates to the smallest team."""
    c = RecoverableCluster(seed=203, n_storage_shards=2, storage_replication=2,
                           durable=False)
    c.knobs.DD_SHARD_SPLIT_KEYS = 60
    db = c.database()
    _put_many(c, db, 200)  # all into one shard

    async def wait_split():
        for _ in range(200):
            if c.dd.shard_splits >= 1:
                return True
            await c.loop.delay(0.2)
        return False

    assert c.run_until(c.loop.spawn(wait_split()), 600)
    assert len(c.controller.storage_teams_tags) >= 3  # a boundary was added
    rows = _get_all(c, db)
    assert len(rows) == 200
    assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))
    c.stop()


def test_heal_dead_replica():
    """A killed storage replica is replaced: the new server takes the tag,
    fetches from the survivor, and the team is whole again."""
    c = RecoverableCluster(seed=204, n_storage_shards=2, storage_replication=2,
                           durable=False)
    db = c.database()
    _put_many(c, db, 100)

    victim = next(s for s in c.storage if s.tag == "ss-0-r0")
    victim.process.kill()

    async def wait_heal():
        for _ in range(300):
            if c.dd.heals >= 1:
                return True
            await c.loop.delay(0.1)
        return False

    assert c.run_until(c.loop.spawn(wait_heal()), 600)
    replacement = c.controller._tag_to_ss["ss-0-r0"]
    assert replacement is not victim
    assert replacement.process.alive

    # writes and reads still work, and the replacement holds real data
    _put_many(c, db, 100, prefix=b"m")
    rows = _get_all(c, db)
    assert len(rows) == 100

    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cons], deadline=300.0)
    assert metrics["ConsistencyCheck"]["shards_checked"] == 2
    assert metrics["ConsistencyCheck"]["replicas_compared"] == 4  # healed!
    c.stop()


def test_move_survives_restart():
    """The keyServers map is durable: after a move + power loss, the
    restarted cluster routes the range to the destination team's files."""
    c = RecoverableCluster(seed=207, n_storage_shards=2, storage_replication=2,
                           durable=True)
    db = c.database()
    _put_many(c, db, 60)

    dest = list(c.controller.storage_teams_tags[1])
    moved = c.run_until(
        c.loop.spawn(c.dd.move_range(b"k00020", b"k00040", dest)), 900
    )
    assert moved

    async def settle():
        await c.loop.delay(8.0)  # past the MVCC window: stores durable

    c.run_until(c.loop.spawn(settle()), 600)
    fs = c.power_off()
    c2 = RecoverableCluster(seed=208, n_storage_shards=2,
                            storage_replication=2, fs=fs, restart=True)
    # the restarted controller recovered the moved map, not the convention
    assert b"k00020" in c2.controller.storage_splits
    seg = c2.controller.storage_splits.index(b"k00020") + 1
    assert c2.controller.storage_teams_tags[seg] == dest
    db2 = c2.database()
    rows = _get_all(c2, db2)
    assert len(rows) == 60
    assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))
    c2.stop()


def test_heal_durable_cluster_restart():
    """Heal on a durable cluster writes to the dead server's file lineage:
    a later power-off + restart recovers the healed data."""
    c = RecoverableCluster(seed=205, n_storage_shards=1, storage_replication=2,
                           durable=True)
    db = c.database()
    _put_many(c, db, 40)

    victim = next(s for s in c.storage if s.tag == "ss-0-r0")
    victim.process.kill()

    async def wait_heal():
        for _ in range(300):
            if c.dd.heals >= 1:
                return True
            await c.loop.delay(0.1)
        return False

    assert c.run_until(c.loop.spawn(wait_heal()), 900)
    _put_many(c, db, 40, prefix=b"p")

    # let storage durability catch up, then power off and restart
    async def settle():
        await c.loop.delay(2.0)

    c.run_until(c.loop.spawn(settle()), 600)
    fs = c.power_off()
    c2 = RecoverableCluster(seed=206, n_storage_shards=1,
                            storage_replication=2, fs=fs, restart=True)
    db2 = c2.database()
    rows = _get_all(c2, db2)
    assert len(rows) == 40
    rows_p = _get_all(c2, db2, b"p", b"q")
    assert len(rows_p) == 40
    c2.stop()


def test_split_on_bytes_threshold():
    """Big values trip the BYTE threshold long before the key count does
    (the reference splits on bytes via StorageMetrics)."""
    c = RecoverableCluster(seed=208, n_storage_shards=2, storage_replication=2,
                           durable=False)
    c.knobs.DD_SHARD_SPLIT_BYTES = 20_000
    db = c.database()

    async def put():
        for base in range(0, 60, 20):
            tr = db.create_transaction()
            for i in range(base, base + 20):
                tr.set(b"big%04d" % i, b"x" * 900)
            await tr.commit()

    c.run_until(c.loop.spawn(put()), 600)

    async def wait_split():
        for _ in range(200):
            if c.dd.shard_splits >= 1:
                return True
            await c.loop.delay(0.2)
        return False

    assert c.run_until(c.loop.spawn(wait_split()), 600)
    rows = _get_all(c, db, b"big", b"bih")
    assert len(rows) == 60
    c.stop()


def test_split_on_write_bandwidth():
    """A small-but-write-hot shard splits on bandwidth alone (the other
    half of shardSplitter's decision)."""
    c = RecoverableCluster(seed=209, n_storage_shards=2, storage_replication=2,
                           durable=False)
    c.knobs.DD_SHARD_SPLIT_WRITE_BYTES_PER_SEC = 2_000
    c.knobs.DD_SHARD_SPLIT_BYTES = 1 << 40       # never by size
    c.knobs.DD_SHARD_SPLIT_KEYS = 1 << 40        # never by count
    db = c.database()

    async def hammer():
        # sustained overwrites of a handful of keys: tiny shard, hot writes
        for round_ in range(60):
            tr = db.create_transaction()
            for i in range(4):
                tr.set(b"hot%02d" % i, b"w" * 200)
            await tr.commit()
            await c.loop.delay(0.05)
        # the move's flip waits for destination durability (~1 MVCC window)
        for _ in range(200):
            if c.dd.shard_splits >= 1:
                return True
            await c.loop.delay(0.2)
        return False

    assert c.run_until(c.loop.spawn(hammer()), 600)
    rows = _get_all(c, db, b"hot", b"hou")
    assert len(rows) == 4
    c.stop()


def test_auto_shard_merge():
    """shardMerger: after a split's data is deleted, the two tiny adjacent
    shards collapse back into one (boundary dropped at a drained barrier)
    with zero data loss."""
    c = RecoverableCluster(seed=208, n_storage_shards=2, storage_replication=2,
                           durable=False)
    c.knobs.DD_SHARD_SPLIT_KEYS = 60
    c.knobs.DD_SHARD_MERGE_KEYS = 20
    c.knobs.DD_SHARD_MERGE_BYTES = 4000
    db = c.database()
    _put_many(c, db, 200)

    async def main():
        for _ in range(200):
            if c.dd.shard_splits >= 1:
                break
            await c.loop.delay(0.2)
        assert c.dd.shard_splits >= 1
        n_shards_split = len(c.controller.storage_teams_tags)

        # delete almost everything: the split shards are now tiny
        async def wipe(tr):
            tr.clear_range(b"k", b"l")
        await db.run(wipe)
        async def keep(tr):
            for i in range(5):
                tr.set(b"k%04d" % i, b"v%d" % i)
        await db.run(keep)

        for _ in range(400):
            if c.dd.shard_merges >= 1:
                break
            await c.loop.delay(0.2)
        assert c.dd.shard_merges >= 1
        assert len(c.controller.storage_teams_tags) < n_shards_split
        tr = db.create_transaction()
        rows = await tr.get_range(b"k", b"l", limit=1000)
        assert [k for k, _v in rows] == [b"k%04d" % i for i in range(5)]
        # writes still flow on the merged map
        async def w(tr):
            tr.set(b"post-merge", b"1")
        await db.run(w)
        return True

    assert c.run_until(c.loop.spawn(main()), 900)
    c.stop()
