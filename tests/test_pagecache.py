"""File-level page cache + read-ahead (storage/pagecache.py, the
AsyncFileCached analog): LRU byte bound, sequential read-ahead in one
pread, coherence across truncate/append/power-kill, fault-plane layering
(corrupt-on-read never cached, ENOSPC/stall/injected errors propagate),
and the tier-1 perf smoke pinning that a cold range scan does fewer disk
reads with the cache on — counter-based, so it can't flake."""

import pytest

from foundationdb_tpu.runtime import buggify, coverage
from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
from foundationdb_tpu.storage.btree import BTreeKeyValueStore
from foundationdb_tpu.storage.files import DiskFull, SimFilesystem
from foundationdb_tpu.storage.pagecache import CachedFile, PageCachePool


def _fixture(pool_bytes=1 << 20, page=4096, readahead=8):
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(3))
    fs.page_pool = PageCachePool(page, pool_bytes, readahead)
    return loop, fs


def _cached(fs, path="f", process=None) -> CachedFile:
    return CachedFile(fs.open(path, process), fs.page_pool)


def _preads(fs, path="f") -> int:
    return fs.disk(path).ops


# ---- basic correctness ------------------------------------------------------

def test_pread_matches_raw_file_across_offsets():
    loop, fs = _fixture(page=64)
    f = _cached(fs)
    data = bytes(range(256)) * 7  # 1792 bytes, spans many 64B pages
    f.append(data)
    raw = fs.open("f", None)
    for off, ln in [(0, 10), (60, 10), (63, 2), (64, 64), (100, 700),
                    (0, 1792), (1700, 500), (1791, 1), (1792, 5), (2000, 3)]:
        assert f.pread(off, ln) == raw.pread(off, ln), (off, ln)
    # and again — everything below the tail now served from cache
    before = _preads(fs)
    assert f.pread(0, 1024) == data[:1024]
    assert _preads(fs) == before  # full pages all cached


def test_partial_tail_page_never_cached_append_stays_coherent():
    loop, fs = _fixture(page=64)
    f = _cached(fs)
    f.append(b"a" * 100)          # page 0 full, page 1 partial
    assert f.pread(0, 100) == b"a" * 100
    f.append(b"b" * 100)          # extends the partial tail
    assert f.pread(0, 200) == b"a" * 100 + b"b" * 100


def test_lru_pool_stays_byte_bounded_and_evicts():
    loop, fs = _fixture(pool_bytes=4 * 64, page=64)
    f = _cached(fs)
    f.append(bytes(64) * 32)
    for p in range(32):
        f.pread(p * 64, 64)
    pool = fs.page_pool
    assert pool.bytes <= 4 * 64
    assert pool.evictions > 0
    assert coverage.hits("cache.evict") > 0


def test_readahead_fetches_run_in_one_pread():
    loop, fs = _fixture(page=64, readahead=8)
    f = _cached(fs)
    f.append(bytes(64) * 32)
    # a sequential page-by-page scan: after the first two demand misses
    # establish the run, read-ahead batches the rest
    ops0 = _preads(fs)
    for p in range(16):
        f.pread(p * 64, 64)
    seq_ops = _preads(fs) - ops0
    assert seq_ops < 16  # far fewer disk reads than pages
    assert f.readahead_pages > 0
    assert f.readahead_hits > 0
    assert fs.page_pool.readahead_batches > 0
    assert coverage.hits("cache.readahead") > 0
    assert coverage.hits("cache.readahead_hit") > 0


def test_truncate_and_cancel_invalidate_cached_pages():
    loop, fs = _fixture(page=64)
    f = _cached(fs)
    f.append(b"x" * 256)

    async def run():
        await f.sync()
        assert f.pread(0, 64) == b"x" * 64   # cached
        f.truncate()
        assert f.pread(0, 64) == b""          # truncated view, not stale
        f.cancel_truncate()
        assert f.pread(0, 64) == b"x" * 64   # restored view
        f.truncate()
        f.append(b"y" * 256)
        assert f.pread(0, 64) == b"y" * 64

    loop.run_until(loop.spawn(run()), 60)
    assert fs.page_pool.invalidations > 0


def test_power_kill_drops_unsynced_and_invalidates():
    """A cached page holding buffered (un-fsynced) bytes must die with
    the process: after the kill the read reflects the REGRESSED durable
    contents, never the cache's memory of dropped data."""
    loop, fs = _fixture(page=64)
    from foundationdb_tpu.rpc.network import SimNetwork

    net = SimNetwork(loop, DeterministicRandom(1), None)
    proc = net.create_process("victim")
    f = CachedFile(fs.open("f", proc), fs.page_pool)

    async def run():
        f.append(b"d" * 128)
        await f.sync()
        f.append(b"u" * 128)            # buffered only
        assert f.pread(128, 64) == b"u" * 64  # caches a full buffered page
        proc.kill()                      # drops unsynced + invalidates

    loop.run_until(loop.spawn(run()), 60)
    assert f.pread(128, 64) == b""      # regressed, not served stale
    assert f.pread(0, 128) == b"d" * 128


# ---- fault-plane layering ---------------------------------------------------

def test_corrupt_read_is_never_cached_reread_heals():
    loop, fs = _fixture(page=64)
    from foundationdb_tpu.rpc.network import SimNetwork

    net = SimNetwork(loop, DeterministicRandom(1), None)
    f = CachedFile(fs.open("f", net.create_process("reader")), fs.page_pool)
    data = bytes(range(64)) * 4
    f.append(data)
    buggify.enable(DeterministicRandom(3))
    assert f.pread(0, 256) == data      # warm the cache, no fault armed
    buggify.force("disk.corrupt_read", 1)
    flipped = f.pread(0, 256)
    assert flipped != data              # the transient flip reached us
    assert coverage.hits("cache.corrupt_read_not_cached") == 1
    # the retry heals FROM CACHE: clean bytes, and no new disk read
    ops0 = _preads(fs)
    assert f.pread(0, 256) == data
    assert _preads(fs) == ops0
    assert fs.disk_usage()["f"]["corrupt_reads"] == 1


def test_enospc_and_injected_errors_propagate_through_cache():
    loop, fs = _fixture()
    f = _cached(fs)
    fs.set_capacity("f", 100)
    with pytest.raises(DiskFull):
        f.append(b"z" * 200)
    fs.set_capacity("f", None)
    fs.inject_errors("f", 1)
    with pytest.raises(IOError):
        f.append(b"z" * 10)


def test_stall_and_io_timeout_kill_reach_through_cache():
    loop, fs = _fixture()
    fs.io_timeout_s = 1.0
    from foundationdb_tpu.rpc.network import SimNetwork

    net = SimNetwork(loop, DeterministicRandom(1), None)
    proc = net.create_process("victim")
    f = CachedFile(fs.open("f", proc), fs.page_pool)
    f.append(b"x" * 10)
    fs.stall("f", 30.0)

    async def sync():
        await f.sync()

    with pytest.raises(IOError):
        loop.run_until(loop.spawn(sync()), 120)
    assert not proc.alive


def test_btree_corrupt_read_retry_heals_with_cache_on():
    """The btree's checksum-retry path composed with the cache: a forced
    flip on a leaf read is detected and the retry serves clean bytes."""
    loop, fs = _fixture()
    from foundationdb_tpu.rpc.network import SimNetwork

    net = SimNetwork(loop, DeterministicRandom(1), None)
    store = BTreeKeyValueStore(fs, "t", net.create_process("ss"),
                               cache_bytes=1 << 12)

    async def run():
        # values big enough that every leaf page overflows the 4K read
        # chunk — a forced flip always lands inside checksummed bytes
        for i in range(400):
            store.set(b"k%04d" % i, b"v%d" % i + b"x" * 200)
        await store.commit({})
        store._cache.clear()
        store._cache_bytes = 0
        buggify.enable(DeterministicRandom(9))
        buggify.force("disk.corrupt_read", 1)
        assert store.get(b"k0007") == b"v7" + b"x" * 200
        assert coverage.hits("disk.btree_corrupt_read_retried") >= 1

    loop.run_until(loop.spawn(run()), 60)


def test_fold_rolled_back_on_mid_fold_disk_fault():
    """A refused append mid-fold must NOT lose the memtable (the
    PageCacheChaos find: DiskSwizzle's ENOSPC/injected-error rounds hit
    the ssd engine's durability flush mid-COW-rewrite; before the fix
    the memtable was consumed and the leaf directory left half-rewritten
    — acked-data loss the memory engine's WAL-push-first design rules
    out).  The retry after the fault clears must land everything."""
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(3))
    store = BTreeKeyValueStore(fs, "t", None)

    async def run():
        for i in range(300):
            store.set(b"k%04d" % i, b"v%d" % i)
        await store.commit({"durable_version": 1})
        # new batch; every append for the next flush raises
        for i in range(300):
            store.set(b"k%04d" % i, b"NEW%d" % i)
        store.set(b"extra", b"row")
        fs.inject_errors("t.a", 1)
        with pytest.raises(IOError):
            await store.commit({"durable_version": 2})
        assert coverage.hits("btree.fold_rolled_back") == 1
        # reads still see the FULL uncommitted batch (memtable intact)...
        assert store.get(b"k0000") == b"NEW0"
        assert store.get(b"k0299") == b"NEW299"
        assert store.get(b"extra") == b"row"
        # ...and the retry (fault cleared) lands it all
        await store.commit({"durable_version": 2})
        rows = store.range_read(b"", b"\xff" * 8, 1 << 30)
        assert len(rows) == 301
        assert all(v.startswith(b"NEW") for k, v in rows if k != b"extra")

    loop.run_until(loop.spawn(run()), 60)


def test_compact_rolled_back_on_mid_rewrite_disk_fault():
    """Same discipline for compaction: an append refused while bulk-
    writing the other file restores the in-memory tree and un-journals
    the truncate; the retried compaction (fault cleared) converges."""
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(3))
    store = BTreeKeyValueStore(fs, "t", None)

    async def run():
        for round_ in range(12):
            for i in range(150):
                store.set(b"k%03d" % i, b"r%02d-%d" % (round_, i) + b"x" * 80)
            # fold first (commit would), THEN probe the compaction trigger
            store._fold_memtable()
            if store._appended > max(4 * store._live_bytes, 1 << 16):
                # this commit will compact: refuse its appends
                other = "t.b" if store._file_id == 0 else "t.a"
                fs.inject_errors(other, 1)
                with pytest.raises(IOError):
                    await store.commit({"durable_version": round_})
                assert coverage.hits("btree.compact_rolled_back") >= 1
                # contents intact after the rollback
                assert store.get(b"k000") == b"r%02d-0" % round_ + b"x" * 80
            await store.commit({"durable_version": round_})
        assert coverage.hits("btree.compact_rolled_back") >= 1
        rows = store.range_read(b"", b"\xff" * 8, 1 << 30)
        assert rows == [
            (b"k%03d" % i, b"r11-%d" % i + b"x" * 80) for i in range(150)
        ]

    loop.run_until(loop.spawn(run()), 60)


# ---- the tier-1 perf smoke --------------------------------------------------

def _cold_scan_preads(cache_on: bool, keys: int = 2000) -> tuple[int, int]:
    loop = EventLoop()
    fs = SimFilesystem(loop, DeterministicRandom(5))
    if cache_on:
        fs.page_pool = PageCachePool(4096, 1 << 20, 8)
    store = BTreeKeyValueStore(fs, "pc", None, cache_bytes=1 << 14)

    async def build():
        for i in range(keys):
            store.set(b"k%06d" % i, b"v" * 64)
        await store.commit({})

    loop.run_until(loop.spawn(build()), 1e12)
    if fs.page_pool is not None:
        fs.page_pool.clear()  # fresh process lifetime: pool cold
    s2 = BTreeKeyValueStore.recover(fs, "pc", None, cache_bytes=1 << 14)

    def scan() -> int:
        ops0 = sum(fs.disk(p).reads for p in ("pc.a", "pc.b", "pc.hdr"))
        rows = s2.range_read(b"", b"\xff" * 8, 1 << 30)
        assert len(rows) == keys
        return sum(fs.disk(p).reads for p in ("pc.a", "pc.b", "pc.hdr")) - ops0

    return scan(), scan()


def test_perf_smoke_cold_scan_fewer_preads_with_cache():
    """The measured claim, pinned by counters (not wall-clock, so it can't
    flake): a read-twice cold range scan through the ssd engine issues
    FEWER SimFile preads with the file-level cache on than off."""
    cold_on, warm_on = _cold_scan_preads(True)
    cold_off, warm_off = _cold_scan_preads(False)
    assert cold_on < cold_off / 2, (cold_on, cold_off)
    assert warm_on < warm_off, (warm_on, warm_off)
    # and the engine answers identically either way
    assert cold_off == warm_off  # no cache: the second scan pays full price


# ---- cluster-level composition ---------------------------------------------

def _cluster(seed, fs=None, restart=False, cache_on=True):
    from foundationdb_tpu.control.recoverable import RecoverableCluster

    overrides = {} if cache_on else {"PAGE_CACHE_BYTES": 0}
    return RecoverableCluster(
        seed=seed, n_storage_shards=2, storage_replication=2,
        storage_engine="ssd", fs=fs, restart=restart,
        knob_overrides=overrides,
    )


def _put_and_poweroff(cache_on: bool):
    c = _cluster(401, cache_on=cache_on)
    db = c.database()

    async def put():
        for base in range(0, 120, 40):
            tr = db.create_transaction()
            for i in range(base, base + 40):
                tr.set(b"s%04d" % i, b"v%d" % i)
            await tr.commit()
        await c.loop.delay(8.0)  # durability crosses the MVCC window

    c.run_until(c.loop.spawn(put()), 900)
    return c.power_off()


def _read_all(c):
    db = c.database()

    async def readall():
        async def fn(tr):
            return await tr.get_range(b"s", b"t", limit=100000)

        return await db.run(fn)

    return c.run_until(c.loop.spawn(readall()), 900)


def test_power_kill_reboot_identical_bytes_cache_on_vs_off():
    """Durable state is cache-independent: a power-killed ssd cluster
    reboots from its disks to byte-identical contents whether the page
    cache is on or off — and a cache-on write survives a cache-off boot
    (and vice versa)."""
    rows_by_mode = {}
    for write_cache in (True, False):
        fs = _put_and_poweroff(write_cache)
        for boot_cache in (True, False):
            c2 = _cluster(402, fs=fs, restart=True, cache_on=boot_cache)
            rows = _read_all(c2)
            rows_by_mode[(write_cache, boot_cache)] = rows
            c2.stop()
    expect = [(b"s%04d" % i, b"v%d" % i) for i in range(120)]
    for mode, rows in rows_by_mode.items():
        assert rows == expect, mode


def test_status_page_cache_blocks_schema_valid():
    """The per-role storage[*].page_cache block and the shared pool block
    land in the status doc and pass the schema (control/status.py)."""
    from foundationdb_tpu.control.status import cluster_status, validate_status

    c = _cluster(403)
    db = c.database()

    async def put():
        tr = db.create_transaction()
        for i in range(60):
            tr.set(b"pc%03d" % i, b"w")
        await tr.commit()
        await c.loop.delay(6.0)

    c.run_until(c.loop.spawn(put()), 900)
    doc = cluster_status(c)
    validate_status(doc)
    assert "page_cache" in doc["cluster"]
    assert doc["cluster"]["page_cache"]["capacity_bytes"] > 0
    for row in doc["storage"]:
        assert "page_cache" in row
        pc = row["page_cache"]
        assert pc["parsed_misses"] + pc["misses"] >= 0
    c.stop()


def test_storage_metrics_event_carries_page_cache_counters():
    from foundationdb_tpu.control.status import validate_metrics_event

    c = _cluster(404)
    db = c.database()

    async def put():
        tr = db.create_transaction()
        tr.set(b"m0", b"w")
        await tr.commit()
        await c.loop.delay(6.0)

    c.run_until(c.loop.spawn(put()), 900)
    evs = [e for e in c.trace.find("StorageMetrics")]
    assert evs
    for e in evs:
        validate_metrics_event(e)
    assert any("PageCacheHits" in e for e in evs)
    c.stop()


def test_kvstore_wal_recovers_identically_with_cache_on():
    """The memory engine's WAL under the cache: recovery replays the same
    committed state whether the pool is armed or not."""
    from foundationdb_tpu.storage.kvstore import DurableMemoryKeyValueStore

    for cache_on in (True, False):
        loop = EventLoop()
        fs = SimFilesystem(loop, DeterministicRandom(3))
        if cache_on:
            fs.page_pool = PageCachePool(4096, 1 << 20, 8)
        store = DurableMemoryKeyValueStore(fs, "wal", None)

        async def run():
            for i in range(300):
                store.set(b"k%04d" % i, b"v%d" % i)
            await store.commit({"durable_version": 7})

        loop.run_until(loop.spawn(run()), 60)
        fs.flush_buffers()
        s2 = DurableMemoryKeyValueStore.recover(fs, "wal", None)
        assert s2.meta["durable_version"] == 7
        assert s2.range_read(b"", b"\xff" * 8, 1 << 30) == [
            (b"k%04d" % i, b"v%d" % i) for i in range(300)
        ]
        assert s2.page_cache_stats()["hits" if cache_on else "misses"] >= 0
