"""Semantics tests for the oracle conflict set, including a brute-force
point-sampled cross-check of its interval step function."""

import random

from foundationdb_tpu.conflict.api import TxInfo, Verdict
from foundationdb_tpu.conflict.oracle import OracleConflictSet, _StepFunction


def tx(snap, reads=(), writes=()):
    return TxInfo(read_snapshot=snap, read_ranges=reads, write_ranges=writes)


def test_basic_conflict():
    cs = OracleConflictSet()
    # txn A writes [b, d) at v10
    assert cs.resolve_batch(10, [tx(5, writes=[(b"b", b"d")])]) == [Verdict.COMMITTED]
    # read at snapshot 5 overlapping -> conflict; snapshot 10 -> fine
    out = cs.resolve_batch(
        20,
        [
            tx(5, reads=[(b"c", b"c\x00")]),
            tx(10, reads=[(b"c", b"c\x00")]),
            tx(5, reads=[(b"d", b"e")]),  # disjoint from [b,d)
        ],
    )
    assert out == [Verdict.CONFLICT, Verdict.COMMITTED, Verdict.COMMITTED]


def test_intra_batch_order_matters():
    cs = OracleConflictSet()
    # first txn writes k; second reads k in same batch -> conflict
    out = cs.resolve_batch(
        10,
        [
            tx(5, writes=[(b"k", b"k\x00")]),
            tx(5, reads=[(b"k", b"k\x00")]),
        ],
    )
    assert out == [Verdict.COMMITTED, Verdict.CONFLICT]
    # reversed order in a fresh set: read comes first -> both commit
    cs2 = OracleConflictSet()
    out2 = cs2.resolve_batch(
        10,
        [
            tx(5, reads=[(b"k", b"k\x00")]),
            tx(5, writes=[(b"k", b"k\x00")]),
        ],
    )
    assert out2 == [Verdict.COMMITTED, Verdict.COMMITTED]


def test_aborted_txn_writes_invisible():
    cs = OracleConflictSet()
    cs.resolve_batch(10, [tx(5, writes=[(b"a", b"b")])])
    out = cs.resolve_batch(
        20,
        [
            tx(5, reads=[(b"a", b"a\x00")], writes=[(b"x", b"y")]),  # conflicts
            tx(15, reads=[(b"x", b"x\x00")]),  # reads aborted txn's write: no conflict
        ],
    )
    assert out == [Verdict.CONFLICT, Verdict.COMMITTED]


def test_too_old():
    cs = OracleConflictSet()
    cs.resolve_batch(10, [tx(5, writes=[(b"a", b"b")])])
    cs.remove_before(8)
    out = cs.resolve_batch(20, [tx(7, reads=[(b"z", b"z\x00")]), tx(9, reads=[(b"z", b"z\x00")])])
    assert out == [Verdict.TOO_OLD, Verdict.COMMITTED]
    # history at v10 still conflicts a snapshot-9 read after GC to 8
    out2 = cs.resolve_batch(30, [tx(9, reads=[(b"a", b"a\x00")])])
    assert out2 == [Verdict.CONFLICT]


def test_step_function_vs_brute_force():
    rng = random.Random(1)
    sf = _StepFunction()
    universe = [bytes([c]) for c in range(0, 120)]
    brute = {k: 0 for k in universe}
    for step in range(200):
        i, j = sorted(rng.sample(range(120), 2))
        b, e = bytes([i]), bytes([j])
        v = step + 1
        sf.assign(b, e, v)
        for k in universe:
            if b <= k < e:
                brute[k] = v
        # random queries
        for _ in range(5):
            qi, qj = sorted(rng.sample(range(120), 2))
            qb, qe = bytes([qi]), bytes([qj])
            expect = max((brute[k] for k in universe if qb <= k < qe), default=0)
            assert sf.query_max(qb, qe) == expect, (step, qb, qe)


def test_verdict_min_combine_ordering():
    """The proxy min-combines verdicts across resolvers; the enum order must
    make CONFLICT and TOO_OLD each veto COMMITTED (ConflictSet.h:36-40)."""
    from foundationdb_tpu.conflict.api import Verdict

    assert min(Verdict.TOO_OLD, Verdict.COMMITTED) == Verdict.TOO_OLD
    assert min(Verdict.CONFLICT, Verdict.TOO_OLD) == Verdict.CONFLICT
    assert min(Verdict.CONFLICT, Verdict.COMMITTED) == Verdict.CONFLICT
