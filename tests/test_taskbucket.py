"""TaskBucket: transactional task queue in the keyspace — contention-safe
claims, version-lease expiry re-queue after worker death, at-least-once
execution (fdbclient/TaskBucket.actor.cpp)."""

from foundationdb_tpu.client.taskbucket import TaskBucket, TaskBucketExecutor
from foundationdb_tpu.control.recoverable import RecoverableCluster


def test_tasks_executed_by_competing_workers():
    c = RecoverableCluster(seed=1001, n_storage_shards=1, storage_replication=2)
    db = c.database()
    bucket = TaskBucket()
    done: list[bytes] = []

    async def handler(db_, task):
        done.append(task.params[b"payload"])

    async def main():
        async def add_all(tr):
            for i in range(12):
                bucket.add(tr, b"t%03d" % i,
                           {b"__type__": b"work", b"payload": b"p%d" % i})

        await db.run(add_all)
        w1 = TaskBucketExecutor(db, bucket, {b"work": handler})
        w2 = TaskBucketExecutor(db, bucket, {b"work": handler})
        for _ in range(600):
            empty = [False]

            async def chk(tr, empty=empty):
                empty[0] = await bucket.is_empty(tr)

            await db.run(chk)
            if empty[0]:
                break
            await c.loop.delay(0.1)
        w1.stop()
        w2.stop()
        return empty[0], len(w1.executed), len(w2.executed)

    empty, n1, n2 = c.run_until(c.loop.spawn(main()), 900)
    assert empty
    # every task ran at least once, claims were contention-exclusive
    assert set(done) == {b"p%d" % i for i in range(12)}
    assert n1 + n2 >= 12
    assert n1 > 0 and n2 > 0  # both workers actually competed and won
    c.stop()


def test_expired_lease_requeues_after_worker_death():
    """A worker claims a task and dies: once its version lease expires the
    task is re-queued and another worker completes it."""
    c = RecoverableCluster(seed=1002, n_storage_shards=1, storage_replication=2)
    db = c.database()
    bucket = TaskBucket(lease_versions=500_000)  # ~0.5s of version time
    done: list[bytes] = []

    async def handler(db_, task):
        done.append(task.id)

    async def main():
        async def add(tr):
            bucket.add(tr, b"solo", {b"__type__": b"work"})

        await db.run(add)
        # claim WITHOUT finishing (the dying worker)
        claimed = [None]

        async def grab(tr):
            claimed[0] = await bucket.claim_one(tr)

        await db.run(grab)
        assert claimed[0] is not None and claimed[0].id == b"solo"
        # a live worker drains the bucket once the lease expires
        w = TaskBucketExecutor(db, bucket, {b"work": handler})
        for _ in range(600):
            if done:
                break
            await c.loop.delay(0.1)
        w.stop()
        return list(done)

    finished = c.run_until(c.loop.spawn(main()), 900)
    assert finished == [b"solo"]
    c.stop()


def test_extend_keeps_lease_alive():
    c = RecoverableCluster(seed=1003, n_storage_shards=1, storage_replication=2)
    db = c.database()
    bucket = TaskBucket(lease_versions=400_000)

    async def main():
        async def add(tr):
            bucket.add(tr, b"long", {b"__type__": b"slow"})

        await db.run(add)
        claimed = [None]

        async def grab(tr):
            claimed[0] = await bucket.claim_one(tr)

        await db.run(grab)
        t = claimed[0]
        # keep extending across several lease windows; nobody steals it
        for _ in range(4):
            await c.loop.delay(0.3)
            v = [0]

            async def ext(tr):
                v[0] = await tr.get_read_version()
                bucket.extend(tr, t, v[0] + 400_000)

            await db.run(ext)
            stolen = [None]

            async def peek(tr):
                stolen[0] = await bucket.claim_one(tr)

            await db.run(peek)
            assert stolen[0] is None  # never re-queued while extended

        async def fin(tr):
            bucket.finish(tr, t)

        await db.run(fin)
        empty = [False]

        async def chk(tr):
            empty[0] = await bucket.is_empty(tr)

        await db.run(chk)
        return empty[0]

    assert c.run_until(c.loop.spawn(main()), 900)
    c.stop()
