"""Kitchen-sink integration: every subsystem at once — worker-recruited
pipeline on a machine/DC topology, ssd (B+tree) storage engine, chaos
(buggify + randomized knobs), data distribution, multiple invariant
workloads, a machine kill, and a power-loss restart.  The cross-feature
interactions are the point: this is the shape of the reference's nightly
correctness packs (tests/slow + SimulatedCluster's randomized topologies)."""

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime import buggify
from foundationdb_tpu.workloads.attrition import AttritionWorkload
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.consistency import ConsistencyCheckWorkload
from foundationdb_tpu.workloads.cycle import CycleWorkload
from foundationdb_tpu.workloads.increment import IncrementWorkload
from foundationdb_tpu.workloads.swizzle import SwizzleWorkload


@pytest.fixture(autouse=True)
def _buggify_off():
    yield
    buggify.disable()


@pytest.mark.parametrize("seed", [1501, 1502])
def test_everything_at_once(seed):
    c = RecoverableCluster(
        seed=seed,
        n_storage_shards=2,
        storage_replication=2,
        n_tlogs=2,
        n_proxies=2,
        n_machines=4,
        n_dcs=2,
        n_workers=8,
        storage_engine="ssd",
        chaos=True,
    )
    cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=5)
    inc = IncrementWorkload(counters=3, clients=2, adds_per_client=5)
    swz = SwizzleWorkload(rounds=1, victims=2, clog_seconds=0.5, start_delay=1.2)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cyc, inc, swz, att, cons], deadline=900.0)
    assert metrics["Cycle"]["committed"] == 10
    assert metrics["Increment"]["committed"] == 10
    assert c.controller.recoveries >= 1
    assert metrics["ConsistencyCheck"]["shards_checked"] == 2
    c.stop()


def test_machine_kill_then_power_loss_roundtrip():
    """Worker cluster on machines + ssd engine: kill a whole machine (a
    worker + a storage replica at once), heal, then power off everything
    and restart — all committed data must come back."""
    c = RecoverableCluster(
        seed=1503, n_storage_shards=2, storage_replication=2,
        n_machines=4, n_dcs=2, n_workers=6, storage_engine="ssd",
    )
    db = c.database()

    async def put(i):
        async def fn(tr):
            tr.set(b"ks%03d" % i, b"v%d" % i)

        await db.run(fn)  # retrying: kills/recoveries are in play

    async def main():
        for i in range(40):
            await put(i)
        victim = c.storage[0].process.machine
        c.net.kill_machine(victim)
        for _ in range(600):
            if c.dd.heals >= 1:
                break
            await c.loop.delay(0.1)
        assert c.dd.heals >= 1
        for i in range(40, 60):
            await put(i)
        await c.loop.delay(8.0)  # durability catches up past the window
        return True

    assert c.run_until(c.loop.spawn(main()), 900)
    fs = c.power_off()
    c2 = RecoverableCluster(
        seed=1504, n_storage_shards=2, storage_replication=2,
        n_machines=4, n_dcs=2, n_workers=6, storage_engine="ssd",
        fs=fs, restart=True,
    )
    db2 = c2.database()

    async def readall():
        async def fn(tr):
            return await tr.get_range(b"ks", b"kt", limit=10000)

        return await db2.run(fn)

    rows = c2.run_until(c2.loop.spawn(readall()), 900)
    assert len(rows) == 60
    assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))
    c2.stop()


@pytest.mark.parametrize("seed", [1601, 1602, 1603, 2003, 2019])
def test_total_feature_chaos_sweep(seed):
    # seeds 2003/2019 are the regression pair that exposed the deposed-
    # proxy phantom-ack hole (zombie in-flight batch + successor TLog on
    # the same worker acking a version it never stored)
    """The widest configuration the framework supports, under chaos: worker
    bootstrap on a machine/DC topology, ssd engine, a remote region's log
    router + replicas, a live backup, buggify + randomized knobs, attrition
    — and every invariant still holds."""
    from foundationdb_tpu.client.backup import BackupAgent, BackupContainer
    from foundationdb_tpu.workloads.increment import IncrementWorkload

    c = RecoverableCluster(
        seed=seed, n_storage_shards=2, storage_replication=2,
        n_machines=4, n_dcs=2, n_workers=8, storage_engine="ssd",
        remote_region=True, chaos=True,
    )
    agent = BackupAgent(c)
    cont = BackupContainer(c.fs, f"bk-sink-{seed}")
    c.run_until(c.loop.spawn(agent.start(cont)), 300)

    cyc = CycleWorkload(nodes=6, clients=2, txns_per_client=4)
    inc = IncrementWorkload(counters=3, clients=2, adds_per_client=4)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
    cons = ConsistencyCheckWorkload()
    metrics = run_workloads(c, [cyc, inc, att, cons], deadline=900.0)
    assert metrics["Cycle"]["committed"] == 8
    assert metrics["Increment"]["committed"] == 8
    assert c.controller.recoveries >= 1
    assert metrics["ConsistencyCheck"]["shards_checked"] == 2

    # the remote region converged through all of it
    async def remote_check():
        v = [0]
        db = c.database()

        async def fn(tr):
            v[0] = await tr.get_read_version()

        await db.run(fn)
        for _ in range(600):
            if all(ss.version.get() >= v[0] for ss in c.remote_storage):
                return True
            await c.loop.delay(0.1)
        return False

    assert c.run_until(c.loop.spawn(remote_check()), 900)
    # and the backup kept up
    async def bk():
        v = [0]
        db = c.database()

        async def fn(tr):
            v[0] = await tr.get_read_version()

        await db.run(fn)
        await agent.wait_backed_up_to(v[0], timeout=120.0)
        await agent.stop()
        return True

    assert c.run_until(c.loop.spawn(bk()), 900)
    c.stop()


def test_device_lsm_kernel_in_chaos_cluster():
    """The LSM device kernel as the RESOLVER backend of a full chaos
    cluster (2 resolvers, worker bootstrap, attrition): the cluster-level
    invariants exercise the kernel through recoveries — fresh conflict
    sets per generation, GC via remove_before, pipelined verdicts."""
    from foundationdb_tpu.conflict.device import DeviceConflictSet
    from foundationdb_tpu.workloads.increment import IncrementWorkload

    c = RecoverableCluster(
        seed=4100, n_storage_shards=2, storage_replication=2,
        n_resolvers=2, n_workers=6, chaos=True,
        conflict_backend=lambda oldest=0: DeviceConflictSet(
            oldest, capacity=1 << 10, lsm=True, recent_capacity=256
        ),
    )
    cyc = CycleWorkload(nodes=6, clients=2, txns_per_client=4)
    inc = IncrementWorkload(counters=3, clients=2, adds_per_client=4)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
    cons = ConsistencyCheckWorkload()
    m = run_workloads(c, [cyc, inc, att, cons], deadline=900.0)
    assert m["Cycle"]["committed"] == 8
    assert m["Increment"]["committed"] == 8
    assert c.controller.recoveries >= 1
    c.stop()


def test_round5_feature_sink():
    """Round-5 features composed: DR streaming to a second cluster WHILE
    the primary runs a Cycle load, takes an exclusion drain, and flips
    redundancy — then failover, and the secondary serves the exact ring."""
    from foundationdb_tpu.client import management as mgmt
    from foundationdb_tpu.client.dr import DRAgent

    buggify.disable()
    primary = RecoverableCluster(
        seed=1701, n_machines=6, n_dcs=2, n_storage_shards=2,
        redundancy="double",
    )
    secondary = RecoverableCluster(seed=1702, loop=primary.loop)
    db = primary.database()

    async def main():
        tr = db.create_transaction()
        for i in range(8):
            tr.set(b"ring/%d" % i, b"%d" % ((i + 1) % 8))
        await tr.commit()

        agent = DRAgent(primary, secondary)
        await agent.start()

        # load + exclusion + redundancy flip, all concurrent with DR
        target = primary.storage[0].process.machine
        await mgmt.exclude(db, [target])
        await mgmt.configure(db, redundancy="triple")

        for i in range(12):
            async def rot(tr, i=i):
                a = await tr.get(b"ring/%d" % (i % 8))
                b_ = await tr.get(b"ring/" + a)
                tr.set(b"ring/%d" % (i % 8), b_)
                tr.set(b"ring/" + a, a)
            await db.run(rot)

        for _ in range(600):
            await primary.loop.delay(0.1)
            if (
                mgmt.exclusion_safe(primary, [target])
                and all(len(t) == 3 for t in primary.controller.storage_teams_tags)
            ):
                break
        assert mgmt.exclusion_safe(primary, [target])
        assert all(len(t) == 3 for t in primary.controller.storage_teams_tags)

        await agent.failover(timeout=240.0)

        # the secondary serves the exact ring the primary ended with
        tr = db.create_transaction()
        pri_ring = dict(await tr.get_range(b"ring/", b"ring0"))
        tr2 = secondary.database().create_transaction()
        sec_ring = dict(await tr2.get_range(b"ring/", b"ring0"))
        assert sec_ring == pri_ring
        # and the ring is still a permutation (no lost rotation)
        vals = sorted(int(v) for v in sec_ring.values())
        assert vals == sorted(int(k[5:]) for k in sec_ring)
        return True

    assert primary.run_until(primary.loop.spawn(main()), 900)
    secondary.stop()
    primary.stop()


def test_round5_feature_sink_chaos():
    """The round-5 composition under CHAOS (buggify + randomized knobs):
    DR streaming + exclusion drain + redundancy flip, then failover with
    the secondary byte-exact.  One CI seed; soak more with the /tmp
    campaign scripts (5 chaos seeds ran green in round 5)."""
    from foundationdb_tpu.client import management as mgmt
    from foundationdb_tpu.client.dr import DRAgent

    buggify.disable()
    primary = RecoverableCluster(
        seed=9501, n_machines=6, n_dcs=2, n_storage_shards=2,
        redundancy="double", chaos=True,
    )
    secondary = RecoverableCluster(seed=59501, loop=primary.loop)
    db = primary.database()

    async def main():
        tr = db.create_transaction()
        for i in range(8):
            tr.set(b"r/%d" % i, b"%d" % ((i + 1) % 8))
        await tr.commit()
        agent = DRAgent(primary, secondary)
        await agent.start()
        target = primary.storage[0].process.machine
        await mgmt.exclude(db, [target])
        await mgmt.configure(db, redundancy="triple")
        for i in range(10):
            async def w(tr, i=i):
                tr.set(b"w/%d" % i, b"x")
            await db.run(w)
        for _ in range(900):
            await primary.loop.delay(0.1)
            if (
                mgmt.exclusion_safe(primary, [target])
                and all(len(t) == 3 for t in primary.controller.storage_teams_tags)
            ):
                break
        assert mgmt.exclusion_safe(primary, [target])
        assert all(len(t) == 3 for t in primary.controller.storage_teams_tags)
        await agent.failover(timeout=300.0)
        tr = db.create_transaction()
        pri = dict(await tr.get_range(b"", b"\xff", limit=100000))
        tr2 = secondary.database().create_transaction()
        sec = dict(await tr2.get_range(b"", b"\xff", limit=100000))
        assert sec == pri
        return True

    assert primary.run_until(primary.loop.spawn(main()), 900)
    secondary.stop()
    primary.stop()
