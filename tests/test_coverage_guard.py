"""Coverage-site guard rails (the WARN-event-guard discipline applied to
the testcov/buggify namespace): every literal `testcov("...")` /
`buggify("...")` / `maybe_delay(loop, "...")` site string in the package
is unique — one name, one call site, so a census row can never silently
aggregate two different code paths — and every required-coverage manifest
(tests/specs/*.coverage, tools/soak.py convention) references only sites
that actually exist in the tree."""

from __future__ import annotations

import ast
import pathlib

PKG = pathlib.Path(__file__).resolve().parent.parent / "foundationdb_tpu"
SPEC_DIR = pathlib.Path(__file__).resolve().parent / "specs"


def _site_call_sites() -> list[tuple[str, str, str]]:
    """Every (kind, name, file:line) with a LITERAL site string.  Kind is
    'testcov' or 'buggify'; `maybe_delay(loop, site)` is a buggify site
    (it delegates), with the site string in argument position 1."""
    out: list[tuple[str, str, str]] = []
    for path in sorted(PKG.rglob("*.py")):
        for node in ast.walk(ast.parse(path.read_text())):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else getattr(fn, "id", None)
            )
            if name == "maybe_delay":
                arg = node.args[1] if len(node.args) > 1 else None
                kind = "buggify"
            elif name in ("testcov", "buggify"):
                arg = node.args[0] if node.args else None
                kind = name
            else:
                continue
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((kind, arg.value, f"{path.name}:{node.lineno}"))
    return out


def test_site_strings_unique_per_call_site():
    """One site name, one call site: a duplicated name would merge two
    code paths into one census row, so a campaign could report a path as
    covered when only its twin ever ran."""
    sites = _site_call_sites()
    assert len(sites) > 40, "site scan found implausibly few call sites"
    seen: dict[tuple[str, str], str] = {}
    dupes = []
    for kind, name, at in sites:
        key = (kind, name)
        if key in seen:
            dupes.append((kind, name, seen[key], at))
        else:
            seen[key] = at
    assert not dupes, f"duplicate coverage site strings: {dupes}"


def test_buggify_names_never_shadow_testcov():
    """buggify fires mirror into testcov under `buggify.<site>`
    (runtime/buggify.py): no literal testcov name may start with
    'buggify.' or the mirror would collide with a hand-written site."""
    for kind, name, at in _site_call_sites():
        if kind == "testcov":
            assert not name.startswith("buggify."), (at, name)


def test_required_coverage_manifests_reference_real_sites():
    """Every tests/specs/*.coverage manifest line must name a real site:
    `buggify.<site>` resolves against the buggify call sites, bare names
    against the testcov ones.  A manifest typo would otherwise fail every
    campaign as 'missing coverage' (or worse, a renamed site would leave
    a stale requirement that can never be satisfied)."""
    from foundationdb_tpu.tools.soak import load_manifest

    sites = _site_call_sites()
    buggify_sites = {n for k, n, _ in sites if k == "buggify"}
    testcov_sites = {n for k, n, _ in sites if k == "testcov"}
    manifests = sorted(SPEC_DIR.glob("*.coverage"))
    assert manifests, "spec corpus carries no required-coverage manifest"
    for mpath in manifests:
        for name in load_manifest(str(mpath)):
            if name.startswith("buggify."):
                site = name[len("buggify."):]
                assert site in buggify_sites, (
                    f"{mpath.name}: {name!r} names no buggify call site"
                )
            else:
                assert name in testcov_sites, (
                    f"{mpath.name}: {name!r} names no testcov call site"
                )


def test_manifests_pair_with_spec_files():
    """A manifest without its spec is dead weight; the pairing convention
    (<stem>.coverage next to <stem>.txt) is what tools/soak.py resolves."""
    for mpath in SPEC_DIR.glob("*.coverage"):
        assert (SPEC_DIR / (mpath.stem + ".txt")).exists(), (
            f"{mpath.name} has no matching spec file"
        )
