"""Coverage-site guard rails — MIGRATED into flowlint (PR 9).

The AST walker that lived here (site-string uniqueness, the `buggify.`
mirror-namespace shadow check, manifest-references-real-sites, and the
manifest/spec pairing convention) is now the `coverage-sites` rule in
foundationdb_tpu/lint/rules_registry.py, sharing one parse per file with
every other rule and running in the tier-1 flowlint gate
(tests/test_flowlint.py::test_committed_baseline_is_fresh).

This wrapper is what the migration left behind: it proves the rule still
FIRES on the known-bad fixture, so the guard cannot silently rot even if
the tier-1 gate's tree happens to be clean."""

from __future__ import annotations

import pathlib

from foundationdb_tpu.lint import run_lint
from foundationdb_tpu.tools.flowlint import REPO_ROOT

FIXTURE = pathlib.Path(__file__).resolve().parent / "lint_fixtures" / "coverage-sites"


def _hits(which: str) -> list:
    findings = run_lint([str(FIXTURE / which)], root=REPO_ROOT, spec_dir=None)
    return [f for f in findings if f.rule == "coverage-sites"]


def test_coverage_sites_rule_fires_on_known_bad_fixture():
    msgs = [f.message for f in _hits("bad")]
    assert any("duplicate" in m for m in msgs), msgs
    assert any("mirror" in m for m in msgs), msgs


def test_coverage_sites_rule_passes_the_clean_fixture():
    assert not _hits("ok")


def test_manifest_checks_ride_the_rule(tmp_path):
    """The manifest half of the old guard (every tests/specs/*.coverage
    line names a real site; every manifest pairs with its spec) migrated
    too: point the rule at a spec dir with a typo'd manifest and an
    orphaned one, and it fires on both."""
    (tmp_path / "Good.txt").write_text("testTitle=Good\n")
    (tmp_path / "Good.coverage").write_text("no.such.site\n")
    (tmp_path / "Orphan.coverage").write_text("# nothing required\n")
    # a HALF-deleted restarting pair orphans its stem manifest too: soak
    # only maps <stem>.coverage for a complete -1/-2 pair
    (tmp_path / "Half-1.txt").write_text("testTitle=Half\n")
    (tmp_path / "Half.coverage").write_text("# pair manifest\n")
    (tmp_path / "Whole-1.txt").write_text(
        "testTitle=Whole\ntestName=SaveAndKill\n")
    (tmp_path / "Whole-2.txt").write_text("testTitle=Whole\n")
    (tmp_path / "Whole.coverage").write_text("# pair manifest\n")
    findings = run_lint([str(FIXTURE / "ok")], root=REPO_ROOT,
                        spec_dir=str(tmp_path))
    msgs = [f.message for f in findings if f.rule == "coverage-sites"]
    assert any("no such call site" in m for m in msgs), msgs
    orphaned = [f.path for f in findings if f.rule == "coverage-sites"
                and "no matching spec file" in f.message]
    assert any("Orphan.coverage" in p for p in orphaned), orphaned
    assert any("Half.coverage" in p for p in orphaned), orphaned
    assert not any("Whole.coverage" in p for p in orphaned), orphaned
