"""Distributed tracing on the REAL TCP fabric: two OS processes — a
tools/server.py cluster rolling its own trace files, and this test process
as a gateway-protocol client rolling ITS own — with sampling on.  A
sampled transaction's debug ID rides the gateway SET_OPTION into the
server, its pipeline stations land in the server's rolled trace files,
the client's commit stations land in the client's file, and
tools/trace_tool.py joins the journey back together BY DEBUG ID across
files, with monotone wall-clock station times and role attribution
spanning >= 3 roles (docs/OBSERVABILITY.md "Distributed tracing")."""

from __future__ import annotations

import glob
import os
import queue
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "PALLAS_AXON_POOL_IPS": "",  # skip the TPU-tunnel plugin: CPU-only procs
    "JAX_PLATFORMS": "cpu",
}


class Proc:
    def __init__(self, *mod_args: str) -> None:
        self.p = subprocess.Popen(
            [sys.executable, "-m", *mod_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=ENV, cwd=REPO,
        )
        self.lines: queue.Queue[str] = queue.Queue()
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self) -> None:
        for line in self.p.stdout:
            self.lines.put(line)

    def wait_line(self, needle: str, timeout: float = 60.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                line = self.lines.get(timeout=0.5)
            except queue.Empty:
                if self.p.poll() is not None:
                    raise RuntimeError(
                        f"process exited rc={self.p.returncode} before {needle!r}"
                    )
                continue
            if needle in line:
                return line
        raise TimeoutError(f"never saw {needle!r}")

    def kill(self) -> None:
        self.p.kill()
        self.p.wait()


def test_trace_join_across_os_processes(tmp_path):
    from foundationdb_tpu.client.gateway_client import GatewayClient, GatewayError
    from foundationdb_tpu.runtime.trace import (
        TraceCollector,
        TraceFileSink,
        g_trace_batch,
    )
    from foundationdb_tpu.tools import trace_tool

    server_base = str(tmp_path / "server-trace")
    server = None
    gc = None
    try:
        server = Proc(
            "foundationdb_tpu.tools.server",
            "--shards", "1", "--replication", "1", "--workers", "0",
            "--engine", "memory",
            "--sample-rate", "1.0",
            "--trace-file", server_base,
            "--trace-roll-size", "1500",   # tiny: force real rolling
            "--trace-max-logs", "50",
            "--metrics-interval", "0.5",
            "--run-seconds", "240",
        )
        line = server.wait_line("fdbtpu server ready on", timeout=120.0)
        port = int(line.strip().rsplit(":", 1)[1])

        # the CLIENT process's own trace plane: wall clock + rolling file,
        # so the joined timeline crosses two processes' files
        client_sink = TraceFileSink(str(tmp_path / "client-trace"),
                                    roll_size=1 << 20)
        client_trace = TraceCollector(clock=time.time, sink=client_sink,
                                      machine="client-proc")
        g_trace_batch.attach_clock(time.time, client_trace)

        gc = GatewayClient("127.0.0.1", port, timeout=30.0)
        done_id = None
        for attempt in range(10):
            did = f"e2e-span-{attempt}"
            tr = gc.transaction()
            try:
                tr.set_debug_id(did)
                tr.set(b"dk%d" % attempt, b"dv")
                tr.commit()
                done_id = did
                break
            except GatewayError:
                continue  # retryable commit failure: fresh txn, fresh id
            finally:
                tr.destroy()
        assert done_id is not None, "no sampled transaction ever committed"

        # volume so the server's tiny roll size actually rolls: more
        # sampled commits + half a metrics interval's periodic events
        for i in range(10):
            tr = gc.transaction()
            try:
                tr.set_debug_id(f"fill-{i}")
                tr.set(b"fk%d" % i, b"fv")
                tr.commit()
            except GatewayError:
                pass
            finally:
                tr.destroy()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if len(glob.glob(server_base + ".*.jsonl")) >= 2:
                break
            time.sleep(0.25)
        server_files = sorted(glob.glob(server_base + ".*.jsonl"))
        assert len(server_files) >= 2, (
            f"server trace files never rolled: {server_files}"
        )
    finally:
        if gc is not None:
            gc.close()
        if server is not None:
            server.kill()
        # detach the test-process trace plane (don't leak the wall clock
        # into later tests' deterministic timelines)
        g_trace_batch.attach_clock(lambda: 0.0)

    # -- the offline join over BOTH processes' rolled files ------------------
    events = trace_tool.load_events([str(tmp_path)])
    joined = trace_tool.join_timelines(events)
    assert done_id in joined, f"debug id {done_id} not in any trace file"
    rep = trace_tool.report_from_stations(done_id, joined[done_id])

    # >= 3 roles crossed, >= 2 trace files (the client's + the server's)
    assert len(rep["roles"]) >= 3, rep["roles"]
    assert {"client", "proxy"} <= set(rep["roles"]), rep["roles"]
    srcs = {s.split(".")[0] for s in rep["sources"]}
    assert {"client-trace", "server-trace"} <= srcs, rep["sources"]

    # monotone per-station times on the SHARED wall clock: the client's
    # commit brackets the server-side pipeline despite different processes
    times = [s["time"] for s in rep["stations"]]
    assert times == sorted(times)
    assert all(s["delta"] >= 0 for s in rep["stations"])
    locs = [s["location"] for s in rep["stations"]]
    assert locs[0] == "GatewayClient.commit.Before", locs
    assert locs[-1] == "GatewayClient.commit.After", locs
    for want in ("CommitProxyServer.commitBatch.Before",
                 "Resolver.resolveBatch.After",
                 "TLog.tLogCommit.AfterTLogCommit"):
        assert want in locs, locs
    # host attribution: both machine identities appear on the journey
    machines = {s.get("machine") for s in rep["stations"]}
    assert "client-proc" in machines
    assert any(m and m.startswith("server:") for m in machines), machines
