"""Restarting test pairs: the SaveAndKill power-kill, reboot-from-disk
invariants, restart-image torn-save handling, the Rollback workload, and
the pair plumbing through spec files / soak / cli (the reference's
tests/restarting/ + SaveAndKill.actor.cpp + tester.actor.cpp:1118
methodology — part 1 power-kills the whole simulation mid-traffic, part 2
boots a second process-lifetime from the surviving disks and proves every
durability claim held across the reboot)."""

from __future__ import annotations

import json
import os
import pathlib
import sys

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime import buggify, coverage
from foundationdb_tpu.storage.image import (
    RestartImageError,
    load_image,
    restore_filesystem,
    save_image,
)
from foundationdb_tpu.workloads import spec as spec_mod
from foundationdb_tpu.workloads.base import Workload, run_workloads
from foundationdb_tpu.workloads.spec import (
    is_restarting_pair,
    resolve_pair,
    run_restarting_pair,
    run_spec,
    run_spec_file,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
RESTARTING = pathlib.Path(__file__).parent / "specs" / "restarting"

P1_MINI = """\
testTitle=MiniRestart
seed=5
shards=2

testName=Cycle
nodes=6
clients=2
txnsPerClient=50

testName=SaveAndKill
restartAfter=0.8
"""

P2_MINI = """\
testTitle=MiniRestart

testName=Cycle
nodes=6
clients=1
txnsPerClient=2
runSetup=false
"""


def _ring_ok(rows, nodes):
    kv = dict(rows)
    if len(kv) != nodes:
        return False
    nxt = {int(k.split(b"/")[1]): int(v) for k, v in kv.items()}
    seen, cur = set(), 0
    for _ in range(nodes):
        if cur in seen:
            return False
        seen.add(cur)
        cur = nxt[cur]
    return cur == 0


# ---------------------------------------------------------------------------
# part 1: the power-kill + image save


class TestSaveAndKill:
    def test_part1_kills_saves_and_reports_phase1(self, tmp_path):
        m = run_spec(P1_MINI, save_dir=str(tmp_path / "img"))
        assert m["phase"] == 1
        assert m["restart_image"] == str(tmp_path / "img")
        assert m["seed"] == 5
        # the kill landed MID-traffic: 2x50 rotations cannot finish in
        # 0.8s, so part 1 must have died with clients still running
        assert 0 < m["Cycle"]["committed"] < 100
        files, manifest = load_image(m["restart_image"])
        assert manifest["seed"] == 5
        assert manifest["cluster"]["n_storage_shards"] == 2
        assert manifest["workloads"]["Cycle"] == [{"nodes": 6}]
        assert manifest["killed_at"] >= 0.8
        assert [n for n, _kw in manifest["stanzas"]] == ["Cycle", "SaveAndKill"]
        # the disks are there: storage files, TLog queues, coordinators
        assert any(p.startswith("ss0") for p in files)
        assert coverage.hits("restart.power_kill") == 1
        assert coverage.hits("restart.image_saved") == 1

    def test_part2_boots_from_image_and_ring_holds(self, tmp_path):
        m1 = run_spec(P1_MINI, save_dir=str(tmp_path / "img"))
        m2 = run_spec(P2_MINI, restart_image=m1["restart_image"])
        assert "phase" not in m2  # part 2 ran its checks for real
        assert m2["Cycle"]["committed"] == 2  # NEW rotations post-reboot
        assert coverage.hits("restart.booted_from_image") == 1
        assert coverage.hits("restart.setup_skipped") == 1

    def test_direct_restart_image_read_back(self, tmp_path):
        """Boot a bare cluster (no spec machinery) from the saved image
        and walk the ring by hand — the image IS the disks."""
        m1 = run_spec(P1_MINI, save_dir=str(tmp_path / "img"))
        files, manifest = load_image(m1["restart_image"])
        c = RecoverableCluster(
            seed=manifest["seed"], n_storage_shards=2,
            fs=restore_filesystem(files), restart=True,
        )
        db = c.database()

        async def walk(tr):
            return await tr.get_range(b"cycle/", b"cycle0", limit=100)

        rows = c.run_until(c.loop.spawn(db.run(walk)), 120)
        assert _ring_ok(rows, 6), f"ring broken after reboot: {sorted(rows)}"
        c.stop()


# ---------------------------------------------------------------------------
# pair resolution + mismatch refusal


class TestPairPlumbing:
    def test_resolution_from_stem_and_either_half(self):
        stem = str(RESTARTING / "CycleRestart")
        want = (stem + "-1.txt", stem + "-2.txt")
        assert resolve_pair(stem) == want
        assert resolve_pair(stem + "-1.txt") == want
        assert resolve_pair(stem + "-2.txt") == want
        assert resolve_pair(stem + "-1") == want
        assert is_restarting_pair(stem)
        # a plain spec is not a pair; a missing half is an error
        assert not is_restarting_pair("tests/specs/CycleTest.txt")
        with pytest.raises(FileNotFoundError, match="missing"):
            resolve_pair("tests/specs/CycleTest.txt")

    def test_same_stem_standalones_are_not_a_pair(self, tmp_path):
        """Two unrelated standalone specs that happen to be named
        Foo-1.txt/Foo-2.txt are NOT a restarting pair — the -1 half must
        actually contain a SaveAndKill stanza, or naming alone would
        hijack them into a bogus pair run and orphan their manifests."""
        plain = ("testTitle=Foo\ntestName=Cycle\nnodes=4\nclients=1\n"
                 "txnsPerClient=2\n")
        (tmp_path / "Foo-1.txt").write_text(plain)
        (tmp_path / "Foo-2.txt").write_text(plain)
        assert not is_restarting_pair(str(tmp_path / "Foo-2.txt"))
        assert not spec_mod.should_run_pair(str(tmp_path / "Foo-2.txt"))
        # each runs as ITSELF through the spec runner
        m = run_spec_file(str(tmp_path / "Foo-2.txt"))
        assert "part1" not in m and m["Cycle"]["committed"] == 2
        # and keeps its own coverage manifest (no remap to Foo.coverage)
        from foundationdb_tpu.tools.soak import manifest_for_spec

        (tmp_path / "Foo-2.coverage").write_text("restart.power_kill\n")
        assert manifest_for_spec(str(tmp_path / "Foo-2.txt")) == str(
            tmp_path / "Foo-2.coverage")

    def test_run_restarting_pair_on_the_committed_corpus(self, tmp_path):
        m = run_restarting_pair(
            str(RESTARTING / "CycleRestart"), image_dir=str(tmp_path / "img"),
        )
        assert m["part1"]["phase"] == 1
        assert m["part2"]["ConsistencyCheck"]["shards_checked"] == 2
        assert m["seed"] == 101
        assert os.path.exists(os.path.join(m["restart_image"], "manifest.json"))

    def test_run_spec_file_autodiscovers_the_pair(self, tmp_path,
                                                  monkeypatch):
        """run_spec_file given either half (or the bare stem) runs BOTH
        halves as a pair; explicit save_dir/restart_image kwargs mean the
        caller drives the halves itself and suppress the discovery."""
        monkeypatch.setenv("FDBTPU_RESTART_DIR", str(tmp_path / "env-img"))
        m = run_spec_file(str(RESTARTING / "CycleRestart-1.txt"))
        assert m["part1"]["phase"] == 1
        assert m["part2"]["ConsistencyCheck"]["shards_checked"] == 2
        # the env knob steered the image directory
        assert (tmp_path / "env-img" / "manifest.json").exists()
        # explicit save_dir: part 1 runs ALONE and saves there
        m1 = run_spec_file(str(RESTARTING / "CycleRestart-1.txt"),
                           save_dir=str(tmp_path / "solo"))
        assert m1["phase"] == 1 and m1["restart_image"] == str(tmp_path / "solo")

    def test_duplicate_same_named_stanzas_compare_positionally(self):
        """Two same-named stanzas must not collapse in the manifest: the
        saved shape is name -> ordered state list, and part 2 pairs its
        stanzas up positionally (a correct mirror passes, a drifted SECOND
        stanza still refuses, extra part-2 stanzas are allowed)."""
        from foundationdb_tpu.workloads.cycle import CycleWorkload
        from foundationdb_tpu.workloads.save_and_kill import invariant_states

        part1 = [CycleWorkload(nodes=8), CycleWorkload(nodes=4)]
        saved = invariant_states(part1)
        assert saved == {"Cycle": [{"nodes": 8}, {"nodes": 4}]}
        # an exact mirror is NOT a mismatch (the collapsed-dict bug
        # compared the first stanza against the last saved state)
        spec_mod._check_restart_states(
            [CycleWorkload(nodes=8), CycleWorkload(nodes=4)], saved)
        # extra same-named part-2 stanza: allowed (a new check)
        spec_mod._check_restart_states(
            [CycleWorkload(nodes=8), CycleWorkload(nodes=4),
             CycleWorkload(nodes=2)], saved)
        with pytest.raises(ValueError, match="restarting-pair mismatch"):
            spec_mod._check_restart_states(
                [CycleWorkload(nodes=8), CycleWorkload(nodes=6)], saved)
        # DROPPING a saved workload is a refusal, not a silent green: the
        # data rode the reboot, something must re-check it
        with pytest.raises(ValueError, match="must be re-checked"):
            spec_mod._check_restart_states([CycleWorkload(nodes=8)], saved)
        with pytest.raises(ValueError, match="must be re-checked"):
            spec_mod._check_restart_states([], saved)
        # JSON-equivalent live state (tuple vs the manifest's list) is NOT
        # drift — the check canonicalizes through the same round-trip
        class TupleState(Workload):
            description = "Tuple"

            def restart_state(self):
                return {"range": (0, 8)}

        spec_mod._check_restart_states(
            [TupleState()], {"Tuple": [{"range": [0, 8]}]})

    def test_resave_into_reused_dir_replaces_cleanly(self, tmp_path):
        """A fixed FDBTPU_RESTART_DIR gets re-saved over: the new image
        must replace the old one whole — no stale payloads from a larger
        earlier image, no staging leftovers, and the result loads."""
        m1 = run_spec(P1_MINI, save_dir=str(tmp_path / "img"))
        # plant a payload the second save will not contain, and a stale
        # staging dir a crashed earlier process (any pid) left behind
        stale = tmp_path / "img" / "files" / "stale-payload"
        stale.write_bytes(b"old disks")
        (tmp_path / "img.saving-99999").mkdir()
        run_spec(P1_MINI, save_dir=str(tmp_path / "img"))
        assert not stale.exists()
        assert not list(tmp_path.glob("img.saving-*"))
        files, manifest = load_image(m1["restart_image"])
        assert manifest["seed"] == 5 and any(
            p.startswith("ss0") for p in files)

    def test_ephemeral_image_dir_cleaned_after_success(self, monkeypatch,
                                                       tmp_path):
        """A pair run that DEFAULTED to a temp image dir deletes it once
        part 2 consumed it; a caller-named dir is kept (it is theirs)."""
        monkeypatch.delenv("FDBTPU_RESTART_DIR", raising=False)
        m = run_restarting_pair(str(RESTARTING / "CycleRestart"))
        # the dir is gone AND the report says so (no dangling path)
        assert m["restart_image"] is None
        assert not os.path.exists(m["part1"]["restart_image"])
        kept = tmp_path / "img"
        m = run_restarting_pair(str(RESTARTING / "CycleRestart"),
                                image_dir=str(kept))
        assert (kept / "manifest.json").exists()

    def test_ephemeral_image_dir_cleaned_when_part1_dies_unsaved(
            self, monkeypatch, tmp_path):
        """Part 1 raising BEFORE SaveAndKill saved anything leaves no
        empty /tmp/fdbtpu-restart-* behind (nothing to triage there)."""
        import glob as _glob

        monkeypatch.delenv("FDBTPU_RESTART_DIR", raising=False)
        (tmp_path / "Dead-1.txt").write_text(
            "testTitle=Dead\ntestName=Cycle\nnodes=6\nclients=1\n"
            "txnsPerClient=200\n\ntestName=SaveAndKill\nrestartAfter=900\n"
        )
        (tmp_path / "Dead-2.txt").write_text(P2_MINI)
        before = set(_glob.glob("/tmp/fdbtpu-restart-*"))
        with pytest.raises(Exception):
            run_restarting_pair(str(tmp_path / "Dead"), deadline=2.0)
        assert set(_glob.glob("/tmp/fdbtpu-restart-*")) == before

    def test_named_standalone_spec_beats_same_stem_pair(self, tmp_path):
        """An explicitly named, EXISTING spec always runs as itself — a
        same-stem -1/-2 pair only substitutes when the path is a bare stem
        or a pair half (run_spec_file, soak.run_one_seed, and `cli spec`
        all route through spec.should_run_pair for this)."""
        standalone = (
            "testTitle=Solo\ntestName=Cycle\nnodes=4\nclients=1\n"
            "txnsPerClient=2\n"
        )
        (tmp_path / "Solo.txt").write_text(standalone)
        (tmp_path / "Solo-1.txt").write_text(P1_MINI)
        (tmp_path / "Solo-2.txt").write_text(P2_MINI)
        assert not spec_mod.should_run_pair(str(tmp_path / "Solo.txt"))
        assert spec_mod.should_run_pair(str(tmp_path / "Solo"))
        assert spec_mod.should_run_pair(str(tmp_path / "Solo-1.txt"))
        m = run_spec_file(str(tmp_path / "Solo.txt"))
        assert "part1" not in m and m["Cycle"]["committed"] == 2

    def test_runsetup_typo_is_refused(self):
        """`runSetup=no` must refuse, not truthy-bool to True — setup
        re-filling the ring would make part 2 check pristine data instead
        of the state that rode the reboot."""
        with pytest.raises(ValueError, match="runSetup expects true/false"):
            run_spec("testTitle=X\ntestName=Cycle\nnodes=4\nclients=1\n"
                     "txnsPerClient=1\nrunSetup=no\n")

    def test_part2_with_its_own_kill_is_refused(self, tmp_path):
        """A SaveAndKill stanza copied into the -2 spec would power-kill
        part 2 before any check ran — run_restarting_pair must refuse the
        phase-1-shaped result, not report a green pair that checked
        nothing."""
        (tmp_path / "KillTwice-1.txt").write_text(P1_MINI)
        (tmp_path / "KillTwice-2.txt").write_text(
            P2_MINI + "\ntestName=SaveAndKill\nrestartAfter=0.5\n")
        with pytest.raises(ValueError, match="must run checks"):
            run_restarting_pair(str(tmp_path / "KillTwice"),
                                image_dir=str(tmp_path / "img"))

    def test_part1_without_kill_is_refused(self, tmp_path):
        (tmp_path / "NoKill-1.txt").write_text(
            "testTitle=NoKill\ntestName=Cycle\nnodes=4\nclients=1\n"
            "txnsPerClient=1\n"
        )
        (tmp_path / "NoKill-2.txt").write_text(P2_MINI)
        with pytest.raises(ValueError, match="without a SaveAndKill"):
            run_restarting_pair(str(tmp_path / "NoKill"),
                                image_dir=str(tmp_path / "img"))

    def test_part2_seed_mismatch_refused(self, tmp_path):
        m1 = run_spec(P1_MINI, save_dir=str(tmp_path / "img"))
        with pytest.raises(ValueError, match="restarting-pair mismatch.*seed"):
            run_spec("testTitle=X\nseed=6\ntestName=Cycle\nnodes=6\n"
                     "runSetup=false\n", restart_image=m1["restart_image"])

    def test_part2_config_mismatch_refused(self, tmp_path):
        m1 = run_spec(P1_MINI, save_dir=str(tmp_path / "img"))
        with pytest.raises(ValueError,
                           match="restarting-pair mismatch.*n_storage_shards"):
            run_spec("testTitle=X\nshards=3\ntestName=Cycle\nnodes=6\n"
                     "runSetup=false\n", restart_image=m1["restart_image"])
        # matching values (including defaulted ones spelled out) are fine
        m2 = run_spec("testTitle=X\nseed=5\nshards=2\nreplication=2\n"
                      "testName=Cycle\nnodes=6\nclients=1\ntxnsPerClient=1\n"
                      "runSetup=false\n", restart_image=m1["restart_image"])
        assert m2["Cycle"]["committed"] == 1

    def test_part2_workload_state_mismatch_refused(self, tmp_path):
        m1 = run_spec(P1_MINI, save_dir=str(tmp_path / "img"))
        with pytest.raises(ValueError,
                           match="restarting-pair mismatch.*Cycle"):
            run_spec("testTitle=X\ntestName=Cycle\nnodes=8\nrunSetup=false\n",
                     restart_image=m1["restart_image"])

    def test_run_setup_spec_key_parses(self):
        _t, _ck, st = spec_mod.parse_spec(
            "testName=Cycle\nnodes=6\nrunSetup=false\n"
        )
        assert st == [("Cycle", {"nodes": 6, "run_setup": False})]

    def test_run_setup_false_skips_setup_phase(self):
        class Probe(Workload):
            description = "Probe"
            setup_ran = False

            async def setup(self, cluster, rng):
                self.setup_ran = True

            async def start(self, cluster, rng):
                pass

        c = RecoverableCluster(seed=11)
        try:
            w = Probe()
            w.run_setup = False
            run_workloads(c, [w], deadline=60.0)
            assert not w.setup_ran
            assert coverage.hits("restart.setup_skipped") == 1
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# torn-save handling


class TestTornImages:
    def _image(self, tmp_path) -> str:
        return run_spec(P1_MINI, save_dir=str(tmp_path / "img"))["restart_image"]

    def test_missing_manifest_refused(self, tmp_path):
        img = self._image(tmp_path)
        os.remove(os.path.join(img, "manifest.json"))
        with pytest.raises(RestartImageError, match="no manifest.json"):
            load_image(img)

    def test_torn_manifest_refused(self, tmp_path):
        img = self._image(tmp_path)
        mp = os.path.join(img, "manifest.json")
        blob = open(mp, "rb").read()
        with open(mp, "wb") as f:
            f.write(blob[: len(blob) // 2])
        with pytest.raises(RestartImageError, match="torn or corrupt"):
            load_image(img)

    def test_corrupt_payload_refused(self, tmp_path):
        img = self._image(tmp_path)
        files_dir = os.path.join(img, "files")
        victim = sorted(
            p for p in os.listdir(files_dir)
            if os.path.getsize(os.path.join(files_dir, p)) > 0
        )[0]
        with open(os.path.join(files_dir, victim), "r+b") as f:
            b = f.read(1)
            f.seek(0)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(RestartImageError, match="crc32"):
            load_image(img)

    def test_missing_payload_refused(self, tmp_path):
        img = self._image(tmp_path)
        files_dir = os.path.join(img, "files")
        os.remove(os.path.join(files_dir, sorted(os.listdir(files_dir))[0]))
        with pytest.raises(RestartImageError, match="payload is missing"):
            load_image(img)

    def test_percent_escape_paths_round_trip(self, tmp_path):
        """Manifest keys are RAW sim paths; a path containing a literal
        %XX sequence must restore under its own name, not a decoded one
        (review-caught: an unquote() on load silently relocated it)."""
        from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
        from foundationdb_tpu.storage.files import SimFilesystem

        fs = SimFilesystem(EventLoop(), DeterministicRandom(1))
        st_path = "wal%41.log"  # unquote() would turn this into walA.log
        f = fs.open(st_path, None)
        f.append(b"data")
        fs.flush_buffers()
        save_image(fs, str(tmp_path / "img"), {"seed": 1})
        files, _m = load_image(str(tmp_path / "img"))
        assert files[st_path] == b"data"
        assert "walA.log" not in files

    def test_torn_tmp_leftover_is_ignored(self, tmp_path):
        """The restart.manifest_corrupt shape: a crashed earlier save
        attempt leaves a torn manifest temp — the loader must read only
        the atomically-renamed manifest proper."""
        img = self._image(tmp_path)
        with open(os.path.join(img, "manifest.json.tmp"), "wb") as f:
            f.write(b'{"format": 1, "files": {"gar')
        files, manifest = load_image(img)
        assert manifest["seed"] == 5 and files

    def test_buggified_torn_save_still_loads(self, tmp_path):
        """Under chaos, SaveAndKill's setup arms restart.manifest_corrupt
        with a seeded coin: the save then plants the torn temp itself,
        fires the census, and the image still boots.  (Arming outside
        run_spec is impossible by design — the cluster's chaos setup owns
        the buggify state — so scan the seed matrix for an armed seed.)"""
        p1_chaos = P1_MINI.replace("seed=5\n", "seed=5\nchaos=true\n")
        img = None
        for seed in range(3000, 3020):
            cand = str(tmp_path / f"img{seed}")
            run_spec(p1_chaos, seed=seed, save_dir=cand)
            if os.path.exists(os.path.join(cand, "manifest.json.tmp")):
                img = cand
                break
        assert img is not None, (
            "no seed in 3000..3019 armed restart.manifest_corrupt — the "
            "seeded coin is broken"
        )
        assert coverage.hits("buggify.restart.manifest_corrupt") >= 1
        m2 = run_spec(P2_MINI, restart_image=img)
        assert m2["Cycle"]["committed"] == 2


# ---------------------------------------------------------------------------
# the crash model, pinned from both directions


class TestCrashDurability:
    def _mk(self, seed):
        c = RecoverableCluster(seed=seed, n_storage_shards=2)
        db = c.database()

        async def committed_write(tr):
            tr.set(b"acked/key", b"promised")

        c.run_until(c.loop.spawn(db.run(committed_write)), 60)
        # a deliberately buffered, never-fsynced write on a live machine's
        # disk — page-cache-only data with NO durability promise attached
        proc = next(p for p in c.net.processes.values() if p.alive)
        f = c.fs.open("negative.probe", proc)
        f.append(b"BUFFERED-NEVER-SYNCED")
        return c

    def test_unsynced_write_must_not_survive_the_power_kill(self, tmp_path):
        """The negative direction: the power-kill is UNCLEAN by contract —
        buffered-but-unsynced data dies with it.  (If SaveAndKill's kill
        were secretly a clean shutdown, this test is exactly the one that
        would fail — see the clean-shutdown twin below.)"""
        c = self._mk(21)
        fs = c.power_off()
        save_image(fs, str(tmp_path / "img"), {"seed": 21})
        files, _m = load_image(str(tmp_path / "img"))
        assert files["negative.probe"] == b"", (
            "un-fsynced page-cache data survived a power kill — the kill "
            "is not unclean"
        )
        # ...while the ACKED commit must be in the image (ack => fsynced)
        c2 = RecoverableCluster(seed=22, n_storage_shards=2,
                                fs=restore_filesystem(files), restart=True)
        db2 = c2.database()

        async def read(tr):
            return await tr.get(b"acked/key")

        assert c2.run_until(c2.loop.spawn(db2.run(read)), 120) == b"promised"
        c2.stop()

    def test_same_write_survives_a_clean_shutdown(self, tmp_path):
        """The discriminating twin: replace the power-kill with an orderly
        flush-then-halt and the SAME buffered write now survives — proving
        the previous test actually discriminates kill from shutdown."""
        c = self._mk(23)
        fs = c.clean_shutdown()
        save_image(fs, str(tmp_path / "img"), {"seed": 23})
        files, _m = load_image(str(tmp_path / "img"))
        assert files["negative.probe"] == b"BUFFERED-NEVER-SYNCED"

    def test_acked_commits_survive_kill_at_any_offset(self, tmp_path):
        """The positive direction, swept: commits acknowledged while the
        power-kill timer runs must ALL be readable after the reboot — a
        write whose fsync was still in flight at the kill either survived
        or was never acknowledged, never a third thing."""
        for offset in (0.05, 0.3, 1.0):
            c = RecoverableCluster(seed=31, n_storage_shards=2)
            db = c.database()
            acked: dict[bytes, bytes] = {}

            async def writer(ci):
                from foundationdb_tpu.client.transaction import RETRYABLE_ERRORS
                from foundationdb_tpu.roles.types import CommitUnknownResult

                for seq in range(1000):
                    key = b"acked/%d/%04d" % (ci, seq)
                    tr = db.create_transaction()
                    while True:
                        try:
                            tr.set(key, b"v")
                            await tr.commit()
                            acked[key] = b"v"
                            break
                        except CommitUnknownResult:
                            break  # either outcome legal: not recorded
                        except RETRYABLE_ERRORS as e:
                            await tr.on_error(e)

            for ci in range(2):
                c.loop.spawn(writer(ci))
            c.run_until(c.loop.delay(0.2 + offset), 120)
            assert acked, f"offset={offset}: nothing acked before the kill"
            fs = c.power_off()
            save_image(fs, str(tmp_path / f"img{offset}"), {"seed": 31})
            files, _m = load_image(str(tmp_path / f"img{offset}"))
            c2 = RecoverableCluster(seed=32, n_storage_shards=2,
                                    fs=restore_filesystem(files),
                                    restart=True)
            db2 = c2.database()

            async def read_all(tr):
                return {k: await tr.get(k) for k in acked}

            got = c2.run_until(c2.loop.spawn(db2.run(read_all)), 120)
            lost = [k for k, v in acked.items() if got.get(k) != v]
            assert not lost, (
                f"offset={offset}: {len(lost)} ACKED commits lost across "
                f"the reboot, e.g. {sorted(lost)[:3]}"
            )
            c2.stop()


# ---------------------------------------------------------------------------
# Rollback workload


class TestRollback:
    def test_rollback_forces_recovery_and_loses_nothing_acked(self):
        m = run_spec(
            "testTitle=RollbackUnit\nseed=41\nshards=2\n\n"
            "testName=Rollback\nrounds=2\nclients=2\nwritesPerClient=8\n",
            deadline=600.0,
        )
        r = m["Rollback"]
        assert r["forced_recoveries"] >= 1
        assert r["acked"] + r["unknown"] == 16
        assert coverage.hits("rollback.forced_recovery") >= 1

    def test_rollback_check_fails_without_a_forced_recovery(self):
        """A Rollback whose kills never landed must FAIL its check (a
        rollback test that never rolled back tested nothing)."""
        from foundationdb_tpu.workloads.rollback import RollbackWorkload

        c = RecoverableCluster(seed=43)
        try:
            w = RollbackWorkload(rounds=0, clients=1, writes_per_client=2)
            with pytest.raises(AssertionError, match="Rollback"):
                run_workloads(c, [w], deadline=120.0)
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# the supervised device backend crossed with the whole-sim kill


class TestSupervisedPipelineKill:
    def test_pair_with_split_phase_resolver_mid_pipeline(self, tmp_path,
                                                          monkeypatch):
        """FDBTPU_PIPELINE=1 + backend=supervised: the power-kill lands
        while the split-phase resolver may hold an open deferred window on
        the device — the composition the deferred-window replay had never
        been crossed with.  The pair must still prove the ring."""
        monkeypatch.setenv("FDBTPU_PIPELINE", "1")
        m = run_restarting_pair(
            str(RESTARTING / "RestartAttritionSwizzle"), seed=3100,
            image_dir=str(tmp_path / "img"),
        )
        assert m["part1"]["phase"] == 1
        assert m["part2"]["ConsistencyCheck"]["shards_checked"] == 2


# ---------------------------------------------------------------------------
# soak + cli integration


class TestHarnessIntegration:
    def test_soak_runs_pair_as_one_seeded_unit(self, tmp_path):
        """A 2-seed campaign over the committed CycleRestart pair: both
        halves run in the same worker with a shared artifact dir, the
        image lands under the seed's artifacts, the merged census crosses
        both lifetimes, and every required kill/reboot site is hit."""
        from foundationdb_tpu.tools import soak

        # seeds chosen so the pair's seeded coins cover BOTH buggify
        # sites across the campaign (3002 fires kill_point, 3005 fires
        # manifest_corrupt under the current knob-randomization stream —
        # a new randomized knob shifts every later seeded coin) — the
        # committed 100-seed campaign report in docs/campaigns/ shows
        # the unchosen-matrix rates
        report = soak.run_campaign(
            str(RESTARTING / "CycleRestart"), [3002, 3005],
            str(tmp_path / "out"), jobs=2, seed_deadline=240.0,
            keep_traces=True,
        )
        assert report["ok"], report["coverage"]["missing_required"]
        assert report["verdicts"]["pass"] == 2
        merged = report["coverage"]["merged"]
        assert merged["testcov"]["restart.power_kill"]["hit_seeds"] == 2
        assert merged["testcov"]["restart.booted_from_image"]["hit_seeds"] == 2
        # the image is a per-seed artifact next to the seed's traces
        assert (tmp_path / "out" / "seed-3002" / "image"
                / "manifest.json").exists()

    def test_manifest_for_spec_pair_vs_standalone_stems(self, tmp_path):
        """A pair shares `<stem>.coverage`; a STANDALONE spec whose name
        merely ends in -1/-2 keeps its own manifest (review-caught: the
        unconditional strip silently dropped required-coverage gating)."""
        from foundationdb_tpu.tools import soak

        pair = str(RESTARTING / "CycleRestart-1.txt")
        assert soak.manifest_for_spec(pair) == str(
            RESTARTING / "CycleRestart.coverage")
        solo = tmp_path / "Foo-2.txt"
        solo.write_text("testName=Cycle\n")
        (tmp_path / "Foo-2.coverage").write_text("recovery.triggered\n")
        assert soak.manifest_for_spec(str(solo)) == str(
            tmp_path / "Foo-2.coverage")

    def test_cli_spec_subcommand_runs_a_pair(self, tmp_path):
        import subprocess

        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            PYTHONPATH=str(REPO) + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        )
        p = subprocess.run(
            [sys.executable, "-m", "foundationdb_tpu.tools.cli", "spec",
             str(RESTARTING / "CycleRestart"), "--seed", "3200",
             "--image-dir", str(tmp_path / "img")],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert p.returncode == 0, p.stdout + p.stderr
        m = json.loads(p.stdout)
        assert m["seed"] == 3200
        assert m["part1"]["phase"] == 1
        assert m["part2"]["ConsistencyCheck"]["shards_checked"] == 2
