"""Key selectors end-to-end + the RYW SnapshotCache (ISSUE 8 acceptance).

Selector resolution happens SERVER-side (roles/storage.py find_key, the
storageserver.actor.cpp findKey walk) with shard-boundary continuation,
and client-side over the merged (cache, writes) view in RYW; the
SnapshotCache makes a read-twice transaction cost exactly one cluster
fetch.  These tests pin the reference semantics: the four constructors,
offset stepping across shard boundaries, boundary clamps (offset overflow
resolves to b"" / b"\xff", never an error), or_equal against keys deleted
in the same transaction's write set, cache hit/eviction behavior, and the
observability surface (status + ClientMetrics)."""

from foundationdb_tpu.client.ryw import ReadYourWritesTransaction
from foundationdb_tpu.cluster import SimCluster
from foundationdb_tpu.roles.types import (
    CLIENT_KEYSPACE_END,
    GetKeyReply,
    GetKeyRequest,
    KeySelector,
)


def run(c, coro, deadline=120.0):
    return c.run_until(c.loop.spawn(coro), deadline)


def _seed_keys(c, db, n=20):
    async def seed():
        tr = db.create_transaction()
        for i in range(n):
            tr.set(b"k%02d" % i, b"v%02d" % i)
        await tr.commit()

    run(c, seed())


def _storage_reads(c) -> int:
    return sum(ss.c_reads.value for ss in c.storage)


# -- the four constructors + offset arithmetic (FDBTypes.h KeySelectorRef) ---


def test_selector_constructors_resolve():
    c = SimCluster(seed=801, n_storage_shards=2)
    db = c.database()
    _seed_keys(c, db)

    async def main():
        tr = db.create_transaction()
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"k05")) == b"k05"
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"k05\x00")) == b"k06"
        assert await tr.get_key(KeySelector.first_greater_than(b"k05")) == b"k06"
        assert await tr.get_key(KeySelector.last_less_or_equal(b"k05")) == b"k05"
        assert await tr.get_key(KeySelector.last_less_or_equal(b"k05\x00")) == b"k05"
        # offset 0 edge: the base position itself
        assert await tr.get_key(KeySelector.last_less_than(b"k05")) == b"k04"
        assert await tr.get_key(KeySelector.last_less_than(b"k00")) == b""
        # arithmetic shifts the offset (KeySelectorRef::operator+)
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"k05") + 3) == b"k08"
        assert await tr.get_key(KeySelector.first_greater_than(b"k05") - 2) == b"k04"
        return True

    assert run(c, main())
    c.stop()


def test_selector_offsets_cross_shard_boundaries():
    """Negative and positive offsets stepping past a shard edge continue on
    the adjacent shard via the updated-selector reply (getKeyQ contract) —
    and both shards actually served selector traffic."""
    c = SimCluster(seed=802, n_storage_shards=3,
                   storage_splits=[b"k05", b"k13"])
    db = c.database()
    _seed_keys(c, db)

    async def main():
        tr = db.create_transaction()
        # forward across two boundaries: k02 + 14 keys -> k16
        sel = KeySelector.first_greater_or_equal(b"k02") + 14
        assert await tr.get_key(sel) == b"k16"
        # backward across both boundaries: last < k17, back 13 -> k03
        sel = KeySelector.last_less_than(b"k17") - 13
        assert await tr.get_key(sel) == b"k03"
        # backward selector anchored EXACTLY on a shard split routes left
        assert await tr.get_key(KeySelector.last_less_than(b"k05")) == b"k04"
        assert await tr.get_key(KeySelector.last_less_than(b"k13")) == b"k12"
        return True

    assert run(c, main())
    assert sum(1 for ss in c.storage if ss.c_selector_reads.value > 0) >= 2, (
        "selector walks never crossed a shard boundary"
    )
    c.stop()


def test_selector_boundary_clamps():
    """Before-begin / after-end resolutions clamp to the keyspace boundary
    (allKeys.begin/end), never error — including large offset overflow."""
    c = SimCluster(seed=803, n_storage_shards=2)
    db = c.database()
    _seed_keys(c, db, n=4)

    async def main():
        tr = db.create_transaction()
        assert await tr.get_key(KeySelector.last_less_than(b"\x00")) == b""
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"k00") - 100) == b""
        assert await tr.get_key(KeySelector.first_greater_than(b"k03")) == CLIENT_KEYSPACE_END
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"k00") + 100) == CLIENT_KEYSPACE_END
        # anchors outside the user keyspace resolve, not raise
        assert await tr.get_key(KeySelector.last_less_or_equal(b"\xfe")) == b"k03"
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"\xff")) == CLIENT_KEYSPACE_END
        return True

    assert run(c, main())
    c.stop()


def test_selector_get_range_endpoints():
    c = SimCluster(seed=804, n_storage_shards=2)
    db = c.database()
    _seed_keys(c, db)

    async def main():
        tr = db.create_transaction()
        rows = await tr.get_range(
            KeySelector.first_greater_or_equal(b"k03"),
            KeySelector.first_greater_than(b"k06"),
        )
        assert [k for k, _ in rows] == [b"k03", b"k04", b"k05", b"k06"]
        # inverted resolution -> empty, not an error
        rows = await tr.get_range(
            KeySelector.first_greater_than(b"k06"),
            KeySelector.first_greater_or_equal(b"k03"),
        )
        assert rows == []
        return True

    assert run(c, main())
    c.stop()


# -- RYW: selectors over the merged (cache, writes) view ---------------------


def test_ryw_selector_sees_writes_and_deletes():
    """or_equal on a key DELETED in this transaction's write set steps past
    it; a key written this transaction is landable (RYWIterator merge)."""
    c = SimCluster(seed=805, n_storage_shards=2)
    db = c.database()
    _seed_keys(c, db, n=10)

    async def main():
        tr = ReadYourWritesTransaction(db)
        tr.clear(b"k05")
        # or_equal anchored on the deleted key: it no longer counts
        assert await tr.get_key(KeySelector.last_less_or_equal(b"k05")) == b"k04"
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"k05")) == b"k06"
        # a key written THIS transaction is a resolution target
        tr.set(b"k045", b"x")
        assert await tr.get_key(KeySelector.first_greater_than(b"k04")) == b"k045"
        assert await tr.get_key(KeySelector.last_less_than(b"k05")) == b"k045"
        # and selector ranges run over the same merged view
        rows = await tr.get_range(
            KeySelector.first_greater_or_equal(b"k04"),
            KeySelector.first_greater_or_equal(b"k07"),
        )
        assert [k for k, _ in rows] == [b"k04", b"k045", b"k06"]
        return True

    assert run(c, main())
    c.stop()


def test_ryw_read_twice_is_one_storage_fetch():
    """THE SnapshotCache acceptance: a repeated point read inside one
    transaction issues exactly one cluster fetch (counted via storage-read
    counters), and a covered range read re-serves from cache too."""
    c = SimCluster(seed=806, n_storage_shards=2)
    db = c.database()
    _seed_keys(c, db)

    async def main():
        tr = ReadYourWritesTransaction(db)
        before = _storage_reads(c)
        assert await tr.get(b"k07") == b"v07"
        after_first = _storage_reads(c)
        assert await tr.get(b"k07") == b"v07"
        assert await tr.get(b"k07") == b"v07"
        assert _storage_reads(c) == after_first, "repeat reads hit the cluster"
        assert after_first - before == 1

        # a range read populates the cache; point reads INSIDE the fetched
        # window (hits and known-absent gaps) are free afterwards
        rows = await tr.get_range(b"k10", b"k15")
        assert len(rows) == 5
        mark = _storage_reads(c)
        assert await tr.get(b"k12") == b"v12"
        assert await tr.get(b"k12\x00") is None      # known-empty gap
        rows2 = await tr.get_range(b"k11", b"k14")   # sub-range
        assert [k for k, _ in rows2] == [b"k11", b"k12", b"k13"]
        assert _storage_reads(c) == mark, "cache-covered reads re-fetched"
        return True

    assert run(c, main())
    stats = db.cache_stats.snapshot()
    assert stats["cache_hits"] >= 4
    assert stats["cache_inserts"] >= 2
    c.stop()


def test_ryw_cache_eviction_respects_byte_cap():
    """RYW_CACHE_BYTES caps the per-transaction cache with LRU-ish
    eviction: over-cap reads still complete and stay CORRECT, evictions
    are counted, and live bytes stay bounded."""
    from foundationdb_tpu.runtime.knobs import ClientKnobs

    knobs = ClientKnobs()
    knobs.RYW_CACHE_BYTES = 256
    c = SimCluster(seed=807)
    db = c.database()
    db.knobs = knobs
    _seed_keys(c, db, n=30)

    async def main():
        tr = ReadYourWritesTransaction(db)
        assert tr._cache.max_bytes == 256
        for i in range(30):
            assert await tr.get(b"k%02d" % i) == b"v%02d" % i
        # re-reads remain correct whether evicted (re-fetch) or cached
        for i in range(30):
            assert await tr.get(b"k%02d" % i) == b"v%02d" % i
        return True

    assert run(c, main())
    stats = db.cache_stats.snapshot()
    assert stats["cache_evictions"] > 0, "cap never evicted"
    assert stats["bytes"] <= 256
    c.stop()


def test_ryw_cache_cleared_on_reset_and_error():
    """reset()/on_error() drop the cache with the write map: the retry
    reads at a NEW version, so nothing cached may survive."""
    c = SimCluster(seed=808)
    db = c.database()
    _seed_keys(c, db, n=4)

    async def main():
        tr = ReadYourWritesTransaction(db)
        await tr.get(b"k01")
        assert tr._cache._segs
        tr.reset()
        assert not tr._cache._segs
        await tr.get(b"k01")
        from foundationdb_tpu.roles.types import NotCommitted

        await tr.on_error(NotCommitted("forced"))
        assert not tr._cache._segs
        return True

    assert run(c, main())
    c.stop()


# -- wire + observability -----------------------------------------------------


def test_get_key_codec_roundtrip_and_protocol_bump():
    from foundationdb_tpu.runtime.serialize import (
        PROTOCOL_VERSION,
        decode_payload,
        encode_payload,
    )

    assert PROTOCOL_VERSION & 0xFF >= 0x03  # selector tags shipped
    for msg in (
        GetKeyRequest(KeySelector(b"a\x00b", True, -3), 17, b"", b"\xff",
                      debug_id="d-1"),
        GetKeyRequest(KeySelector(b"", False, 0), 0, b"a", b"b"),
        GetKeyReply(KeySelector(b"\xff", True, 0)),
        GetKeyReply(KeySelector(b"k", False, 12)),
    ):
        back = decode_payload(encode_payload(msg, strict=True))
        assert back == msg, (msg, back)


def test_cache_counters_in_cluster_status():
    from foundationdb_tpu.control.status import cluster_status, validate_status

    c = SimCluster(seed=809)
    db = c.database()
    _seed_keys(c, db, n=6)

    async def main():
        tr = ReadYourWritesTransaction(db)
        await tr.get(b"k01")
        await tr.get(b"k01")
        await tr.get_key(KeySelector.first_greater_or_equal(b"k00"))
        return True

    assert run(c, main())
    doc = cluster_status(c)
    validate_status(doc)
    rc = doc["clients"]["ryw_cache"]
    assert doc["clients"]["databases"] == 1
    assert rc["cache_hits"] >= 1
    assert rc["cache_inserts"] >= 1
    assert rc["selector_reads"] >= 1
    c.stop()


def test_client_metrics_event_emitted():
    """The periodic ClientMetrics trace event (the client-side slice of the
    *Metrics plane) emits within one interval and validates against
    ROLE_METRICS_SCHEMA."""
    from foundationdb_tpu.control.status import validate_metrics_event
    from foundationdb_tpu.runtime.knobs import CoreKnobs

    knobs = CoreKnobs()
    knobs.METRICS_INTERVAL = 0.5
    c = SimCluster(seed=810, knobs=knobs)
    db = c.database()
    _seed_keys(c, db, n=4)

    async def main():
        tr = ReadYourWritesTransaction(db)
        for _ in range(3):
            await tr.get(b"k01")
        await c.loop.delay(0.6)
        return True

    assert run(c, main())
    evs = c.trace.find("ClientMetrics")
    assert evs, "no ClientMetrics emitted"
    for ev in evs:
        validate_metrics_event(ev)
    assert any(e["CacheHitsPerSec"] > 0 for e in evs)
    c.stop()


def test_selector_resolution_adds_conflict_range():
    """A get_key read-conflicts on the span that DETERMINED the resolution
    (getKeyAndConflictRange): a write landing inside it between read
    version and commit aborts the transaction."""
    from foundationdb_tpu.client.transaction import NotCommitted

    c = SimCluster(seed=811)
    db = c.database()
    _seed_keys(c, db, n=6)

    async def main():
        tr = db.create_transaction()
        # resolution (k02, k03]-dependent: first key > k02 is k03
        assert await tr.get_key(KeySelector.first_greater_than(b"k02")) == b"k03"
        # a concurrent commit inserts INTO the determining span
        tr2 = db.create_transaction()
        tr2.set(b"k02\x01", b"zap")
        await tr2.commit()
        tr.set(b"out", b"x")
        try:
            await tr.commit()
            return False
        except NotCommitted:
            return True

    assert run(c, main()), "selector read did not conflict-protect its span"
    c.stop()


def test_selector_walk_past_large_uncompacted_clear():
    """A committed clear_range whose keys are still in the base store (the
    overlay not yet folded) leaves >1000 DEAD base rows in the walk window;
    find_key must re-fetch past a truncated base chunk instead of resolving
    against a partial candidate set (regression: the walk used to cap its
    base scan at need+1000 rows and silently skip the live key beyond)."""
    from foundationdb_tpu.runtime.knobs import CoreKnobs

    knobs = CoreKnobs()
    knobs.STORAGE_DURABILITY_LAG = 5.0  # folds at fixed, avoidable ticks
    c = SimCluster(seed=807, n_storage_shards=1, knobs=knobs)
    db = c.database()

    async def main():
        for chunk in range(12):  # 1200 keys, committed in batches
            tr = db.create_transaction()
            for i in range(100):
                j = chunk * 100 + i
                tr.set(b"t%04d" % j, b"v")
            await tr.commit()
        tr = db.create_transaction()
        tr.set(b"zz", b"end")
        await tr.commit()
        await c.loop.delay(11.0)  # >= 2 durability folds: keys now in base
        tr = db.create_transaction()
        tr.clear_range(b"t", b"u")  # dead in the overlay, live in the base
        await tr.commit()
        # read BEFORE the next fold (t ~= 11, next fold at 15): the walk
        # crosses 1200 dead base rows and must land on the live key beyond
        tr = db.create_transaction()
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"t")) == b"zz"
        assert await tr.get_key(
            KeySelector.first_greater_or_equal(b"t0500") + 2
        ) == CLIENT_KEYSPACE_END  # zz +1, then clamp
        return True

    assert run(c, main())
    c.stop()
