"""Regression pins for interleaving hazards the flowcheck audit found.

PR 12's flowcheck rules (docs/LINT.md "Interleaving hazards") audited the
tree for state read before an `await` and trusted after it.  Two of the
findings were REAL bugs; each test here crafts the exact interleaving and
was demonstrated to fail on the pre-fix code:

  * `RecoverableCluster._promote_remote_region` pinned the promotion's
    convergence wait to the replica OBJECTS captured before the wait.  A
    remote replica power-killed and rebuilt mid-wait
    (`restart_remote_region` replaces the object in place) left the
    promotion polling a dead server's frozen version forever — a
    configured failover that never completes, with the cluster already
    committed to the promoted map.

  * `Transaction.get_read_version` checked `_read_version is None` and
    assigned it after the GRV await.  Two reads racing the FIRST read
    version each passed the check and issued their own GRV; landing in
    different proxy batches pins two DIFFERENT snapshots to one
    transaction (reads before/after disagree about committed data).  The
    fix takes ownership of the fetch before suspending — followers share
    the leader's future, one GRV per transaction (the reference caches
    Future<Version>, NativeAPI's readVersion).
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.runtime import buggify as bg
from foundationdb_tpu.runtime.core import DeterministicRandom, TimedOut


def test_promotion_survives_remote_region_rebuild_mid_wait():
    """Park a region failover in its convergence wait (dead router ⇒ the
    remote replicas cannot advance), then rebuild the whole remote region
    from disk.  The promotion must re-resolve the replicas from the LIVE
    set and complete; pre-fix it watched the dead objects forever."""
    from foundationdb_tpu.control.region import teams_promoted

    c = RecoverableCluster(seed=9301, n_storage_shards=1, remote_region=True)
    try:
        db = c.database()
        loop = c.loop

        async def put(tr):
            tr.set(b"ir-k1", b"v1")

        loop.run_until(loop.spawn(db.run(put)), 200.0)
        # let the relay land the write remotely, so the replicas are live
        # at SOME version before they stall
        fut = loop.spawn(_wait_remote_nonzero(c))
        loop.run_until(fut, loop.now() + 60.0)

        # stall the remote plane: the router dies, replicas stop advancing
        c.log_router.process.kill()

        async def put2(tr):
            tr.set(b"ir-k2", b"v2")

        loop.run_until(loop.spawn(db.run(put2)), loop.now() + 200.0)

        promo = loop.spawn(c.promote_remote_region())
        # drive until the promoted map is installed — the promotion is now
        # inside its convergence wait (remote versions < boundary, and
        # they cannot advance: the router is dead)
        for _ in range(200_000):
            if teams_promoted(c.controller.storage_teams_tags):
                break
            loop.run_one()
        assert teams_promoted(c.controller.storage_teams_tags)
        for _ in range(200):
            loop.run_one()
        assert not promo.done(), "test setup: promotion must be parked"

        # the audited interleaving: every remote replica is power-killed
        # and the region is rebuilt from its disks — the replica OBJECTS
        # the promotion captured are now corpses
        for ss in list(c.remote_storage):
            ss.process.kill()
        c.restart_remote_region()

        assert loop.run_until(promo, loop.now() + 300.0) is True

        async def read(tr):
            return [await tr.get(b"ir-k1"), await tr.get(b"ir-k2")]

        got = loop.run_until(loop.spawn(db.run(read)), loop.now() + 300.0)
        assert got == [b"v1", b"v2"]
    finally:
        c.stop()


async def _wait_remote_nonzero(c):
    while not all(ss.version.get() > 0 for ss in c.remote_storage):
        await c.loop.delay(0.05)


def test_reset_during_grv_fetch_never_pins_the_stale_leader_version():
    """Review pin on the single-flight fix itself: a reset() while the
    GRV leader's RPC is in flight disowns that fetch — when the OLD
    leader's reply lands AFTER the retry's new fetch resolved, it must
    NOT stamp the pre-reset version onto the retried transaction."""
    from foundationdb_tpu.cluster import SimCluster
    from foundationdb_tpu.runtime.core import Promise

    c = SimCluster(seed=3)
    try:
        loop = c.loop
        db = c.database()
        tr = db.create_transaction()
        gates: list[Promise] = []

        async def fake_fetch():
            p = Promise()
            gates.append(p)
            return await p.future

        tr._fetch_read_version = fake_fetch
        def drive_until(pred):
            for _ in range(100_000):
                if pred():
                    return
                if not loop.run_one():
                    break  # idle loop: spinning would hang the test
            assert pred(), "test setup: condition never reached"

        ta = loop.spawn(tr.get_read_version())   # leader A
        drive_until(lambda: len(gates) >= 1)
        tr.reset()                               # retry path: disowns A
        tb = loop.spawn(tr.get_read_version())   # NEW leader B
        drive_until(lambda: len(gates) >= 2)
        gates[1].send(200)                       # the retry's version lands
        loop.run_until(tb, loop.now() + 5.0)
        assert tb.result() == 200
        gates[0].send(100)                       # the STALE reply lands late
        loop.run_until(ta, loop.now() + 5.0)
        # the disowned leader must not clobber the retry's snapshot — and
        # its own caller follows the live value instead of the stale one
        assert tr._read_version == 200
        assert ta.result() == 200
    finally:
        c.stop()


def test_concurrent_first_reads_share_one_read_version():
    """Two reads racing a transaction's FIRST get_read_version must pin
    ONE snapshot.  The forced `proxy.delay_grv` splits the two GRVs into
    separate proxy batches with the committed version advancing in
    between — pre-fix the two callers observed different versions."""
    from foundationdb_tpu.cluster import SimCluster

    c = SimCluster(seed=31)
    try:
        bg.enable(DeterministicRandom(7), enable_prob=0.0, fire_prob=0.0)
        bg.force("proxy.delay_grv", times=2)
        db = c.database()
        tr = db.create_transaction()
        got = {}

        async def read(which):
            got[which] = await tr.get_read_version()

        ta = c.loop.spawn(read("a"))
        # drive until A's batch entered its FORCED delay (the force budget
        # decrements exactly when maybe_delay consumes it) — the GRV server
        # is now parked mid-batch with A admitted
        for _ in range(100_000):
            c.loop.run_one()
            if bg.snapshot()["forced"].get("proxy.delay_grv", 0) < 2:
                break
            if ta.done():
                break
        assert bg.snapshot()["forced"].get("proxy.delay_grv", 0) == 1, (
            "test setup: A's GRV batch never reached the forced delay"
        )
        assert not ta.done(), "test setup: A must still be in flight"

        tb = c.loop.spawn(read("b"))
        while not ta.done():
            c.loop.run_one()
        # the cluster commits between the two GRV batches
        c.proxy.committed_version.set(
            c.proxy.committed_version.get() + 1_000_000
        )
        c.loop.run_until(tb, c.loop.now() + 30.0)
        assert got["a"] == got["b"], (
            f"one transaction observed two snapshots: {got}"
        )
        assert tr._read_version == got["a"]
    finally:
        bg.disable()
        c.stop()
