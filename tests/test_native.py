"""Native C++ skip-list backend: parity vs oracle, GC, plugin ABI."""

import random

import pytest

from foundationdb_tpu.conflict.api import TxInfo, Verdict
from foundationdb_tpu.conflict.native import NativeConflictSet, native_plugin
from foundationdb_tpu.conflict.oracle import OracleConflictSet


def test_plugin_loads():
    assert native_plugin().backend_name == "skiplist-cpp"


def test_basic_semantics():
    cs = NativeConflictSet()
    assert cs.resolve_batch(10, [TxInfo(5, [], [(b"a", b"b")])]) == [Verdict.COMMITTED]
    got = cs.resolve_batch(
        20,
        [
            TxInfo(5, [(b"a", b"a\x00")], []),          # sees write @10 -> conflict
            TxInfo(10, [(b"a", b"a\x00")], [(b"c", b"d")]),  # commits
            TxInfo(10, [(b"c", b"c\x00")], []),          # intra-batch conflict
            TxInfo(10, [(b"x", b"y")], []),              # commits
        ],
    )
    assert got == [Verdict.CONFLICT, Verdict.COMMITTED, Verdict.CONFLICT, Verdict.COMMITTED]
    cs.remove_before(15)
    got = cs.resolve_batch(30, [TxInfo(12, [], []), TxInfo(16, [(b"zz", b"zzz")], [])])
    assert got == [Verdict.TOO_OLD, Verdict.COMMITTED]
    cs.close()


def test_version_monotonicity_enforced():
    cs = NativeConflictSet()
    cs.resolve_batch(10, [TxInfo(0, [], [])])
    with pytest.raises(ValueError):
        cs.resolve_batch(10, [TxInfo(0, [], [])])
    cs.close()


def _random_key(rng, alpha=5, maxlen=5):
    return bytes(rng.randrange(alpha) for _ in range(rng.randrange(1, maxlen)))


def _random_range(rng):
    a, b = _random_key(rng), _random_key(rng)
    return (a, a + b"\x00") if a == b else (min(a, b), max(a, b))


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_randomized_parity_vs_oracle(seed):
    rng = random.Random(seed)
    nat, orc = NativeConflictSet(), OracleConflictSet()
    version = 0
    for i in range(120):
        version += rng.randrange(1, 4)
        txns = [
            TxInfo(
                rng.randrange(max(version - 8, 0), version),
                [_random_range(rng) for _ in range(rng.randrange(0, 4))],
                [_random_range(rng) for _ in range(rng.randrange(0, 4))],
            )
            for _ in range(rng.randrange(1, 10))
        ]
        vn = nat.resolve_batch(version, txns)
        vo = orc.resolve_batch(version, txns)
        assert vn == vo, f"seed {seed} batch {i} @v{version}: {vn} != {vo}"
        if i % 9 == 8:
            floor = max(version - 6, 0)
            nat.remove_before(floor)
            orc.remove_before(floor)
    nat.close()


def test_gc_keeps_node_count_bounded():
    rng = random.Random(9)
    cs = NativeConflictSet()
    version = 0
    peaks = []
    for i in range(200):
        version += 1
        txns = [TxInfo(version - 1, [], [_random_range(rng)]) for _ in range(8)]
        cs.resolve_batch(version, txns)
        cs.remove_before(max(version - 5, 0))
        peaks.append(cs.node_count)
    # the whole key alphabet is tiny; after GC the step function must stay
    # near the alphabet size rather than growing with batches
    assert max(peaks[100:]) < 2000
    cs.close()
