"""Recovery: pipeline kills mid-workload, generation change, invariants hold
(the CycleTest-with-Attrition configuration of the reference test suite)."""

import pytest

from foundationdb_tpu.control.recoverable import RecoverableCluster
from foundationdb_tpu.workloads.attrition import AttritionWorkload
from foundationdb_tpu.workloads.bank import BankWorkload
from foundationdb_tpu.workloads.base import run_workloads
from foundationdb_tpu.workloads.cycle import CycleWorkload


def test_basic_commit_and_read_through_controller():
    c = RecoverableCluster(seed=31)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"k", b"v1")
        await tr.commit()
        tr2 = db.create_transaction()
        return await tr2.get(b"k")

    assert c.run_until(c.loop.spawn(main()), 60) == b"v1"
    c.stop()


def test_explicit_recovery_preserves_data():
    c = RecoverableCluster(seed=32, n_storage_shards=2)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        for i in range(10):
            tr.set(b"pre/%02d" % i, b"x%d" % i)
        await tr.commit()
        epoch_before = c.controller.epoch
        # kill the proxy: the monitor must notice and rebuild the pipeline
        c.controller.generation.proxy.commit_stream._process.kill()
        await c.loop.delay(8.0)
        assert c.controller.epoch > epoch_before
        # data written before the crash is still there; new writes work
        tr = db.create_transaction()
        rows = await tr.get_range(b"pre/", b"pre0")
        tr.set(b"post", b"alive")
        await tr.commit()
        tr2 = db.create_transaction()
        post = await tr2.get(b"post")
        return len(rows), post

    nrows, post = c.run_until(c.loop.spawn(main()), 120)
    assert nrows == 10 and post == b"alive"
    assert c.controller.recoveries >= 1
    c.stop()


def test_cycle_survives_attrition():
    c = RecoverableCluster(seed=33, n_resolvers=2, n_storage_shards=2)
    cyc = CycleWorkload(nodes=10, clients=2, txns_per_client=12)
    att = AttritionWorkload(kills=2, interval=4.0, start_delay=0.5)
    metrics = run_workloads(c, [cyc, att], deadline=600.0)
    assert metrics["Cycle"]["committed"] == 24
    assert len(metrics["Attrition"]["killed"]) == 2
    assert c.controller.recoveries >= 2
    c.stop()


def test_bank_survives_tlog_kill():
    c = RecoverableCluster(seed=34, n_storage_shards=2, n_tlogs=2)
    bank = BankWorkload(accounts=6, clients=2, transfers_per_client=10)
    att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.8)
    metrics = run_workloads(c, [bank, att], deadline=600.0)
    assert metrics["Bank"]["committed"] == 20
    c.stop()


def test_recovery_determinism():
    def once():
        c = RecoverableCluster(seed=35, n_resolvers=2)
        cyc = CycleWorkload(nodes=8, clients=2, txns_per_client=6)
        att = AttritionWorkload(kills=1, interval=2.0, start_delay=0.6)
        m = run_workloads(c, [cyc, att], deadline=600.0)
        out = (m, c.controller.epoch, round(c.loop.now(), 9))
        c.stop()
        return out

    assert once() == once()


def test_fence_aborts_zombie_original():
    """The unknown-result fence property (NativeAPI.actor.cpp:2482-2502):
    once the fence commits, an in-flight 'zombie' commit whose read snapshot
    predates it can NEVER land — its read set conflicts with the fence's
    write set."""
    from foundationdb_tpu.cluster import SimCluster
    from foundationdb_tpu.roles.types import NotCommitted

    c = SimCluster(seed=71)
    db = c.database()

    async def main():
        tr = db.create_transaction()
        tr.set(b"ctr", b"0")
        await tr.commit()
        # the 'original': reads ctr, writes ctr, but its commit is delayed
        zombie = db.create_transaction()
        v = int(await zombie.get(b"ctr"))
        zombie.set(b"ctr", b"%d" % (v + 1))
        # the fence lands first (what on_error does after unknown result)
        await zombie._commit_fence(b"ctr")
        # the zombie arrives late: it must abort, not double-apply
        try:
            await zombie.commit()
            return "committed"
        except NotCommitted:
            tr2 = db.create_transaction()
            return await tr2.get(b"ctr")

    assert c.run_until(c.loop.spawn(main()), 60) == b"0"
    c.stop()


def test_unknown_result_exactly_once_increment():
    """Kill the proxy mid-commit; the client sees CommitUnknownResult,
    fences via on_error, then VERIFIES by re-reading before retrying — the
    fence guarantees the read's answer is final.  The counter ends at
    exactly initial+1 whichever side of the commit the kill landed on."""
    from foundationdb_tpu.roles.types import CommitUnknownResult, NotCommitted
    from foundationdb_tpu.runtime.core import TimedOut

    for kill_delay in (0.001, 0.05, 0.4):
        c = RecoverableCluster(seed=72, n_storage_shards=2)
        db = c.database()

        async def main():
            tr = db.create_transaction()
            tr.set(b"ctr", b"100")
            await tr.commit()

            tr = db.create_transaction()
            val = int(await tr.get(b"ctr"))
            tr.set(b"ctr", b"%d" % (val + 1))

            async def attempt():
                try:
                    await tr.commit()
                    return "committed"
                except (CommitUnknownResult, TimedOut):
                    return "unknown"
                except NotCommitted:
                    return "aborted"

            async def get_retry(t, key):
                while True:
                    try:
                        return await t.get(key)
                    except TimedOut as e:  # recovery window: retry the read
                        await t.on_error(e)

            task = c.loop.spawn(attempt())
            await c.loop.delay(kill_delay)
            c.controller.generation.proxy.commit_stream._process.kill()
            outcome = await task
            if outcome == "unknown":
                await tr.on_error(CommitUnknownResult())
                seen = int(await get_retry(tr, b"ctr"))
                if seen == val:  # original provably did not land: retry once
                    tr.set(b"ctr", b"%d" % (val + 1))
                    while True:
                        try:
                            await tr.commit()
                            break
                        except (CommitUnknownResult, TimedOut, NotCommitted):
                            await tr.on_error(CommitUnknownResult())
                            seen = int(await get_retry(tr, b"ctr"))
                            if seen != val:
                                break
                            tr.set(b"ctr", b"%d" % (val + 1))
            tr3 = db.create_transaction()
            return await get_retry(tr3, b"ctr")

        final = c.run_until(c.loop.spawn(main()), 300)
        assert final == b"101", f"kill_delay={kill_delay}: got {final}"
        c.stop()
