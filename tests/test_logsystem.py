"""LogSystem abstraction (fdbserver/LogSystem.h:787 ILogSystem;
TagPartitionedLogSystem.actor.cpp): epoch-end determination over a TLog
set — lock, minority-survival recovery, pair-loss refusal, seed fan-out."""

import pytest

from foundationdb_tpu.control.logsystem import LogSystem
from foundationdb_tpu.roles.tlog import TLog
from foundationdb_tpu.roles.types import Mutation, MutationType
from foundationdb_tpu.rpc.network import SimNetwork
from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
from foundationdb_tpu.runtime.trace import TraceCollector


def _mut(k: bytes) -> Mutation:
    return Mutation(MutationType.SET_VALUE, k, b"v")


def _mini_set(loop, net, n_slots: int, tags: list[str], upto: int):
    """n_slots TLogs seeded so each tag's entries live on its replica pair
    (the same placement the proxies' tag fan-out produces)."""
    seeds = [dict() for _ in range(n_slots)]
    for tag in tags:
        entries = [(v, [_mut(b"%s-%d" % (tag.encode(), v))]) for v in range(1, upto + 1)]
        for s in LogSystem.tag_slots(tag, n_slots):
            seeds[s][tag] = list(entries)
    tlogs = [
        TLog(net.create_process(f"tlog{i}"), loop, initial_tags=seeds[i])
        for i in range(n_slots)
    ]
    for i, t in enumerate(tlogs):
        t.version.set(upto + i)  # survivors disagree on their end version
    return tlogs


def test_tag_slots_replication_pairs():
    assert LogSystem.tag_slots("ss-0-r0", 3) == [0, 1]
    assert LogSystem.tag_slots("ss-1-r0", 3) == [1, 2]
    assert LogSystem.tag_slots("ss-2-r0", 3) == [2, 0]
    assert LogSystem.tag_slots("ss-0-r1", 3) == [1, 2]
    assert LogSystem.tag_slots("ss-5", 4) == [1, 2]  # legacy replica-0 form
    assert LogSystem.tag_slots("ss-0-r0", 1) == [0]


def test_lock_recovers_from_minority_of_tlogs():
    """Epoch end with only a MINORITY of the set reachable: every tag still
    has a surviving replica, so recovery proceeds with the min surviving
    end version (the recovery-version rule)."""
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(7), TraceCollector(clock=loop.now))
    tags = ["ss-0-r0", "ss-1-r0", "ss-2-r0"]
    tlogs = _mini_set(loop, net, 3, tags, upto=5)
    # kill slots 0 and 2: a single survivor (slot 1) still covers
    # ss-0 (pair 0,1) and ss-1 (pair 1,2) but ss-2's pair is (2,0) — both
    # dead.  First check the SURVIVABLE shape: kill only slot 0.
    tlogs[0].process.kill()
    ls = LogSystem(1, tlogs)
    cc = net.create_process("cc")

    async def go():
        rv, replies = await ls.lock(net, cc, None, required_tags=tags)
        seeds = LogSystem.merge_replies(replies, rv, 3, lambda t: True)
        return rv, replies, seeds

    rv, replies, seeds = loop.run_until(loop.spawn(go()), 30)
    assert replies[0] is None  # dead, no fs: no disk fallback
    # min over survivors' ends: slots 1,2 ended at 6 and 7
    assert rv == 6
    # every tag's entries survived into the new seeds, on its replica pair
    for tag in tags:
        for s in LogSystem.tag_slots(tag, 3):
            assert [v for v, _ in seeds[s][tag]] == [1, 2, 3, 4, 5]
    for t in tlogs:
        t.stop()


def test_lock_refuses_pair_loss():
    """Both replicas of one tag lost with no disk fallback: recovery must
    REFUSE (silent proceeding would be acked-data loss)."""
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(8), TraceCollector(clock=loop.now))
    tags = ["ss-0-r0", "ss-1-r0", "ss-2-r0"]
    tlogs = _mini_set(loop, net, 3, tags, upto=4)
    tlogs[2].process.kill()
    tlogs[0].process.kill()  # ss-2's pair is (2, 0): both gone
    ls = LogSystem(1, tlogs)
    cc = net.create_process("cc")

    class FakeFS:  # fs present but no files: the fallback finds nothing
        @staticmethod
        def exists(_path):
            return False

    async def go():
        with pytest.raises(RuntimeError, match="ss-2.*lost"):
            await ls.lock(net, cc, FakeFS(), required_tags=tags)
        return True

    assert loop.run_until(loop.spawn(go()), 30)
    for t in tlogs:
        t.stop()


def test_merge_replies_drops_finished_consumer_tags():
    replies = [
        type("R", (), {"tags": {
            "ss-0-r0": [(1, [_mut(b"a")])],
            "backup-0": [(1, [_mut(b"b")])],
            "dr-0": [(1, [_mut(b"c")])],
        }})(),
    ]
    live = {"dr-0"}
    seeds = LogSystem.merge_replies(
        replies, 1, 2, lambda t: not t.startswith(("backup-", "dr-")) or t in live
    )
    all_tags = {t for s in seeds for t in s}
    assert "backup-0" not in all_tags  # finished consumer: residue dropped
    assert "dr-0" in all_tags          # live consumer: re-seeded
    assert "ss-0-r0" in all_tags
