"""Mutual-TLS transport (rpc/transport.py TLSConfig — the FDBLibTLS slot):
cluster-CA-signed peers handshake and serve RPCs; plaintext and wrong-CA
peers are severed by the verify-peers policy."""

import subprocess
import time as _time

import pytest

from foundationdb_tpu.roles.types import GetValueRequest  # any dataclass payload
from foundationdb_tpu.rpc.stream import RequestStream, RequestStreamRef
from foundationdb_tpu.rpc.transport import NetDriver, RealNetwork, TLSConfig
from foundationdb_tpu.runtime.core import BrokenPromise, EventLoop, TimedOut


def _mkcert(tmp, name, ca=None):
    """Self-signed CA or CA-signed leaf via the openssl CLI."""
    key, crt = tmp / f"{name}.key", tmp / f"{name}.crt"
    if ca is None:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(crt), "-days", "1",
             "-subj", f"/CN={name}"],
            check=True, capture_output=True,
        )
    else:
        ca_key, ca_crt = ca
        csr = tmp / f"{name}.csr"
        subprocess.run(
            ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={name}"],
            check=True, capture_output=True,
        )
        subprocess.run(
            ["openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
             "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
             "-days", "1"],
            check=True, capture_output=True,
        )
    return key, crt


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tls")
    ca = _mkcert(tmp, "cluster-ca")
    a = _mkcert(tmp, "node-a", ca=ca)
    b = _mkcert(tmp, "node-b", ca=ca)
    rogue_ca = _mkcert(tmp, "rogue-ca")
    rogue = _mkcert(tmp, "rogue", ca=rogue_ca)
    return {"ca": ca, "a": a, "b": b, "rogue_ca": rogue_ca, "rogue": rogue}


def _tls(certs, who, ca="ca"):
    key, crt = certs[who]
    return TLSConfig(str(crt), str(key), str(certs[ca][1]))


def _pump_until(drivers, fut, wall_timeout=20.0):
    start = _time.monotonic()
    while not fut.done():
        if _time.monotonic() - start > wall_timeout:
            raise TimedOut("tls test wall timeout")
        for d in drivers:
            d._tick()
    return fut.result()


def _echo_server(net, loop):
    rs = RequestStream(net.process, "wlt:echo")

    async def serve():
        while True:
            req = await rs.next()
            req.reply(("echoed", req.payload))

    loop.spawn(serve())


def test_mtls_request_reply(certs):
    loop_s, loop_c = EventLoop(), EventLoop()
    server = RealNetwork(loop_s, name="server", tls=_tls(certs, "a"))
    client = RealNetwork(loop_c, name="client", tls=_tls(certs, "b"))
    try:
        _echo_server(server, loop_s)
        from foundationdb_tpu.rpc.network import Endpoint

        ref = RequestStreamRef(
            client, client.process, Endpoint(server.address, "wlt:echo")
        )

        async def ask():
            return await ref.get_reply(GetValueRequest(b"k", 1), timeout=15.0)

        fut = loop_c.spawn(ask())
        kind, payload = _pump_until(
            [NetDriver(loop_s, server), NetDriver(loop_c, client)], fut
        )
        assert kind == "echoed" and payload.key == b"k"
    finally:
        server.close()
        client.close()


@pytest.mark.parametrize("client_tls", ["plaintext", "rogue_ca"])
def test_untrusted_client_rejected(certs, client_tls):
    """Verify-peers policy: both a plaintext peer and one whose cert chains
    to a DIFFERENT CA are severed before any frame is served."""
    loop_s, loop_c = EventLoop(), EventLoop()
    server = RealNetwork(loop_s, name="server", tls=_tls(certs, "a"))
    client = RealNetwork(
        loop_c, name="untrusted",
        tls=None if client_tls == "plaintext"
        else _tls(certs, "rogue", ca="rogue_ca"),
    )
    try:
        _echo_server(server, loop_s)
        from foundationdb_tpu.rpc.network import Endpoint

        ref = RequestStreamRef(
            client, client.process, Endpoint(server.address, "wlt:echo")
        )

        async def ask():
            try:
                await ref.get_reply(GetValueRequest(b"k", 1), timeout=3.0)
                return "replied"
            except (BrokenPromise, TimedOut) as e:
                return type(e).__name__

        fut = loop_c.spawn(ask())
        out = _pump_until(
            [NetDriver(loop_s, server), NetDriver(loop_c, client)], fut
        )
        assert out in ("BrokenPromise", "TimedOut")
    finally:
        server.close()
        client.close()
