"""Headline benchmark: device OCC conflict kernel vs native CPU skip list.

North star (BASELINE.json): conflict-checks/s at 64K live write ranges with
abort-set parity.  The stream mimics the reference's skipListTest shape
(fdbserver/SkipList.cpp:1412-1502: batches of transactions with point-ish
16-byte-key ranges) at steady state inside an MVCC window:

  * history pre-populated to ~64K live write ranges (untimed)
  * timed: batches of TXNS_PER_BATCH txns, each 2 point reads + 1 point
    write, keys uniform over a pool, snapshots uniform in the window
  * both backends consume pre-packed arrays (the proxy->resolver wire format
    is packed tensors, so marshalling is not what's being compared)
  * verdict parity asserted batch-by-batch

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where value
is device checks/s and vs_baseline is the speedup over the native CPU skip
list on this host.

Per-phase accounting (the skipListTest PerfCounters analog) lives in
phase_timings.py; the bench itself autotunes the kernel's search/merge
implementations on the live device before timing (see _autotune).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

TXNS_PER_BATCH = 8192  # the BASELINE configs' 10K-class commit batches
READS_PER_TXN = 2
TIMED_BATCHES = 16
PREFILL_BATCHES = 8  # 8 * 8192 point writes ≈ 64K live ranges at t0
KEY_BYTES = 16  # reference benchmark key width (performance.rst:14)
# 16-byte lanes: the [k, k+\x00) end key differs from its begin only in the
# length lane (the \x00 is zero padding), so 4 data words + length suffice
MAX_KEY_BYTES = 16
KEY_POOL = 1 << 20
WINDOW = PREFILL_BATCHES + TIMED_BATCHES + 2  # no GC mid-run: window covers it
CAP = 1 << 19
REC_CAP = 1 << 17  # LSM recent level: ~8 batches (2*8192 boundaries each)
SEED = 20260729


def gen_pool(rng):
    return rng.integers(0, 256, size=(KEY_POOL, KEY_BYTES), dtype=np.uint8)


def gen_batch(rng, pool, version):
    """One batch as index arrays: reads[B, READS], writes[B], snaps[B]."""
    b = TXNS_PER_BATCH
    return dict(
        version=version,
        reads=rng.integers(0, KEY_POOL, size=(b, READS_PER_TXN)),
        writes=rng.integers(0, KEY_POOL, size=(b,)),
        snaps=np.maximum(version - 1 - rng.integers(0, WINDOW // 2, size=(b,)), 0).astype(np.int64),
    )


# ---------------- device packing (uint32 word lanes, keys.py layout) --------


def device_pack(pool_words, batch, bucket):
    """Build resolve_arrays inputs from index arrays, fully vectorized."""
    b = TXNS_PER_BATCH
    n_read, n_write = b * READS_PER_TXN, b
    R, Wn = bucket(2 * n_read) // 2, bucket(n_write)
    R = max(R, n_read)
    W = pool_words.shape[1]  # data words + length lane

    def keyed(idx, is_end):
        k = pool_words[idx.ravel()]
        if is_end:  # [k, k + b"\x00"): same words, length 17
            k = k.copy()
            k[:, -1] = KEY_BYTES + 1
        return k

    rbv = np.full((R, W), 0xFFFFFFFF, dtype=np.uint32)
    rev = np.full((R, W), 0xFFFFFFFF, dtype=np.uint32)
    rtv = np.full(R, -1, dtype=np.int32)
    rbv[:n_read] = keyed(batch["reads"], False)
    rev[:n_read] = keyed(batch["reads"], True)
    rtv[:n_read] = np.repeat(np.arange(b, dtype=np.int32), READS_PER_TXN)

    wbv = np.full((Wn, W), 0xFFFFFFFF, dtype=np.uint32)
    wev = np.full((Wn, W), 0xFFFFFFFF, dtype=np.uint32)
    wtv = np.full(Wn, -1, dtype=np.int32)
    wbv[:n_write] = keyed(batch["writes"], False)
    wev[:n_write] = keyed(batch["writes"], True)
    wtv[:n_write] = np.arange(b, dtype=np.int32)

    Bp = bucket(b)
    snap = np.zeros(Bp, dtype=np.int32)
    snap[:b] = batch["snaps"]
    active = np.zeros(Bp, dtype=bool)
    active[:b] = True
    return rbv, rev, rtv, wbv, wev, wtv, snap, active


def pool_to_words(pool):
    """uint8[P, 16] -> uint32[P, words+1] in the keys.py lane layout."""
    from foundationdb_tpu import keys as keymod

    return keymod.encode_fixed(pool, MAX_KEY_BYTES)


# ---------------- native packing (byte stream + offsets) --------------------


def native_pack(pool, batch):
    """C-ABI arrays: per txn, reads (b,e)* then write (b,e); e = k+\\x00."""
    b = TXNS_PER_BATCH
    keys_per_txn = 2 * (READS_PER_TXN + 1)
    lens = np.tile(
        np.array([KEY_BYTES, KEY_BYTES + 1] * (READS_PER_TXN + 1), dtype=np.int64),
        b,
    )
    offsets = np.zeros(b * keys_per_txn + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    buf = np.zeros(offsets[-1], dtype=np.uint8)
    # txn t occupies a fixed-size slab; fill via strided views
    slab = KEY_BYTES * keys_per_txn + (READS_PER_TXN + 1)  # ends carry +1 byte
    view = buf.reshape(b, slab)
    pos = 0
    for r in range(READS_PER_TXN):
        k = pool[batch["reads"][:, r]]
        view[:, pos : pos + KEY_BYTES] = k
        pos += KEY_BYTES
        view[:, pos : pos + KEY_BYTES] = k
        pos += KEY_BYTES + 1  # trailing \x00 already zero
    k = pool[batch["writes"]]
    view[:, pos : pos + KEY_BYTES] = k
    pos += KEY_BYTES
    view[:, pos : pos + KEY_BYTES] = k
    return (
        batch["snaps"],
        np.full(b, READS_PER_TXN, dtype=np.int32),
        np.ones(b, dtype=np.int32),
        buf,
        offsets,
    )


def _bucket(n: int, lo: int = 16) -> int:
    """Power-of-two rounding (mirror of conflict.device._bucket, inlined so
    the native baseline never has to import JAX)."""
    b = lo
    while b < n:
        b *= 2
    return b


_PROBE_SRC = """
import time, sys
t0 = time.time()
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.arange(64, dtype=jnp.int32)
int((x * x).sum().block_until_ready())  # round-trip through the device
print(f"PROBE_OK {jax.default_backend()} {time.time() - t0:.1f}s")
"""


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: re-runs of the bench (and the
    autotune, when enabled) skip every compile they have seen before —
    compile time is exactly what a flaky device tunnel punishes most."""
    import jax

    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        print(f"[bench] compile cache unavailable: {e!r}", file=sys.stderr)


_BENCH_STATE_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".bench_state"
)
_PROBE_CACHE = os.path.join(_BENCH_STATE_DIR, "probe.json")
_PROBE_LOG = os.path.join(_BENCH_STATE_DIR, "probe.log")


def _probe_log(cls: str, detail: str, attempt: int, n_attempts: int,
               budget: float, dt: float) -> None:
    """Append one classified probe outcome to .bench_state/probe.log — the
    forensic trail the ISSUE's verdict rounds were missing (rc=124 with no
    failure class).  Classes share the DeviceSupervisor vocabulary
    (conflict/supervisor.py classify_failure): ok | hang | no_device |
    compile_fail | lost | error."""
    try:
        os.makedirs(_BENCH_STATE_DIR, exist_ok=True)
        with open(_PROBE_LOG, "a") as f:
            f.write(
                f"{time.strftime('%Y-%m-%dT%H:%M:%S')} "
                f"attempt={attempt}/{n_attempts} budget={budget:.0f}s "
                f"dt={dt:.1f}s class={cls} detail={detail[:300]}\n"
            )
    except Exception as e:  # noqa: BLE001 — the log is forensics only
        print(f"[bench] probe log write failed: {e!r}", file=sys.stderr)


def _classify_probe(timed_out: bool, rc: int | None, text: str) -> str:
    """Failure class of one probe attempt — the supervisor's vocabulary."""
    from foundationdb_tpu.conflict.supervisor import classify_failure

    if timed_out:
        return "hang"
    cls = classify_failure(RuntimeError(text))
    if cls == "error" and rc not in (0, None):
        # a dead probe subprocess with no recognizable backend error text
        # is still most usefully binned as "no device answered"
        return "no_device"
    return cls


def _probe_cache_read() -> dict | None:
    try:
        with open(_PROBE_CACHE) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001 — missing/corrupt cache == no cache
        return None


def _probe_cache_write(ok: bool, detail: str) -> None:
    try:
        os.makedirs(_BENCH_STATE_DIR, exist_ok=True)
        with open(_PROBE_CACHE, "w") as f:
            json.dump(
                {"ok": ok, "detail": detail[:300], "ts": time.time()}, f
            )
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        print(f"[bench] probe cache write failed: {e!r}", file=sys.stderr)


def _probe_budgets(cache: dict | None, env=None) -> list[float]:
    """Per-attempt probe budgets — a PURE function so the total probe
    bound is testable (tests/test_bench_probe.py pins it).

    Every budget is CLAMPED to the supervisor's watchdog knob
    (DEVICE_WATCHDOG_S): BENCH_INIT_TIMEOUT may only lower it.  BENCH_r05
    recorded two consecutive ~180 s probe "hangs" despite PR 4's
    documented <60 s worst case — driver-supplied env overrides must
    never be able to reopen that hole.  A cached failure keeps exactly
    ONE short attempt."""
    from foundationdb_tpu.runtime.knobs import CoreKnobs

    env = os.environ if env is None else env
    watchdog = CoreKnobs().DEVICE_WATCHDOG_S
    try:
        retry_s = float(env.get("BENCH_INIT_TIMEOUT", str(watchdog)))
    except ValueError:
        retry_s = watchdog
    retry_s = min(retry_s, watchdog)
    try:
        fast_s = float(env.get("BENCH_PROBE_FAST_S", "20"))
    except ValueError:
        fast_s = 20.0
    fast_s = min(fast_s, retry_s)
    if cache is not None and not cache.get("ok", False):
        return [fast_s]
    return [fast_s, retry_s]


def _run_probe(budget: float) -> tuple[bool, bool, int | None, str]:
    """One probe attempt in its own PROCESS GROUP, hard-bounded by
    `budget` wall seconds.  Returns (ok, timed_out, rc, detail).

    The BENCH_r05 regression: `subprocess.run(capture_output=True,
    timeout=...)` kills only the direct child on timeout, then BLOCKS
    reading its pipes until every grandchild holding them exits — a wedged
    PJRT helper turned a 20 s budget into the driver's 180 s bound, twice.
    Killing the whole process group closes the pipes inside the budget."""
    import signal
    import subprocess

    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_SRC],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,  # own group: killpg reaps grandchildren too
    )
    try:
        out, err = proc.communicate(timeout=budget)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (OSError, AttributeError):
            proc.kill()
        try:  # group is dead: the pipes close promptly
            proc.communicate(timeout=5)
        except Exception:  # noqa: BLE001 — abandon the fds, never block
            for f in (proc.stdout, proc.stderr):
                if f is not None:
                    f.close()
        return False, True, None, f"probe hung > {budget}s (killed by watchdog)"
    rc = proc.returncode
    ok = rc == 0 and "PROBE_OK" in out
    text = (out + err).strip()
    detail = text.splitlines()[-1][:300] if text else f"rc={rc}"
    return ok, False, rc, detail


def _init_backend() -> dict:
    """Initialize the JAX backend defensively.

    The axon TPU tunnel in this environment can hang for minutes or die
    with Unavailable; a bench that crashes before printing ANY number is
    worthless (round-1 lesson: BENCH_r01 was rc=1 with no output), and a
    bench that burns minutes of probe timeout on EVERY run while the
    tunnel is down wastes most of the round budget re-measuring a
    known-dead link (round-4/5/6 lesson: BENCH_r04/r05).  So:

      * the last probe outcome is cached in .bench_state/probe.json, and
        the failure cache is written after EVERY failed attempt — a run
        the driver kills mid-probe still fast-fails the next run;
      * the first probe is SHORT (~20 s — a live tunnel answers the 64-int
        round trip well inside that);
      * at most one retry follows, clamped to the supervisor's watchdog
        knob (DEVICE_WATCHDOG_S; BENCH_INIT_TIMEOUT may only lower it —
        _probe_budgets), skipped entirely when the cache already says the
        tunnel was down OR the first attempt classified as a hang (a
        tunnel that ignored 20 s does not answer a 30 s retry);
      * probes run in their own PROCESS GROUP and are group-killed on
        timeout (_run_probe) — a wedged PJRT grandchild holding our pipes
        can no longer stretch a 20 s budget to the driver's bound;
      * every attempt's outcome is CLASSIFIED (hang / no_device /
        compile_fail / lost — conflict/supervisor.py classify_failure) and
        appended to .bench_state/probe.log, so a dead round leaves a
        forensic trail instead of a bare rc=124.

    Worst-case probing is ~20 + 30 s < 60 s (test-pinned), after which
    main() emits the native-CPU metric line (already measured before
    probing started).  A hung in-process PJRT init cannot be retried — the
    C++ layer holds global state — so probes run in a SUBPROCESS; only
    after one succeeds does the in-process init run (on a daemon thread
    with a timeout, in case the tunnel dies in the gap)."""
    import threading
    import traceback

    cache = _probe_cache_read()
    budgets = _probe_budgets(cache)
    if len(budgets) == 1:
        print(
            f"[bench] probe cache: tunnel was down last run "
            f"({(cache or {}).get('detail', '?')}); one short probe only",
            file=sys.stderr,
        )

    result: dict = {}
    for attempt, budget in enumerate(budgets):
        t0 = time.perf_counter()
        ok, timed_out, rc, detail = _run_probe(budget)
        dt = time.perf_counter() - t0
        if ok:
            print(f"[bench] probe OK in {dt:.1f}s: {detail}", file=sys.stderr)
            _probe_cache_write(True, detail)
            _probe_log("ok", detail, attempt + 1, len(budgets), budget, dt)
            break
        # classify on the LAST output line (the exception message), not the
        # whole stdout+stderr — incidental runtime chatter ("compilation
        # cache", "connection" info lines) must not misclassify the failure
        cls = _classify_probe(timed_out, rc, detail)
        result["error"] = f"[{cls}] {detail}"
        result["failure_class"] = cls
        # cache the failure NOW: a driver-killed run must not cost the next
        # run a full budget re-discovering a dead tunnel
        _probe_cache_write(False, result["error"])
        _probe_log(cls, detail, attempt + 1, len(budgets), budget, dt)
        print(
            f"[bench] probe attempt {attempt + 1}/{len(budgets)} failed "
            f"after {dt:.1f}s [{cls}]: {detail}",
            file=sys.stderr,
        )
        if cls == "hang":
            # a hung tunnel ignored this whole budget; the retry would
            # spend DEVICE_WATCHDOG_S more learning nothing
            break
    if not ok:
        return result

    # tunnel answers: init in-process (still guarded — it can die in the gap)
    state: dict = {}

    def target() -> None:
        try:
            _enable_compile_cache()
            import jax

            state["devices"] = jax.devices()
            state["backend"] = jax.default_backend()
        except Exception:  # noqa: BLE001 — reported as data
            state["error"] = traceback.format_exc(limit=3)

    t = threading.Thread(target=target, daemon=True)
    t.start()
    # the probe JUST verified the tunnel; a subsequent in-process hang
    # means it died in the gap, and waiting long again only delays the
    # native-number fallback
    join_s = float(os.environ.get("BENCH_INIT_JOIN_S", "120"))
    t.join(join_s)
    if t.is_alive():
        detail = f"in-process init hung > {join_s}s after probe OK"
        result["error"] = f"[hang] {detail}"
        result["failure_class"] = "hang"
        _probe_log("hang", detail, 1, 1, join_s, join_s)
        return result
    if "backend" in state:
        return state
    detail = state.get("error", "unknown init failure")
    cls = _classify_probe(False, None, detail)
    result["error"] = f"[{cls}] {detail}"
    result["failure_class"] = cls
    _probe_log(cls, detail, 1, 1, join_s, 0.0)
    return result


def _emit(metric: str, value: float, vs_baseline: float, error: str | None = None,
          kernel: dict | None = None, commit_wire: dict | None = None,
          metrics_series: dict | None = None,
          page_cache: dict | None = None) -> None:
    doc = {
        "metric": metric,
        "value": round(value, 1),
        "unit": "checks/s",
        "vs_baseline": round(vs_baseline, 3),
    }
    if error is not None:
        doc["error"] = error
    if page_cache is not None:
        # storage read-path trajectory (storage/pagecache.py): cold/warm
        # range-scan pread counts through the ssd engine with the file-
        # level page cache on vs off — the host-read-path counterpart of
        # the commit_wire block
        doc["page_cache"] = page_cache
    if kernel is not None:
        # kernel profiling counters (conflict/api.py KernelStats): the perf
        # trajectory future rounds regress against — padding occupancy,
        # bucket-induced recompiles, per-batch resolve-time percentiles
        doc["kernel"] = kernel
    if commit_wire is not None:
        # commit-plane wire trajectory (docs/WIRE.md): codec encode/decode
        # wall + bytes for a bench-class resolver batch and TLog push,
        # speedup vs protocol-4 pickle, and the transport coalescing factor
        doc["commit_wire"] = commit_wire
    if metrics_series is not None:
        # per-role *Metrics time-series from a fixed sim commit workload
        # (docs/OBSERVABILITY.md "Distributed tracing"): resolver-metrics
        # samples over the run, not just an end-of-run snapshot
        doc["metrics_series"] = metrics_series
    print(json.dumps(doc))


def _page_cache_probe(keys: int = 4000) -> dict | None:
    """Measure the ssd engine's read path with the file-level page cache
    on vs off (storage/pagecache.py): build one B-tree, then run a COLD
    full-range scan (fresh recover, pool cleared — every parsed page
    gone) followed by the same scan warm, counting the disk preads each
    needed.  Simulated reads are instant, so the pread COUNT is the
    honest measurable (the cold-range-read wall's proxy); the counters
    also carry hit/miss/read-ahead attribution.  Pure CPU + sim clock —
    safe on device and no-device runs alike, deterministic by seed."""
    try:
        from foundationdb_tpu.runtime.core import DeterministicRandom, EventLoop
        from foundationdb_tpu.storage.btree import BTreeKeyValueStore
        from foundationdb_tpu.storage.files import SimFilesystem
        from foundationdb_tpu.storage.pagecache import PageCachePool

        def scan_ops(fs, store) -> int:
            ops0 = sum(fs.disk(p).reads for p in ("pc.a", "pc.b", "pc.hdr"))
            rows = store.range_read(b"", b"\xff" * 8, 1 << 30)
            assert len(rows) == keys
            return sum(fs.disk(p).reads for p in ("pc.a", "pc.b", "pc.hdr")) - ops0

        def one(cache_on: bool) -> dict:
            loop = EventLoop()
            fs = SimFilesystem(loop, DeterministicRandom(5))
            if cache_on:
                fs.page_pool = PageCachePool(4096, 1 << 20, 8)
            store = BTreeKeyValueStore(fs, "pc", None, cache_bytes=1 << 14)

            async def build():
                for i in range(keys):
                    store.set(b"k%06d" % i, b"v" * 64)
                await store.commit({})

            loop.run_until(loop.spawn(build()), 1e12)
            # a fresh process lifetime: parsed cache empty, pool cold
            if fs.page_pool is not None:
                fs.page_pool.clear()
            s2 = BTreeKeyValueStore.recover(fs, "pc", None,
                                            cache_bytes=1 << 14)
            cold = scan_ops(fs, s2)
            warm = scan_ops(fs, s2)
            out = {"cold_scan_preads": cold, "warm_scan_preads": warm}
            out.update(s2.page_cache_stats())
            return out

        on, off = one(True), one(False)
        return {
            "keys": keys,
            "cache_on": on,
            "cache_off": off,
            "cold_preads_saved": off["cold_scan_preads"] - on["cold_scan_preads"],
            "warm_preads_saved": off["warm_scan_preads"] - on["warm_scan_preads"],
        }
    except Exception as e:  # noqa: BLE001 — the block is additive data
        print(f"[bench] page cache probe failed: {e!r}", file=sys.stderr)
        return None


def _metrics_series_probe(n_commits: int = 200) -> dict | None:
    """The periodic-metrics time-series BENCH artifact: a fixed sim commit
    workload with a fast METRICS_INTERVAL, returning every ResolverMetrics
    emission — rates per interval, the conflict backend's phase-wall
    deltas, and the MVCC version floor over (simulated) time.  CPU-only
    (oracle backend on the sim fabric), so it runs on device and
    no-device rounds alike."""
    try:
        from foundationdb_tpu.cluster import SimCluster
        from foundationdb_tpu.runtime.knobs import CoreKnobs

        knobs = CoreKnobs()
        knobs.METRICS_INTERVAL = 0.25
        c = SimCluster(seed=5, n_resolvers=2, n_tlogs=1, knobs=knobs)
        db = c.database()

        async def drive():
            for i in range(n_commits):
                tr = db.create_transaction()
                tr.set(b"m%04d" % (i % 97), b"v%04d" % i)
                await tr.commit()

        c.run_until(c.loop.spawn(drive()), 120.0)
        series = [
            {
                "t": round(e["Time"], 4),
                "instance": e["Instance"],  # two resolvers interleave here
                "txns_per_sec": round(e["TxnsPerSec"], 1),
                "conflicts_per_sec": round(e["ConflictsPerSec"], 1),
                "version": e["Version"],
                "kernel_resolve_ms_delta": round(e["KernelResolveMsDelta"], 3),
            }
            for e in c.trace.find("ResolverMetrics")
        ]
        c.stop()
        if not series:
            return None
        return {
            "interval_s": 0.25,
            "workload_commits": n_commits,
            "ResolverMetrics": series,
        }
    except Exception as e:  # noqa: BLE001 — the series is additive data
        print(f"[bench] metrics series probe failed: {e!r}", file=sys.stderr)
        return None


def _commit_wire_probe(n_txns: int = 4096, reps: int = 5) -> dict | None:
    """Measure the commit-plane wire path at bench shapes (docs/WIRE.md):

      * codec encode/decode of ONE bench-class ResolveTransactionBatchRequest
        (n_txns txns × 2 point reads + 1 point write, 16-byte keys) and the
        matching TLogCommitRequest, best-of-`reps`, vs protocol-4 pickle;
      * a real loopback-TCP burst through two RealNetworks to read the
        transport's frames-per-flush coalescing factor.

    Pure CPU + loopback sockets — safe on device and no-device runs alike."""
    import pickle

    from foundationdb_tpu.conflict.api import TxInfo
    from foundationdb_tpu.roles.types import (
        Mutation,
        MutationType,
        ResolveTransactionBatchRequest,
        TLogCommitRequest,
    )
    from foundationdb_tpu.runtime.metrics import WireStats
    from foundationdb_tpu.runtime.serialize import decode_payload, encode_payload

    rng = np.random.default_rng(SEED + 7)
    pool = rng.integers(0, 256, size=(1 << 14, KEY_BYTES), dtype=np.uint8)
    keys = [bytes(pool[i]) for i in range(pool.shape[0])]
    idx = rng.integers(0, len(keys), size=(n_txns, 3))
    req = ResolveTransactionBatchRequest(9, 10, [
        TxInfo(
            5,
            [(keys[i], keys[i] + b"\x00"), (keys[j], keys[j] + b"\x00")],
            [(keys[k], keys[k] + b"\x00")],
        )
        for i, j, k in idx
    ])
    push = TLogCommitRequest(9, 10, {
        f"ss-{t}": [
            Mutation(MutationType.SET_VALUE, keys[i], b"v" * 16)
            for i in rng.integers(0, len(keys), size=n_txns // 4)
        ]
        for t in range(4)
    }, known_committed=8)

    def best(f):
        out = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            out.append(time.perf_counter() - t0)
        return min(out)

    try:
        stats = WireStats()
        blobs = [(m, encode_payload(m, stats=stats)) for m in (req, push)]
        out = {"pickle_fallbacks": stats.pickle_fallbacks}
        enc_s = dec_s = pk_enc_s = pk_dec_s = 0.0
        nbytes = pk_bytes = 0
        for msg, blob in blobs:
            pk = pickle.dumps(msg, protocol=4)
            enc_s += best(lambda m=msg: encode_payload(m))
            dec_s += best(lambda b=blob: decode_payload(b))
            pk_enc_s += best(lambda m=msg: pickle.dumps(m, protocol=4))
            pk_dec_s += best(lambda b=pk: pickle.loads(b))
            nbytes += len(blob)
            pk_bytes += len(pk)
        out.update(
            encode_ms=round(enc_s * 1e3, 3),
            decode_ms=round(dec_s * 1e3, 3),
            bytes=nbytes,
            pickle_bytes=pk_bytes,
            vs_pickle_encode=round(pk_enc_s / enc_s, 2) if enc_s else 0.0,
            vs_pickle_decode=round(pk_dec_s / dec_s, 2) if dec_s else 0.0,
            txns=n_txns,
        )
        out.update(_wire_flush_probe() or {})
        return out
    except Exception as e:  # noqa: BLE001 — the wire probe is additive data
        print(f"[bench] commit_wire probe failed: {e!r}", file=sys.stderr)
        return None


def _wire_flush_probe(n_frames: int = 64) -> dict | None:
    """Send a burst of small resolver batches across two in-process
    RealNetworks (real loopback TCP) and report the sender's coalescing
    factor — frames per flushed write."""
    from foundationdb_tpu.conflict.api import TxInfo
    from foundationdb_tpu.roles.types import ResolveTransactionBatchRequest
    from foundationdb_tpu.rpc.stream import RequestStream, RequestStreamRef
    from foundationdb_tpu.rpc.transport import RealNetwork
    from foundationdb_tpu.runtime.core import EventLoop

    loop = EventLoop()
    a = RealNetwork(loop, name="bench-a")
    b = RealNetwork(loop, name="bench-b")
    try:
        rs = RequestStream(b.process, "wlt:sink")
        got = []

        async def sink():
            while True:
                got.append(await rs.next())

        loop.spawn(sink())
        ref = RequestStreamRef(a, a.process, rs.endpoint)
        msg = ResolveTransactionBatchRequest(
            1, 2, [TxInfo(1, [(b"k%04d" % i, b"k%04d\x00" % i)], []) for i in range(32)]
        )
        for _ in range(n_frames):
            ref.send(msg)  # one-way: the burst queues before any flush

        async def waiter():
            while len(got) < n_frames:
                await loop.delay(0.001)

        from foundationdb_tpu.rpc.transport import WallDriver
        from foundationdb_tpu.runtime.core import TimedOut

        try:
            WallDriver(loop, [a.pump, b.pump]).run_until(
                loop.spawn(waiter()), wall_timeout=10.0
            )
        except TimedOut:
            return None
        snap = a.wire.snapshot()
        return {
            "frames_per_flush": round(snap["frames_per_flush"], 1),
            "flushes": snap["flushes"],
        }
    finally:
        a.close()
        b.close()


def _resolver_e2e(n_batches: int, n_txns: int, cap: int, *, stage=None,
                  warm_batches: int = 2, seed: int = SEED + 1):
    """Steady-state TxInfo→verdict throughput through the PIPELINED input
    path (docs/KERNEL.md "Input pipeline") — the resolver-e2e number, not
    the bare kernel: a PipelinedPacker packs (and, with `stage`, host→device
    stages) batch N+1 on a background thread while the device executes batch
    N's sync=False dispatch; deferred validity drains once at the end.

    Returns (checks_per_sec, kernel_stats_snapshot).  The snapshot's
    encode_ms/pad_ms/h2d_ms are the input-pipeline phase split for this
    stream.  Keys are 15 bytes so the [k, k+\\x00) end keys fit the bench's
    16-byte lanes through the TxInfo path."""
    import jax

    from foundationdb_tpu.conflict.api import TxInfo
    from foundationdb_tpu.conflict.device import DeviceConflictSet, pack_batch
    from foundationdb_tpu.conflict.pipeline import PackArena, PipelinedPacker

    rng = np.random.default_rng(seed)
    dev = DeviceConflictSet(max_key_bytes=MAX_KEY_BYTES, capacity=cap)
    pool = rng.integers(0, 256, size=(1 << 16, MAX_KEY_BYTES - 1), dtype=np.uint8)
    keys = [bytes(pool[i]) for i in range(pool.shape[0])]

    def mk_batch(version):
        idx = rng.integers(0, len(keys), size=(n_txns, 3))
        return version, [
            TxInfo(
                max(version - 2, 0),
                [(keys[i], keys[i] + b"\x00"), (keys[j], keys[j] + b"\x00")],
                [(keys[k], keys[k] + b"\x00")],
            )
            for i, j, k in idx
        ]

    batches = [mk_batch(v) for v in range(1, warm_batches + n_batches + 1)]
    for v, txns in batches[:warm_batches]:  # compile + state warm, untimed
        dev.resolve_batch(v, txns)
    # kernel k+1 consumes kernel k's state, so dispatches execute in order;
    # a depth-6 arena + depth-2 packer backpressure + a 2-deep dispatch
    # window keeps every slot untouched until its kernel has completed
    arena = PackArena(depth=6)
    packer = PipelinedPacker(
        lambda item: pack_batch(
            item[1], dev.oldest_version, dev._offset, dev._max_key_bytes,
            arena=arena, stats=dev.stats, offset_array=dev._offset_array,
        )[:8],
        depth=2, stage=stage, stats=dev.stats,
    )
    timed = batches[warm_batches:]
    try:
        t0 = time.perf_counter()
        verdicts: list = []
        submitted = 0
        for i, (v, _txns) in enumerate(timed):
            while submitted < len(timed) and submitted <= i + 1:
                packer.submit(timed[submitted])
                submitted += 1
            packed = packer.get()
            if i >= 2:
                jax.block_until_ready(verdicts[i - 2])
            verdicts.append(dev.resolve_arrays(v, *packed, sync=False))
        jax.block_until_ready(verdicts[-1])
        dev.check_pipelined()
        dt = time.perf_counter() - t0
    finally:
        packer.close()
    checks = n_batches * n_txns * (READS_PER_TXN + 1)
    return checks / dt, dev.kernel_stats()


def _cpu_phase_main() -> None:
    """`bench.py --cpu-phase`: a small JAX-CPU kernel pass that prints the
    per-phase breakdown as one JSON line — run in a SUBPROCESS by the
    no-device path so the kernel's phase costs land in BENCH json even when
    the tunnel is down (small shapes: this is a phase-shape sample, not a
    throughput number).  The drive loop is shared with
    `profile_kernel.py --phase` so the two reports cannot desynchronize."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from profile_kernel import drive_phase_stream

    _dev, snap = drive_phase_stream(
        n_batches=10, n_txns=256, cap=1 << 14, run_slots=4, seed=SEED,
    )
    # resolver-e2e pass at small shapes: the pipelined TxInfo→verdict rate
    # plus the encode/pad/h2d input-pipeline split, so the no-device BENCH
    # json still carries the input-pipeline trajectory
    e2e_rate, e2e = _resolver_e2e(8, 256, cap=1 << 14)
    print(json.dumps({
        "phase": {k: round(v, 2) for k, v in snap["phase"].items()},
        "phase_backend": "cpu",
        "runs_appended": snap["runs_appended"],
        "full_merges": snap["full_merges"],
        "compactions": snap["compactions"],
        "batches": snap["batches"],
        "encode_ms": round(e2e["encode_ms"], 2),
        "pad_ms": round(e2e["pad_ms"], 2),
        "h2d_ms": round(e2e["h2d_ms"], 2),
        "resolver_e2e_checks_per_sec": round(e2e_rate, 1),
        "commit_wire": _commit_wire_probe(),
        "page_cache": _page_cache_probe(),
    }))


def _phase_profile_probe(*, cpu: bool) -> dict | None:
    """Run phase_timings.py --json in a subprocess and return the parsed
    phase report (the kernel.phase_profile block — per-phase walls, LSM
    amortization and the merge-impl shootout), or None.

    BENCH_PHASE_PROFILE: "small" (default) runs reduced shapes so the
    probe fits the budget; "full" runs the probe.log-grade shapes (the
    BENCH_r* artifact path); "0" disables.  Budgeted by
    BENCH_PHASE_PROFILE_TIMEOUT (seconds)."""
    import subprocess

    mode = os.environ.get("BENCH_PHASE_PROFILE", "small")
    if mode == "0":
        return None
    budget = float(os.environ.get("BENCH_PHASE_PROFILE_TIMEOUT", "900"))
    args = [
        sys.executable,
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "phase_timings.py"),
        "--json", "-",
    ]
    if mode != "full":
        args.append("--small")
    env = {**os.environ}
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            args, capture_output=True, text=True, timeout=budget, env=env
        )
        for line in reversed(proc.stdout.strip().splitlines()):
            if line.startswith("PHASE_PROFILE "):
                return json.loads(line[len("PHASE_PROFILE "):])
        print(
            f"[bench] phase profile pass produced no report "
            f"(rc={proc.returncode})",
            file=sys.stderr,
        )
    except Exception as e:  # noqa: BLE001 — the profile is additive data
        print(f"[bench] phase profile pass failed: {e!r}", file=sys.stderr)
    return None


def _cpu_phase_probe() -> dict | None:
    """Run _cpu_phase_main in a subprocess (budgeted, opt-out with
    BENCH_CPU_PHASE=0) and return its parsed JSON, or None."""
    import subprocess

    if os.environ.get("BENCH_CPU_PHASE", "1") == "0":
        return None
    budget = float(os.environ.get("BENCH_CPU_PHASE_TIMEOUT", "180"))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-phase"],
            capture_output=True, text=True, timeout=budget,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        line = proc.stdout.strip().splitlines()[-1]
        return json.loads(line)
    except Exception as e:  # noqa: BLE001 — the phase sample is optional data
        print(f"[bench] cpu phase pass failed: {e!r}", file=sys.stderr)
        return None


def main() -> None:
    if "--cpu-phase" in sys.argv:
        _cpu_phase_main()
        return
    from foundationdb_tpu.conflict.native import NativeConflictSet

    rng = np.random.default_rng(SEED)
    pool = gen_pool(rng)
    pool_words = pool_to_words(pool)

    versions = iter(range(1, 10_000))
    prefill = [gen_batch(rng, pool, next(versions)) for _ in range(PREFILL_BATCHES)]
    timed = [gen_batch(rng, pool, next(versions)) for _ in range(TIMED_BATCHES)]
    # post-run batches: resolved SYNC one-by-one after the headline timing to
    # put per-batch resolve-time percentiles into the kernel counters
    post = [gen_batch(rng, pool, next(versions)) for _ in range(6)]

    total_checks = TIMED_BATCHES * TXNS_PER_BATCH * (READS_PER_TXN + 1)

    # ---------------- native baseline (no JAX required) ----------------
    nat = NativeConflictSet()
    for b in prefill:
        nat.resolve_packed(b["version"], *native_pack(pool, b))
    packed_nat = [(b["version"], native_pack(pool, b)) for b in timed]
    t0 = time.perf_counter()
    nat_verdicts = [nat.resolve_packed(v, *args) for v, args in packed_nat]
    native_s = time.perf_counter() - t0
    live_ranges = nat.node_count // 2
    print(
        f"[bench] native: {native_s * 1e3:.1f} ms for {total_checks} checks "
        f"({total_checks / native_s / 1e6:.2f} M checks/s), "
        f"~{live_ranges} live ranges at timing start",
        file=sys.stderr,
    )
    nat.close()
    native_rate = total_checks / native_s

    # ---------------- backend init (resilient) ----------------
    # worst case time-to-JSON: one short probe (+ one ~35s retry when the
    # cache doesn't already record a dead tunnel) + a 120s in-process init
    # join — well inside any plausible driver budget; the native line hits
    # stdout if no device ever materializes
    init = _init_backend()
    if "backend" not in init:
        # no device available: the native number is still a result — emit it
        # with an error tag so the round records data instead of an rc=1.
        # The kernel's phase breakdown still lands in BENCH json via a
        # small JAX-CPU pass in a subprocess (the wedged-PJRT state of THIS
        # process cannot be trusted to run jax).
        print(f"[bench] NO DEVICE BACKEND: {init.get('error')}", file=sys.stderr)
        kern = _cpu_phase_probe()
        # the cpu-phase subprocess already measured the wire + page-cache
        # probes under a clean JAX-CPU env; lift them to the top-level
        # block (measure in-process only if that pass failed)
        wire = (kern or {}).pop("commit_wire", None) or _commit_wire_probe()
        pcache = (kern or {}).pop("page_cache", None) or _page_cache_probe()
        profile = _phase_profile_probe(cpu=True)
        if profile is not None:
            kern = kern or {}
            kern["phase_profile"] = profile
        _emit(
            "occ_conflict_checks_per_sec_native_cpu_64k_live_ranges",
            native_rate,
            0.0,
            error=f"device backend unavailable: {init.get('error', '?')[:500]}",
            kernel=kern,
            commit_wire=wire,
            metrics_series=_metrics_series_probe(),
            page_cache=pcache,
        )
        os._exit(0)  # daemon init thread may be wedged in PJRT; exit hard
    backend = init["backend"]
    try:
        _device_run(backend, prefill, timed, post, pool_words, nat_verdicts,
                    total_checks, native_s, native_rate)
    except SystemExit:
        raise
    except Exception:  # noqa: BLE001 — a device-side crash still reports data
        import traceback

        tb = traceback.format_exc(limit=5)
        print(f"[bench] DEVICE RUN FAILED:\n{tb}", file=sys.stderr)
        _emit(
            "occ_conflict_checks_per_sec_native_cpu_64k_live_ranges",
            native_rate,
            0.0,
            error=f"device run failed: {tb.splitlines()[-1][:300]}",
        )


# Best-known configuration on TPU, committed so the default timed path needs
# no exploratory compiles at all (VERDICT r4 #1a): the INCREMENTAL layout
# (run append + deferred fold + the sort-scan probe) removes the measured
# dominator — the per-batch committed-write merge — entirely; the LSM main
# level keeps its cached sparse table, the bucketed search amortizes batched
# row gathers (r3/r4 measurements).  Override with FDBTPU_SEARCH_IMPL /
# FDBTPU_MERGE_IMPL / FDBTPU_LSM / FDBTPU_INCREMENTAL / FDBTPU_PALLAS, or
# set BENCH_AUTOTUNE=1 to re-measure all combos on the live device.
# Tuple: (search_impl, merge_impl, lsm, incremental).  merge="scatter" per
# the r05-session shootout (recent 2^17: 130.9->55.3 ms, main 2^19:
# 671.3->179.2 ms over the sort fold; re-confirmed post-adoption in
# .bench_state/probe.log) — the fold recipe now also drives the deferred
# k-way compaction and the run-append union, so the dimension matters on
# the incremental path too.
BEST_KNOWN = ("bucket", "scatter", True, True)


def _autotune(backend, prefill, timed, pool_words) -> tuple[str, str, bool, bool]:
    """Pick the fastest (search_impl, merge_impl, lsm) combo ON THIS DEVICE.

    XLA's lowering quality for scatters/gathers vs sorts differs wildly
    across backends (TPU scatters serialize per row; sorts are tuned
    networks — and the CPU backend inverts that), so the kernel ships both
    implementations of its two heavy phases and the bench can measure which
    combination wins before taking the headline number.  OPT-IN with
    BENCH_AUTOTUNE=1; the default path uses the committed BEST_KNOWN combo
    (one compile, flaky-tunnel insurance) with env overrides honored."""
    import jax

    from foundationdb_tpu.conflict.device import DeviceConflictSet

    if os.environ.get("BENCH_AUTOTUNE", "0") != "1":
        from foundationdb_tpu.conflict.device import impl_from_env

        si = impl_from_env("search", override=os.environ.get(
            "FDBTPU_SEARCH_IMPL", BEST_KNOWN[0]))
        mi = impl_from_env("merge", override=os.environ.get(
            "FDBTPU_MERGE_IMPL", BEST_KNOWN[1]))
        lsm = os.environ.get("FDBTPU_LSM", "1" if BEST_KNOWN[2] else "") == "1"
        inc = os.environ.get(
            "FDBTPU_INCREMENTAL", "1" if BEST_KNOWN[3] else "0"
        ) == "1"
        print(
            f"[bench] autotune off (best-known): search={si} merge={mi} "
            f"lsm={int(lsm)} incremental={int(inc)}",
            file=sys.stderr,
        )
        return si, mi, lsm, inc

    # (search_impl, merge_impl, lsm): lsm=True pays a rare O(CAP) compaction
    # instead of a per-batch full-state merge — the merge phase dominates on
    # TPU (52.8 of ~57ms/batch measured in r4), so it usually wins there.
    # "gather" is the scatter-free/full-sort-free merge (positions from the
    # ONE search's ranks; batched row gathers).  Best-known-first: a
    # time-boxed autotune (flaky tunnel insurance) that stops early still
    # lands on a good configuration.
    combos = [
        ("bucket", "scatter", True, True),  # incremental + scatter folds
        ("bucket", "sort", True, True),     # incremental + sort folds
        ("bucket", "gather", True, True),   # incremental + gather folds
        ("sort", "scatter", True, True),    # exact sort search, incremental
        ("bucket", "scatter", False, True),  # incremental over flat main
        ("bucket", "scatter", True, False),  # legacy per-batch merges below
        ("bucket", "gather", True, False),
        ("bucket", "sort", True, False),
        ("bucket", "sort", False, False),
    ]
    budget_s = float(os.environ.get("BENCH_AUTOTUNE_BUDGET_S", "900"))
    t_start = time.perf_counter()
    results = {}
    for si, mi, lsm, inc in combos:
        if results and time.perf_counter() - t_start > budget_s:
            print("[bench] autotune budget exhausted; using best so far",
                  file=sys.stderr)
            break
        try:
            dev = DeviceConflictSet(
                max_key_bytes=MAX_KEY_BYTES, capacity=CAP,
                search_impl=si, merge_impl=mi,
                lsm=lsm, recent_capacity=REC_CAP,
                incremental=inc, run_slots=8, run_capacity=1 << 14,
            )
            for b in prefill[:2]:
                dev.resolve_arrays(b["version"], *device_pack(pool_words, b, _bucket))
            probes = [
                (b["version"], jax.device_put(device_pack(pool_words, b, _bucket)))
                for b in prefill[2:5]
            ]
            jax.block_until_ready(probes)
            # warm/compile on the first probe, time the remaining two
            dev.resolve_arrays(probes[0][0], *probes[0][1], sync=False)
            dev.check_pipelined()
            t0 = time.perf_counter()
            for v, args in probes[1:]:
                dev.resolve_arrays(v, *args, sync=False)
            dev.check_pipelined()  # scalar fetch = completion barrier
            dt = time.perf_counter() - t0
            results[(si, mi, lsm, inc)] = dt
            print(
                f"[bench] autotune search={si:<6} merge={mi:<7} lsm={int(lsm)} "
                f"inc={int(inc)}: {dt * 1e3 / 2:.1f} ms/batch",
                file=sys.stderr,
            )
        except Exception as e:  # noqa: BLE001 — a combo failing is data
            print(
                f"[bench] autotune {si}/{mi}/lsm={int(lsm)}/inc={int(inc)} "
                f"FAILED: {e!r}",
                file=sys.stderr,
            )
    if not results:
        return "sort", "sort", True, True
    (si, mi, lsm, inc) = min(results, key=results.get)
    print(
        f"[bench] autotune winner: search={si} merge={mi} lsm={int(lsm)} "
        f"inc={int(inc)}",
        file=sys.stderr,
    )
    return si, mi, lsm, inc


def _device_run(backend, prefill, timed, post, pool_words, nat_verdicts,
                total_checks, native_s, native_rate) -> None:
    import jax

    from foundationdb_tpu.conflict.device import DeviceConflictSet

    search_impl, merge_impl, lsm, incremental = _autotune(
        backend, prefill, timed, pool_words
    )

    # ---------------- device ----------------
    dev = DeviceConflictSet(
        max_key_bytes=MAX_KEY_BYTES, capacity=CAP,
        search_impl=search_impl, merge_impl=merge_impl,
        lsm=lsm, recent_capacity=REC_CAP,
        incremental=incremental, run_slots=8, run_capacity=1 << 14,
    )
    for b in prefill:
        dev.resolve_arrays(b["version"], *device_pack(pool_words, b, _bucket))
    if getattr(dev, "_incremental", False):
        # compile the deferred-fold kernel OUTSIDE the timed window and
        # start the timed stream with empty run slots (compactions that
        # fire mid-stream are still timed — the honest amortized cost)
        dev._compact_runs()
    elif lsm:
        # compile the compaction kernel OUTSIDE the timed window and start
        # the timed stream with an empty recent level (compactions that fire
        # mid-stream are still timed — that's the honest amortized cost)
        dev._compact()
    # pre-stage the packed batches on device: in production the resolver
    # sits on the TPU host (PCIe DMA, ~60us for these ~1MB batches); in this
    # dev environment the device is behind a network tunnel, so per-batch
    # uploads would measure the tunnel, not the kernel
    packed_dev = [
        (b["version"], jax.device_put(device_pack(pool_words, b, _bucket)))
        for b in timed
    ]
    jax.block_until_ready(packed_dev)
    # (prefill already compiled the kernel: identical static shapes)
    # pipelined resolves: batch N+1 needs only batch N's device-resident
    # state, so the stream overlaps kernels with the host link; deferred
    # validity checks drain once at the end (resolver double-buffering)
    t0 = time.perf_counter()
    dev_verdicts = [
        dev.resolve_arrays(v, *args, sync=False) for v, args in packed_dev
    ]
    # device executes in dispatch order: the last verdict ready => all done
    jax.block_until_ready(dev_verdicts[-1])
    dev.check_pipelined()
    device_s = time.perf_counter() - t0
    print(
        f"[bench] device[{backend}]: {device_s * 1e3:.1f} ms "
        f"({total_checks / device_s / 1e6:.2f} M checks/s)",
        file=sys.stderr,
    )

    # ---------------- parity ----------------
    mismatches = 0
    for i, (nv, dv) in enumerate(zip(nat_verdicts, dev_verdicts)):
        if not np.array_equal(np.asarray(nv), np.asarray(dv)[: len(nv)]):
            mismatches += 1
            bad = np.nonzero(np.asarray(nv) != np.asarray(dv)[: len(nv)])[0][:5]
            print(f"[bench] PARITY MISMATCH batch {i} txns {bad}", file=sys.stderr)
    if mismatches:
        raise SystemExit(f"abort-set parity FAILED in {mismatches} batches")
    print("[bench] abort-set parity OK", file=sys.stderr)

    # ---------------- kernel counters (observability PR) ----------------
    # a short SYNC pass: each batch's wall time is individually observable
    # (the pipelined headline stream is not), giving honest p50/p99 — and,
    # with phase timing flipped on for just these batches, the per-phase
    # sort/scan/merge split (each phase its own dispatch + barrier; the
    # pipelined headline stream above stayed fused)
    sync_ms = []
    dev._phase_timing = True
    for b in post:
        args = device_pack(pool_words, b, _bucket)
        t0 = time.perf_counter()
        dev.resolve_arrays(b["version"], *args)
        sync_ms.append((time.perf_counter() - t0) * 1e3)
    dev._phase_timing = False
    snap = dev.kernel_stats()
    kernel = {
        "occupancy": round(snap["occupancy"], 4),
        "recompiles": snap["recompiles"],
        "search_fallbacks": snap["search_fallbacks"],
        "compactions": snap["compactions"],
        "node_count": snap["node_count"],
        "abort_rate": round(snap["abort_rate"], 4),
        "resolve_ms_p50": round(float(np.percentile(sync_ms, 50)), 2),
        "resolve_ms_p99": round(float(np.percentile(sync_ms, 99)), 2),
        "pipelined_ms_per_batch": round(device_s * 1e3 / len(timed), 2),
        # incremental-merge proof: every timed batch appends a run
        # (runs_appended) instead of rewriting state (full_merges == 0 on
        # the incremental path), with bounded deferred compactions
        "runs_appended": snap["runs_appended"],
        "full_merges": snap["full_merges"],
        "incremental": bool(getattr(dev, "_incremental", False)),
        "probe_impl": getattr(dev, "_probe_impl", "?"),
        "merge_impl": getattr(dev, "_merge_impl", "?"),
    }
    profile = _phase_profile_probe(cpu=(backend == "cpu"))
    if profile is not None:
        kernel["phase_profile"] = profile
    if getattr(dev, "_incremental", False):
        # only the incremental path honors _phase_timing; a legacy-config
        # run must not report a zeroed split as a measured one
        kernel["phase"] = {k: round(v, 2) for k, v in snap["phase"].items()}
        kernel["phase_backend"] = backend

    # ---------------- resolver e2e (input pipeline) ----------------
    # the steady-state TxInfo→verdict rate through the PIPELINED feeder
    # (PipelinedPacker packs + stages batch N+1 while the device runs N) —
    # the number VERDICT r5 #1 asks for: host wall-time included, not the
    # bare kernel; plus the encode/pad/h2d pack-phase split proving where
    # the host milliseconds went
    try:
        e2e_rate, e2e = _resolver_e2e(
            6, TXNS_PER_BATCH, cap=CAP, stage=jax.device_put
        )
        kernel["resolver_e2e_checks_per_sec"] = round(e2e_rate, 1)
        kernel["encode_ms"] = round(e2e["encode_ms"], 2)
        kernel["pad_ms"] = round(e2e["pad_ms"], 2)
        kernel["h2d_ms"] = round(e2e["h2d_ms"], 2)
    except Exception as e:  # noqa: BLE001 — e2e is additive data
        print(f"[bench] resolver e2e pass failed: {e!r}", file=sys.stderr)
    print(f"[bench] kernel counters: {kernel}", file=sys.stderr)

    _emit(
        f"occ_conflict_checks_per_sec_{backend}_64k_live_ranges",
        total_checks / device_s,
        native_s / device_s,
        kernel=kernel,
        commit_wire=_commit_wire_probe(),
        metrics_series=_metrics_series_probe(),
        page_cache=_page_cache_probe(),
    )


if __name__ == "__main__":
    main()
