"""Bank-transfer workload — money-conservation invariant under contention
(the Inventory/Serializability family of reference workloads)."""

from __future__ import annotations

from .base import Workload
from ..roles.types import NotCommitted, TransactionTooOld
from ..runtime.combinators import wait_all


def _acct(i: int) -> bytes:
    return b"bank/%03d" % i


class BankWorkload(Workload):
    description = "Bank"

    def __init__(self, accounts: int = 10, clients: int = 4,
                 transfers_per_client: int = 20, initial: int = 100):
        self.accounts = accounts
        self.clients = clients
        self.transfers = transfers_per_client
        self.initial = initial
        self.committed = 0

    async def setup(self, cluster, rng) -> None:
        db = cluster.database()

        async def fill(tr):
            for i in range(self.accounts):
                tr.set(_acct(i), str(self.initial).encode())

        await db.run(fill)

    async def start(self, cluster, rng) -> None:
        db = cluster.database()

        async def client(crng):
            for _ in range(self.transfers):
                src = crng.random_int(0, self.accounts)
                dst = crng.random_int(0, self.accounts)
                amt = crng.random_int(1, 20)

                async def xfer(tr, src=src, dst=dst, amt=amt):
                    a = int(await tr.get(_acct(src)))
                    b = int(await tr.get(_acct(dst)))
                    if a < amt or src == dst:
                        return
                    tr.set(_acct(src), str(a - amt).encode())
                    tr.set(_acct(dst), str(b + amt).encode())

                await db.run(xfer)
                self.committed += 1

        await wait_all(
            [cluster.loop.spawn(client(rng.split())) for _ in range(self.clients)]
        )

    async def check(self, cluster, rng) -> bool:
        db = cluster.database()
        rows = await db.run(lambda tr: tr.get_range(b"bank/", b"bank0"))
        total = sum(int(v) for _k, v in rows)
        return len(rows) == self.accounts and total == self.accounts * self.initial

    def metrics(self) -> dict:
        return {"committed": self.committed}

    def restart_state(self) -> dict:
        # money conservation is relative to these: a part 2 declaring a
        # different account count or initial balance would assert the
        # wrong total against the saved disks
        return {"accounts": self.accounts, "initial": self.initial,
                "expected_total": self.accounts * self.initial}
