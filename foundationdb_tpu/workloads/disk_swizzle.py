"""DiskSwizzle workload — cycle every data disk through the resource-
exhaustion fault plane under live traffic (the disk half of the
reference's machine swizzling: AsyncFileNonDurable + SimulatedMachine
model slow, stalled, erroring, and nearly-full disks; this workload
drives each of those states deterministically AND forces the `disk.*`
buggify sites so a campaign's census proves the faults really fired).

Each round walks the commit/storage-plane disks (TLog disk queues,
storage WAL/B-tree files) and applies one fault per disk, rotating
through the classes:

  slow    — degraded mode: fsyncs pay `slowMult`x latency for the round
  stall   — fsyncs hang for `stallSeconds` (crossing IO_TIMEOUT_S
            fail-fasts the process through kill/recovery — that is the
            io_timeout story working, not a failure)
  error   — the next ops on the disk raise injected IOErrors
  enospc  — capacity clamps to just above current usage, so appends hit
            ENOSPC until the round ends

Every fault is cleared at the end of its round, and `check` drives probe
commits until one succeeds — the cluster must come back from every round
of disk abuse with the commit plane intact."""

from __future__ import annotations

from .base import Workload
from ..runtime.core import ActorCancelled

_FAULTS = ("slow", "stall", "error", "enospc")


class DiskSwizzleWorkload(Workload):
    description = "DiskSwizzle"

    def __init__(self, rounds: int = 2, interval: float = 1.0,
                 start_delay: float = 0.5, stall_seconds: float = 0.4,
                 slow_mult: float = 8.0, errors: int = 1,
                 enospc_headroom: int = 256):
        self.rounds = rounds
        self.interval = interval
        self.start_delay = start_delay
        self.stall_seconds = stall_seconds
        self.slow_mult = slow_mult
        self.errors = errors
        self.enospc_headroom = enospc_headroom
        self.faults_applied = 0
        self.probe_commits = 0

    @staticmethod
    def _data_disks(fs) -> list[str]:
        """The commit/storage-plane disks: TLog disk queues and storage
        store files — the surfaces whose exhaustion the roles must
        degrade gracefully under.  Coordinator registers and cluster
        files are deliberately out of scope (their write paths are
        control-plane rare and covered by the kill plane)."""
        return [
            p for p in fs.list()
            if p.startswith(("tlog", "ss", "remote"))
        ]

    async def start(self, cluster, rng) -> None:
        from ..runtime import buggify

        fs = getattr(cluster, "fs", None)
        assert fs is not None, (
            "DiskSwizzle needs a durable cluster (the faults live on the "
            "sim disks)"
        )
        assert buggify.is_enabled(), (
            "DiskSwizzle requires chaos=true in the spec's cluster stanza "
            "(the disk.* buggify sites must be armable)"
        )
        await cluster.loop.delay(self.start_delay)
        for rnd in range(self.rounds):
            # the seed-armed half: force each site so its firing is a
            # campaign REQUIREMENT, not a dice roll — the live traffic
            # below consumes the forced queries in the disk I/O paths
            for site in ("disk.slow", "disk.stall", "disk.error",
                         "disk.enospc", "disk.corrupt_read",
                         # the page-cache memory-pressure flush
                         # (storage/pagecache.py): queried on cache fills,
                         # so it fires only when a durable engine's read
                         # path is really caching — always safe (the pool
                         # is clean by construction)
                         "cache.evict_all"):
                buggify.force(site, 1)
            capped: list[str] = []
            for i, path in enumerate(self._data_disks(fs)):
                fault = _FAULTS[(i + rnd) % len(_FAULTS)]
                if fault == "enospc" and path.startswith("tlog"):
                    # a capacity clamp on a TLog's disk queue blanks the
                    # WHOLE commit plane for the round — that scenario has
                    # its own negative-durability tests (refuse loudly,
                    # recover); the chaos rotation gives TLogs transient
                    # errors instead, and storage disks take the sustained
                    # ENOSPC (their durability loop must retry through it)
                    fault = "error"
                if fault == "slow":
                    fs.degrade(path, self.slow_mult)
                elif fault == "stall":
                    fs.stall(path, self.stall_seconds)
                elif fault == "error":
                    fs.inject_errors(path, self.errors)
                else:
                    used, _cap = fs.usage_for(path)
                    fs.set_capacity(path, used + self.enospc_headroom)
                    capped.append(path)
                self.faults_applied += 1
            # scrub pass (read-only): pread a chunk of every data disk so
            # the corrupt-on-read site meets real read traffic even when
            # nothing in the round happens to page data in — checksummed
            # consumers heal the flip, the scrub just provides the reads.
            # The handles ride a live CLUSTER process: buggify disk faults
            # arm only for process-owned I/O (the off-cluster blob store
            # keeps its own blob.* vocabulary)
            scrub_proc = next(
                (p for p in cluster.net.processes.values() if p.alive), None
            )
            for path in self._data_disks(fs):
                f = fs.open(path, scrub_proc)
                if f.size():
                    f.pread(0, min(4096, f.size()))
                f.close()
            # capacity probe on a THROWAWAY disk: proves the ENOSPC
            # enforcement plane itself every round (the live ss disks are
            # capped above, but whether a durability flush lands inside
            # the window is seed timing) — never append into live files
            probe = fs.open("diskswizzle.probe", scrub_proc)
            fs.set_capacity("diskswizzle.probe", probe.size() + 8)
            for _ in range(3):
                # a forced/armed injected fault may preempt the capacity
                # check on any one attempt; three tries guarantees the
                # ENOSPC enforcement itself is exercised
                try:
                    probe.append(b"x" * 64)
                except IOError:
                    continue  # DiskFull expected — disk.enospc_hit recorded
            fs.set_capacity("diskswizzle.probe", None)
            probe.close()
            await cluster.loop.delay(self.interval)
            # end of round: the operator "cleared" the faults
            for path in self._data_disks(fs):
                fs.degrade(path, 1.0)
            for path in capped:
                fs.set_capacity(path, None)

    async def check(self, cluster, rng) -> bool:
        if self.faults_applied == 0:
            return False
        # the cluster must serve commits again with every fault cleared;
        # recoveries in flight (an io_timeout kill mid-round) are given
        # time to land
        db = cluster.database()
        for attempt in range(40):
            try:
                async def body(tr, n=attempt):
                    tr.set(b"diskswizzle/probe", b"%d" % n)

                await db.run(body)
                self.probe_commits += 1
                return True
            except ActorCancelled:
                raise
            except Exception:  # noqa: BLE001 — recovery window, retry
                await cluster.loop.delay(0.5)
        return False

    def metrics(self) -> dict:
        return {
            "faults_applied": self.faults_applied,
            "probe_commits": self.probe_commits,
        }
