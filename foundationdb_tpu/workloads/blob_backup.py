"""BlobBackup workload — continuous backup into a blob-store container
under injected blob faults, with the uploader killed mid-stream
(fdbserver/workloads/BackupToDBCorrectness.actor.cpp crossed with the
BlobStore fault model: the backup is only real if it restores byte-exact
after connection failures, torn multipart uploads, corrupt reads, AND the
uploading process dying at an arbitrary offset).

The workload keeps its own committed model (every acknowledged burst) and
verifies the container by folding it back through the restore referee
(`client/backup.py apply_backup`) — the exact clip/replay a real restore
performs, compared byte-for-byte against the model.  The blob store lives
ON the simulated filesystem (`SimFSBacking`), so it has the same crash
semantics as every other disk: a restarting pair's part 2 (`action=verify`)
re-opens the container that rode the reboot and proves it still restores
to exactly what the rebooted cluster serves.

Buggify: `blob.connect_fail` / `blob.upload_torn` / `blob.read_corrupt`
(storage/blobstore.py) are force()-armed by seeded coins in setup, and
`blob.uploader_kill_point` jitters the kill offset."""

from __future__ import annotations

from .base import Workload
from ..runtime.buggify import buggify
from ..runtime.core import TaskPriority
from ..runtime.coverage import testcov

_CONTAINER = "bk"
_KEY_FMT = b"bb/k%05d"
_NUDGE_KEY = b"bb/n%04d"


class BlobBackupWorkload(Workload):
    description = "BlobBackup"

    def __init__(self, keys: int = 24, burst: int = 6,
                 start_delay: float = 0.2, kill_uploader: bool = True,
                 kill_jitter: float = 0.4, action: str = "full") -> None:
        if action not in ("full", "verify"):
            raise ValueError(f"action must be full|verify, got {action!r}")
        self.keys = keys
        self.burst = burst
        self.start_delay = start_delay
        self.kill_uploader = kill_uploader
        self.kill_jitter = kill_jitter
        self.action = action
        self.model: dict[bytes, bytes] = {}
        self.verified = False
        self.part1_verified = False
        self.uploader_killed = False

    def restart_state(self) -> dict:
        return {"keys": self.keys}

    def load_restart_manifest(self, manifest: dict) -> None:
        """Part 1 recorded whether its backup verified before the power
        kill; if it did, the rebooted container must still hold a full
        restorable snapshot — losing it in the reboot is a failure, not a
        vacuous pass."""
        m = manifest.get("part1_metrics", {}).get(self.description, {})
        self.part1_verified = bool(m.get("verified"))

    async def setup(self, cluster, rng) -> None:
        from ..runtime import buggify as _buggify

        if self.action == "full" and _buggify.is_enabled():
            # seeded arming so campaigns hit every blob fault site without
            # waiting on the dice (the SaveAndKill discipline)
            if rng.coinflip(0.6):
                _buggify.force("blob.connect_fail", times=2)
            if rng.coinflip(0.6):
                _buggify.force("blob.upload_torn")
            if rng.coinflip(0.6):
                _buggify.force("blob.read_corrupt")

    def _container(self, cluster, rng):
        """The blob container over the cluster's simulated filesystem —
        rebuilt identically (same name) by part 2 of a restarting pair."""
        from ..client.backup import backup_container
        from ..storage.blobstore import (
            BlobObjectStore,
            BlobStoreClient,
            SimBlobTransport,
            SimFSBacking,
        )

        assert cluster.fs is not None, "BlobBackup needs a durable cluster"
        store = BlobObjectStore(SimFSBacking(cluster.fs))
        uid_rng = rng.split()
        client = BlobStoreClient(
            SimBlobTransport(store, cluster.loop, rng.split()),
            knobs=cluster.knobs, trace=cluster.trace,
            sleep=lambda s: cluster.loop.delay(s, TaskPriority.DEFAULT_DELAY),
            nonce=f"c{uid_rng.random_unique_id()[:6]}",
        )
        return backup_container(
            f"blob://{_CONTAINER}", blob_client=client,
            uid=lambda: uid_rng.random_unique_id()[:8],
        )

    async def _commit_burst(self, db, lo: int, hi: int) -> None:
        async def fn(tr):
            for i in range(lo, hi):
                tr.set(_KEY_FMT % i, b"b%d" % (i * 31 + 7))

        await db.run(fn)
        for i in range(lo, hi):
            self.model[_KEY_FMT % i] = b"b%d" % (i * 31 + 7)

    async def start(self, cluster, rng) -> None:
        if self.action == "verify":
            return  # part 2: verification happens in check()
        from ..client.backup import BackupAgent, apply_backup

        db = cluster.database()
        await cluster.loop.delay(self.start_delay)
        container = self._container(cluster, rng)
        agent = BackupAgent(cluster)
        await agent.start(container)

        half = max(1, self.keys // 2)
        await self._commit_burst(db, 0, half)
        if self.kill_uploader:
            # kill the uploader mid-stream at a buggify-jittered offset: a
            # multipart upload may be half-staged — it must be detected
            # (never finalized ⇒ invisible; torn ⇒ refused at complete)
            # and re-uploaded by the replacement, never restored
            if buggify("blob.uploader_kill_point"):
                await cluster.loop.delay(rng.random() * self.kill_jitter)
            agent.kill_worker()
            self.uploader_killed = True
            testcov("backup.uploader_killed")
            cluster.trace.trace("BackupUploaderKilled")
            await agent.restart_worker(container)
        await self._commit_burst(db, half, self.keys)

        snap_v = await agent.snapshot(container, chunk_rows=16)
        # the backup is restorable once the log passes the newest chunk:
        # nudge commits (append-only keys, so a mid-upload kill leaves lag,
        # never a stale overwrite) push known_committed past the boundary
        for n in range(400):
            if agent.worker.backed_up.get() >= snap_v:
                break

            async def fn(tr, n=n):
                tr.set(_NUDGE_KEY % n, b"%d" % n)

            await db.run(fn)
            self.model[_NUDGE_KEY % n] = b"%d" % n
            await cluster.loop.delay(0.05, TaskPriority.DEFAULT_DELAY)
        assert agent.worker.backed_up.get() >= snap_v, (
            "backup log never reached the snapshot boundary"
        )
        # drain: the container must cover the LAST committed version, or
        # the model comparison below would count uploader lag as loss
        vfin = [0]

        async def fv(tr):
            vfin[0] = await tr.get_read_version()

        await db.run(fv)
        await agent.wait_backed_up_to(vfin[0], timeout=120.0)
        await agent.stop()

        # restore referee: fold the container back and compare the bb/
        # range byte-for-byte against the committed model
        chunks, log = await container.read()
        state = apply_backup(chunks, log)
        got = {k: v for k, v in state.items() if k.startswith(b"bb/")}
        assert got == self.model, (
            f"blob restore diverges from the committed model: "
            f"{len(got)} restored vs {len(self.model)} committed"
        )
        self.verified = True
        testcov("backup.blob_verified")

    async def check(self, cluster, rng) -> bool:
        if self.action == "full":
            return self.verified
        # part 2: the container rode the reboot on the simulated disks —
        # it must still restore to exactly what the rebooted cluster
        # serves (both recovered independently: storage from its files +
        # TLog re-pull, the container from its synced objects)
        from ..client.backup import apply_backup

        container = self._container(cluster, rng)
        chunks, log = await container.read()
        if not chunks:
            # legal only when part 1 never finished its snapshot (the kill
            # point is buggify-jittered on purpose); a backup part 1 had
            # VERIFIED restorable must not vanish in the reboot
            return not self.part1_verified
        state = apply_backup(chunks, log)
        db = cluster.database()

        async def fn(tr):
            return await tr.get_range(b"bb/", b"bb0", limit=1 << 20)

        rows = dict(await db.run(fn))
        got = {k: v for k, v in state.items() if k.startswith(b"bb/")}
        # every byte the container restores must match the rebooted
        # cluster (a torn/phantom object surviving into a restore would
        # diverge HERE); the container may trail the cluster when the kill
        # landed mid-upload — that is lag, not loss
        for k, v in got.items():
            if rows.get(k) != v:
                return False
        if got == rows:
            # the common case: part 1 finished its backup before the kill,
            # so the reboot-surviving container restores the FULL range
            testcov("backup.blob_reverified_after_reboot")
        elif self.part1_verified:
            # part 1 proved the container byte-exact and nothing mutated
            # bb/ afterwards: anything short of full equality now means
            # the reboot lost committed data or backup objects
            return False
        return True

    def metrics(self) -> dict:
        return {
            "committed": len(self.model),
            "uploader_killed": self.uploader_killed,
            "verified": self.verified,
        }
