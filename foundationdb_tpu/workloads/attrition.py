"""Attrition workload — kill pipeline processes while other workloads run
(fdbserver/workloads/MachineAttrition.actor.cpp; composed with Cycle etc. in
specs like tests/fast/CycleTest.txt)."""

from __future__ import annotations

from .base import Workload


class AttritionWorkload(Workload):
    """Kills `kills` random write-pipeline processes, spaced by `interval`
    of virtual time.  Requires a RecoverableCluster (controller present)."""

    description = "Attrition"

    def __init__(self, kills: int = 2, interval: float = 3.0, start_delay: float = 1.0):
        self.kills = kills
        self.interval = interval
        self.start_delay = start_delay
        self.killed: list[str] = []

    async def start(self, cluster, rng) -> None:
        await cluster.loop.delay(self.start_delay)
        for _ in range(self.kills):
            gen = cluster.controller.generation
            victims = [p for p in gen.processes if p.alive]
            if victims:
                victim = rng.random_choice(victims)
                self.killed.append(victim.name)
                cluster.trace.trace("AttritionKill", Process=victim.name)
                victim.kill()
            await cluster.loop.delay(self.interval)

    async def check(self, cluster, rng) -> bool:
        # every kill must have produced a completed recovery
        return cluster.controller.recoveries >= len(self.killed) > 0

    def metrics(self) -> dict:
        return {"killed": self.killed}
