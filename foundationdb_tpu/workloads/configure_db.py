"""ConfigureDatabase workload — random online reconfiguration under load
(fdbserver/workloads/ConfigureDatabase.actor.cpp: flip role counts,
redundancy modes, and the STORAGE ENGINE mid-traffic; every flip must
preserve every invariant).

Each step commits a random `configure` change (n_tlogs / n_proxies /
n_resolvers / redundancy double<->triple / engine memory<->ssd) and
waits for the cluster to converge before the next.  An engine flip is
the heaviest: the conf watch migrates one replica at a time through the
dd heal path (kill → re-replicate on the new engine), so convergence
means every replica's store is the new class.  Runs composed with an
invariant workload (Cycle, Increment) whose checks prove no flip lost
or forked data."""

from __future__ import annotations

from .base import Workload
from ..client.management import configure


class ConfigureDatabaseWorkload(Workload):
    description = "ConfigureDatabase"

    def __init__(self, flips: int = 3, interval: float = 1.5,
                 include_redundancy: bool = True,
                 include_engine: bool = False,
                 engine_only: bool = False):
        self.flips = flips
        self.interval = interval
        self.include_redundancy = include_redundancy
        # engine flips need a durable cluster with replication >= 2 (the
        # migrating replica re-fetches from live teammates), so specs opt
        # in explicitly; engine_only pins EVERY flip to a swap — the
        # deterministic-migration spec shape (EngineSwap.txt)
        self.include_engine = include_engine or engine_only
        self.engine_only = engine_only
        self.applied = 0
        self.converged = 0
        self.engine_flips = 0

    def _choices(self) -> int:
        n = 3
        if self.include_redundancy:
            n += 1
        if self.include_engine:
            n += 1
        return n

    async def start(self, cluster, rng) -> None:
        db = cluster.database()
        cc = cluster.controller
        for _ in range(self.flips):
            await cluster.loop.delay(self.interval)
            # random_int is half-open [lo, hi)
            choice = (
                self._choices() - 1 if self.engine_only
                else rng.random_int(0, self._choices())
            )
            if choice == 0:
                want = {"n_tlogs": rng.random_int(2, 4)}
            elif choice == 1:
                want = {"n_proxies": rng.random_int(1, 3)}
            elif choice == 2:
                want = {"n_resolvers": rng.random_int(1, 3)}
            elif choice == 3 and self.include_redundancy:
                want = {"redundancy": rng.random_choice(["double", "triple"])}
            else:
                want = {
                    "engine": "ssd"
                    if cluster.storage_engine == "memory" else "memory"
                }
                self.engine_flips += 1
            await configure(db, **want)
            self.applied += 1

            def done() -> bool:
                gen = cc.generation
                if gen is None or cc._recovering:
                    return False
                if "n_tlogs" in want and len(gen.tlogs) != want["n_tlogs"]:
                    return False
                if "n_proxies" in want and len(gen.proxies) != want["n_proxies"]:
                    return False
                if "n_resolvers" in want and len(gen.resolvers) != want["n_resolvers"]:
                    return False
                if "redundancy" in want:
                    target = 2 if want["redundancy"] == "double" else 3
                    if any(len(t) != target for t in cc.storage_teams_tags):
                        return False
                if "engine" in want and cluster._engine_applied != want["engine"]:
                    # applied only once EVERY replica migrated — the swap's
                    # own convergence marker
                    return False
                return True

            for _ in range(600):
                if done():
                    self.converged += 1
                    break
                await cluster.loop.delay(0.1)

    async def check(self, cluster, rng) -> bool:
        # every requested flip converged (partial convergence = a wedged
        # reconfiguration path)
        return self.converged == self.applied

    def metrics(self) -> dict:
        return {"applied": self.applied, "converged": self.converged,
                "engine_flips": self.engine_flips}
