"""Rollback workload — force a recovery that discards in-flight commits,
then prove no ACKNOWLEDGED commit was lost
(fdbserver/workloads/Rollback.actor.cpp: clog the proxy→TLog links while
commits are in flight, then kill the TLog so recovery rolls the
un-acknowledged suffix back; the reference's point is that rollback may
discard anything still in flight but never anything a client was told
committed).

Writer clients stream unique keys and record each commit the moment the
cluster ACKNOWLEDGES it; concurrently, each round the chaos half clogs
the commit plane mid-burst and kills a TLog process, forcing a generation
recovery while commits are stalled inside the pipeline.  `check` then
reads every acknowledged key back: all must be present with their exact
values (the durability contract), commits that ended CommitUnknownResult
are allowed either outcome, and at least one forced recovery must have
actually happened (a Rollback run that never rolled back tested
nothing)."""

from __future__ import annotations

from .base import Workload
from ..client.transaction import RETRYABLE_ERRORS
from ..roles.types import CommitUnknownResult
from ..runtime.combinators import wait_all
from ..runtime.coverage import testcov


class RollbackWorkload(Workload):
    description = "Rollback"

    def __init__(self, rounds: int = 2, clients: int = 2,
                 writes_per_client: int = 12, start_delay: float = 0.4,
                 interval: float = 1.2, clog_seconds: float = 0.5):
        self.rounds = rounds
        self.clients = clients
        self.writes_per_client = writes_per_client
        self.start_delay = start_delay
        self.interval = interval
        self.clog_seconds = clog_seconds
        self.acked: dict[bytes, bytes] = {}
        self.unknown: list[bytes] = []
        self.forced_recoveries = 0
        self._recoveries_before = 0

    async def start(self, cluster, rng) -> None:
        self._recoveries_before = cluster.controller.recoveries

        async def writer(ci: int, crng) -> None:
            db = cluster.database()
            for seq in range(self.writes_per_client):
                key = b"rollback/%d/%04d" % (ci, seq)
                val = b"v%d" % crng.random_int(0, 1 << 30)
                tr = db.create_transaction()
                while True:
                    try:
                        tr.set(key, val)
                        await tr.commit()
                        # the ack is the contract: from here this write
                        # must survive anything short of data loss
                        self.acked[key] = val
                        break
                    except CommitUnknownResult:
                        # either outcome is legal for an UNKNOWN commit;
                        # record it as such and move on — the bookkeeping
                        # must stay honest about what was acknowledged
                        self.unknown.append(key)
                        break
                    except RETRYABLE_ERRORS as e:
                        await tr.on_error(e)

        async def chaos(crng) -> None:
            from ..control.controller import RecoveryState

            await cluster.loop.delay(self.start_delay)
            for _ in range(self.rounds):
                # wait out any in-flight recovery first: the controller
                # COALESCES kills landing mid-recovery (_recover returns
                # on its re-entry guard), so a kill only forces a distinct
                # rollback when it lands on a fully-recovered generation
                settle = cluster.loop.now() + 60.0
                while (cluster.controller.recovery_state
                       != RecoveryState.FULLY_RECOVERED
                       and cluster.loop.now() < settle):
                    await cluster.loop.delay(0.2)
                gen = cluster.controller.generation
                tlogs = [t for t in gen.tlogs if t.process.alive]
                if not tlogs:
                    await cluster.loop.delay(self.interval)
                    continue
                victim = crng.random_choice(tlogs)
                # clog the victim against the whole commit plane first so
                # in-flight commits stall INSIDE the pipeline when it dies
                # (the reference's clogging-then-kill signature move)
                for proc in gen.processes:
                    if proc is not victim.process and proc.alive:
                        cluster.net.clog_pair(
                            victim.process.address, proc.address,
                            self.clog_seconds,
                        )
                await cluster.loop.delay(self.clog_seconds / 2)
                cluster.trace.trace("RollbackKill",
                                    Process=victim.process.name)
                victim.process.kill()
                self.forced_recoveries += 1
                testcov("rollback.forced_recovery")
                await cluster.loop.delay(self.interval)

        await wait_all(
            [cluster.loop.spawn(writer(i, rng.split()))
             for i in range(self.clients)]
            + [cluster.loop.spawn(chaos(rng.split()))]
        )

    async def check(self, cluster, rng) -> bool:
        if self.forced_recoveries == 0:
            return False
        # at least one COMPLETED recovery must separate the writes from
        # this read-back (a Rollback that never rolled back tested
        # nothing); not one-per-kill — co-composed chaos (attrition,
        # swizzle) can legitimately coalesce kills into one recovery
        if cluster.controller.recoveries <= self._recoveries_before:
            return False
        db = cluster.database()

        async def read_all(tr):
            out = {}
            # snapshot: tr.get suspends, and a retried read_all must walk a
            # stable key list even if a straggler writer raced in (flowcheck)
            for key in list(self.acked):
                out[key] = await tr.get(key)
            return out

        got = await db.run(read_all)
        lost = {k for k, v in self.acked.items() if got.get(k) != v}
        if lost:
            cluster.trace.trace("RollbackLostAckedCommit",
                                Keys=[k.decode() for k in sorted(lost)])
            return False
        return True

    def metrics(self) -> dict:
        return {
            "acked": len(self.acked),
            "unknown": len(self.unknown),
            "forced_recoveries": self.forced_recoveries,
        }
