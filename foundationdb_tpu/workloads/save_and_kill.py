"""SaveAndKill workload — part 1 of a restarting test pair
(fdbserver/workloads/SaveAndKill.actor.cpp: run workloads, then power-kill
the whole simulation and copy the surviving disks + a restart manifest to
a host directory; tester.actor.cpp:1118 boots part 2 from it).

At `restart_after` virtual seconds this workload kills EVERY simulated
process at once — no clean shutdown, no draining, in-memory state
discarded, un-fsynced file buffers dropped (the SimFile crash model) —
then serializes the surviving `SimFilesystem` image plus the manifest
(seed, cluster/spec config, each co-workload's invariant state) via
`storage/image.py` and raises `RestartKill`, which `run_spec` recognizes
as the part-1 verdict: the simulation ended on purpose, checks belong to
part 2's lifetime.

Buggify sites: `restart.kill_point` jitters the kill instant (the
reference varies when in the workload's life the power dies) and
`restart.manifest_corrupt` (in image.py) plants a torn manifest temp next
to the save.  Under chaos the setup phase deterministically force()s each
with a seeded coin so restarting soak campaigns hit both without waiting
on the dice."""

from __future__ import annotations

from .base import Workload
from ..runtime.buggify import buggify
from ..runtime.coverage import testcov
from ..storage.image import save_image


def invariant_states(workloads: list[Workload]) -> dict[str, list[dict]]:
    """Manifest shape for workload invariant state: name -> ORDERED list of
    `restart_state()` dicts, one per stanza.  A list, not a flat dict, so
    two same-named stanzas (e.g. two Cycle rings of different sizes) both
    survive into part 2's positional comparison instead of collapsing to
    whichever came last."""
    out: dict[str, list[dict]] = {}
    for w in workloads:
        state = w.restart_state()
        if state:
            out.setdefault(w.description, []).append(state)
    return out


class RestartKill(Exception):
    """Control-flow signal, not a failure: part 1 power-killed the sim and
    saved its image.  run_spec catches this and returns phase-1 metrics."""

    def __init__(self, image_dir: str) -> None:
        super().__init__(image_dir)
        self.image_dir = image_dir


class SaveAndKillWorkload(Workload):
    description = "SaveAndKill"

    def __init__(self, restart_after: float = 2.0, kill_jitter: float = 0.5):
        self.restart_after = restart_after
        self.kill_jitter = kill_jitter
        self.killed_at: float | None = None
        # bound by run_spec (only it knows the spec/cluster config the
        # manifest must carry): (save_dir, manifest base, co-workloads)
        self._save_dir: str | None = None
        self._manifest: dict | None = None
        self._co_workloads: list[Workload] = []

    def bind(self, save_dir: str, manifest: dict,
             co_workloads: list[Workload]) -> None:
        self._save_dir = save_dir
        self._manifest = manifest
        self._co_workloads = [w for w in co_workloads if w is not self]

    async def setup(self, cluster, rng) -> None:
        from ..runtime import buggify as _buggify

        if _buggify.is_enabled():
            # deterministic per-seed arming: roughly half of a campaign's
            # seeds walk each rare path, the other half keep the clean one
            if rng.coinflip(0.5):
                _buggify.force("restart.kill_point")
            if rng.coinflip(0.5):
                _buggify.force("restart.manifest_corrupt")

    async def start(self, cluster, rng) -> None:
        assert self._save_dir is not None and self._manifest is not None, (
            "SaveAndKill only runs under run_spec (it needs the spec's "
            "cluster config for the restart manifest)"
        )
        await cluster.loop.delay(self.restart_after)
        if buggify("restart.kill_point"):
            # power loss does not consult the test plan for a good moment
            await cluster.loop.delay(rng.random() * self.kill_jitter)
        self.killed_at = cluster.loop.now()
        # traced BEFORE the kill so the event lands in part 1's stream —
        # the marker triage uses to join part-1/part-2 trace files
        cluster.trace.trace("SaveAndKill", KilledAt=self.killed_at,
                            SaveDir=self._save_dir)
        testcov("restart.power_kill")
        fs = cluster.power_off()  # every process dies NOW, buffers dropped
        manifest = dict(self._manifest)
        manifest["killed_at"] = self.killed_at
        manifest["workloads"] = invariant_states(self._co_workloads)
        manifest["part1_metrics"] = {
            w.description: w.metrics() for w in self._co_workloads
        }
        raise RestartKill(save_image(fs, self._save_dir, manifest))

    def metrics(self) -> dict:
        return {"killed_at": self.killed_at, "image": self._save_dir}
