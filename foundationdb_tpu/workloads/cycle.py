"""Cycle workload — the canonical serializability invariant
(fdbserver/workloads/Cycle.actor.cpp).

N keys form a ring: key i stores the index of its successor.  Each
transaction picks a random node A, reads A -> B -> C, and swaps so A points
to C and B points past it — a 3-node rotation that keeps the graph a single
N-cycle *only if transactions are serializable*.  Lost updates, stale
reads, or phantom commits break the ring, which `check` detects by walking
it."""

from __future__ import annotations

from .base import Workload
from ..client.transaction import RETRYABLE_ERRORS
from ..runtime.combinators import wait_all


def _key(i: int) -> bytes:
    return b"cycle/%04d" % i


class CycleWorkload(Workload):
    description = "Cycle"

    def __init__(self, nodes: int = 20, clients: int = 4, txns_per_client: int = 25):
        self.nodes = nodes
        self.clients = clients
        self.txns_per_client = txns_per_client
        self.committed = 0
        self.retries = 0

    async def setup(self, cluster, rng) -> None:
        db = cluster.database()

        async def fill(tr):
            for i in range(self.nodes):
                tr.set(_key(i), b"%d" % ((i + 1) % self.nodes))

        await db.run(fill)

    async def start(self, cluster, rng) -> None:
        db = cluster.database()

        async def client(crng):
            # a rotation retried after CommitUnknownResult is safe: on_error
            # fences the in-flight original, and the retry re-reads state —
            # either outcome of the original yields a valid rotation
            for _ in range(self.txns_per_client):
                tr = db.create_transaction()
                while True:
                    try:
                        a = crng.random_int(0, self.nodes)
                        b = int(await tr.get(_key(a)))
                        c = int(await tr.get(_key(b)))
                        d = int(await tr.get(_key(c)))
                        tr.set(_key(a), b"%d" % c)
                        tr.set(_key(b), b"%d" % d)
                        tr.set(_key(c), b"%d" % b)
                        await tr.commit()
                        self.committed += 1
                        break
                    except RETRYABLE_ERRORS as e:
                        self.retries += 1
                        await tr.on_error(e)

        await wait_all(
            [cluster.loop.spawn(client(rng.split())) for _ in range(self.clients)]
        )

    async def check(self, cluster, rng) -> bool:
        db = cluster.database()

        async def walk(tr):
            seen = set()
            cur = 0
            for _ in range(self.nodes):
                if cur in seen:
                    return False
                seen.add(cur)
                nxt = await tr.get(_key(cur))
                if nxt is None:
                    return False
                cur = int(nxt)
            return cur == 0 and len(seen) == self.nodes

        return await db.run(walk)

    def metrics(self) -> dict:
        return {"committed": self.committed, "retries": self.retries}

    def restart_state(self) -> dict:
        # the ring size IS the invariant: part 2 walking a different-sized
        # ring against part 1's disks would be checking nothing
        return {"nodes": self.nodes}
