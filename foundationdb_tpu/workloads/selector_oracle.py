"""Selector + SnapshotCache oracle workload — the chaos referee for the
client API layer (ROADMAP item #2 acceptance).

Every round commits a known batch of mutations (tracking a committed
MODEL dict, with CommitUnknownResult resolved through a per-round marker
key), then opens a read-your-writes transaction, applies more UNCOMMITTED
writes to it, and fires a barrage of randomized reads through the merged
(SnapshotCache, WriteMap) view:

    get_key(KeySelector)      vs naive bisect resolution over the model
    get_range(sel, sel)       vs the model slice between naive resolutions
    get_range(bytes, bytes)   vs the model slice
    get(key) read TWICE       vs the model (and cache-served must agree)

The naive oracle is the reference definition of a selector — base index
"last key < / <= anchor", plus offset, clamped to b"" / b"\\xff" — so any
divergence in the storage findKey walk, the shard-boundary continuation,
the RYW merge iterator, or a stale SnapshotCache entry shows up as a
byte-level mismatch.  Runs composed with attrition + swizzle clogging
under buggify, so resolution is exercised across failed storage replicas,
clogged links, and recoveries; retryable errors restart the round's read
phase via on_error (which drops cache + writes, like a real retry loop).

Keys are spread across single-byte prefixes so the default storage splits
put shard boundaries INSIDE the key population: negative- and positive-
offset walks must hop shards to resolve.
"""

from __future__ import annotations

import bisect

from .base import Workload
from ..client.ryw import ReadYourWritesTransaction
from ..client.transaction import CommitUnknownResult, RETRYABLE_ERRORS
from ..roles.types import CLIENT_KEYSPACE_END, KeySelector


def naive_resolve(keys: list[bytes], sel: KeySelector) -> bytes:
    """Reference selector resolution over a SORTED key list: base position
    is the last key < anchor (or <= with or_equal), move `offset` keys
    right; off either end clamps to the keyspace boundary."""
    base = (
        bisect.bisect_right(keys, sel.key)
        if sel.or_equal
        else bisect.bisect_left(keys, sel.key)
    ) - 1
    i = base + sel.offset
    if i < 0:
        return b""
    if i >= len(keys):
        return CLIENT_KEYSPACE_END
    return keys[i]


class SelectorOracleWorkload(Workload):
    description = "SelectorOracle"

    def __init__(self, rounds: int = 3, checks_per_round: int = 10,
                 keyspace: int = 18):
        self.rounds = rounds
        self.checks_per_round = checks_per_round
        self.keyspace = keyspace
        self.checks = 0
        self.selector_checks = 0
        self.retries = 0
        self.failures: list = []  # recorded, asserted in check()

    def _key(self, i: int) -> bytes:
        # spread first bytes across [0x10, 0xEF]: the default shard splits
        # (evenly spaced single-byte prefixes) land inside the population
        return bytes([0x10 + (0xE0 * i) // self.keyspace]) + b"sel%03d" % i

    def _anchor(self, rng) -> bytes:
        # anchors on, between, below, and above the population
        kind = rng.random_int(0, 3)
        if kind == 0:
            return self._key(rng.random_int(0, self.keyspace - 1))
        if kind == 1:
            return self._key(rng.random_int(0, self.keyspace - 1)) + b"\x00"
        if kind == 2:
            return b"\x01below"
        return b"\xfe\xffabove"

    def _rand_sel(self, rng) -> KeySelector:
        return KeySelector(
            self._anchor(rng), rng.random_int(0, 1) == 1,
            rng.random_int(-5, 6),
        )

    async def _commit_round(self, db, rng, model: dict, r: int) -> None:
        """Commit a randomized batch against `model`, resolving
        CommitUnknownResult through the round's marker key."""
        marker = b"\x0fselmark/%03d" % r
        pend = dict(model)
        ops: list = []
        for _ in range(4):
            i = rng.random_int(0, self.keyspace - 1)
            if rng.random_int(0, 3) == 0:
                j = rng.random_int(0, self.keyspace - 1)
                b, e = sorted((self._key(i), self._key(j) + b"\xff"))
                ops.append(("clear", b, e))
                for k in list(pend):
                    if b <= k < e:
                        del pend[k]
            else:
                v = b"r%03d.%d" % (r, i)
                ops.append(("set", self._key(i), v))
                pend[self._key(i)] = v
        ops.append(("set", marker, b"1"))
        pend[marker] = b"1"

        tr = db.create_transaction()
        while True:
            try:
                for op in ops:
                    if op[0] == "set":
                        tr.set(op[1], op[2])
                    else:
                        tr.clear_range(op[1], op[2])
                await tr.commit()
                model.clear()
                model.update(pend)
                return
            except RETRYABLE_ERRORS as e:
                self.retries += 1
                if isinstance(e, CommitUnknownResult):
                    # the marker key decides whether the batch landed
                    await tr.on_error(e)
                    landed = await self._marker_landed(db, marker)
                    if landed:
                        model.clear()
                        model.update(pend)
                        return
                else:
                    await tr.on_error(e)

    async def _marker_landed(self, db, marker: bytes) -> bool:
        tr = db.create_transaction()
        while True:
            try:
                return await tr.get(marker) is not None
            except RETRYABLE_ERRORS as e:
                self.retries += 1
                await tr.on_error(e)

    def _model_range(self, merged: dict, b: bytes, e: bytes,
                     limit: int) -> list:
        return sorted(
            ((k, v) for k, v in merged.items() if b <= k < e)
        )[:limit]

    async def _read_phase(self, db, rng, model: dict) -> None:
        """One RYW transaction: uncommitted local writes + the randomized
        read barrage, every answer cross-checked against the merged model.
        Retryable read errors restart the phase (on_error drops the write
        map and the snapshot cache, so local writes are re-applied)."""
        while True:
            ryw = ReadYourWritesTransaction(db)
            merged = dict(model)
            try:
                for _ in range(3):
                    i = rng.random_int(0, self.keyspace - 1)
                    if rng.random_int(0, 2) == 0:
                        b, e = self._key(i), self._key(i) + b"\xff\xff"
                        ryw.clear_range(b, e)
                        for k in list(merged):
                            if b <= k < e:
                                del merged[k]
                    else:
                        v = b"local.%d" % i
                        ryw.set(self._key(i), v)
                        merged[self._key(i)] = v
                keys = sorted(merged)
                for _ in range(self.checks_per_round):
                    kind = rng.random_int(0, 3)
                    if kind == 0:  # selector resolution
                        sel = self._rand_sel(rng)
                        got = await ryw.get_key(sel)
                        want = naive_resolve(keys, sel)
                        self.selector_checks += 1
                        if got != want:
                            self.failures.append(
                                ("get_key", sel, got, want)
                            )
                    elif kind == 1:  # selector-endpoint range
                        bs, es = self._rand_sel(rng), self._rand_sel(rng)
                        limit = rng.random_int(1, 12)
                        got = await ryw.get_range(bs, es, limit=limit)
                        b, e = naive_resolve(keys, bs), naive_resolve(keys, es)
                        want = (
                            [] if b >= e
                            else self._model_range(merged, b, e, limit)
                        )
                        if got != want:
                            self.failures.append(
                                ("get_range_sel", bs, es, got, want)
                            )
                    elif kind == 2:  # plain range over the merged view
                        b, e = sorted(
                            (self._anchor(rng), self._anchor(rng))
                        )
                        got = await ryw.get_range(b, e, limit=20)
                        want = self._model_range(merged, b, e, 20)
                        if got != want:
                            self.failures.append(("get_range", b, e, got, want))
                    else:  # point read, twice (second must be cache-served
                        # and still agree)
                        k = self._key(rng.random_int(0, self.keyspace - 1))
                        first = await ryw.get(k)
                        second = await ryw.get(k)
                        want = merged.get(k)
                        if first != want or second != want:
                            self.failures.append(("get", k, first, second, want))
                    self.checks += 1
                return
            except RETRYABLE_ERRORS as e:
                self.retries += 1
                await ryw.on_error(e)

    async def start(self, cluster, rng) -> None:
        db = cluster.database()
        model: dict[bytes, bytes] = {}
        for r in range(self.rounds):
            await self._commit_round(db, rng, model, r)
            await self._read_phase(db, rng, model)

    async def check(self, cluster, rng) -> bool:
        if self.failures:
            for f in self.failures[:5]:
                print(f"[SelectorOracle] divergence: {f}")
            return False
        return self.checks > 0 and self.selector_checks > 0

    def metrics(self) -> dict:
        return {
            "checks": self.checks,
            "selector_checks": self.selector_checks,
            "retries": self.retries,
            "divergences": len(self.failures),
        }
