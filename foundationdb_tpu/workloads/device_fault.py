"""DeviceFault workload — deterministic device-fault injection as a spec
stanza (the targeted half of the device-fault chaos campaign: the random
half is buggify's per-run arming; this workload FORCES each device.*
site so a spec/soak campaign is guaranteed to walk the supervisor's
failure paths, the way the reference's targeted simulation tests force
specific SBVars rather than waiting on the dice).

Each site is forced `times` queries, then a few driver commits push live
traffic through the resolver so the armed fault actually meets a device
interaction (a forced site only fires when the supervisor guards a real
device call).  Requires a supervised device conflict backend
(`backend=supervised` in the spec's cluster stanza) — under any other
backend nothing guards device calls and `check` fails loudly instead of
the campaign silently testing nothing."""

from __future__ import annotations

from .base import Workload


class DeviceFaultWorkload(Workload):
    description = "DeviceFault"

    DEFAULT_SITES = (
        "device.lost",
        "device.dispatch_hang",
        "device.compile_fail",
        "device.readback_corrupt",
    )

    def __init__(self, sites: str = "", times: int = 2,
                 start_delay: float = 0.4, writes_per_site: int = 6,
                 interval: float = 0.3):
        self._sites = (
            tuple(s.strip() for s in sites.split(",") if s.strip())
            or self.DEFAULT_SITES
        )
        # times < DEVICE_RETRY_LIMIT by default: the streak heals on the
        # next success instead of tripping the breaker, so LATER sites
        # still meet a device-serving backend to fire against
        self.times = times
        self.start_delay = start_delay
        self.writes_per_site = writes_per_site
        self.interval = interval
        self.forced = 0

    async def start(self, cluster, rng) -> None:
        from ..runtime import buggify

        # force() is a silent no-op outside simulation chaos mode — a spec
        # composing this workload without `chaos=true` would test nothing
        # and then fail check() with no hint of why
        assert buggify.is_enabled(), (
            "DeviceFault requires chaos=true in the spec's cluster stanza "
            "(buggify must be enabled for forced device faults to fire)"
        )
        db = cluster.database()
        await cluster.loop.delay(self.start_delay)
        for n, site in enumerate(self._sites):
            buggify.force(site, self.times)
            self.forced += 1
            # drive enough commits that the forced fires are consumed even
            # if the concurrent workloads have already finished
            for i in range(self.writes_per_site):
                key = b"devfault/%d/%d" % (n, i)

                async def body(tr, k=key):
                    tr.set(k, b"x")

                await db.run(body)
            await cluster.loop.delay(self.interval)

    async def check(self, cluster, rng) -> bool:
        from ..runtime import coverage

        missing = [
            s for s in self._sites if not coverage.hits(f"buggify.{s}")
        ]
        if not missing:
            return True
        # a breaker trip mid-run parks the backend on the CPU reference and
        # stops consuming forced device faults — that's the supervisor
        # doing its job, not a coverage failure of this seed (the campaign
        # census still requires every site to fire across SOME seed).  The
        # evidence is the degrade path's own coverage marker, which is
        # process-global and so survives a recovery recruiting FRESH
        # supervisors (whose trip counters restart at zero).
        return coverage.hits("device.degraded") >= 1

    def metrics(self) -> dict:
        return {"forced_sites": self.forced}
