"""Swizzle workload — clog a random subset of the cluster's network links,
then unclog them in reverse order (the reference's swizzling clogging,
fdbrpc/sim2.actor.cpp + RandomClogging/Rollback workload family: the
reverse-order unclog is the signature move that surfaces ordering bugs)."""

from __future__ import annotations

from .base import Workload


class SwizzleWorkload(Workload):
    description = "Swizzle"

    def __init__(self, rounds: int = 2, victims: int = 3,
                 clog_seconds: float = 0.8, interval: float = 1.5,
                 start_delay: float = 0.5):
        self.rounds = rounds
        self.victims = victims
        self.clog_seconds = clog_seconds
        self.interval = interval
        self.start_delay = start_delay
        self.swizzles = 0

    async def start(self, cluster, rng) -> None:
        net = cluster.net
        await cluster.loop.delay(self.start_delay)
        for _ in range(self.rounds):
            alive = [p.address for p in net.processes.values() if p.alive]
            if len(alive) < 2:
                continue
            chosen = []
            for _ in range(min(self.victims, len(alive))):
                a = rng.random_choice(alive)
                if a not in chosen:
                    chosen.append(a)
            # clog each victim against every other process, staggered; the
            # REVERSE-order unclog emerges from the staggered expiries
            for i, addr in enumerate(chosen):
                stagger = self.clog_seconds * (len(chosen) - i) / len(chosen)
                for other in alive:
                    if other != addr:
                        net.clog_pair(addr, other, stagger)
            self.swizzles += 1
            await cluster.loop.delay(self.interval)

    async def check(self, cluster, rng) -> bool:
        return self.swizzles > 0

    def metrics(self) -> dict:
        return {"swizzles": self.swizzles}
