"""ConsistencyCheck — replica-equality verification after quiescence
(fdbserver/workloads/ConsistencyCheck.actor.cpp checkDataConsistency +
the QuietDatabase wait it runs under).

For every shard team: wait until each live replica has applied a fresh read
version (the quiet-database analog — nothing in flight below it), then read
the replica's ENTIRE holdings at that version and assert byte equality
across the team.  A dead replica is skipped (data distribution healing is
the component that would re-replicate it); a team with NO live replica
fails the check.
"""

from __future__ import annotations

from .base import Workload
from ..roles.types import (
    FutureVersion,
    GetKeyValuesRequest,
    TransactionTooOld,
)
from ..rpc.stream import RequestStreamRef
from ..runtime.combinators import timeout_error
from ..runtime.core import BrokenPromise, TimedOut

_END = b"\xff\xff\xff\xff\xff\xff\xff\xff"  # past any user key in the sim


class ConsistencyCheckWorkload(Workload):
    description = "ConsistencyCheck"

    def __init__(self, quiesce_timeout: float = 30.0, attempts: int = 6):
        self.quiesce_timeout = quiesce_timeout
        self.attempts = attempts
        self.shards_checked = 0
        self.replicas_compared = 0
        self.rows_checked = 0

    async def start(self, cluster, rng) -> None:
        pass  # pure check-phase workload

    async def _check_shard(self, cluster, db, proc, begin, end, team) -> bool:
        """One shard's replica comparison at a FRESH read version (so a
        chaos seed's shrunken MVCC window can't age the version out while
        earlier shards were being compared)."""
        async def grv(tr):
            return await tr.get_read_version()

        v = await db.run(grv)
        live = [ss for ss in team if ss.process.alive]
        if not live:
            return False  # an entire team lost: data IS gone
        datasets = []
        for ss in live:
            # quiet-database wait: the replica must catch up to v
            try:
                await timeout_error(
                    cluster.loop, ss.version.when_at_least(v),
                    self.quiesce_timeout,
                )
            except TimedOut:
                return False
            ref = RequestStreamRef(cluster.net, proc, ss.getkv_stream.endpoint)
            rep = await ref.get_reply(
                GetKeyValuesRequest(begin, end, v, 1_000_000), timeout=10.0
            )
            datasets.append(rep.data)
        if any(d != datasets[0] for d in datasets[1:]):
            return False
        # count only the attempt that verified (retries must not inflate
        # the campaign-triage metrics)
        self.replicas_compared += len(datasets)
        self.rows_checked += len(datasets[0])
        self.shards_checked += 1
        return True

    async def check(self, cluster, rng) -> bool:
        db = cluster.database()
        proc = cluster.net.create_process(
            f"cons-check-{rng.random_unique_id()[:6]}"
        )
        teams = cluster.storage_teams()
        # clip each comparison to the shard's range: after a data-
        # distribution move a server may serve several segments, so full-
        # holdings reads would differ between teammates with different
        # OTHER assignments
        bounds = [b""] + list(cluster.storage_splits) + [_END]
        for shard, team in enumerate(teams):
            begin, end = bounds[shard], bounds[shard + 1]
            ok = False
            for attempt in range(self.attempts):
                # TRANSIENT failures retry with a fresh version (the
                # reference's ConsistencyCheck loops the same way): a
                # reply lost to chaos clogging, a replica still serving
                # FutureVersion mid-recovery, the version aging out of a
                # shrunken MVCC window, a whole team mid-reboot, or a
                # lagging quiesce wait — the environment being noisy.
                # Only a REPEATABLE failure (False on the last attempt
                # too) is an inconsistency verdict: real divergence is
                # durable, so retrying can't mask it.
                try:
                    ok = await self._check_shard(
                        cluster, db, proc, begin, end, team
                    )
                    if ok:
                        break
                except (TimedOut, BrokenPromise, TransactionTooOld,
                        FutureVersion):
                    if attempt == self.attempts - 1:
                        raise
                if attempt < self.attempts - 1:
                    await cluster.loop.delay(0.5)
            if not ok:
                return False
        return True

    def metrics(self) -> dict:
        return {
            "shards_checked": self.shards_checked,
            "replicas_compared": self.replicas_compared,
            "rows_checked": self.rows_checked,
        }
