"""RandomMoveKeys — adversarial MoveKeys churn under load
(fdbserver/workloads/RandomMoveKeys.actor.cpp: the reference moves
random ranges to random teams while other workloads run, proving the
MoveKeys dance and data distribution survive concurrent interference).

Two modes:

* ``mode=random`` — the reference's shape: every interval, move one
  randomly chosen shard onto a randomly chosen OTHER serving team.
* ``mode=pileup`` — the anti-balancer: watch the sampled shard-load
  plane (dd.shard_load, the same waitMetrics-style poll DD itself uses)
  and move the busiest shard that is NOT on the hottest shard's team
  onto that team.  This manufactures exactly the imbalance the
  hot-shard relocation loop exists to undo — two busy shards on one
  team, a cooler team elsewhere — so a chaos spec composing this with
  skewed load deterministically drives dd.hot_shard_detected /
  dd.hot_shard_relocate instead of hoping churn lines up.

Moves go through the DataDistributor's own move_range (the two-phase
MoveKeys path), so they serialize against splits/heals on the _moving
mutex; a refused move (mover busy, mid-recovery) just retries next
interval.
"""

from __future__ import annotations

from .base import Workload
from ..runtime.coverage import testcov


class RandomMoveKeysWorkload(Workload):
    description = "RandomMoveKeys"

    def __init__(
        self,
        mode: str = "random",
        moves: int = 2,
        interval: float = 1.0,
        duration: float = 10.0,
        start_delay: float = 0.0,
        min_bytes_per_ksec: float = 1000.0,
    ):
        if mode not in ("random", "pileup"):
            raise ValueError(f"mode must be random|pileup, got {mode!r}")
        self.mode = mode
        self.moves = moves
        self.interval = interval
        self.duration = duration
        self.start_delay = start_delay
        # pileup only piles shards that actually carry sampled traffic —
        # moving idle shards would not create a relocatable imbalance
        self.min_bytes_per_ksec = min_bytes_per_ksec
        self.moved = 0
        self.refused = 0

    def _plan(self, load: list[dict], rng):
        """-> (begin, end, dest_team) or None when no move applies."""
        if self.mode == "random":
            i = rng.random_int(0, len(load))
            src = set(load[i]["team"])
            others = [m["team"] for m in load if set(m["team"]) != src]
            if not others:
                return None
            dest = others[rng.random_int(0, len(others))]
            return load[i]["begin"], load[i]["end"], list(dest)
        combined = [
            m["bytes_read_per_ksec"] + m["bytes_written_per_ksec"]
            for m in load
        ]
        order = sorted(range(len(load)), key=lambda i: -combined[i])
        hot = order[0]
        hot_team = set(load[hot]["team"])
        victim = next(
            (
                j for j in order[1:]
                if set(load[j]["team"]) != hot_team
                and combined[j] >= self.min_bytes_per_ksec
            ),
            None,
        )
        if victim is None or combined[hot] < self.min_bytes_per_ksec:
            return None
        return (
            load[victim]["begin"], load[victim]["end"],
            list(load[hot]["team"]),
        )

    async def start(self, cluster, rng) -> None:
        dd = cluster.dd
        loop = cluster.loop
        if self.start_delay > 0:
            await loop.delay(self.start_delay)
        t_end = loop.now() + self.duration
        while loop.now() < t_end and self.moved < self.moves:
            await loop.delay(self.interval)
            cc = cluster.controller
            if cc.generation is None or cc._recovering:
                continue
            try:
                load = dd.shard_load()
            except KeyError:
                continue  # keyServers map churn mid-poll; retry
            if len(load) < 2:
                continue
            plan = self._plan(load, rng)
            if plan is None:
                continue
            b, e, dest = plan
            try:
                ok = await dd.move_range(b, e, dest)
            except IOError:
                continue  # disk fault plane refused; retry next interval
            if ok:
                self.moved += 1
                testcov("workload.random_move")
            else:
                self.refused += 1

    async def check(self, cluster, rng) -> bool:
        # the workload is interference, not an invariant: refusals are
        # legitimate (mover busy, recovery), so nothing to assert beyond
        # having survived
        return True

    def metrics(self) -> dict:
        return {"moves": self.moved, "refused": self.refused}
