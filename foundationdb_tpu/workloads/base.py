"""Workload API — the TestWorkload pattern
(fdbserver/workloads/workloads.h:55-74: description/setup/start/check,
composed concurrently by the tester and checked after a quiet period).

A workload runs against a SimCluster's database; `run_workloads` composes
several concurrently (the reference composes e.g. Cycle + RandomClogging +
Attrition in one spec), waits for all `start` phases, then runs every
`check` — the post-condition gate."""

from __future__ import annotations

from ..cluster import SimCluster
from ..runtime.combinators import wait_all
from ..runtime.core import DeterministicRandom


class Workload:
    description = "workload"
    # part 2 of a restarting pair runs invariant workloads with
    # `runSetup=false` (the reference's restarting-spec convention): the
    # data under test is what RODE THE REBOOT, and a re-run setup would
    # overwrite it with a pristine copy that proves nothing
    run_setup = True

    async def setup(self, cluster: SimCluster, rng: DeterministicRandom) -> None:
        pass

    async def start(self, cluster: SimCluster, rng: DeterministicRandom) -> None:
        raise NotImplementedError

    async def check(self, cluster: SimCluster, rng: DeterministicRandom) -> bool:
        return True

    def metrics(self) -> dict:
        return {}

    def restart_state(self) -> dict:
        """Invariant-shaping config a restart manifest records (the Cycle
        ring size, the Bank total): part 2 refuses to boot when its
        same-named workload declares different values — it would check
        the wrong invariant against the saved disks."""
        return {}

    def load_restart_manifest(self, manifest: dict) -> None:
        """Part-2 hook: run_spec hands each workload the restart manifest
        (including `part1_metrics`, what part 1's workloads had actually
        achieved at the kill) before the run.  A verify-mode workload can
        anchor its checks to part 1's recorded progress — e.g. KillRegion
        requires the rebooted watermark to cover every commit part 1 had
        ACKNOWLEDGED, instead of guessing how far part 1 got before the
        buggify-jittered power kill landed."""


def run_workloads(
    cluster: SimCluster, workloads: list[Workload], deadline: float = 300.0
) -> dict:
    """Run setup → concurrent starts → checks; returns merged metrics.
    Raises AssertionError if any check fails."""
    rng = cluster.rng.split()

    async def driver():
        for w in workloads:
            if not w.run_setup:
                from ..runtime.coverage import testcov

                testcov("restart.setup_skipped")
                continue
            await w.setup(cluster, rng.split())
        await wait_all(
            [cluster.loop.spawn(w.start(cluster, rng.split())) for w in workloads]
        )
        results = {}
        for w in workloads:
            ok = await w.check(cluster, rng.split())
            assert ok, f"workload check failed: {w.description}"
            results[w.description] = w.metrics()
        return results

    return cluster.run_until(cluster.loop.spawn(driver()), deadline)
