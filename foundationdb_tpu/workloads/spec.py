"""Spec-file-driven simulation runs — the tests/fast/*.txt analog
(fdbserver/tester.actor.cpp:848 readTests; the reference composes
workloads from key=value stanzas and runs them against a simulated
cluster, e.g. tests/fast/CycleTest.txt = Cycle + RandomClogging +
Attrition concurrently).

Format (one file = one simulation):

    testTitle=CycleWithChaos
    ; cluster parameters (optional, defaults in brackets)
    seed=7
    shards=2
    replication=2
    machines=4
    chaos=true

    testName=Cycle
    nodes=8
    clients=2
    txnsPerClient=6

    testName=Attrition
    kills=1
    interval=2.0

`testName` opens a workload stanza; parameters until the next `testName`
are constructor kwargs (camelCase -> snake_case), except `runSetup=false`
which skips the workload's setup phase (the restarting-pair part-2
convention: the data under test rode the reboot).  Everything before the
first `testName` configures the cluster — including `backend=supervised`
(the DeviceSupervisor-wrapped TPU/XLA conflict backend), `sampleRate=R`
(transaction-timeline sampling into the trace files), and
`knob.NAME=value` lines (the reference's per-test --knob_ overrides:
applied via set_knob after knob construction, so they compose with chaos
randomization and unknown names fail loudly — e.g. the PageCacheChaos
spec shrinks PAGE_CACHE_BYTES / BTREE_CACHE_BYTES to stress the cache).
`run_spec` builds the cluster, composes the workloads, runs them, and
returns the metrics dict; its seed/trace_sink/sample_rate keywords are
the per-seed artifact hooks the soak harness (tools/soak.py) drives, and
teardown emits the run's buggify/testcov census as `CodeCoverage` trace
events.

Restarting pairs (tests/restarting/CycleTestRestart-{1,2}.txt in the
reference): `<stem>-1.txt` composes a `SaveAndKill` stanza that
power-kills the whole sim and saves its disk image + manifest;
`<stem>-2.txt` boots a second process-lifetime from that image
(`run_spec(..., restart_image=dir)`) and re-runs the invariant checks.
`run_restarting_pair` drives both halves as one seeded unit and
`resolve_pair` finds the pair from either half or the bare stem.  Part 2
REFUSES to boot when its declared seed or disk-shaping cluster config
mismatches part 1's manifest, or when a same-named workload declares
different invariant state (`Workload.restart_state`)."""

from __future__ import annotations

import inspect
import json
import os
import re

from .attrition import AttritionWorkload
from .bank import BankWorkload
from .base import run_workloads
from .blob_backup import BlobBackupWorkload
from .configure_db import ConfigureDatabaseWorkload
from .conflict_range import ConflictRangeWorkload
from .consistency import ConsistencyCheckWorkload
from .cycle import CycleWorkload
from .device_fault import DeviceFaultWorkload
from .disk_swizzle import DiskSwizzleWorkload
from .low_space import LowSpaceWorkload
from .fuzzapi import FuzzApiWorkload
from .increment import IncrementWorkload
from .kill_region import KillRegionWorkload
from .random_move import RandomMoveKeysWorkload
from .readwrite import ReadWriteWorkload
from .rollback import RollbackWorkload
from .save_and_kill import RestartKill, SaveAndKillWorkload, invariant_states
from .selector_oracle import SelectorOracleWorkload
from .serializability import SerializabilityWorkload
from .swizzle import SwizzleWorkload
from .write_during_read import WriteDuringReadWorkload

# WorkloadFactory (workloads.h:55 registration): spec testName -> class
WORKLOAD_FACTORY = {
    "Cycle": CycleWorkload,
    "Bank": BankWorkload,
    "Increment": IncrementWorkload,
    "Attrition": AttritionWorkload,
    "ConsistencyCheck": ConsistencyCheckWorkload,
    "ConflictRange": ConflictRangeWorkload,
    "Serializability": SerializabilityWorkload,
    "FuzzApi": FuzzApiWorkload,
    "ConfigureDatabase": ConfigureDatabaseWorkload,
    "ReadWrite": ReadWriteWorkload,
    "RandomMoveKeys": RandomMoveKeysWorkload,
    "Swizzle": SwizzleWorkload,
    "WriteDuringRead": WriteDuringReadWorkload,
    "DeviceFault": DeviceFaultWorkload,
    "DiskSwizzle": DiskSwizzleWorkload,
    "LowSpace": LowSpaceWorkload,
    "SelectorOracle": SelectorOracleWorkload,
    "SaveAndKill": SaveAndKillWorkload,
    "Rollback": RollbackWorkload,
    "KillRegion": KillRegionWorkload,
    "BlobBackup": BlobBackupWorkload,
}

# spec key -> RecoverableCluster kwarg
_CLUSTER_KEYS = {
    "seed": ("seed", int),
    "shards": ("n_storage_shards", int),
    "replication": ("storage_replication", int),
    "machines": ("n_machines", int),
    "dcs": ("n_dcs", int),
    "workers": ("n_workers", int),
    "tlogs": ("n_tlogs", int),
    "proxies": ("n_proxies", int),
    "resolvers": ("n_resolvers", int),
    "engine": ("storage_engine", str),
    "redundancy": ("redundancy", str),
    "chaos": ("chaos", "bool"),
    # region-configuration bootstrap (control/region.py): 2 builds the
    # remote plane (log router + remote replicas) from birth
    "usableRegions": ("usable_regions", int),
    # fraction of transactions given a pipeline-timeline debug ID — the
    # per-seed trace-artifact hook (soak campaigns override per run)
    "sampleRate": ("debug_sample_rate", float),
    # conflict backend by name: "oracle" (default) or "supervised" (the
    # DeviceSupervisor-wrapped TPU/XLA kernel — required for device.*
    # buggify sites to mean anything); resolved in run_spec
    "backend": ("backend", str),
}

# cluster kwargs that SHAPE THE DISK IMAGE (file names, shard layout,
# replica placement, recovery seeding): part 2 of a restarting pair must
# match part 1's manifest on these or refuse to boot — booting different
# values against the saved disks checks the wrong cluster's invariants
_IMAGE_KEYS = (
    "seed", "n_storage_shards", "storage_replication", "n_tlogs",
    "n_machines", "n_dcs", "storage_engine", "redundancy",
    # shapes the disks (remote<i>.kv files + which serving set the saved
    # keyServers map can name), so a pair must agree on it
    "usable_regions",
)

# spec `backend=` values -> conflict-backend factories
_BACKENDS = {
    "oracle": None,
}


def _supervised_backend(oldest: int = 0):
    from ..conflict.device import DeviceConflictSet
    from ..conflict.supervisor import DeviceSupervisor

    return DeviceSupervisor(
        lambda o=0: DeviceConflictSet(o, capacity=1 << 10),
        oldest_version=oldest,
    )


_BACKENDS["supervised"] = _supervised_backend


def _parse_bool(v: str) -> bool:
    if v.lower() not in ("true", "false"):
        raise ValueError(f"expected true/false, got {v!r}")
    return v.lower() == "true"


def _snake(name: str) -> str:
    return re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name).lower()


def _coerce(v: str):
    for conv in (int, float):
        try:
            return conv(v)
        except ValueError:
            continue
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


def parse_spec(text: str) -> tuple[str, dict, list[tuple[str, dict]]]:
    """-> (title, cluster_kwargs, [(workload_name, kwargs), ...])"""
    title = "untitled"
    cluster_kwargs: dict = {}
    stanzas: list[tuple[str, dict]] = []
    current: dict | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith((";", "#")):
            continue
        if "=" not in line:
            raise ValueError(f"line {lineno}: expected key=value, got {line!r}")
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if key == "testTitle":
            title = val
        elif key == "testName":
            if val not in WORKLOAD_FACTORY:
                raise ValueError(
                    f"line {lineno}: unknown workload {val!r}; "
                    f"registered: {sorted(WORKLOAD_FACTORY)}"
                )
            current = {}
            stanzas.append((val, current))
        elif current is not None:
            current[_snake(key)] = _coerce(val)
        elif key in _CLUSTER_KEYS:
            kw, conv = _CLUSTER_KEYS[key]
            try:
                cluster_kwargs[kw] = (
                    _parse_bool(val) if conv == "bool" else conv(val)
                )
            except ValueError as e:
                raise ValueError(f"line {lineno}: {key}: {e}") from None
        elif key.startswith("knob."):
            # the reference's per-test knob override lines (--knob_ path):
            # applied via set_knob after knob construction, so they compose
            # with chaos randomization and unknown names fail loudly
            cluster_kwargs.setdefault("knob_overrides", {})[key[5:]] = val
        else:
            raise ValueError(
                f"line {lineno}: unknown cluster key {key!r} "
                f"(known: {sorted(_CLUSTER_KEYS)})"
            )
    if not stanzas:
        raise ValueError("spec has no testName stanza")
    return title, cluster_kwargs, stanzas


def _cluster_default(kwarg: str):
    """RecoverableCluster's own signature default for `kwarg` — the value
    a spec that omits the key effectively ran with (mismatch checks must
    compare EFFECTIVE config, not declared-key sets)."""
    from ..control.recoverable import RecoverableCluster

    return inspect.signature(RecoverableCluster.__init__).parameters[kwarg].default


def _check_part2_config(cluster_kwargs: dict, manifest: dict) -> dict:
    """Validate part 2's declared config against part 1's manifest and
    return the merged cluster kwargs part 2 boots with: image-shaping
    keys come from the manifest (declared part-2 values must MATCH),
    everything else is part 1's value unless part 2 overrides it."""
    part1 = dict(manifest.get("cluster", {}))
    part1.pop("backend", None)
    for key in _IMAGE_KEYS:
        if key not in cluster_kwargs:
            continue
        effective1 = part1.get(key, _cluster_default(key))
        if cluster_kwargs[key] != effective1:
            raise ValueError(
                f"restarting-pair mismatch: part 2 declares {key}="
                f"{cluster_kwargs[key]!r} but part 1 ran with "
                f"{effective1!r} (the saved disks belong to part 1's "
                f"config; fix the -2 spec or re-save the image)"
            )
    merged = dict(part1)
    merged.update(
        {k: v for k, v in cluster_kwargs.items() if k not in _IMAGE_KEYS}
    )
    return merged


def _check_restart_states(workloads, saved_states: dict) -> None:
    """Part 2's same-named-workload drift check.  Saved shape: name ->
    ORDERED list of states, one per part-1 stanza (save_and_kill.py
    invariant_states); compare positionally among same-named stanzas so
    duplicates don't collapse.  Every saved stanza must be covered — a
    part-2 spec that DROPS a workload whose data rode the reboot would
    pass while checking nothing.  Extra part-2 stanzas (new checks) are
    fine.  Live states go through the same JSON round-trip the manifest
    did, so JSON-equivalent values (tuples vs lists) never refuse a
    matching pair."""

    def canon(state):
        return json.loads(json.dumps(state, default=str))

    declared = {name: [canon(s) for s in states]
                for name, states in invariant_states(workloads).items()}
    for name, saved in sorted(saved_states.items()):
        got = declared.get(name, [])
        if len(got) < len(saved):
            raise ValueError(
                f"restarting-pair mismatch: part 1 saved invariant state "
                f"for {len(saved)} {name} stanza(s) but part 2 declares "
                f"{len(got)} — every ring/ledger that rode the reboot "
                f"must be re-checked"
            )
        for i, s in enumerate(saved):
            if got[i] != s:
                raise ValueError(
                    f"restarting-pair mismatch: {name} declares invariant "
                    f"state {got[i]} but part 1 saved {s}"
                )


def run_spec(text: str, deadline: float = 900.0, *, seed: int | None = None,
             trace_sink=None, sample_rate: float | None = None,
             save_dir: str | None = None,
             restart_image: str | None = None) -> dict:
    """Parse, build the cluster, compose the workloads, run, check.

    The keyword hooks are the per-seed artifact surface soak campaigns
    drive (tools/soak.py): `seed` overrides the spec's cluster seed (the
    campaign's seed matrix beats the file's fixed value), `trace_sink`
    streams the run's trace events into rolling files, and `sample_rate`
    overrides the spec's `sampleRate` so every seed carries joinable
    transaction timelines.  At teardown — pass OR fail — the run's
    buggify/testcov census is emitted into the trace stream as
    `CodeCoverage` events (runtime/{buggify,coverage}.py), which is how
    coverage crosses the process boundary to the campaign driver.

    Restarting-pair hooks: `save_dir` is where a SaveAndKill stanza lands
    its disk image + manifest (part 1 returns phase-1 metrics with
    `restart_image` set instead of running checks); `restart_image` boots
    THIS run from a saved image (part 2) after refusing seed/config/
    invariant-state mismatches against its manifest."""
    from ..control.recoverable import RecoverableCluster
    from ..runtime import buggify, coverage
    from ..runtime.coverage import testcov
    from ..storage.image import load_image, restore_filesystem

    title, cluster_kwargs, stanzas = parse_spec(text)
    backend_declared = "backend" in cluster_kwargs
    backend = cluster_kwargs.pop("backend", "oracle")
    if backend not in _BACKENDS:
        raise ValueError(
            f"unknown backend {backend!r} (known: {sorted(_BACKENDS)})"
        )
    if seed is not None:
        cluster_kwargs["seed"] = seed
    if sample_rate is not None:
        cluster_kwargs["debug_sample_rate"] = sample_rate

    # the census baseline must predate load_image: part 2's
    # restart.image_loaded hit belongs to THIS run's coverage delta
    cov_base = coverage.snapshot()
    restart_manifest = None
    restored_fs = None
    if restart_image is not None:
        files, restart_manifest = load_image(restart_image)
        if not backend_declared:
            backend = restart_manifest.get("cluster", {}).get("backend", "oracle")
            if backend not in _BACKENDS:
                # a version-skewed manifest must fail with the same
                # diagnostic a bad spec gets, not a KeyError later
                raise ValueError(
                    f"unknown backend {backend!r} in restart manifest "
                    f"(known: {sorted(_BACKENDS)})"
                )
        cluster_kwargs = _check_part2_config(cluster_kwargs, restart_manifest)
        restored_fs = restore_filesystem(files)

    # what the restart manifest records (serializable names, not factories)
    manifest_cluster = dict(cluster_kwargs, backend=backend)

    c_kwargs = dict(cluster_kwargs)
    if _BACKENDS[backend] is not None:
        c_kwargs["conflict_backend"] = _BACKENDS[backend]
    if restored_fs is not None:
        c_kwargs["fs"] = restored_fs
        c_kwargs["restart"] = True
    c = RecoverableCluster(trace_sink=trace_sink, **c_kwargs)
    try:
        workloads = []
        for name, kw in stanzas:
            kw = dict(kw)
            run_setup = kw.pop("run_setup", True)
            if not isinstance(run_setup, bool):
                # a typo'd runSetup=no would bool() truthy and re-fill the
                # ring part 2 exists to check — refuse, don't guess
                raise ValueError(
                    f"{name}: runSetup expects true/false, "
                    f"got {run_setup!r}"
                )
            w = WORKLOAD_FACTORY[name](**kw)
            w.run_setup = run_setup
            workloads.append(w)
        if restart_manifest is not None:
            _check_restart_states(workloads,
                                  restart_manifest.get("workloads", {}))
            for w in workloads:
                w.load_restart_manifest(restart_manifest)
            testcov("restart.booted_from_image")
            c.trace.trace("RestartFromImage", Image=restart_image,
                          Seed=cluster_kwargs.get("seed", 0),
                          KilledAt=restart_manifest.get("killed_at"))
        for w in workloads:
            if isinstance(w, SaveAndKillWorkload):
                if save_dir is None:
                    save_dir = _default_image_dir()
                w.bind(
                    save_dir=save_dir,
                    manifest={
                        "title": title,
                        "seed": cluster_kwargs.get("seed", 0),
                        "cluster": manifest_cluster,
                        "stanzas": [[n, kw] for n, kw in stanzas],
                    },
                    co_workloads=workloads,
                )
        try:
            metrics = run_workloads(c, workloads, deadline=deadline)
        except RestartKill as rk:
            # part 1 of a restarting pair: the sim power-killed itself on
            # purpose; checks belong to part 2's process lifetime
            metrics = {
                w.description: w.metrics() for w in workloads
            }
            metrics["phase"] = 1
            metrics["restart_image"] = rk.image_dir
        metrics["testTitle"] = title
        metrics["seed"] = cluster_kwargs.get("seed", 0)
        return metrics
    finally:
        # census emission must precede stop()/disable(): disabling clears
        # the buggify census, and the collector's sink is what carries
        # coverage to a cross-process campaign driver
        buggify.emit_coverage(c.trace)
        coverage.emit_coverage(c.trace, baseline=cov_base)
        c.stop()
        buggify.disable()


def run_spec_file(path: str, deadline: float = 900.0, *,
                  seed: int | None = None, trace_sink=None,
                  sample_rate: float | None = None,
                  save_dir: str | None = None,
                  restart_image: str | None = None) -> dict:
    """Run one spec file — or a whole restarting pair, auto-discovered
    when `path` is a bare pair stem or either half (`Name-1.txt` /
    `Name-2.txt`) and the caller passed no save_dir/restart_image (those
    kwargs mean a driver like run_restarting_pair is running the halves
    itself)."""
    if save_dir is None and restart_image is None and should_run_pair(path):
        return run_restarting_pair(
            path, deadline=deadline, seed=seed, trace_sink=trace_sink,
            sample_rate=sample_rate,
        )
    with open(path) as f:
        return run_spec(f.read(), deadline=deadline, seed=seed,
                        trace_sink=trace_sink, sample_rate=sample_rate,
                        save_dir=save_dir, restart_image=restart_image)


# ---------------------------------------------------------------------------
# restarting pairs


def _default_image_dir() -> str:
    """Where a restart image lands when the caller named no directory:
    FDBTPU_RESTART_DIR, else a fresh temp dir — never a CWD-relative path
    derived from the spec title (titles are arbitrary text)."""
    d = os.environ.get("FDBTPU_RESTART_DIR")
    if d is None:
        import tempfile

        d = tempfile.mkdtemp(prefix="fdbtpu-restart-")
    return d


def pair_stem(path: str) -> str:
    """The ONE encoding of the pairing convention: strip `.txt` and a
    trailing `-1`/`-2` to get the stem shared by both halves (and by the
    pair's `<stem>.coverage` manifest)."""
    base = path[:-4] if path.endswith(".txt") else path
    if base.endswith(("-1", "-2")):
        base = base[:-2]
    return base


def should_run_pair(path: str) -> bool:
    """Whether a runner given `path` should substitute the whole pair:
    only when the path does not name an existing standalone spec, or is
    itself a pair half — an explicitly named, existing spec always runs
    as itself even if a same-stem pair coexists."""
    return (not os.path.exists(path)
            or path.endswith(("-1.txt", "-2.txt"))) and is_restarting_pair(path)


def resolve_pair(path: str) -> tuple[str, str]:
    """Find a restarting pair from either half or the bare stem:
    `Name-1.txt`, `Name-2.txt`, `Name.txt`, or `Name` all resolve to
    (`Name-1.txt`, `Name-2.txt`).  Raises FileNotFoundError when either
    half is missing — half a restarting test is not a test."""
    base = pair_stem(path)
    p1, p2 = base + "-1.txt", base + "-2.txt"
    missing = [p for p in (p1, p2) if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"restarting pair incomplete for {path!r}: missing "
            f"{', '.join(missing)}"
        )
    return p1, p2


def is_restarting_pair(path: str) -> bool:
    """A restarting pair is two same-stem halves whose -1 half actually
    contains a SaveAndKill stanza — filename shape alone is not enough,
    or two unrelated standalone specs that happen to be named Foo-1.txt
    and Foo-2.txt would be hijacked into a bogus pair run (and their own
    coverage manifests silently dropped)."""
    try:
        p1, _p2 = resolve_pair(path)
    except FileNotFoundError:
        return False
    try:
        with open(p1) as f:
            _title, _ck, stanzas = parse_spec(f.read())
    except (OSError, ValueError, KeyError):
        return False  # a half that does not parse is not half a pair
    return any(name == "SaveAndKill" for name, _kw in stanzas)


def run_restarting_pair(path: str, deadline: float = 900.0, *,
                        seed: int | None = None, trace_sink=None,
                        sample_rate: float | None = None,
                        image_dir: str | None = None) -> dict:
    """Both halves of a restarting pair as ONE seeded unit (how the soak
    harness runs them: same worker, shared artifact dir, one trace sink so
    triage joins part-1/part-2 timelines).  Part 1 runs to its SaveAndKill
    power-kill and saves the image under `image_dir`; part 2 boots from it
    and runs the invariant checks.  `seed` overrides BOTH halves (so the
    manifest seed check still passes) — the campaign seed matrix never
    forks the pair."""
    p1, p2 = resolve_pair(path)
    # a temp dir WE made is ours to delete once part 2 consumed it; a
    # directory the caller (or FDBTPU_RESTART_DIR) named is theirs, and a
    # FAILED pair keeps its image either way — it is the triage artifact
    ephemeral = image_dir is None and "FDBTPU_RESTART_DIR" not in os.environ
    if image_dir is None:
        image_dir = _default_image_dir()

    def discard_ephemeral() -> None:
        if ephemeral:
            import shutil

            shutil.rmtree(image_dir, ignore_errors=True)

    try:
        m1 = run_spec_file(p1, deadline=deadline, seed=seed,
                           trace_sink=trace_sink, sample_rate=sample_rate,
                           save_dir=image_dir)
    except BaseException:
        from ..storage.image import MANIFEST

        if not os.path.exists(os.path.join(image_dir, MANIFEST)):
            # part 1 died before SaveAndKill completed a save: the temp
            # dir holds no image, so there is nothing to keep for triage
            discard_ephemeral()
        raise
    if "restart_image" not in m1:
        discard_ephemeral()  # nothing saved
        raise ValueError(
            f"{p1} ran to completion without a SaveAndKill power-kill — "
            f"not a part-1 restarting spec"
        )
    image = m1["restart_image"]
    m2 = run_spec_file(p2, deadline=deadline, seed=seed,
                       trace_sink=trace_sink, sample_rate=sample_rate,
                       restart_image=image)
    if "restart_image" in m2:
        # part 2 power-killed ITSELF (a SaveAndKill stanza copied into
        # the -2 spec): every check was skipped, so this is not a green
        # pair — it is a part-2 spec that never checked anything
        raise ValueError(
            f"{p2} ended in a SaveAndKill power-kill of its own — part 2 "
            f"of a restarting pair must run checks, not kill again"
        )
    # a FAILED pair (either half raising after the save) keeps its image
    # for triage; a passing one has no consumer left, so delete a temp
    # dir and report no path rather than one that no longer exists
    discard_ephemeral()
    if ephemeral:
        image = None
    return {
        "testTitle": m2.get("testTitle", m1.get("testTitle")),
        "seed": m1.get("seed", 0),
        "restart_image": image,
        "part1": m1,
        "part2": m2,
    }
