"""Spec-file-driven simulation runs — the tests/fast/*.txt analog
(fdbserver/tester.actor.cpp:848 readTests; the reference composes
workloads from key=value stanzas and runs them against a simulated
cluster, e.g. tests/fast/CycleTest.txt = Cycle + RandomClogging +
Attrition concurrently).

Format (one file = one simulation):

    testTitle=CycleWithChaos
    ; cluster parameters (optional, defaults in brackets)
    seed=7
    shards=2
    replication=2
    machines=4
    chaos=true

    testName=Cycle
    nodes=8
    clients=2
    txnsPerClient=6

    testName=Attrition
    kills=1
    interval=2.0

`testName` opens a workload stanza; parameters until the next `testName`
are constructor kwargs (camelCase -> snake_case).  Everything before the
first `testName` configures the cluster — including `backend=supervised`
(the DeviceSupervisor-wrapped TPU/XLA conflict backend) and
`sampleRate=R` (transaction-timeline sampling into the trace files).
`run_spec` builds the cluster, composes the workloads, runs them, and
returns the metrics dict; its seed/trace_sink/sample_rate keywords are
the per-seed artifact hooks the soak harness (tools/soak.py) drives, and
teardown emits the run's buggify/testcov census as `CodeCoverage` trace
events."""

from __future__ import annotations

import re

from .attrition import AttritionWorkload
from .bank import BankWorkload
from .base import run_workloads
from .configure_db import ConfigureDatabaseWorkload
from .conflict_range import ConflictRangeWorkload
from .consistency import ConsistencyCheckWorkload
from .cycle import CycleWorkload
from .device_fault import DeviceFaultWorkload
from .fuzzapi import FuzzApiWorkload
from .increment import IncrementWorkload
from .readwrite import ReadWriteWorkload
from .selector_oracle import SelectorOracleWorkload
from .serializability import SerializabilityWorkload
from .swizzle import SwizzleWorkload
from .write_during_read import WriteDuringReadWorkload

# WorkloadFactory (workloads.h:55 registration): spec testName -> class
WORKLOAD_FACTORY = {
    "Cycle": CycleWorkload,
    "Bank": BankWorkload,
    "Increment": IncrementWorkload,
    "Attrition": AttritionWorkload,
    "ConsistencyCheck": ConsistencyCheckWorkload,
    "ConflictRange": ConflictRangeWorkload,
    "Serializability": SerializabilityWorkload,
    "FuzzApi": FuzzApiWorkload,
    "ConfigureDatabase": ConfigureDatabaseWorkload,
    "ReadWrite": ReadWriteWorkload,
    "Swizzle": SwizzleWorkload,
    "WriteDuringRead": WriteDuringReadWorkload,
    "DeviceFault": DeviceFaultWorkload,
    "SelectorOracle": SelectorOracleWorkload,
}

# spec key -> RecoverableCluster kwarg
_CLUSTER_KEYS = {
    "seed": ("seed", int),
    "shards": ("n_storage_shards", int),
    "replication": ("storage_replication", int),
    "machines": ("n_machines", int),
    "dcs": ("n_dcs", int),
    "workers": ("n_workers", int),
    "tlogs": ("n_tlogs", int),
    "proxies": ("n_proxies", int),
    "resolvers": ("n_resolvers", int),
    "engine": ("storage_engine", str),
    "redundancy": ("redundancy", str),
    "chaos": ("chaos", "bool"),
    # fraction of transactions given a pipeline-timeline debug ID — the
    # per-seed trace-artifact hook (soak campaigns override per run)
    "sampleRate": ("debug_sample_rate", float),
    # conflict backend by name: "oracle" (default) or "supervised" (the
    # DeviceSupervisor-wrapped TPU/XLA kernel — required for device.*
    # buggify sites to mean anything); resolved in run_spec
    "backend": ("backend", str),
}

# spec `backend=` values -> conflict-backend factories
_BACKENDS = {
    "oracle": None,
}


def _supervised_backend(oldest: int = 0):
    from ..conflict.device import DeviceConflictSet
    from ..conflict.supervisor import DeviceSupervisor

    return DeviceSupervisor(
        lambda o=0: DeviceConflictSet(o, capacity=1 << 10),
        oldest_version=oldest,
    )


_BACKENDS["supervised"] = _supervised_backend


def _parse_bool(v: str) -> bool:
    if v.lower() not in ("true", "false"):
        raise ValueError(f"expected true/false, got {v!r}")
    return v.lower() == "true"


def _snake(name: str) -> str:
    return re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name).lower()


def _coerce(v: str):
    for conv in (int, float):
        try:
            return conv(v)
        except ValueError:
            continue
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


def parse_spec(text: str) -> tuple[str, dict, list[tuple[str, dict]]]:
    """-> (title, cluster_kwargs, [(workload_name, kwargs), ...])"""
    title = "untitled"
    cluster_kwargs: dict = {}
    stanzas: list[tuple[str, dict]] = []
    current: dict | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith((";", "#")):
            continue
        if "=" not in line:
            raise ValueError(f"line {lineno}: expected key=value, got {line!r}")
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if key == "testTitle":
            title = val
        elif key == "testName":
            if val not in WORKLOAD_FACTORY:
                raise ValueError(
                    f"line {lineno}: unknown workload {val!r}; "
                    f"registered: {sorted(WORKLOAD_FACTORY)}"
                )
            current = {}
            stanzas.append((val, current))
        elif current is not None:
            current[_snake(key)] = _coerce(val)
        elif key in _CLUSTER_KEYS:
            kw, conv = _CLUSTER_KEYS[key]
            try:
                cluster_kwargs[kw] = (
                    _parse_bool(val) if conv == "bool" else conv(val)
                )
            except ValueError as e:
                raise ValueError(f"line {lineno}: {key}: {e}") from None
        else:
            raise ValueError(
                f"line {lineno}: unknown cluster key {key!r} "
                f"(known: {sorted(_CLUSTER_KEYS)})"
            )
    if not stanzas:
        raise ValueError("spec has no testName stanza")
    return title, cluster_kwargs, stanzas


def run_spec(text: str, deadline: float = 900.0, *, seed: int | None = None,
             trace_sink=None, sample_rate: float | None = None) -> dict:
    """Parse, build the cluster, compose the workloads, run, check.

    The keyword hooks are the per-seed artifact surface soak campaigns
    drive (tools/soak.py): `seed` overrides the spec's cluster seed (the
    campaign's seed matrix beats the file's fixed value), `trace_sink`
    streams the run's trace events into rolling files, and `sample_rate`
    overrides the spec's `sampleRate` so every seed carries joinable
    transaction timelines.  At teardown — pass OR fail — the run's
    buggify/testcov census is emitted into the trace stream as
    `CodeCoverage` events (runtime/{buggify,coverage}.py), which is how
    coverage crosses the process boundary to the campaign driver."""
    from ..control.recoverable import RecoverableCluster
    from ..runtime import buggify, coverage

    title, cluster_kwargs, stanzas = parse_spec(text)
    backend = cluster_kwargs.pop("backend", "oracle")
    if backend not in _BACKENDS:
        raise ValueError(
            f"unknown backend {backend!r} (known: {sorted(_BACKENDS)})"
        )
    if _BACKENDS[backend] is not None:
        cluster_kwargs["conflict_backend"] = _BACKENDS[backend]
    if seed is not None:
        cluster_kwargs["seed"] = seed
    if sample_rate is not None:
        cluster_kwargs["debug_sample_rate"] = sample_rate
    cov_base = coverage.snapshot()
    c = RecoverableCluster(trace_sink=trace_sink, **cluster_kwargs)
    try:
        workloads = [WORKLOAD_FACTORY[name](**kw) for name, kw in stanzas]
        metrics = run_workloads(c, workloads, deadline=deadline)
        metrics["testTitle"] = title
        metrics["seed"] = cluster_kwargs.get("seed", 0)
        return metrics
    finally:
        # census emission must precede stop()/disable(): disabling clears
        # the buggify census, and the collector's sink is what carries
        # coverage to a cross-process campaign driver
        buggify.emit_coverage(c.trace)
        coverage.emit_coverage(c.trace, baseline=cov_base)
        c.stop()
        buggify.disable()


def run_spec_file(path: str, deadline: float = 900.0, *,
                  seed: int | None = None, trace_sink=None,
                  sample_rate: float | None = None) -> dict:
    with open(path) as f:
        return run_spec(f.read(), deadline=deadline, seed=seed,
                        trace_sink=trace_sink, sample_rate=sample_rate)
