"""FuzzApiCorrectness — randomized API-call sequences asserting the client
surface never crashes and only throws registered errors
(fdbserver/workloads/FuzzApiCorrectness.actor.cpp + fdbrpc/actorFuzz.py:
generate adversarial call sequences, accept only sanctioned outcomes).

Hammers the transaction API with random ops over adversarial keys (empty,
near-`\\xff`, long, embedded NULs), inverted/empty ranges, zero/huge
limits, atomic ops with odd operand widths, option churn, mid-stream
reset/on_error, snapshot reads — and requires every outcome to be either
success or an error from the sanctioned set.  A final invariant write
proves the database still works afterwards."""

from __future__ import annotations

from .base import Workload
from ..client.transaction import RETRYABLE_ERRORS
from ..roles.types import DatabaseLocked, MutationType
from ..runtime.combinators import wait_all

_SANCTIONED = RETRYABLE_ERRORS + (ValueError, KeyError, DatabaseLocked)

_ATOMICS = [
    MutationType.ADD, MutationType.BIT_AND, MutationType.BIT_OR,
    MutationType.BIT_XOR, MutationType.APPEND_IF_FITS,
    MutationType.MAX_, MutationType.MIN_,
    MutationType.BYTE_MIN, MutationType.BYTE_MAX,
]

_OPTIONS = [b"priority_batch", b"causal_write_risky", b"lock_aware",
            b"priority_system_immediate", b"bogus_option"]


def _fuzz_key(rng) -> bytes:
    kind = rng.random_int(0, 5)
    if kind == 0:
        return b""
    if kind == 1:
        return b"\xfe" + rng.random_bytes(rng.random_int(0, 3))
    if kind == 2:
        return b"fz/" + rng.random_bytes(rng.random_int(0, 40))
    if kind == 3:
        return b"fz/\x00\x00" + bytes([rng.random_int(0, 255)])
    if kind == 4:
        return b"fz/" + b"k" * rng.random_int(0, 200)
    k = rng.random_bytes(rng.random_int(1, 8))
    # stay out of the system keyspace: a fuzz clear_range must never wipe
    # `\xff/conf` (the reference fuzzes a restricted keyspace too)
    return (b"\xfe" + k[1:]) if k >= b"\xff" else k


class FuzzApiWorkload(Workload):
    description = "FuzzApi"

    def __init__(self, clients: int = 3, ops_per_client: int = 120):
        self.clients = clients
        self.ops_per_client = ops_per_client
        self.ops_run = 0
        self.sanctioned_errors = 0

    async def start(self, cluster, rng) -> None:
        db = cluster.database()

        async def client(crng) -> None:
            tr = db.create_transaction()
            for _ in range(self.ops_per_client):
                op = crng.random_int(0, 9)
                self.ops_run += 1
                try:
                    if op == 0:
                        await tr.get(_fuzz_key(crng))
                    elif op == 1:
                        await tr.get(_fuzz_key(crng), snapshot=True)
                    elif op == 2:
                        b, e = _fuzz_key(crng), _fuzz_key(crng)
                        await tr.get_range(
                            b, e, limit=crng.random_choice([0, 1, 7, 100000])
                        )
                    elif op == 3:
                        tr.set(_fuzz_key(crng), crng.random_bytes(crng.random_int(0, 300)))
                    elif op == 4:
                        tr.clear_range(_fuzz_key(crng), _fuzz_key(crng))
                    elif op == 5:
                        tr.atomic_op(
                            crng.random_choice(_ATOMICS), _fuzz_key(crng),
                            crng.random_bytes(crng.random_int(0, 12)),
                        )
                    elif op == 6:
                        tr.set_option(crng.random_choice(_OPTIONS))
                    elif op == 7:
                        tr.reset()
                    elif op == 8:
                        await tr.commit()
                        tr = db.create_transaction()
                    else:
                        await tr.get_read_version()
                except _SANCTIONED as e:  # noqa: PERF203 — the point
                    self.sanctioned_errors += 1
                    if isinstance(e, RETRYABLE_ERRORS):
                        try:
                            await tr.on_error(e)
                        except _SANCTIONED:
                            tr = db.create_transaction()
                    else:
                        tr = db.create_transaction()
            # anything OTHER than a sanctioned error propagates = failure

        await wait_all(
            [cluster.loop.spawn(client(rng.split())) for _ in range(self.clients)]
        )

    async def check(self, cluster, rng) -> bool:
        # the database still works after the fuzz
        db = cluster.database()

        async def fn(tr):
            tr.set(b"fz/alive", b"1")

        await db.run(fn)
        tr = db.create_transaction()
        return await tr.get(b"fz/alive") == b"1"

    def metrics(self) -> dict:
        return {"ops": self.ops_run, "sanctioned_errors": self.sanctioned_errors}
