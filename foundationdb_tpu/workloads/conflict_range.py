"""ConflictRange workload — the OCC abort-parity oracle
(fdbserver/workloads/ConflictRange.actor.cpp; specs
tests/rare/ConflictRangeCheck.txt).

Directly randomizes pairs of transactions with controlled interleaving and
asserts the cluster's OCC verdicts against first-principles expectations:

  tr_B takes its read version, reads range R; tr_A then commits a write W;
  tr_B then writes and commits.  Expected: B aborts iff W ∩ R ≠ ∅.

Because the sim is deterministic and we sequence A's commit strictly
between B's read and B's commit, the expectation is exact — any false
abort or false commit is a resolver bug.  This is the workload-level twin
of the kernel parity tests (tests/test_device.py)."""

from __future__ import annotations

from .base import Workload
from ..roles.types import NotCommitted


class ConflictRangeWorkload(Workload):
    description = "ConflictRange"

    def __init__(self, rounds: int = 40, keyspace: int = 30):
        self.rounds = rounds
        self.keyspace = keyspace
        self.false_aborts = 0
        self.false_commits = 0
        self.checked = 0

    def _rand_range(self, rng) -> tuple[bytes, bytes]:
        a = rng.random_int(0, self.keyspace)
        b = rng.random_int(0, self.keyspace)
        lo, hi = min(a, b), max(a, b) + 1
        return (b"cr/%03d" % lo, b"cr/%03d" % hi)

    async def start(self, cluster, rng) -> None:
        db = cluster.database()
        for _ in range(self.rounds):
            read_range = self._rand_range(rng)
            write_range = self._rand_range(rng)
            overlap = read_range[0] < write_range[1] and write_range[0] < read_range[1]

            tr_b = db.create_transaction()
            await tr_b.get_range(*read_range, snapshot=False)

            tr_a = db.create_transaction()
            tr_a.clear_range(*write_range)  # write conflict over the range
            await tr_a.commit()

            tr_b.set(b"cr/out", b"x")
            aborted = False
            try:
                await tr_b.commit()
            except NotCommitted:
                aborted = True
            self.checked += 1
            if aborted and not overlap:
                self.false_aborts += 1
            if not aborted and overlap:
                self.false_commits += 1

    async def check(self, cluster, rng) -> bool:
        # false commits are serializability violations — never acceptable.
        # false aborts are permitted by OCC in principle, but with this
        # controlled interleaving (no other writers) they indicate a bug too.
        return self.false_commits == 0 and self.false_aborts == 0

    def metrics(self) -> dict:
        return {
            "checked": self.checked,
            "false_aborts": self.false_aborts,
            "false_commits": self.false_commits,
        }
