"""KillRegion workload — power-kill a whole region mid-burst and prove
zero committed-data loss (fdbserver/workloads/KillRegion.actor.cpp: the
reference configures usableRegions, kills a region's datacenter, forces
the failover through `configure`, and checks every acknowledged commit).

The workload is its own committed-model oracle: every burst commits its
keys TOGETHER with a watermark key (`kr/acked`) in one transaction, so
any state the cluster can ever serve is consistent — watermark W implies
keys 0..W-1 present with their deterministic values.  `self.acked` (the
highest burst this process saw acknowledged) must equal the watermark at
check time: an acked commit that vanished would leave W below it.

Two region kills in one run:

  1. the REMOTE region (log router + every remote replica) dies mid-burst
     and is rebooted from its disks (`restart_remote_region`): the
     replacement router re-pulls the retained TLog backlog
     (`region.router_repull`) and the replicas converge exactly,
  2. the PRIMARY storage region dies mid-burst, and failover is driven
     the first-class way — `configure_regions(primary="remote")` — which
     the controller's conf watch reads through the surviving remote
     replica (`region.conf_read_fallback`) and applies as a promotion.
     Commits (blind writes) keep flowing through the outage: the commit
     plane never needed the dead storage, which is exactly the
     region-redundancy claim.

Composable with PR-10 restart pairs: a `-1` spec adds SaveAndKill (kill
the whole sim AFTER the failover, reboot from disk in part 2 with
`action=verify` — the promoted keyServers map and every acked commit
must ride the reboot).

Buggify: `region.kill_point` jitters each kill instant (forced by a
seeded coin under chaos so campaigns explore both timings)."""

from __future__ import annotations

from .base import Workload
from ..runtime.buggify import buggify
from ..runtime.core import TaskPriority
from ..runtime.coverage import testcov

_ACKED_KEY = b"kr/acked"
_KEY_FMT = b"kr/k%06d"


class KillRegionWorkload(Workload):
    description = "KillRegion"

    def __init__(self, keys: int = 48, burst: int = 8,
                 start_delay: float = 0.3, kill_jitter: float = 0.5,
                 cycle_remote: bool = True, action: str = "full") -> None:
        if action not in ("full", "verify"):
            raise ValueError(f"action must be full|verify, got {action!r}")
        self.keys = keys
        self.burst = burst
        self.start_delay = start_delay
        self.kill_jitter = kill_jitter
        self.cycle_remote = cycle_remote
        self.action = action
        self.acked = 0          # highest burst end acknowledged to us
        self.part1_acked = 0    # what part 1 had acked at the power kill
        self.kills: list[str] = []

    def restart_state(self) -> dict:
        return {"keys": self.keys}

    def load_restart_manifest(self, manifest: dict) -> None:
        """Anchor the verify half to part 1's RECORDED progress: every
        commit part 1 acknowledged must be covered by the rebooted
        watermark — on a seed where chaos crawled the commit plane and
        part 1 acked nothing before the kill, the check is vacuous but
        honest, never a guess."""
        m = manifest.get("part1_metrics", {}).get(self.description, {})
        self.part1_acked = int(m.get("acked") or 0)

    @staticmethod
    def _value(i: int) -> bytes:
        return b"v%d" % (i * 7919 + 13)

    async def setup(self, cluster, rng) -> None:
        from ..runtime import buggify as _buggify

        if self.action == "full" and _buggify.is_enabled():
            # deterministic per-seed arming: half a campaign's seeds jitter
            # the kill instants, the other half keep the clean timing
            if rng.coinflip(0.5):
                _buggify.force("region.kill_point", times=2)

    async def _commit_through(self, db, hi: int) -> None:
        lo = self.acked

        async def fn(tr, lo=lo, hi=hi):
            for i in range(lo, hi):
                tr.set(_KEY_FMT % i, self._value(i))
            tr.set(_ACKED_KEY, b"%d" % hi)

        await db.run(fn)  # retrying; on return the commit is ACKNOWLEDGED
        self.acked = hi

    async def _kill_region(self, cluster, rng, region: str) -> None:
        """Power-kill every process in one region at once (the correlated
        loss KillRegion.actor.cpp injects)."""
        if buggify("region.kill_point"):
            # a region does not consult the test plan for a good moment
            await cluster.loop.delay(rng.random() * self.kill_jitter)
        if region == "remote":
            victims = [s.process for s in cluster.remote_storage]
            if cluster.log_router is not None:
                victims.append(cluster.log_router.process)
        else:
            victims = [
                s.process for s in cluster.storage
                if s.tag.startswith("ss-")
            ]
        for p in victims:
            if p.alive:
                p.kill()
        self.kills.append(region)
        testcov("region.kill")
        cluster.trace.trace(
            "RegionKilled", Region=region, Procs=len(victims),
        )

    async def _wait_remote_converged(self, cluster, db) -> None:
        v = [0]

        async def fn(tr):
            v[0] = await tr.get_read_version()

        await db.run(fn)
        for _ in range(4000):
            if all(s.version.get() >= v[0] for s in cluster.remote_storage):
                return
            await cluster.loop.delay(0.05, TaskPriority.DEFAULT_DELAY)
        raise AssertionError("remote region never converged after reboot")

    async def start(self, cluster, rng) -> None:
        if self.action == "verify":
            return  # part 2 of a restarting pair: the data rode the reboot
        assert cluster.remote_storage, (
            "KillRegion needs a two-region cluster (usableRegions=2)"
        )
        from ..client.management import configure_regions

        db = cluster.database()
        await cluster.loop.delay(self.start_delay)
        third = max(1, self.keys // 3)

        # phase 1: burst, then lose and reboot the REMOTE region
        await self._commit_through(db, third)
        if self.cycle_remote:
            await self._kill_region(cluster, rng, "remote")
            await self._commit_through(db, 2 * third)  # mid-outage traffic
            cluster.restart_remote_region()
            await self._wait_remote_converged(cluster, db)
        else:
            await self._commit_through(db, 2 * third)

        # phase 2: lose the PRIMARY storage region mid-burst; failover is
        # configure-driven (the KillRegion.actor.cpp contract)
        await self._kill_region(cluster, rng, "primary")
        await configure_regions(db, usable_regions=2, primary="remote")
        # blind writes keep committing through the outage: the commit
        # plane (proxies/resolvers/TLogs) never needed the dead storage
        await self._commit_through(db, self.keys)
        for _ in range(6000):
            if cluster._region_promoted:
                break
            await cluster.loop.delay(0.05, TaskPriority.DEFAULT_DELAY)
        assert cluster._region_promoted, (
            "configure-driven region failover never completed"
        )
        testcov("region.failover_complete")

    async def check(self, cluster, rng) -> bool:
        db = cluster.database()

        async def fn(tr):
            w = await tr.get(_ACKED_KEY)
            rows = await tr.get_range(b"kr/k", b"kr/l", limit=1 << 20)
            return w, rows

        w, rows = await db.run(fn)
        if w is None:
            # no watermark at all: only legal when nothing was ever acked
            # (a chaos-crawled part 1 killed before its first ack)
            return not rows and self.acked == 0 and self.part1_acked == 0
        watermark = int(w)
        if self.acked and watermark != self.acked:
            # an ACKNOWLEDGED commit did not survive the region loss (or a
            # phantom survived past the kill) — the exact contract violated
            return False
        if watermark < self.part1_acked:
            # part 1 acked further than the rebooted watermark reaches:
            # an acknowledged commit died in the reboot
            return False
        got = dict(rows)
        for i in range(watermark):
            if got.get(_KEY_FMT % i) != self._value(i):
                return False
        return len(got) == watermark

    def metrics(self) -> dict:
        return {"acked": self.acked, "kills": list(self.kills)}
