"""LowSpace workload — fill a storage disk until ratekeeper's free-space
limiting engages, then drain and prove admission recovers (the
storage_server_min_free_space story end to end: the cluster sheds load
BEFORE the disk melts down, and un-sheds when the operator adds space).

The victim is the first storage replica's store disk: its capacity is
clamped so ~35% is free, then a write burst fills it.  The workload
requires the ratekeeper `limit_reason` to pass through `free_space` (or
the e-brake, if the burst outruns the spring) while writing, and to
return to `unlimited` after the drain (capacity lifted + data cleared).
Composed with an invariant workload (Cycle), it also proves shedding
load never corrupts it."""

from __future__ import annotations

from .base import Workload


class LowSpaceWorkload(Workload):
    description = "LowSpace"

    def __init__(self, rows: int = 600, value_bytes: int = 96,
                 start_delay: float = 0.5, free_at_start: float = 0.35):
        self.rows = rows
        self.value_bytes = value_bytes
        self.start_delay = start_delay
        self.free_at_start = free_at_start
        self.reasons_seen: list[str] = []
        self.engaged = False
        self.drained = False

    @staticmethod
    def _store_paths(store) -> list[str]:
        dq = getattr(store, "_dq", None)
        if dq is not None:  # durable memory engine: one WAL file
            return [dq.file.path]
        files = getattr(store, "_files", None)
        if files is not None:  # ssd engine: data files + header
            return [f.path for f in files] + [store._hdr.file.path]
        return []

    def _note(self, reason: str) -> None:
        if not self.reasons_seen or self.reasons_seen[-1] != reason:
            self.reasons_seen.append(reason)

    async def _await_reason(self, cluster, rk, want: tuple[str, ...],
                            ticks: int = 120) -> bool:
        for _ in range(ticks):
            await cluster.loop.delay(0.25)
            self._note(rk.limit_reason)
            if rk.limit_reason in want:
                return True
        return False

    async def start(self, cluster, rng) -> None:
        fs = getattr(cluster, "fs", None)
        rk = getattr(cluster, "ratekeeper", None)
        assert fs is not None and rk is not None, (
            "LowSpace needs a durable RecoverableCluster (disks + ratekeeper)"
        )
        await cluster.loop.delay(self.start_delay)
        ss = cluster.storage[0]
        paths = self._store_paths(ss.store)
        assert paths, "LowSpace: the victim store has no disk files"
        victim = paths[0]
        db = cluster.database()
        value = bytes(self.value_bytes)
        # fill first: the MVCC window holds the WAL flush back a few
        # virtual seconds, so write the burst, then wait for the disk to
        # actually absorb it (usage stops growing)
        for i in range(self.rows):
            async def body(tr, i=i):
                tr.set(b"low/%06d" % i, value)

            await db.run(body)
        last, stable = -1, 0
        for _ in range(200):
            await cluster.loop.delay(0.25)
            used, _cap = fs.usage_for(victim)
            stable = stable + 1 if used == last and used > 0 else 0
            last = used
            if stable >= 8:
                break
        # squeeze band first: capacity chosen so ~15% is free — inside
        # (MIN_FREE_SPACE_FRACTION, FREE_SPACE_TARGET_FRACTION), so the
        # spring compresses without slamming
        fs.set_capacity(victim, max(int(last / 0.85), last + 64))
        self.engaged = await self._await_reason(
            cluster, rk, ("free_space",)
        )
        # then the cliff: ~3% free is under the minimum — the e-brake
        # must slam admission to the floor
        fs.set_capacity(victim, max(int(last / 0.97), last + 8))
        braked = await self._await_reason(cluster, rk, ("e_brake",))
        self.engaged = self.engaged and braked
        # drain: the operator adds space and clears the bulk data; the
        # limit must release
        fs.set_capacity(victim, None)

        async def clear(tr):
            tr.clear_range(b"low/", b"low0")

        await db.run(clear)
        self.drained = await self._await_reason(cluster, rk, ("unlimited",))

    async def check(self, cluster, rng) -> bool:
        # every transition is REQUIRED: free_space that never engaged (or
        # an e-brake that never slammed) tested nothing, limiting that
        # never released is a wedged cluster
        return self.engaged and self.drained

    def metrics(self) -> dict:
        return {
            "reasons_seen": self.reasons_seen,
            "engaged": self.engaged,
            "drained": self.drained,
        }
