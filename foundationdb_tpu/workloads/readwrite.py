"""ReadWrite — the reference's throughput/latency benchmark workload
(fdbserver/workloads/ReadWrite.actor.cpp: configurable read/write mix,
skewed "hot traffic" key choice, range reads, warmup-then-measure
phases, per-operation latency samples, :252-270 metrics emission).

Each client loops transactions of `reads_per_tx` point reads,
`range_reads_per_tx` range reads of `range_len` keys, and
`writes_per_tx` point writes over a configurable key pool for a fixed
duration, recording GRV / read / range / commit latencies.  Key choice
is uniform by default; `skew > 0` draws key RANKS from a zipf-like
distribution with that exponent (the reference's skewed-workload knob),
with ranks scattered across the keyspace by a fixed multiplicative hash
so hot keys spread over shards instead of piling into the first one.

`warmup` seconds split the run into a cold-start phase and a measured
warmed phase (the reference's metrics-start discipline): the headline
rates/percentiles cover only the warmed phase, and the cold phase's
read percentiles are reported separately — the cold-vs-warm split is
what makes a page-cache effect visible in one run.  Metrics report op
rates and p50/p90/p99 latencies — the repo counterpart of BASELINE.md's
per-core ops/s rows, so perf regressions show up in CI.
"""

from __future__ import annotations

import bisect

from .base import Workload
from ..client.transaction import RETRYABLE_ERRORS
from ..runtime.combinators import wait_all


def _key(i: int) -> bytes:
    return b"rw/%06d" % i


# rank -> key-index scatter (Knuth's multiplicative hash): hot zipf ranks
# land all over the keyspace, so skewed load exercises every shard
_SCATTER = 2654435761


def percentile(sorted_xs: list[float], p: float) -> float:
    if not sorted_xs:
        return 0.0
    idx = min(int(p * len(sorted_xs)), len(sorted_xs) - 1)
    return sorted_xs[idx]


def _pcts(lat: list[float], prefix: str, out: dict) -> None:
    xs = sorted(lat)
    out[f"{prefix}_p50_ms"] = round(percentile(xs, 0.50) * 1e3, 3)
    out[f"{prefix}_p90_ms"] = round(percentile(xs, 0.90) * 1e3, 3)
    out[f"{prefix}_p99_ms"] = round(percentile(xs, 0.99) * 1e3, 3)


class ReadWriteWorkload(Workload):
    description = "ReadWrite"

    def __init__(
        self,
        keys: int = 1000,
        clients: int = 8,
        duration: float = 5.0,
        reads_per_tx: int = 9,
        writes_per_tx: int = 1,
        value_bytes: int = 16,
        skew: float = 0.0,
        scatter: bool = True,
        range_reads_per_tx: int = 0,
        range_len: int = 10,
        warmup: float = 0.0,
        start_delay: float = 0.0,
    ):
        self.keys = keys
        self.clients = clients
        self.duration = duration
        self.reads_per_tx = reads_per_tx
        self.writes_per_tx = writes_per_tx
        self.value_bytes = value_bytes
        self.skew = skew
        # scatter=False keeps hot zipf ranks CONTIGUOUS at the bottom of
        # the keyspace — the hot-shard workload: skewed traffic piles into
        # one shard so the load-metric plane has something to detect
        self.scatter = scatter
        self.range_reads_per_tx = range_reads_per_tx
        self.range_len = range_len
        self.warmup = warmup
        self.start_delay = start_delay
        self.committed = 0
        self.retries = 0
        # measured (post-warmup) samples; the cold phase keeps its own
        self.grv_lat: list[float] = []
        self.read_lat: list[float] = []
        self.range_lat: list[float] = []
        self.commit_lat: list[float] = []
        self.cold_read_lat: list[float] = []
        self.cold_committed = 0
        self._warm_committed = 0
        self._elapsed = 0.0
        self._zipf_cdf: list[float] | None = None

    def _build_zipf(self) -> None:
        w = [(i + 1) ** -self.skew for i in range(self.keys)]
        total = sum(w)
        cdf, acc = [], 0.0
        for x in w:
            acc += x / total
            cdf.append(acc)
        self._zipf_cdf = cdf

    def _pick(self, crng) -> int:
        if self.skew <= 0.0:
            return crng.random_int(0, self.keys)
        rank = min(bisect.bisect_left(self._zipf_cdf, crng.random()),
                   self.keys - 1)
        return (rank * _SCATTER) % self.keys if self.scatter else rank

    async def setup(self, cluster, rng) -> None:
        if self.skew > 0.0:
            self._build_zipf()
        db = cluster.database()
        val = b"x" * self.value_bytes
        # chunked fills (one giant txn would blow batch limits)
        for lo in range(0, self.keys, 500):

            async def fill(tr, lo=lo):
                for i in range(lo, min(lo + 500, self.keys)):
                    tr.set(_key(i), val)

            await db.run(fill)

    async def start(self, cluster, rng) -> None:
        if self.skew > 0.0 and self._zipf_cdf is None:
            self._build_zipf()  # runSetup=false still needs the CDF
        db = cluster.database()
        loop = cluster.loop
        if self.start_delay > 0:
            # composes with fault workloads: measure after their rounds
            await loop.delay(self.start_delay)
        t_start = loop.now()
        t_warm = t_start + self.warmup
        t_end = t_start + self.duration
        val = b"y" * self.value_bytes

        async def client(crng):
            while loop.now() < t_end:
                warm = loop.now() >= t_warm
                tr = db.create_transaction()
                try:
                    t0 = loop.now()
                    await tr.get_read_version()
                    if warm:
                        self.grv_lat.append(loop.now() - t0)
                    for _ in range(self.reads_per_tx):
                        k = _key(self._pick(crng))
                        t0 = loop.now()
                        await tr.get(k)
                        (self.read_lat if warm else self.cold_read_lat).append(
                            loop.now() - t0
                        )
                    for _ in range(self.range_reads_per_tx):
                        lo = self._pick(crng)
                        t0 = loop.now()
                        await tr.get_range(
                            _key(lo), _key(min(lo + self.range_len, self.keys)),
                            limit=self.range_len,
                        )
                        if warm:
                            self.range_lat.append(loop.now() - t0)
                    for _ in range(self.writes_per_tx):
                        tr.set(_key(self._pick(crng)), val)
                    t0 = loop.now()
                    await tr.commit()
                    if warm:
                        self.commit_lat.append(loop.now() - t0)
                        self._warm_committed += 1
                    else:
                        self.cold_committed += 1
                    self.committed += 1
                except RETRYABLE_ERRORS as e:
                    self.retries += 1
                    await tr.on_error(e)

        await wait_all(
            [loop.spawn(client(rng.split())) for _ in range(self.clients)]
        )
        # the measured window excludes warmup (cold fills are setup cost)
        self._elapsed = max(loop.now() - t_warm, 1e-9)

    async def check(self, cluster, rng) -> bool:
        return self.committed > 0

    def metrics(self) -> dict:
        measured = self._warm_committed if self.warmup > 0 else self.committed
        out = {
            "committed": self.committed,
            "retries": self.retries,
            "elapsed_s": round(self._elapsed, 3),
            "tx_per_s": round(measured / self._elapsed, 1),
            "reads_per_s": round(len(self.read_lat) / self._elapsed, 1),
        }
        for name, lat in (
            ("grv", self.grv_lat),
            ("read", self.read_lat),
            ("commit", self.commit_lat),
        ):
            _pcts(lat, name, out)
        if self.range_reads_per_tx:
            out["ranges_per_s"] = round(len(self.range_lat) / self._elapsed, 1)
            _pcts(self.range_lat, "range", out)
        if self.warmup > 0:
            # the cold-start phase's read tail vs the warmed one above —
            # the page-cache effect in one row pair
            out["cold_committed"] = self.cold_committed
            _pcts(self.cold_read_lat, "cold_read", out)
        return out
