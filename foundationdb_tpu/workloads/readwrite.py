"""ReadWrite — the reference's throughput/latency benchmark workload
(fdbserver/workloads/ReadWrite.actor.cpp: configurable read/write mix,
per-operation latency samples, :252-270 metrics emission).

Each client loops transactions of `reads_per_tx` point reads and
`writes_per_tx` point writes over a uniform key pool for a fixed duration,
recording GRV / read / commit latencies.  Metrics report op rates and
p50/p90/p99 latencies — the repo counterpart of BASELINE.md's per-core
ops/s rows, so perf regressions show up in CI.
"""

from __future__ import annotations

from .base import Workload
from ..client.transaction import RETRYABLE_ERRORS
from ..runtime.combinators import wait_all


def _key(i: int) -> bytes:
    return b"rw/%06d" % i


def percentile(sorted_xs: list[float], p: float) -> float:
    if not sorted_xs:
        return 0.0
    idx = min(int(p * len(sorted_xs)), len(sorted_xs) - 1)
    return sorted_xs[idx]


class ReadWriteWorkload(Workload):
    description = "ReadWrite"

    def __init__(
        self,
        keys: int = 1000,
        clients: int = 8,
        duration: float = 5.0,
        reads_per_tx: int = 9,
        writes_per_tx: int = 1,
        value_bytes: int = 16,
    ):
        self.keys = keys
        self.clients = clients
        self.duration = duration
        self.reads_per_tx = reads_per_tx
        self.writes_per_tx = writes_per_tx
        self.value_bytes = value_bytes
        self.committed = 0
        self.retries = 0
        self.grv_lat: list[float] = []
        self.read_lat: list[float] = []
        self.commit_lat: list[float] = []
        self._elapsed = 0.0

    async def setup(self, cluster, rng) -> None:
        db = cluster.database()
        val = b"x" * self.value_bytes
        # chunked fills (one giant txn would blow batch limits)
        for lo in range(0, self.keys, 500):

            async def fill(tr, lo=lo):
                for i in range(lo, min(lo + 500, self.keys)):
                    tr.set(_key(i), val)

            await db.run(fill)

    async def start(self, cluster, rng) -> None:
        db = cluster.database()
        loop = cluster.loop
        t_end = loop.now() + self.duration
        val = b"y" * self.value_bytes

        async def client(crng):
            while loop.now() < t_end:
                tr = db.create_transaction()
                try:
                    t0 = loop.now()
                    await tr.get_read_version()
                    self.grv_lat.append(loop.now() - t0)
                    for _ in range(self.reads_per_tx):
                        k = _key(crng.random_int(0, self.keys))
                        t0 = loop.now()
                        await tr.get(k)
                        self.read_lat.append(loop.now() - t0)
                    for _ in range(self.writes_per_tx):
                        tr.set(_key(crng.random_int(0, self.keys)), val)
                    t0 = loop.now()
                    await tr.commit()
                    self.commit_lat.append(loop.now() - t0)
                    self.committed += 1
                except RETRYABLE_ERRORS as e:
                    self.retries += 1
                    await tr.on_error(e)

        t0 = loop.now()
        await wait_all(
            [loop.spawn(client(rng.split())) for _ in range(self.clients)]
        )
        self._elapsed = max(loop.now() - t0, 1e-9)

    async def check(self, cluster, rng) -> bool:
        return self.committed > 0

    def metrics(self) -> dict:
        out = {
            "committed": self.committed,
            "retries": self.retries,
            "elapsed_s": round(self._elapsed, 3),
            "tx_per_s": round(self.committed / self._elapsed, 1),
            "reads_per_s": round(len(self.read_lat) / self._elapsed, 1),
        }
        for name, lat in (
            ("grv", self.grv_lat),
            ("read", self.read_lat),
            ("commit", self.commit_lat),
        ):
            xs = sorted(lat)
            out[f"{name}_p50_ms"] = round(percentile(xs, 0.50) * 1e3, 3)
            out[f"{name}_p90_ms"] = round(percentile(xs, 0.90) * 1e3, 3)
            out[f"{name}_p99_ms"] = round(percentile(xs, 0.99) * 1e3, 3)
        return out
