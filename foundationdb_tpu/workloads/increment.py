"""Increment workload — atomic-op exactly-once accounting
(fdbserver/workloads/Increment.actor.cpp + AtomicOps.actor.cpp: concurrent
ADDs whose grand total must equal the committed op count exactly; any
double-apply from a mishandled commit_unknown_result shows up as a sum
mismatch)."""

from __future__ import annotations

from .base import Workload
from ..roles.types import MutationType


class IncrementWorkload(Workload):
    description = "Increment"

    def __init__(self, counters: int = 4, clients: int = 3,
                 adds_per_client: int = 10, delta: int = 3):
        self.counters = counters
        self.clients = clients
        self.adds = adds_per_client
        self.delta = delta
        self.committed = 0

    def _key(self, i: int) -> bytes:
        return b"incr/%02d" % i

    async def start(self, cluster, rng) -> None:
        db = cluster.database()

        async def client(crng):
            for _ in range(self.adds):
                idx = crng.random_int(0, self.counters)

                async def fn(tr, idx=idx):
                    tr.atomic_op(
                        MutationType.ADD, self._key(idx),
                        self.delta.to_bytes(8, "little"),
                    )

                # db.run's unknown-result fence makes the retry exactly-once
                await db.run(fn)
                self.committed += 1

        from ..runtime.combinators import wait_all

        await wait_all(
            [cluster.loop.spawn(client(rng.split())) for _ in range(self.clients)]
        )

    async def check(self, cluster, rng) -> bool:
        db = cluster.database()

        async def fn(tr):
            return await tr.get_range(b"incr/", b"incr0", limit=1000)

        rows = await db.run(fn)
        total = sum(int.from_bytes(v[:8], "little") for _k, v in rows)
        return total == self.committed * self.delta

    def metrics(self) -> dict:
        return {"committed": self.committed}
