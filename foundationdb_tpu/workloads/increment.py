"""Increment workload — atomic-op ledger accounting
(fdbserver/workloads/AtomicOps.actor.cpp: every transaction ADDs to a
random counter AND to a tally ledger IN THE SAME TRANSACTION, and the two
sides must agree exactly at the end.  An unknown-result retry may re-apply
a transaction — the reference accepts that and checks ATOMICITY instead:
a half-applied transaction, a lost mutation, or a replica divergence all
break counters == ledger, while a double-applied transaction moves both
sides together)."""

from __future__ import annotations

from .base import Workload
from ..roles.types import MutationType


class IncrementWorkload(Workload):
    description = "Increment"

    def __init__(self, counters: int = 4, clients: int = 3,
                 adds_per_client: int = 10, delta: int = 3):
        self.counters = counters
        self.clients = clients
        self.adds = adds_per_client
        self.delta = delta
        self.committed = 0

    def _key(self, i: int) -> bytes:
        return b"incr/%02d" % i

    async def start(self, cluster, rng) -> None:
        db = cluster.database()

        async def client(crng):
            for _ in range(self.adds):
                idx = crng.random_int(0, self.counters)

                async def fn(tr, idx=idx):
                    d = self.delta.to_bytes(8, "little")
                    tr.atomic_op(MutationType.ADD, self._key(idx), d)
                    tr.atomic_op(MutationType.ADD, b"incr/ledger", d)

                await db.run(fn)
                self.committed += 1

        from ..runtime.combinators import wait_all

        await wait_all(
            [cluster.loop.spawn(client(rng.split())) for _ in range(self.clients)]
        )

    async def check(self, cluster, rng) -> bool:
        db = cluster.database()

        async def fn(tr):
            return await tr.get_range(b"incr/", b"incr0", limit=1000)

        rows = await db.run(fn)
        counters = sum(
            int.from_bytes(v[:8], "little")
            for k, v in rows if k != b"incr/ledger"
        )
        ledger = next(
            (int.from_bytes(v[:8], "little") for k, v in rows
             if k == b"incr/ledger"), 0,
        )
        # every transaction moved both sides together, and nothing less
        # than the acked op count can be present
        return counters == ledger and ledger >= self.committed * self.delta

    def metrics(self) -> dict:
        return {"committed": self.committed}
