"""WriteDuringRead-style RYW fuzz — random interleavings of reads, writes,
and clears inside read-your-writes transactions, mirrored against a local
model (fdbserver/workloads/WriteDuringRead.actor.cpp: the workload that
polices the RYW machinery's every edge)."""

from __future__ import annotations

from .base import Workload


class WriteDuringReadWorkload(Workload):
    description = "WriteDuringRead"

    def __init__(self, txns: int = 20, ops_per_txn: int = 12, keys: int = 12):
        self.txns = txns
        self.ops = ops_per_txn
        self.keys = keys
        self.committed = 0
        self._model: dict[bytes, bytes] = {}  # committed state mirror

    def _key(self, rng) -> bytes:
        return b"wdr/%02d" % rng.random_int(0, self.keys)

    async def start(self, cluster, rng) -> None:
        db = cluster.database()
        for _ in range(self.txns):
            tr = db.create_ryw_transaction()
            local = dict(self._model)  # what RYW reads must show
            try:
                for _ in range(self.ops):
                    roll = rng.random()
                    k = self._key(rng)
                    if roll < 0.4:
                        v = b"v%d" % rng.random_int(0, 1000)
                        tr.set(k, v)
                        local[k] = v
                    elif roll < 0.55:
                        k2 = self._key(rng)
                        lo, hi = min(k, k2), max(k, k2 + b"\x00")
                        tr.clear_range(lo, hi)
                        for kk in [kk for kk in local if lo <= kk < hi]:
                            del local[kk]
                    elif roll < 0.85:
                        got = await tr.get(k)
                        assert got == local.get(k), (
                            f"RYW get({k!r}) = {got!r}, model {local.get(k)!r}"
                        )
                    else:
                        lo, hi = b"wdr/", b"wdr0"
                        got = await tr.get_range(lo, hi)
                        want = sorted(
                            (kk, vv) for kk, vv in local.items() if lo <= kk < hi
                        )
                        assert got == want, f"RYW range {got} != {want}"
                await tr.commit()
                self._model = local
                self.committed += 1
            except Exception as e:  # noqa: BLE001 — retryable → resync
                from ..client.transaction import RETRYABLE_ERRORS

                if isinstance(e, RETRYABLE_ERRORS):
                    # an unknown-result commit may have APPLIED: re-read the
                    # authoritative state instead of assuming the model
                    async def snap(tr):
                        return await tr.get_range(b"wdr/", b"wdr0", limit=10000)

                    self._model = dict(await db.run(snap))
                    continue
                raise

    async def check(self, cluster, rng) -> bool:
        db = cluster.database()

        async def fn(tr):
            return await tr.get_range(b"wdr/", b"wdr0", limit=10000)

        rows = await db.run(fn)
        return rows == sorted(self._model.items())

    def metrics(self) -> dict:
        return {"committed": self.committed}
