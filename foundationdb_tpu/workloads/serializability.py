"""Serializability workload — random transactions whose effects are
re-derivable only under a serial order (fdbserver/workloads/
Serializability.actor.cpp: random op sequences asserted equivalent to a
serial execution).

Each transaction performs a random mix over a small key domain:

    set    k := f(txn-id)              (state-independent write)
    add    k += delta                  (atomic; commutes, order-checked)
    clear  k                           (delete)
    copy   dst := read(src) + suffix   (STATE-DEPENDENT: a stale read here
                                       is a serializability violation the
                                       replay detects)

and journals its op list under a VERSIONSTAMPED key — so the journal's key
order IS the commit order (8-byte big-endian version + in-batch index).
`check` replays the journal serially against a model and compares the
model's final domain with the database's: any lost update, stale read
feeding a write, phantom commit (journal entry for an aborted txn), or
missing commit (committed txn absent from the journal) diverges."""

from __future__ import annotations

import json

from .base import Workload
from ..client.transaction import RETRYABLE_ERRORS
from ..roles.types import MutationType, apply_atomic
from ..runtime.combinators import wait_all

DOMAIN = 12
LOG_PREFIX = b"ser/log/"
DATA_PREFIX = b"ser/d/"


def _dk(i: int) -> bytes:
    return DATA_PREFIX + b"%02d" % i


def _stamped_log_key() -> bytes:
    """Placeholder key: prefix + 10-byte stamp slot + little-endian offset
    of the slot (the API >= 520 versionstamped-key format)."""
    return (
        LOG_PREFIX + b"\x00" * 10 + len(LOG_PREFIX).to_bytes(4, "little")
    )


class SerializabilityWorkload(Workload):
    description = "Serializability"

    def __init__(self, clients: int = 3, txns_per_client: int = 12,
                 ops_per_txn: int = 4):
        self.clients = clients
        self.txns_per_client = txns_per_client
        self.ops_per_txn = ops_per_txn
        self.committed = 0
        self.unknown = 0

    def _gen_ops(self, rng, txn_id: str) -> list:
        ops = []
        for j in range(self.ops_per_txn):
            kind = rng.random_int(0, 3)
            k = rng.random_int(0, DOMAIN - 1)
            if kind == 0:
                ops.append(["set", k, f"{txn_id}.{j}"])
            elif kind == 1:
                ops.append(["add", k, rng.random_int(1, 9)])
            elif kind == 2:
                ops.append(["clear", k])
            else:
                ops.append(["copy", k, rng.random_int(0, DOMAIN - 1), f"+{txn_id}"])
        return ops

    async def start(self, cluster, rng) -> None:
        db = cluster.database()

        async def client(crng, cid: int):
            for t in range(self.txns_per_client):
                txn_id = f"c{cid}t{t}"
                ops = self._gen_ops(crng, txn_id)
                tr = db.create_transaction()
                while True:
                    try:
                        for op in ops:
                            if op[0] == "set":
                                tr.set(_dk(op[1]), op[2].encode())
                            elif op[0] == "add":
                                tr.atomic_op(
                                    MutationType.ADD, _dk(op[1]),
                                    int(op[2]).to_bytes(8, "little"),
                                )
                            elif op[0] == "clear":
                                tr.clear(_dk(op[1]))
                            else:  # copy: state-dependent
                                src = await tr.get(_dk(op[1]))
                                tr.set(
                                    _dk(op[2]),
                                    (src or b"<nil>") + op[3].encode(),
                                )
                        tr.atomic_op(
                            MutationType.SET_VERSIONSTAMPED_KEY,
                            _stamped_log_key(),
                            json.dumps(ops).encode(),
                        )
                        await tr.commit()
                        self.committed += 1
                        break
                    except RETRYABLE_ERRORS as e:
                        from ..client.transaction import CommitUnknownResult

                        if isinstance(e, CommitUnknownResult):
                            # the journal entry decides whether it landed;
                            # regenerate the txn id so a double-landing
                            # would be visible as two entries
                            self.unknown += 1
                            await tr.on_error(e)
                            break
                        await tr.on_error(e)

        await wait_all(
            [
                cluster.loop.spawn(client(rng.split(), c))
                for c in range(self.clients)
            ]
        )

    async def check(self, cluster, rng) -> bool:
        db = cluster.database()
        tr = db.create_transaction()
        journal = await tr.get_range(LOG_PREFIX, LOG_PREFIX + b"\xff",
                                     limit=100000)
        actual_rows = await tr.get_range(DATA_PREFIX, DATA_PREFIX + b"\xff",
                                         limit=100000)
        # serial replay in commit order (journal key order)
        model: dict[int, bytes] = {}
        for _k, v in journal:
            for op in json.loads(v):
                if op[0] == "set":
                    model[op[1]] = op[2].encode()
                elif op[0] == "add":
                    model[op[1]] = apply_atomic(
                        MutationType.ADD, model.get(op[1]),
                        int(op[2]).to_bytes(8, "little"),
                    )
                elif op[0] == "clear":
                    model.pop(op[1], None)
                else:
                    src = model.get(op[1])
                    model[op[2]] = (src or b"<nil>") + op[3].encode()
        expect = {_dk(i): v for i, v in model.items()}
        actual = dict(actual_rows)
        if expect != actual:
            only_e = {k for k in expect if actual.get(k) != expect[k]}
            only_a = {k for k in actual if expect.get(k) != actual[k]}
            print(f"[Serializability] divergence: expect!={only_e}, "
                  f"actual!={only_a}")
            return False
        # every definite commit journaled exactly once (no phantom/missing)
        if len(journal) < self.committed:
            print(f"[Serializability] journal {len(journal)} < committed "
                  f"{self.committed}")
            return False
        if len(journal) > self.committed + self.unknown:
            print(f"[Serializability] journal {len(journal)} > committed+unknown")
            return False
        return True

    def metrics(self) -> dict:
        return {"committed": self.committed, "unknown": self.unknown}
