"""Cluster assembly: wire the write pipeline + storage into one simulated
cluster (the SimulatedCluster analog, fdbserver/SimulatedCluster.actor.cpp).

`SimCluster` builds the minimum end-to-end system of SURVEY §7 step 5:
sequencer + N resolvers (pluggable conflict backend) + M TLogs + storage
servers per key shard + a commit proxy, all as simulated processes on one
deterministic EventLoop.  `database()` hands back a client handle.

The control plane (coordinators, recruitment, recovery) layers on top in
control/; this module is also what benchmarks and workloads drive.
"""

from __future__ import annotations

from typing import Callable

from .client.transaction import ClusterView, Database
from .conflict.api import ConflictSet
from .conflict.oracle import OracleConflictSet
from .roles.proxy import CommitProxy, KeyPartitionMap
from .roles.resolver import Resolver
from .roles.sequencer import Sequencer
from .roles.storage import MemoryKeyValueStore, StorageServer
from .roles.tlog import TLog
from .rpc.network import SimNetwork
from .rpc.stream import RequestStreamRef
from .runtime.core import DeterministicRandom, EventLoop
from .runtime.knobs import CoreKnobs
from .runtime.trace import TraceCollector


class SimCluster:
    def __init__(
        self,
        seed: int = 0,
        n_resolvers: int = 1,
        n_storage_shards: int = 1,
        n_tlogs: int = 1,
        conflict_backend: Callable[[], ConflictSet] | None = None,
        knobs: CoreKnobs | None = None,
        resolver_splits: list[bytes] | None = None,
        storage_splits: list[bytes] | None = None,
    ) -> None:
        self.loop = EventLoop()
        self.rng = DeterministicRandom(seed)
        self.knobs = knobs or CoreKnobs()
        self.trace = TraceCollector(
            clock=self.loop.now, min_severity=self.knobs.TRACE_SEVERITY,
            # sim trace files stamp VIRTUAL wall time: a seed's rolled
            # trace output is byte-stable across reruns (flowlint wall-clock)
            wall_clock=self.loop.now,
        )
        from .runtime.trace import g_trace_batch, spawn_wire_metrics

        g_trace_batch.attach_clock(self.loop.now, self.trace)
        # Net2 slow-task watch: a run-loop callback stalling past the knob
        # (host wall) traces a SEV_WARN SlowTask into this collector
        self.loop.slow_task_trace = self.trace
        self.loop.slow_task_trace_threshold = self.knobs.SLOW_TASK_THRESHOLD
        self.net = SimNetwork(self.loop, self.rng, self.trace)
        self._wire_metrics_task = spawn_wire_metrics(
            self.loop, self.trace, self.net.wire,
            self.knobs.METRICS_INTERVAL, "sim",
        )
        make_cs = conflict_backend or OracleConflictSet

        # default splits: evenly spread single-byte prefixes
        def default_splits(n: int) -> list[bytes]:
            return [bytes([256 * i // n]) for i in range(1, n)]

        self.resolver_splits = (
            resolver_splits if resolver_splits is not None else default_splits(n_resolvers)
        )
        self.storage_splits = (
            storage_splits if storage_splits is not None else default_splits(n_storage_shards)
        )

        # -- processes & roles ------------------------------------------------
        self.seq_proc = self.net.create_process("sequencer")
        self.sequencer = Sequencer(self.seq_proc, self.loop, self.knobs)

        self.tlogs: list[TLog] = []
        for i in range(n_tlogs):
            p = self.net.create_process(f"tlog-{i}")
            self.tlogs.append(TLog(
                p, self.loop,
                hard_limit_bytes=self.knobs.TLOG_HARD_LIMIT_BYTES,
                trace=self.trace,
            ))

        self.resolvers: list[Resolver] = []
        for i in range(n_resolvers):
            p = self.net.create_process(f"resolver-{i}")
            self.resolvers.append(Resolver(p, self.loop, self.knobs, make_cs()))

        # storage shards: tag "ss-i" owned by storage server i, pulling from
        # tlog i % n_tlogs
        self.storage: list[StorageServer] = []
        for i in range(n_storage_shards):
            p = self.net.create_process(f"storage-{i}")
            tlog = self.tlogs[i % n_tlogs]
            ss = StorageServer(
                p,
                self.loop,
                self.knobs,
                tlog_peek_ref=self._ref(p, tlog.peek_stream.endpoint),
                tlog_pop_ref=self._ref(p, tlog.pop_stream.endpoint),
                tag=f"ss-{i}",
                store=MemoryKeyValueStore(),
            )
            self.storage.append(ss)

        self.proxy_proc = self.net.create_process("proxy")
        storage_tag_map = KeyPartitionMap(
            self.storage_splits, [[f"ss-{i}"] for i in range(n_storage_shards)]
        )
        self.proxy = CommitProxy(
            self.proxy_proc,
            self.loop,
            self.knobs,
            sequencer_ref=self._ref(self.proxy_proc, self.sequencer.stream.endpoint),
            resolver_refs=[
                self._ref(self.proxy_proc, r.stream.endpoint) for r in self.resolvers
            ],
            resolver_splits=self.resolver_splits,
            tlog_refs=[
                self._ref(self.proxy_proc, t.commit_stream.endpoint) for t in self.tlogs
            ],
            storage_tags=storage_tag_map,
            tag_to_tlogs={f"ss-{i}": [i % n_tlogs] for i in range(n_storage_shards)},
        )

        self.client_proc = self.net.create_process("client")
        self.client_dbs: list[Database] = []
        self._client_metric_tasks: list = []

        # the periodic *Metrics plane (runtime/trace.py spawn_role_metrics):
        # the statically-wired cluster starts every role's emitter itself —
        # the controller does this per generation in the full topology
        iv = self.knobs.METRICS_INTERVAL
        self.sequencer.start_metrics(self.trace, iv)
        self.proxy.start_metrics(self.trace, iv)
        for r in self.resolvers:
            r.start_metrics(self.trace, iv)
        for t in self.tlogs:
            t.start_metrics(self.trace, iv)
        for ss in self.storage:
            ss.start_metrics(self.trace, iv)

    def _ref(self, process, endpoint) -> RequestStreamRef:
        return RequestStreamRef(self.net, process, endpoint)

    def storage_teams(self):
        """Storage servers grouped per shard (single-replica teams)."""
        return [[ss] for ss in self.storage]

    def database(self, process=None) -> Database:
        proc = process or self.client_proc
        storage_members = [
            [
                {
                    "getvalue": self._ref(proc, ss.getvalue_stream.endpoint),
                    "getkeyvalues": self._ref(proc, ss.getkv_stream.endpoint),
                    "getkey": self._ref(proc, ss.getkey_stream.endpoint),
                    "watch": self._ref(proc, ss.watch_stream.endpoint),
                }
            ]
            for ss in self.storage
        ]
        view = ClusterView(
            grv_refs=[self._ref(proc, self.proxy.grv_stream.endpoint)],
            commit_refs=[self._ref(proc, self.proxy.commit_stream.endpoint)],
            storage_map=KeyPartitionMap(self.storage_splits, storage_members),
        )
        db = Database(self.loop, view, self.rng)
        # status + the periodic ClientMetrics plane see every handle
        self.client_dbs.append(db)
        self._client_metric_tasks.append(
            db.start_metrics(self.trace, self.knobs.METRICS_INTERVAL, proc)
        )
        return db

    def run_until(self, fut, deadline: float | None = None):
        return self.loop.run_until(fut, deadline)

    def stop(self) -> None:
        self._wire_metrics_task.cancel()
        for t in self._client_metric_tasks:
            t.cancel()
        self.loop.slow_task_trace = None
        self.proxy.stop()
        for r in self.resolvers:
            r.stop()
        for t in self.tlogs:
            t.stop()
        for s in self.storage:
            s.stop()
        self.sequencer.stop()
