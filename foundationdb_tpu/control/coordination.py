"""Coordinators: replicated generation registers — the cluster's ground truth
(fdbserver/Coordination.actor.cpp: GenerationRegVal :31, localGenerationReg
:125; CoordinatedState quorum logic fdbserver/CoordinatedState.actor.cpp).

Each coordinator holds a single versioned register (the serialized cluster
state).  Reads and writes use the Paxos-register discipline the reference
uses: a client first `read`s with a fresh read-generation from a majority
(learning the newest value and the highest write-generation seen), then
`write`s with a higher generation to a majority.  Two masters racing for
the register cannot both succeed — the loser's generation is stale and a
majority rejects it, which is exactly how split-brain is prevented during
recovery (SURVEY §3.3).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream, RequestStreamRef
from ..runtime.combinators import wait_any
from ..runtime.buggify import maybe_delay
from ..runtime.core import EventLoop, Future, Promise, TaskPriority, TimedOut
from ..runtime.coverage import testcov


@dataclasses.dataclass(frozen=True, order=True)
class Generation:
    """(batch, id) ordered pair (reference UniqueGeneration)."""

    number: int
    owner: str


GEN_ZERO = Generation(0, "")


@dataclasses.dataclass
class ReadRegRequest:
    read_gen: Generation


@dataclasses.dataclass
class ReadRegReply:
    value: Any
    write_gen: Generation   # generation that wrote `value`
    read_gen: Generation    # highest read/write generation promised


@dataclasses.dataclass
class WriteRegRequest:
    value: Any
    write_gen: Generation


@dataclasses.dataclass
class WriteRegReply:
    ok: bool
    promised: Generation


class Coordinator:
    """One coordination server: a generation register, disk-backed when a
    filesystem is given (the reference's OnDemandStore-backed
    localGenerationReg — registers must survive whole-cluster restarts or
    recovery cannot find the last log-system epoch)."""

    WLT_READ = "wlt:coord_read"
    WLT_WRITE = "wlt:coord_write"

    def __init__(self, process: SimProcess, loop: EventLoop,
                 fs=None, path: str | None = None,
                 tokens: tuple[str, str] | None = None) -> None:
        """`tokens` overrides the well-known stream tokens so one process
        can host several registers (the reference's coordinators serve the
        cstate AND the leader-election register from one server)."""
        self.process = process
        self.loop = loop
        self.value: Any = None
        self.write_gen: Generation = GEN_ZERO
        self.promised: Generation = GEN_ZERO
        self._persists = 0
        self._file = None
        if fs is not None:
            self._file = fs.open(path or f"coord-{process.name}.reg", process)
            self._load()
        read_tok, write_tok = tokens or (self.WLT_READ, self.WLT_WRITE)
        self.read_stream = RequestStream(process, read_tok)
        self.write_stream = RequestStream(process, write_tok)
        self._tasks = [
            loop.spawn(self._serve_read(), TaskPriority.COORDINATION, "coord-read"),
            loop.spawn(self._serve_write(), TaskPriority.COORDINATION, "coord-write"),
        ]

    # -- durability ---------------------------------------------------------
    def _load(self) -> None:
        import json

        from ..storage.diskqueue import DiskQueue

        records = DiskQueue(self._file).recover()
        if records:
            doc = json.loads(records[-1])  # last synced write wins
            self.value = doc["value"]
            self.write_gen = Generation(*doc["write_gen"])
            self.promised = Generation(*doc["promised"])

    async def _persist(self) -> None:
        import json

        from ..storage.diskqueue import DiskQueue

        # append-only (recover() takes the last record); every ~64 writes
        # the log is compacted to one record via the JOURNALED truncate
        # (diskqueue.rewrite keeps the old synced contents recoverable until
        # the replacement syncs), so read-promise churn can't grow the file
        # unboundedly
        self._persists += 1
        dq = DiskQueue(self._file)
        if self._persists % 64 == 0:
            dq.rewrite([])
        dq.push(
            json.dumps(
                {
                    "value": self.value,
                    "write_gen": [self.write_gen.number, self.write_gen.owner],
                    "promised": [self.promised.number, self.promised.owner],
                },
                sort_keys=True,
            ).encode()
        )
        await dq.sync()

    async def _persist_retried(self) -> bool:
        """Persist the register, retrying transient disk faults (the
        injected-error plane, storage/files.py) a few times.  False —
        persistently refused — means the caller must NOT ack the request:
        a promise/write that is not durable may not be acknowledged.  It
        must equally NOT kill the serve loop, which would take this
        coordinator out of the quorum forever (found by the DiskSwizzle
        chaos: erode 2 of 3 registers and recovery wedges for good).  The
        in-memory state staying stricter/ahead of disk is the safe
        direction — the prepared-but-unacked state every quorum round
        already tolerates."""
        for attempt in range(3):
            try:
                await self._persist()
                return True
            except IOError:
                testcov("coord.persist_io_error")
                await self.loop.delay(0.02 * (attempt + 1),
                                      TaskPriority.COORDINATION)
        return False

    async def _serve_read(self) -> None:
        while True:
            req = await self.read_stream.next()
            await maybe_delay(self.loop, "coord.delay_read")
            r: ReadRegRequest = req.payload
            if r.read_gen > self.promised:
                self.promised = r.read_gen
                if self._file is not None and not await self._persist_retried():
                    continue  # refused: requester times out and retries
            req.reply(ReadRegReply(self.value, self.write_gen, self.promised))

    async def _serve_write(self) -> None:
        while True:
            req = await self.write_stream.next()
            await maybe_delay(self.loop, "coord.delay_write")
            r: WriteRegRequest = req.payload
            if r.write_gen >= self.promised:
                self.promised = r.write_gen
                self.write_gen = r.write_gen
                self.value = r.value
                if self._file is not None and not await self._persist_retried():
                    continue  # refused: no durable write, no ack
                req.reply(WriteRegReply(True, self.promised))
            else:
                req.reply(WriteRegReply(False, self.promised))

    def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self.read_stream.close()
        self.write_stream.close()


class CoordinatedState:
    """Majority-quorum client over the coordinators (CoordinatedState.actor.cpp):
    read-then-conditional-write of the replicated cluster state."""

    def __init__(self, loop: EventLoop, read_refs: list[RequestStreamRef],
                 write_refs: list[RequestStreamRef], owner: str) -> None:
        self.loop = loop
        self._reads = read_refs
        self._writes = write_refs
        self._owner = owner
        self._gen_number = 0

    @property
    def quorum_size(self) -> int:
        return len(self._reads) // 2 + 1

    async def _majority(self, futures: list[Future]) -> list:
        """Collect replies until a majority succeeded.  Individual failures
        (dead coordinator → BrokenPromise, unreachable → TimedOut) are
        skipped; the call fails only when a majority can no longer succeed."""
        need = self.quorum_size
        got: list = []
        failures = 0
        pending: list[Future] = []
        for f in futures:
            p = Promise()

            def settle(fut: Future, p=p) -> None:
                err = fut.exception()
                p.send((False, err) if err is not None else (True, fut.result()))

            f.add_done_callback(settle)
            pending.append(p.future)
        while pending and len(got) < need:
            idx, (ok, result) = await wait_any(pending)
            pending.pop(idx)
            if ok:
                got.append(result)
            else:
                failures += 1
                if failures > len(futures) - need:
                    raise TimedOut("no coordinator quorum")
        if len(got) < need:
            raise TimedOut("no coordinator quorum")
        return got

    async def read(self) -> tuple[Any, Generation]:
        self._gen_number += 1
        rg = Generation(self._gen_number, self._owner)
        replies = await self._majority(
            [ref.get_reply(ReadRegRequest(rg), timeout=2.0) for ref in self._reads]
        )
        # newest write wins; also learn any higher promised generation
        best = max(replies, key=lambda r: r.write_gen)
        top_promise = max(r.read_gen for r in replies)
        if top_promise.number > self._gen_number:
            self._gen_number = top_promise.number
        return best.value, best.write_gen

    async def write(self, value: Any) -> bool:
        """Conditional write with a fresh higher generation; False = lost the
        race to a newer writer (caller must re-read and reconsider)."""
        self._gen_number += 1
        wg = Generation(self._gen_number, self._owner)
        replies = await self._majority(
            [
                ref.get_reply(WriteRegRequest(value, wg), timeout=2.0)
                for ref in self._writes
            ]
        )
        ok = sum(1 for r in replies if r.ok) >= self.quorum_size
        if not ok:
            top = max(r.promised for r in replies)
            if top.number > self._gen_number:
                self._gen_number = top.number
        return ok
