"""Ratekeeper — global admission control (fdbserver/Ratekeeper.actor.cpp).

Watches every storage server's durability lag and every TLog's queue depth
and computes a cluster-wide transactions-per-second budget (updateRate
:250) that the proxies' GRV service spends, shedding load *before* queues
melt down — the reference's core flow-control loop.

Per-server model (the reference's shape, simplified to this runtime's
observables): each server's lag is exponentially SMOOTHED (Smoother, so
transient spikes don't whipsaw admission), a proportional controller maps
smoothed lag to a per-server TPS limit with slack above the target, the
binding (minimum) constraint wins, and the published budget is itself
smoothed.  `status()` exposes the per-server model the reference prints
in its RkUpdate trace events."""

from __future__ import annotations

from ..roles.storage import StorageServer
from ..runtime.core import EventLoop, TaskPriority
from ..runtime.coverage import testcov
from ..runtime.knobs import CoreKnobs
from ..runtime.metrics import Smoother
from ..runtime.trace import SEV_INFO, SEV_WARN


class Ratekeeper:
    def __init__(
        self,
        loop: EventLoop,
        knobs: CoreKnobs,
        storage: list[StorageServer],
        tlogs_fn,  # callable -> current list[TLog] (generation changes)
        max_tps: float = 1e6,
        trace=None,  # TraceCollector: RkUpdate track_latest events feed the
                     # status messages roll-up (the reference's RkUpdate)
    ) -> None:
        self.loop = loop
        self.knobs = knobs
        self.storage = storage
        self.tlogs_fn = tlogs_fn
        self.max_tps = max_tps
        self.trace = trace
        self.tps_budget = max_tps
        self.batch_tps_budget = max_tps
        # operator-imposed cap (fdbcli `throttle`, `\xff/conf/throttle_tps`):
        # an upper bound composed with the automatic model, None = off
        self.manual_tps_cap: float | None = None
        self.limit_reason = "unlimited"
        self.limiting_server: str | None = None
        # WHICH range drove a storage-side limit (the load-metric plane's
        # attribution): the limiting server's hottest sampled key + its
        # bytes/sec, so saturation reports point at the hot range, not
        # just the hot process; None while unlimited / TLog-limited
        self.limiting_shard: str | None = None
        self.limiting_shard_bps: float = 0.0
        # e-brake: a queue crossed its HARD limit or a disk is nearly full —
        # the budget is slammed to the floor (no smoothing) until it clears
        self.e_brake = False
        self._lag_smoothers: dict[str, Smoother] = {}
        self._queue_smoothers: dict[int, Smoother] = {}
        self._squeue_smoothers: dict[str, Smoother] = {}
        self._tlog_names: dict = {}  # endpoint token -> "tlogN" (status keys)
        self._budget = Smoother(
            knobs.RATEKEEPER_SMOOTHING_E, clock=loop.now
        )
        self._budget.reset(max_tps)
        self._task = loop.spawn(self._run(), TaskPriority.RATEKEEPER, "ratekeeper")

    def _smoothed(self, table: dict, key, value: float) -> float:
        s = table.get(key)
        if s is None:
            s = table[key] = Smoother(
                self.knobs.RATEKEEPER_SMOOTHING_E, clock=self.loop.now
            )
            s.reset(value)
        else:
            s.set_total(value)
        return s.smooth_total()

    @staticmethod
    def _limit(lag: float, target: float, max_tps: float) -> float:
        """Proportional controller: full rate below target, linear squeeze
        to the 1% floor as lag approaches 2x target (the spring the
        reference's updateRate builds per server)."""
        if lag <= target:
            return max_tps
        frac = max(0.0, (2 * target - lag) / target)
        return max(max_tps * frac, max_tps * 0.01)

    def _free_space(self, ss) -> float | None:
        """The storage server's disk free-space FRACTION, or None when its
        store has no bounded disk (pure-memory engines, unlimited sim
        disks) — the storage_server_min_free_space input."""
        du = getattr(getattr(ss, "store", None), "disk_usage", None)
        if du is None:
            return None
        used, cap = du()
        if cap is None or cap <= 0:
            return None
        return max(0.0, 1.0 - used / cap)

    def _update(self) -> None:
        tps = self.max_tps
        reason = "unlimited"
        limiting = None
        brake = None  # (server,) that crossed a HARD limit / min free space

        # TLog smoothers are keyed by the TLog's own endpoint token: a
        # recovery's fresh TLogs must start with fresh models, not inherit a
        # deposed slot-mate's backlog estimate; departed keys are pruned
        target_bytes = float(self.knobs.TARGET_QUEUE_BYTES)
        hard_tlog = float(self.knobs.TLOG_HARD_LIMIT_BYTES)
        tlogs = self.tlogs_fn()
        live_keys = set()
        self._tlog_names = {}
        for i, t in enumerate(tlogs):
            key = t.commit_stream.endpoint.token
            live_keys.add(key)
            self._tlog_names[key] = f"tlog{i}"
            raw = float(t.bytes_queued)
            q = self._smoothed(self._queue_smoothers, key, raw)
            lim = self._limit(q, target_bytes, self.max_tps)
            if lim < tps:
                tps, reason, limiting = lim, "tlog_queue", f"tlog{i}"
            if hard_tlog and raw >= hard_tlog and brake is None:
                # the RAW gauge, not the smoothed model: the e-brake exists
                # for exactly the moment smoothing would lag behind
                brake = f"tlog{i}"
        for key in [k for k in self._queue_smoothers if k not in live_keys]:
            del self._queue_smoothers[key]

        # storage smoothers key by TAG: a healed replacement inherits its
        # predecessor's model on purpose (same data responsibility)
        target_lag = 2.0 * self.knobs.mvcc_window_versions
        target_squeue = float(self.knobs.TARGET_STORAGE_QUEUE_BYTES)
        hard_squeue = float(self.knobs.STORAGE_HARD_LIMIT_BYTES)
        free_target = self.knobs.FREE_SPACE_TARGET_FRACTION
        free_min = self.knobs.MIN_FREE_SPACE_FRACTION
        live_tags = set()
        for ss in self.storage:
            live_tags.add(ss.tag)
            lag = self._smoothed(
                self._lag_smoothers, ss.tag,
                float(ss.version.get() - ss.durable_version),
            )
            lim = self._limit(lag, target_lag, self.max_tps)
            if lim < tps:
                tps, reason, limiting = lim, "storage_lag", ss.tag
            # bytes-in-queue spring (applied-above-durable; the reference's
            # storage queue input to updateRate)
            raw_q = float(getattr(ss, "queue_bytes", 0))
            q = self._smoothed(self._squeue_smoothers, ss.tag, raw_q)
            lim = self._limit(q, target_squeue, self.max_tps)
            if lim < tps:
                tps, reason, limiting = lim, "storage_queue", ss.tag
            if hard_squeue and raw_q >= hard_squeue and brake is None:
                brake = ss.tag
            # free-space squeeze (storage_server_min_free_space): linear
            # from full rate at the target fraction down to the floor at
            # the minimum; at or below the minimum the e-brake engages
            free = self._free_space(ss)
            if free is not None and free < free_target:
                frac = max(0.0, (free - free_min) / (free_target - free_min))
                lim = max(self.max_tps * frac, self.max_tps * 0.01)
                if lim < tps:
                    tps, reason, limiting = lim, "free_space", ss.tag
                if free <= free_min and brake is None:
                    brake = ss.tag
        for tag in [t for t in self._lag_smoothers if t not in live_tags]:
            del self._lag_smoothers[tag]
        for tag in [t for t in self._squeue_smoothers if t not in live_tags]:
            del self._squeue_smoothers[tag]

        if self.manual_tps_cap is not None and self.manual_tps_cap < tps:
            tps, reason, limiting = self.manual_tps_cap, "manual_throttle", None

        self.e_brake = brake is not None
        if brake is not None:
            # e-brake: slam the budget to the floor NOW — the smoother's
            # job is to keep transients from whipsawing admission, but a
            # queue past its hard limit / a nearly-full disk is not a
            # transient, and every admitted transaction digs the hole
            # deeper.  The floor (0.1% of max) keeps the recovery path and
            # operator transactions alive.
            tps, reason, limiting = self.max_tps * 0.001, "e_brake", brake
            self._budget.reset(tps)
            self.tps_budget = tps
            self.batch_tps_budget = 0.0
        else:
            self._budget.set_total(tps)
            self.tps_budget = max(self._budget.smooth_total(), self.max_tps * 0.01)
            if self.manual_tps_cap is not None:
                # the cap is a hard ceiling, not a smoothed target
                self.tps_budget = min(self.tps_budget, self.manual_tps_cap)
            # batch-priority budget (the reference's separate batch limit):
            # batch traffic starves FIRST — it reaches zero while
            # default-class work still has 25% of the full rate left
            self.batch_tps_budget = max(
                0.0, (self.tps_budget - 0.25 * self.max_tps) / 0.75
            )
        # attribute the hot RANGE behind a storage-side limit from the
        # limiting server's bandwidth samples (busiest sampled key): the
        # difference between "ss-1-r0 is slow" and "rw/000123 is hot"
        shard, shard_bps = None, 0.0
        if reason in ("storage_queue", "storage_lag", "e_brake"):
            ss = next((s for s in self.storage if s.tag == limiting), None)
            busiest = getattr(ss, "busiest_range", None)
            if busiest is not None:
                hot_key, shard_bps = busiest()
                if hot_key is not None:
                    shard = repr(hot_key)
        self.limiting_shard = shard
        self.limiting_shard_bps = shard_bps

        if reason != self.limit_reason:
            if reason == "storage_queue":
                testcov("ratekeeper.limit_storage_queue")
            elif reason == "free_space":
                testcov("ratekeeper.limit_free_space")
            elif reason == "e_brake":
                testcov("ratekeeper.e_brake")
            if self.trace is not None:
                # only on TRANSITIONS (not every 0.25s tick): the latest
                # event is what status scrapes; WARN while limited makes it
                # a message
                self.trace.trace(
                    "RkUpdate",
                    severity=SEV_WARN if reason != "unlimited" else SEV_INFO,
                    track_latest="ratekeeper",
                    Reason=reason,
                    LimitingServer=limiting,
                    LimitingShard=shard,
                    LimitingShardBps=round(shard_bps, 1),
                    TPSBudget=round(self.tps_budget, 1),
                )
        self.limit_reason = reason
        self.limiting_server = limiting

    def status(self) -> dict:
        """The RkUpdate view: budget, binding constraint, per-server model.
        TLog rows are attributed as `tlogN` (the limiting_server naming),
        never raw endpoint tokens — the model is keyed by token internally
        so recoveries reset it, but operators read slot names."""
        return {
            "tps_budget": self.tps_budget,
            "batch_tps_budget": self.batch_tps_budget,
            "limit_reason": self.limit_reason,
            "limiting_server": self.limiting_server,
            "limiting_shard": self.limiting_shard,
            "limiting_shard_bps": self.limiting_shard_bps,
            "e_brake": self.e_brake,
            "storage_lag_smoothed": {
                tag: s.smooth_total() for tag, s in self._lag_smoothers.items()
            },
            "storage_queue_smoothed": {
                tag: s.smooth_total()
                for tag, s in self._squeue_smoothers.items()
            },
            "free_space": {
                ss.tag: self._free_space(ss) for ss in self.storage
            },
            "tlog_queue_smoothed": {
                self._tlog_names.get(k, f"tlog?{k[:6]}"): s.smooth_total()
                for k, s in self._queue_smoothers.items()
            },
        }

    async def _run(self) -> None:
        while True:
            await self.loop.delay(self.knobs.RATEKEEPER_UPDATE_INTERVAL, TaskPriority.RATEKEEPER)
            self._update()

    def stop(self) -> None:
        self._task.cancel()
