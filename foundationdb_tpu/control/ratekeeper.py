"""Ratekeeper — global admission control (fdbserver/Ratekeeper.actor.cpp).

Watches storage-server write lag and TLog queue depth and computes a
cluster-wide transactions-per-second budget (updateRate :250); the proxy's
GRV service spends that budget, shedding load *before* queues melt down —
the reference's core flow-control loop.
"""

from __future__ import annotations

from ..roles.storage import StorageServer
from ..roles.tlog import TLog
from ..runtime.core import EventLoop, TaskPriority
from ..runtime.knobs import CoreKnobs


class Ratekeeper:
    def __init__(
        self,
        loop: EventLoop,
        knobs: CoreKnobs,
        storage: list[StorageServer],
        tlogs_fn,  # callable -> current list[TLog] (generation changes)
        max_tps: float = 1e6,
    ) -> None:
        self.loop = loop
        self.knobs = knobs
        self.storage = storage
        self.tlogs_fn = tlogs_fn
        self.max_tps = max_tps
        self.tps_budget = max_tps
        self.smoothed_release = 0.0
        self.limit_reason = "unlimited"
        self._task = loop.spawn(self._run(), TaskPriority.RATEKEEPER, "ratekeeper")

    def _update(self) -> None:
        """One updateRate pass: the binding constraint wins."""
        tps = self.max_tps
        reason = "unlimited"
        target_bytes = self.knobs.TARGET_QUEUE_BYTES
        for t in self.tlogs_fn():
            q = t.bytes_queued
            if q > target_bytes:
                frac = max(0.0, 1.0 - (q - target_bytes) / target_bytes)
                if tps > self.max_tps * frac:
                    tps = self.max_tps * frac
                    reason = "tlog_queue"
        window = self.knobs.mvcc_window_versions
        for ss in self.storage:
            lag = ss.version.get() - ss.durable_version
            # durability lag beyond ~2 MVCC windows: storage is drowning
            if lag > 2 * window:
                frac = max(0.0, 1.0 - (lag - 2 * window) / window)
                if tps > self.max_tps * frac:
                    tps = self.max_tps * frac
                    reason = "storage_lag"
        self.tps_budget = max(tps, self.max_tps * 0.01)
        self.limit_reason = reason

    async def _run(self) -> None:
        while True:
            await self.loop.delay(self.knobs.RATEKEEPER_UPDATE_INTERVAL, TaskPriority.RATEKEEPER)
            self._update()

    def stop(self) -> None:
        self._task.cancel()
