"""Cluster status document — the clusterGetStatus analog
(fdbserver/Status.actor.cpp:1698; schema fdbclient/Schemas.cpp).

Aggregates role counters, trace `track_latest` snapshots, and queue depths
into one machine-readable dict, the surface `fdbcli status` renders and
operators script against."""

from __future__ import annotations

from typing import Any


def cluster_status(cluster) -> dict[str, Any]:
    """Works on SimCluster (static generation) and RecoverableCluster."""
    loop = cluster.loop
    trace = cluster.trace
    controller = getattr(cluster, "controller", None)
    if controller is not None:
        gen = controller.generation
        proxy = gen.proxy
        resolvers = gen.resolvers
        tlogs = gen.tlogs
        epoch = controller.epoch
        recovery = {
            "state": controller.recovery_state,
            "epoch": epoch,
            "count": controller.recoveries,
        }
    else:
        proxy = cluster.proxy
        resolvers = cluster.resolvers
        tlogs = cluster.tlogs
        recovery = {"state": "accepting_commits", "epoch": 1, "count": 0}

    doc: dict[str, Any] = {
        "cluster": {
            "generation": recovery,
            "clock": loop.now(),
            "messages_sent": cluster.net.messages_sent,
            "messages_dropped": cluster.net.messages_dropped,
            "processes": {
                str(addr): {"name": p.name, "alive": p.alive, "reboots": p.reboots}
                for addr, p in cluster.net.processes.items()
            },
            "latest_events": {k: v for k, v in trace.latest.items()},
        },
        "proxy": {
            **proxy.counters.snapshot(),
            "committed_version": proxy.committed_version.get(),
            "batch_interval": proxy._batch_interval,
        },
        "resolvers": [
            {
                **r.counters.snapshot(),
                "version": r.version.get(),
                "oldest_version": r.cs.oldest_version,
            }
            for r in resolvers
        ],
        "tlogs": [
            {"version": t.version.get(), "bytes_queued": t.bytes_queued,
             "locked": t.locked}
            for t in tlogs
        ],
        "storage": [
            {
                "tag": ss.tag,
                "version": ss.version.get(),
                "durable_version": ss.durable_version,
                "keys": ss.store.key_count(),
            }
            for ss in cluster.storage
        ],
    }
    return doc
