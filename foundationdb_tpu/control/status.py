"""Cluster status document — the clusterGetStatus analog
(fdbserver/Status.actor.cpp:1698; schema fdbclient/Schemas.cpp).

Aggregates role counters, trace `track_latest` snapshots, and queue depths
into one machine-readable dict, the surface `fdbcli status` renders and
operators script against."""

from __future__ import annotations

from typing import Any

from ..runtime.metrics import LatencyTracker
from ..runtime.trace import SEV_WARN


def _severity_name(sev: int) -> str:
    return {5: "debug", 10: "info", 20: "warn", 30: "warn_always"}.get(
        sev, "error"
    )


def _device_messages(resolvers) -> list[dict[str, Any]]:
    """A degraded or probing device backend is exactly the kind of
    'cluster serves but you should know' condition cluster.messages exists
    for (the runbook entry point: docs/OPERATIONS.md)."""
    msgs: list[dict[str, Any]] = []
    for i, r in enumerate(resolvers):
        h = getattr(r.cs, "health", None)
        if h is None:
            continue
        health = h()
        # message while NOT fully recovered: degraded/probing state, live
        # failure streak, or still serving from the CPU after a trip.  A
        # fresh resolver that hasn't probed yet (lazy first promotion) and
        # a fully re-promoted one (healthy, serving device) stay silent —
        # an empty message list must mean healthy.
        if (
            health["state"] != "healthy"
            or health["consecutive_failures"]
            or (health["serving"] == "cpu" and health["trips"])
        ):
            msgs.append({
                "name": "device_backend_degraded",
                "severity": "warn",
                "time": None,
                "description": (
                    f"resolver{i} conflict backend {health['state']}"
                    f" (serving {health['serving']},"
                    f" trips {health['trips']},"
                    f" last_failure {health['last_failure']},"
                    f" degraded {health['time_degraded_s']:.3f}s)"
                ),
            })
    return msgs


def _messages(trace, ratekeeper) -> list[dict[str, Any]]:
    """Operator-facing message list (the reference status doc's
    cluster.messages): every SEV_WARN+ `track_latest` snapshot becomes a
    message, plus the ratekeeper's live limiting reason — the two channels
    that say WHY a cluster is degraded rather than just that it is."""
    msgs: list[dict[str, Any]] = []
    for key, ev in sorted(trace.latest.items()):
        if ev.get("Severity", 0) >= SEV_WARN:
            msgs.append({
                "name": ev["Type"],
                "severity": _severity_name(ev["Severity"]),
                "time": ev.get("Time", 0.0),
                "description": ", ".join(
                    f"{k}={v}" for k, v in ev.items()
                    if k not in ("Type", "Severity", "Time")
                ),
            })
    if ratekeeper is not None and ratekeeper.limit_reason != "unlimited":
        msgs.append({
            "name": "performance_limited",
            "severity": "warn",
            "time": None,
            "description": (
                f"admission limited by {ratekeeper.limit_reason}"
                + (
                    f" on {ratekeeper.limiting_server}"
                    if ratekeeper.limiting_server else ""
                )
                + (
                    # the load-metric plane's attribution: the hot RANGE
                    # behind the limit, not just the hot process
                    f" (hot range {ratekeeper.limiting_shard})"
                    if ratekeeper.limiting_shard else ""
                )
                + f" (tps_budget {ratekeeper.tps_budget:.0f})"
            ),
        })
    return msgs


def _data_block(cluster, dd) -> dict[str, Any]:
    """cluster.data — the movingData/totalKVBytes analog, fed entirely by
    the storage servers' SAMPLED metric plane (dd.shard_load: one
    waitMetrics-style poll per shard, no scans): total estimated bytes,
    bytes overlapping in-flight fetchKeys ranges, and the top-k hottest
    shards by sampled read+write bandwidth."""
    load = dd.shard_load()
    moving_ranges = [
        (fs.begin, fs.end_key)
        for ss in cluster.storage for fs in ss._fetching
    ]

    def overlaps(m) -> bool:
        me = m["end"] if m["end"] is not None else b"\xff\xff\xff\xff\xff\xff"
        return any(b < me and m["begin"] < e for b, e in moving_ranges)

    ranked = sorted(
        load,
        key=lambda m: -(m["bytes_read_per_ksec"] + m["bytes_written_per_ksec"]),
    )
    return {
        "total_kv_bytes_estimate": sum(m["bytes"] for m in load),
        "moving_bytes_estimate": sum(m["bytes"] for m in load if overlaps(m)),
        "moving_ranges": len(moving_ranges),
        "shard_count": len(load),
        "hot_shards": [
            {
                "begin": repr(m["begin"]),
                "end": repr(m["end"]) if m["end"] is not None else None,
                "bytes": m["bytes"],
                "bytes_read_per_ksec": round(m["bytes_read_per_ksec"], 1),
                "bytes_written_per_ksec":
                    round(m["bytes_written_per_ksec"], 1),
                "team": list(m["team"]),
            }
            for m in ranked[:3]
        ],
    }


# Schema of the offline phase-profile artifact (phase_timings.py --json,
# embedded by bench.py as kernel.phase_profile).  Not part of the live
# status document — the profile needs dispatch barriers the hot path
# must not pay — but schema'd here next to the kernel roll-up so the two
# kernel-cost surfaces stay reviewed together.  Optional-key convention
# matches STATUS_SCHEMA ("?" suffix = may be absent).
PHASE_PROFILE_SCHEMA: dict[str, Any] = {
    "backend": str,            # jax backend the profile ran on
    "small": bool,             # reduced shapes (bench embed) vs full probe
    "cap": int,                # main state capacity profiled
    "rec_cap": int,            # LSM recent capacity profiled
    "merge_impl_default": str,  # compiled-in fold default (scatter)
    "shapes": dict,            # {n_txn, n_read, n_write, cap}
    "rtt_ms": (int, float),    # host<->device dispatch floor
    "intra_iters": int,        # intra-batch fixpoint iterations observed
    "cumulative_ms": dict,     # truncation ladder, keyed by probe.log label
    "phases_ms": dict,         # {search, history, intra, merge_buckets, full}
    "lsm": dict,               # {full_ms, compact_ms, batches_per_compact,
                               #  effective_ms}
    "merge_shootout_ms": dict,  # {level_size: {sort, gather, scatter}}
}


def check_phase_profile(doc: dict) -> list[str]:
    """Validate a phase-profile dict against PHASE_PROFILE_SCHEMA; returns
    human-readable problems (empty = conforming).  Used by the bench embed
    test so the artifact can't silently drift from the schema."""
    problems: list[str] = []
    for key, typ in PHASE_PROFILE_SCHEMA.items():
        if key not in doc:
            problems.append(f"phase_profile missing key: {key}")
        elif not isinstance(doc[key], typ):
            problems.append(
                f"phase_profile.{key}: expected {typ}, got "
                f"{type(doc[key]).__name__}"
            )
    for key in doc:
        if key not in PHASE_PROFILE_SCHEMA:
            problems.append(f"phase_profile unknown key: {key}")
    return problems


def _kernel_rollup(resolvers) -> dict[str, Any]:
    """Aggregate the resolvers' conflict-backend KernelStats into one
    cluster-level view (counters sum; occupancy re-derives from the summed
    row counts; resolve-time percentiles take the worst resolver — the one
    that paces the commit pipeline's barrier)."""
    per = [r.cs.kernel_stats() for r in resolvers]
    if not per:
        from ..conflict.api import KernelStats

        return {
            **KernelStats(backend="none").snapshot(),
            "per_resolver": [],
        }
    out: dict[str, Any] = {
        "backend": per[0]["backend"],
        "per_resolver": per,
    }
    for k in (
        "batches", "txns", "aborted", "rows_real", "rows_padded",
        "recompiles", "search_fallbacks", "compactions", "gc_calls",
        "rows_reclaimed", "node_count", "pack_ms", "encode_ms", "pad_ms",
        "h2d_ms", "resolve_ms", "merge_ms",
        "runs_appended", "full_merges",
    ):
        out[k] = sum(p.get(k, 0) for p in per)
    out["phase"] = {
        k: sum(p.get("phase", {}).get(k, 0.0) for p in per)
        for k in ("sort_ms", "scan_ms", "merge_ms", "compact_ms")
    }
    # fold impl: single value when the fleet agrees, "mixed" otherwise
    # (an autotune sweep can leave resolvers on different impls); fold_ms
    # sums per impl so mixed fleets stay attributable
    impls = {p.get("merge_impl", "?") for p in per}
    out["merge_impl"] = impls.pop() if len(impls) == 1 else "mixed"
    fold: dict[str, float] = {}
    for p in per:
        for k, v in p.get("fold_ms", {}).items():
            fold[k] = fold.get(k, 0.0) + v
    out["fold_ms"] = dict(sorted(fold.items()))
    out["abort_rate"] = out["aborted"] / out["txns"] if out["txns"] else 0.0
    out["occupancy"] = (
        out["rows_real"] / out["rows_padded"] if out["rows_padded"] else 1.0
    )
    for k in ("resolve_ms_p50", "resolve_ms_p99"):
        out[k] = max(p[k] for p in per)
    sup = [p["supervisor"] for p in per if "supervisor" in p]
    if sup:
        # supervised device backends (conflict/supervisor.py): one roll-up
        # of the degraded/healthy/probing fleet — counts by state, total
        # breaker trips, and the worst time-in-degraded
        out["device"] = {
            "states": {
                s: sum(1 for h in sup if h["state"] == s)
                for s in ("healthy", "probing", "degraded")
            },
            "serving_cpu": sum(1 for h in sup if h["serving"] == "cpu"),
            "trips": sum(h["trips"] for h in sup),
            "promotions": sum(h["promotions"] for h in sup),
            "probes": sum(h["probes"] for h in sup),
            "time_degraded_s": max(h["time_degraded_s"] for h in sup),
        }
    return out


def cluster_status(cluster) -> dict[str, Any]:
    """Works on SimCluster (static generation) and RecoverableCluster."""
    loop = cluster.loop
    trace = cluster.trace
    controller = getattr(cluster, "controller", None)
    if controller is not None:
        gen = controller.generation
        proxy = gen.proxy
        proxies = gen.proxies
        resolvers = gen.resolvers
        tlogs = gen.tlogs
        epoch = controller.epoch
        recovery = {
            "state": controller.recovery_state,
            "epoch": epoch,
            "count": controller.recoveries,
        }
    else:
        proxy = cluster.proxy
        proxies = [cluster.proxy]
        resolvers = cluster.resolvers
        tlogs = cluster.tlogs
        recovery = {"state": "accepting_commits", "epoch": 1, "count": 0}

    doc: dict[str, Any] = {
        "cluster": {
            "generation": recovery,
            "clock": loop.now(),
            "messages_sent": cluster.net.messages_sent,
            "messages_dropped": cluster.net.messages_dropped,
            "processes": {
                str(addr): {"name": p.name, "alive": p.alive, "reboots": p.reboots}
                for addr, p in cluster.net.processes.items()
            },
            "latest_events": {k: v for k, v in trace.latest.items()},
        },
        "proxy": {
            **proxy.counters.snapshot(),
            "committed_version": proxy.committed_version.get(),
            "batch_interval": proxy._batch_interval,
        },
        "resolvers": [
            {
                **r.counters.snapshot(),
                "version": r.version.get(),
                "oldest_version": r.cs.oldest_version,
                "latency": r.latency.snapshot(),
            }
            for r in resolvers
        ],
        "tlogs": [
            {"version": t.version.get(), "bytes_queued": t.bytes_queued,
             "locked": t.locked, "spill_events": getattr(t, "spill_events", 0),
             "commits_refused": getattr(t, "commits_refused", 0)}
            for t in tlogs
        ],
        "storage": [
            {
                "tag": ss.tag,
                "version": ss.version.get(),
                "durable_version": ss.durable_version,
                "keys": ss.store.key_count(),
                "queue_bytes": getattr(ss, "queue_bytes", 0),
                "read_latency": ss.read_latency.snapshot(),
                # ssd engine only: parsed-page cache accounting (kept for
                # continuity; the structured block below carries the rest)
                **(
                    {"cache_hits": ss.store.cache_hits,
                     "cache_misses": ss.store.cache_misses}
                    if hasattr(ss.store, "cache_hits") else {}
                ),
                # durable engines: the file-level page-cache counter block
                # (storage/pagecache.py — hit/miss/read-ahead per store,
                # plus the ssd engine's parsed-page cache gauges)
                **(
                    {"page_cache": ss.store.page_cache_stats()}
                    if hasattr(ss.store, "page_cache_stats") else {}
                ),
            }
            for ss in cluster.storage
        ],
    }
    # -- latency bands + per-stage histograms (tentpole seam 1) -------------
    # commit/GRV merge across ALL proxies (each proxy owns its trackers);
    # the stage histograms say where inside commitBatch the time goes
    doc["latency_bands"] = {
        "commit": LatencyTracker.merged([p.latency["commit"] for p in proxies]),
        "grv": LatencyTracker.merged([p.latency["grv"] for p in proxies]),
        "stages": {
            stage: LatencyTracker.merged([p.latency[stage] for p in proxies])
            for stage in ("batch_wait", "version_assign", "resolution",
                          "tlog_push")
        },
        "resolver": LatencyTracker.merged([r.latency for r in resolvers]),
        "storage_read": LatencyTracker.merged(
            [ss.read_latency for ss in cluster.storage]
        ),
    }

    # -- conflict-kernel profiling counters (tentpole seam 2) ---------------
    doc["kernel"] = _kernel_rollup(resolvers)

    # -- commit-plane wire counters (docs/WIRE.md) --------------------------
    # codec bytes/wall, frames per coalesced flush, and the pickle-fallback
    # census (by type: a hot message regressing off its codec shows up here
    # by NAME).  SimNetwork and RealNetwork expose the same WireStats shape;
    # the cluster fabric is the sim one, so the coalescing counters live in
    # the REAL transport's snapshot — merged under `transport` when the
    # server runs a wall-clock TCP fabric alongside (tools/server.py).
    wire = getattr(cluster.net, "wire", None)
    if wire is not None:
        doc["commit_wire"] = snap = wire.snapshot()
        rnet = getattr(getattr(cluster, "_wall_driver", None), "net", None)
        rwire = getattr(rnet, "wire", None)
        if rwire is not None:
            snap["transport"] = rwire.snapshot()

    # -- client-side RYW SnapshotCache counters -----------------------------
    # aggregated across every Database handle the cluster handed out
    # (client/snapshot_cache.py): hit/miss/insert/eviction totals, the live
    # byte gauge, and selector resolutions through the merged view
    dbs = getattr(cluster, "client_dbs", None)
    if dbs is not None:
        agg: dict[str, int] = {
            "cache_hits": 0, "cache_misses": 0, "cache_inserts": 0,
            "cache_evictions": 0, "selector_reads": 0, "bytes": 0,
            "transactions": 0,
        }
        for db in dbs:
            for k, v in db.cache_stats.snapshot().items():
                agg[k] = agg.get(k, 0) + v
        doc["clients"] = {"databases": len(dbs), "ryw_cache": agg}

    rk = getattr(cluster, "ratekeeper", None)
    doc["cluster"]["messages"] = _messages(trace, rk) + _device_messages(resolvers)

    # -- per-disk gauges (storage/files.py fault plane) ----------------------
    # bytes used vs capacity, degraded-mode multiplier, stall/error/ENOSPC
    # counters: the operator's which-disk-is-melting table (the runbook's
    # first read when ratekeeper says free_space / e_brake)
    fs = getattr(cluster, "fs", None)
    if fs is not None:
        doc["cluster"]["disks"] = fs.disk_usage()
        # the SHARED page pool's gauges (one pool per process lifetime —
        # byte budget, live bytes, evictions; per-store hit/miss counters
        # live in the storage rows above)
        pool = getattr(fs, "page_pool", None)
        if pool is not None:
            doc["cluster"]["page_cache"] = pool.stats()

    dd = getattr(cluster, "dd", None)
    if dd is not None:
        doc["cluster"]["data_distribution"] = {
            "moves": dd.moves,
            "heals": dd.heals,
            "shard_splits": dd.shard_splits,
            "shard_merges": dd.shard_merges,
            "hot_relocations": dd.hot_relocations,
            "frozen": dd.frozen,
            "shards": len(controller.storage_teams_tags),
            "exclusion_drains": dd.exclusion_drains,
        }
        try:
            doc["cluster"]["data"] = _data_block(cluster, dd)
        except KeyError:
            pass  # keyServers map churning mid-status; omit this scrape
    if controller is not None:
        doc["cluster"]["backup_running"] = controller.backup_worker is not None
        # round-5 operational surface (ManagementAPI state + liveness map)
        fm = controller.failure_monitor
        doc["cluster"]["configuration"] = {
            "excluded": sorted(controller.excluded_targets),
            "locked": controller._locked is not None,
            "coordinators": len(getattr(cluster, "coordinators", []) or []),
            "maintenance_zones": sorted(controller.maintenance_zones),
            "redundancy_policy": repr(controller.replication_policy)
            if controller.replication_policy is not None else None,
            "team_sizes": [len(t) for t in controller.storage_teams_tags],
        }
        devices = fm.device_report()
        doc["cluster"]["failure_monitor"] = {
            "tracked": len(fm._status),
            "failed": [str(a) for a in fm.failed_addresses()],
            "transitions": fm.transitions,
            **(
                {"devices": devices,
                 "device_transitions": fm.device_transitions}
                if devices else {}
            ),
        }
        doc["cluster"]["stream_consumers"] = sorted(controller.stream_consumers)
        rc = getattr(controller, "region_config", None)
        lr = getattr(cluster, "log_router", None)
        if rc is not None and (
            rc.usable_regions >= 2 or getattr(cluster, "remote_storage", [])
        ):
            # the region plane (control/region.py): applied configuration +
            # relay health — the operator's failover dashboard
            doc["cluster"]["regions"] = {
                "usable_regions": rc.usable_regions,
                "satellite": rc.satellite,
                "primary": rc.primary,
                "promoted": bool(getattr(cluster, "_region_promoted", False)),
                "remote_replicas": len(getattr(cluster, "remote_storage", [])),
                "router": (
                    {
                        "version": lr.version.get(),
                        "known_committed": lr.known_committed,
                        "queue_depth": sum(len(q) for q in lr._tags.values()),
                    }
                    if lr is not None else None
                ),
            }
    if rk is not None:
        doc["ratekeeper"] = rk.status()
    if loop.profile:
        doc["profiler"] = {
            "busy_s_by_priority": dict(loop.busy_s_by_priority),
            "slow_tasks": len(loop.slow_tasks),
        }
    return doc


# -- status schema (fdbclient/Schemas.cpp + tests/status/* goldens) ----------
#
# A field spec is: a type (isinstance check), a dict (required keys,
# recursed), a [spec] (list, every element validated), or a tuple of
# accepted types.  Optional keys are suffixed '?'.

_LATENCY_SPEC: dict = {
    "count": int,
    "mean": (int, float),
    "max": (int, float),
    "p50": (int, float),
    "p95": (int, float),
    "p99": (int, float),
    "bands": dict,
}

STATUS_SCHEMA: dict = {
    "cluster": {
        "generation": {"state": str, "epoch": int, "count": int},
        "clock": (int, float),
        "messages_sent": int,
        "messages_dropped": int,
        "processes": dict,
        "latest_events": dict,
        "messages": [
            {"name": str, "severity": str, "description": str}
        ],
        "data_distribution?": {
            "moves": int, "heals": int, "shard_splits": int,
            "shard_merges": int, "hot_relocations": int, "frozen": bool,
            "shards": int, "exclusion_drains": int,
        },
        # the load-metric plane roll-up (cluster.data — movingData /
        # totalKVBytes analog): sampled byte totals + top-k hot shards
        "data?": {
            "total_kv_bytes_estimate": int,
            "moving_bytes_estimate": int,
            "moving_ranges": int,
            "shard_count": int,
            "hot_shards": [
                {
                    "begin": str,
                    "end": (str, type(None)),
                    "bytes": int,
                    "bytes_read_per_ksec": (int, float),
                    "bytes_written_per_ksec": (int, float),
                    "team": list,
                }
            ],
        },
        "backup_running?": bool,
        "configuration?": {
            "excluded": list,
            "locked": bool,
            "coordinators": int,
            "maintenance_zones": list,
            "redundancy_policy": (str, type(None)),
            "team_sizes": list,
        },
        "failure_monitor?": {
            "tracked": int, "failed": list, "transitions": int,
            "devices?": dict, "device_transitions?": int,
        },
        "stream_consumers?": list,
        # per-disk gauges (storage/files.py SimFilesystem.disk_usage):
        # path -> {bytes_used, capacity, latency_mult, stalled, ops, syncs,
        # stalls, errors_injected, enospc_errors, corrupt_reads, sync_s}
        "disks?": dict,
        # shared file-level page pool (storage/pagecache.py PageCachePool):
        # budget/occupancy/eviction gauges for the one per-process pool
        "page_cache?": {
            "page_size": int,
            "capacity_bytes": int,
            "bytes": int,
            "pages": int,
            "evictions": int,
            "invalidations": int,
            "readahead_batches": int,
        },
        "regions?": {
            "usable_regions": int,
            "satellite": str,
            "primary": str,
            "promoted": bool,
            "remote_replicas": int,
            "router": (dict, type(None)),
        },
    },
    "proxy": {
        "committed_version": int,
        "batch_interval": (int, float),
        "txns_committed": int,
        "txns_conflicted": int,
        "commit_batches": int,
        "mvcc_window_throttles": int,
    },
    "resolvers": [
        {"version": int, "oldest_version": int, "latency": _LATENCY_SPEC}
    ],
    "tlogs": [
        {"version": int, "bytes_queued": int, "locked": bool,
         "spill_events": int, "commits_refused": int}
    ],
    "storage": [
        {"tag": str, "version": int, "durable_version": int, "keys": int,
         "queue_bytes": int, "read_latency": _LATENCY_SPEC,
         # durable engines: file-level page-cache counters for this
         # store's files + the ssd engine's parsed-page cache gauges
         "page_cache?": {
             "hits": int, "misses": int,
             "readahead_pages": int, "readahead_hits": int,
             "parsed_hits": int, "parsed_misses": int, "parsed_bytes": int,
         }}
    ],
    "latency_bands": {
        "commit": _LATENCY_SPEC,
        "grv": _LATENCY_SPEC,
        "stages": {
            "batch_wait": _LATENCY_SPEC,
            "version_assign": _LATENCY_SPEC,
            "resolution": _LATENCY_SPEC,
            "tlog_push": _LATENCY_SPEC,
        },
        "resolver": _LATENCY_SPEC,
        "storage_read": _LATENCY_SPEC,
    },
    "kernel": {
        "backend": str,
        "batches": int,
        "txns": int,
        "abort_rate": (int, float),
        "occupancy": (int, float),
        "recompiles": int,
        "search_fallbacks": int,
        "compactions": int,
        "gc_calls": int,
        "rows_reclaimed": int,
        "node_count": int,
        "runs_appended": int,
        "full_merges": int,
        "merge_impl": str,
        "fold_ms": dict,
        "phase": dict,
        "resolve_ms_p50": (int, float),
        "resolve_ms_p99": (int, float),
        "per_resolver": list,
        "device?": {
            "states": dict,
            "serving_cpu": int,
            "trips": int,
            "promotions": int,
            "probes": int,
            "time_degraded_s": (int, float),
        },
    },
    "commit_wire?": {
        "frames_encoded": int,
        "frames_decoded": int,
        "bytes_encoded": int,
        "bytes_decoded": int,
        "encode_ms": (int, float),
        "decode_ms": (int, float),
        "pickle_fallbacks": int,
        "fallback_types": dict,
        "decode_fallbacks": int,
        "flushes": int,
        "frames_flushed": int,
        "frames_per_flush": (int, float),
        # the wall-clock TCP fabric's WireStats (same shape), present when
        # the server runs one alongside the sim fabric (tools/server.py) —
        # its flushes/frames_per_flush are where coalescing actually shows
        "transport?": dict,
    },
    # client-side RYW SnapshotCache roll-up (client/snapshot_cache.py):
    # aggregated over every Database handle the cluster handed out
    "clients?": {
        "databases": int,
        "ryw_cache": {
            "cache_hits": int,
            "cache_misses": int,
            "cache_inserts": int,
            "cache_evictions": int,
            "selector_reads": int,
            "bytes": int,
            "transactions": int,
        },
    },
    "profiler?": {"busy_s_by_priority": dict, "slow_tasks": int},
    "ratekeeper?": {
        "tps_budget": (int, float),
        "batch_tps_budget": (int, float),
        "limit_reason": str,
        "limiting_server": (str, type(None)),
        # hot-range attribution from the bandwidth samples (repr'd key)
        "limiting_shard": (str, type(None)),
        "limiting_shard_bps": (int, float),
        "e_brake": bool,
        "storage_lag_smoothed": dict,
        # keyed by tag (storage) / `tlogN` slot name (tlogs) — the
        # ratekeeper status test pins the key shapes
        "storage_queue_smoothed": dict,
        "free_space": dict,
        "tlog_queue_smoothed": dict,
    },
}


# -- periodic role-metrics events (runtime/trace.py spawn_role_metrics) ------
#
# The `*Metrics` vocabulary every role emits each METRICS_INTERVAL: one
# schema per event type, validated with the same field-spec machinery as
# the status document (the reference's status-schema discipline applied to
# the trace plane — tests assert every role type emits a conforming event
# within one interval).

_NUM = (int, float)

ROLE_METRICS_SCHEMA: dict = {
    "ProxyMetrics": {
        "Elapsed": _NUM,
        "TxnsCommittedPerSec": _NUM,
        "TxnsConflictedPerSec": _NUM,
        "CommitBatchesPerSec": _NUM,
        "ThrottlesPerSec": _NUM,
        "CommittedVersion": int,
        "BatchInterval": _NUM,
        "CommitP99Ms": _NUM,
        "GrvP99Ms": _NUM,
    },
    "ResolverMetrics": {
        "Elapsed": _NUM,
        "BatchesPerSec": _NUM,
        "TxnsPerSec": _NUM,
        "ConflictsPerSec": _NUM,
        "Version": int,
        "OldestVersion": int,
        "LatencyP99Ms": _NUM,
        "KernelBackend": str,
        "KernelBatchesDelta": int,
        "KernelPackMsDelta": _NUM,
        "KernelResolveMsDelta": _NUM,
        "KernelMergeMsDelta": _NUM,
        "DeviceState?": str,
        "DeviceServing?": str,
        "DeviceTrips?": int,
    },
    "TLogMetrics": {
        "Elapsed": _NUM,
        "Version": int,
        "KnownCommitted": int,
        "BytesQueued": int,
        "SpillEvents": int,
        "Locked": bool,
        "CommitsPerSec": _NUM,
        "BytesPerSec": _NUM,
    },
    "StorageMetrics": {
        "Elapsed": _NUM,
        "Tag": str,
        "Version": int,
        "DurableVersion": int,
        "KnownCommitted": int,
        "Keys": int,
        "QueueBytes": int,
        "ReadsPerSec": _NUM,
        "MutationsPerSec": _NUM,
        "ReadP99Ms": _NUM,
        # load-metric plane gauges (roles/storage_metrics.py): byte-sample
        # totals + decayed read/write bandwidth estimates
        "SampledBytes": int,
        "SampledKeys": int,
        "BytesReadPerKSec": _NUM,
        "BytesWrittenPerKSec": _NUM,
        # durable engines: cumulative page-cache counters (storage/
        # pagecache.py) — present when the store exposes the block
        "PageCacheHits?": int,
        "PageCacheMisses?": int,
        "PageCacheReadaheadHits?": int,
        "PageCacheParsedHits?": int,
    },
    "SequencerMetrics": {
        "Elapsed": _NUM,
        "LastAssigned": int,
        "MaxCommitted": int,
        "RequestsPerSec": _NUM,
        "VersionsAssignedPerSec": _NUM,
    },
    "LogRouterMetrics": {
        "Elapsed": _NUM,
        "Version": int,
        "KnownCommitted": int,
        "EntriesPerSec": _NUM,
        "QueueDepth": int,
    },
    "ClientMetrics": {
        "Elapsed": _NUM,
        "CacheHitsPerSec": _NUM,
        "CacheMissesPerSec": _NUM,
        "CacheInsertsPerSec": _NUM,
        "CacheEvictionsPerSec": _NUM,
        "SelectorReadsPerSec": _NUM,
        "CacheBytes": int,
        "CachedTransactions": int,
    },
    "WireMetrics": {
        "Elapsed": _NUM,
        "Source": str,
        "FramesEncodedPerSec": _NUM,
        "FramesDecodedPerSec": _NUM,
        "BytesEncodedPerSec": _NUM,
        "BytesDecodedPerSec": _NUM,
        "PickleFallbacks": int,
        "DecodeFallbacks": int,
        "FramesPerFlush": _NUM,
    },
}


# every emission carries its per-instance attribution (spawn_role_metrics
# stamps it centrally, so the event stream stays separable when several
# same-role instances share one process)
for _spec in ROLE_METRICS_SCHEMA.values():
    _spec["Instance"] = str


# -- coverage census events (runtime/coverage.py + runtime/buggify.py) -------
#
# One `CodeCoverage` event per testcov name / buggify site, emitted at sim
# teardown (the reference's coveragetool rows, ridden over the trace plane
# so the soak driver can scrape census data out of per-seed trace files
# instead of a side channel).  Kind says which namespace the Name lives
# in; Armed distinguishes a buggify site that enabled this run from one
# that only fired because a test force()d it.

CODE_COVERAGE_SCHEMA: dict = {
    "Name": str,
    "Kind": str,   # "testcov" | "buggify"
    "Hits": int,
    "Armed": bool,
}


def validate_coverage_event(ev: dict) -> None:
    """Raise ValueError where a `CodeCoverage` trace event violates its
    schema (same field-spec machinery as the status document)."""
    if ev.get("Type") != "CodeCoverage":
        raise ValueError(f"not a CodeCoverage event: {ev.get('Type')!r}")
    if ev.get("Kind") not in ("testcov", "buggify"):
        raise ValueError(f"coverage.Kind: unknown kind {ev.get('Kind')!r}")
    validate_status(ev, CODE_COVERAGE_SCHEMA, "coverage")


# -- process-supervisor events (tools/fdbmonitor.py) --------------------------
#
# The supervisor's trace plane is an operator-facing contract: the bounce
# driver (tools/bounce.py) and soak triage parse these events, so their
# shapes are schema-pinned like role metrics.  Extra harness-stamped
# fields (Time/Severity/Machine/WallTime) pass through unchecked, same as
# every other event schema here.

MONITOR_EVENT_SCHEMA: dict = {
    "MonitorStarted": {
        "Conf": str,
        "Pid": int,
        "Sections": str,        # comma-joined section names
    },
    "MonitorStopped": {
        "Pid": int,
    },
    "ProcessStarted": {
        "Section": str,
        "Pid": int,
        "Cmd": str,
    },
    "ProcessRestarted": {
        "Section": str,
        "Pid": int,
        "Restarts": int,
    },
    "ProcessStopped": {
        "Section": str,
        "Pid": int,
        "Reason": str,          # shutdown | conf-removed | conf-changed
    },
    "ProcessDied": {
        "Section": str,
        "Pid": int,
        "ExitCode": int,        # negative = killed by that signal number
        "RanS": _NUM,
        "RestartInS": _NUM,     # -1.0 = restart disabled: stays dead
    },
    "ProcessSpawnFailed": {
        "Section": str,
        "Error": str,
        "RetryInS": _NUM,
    },
    "MonitorConfInvalid": {
        "Conf": str,
        "Error": str,
    },
    "ConfReloaded": {
        "Generation": int,
        "Added": str,           # comma-joined section names (may be empty)
        "Removed": str,
        "Changed": str,
    },
}


def validate_monitor_event(ev: dict) -> None:
    """Raise ValueError where a supervisor trace event violates its schema
    (unknown supervisor event types also raise)."""
    spec = MONITOR_EVENT_SCHEMA.get(ev.get("Type"))
    if spec is None:
        raise ValueError(f"unknown monitor event type {ev.get('Type')!r}")
    validate_status(ev, spec, f"monitor.{ev['Type']}")


def validate_metrics_event(ev: dict) -> None:
    """Raise ValueError where a `*Metrics` trace event violates its schema
    (unknown metrics event types also raise: a new role metric must be
    schema-listed before it ships)."""
    spec = ROLE_METRICS_SCHEMA.get(ev.get("Type"))
    if spec is None:
        raise ValueError(f"unknown metrics event type {ev.get('Type')!r}")
    validate_status(ev, spec, f"metrics.{ev['Type']}")


def validate_status(doc, schema=None, path: str = "status") -> None:
    """Raise ValueError where `doc` violates the schema — the analog of the
    reference's schema-checked status (Status.actor.cpp checks emitted docs
    against Schemas.cpp in simulation)."""
    schema = STATUS_SCHEMA if schema is None else schema
    if isinstance(schema, dict):
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: expected object, got {type(doc).__name__}")
        for key, sub in schema.items():
            optional = key.endswith("?")
            k = key[:-1] if optional else key
            if k not in doc:
                if optional:
                    continue
                raise ValueError(f"{path}.{k}: missing")
            validate_status(doc[k], sub, f"{path}.{k}")
    elif isinstance(schema, list):
        if not isinstance(doc, list):
            raise ValueError(f"{path}: expected array, got {type(doc).__name__}")
        for i, item in enumerate(doc):
            validate_status(item, schema[0], f"{path}[{i}]")
    else:
        if not isinstance(doc, schema):
            raise ValueError(
                f"{path}: expected {schema}, got {type(doc).__name__}"
            )
